"""
On-device peak detection vs the host reference implementation.

The contract (VERDICT round-2 ask #3): identical candidates on the
synthetic-pulsar test via the on-device path, with only KB-sized peak
buffers crossing the device boundary.
"""
import numpy as np
import pytest

from riptide_tpu.libffa import generate_signal
from riptide_tpu.metadata import Metadata
from riptide_tpu.peak_detection import find_peaks
from riptide_tpu.periodogram import Periodogram
from riptide_tpu.search.engine import run_periodogram_batch, run_search_batch
from riptide_tpu.search.plan import periodogram_plan


TSAMP = 1e-3
N = 65536  # 65.5 s
PKW = dict(smin=6.0, segwidth=5.0, nstd=6.0, minseg=10, polydeg=2, clrad=0.1)


@pytest.fixture(scope="module")
def search_setup():
    plan = periodogram_plan(N, TSAMP, (1, 2, 3, 4, 6), 0.3, 1.5, 64, 71)
    rng = np.random.RandomState(42)
    batch = np.empty((3, N), np.float32)
    # trial 0: bright pulsar, trial 1: pure noise, trial 2: faint pulsar
    np.random.seed(1)
    batch[0] = generate_signal(N, 0.5 / TSAMP, amplitude=18.0, ducy=0.03)
    batch[1] = rng.normal(size=N).astype(np.float32)
    np.random.seed(2)
    batch[2] = generate_signal(N, 0.9 / TSAMP, amplitude=10.0, ducy=0.05)
    # normalise (the engine expects normalised input)
    batch -= batch.mean(axis=1, keepdims=True)
    batch /= batch.std(axis=1, keepdims=True)
    return plan, batch


def _host_peaks(plan, batch, dms):
    periods, foldbins, snrs = run_periodogram_batch(plan, batch)
    out = []
    for d in range(batch.shape[0]):
        md = Metadata({"dm": float(dms[d]), "tobs": N * TSAMP})
        pgram = Periodogram(plan.widths, periods, foldbins, snrs[d], md)
        peaks, polycos = find_peaks(pgram, **PKW)
        out.append(peaks)
    return out


def test_device_peaks_match_host(search_setup):
    plan, batch = search_setup
    dms = [0.0, 10.0, 20.0]
    host = _host_peaks(plan, batch, dms)
    dev, _ = run_search_batch(plan, batch, tobs=N * TSAMP, dms=dms, **PKW)

    assert len(dev) == len(host)
    for d, (hp, dp) in enumerate(zip(host, dev)):
        hset = [(p.ip, p.iw, round(p.snr, 4)) for p in hp]
        dset = [(p.ip, p.iw, round(p.snr, 4)) for p in dp]
        assert dset == hset, f"trial {d}: {dset} != {hset}"
        for p in dp:
            assert p.dm == dms[d]


def test_device_peaks_recover_pulsar(search_setup):
    plan, batch = search_setup
    dev, polycos = run_search_batch(plan, batch, tobs=N * TSAMP, **PKW)
    # bright pulsar found at P = 0.5 s
    assert dev[0], "no peaks found for the bright pulsar"
    top = dev[0][0]
    assert abs(top.period - 0.5) < 1e-3
    assert top.snr > 15
    # peaks sorted by decreasing S/N; polycos present per width
    snrs = [p.snr for p in dev[0]]
    assert snrs == sorted(snrs, reverse=True)
    assert set(polycos[0].keys()) <= set(range(len(plan.widths)))


def test_device_peaks_noise_only(search_setup):
    plan, batch = search_setup
    dev, _ = run_search_batch(plan, batch, tobs=N * TSAMP, **PKW)
    # pure-noise trial: no (or only marginal) detections above smin
    for p in dev[1]:
        assert p.snr < 8.0


def test_queue_collect_pipelining(search_setup):
    """Two batches queued BEFORE either is collected (the queue-ahead
    pattern of the batcher/benchmark) must produce the same peaks as
    two sequential run_search_batch calls, and collecting must release
    the handle's device buffers."""
    from riptide_tpu.search.engine import (
        collect_search_batch, queue_search_batch,
    )

    plan, batch = search_setup
    dms = [0.0, 10.0, 20.0]
    want, _ = run_search_batch(plan, batch, tobs=N * TSAMP, dms=dms, **PKW)

    h1 = queue_search_batch(plan, batch, tobs=N * TSAMP, **PKW)
    h2 = queue_search_batch(plan, batch[::-1].copy(), tobs=N * TSAMP, **PKW)
    got1, _ = collect_search_batch(h1, dms)
    got2, _ = collect_search_batch(h2, dms[::-1])

    def key(trials):
        return [[(p.ip, p.iw, round(p.snr, 4)) for p in t] for t in trials]

    assert key(got1) == key(want)
    assert key(got2) == key(want[::-1])
    # collect released the fused buffer (and the S/N cube unless a
    # column overflowed, which these tiny searches never do)
    assert h1[1][0] is None and h1[1][1] is None
