"""
Boxcar S/N tests: analytic values, phase-rotation invariance, output
dims, oracle parity, and the batched padded-container path. Mirrors
riptide/tests/test_snr.py.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from riptide_tpu.ops import reference as ref
from riptide_tpu.ops import boxcar_snr, boxcar_coeffs, snr_batched


def test_errors():
    data = np.zeros(32, dtype=np.float32)
    with pytest.raises(ValueError):
        boxcar_snr(data, [0, 1])
    with pytest.raises(ValueError):
        boxcar_snr(data, [1, 32])
    with pytest.raises(ValueError):
        boxcar_snr(data, [1, 2], stdnoise=-42.0)


def test_output_dims():
    widths = [1, 2, 3, 5]
    assert boxcar_snr(np.zeros(32, "f"), widths).shape == (4,)
    assert boxcar_snr(np.zeros((4, 32), "f"), widths).shape == (4, 4)
    assert boxcar_snr(np.zeros((3, 4, 32), "f"), widths).shape == (3, 4, 4)


def test_phase_rotation_invariance():
    rng = np.random.RandomState(3)
    data = rng.normal(size=(4, 32)).astype(np.float32)
    widths = [1, 2, 5, 11, 18, 31]
    snr_ref = boxcar_snr(data, widths)
    for shift in range(1, 33):
        snr = boxcar_snr(np.roll(data, shift, axis=-1), widths)
        assert np.allclose(snr, snr_ref, atol=1e-4)


def test_analytic_values():
    """A unit boxcar pulse of true width w in zeros: best trial must be w,
    with S/N exactly w * h(w) (riptide/tests/test_snr.py:62-78)."""
    n = 64
    widths = np.arange(1, n)
    for w in range(1, n):
        data = np.zeros(n, dtype=np.float32)
        data[:w] = 1.0
        snr = boxcar_snr(data, widths)
        assert snr.argmax() == w - 1
        h = np.sqrt((n - w) / (n * w))
        assert np.allclose(snr.max(), w * h, rtol=1e-5)


def test_vs_oracle():
    rng = np.random.RandomState(11)
    data = rng.normal(size=(20, 260)).astype(np.float32)
    widths = ref.generate_width_trials(240)
    got = boxcar_snr(data, widths, stdnoise=2.5)
    expected = ref.boxcar_snr_2d(data, widths, stdnoise=2.5)
    assert np.allclose(got, expected, atol=1e-4)


def test_snr_batched_padded():
    """Padded batch: each problem must match the single-profile oracle."""
    rng = np.random.RandomState(5)
    widths = (1, 2, 3, 4, 6, 9)
    shapes = [(7, 50), (5, 64), (9, 47)]
    B, R, P = len(shapes), 10, 64
    stds = np.asarray([1.0, 2.0, 0.5], np.float32)
    buf = np.zeros((B, R, P), np.float32)
    for b, (m, p) in enumerate(shapes):
        buf[b, :m, :p] = rng.normal(size=(m, p))

    hcoef = np.zeros((B, len(widths)), np.float32)
    bcoef = np.zeros((B, len(widths)), np.float32)
    for b, (_, p) in enumerate(shapes):
        h, bb = boxcar_coeffs(p, widths)
        hcoef[b], bcoef[b] = h, bb

    out = np.asarray(
        snr_batched(
            jnp.asarray(buf),
            jnp.asarray([p for _, p in shapes], jnp.int32),
            widths,
            jnp.asarray(hcoef),
            jnp.asarray(bcoef),
            jnp.asarray(stds),
        )
    )
    for b, (m, p) in enumerate(shapes):
        expected = ref.boxcar_snr_2d(buf[b, :m, :p], np.asarray(widths), stds[b])
        assert np.allclose(out[b, :m], expected, atol=1e-4)
