"""RIP013 good fixture: reads are free, writes route through fsio,
and a non-literal mode is (conservatively) not flagged (destination:
riptide_tpu/obs/writer.py)."""
from ..utils import fsio


def publish(path, data):
    fsio.atomic_write_bytes(path, data)


def publish_text(path, text):
    fsio.atomic_write_text(path, text)


def read(path):
    with open(path) as fobj:
        return fobj.read()


def read_bytes(path):
    with open(path, "rb") as fobj:
        return fobj.read()


def reopen(path, mode):
    # Dynamic mode: the zero-alias contract says no finding.
    return open(path, mode)
