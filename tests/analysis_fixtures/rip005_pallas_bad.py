"""BAD fixture for RIP005: implicit memory space, missing out_shape,
dynamic grid, nondeterminism inside a kernel closure."""
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _noise():
    return time.time()


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * _noise()    # host nondeterminism captured


def run(x, n):
    call = pl.pallas_call(                 # no out_shape
        _kernel,
        grid=(compute_grid(n),),           # dynamic grid expression
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],  # no memory_space
    )
    return call(x)


def compute_grid(n):
    return n // 8
