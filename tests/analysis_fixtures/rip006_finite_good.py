"""GOOD fixture for RIP006: every checked entry point routes through
riptide_tpu.quality (directly or via one local helper)."""
from .. import quality


def _scan(x):
    return quality.check_finite_array(x)


def boxcar_snr(x, widths):
    quality.check_finite_array(x)
    return x.sum() + len(widths)


def snr_batched(x, widths):
    _scan(x)
    return x.sum()
