"""GOOD fixture for RIP003: flags read through the typed registry
(and non-RIPTIDE environment reads stay unrestricted)."""
import os

from riptide_tpu.utils import envflags


def registry_reads():
    path = envflags.get("RIPTIDE_FFA_PATH")
    budget = envflags.get("RIPTIDE_EXEC_CACHE_MAX_BYTES")
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")  # not a RIPTIDE_ flag
    return path, budget, coord
