"""RIP014 good fixture: try/finally pairing, ownership escape, and an
out-of-protocol receiver name (destination:
riptide_tpu/survey/gatemod.py)."""


def run_chunk(chunk_gate, cid, work):
    chunk_gate.begin(cid)
    try:
        work(cid)
    finally:
        chunk_gate.end(cid)


def prep(pool, fill):
    buf = pool.acquire((4, 4), "float32")
    try:
        fill(buf)
    finally:
        pool.release(buf)


def prep_handoff(pool):
    # Ownership escapes to the caller: release is its job.
    buf = pool.acquire((4, 4), "float32")
    return buf


def prep_stash(pool, meta):
    out = pool.acquire((4, 4), "float32")
    meta["staging"] = out
    return meta


class Folder:
    def fold(self, compute):
        acc = self.integrity.begin_fold("c0")
        try:
            compute(acc)
        finally:
            return self.integrity.finish_fold(acc)


def other_protocol(session, cid):
    # Receiver outside the protocol name sets: not this rule's business.
    session.begin(cid)
    session.end(cid)
