"""GOOD fixture for RIP005: static geometry, explicit memory spaces,
pure kernel body."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def run(x, N, B):
    call = pl.pallas_call(
        _kernel,
        grid=(B,),
        in_specs=[pl.BlockSpec((N, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((N, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * N, 128), jnp.float32),
    )
    return call(x)
