"""BAD fixture for RIP003: raw RIPTIDE_* environment reads and an
unregistered flag."""
import os

from riptide_tpu.utils import envflags


def raw_reads():
    a = os.environ.get("RIPTIDE_BOGUS_FLAG")        # raw read
    b = os.getenv("RIPTIDE_FAULT_INJECT")           # raw read
    c = os.environ["RIPTIDE_CACHE_ROOT"]            # raw subscript
    d = envflags.get("RIPTIDE_NOT_REGISTERED")      # unknown flag
    return a, b, c, d
