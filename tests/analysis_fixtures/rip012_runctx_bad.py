"""RIP012 bad fixture: serve-plane thread spawns without a context
route (destination: riptide_tpu/serve/spawnmod.py; the real runctx.py
and incidents.py ride along in the mini repo so the wrap/establish/
emit fqns resolve)."""
import threading

from ..survey import incidents
from ..utils import runctx  # noqa: F401  (imported but never used: the bug)


class Daemon:
    def _worker(self):
        # Reaches incidents.emit -> prong 2 when spawned unwrapped.
        incidents.emit("chunk_parked", reason="drill")

    def _plain(self):
        return 1

    def start(self):
        # Unwrapped target that emits: finding (prong 2).
        threading.Thread(target=self._worker, daemon=True).start()
        # Unwrapped target in the serve plane: finding (prong 1).
        threading.Thread(target=self._plain, daemon=True).start()

    def enqueue(self, pool):
        # Plain alias does not launder the handoff: finding.
        handle = self._worker
        pool.submit(handle)
