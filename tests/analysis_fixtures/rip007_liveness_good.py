"""GOOD fixture for RIP007: collectives only inside the allowed
bounded-wait wrappers."""
from jax.experimental import multihost_utils


def ok(x):
    # The allowed wrapper (tests allowlist this function name); its
    # presence also satisfies the vacuous-lint guard.
    return multihost_utils.process_allgather(x)


def caller(x):
    return ok(x)
