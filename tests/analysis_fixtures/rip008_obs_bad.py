"""BAD fixture for RIP008 (obs discipline): bare span() calls, tracing
inside a jit body and a Pallas kernel closure, and an unregistered
observability flag."""
import jax
import jax.experimental.pallas as pl

from riptide_tpu.obs.trace import span
from riptide_tpu.utils import envflags


def leaky(x):
    s = span("phase", chunk=1)  # BAD: span() not used as a context manager
    s.__enter__()
    return x


@jax.jit
def traced(x):
    with span("inside_jit"):  # BAD: tracing call inside a jit body
        return x * 2


def _kernel(x_ref, o_ref):
    with span("inside_kernel"):  # BAD: tracing inside a kernel closure
        o_ref[...] = x_ref[...]


def launch(x):
    return pl.pallas_call(_kernel, out_shape=x, grid=(1,))(x)


RING = envflags.get("RIPTIDE_TRACE_BOGUS")  # BAD: unregistered flag
