"""BAD fixture for RIP002: implicit dtypes in the numeric core."""
import jax.numpy as jnp
import numpy as np


def prefix(data, pad):
    cs = np.cumsum(data)                 # accumulator dtype unstated
    buf = np.zeros(pad)                  # silent float64
    idx = jnp.arange(16)                 # index dtype unstated
    w = jnp.asarray([1.0, 2.0])          # weak-type literal
    return cs, buf, idx, w
