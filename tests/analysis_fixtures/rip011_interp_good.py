"""RIP011 good fixture: the helper chain below the jit body stays on
device end to end."""
import jax
import jax.numpy as jnp


def _deep(x):
    return jnp.sum(x)


def _peak_value(x):
    return jnp.max(x) + _deep(x)


@jax.jit
def search(x):
    return jnp.float32(_peak_value(x))
