"""GOOD fixture for RIP008 (obs discipline): spans only as context
managers on host-side phases, jit bodies and kernel closures free of
tracing, and only registered observability flags."""
import jax
import jax.experimental.pallas as pl

from riptide_tpu.obs.trace import span
from riptide_tpu.utils import envflags


def staged(x):
    with span("stage", chunk=0) as s:
        s.set(files=3)
        return x + 1


@jax.jit
def traced(x):
    return x * 2


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def launch(x):
    return pl.pallas_call(_kernel, out_shape=x, grid=(1,))(x)


TRACING = envflags.get("RIPTIDE_TRACE")
