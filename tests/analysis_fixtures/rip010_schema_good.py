"""RIP010 good fixture: the bad twin with both halves agreeing —
every consumed key and kind is emitted, and the decomposition-merged
row names no decomposition key of its own."""


def _append_line(path, obj):
    del path, obj


def write_chunk(path, cid):
    rec = {"kind": "chunk", "chunk_id": cid, "peaks_offset": 0}
    _append_line(path, rec)


def write_row(path, decomposition):
    row = {"kind": "ledger", "nrows": 1}
    row.update(decomposition or {})
    _append_line(path, row)


def read_chunks(records):
    out = []
    for rec in records:
        if rec.get("kind") == "chunk":
            out.append(rec["peaks_offset"])
    return out
