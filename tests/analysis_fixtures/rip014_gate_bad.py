"""RIP014 bad fixture: begin/acquire whose close is not on every path
(destination: riptide_tpu/survey/gatemod.py)."""


def run_chunk(chunk_gate, cid, work):
    chunk_gate.begin(cid)
    work(cid)          # raises -> the device turn is held forever
    chunk_gate.end(cid)


def prep(pool, fill):
    buf = pool.acquire((4, 4), "float32")
    fill(buf)          # raises -> the staging buffer leaks
    pool.release(buf)


class Folder:
    def fold(self, compute):
        acc = self.integrity.begin_fold("c0")
        compute(acc)   # raises -> the fold accumulator never closes
        return self.integrity.finish_fold(acc)
