"""RIP013 bad fixture: raw durable writes in a persistence-plane
module (destination: riptide_tpu/obs/writer.py)."""
import os


def rotate(path):
    os.replace(path, path + ".1")


def dump(path, text):
    with open(path, "w") as fobj:
        fobj.write(text)


def dump_fd(fd, data):
    os.write(fd, data)


def append_line(path, line):
    fobj = open(path, mode="ab")
    fobj.write(line)
    fobj.close()
