"""BAD fixture for RIP006: a checked entry point that skips the
data-quality layer."""
from .. import quality


def _scan(x):
    return quality.check_finite_array(x)


def boxcar_snr(x, widths):
    return x.sum() + len(widths)   # unguarded: no quality routing


def snr_batched(x, widths):
    _scan(x)                       # guarded via a local helper
    return x.sum()
