"""RIP012 good fixture: every serve-plane thread either goes through
runctx.wrap or establishes its own context (destination:
riptide_tpu/serve/spawnmod.py)."""
import threading

from ..survey import incidents
from ..utils import runctx


class Daemon:
    def _worker(self):
        incidents.emit("chunk_parked", reason="drill")

    def _job_loop(self):
        # Establishes its own context: compliant without wrap().
        ctx = runctx.RunContext(label="job")
        prev = runctx.install(ctx)
        try:
            incidents.emit("chunk_parked", reason="drill")
        finally:
            runctx.install(prev)

    def start(self):
        # Wrapped inline.
        threading.Thread(target=runctx.wrap(self._worker),
                         daemon=True).start()
        # Context-establishing target.
        threading.Thread(target=self._job_loop, daemon=True).start()

    def enqueue(self, pool):
        # Wrap-alias form.
        handle = runctx.wrap(self._worker)
        pool.submit(handle)
