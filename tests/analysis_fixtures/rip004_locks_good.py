"""GOOD fixture for RIP004: bounded waits, explicit daemon flags,
blocking work outside the critical section."""
import subprocess
import threading
import time

_lock = threading.Lock()


def build_outside_lock(cmd):
    with _lock:
        stale = True
    if stale:
        subprocess.run(cmd, check=True)


def shutdown(worker, done):
    worker.join(timeout=5.0)
    if worker.is_alive():
        raise TimeoutError("worker wedged")
    done.wait(5.0)


def spawn(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t


def pace():
    time.sleep(0.01)  # sleeping outside a lock is fine
