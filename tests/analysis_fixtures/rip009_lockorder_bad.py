"""RIP009 bad fixture: a cross-function lock-order cycle (each lock
acquired while the other is held, one of them through a helper call)
plus a lock-free write to an attribute guarded elsewhere."""
import threading

_b_lock = threading.Lock()


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def add(self):
        with self._lock:
            self.count = self.count + 1
            _grab_b()  # Store._lock -> _b_lock, one call deep

    def reset_unlocked(self):
        self.count = 0  # guarded in add(), lock-free here


_store = Store()


def _grab_b():
    with _b_lock:
        pass


def flush():
    with _b_lock:
        _store.add()  # _b_lock -> Store._lock: the inversion
