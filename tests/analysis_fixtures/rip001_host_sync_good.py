"""GOOD fixture for RIP001: the same shapes of code with the syncs
kept out of the traced/queueing regions."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("n",))
def traced(x, n):
    return jnp.sum(x) + jnp.float32(n)


def _queue_stages(plan, parts):
    return [jnp.asarray(p) for p in parts]  # host->device ship is fine


def collect(handles):
    # Pulls belong on the collect side — this function is not listed as
    # a queueing hot path.
    return [np.asarray(h) for h in handles]
