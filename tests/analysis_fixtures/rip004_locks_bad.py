"""BAD fixture for RIP004: blocking under a lock, untimed join/wait,
implicit daemon flag."""
import subprocess
import threading
import time

_lock = threading.Lock()


def build_under_lock(cmd):
    with _lock:
        subprocess.run(cmd, check=True)   # subprocess while holding a lock
        time.sleep(1.0)                   # sleep while holding a lock


def shutdown(worker, done):
    worker.join()                         # untimed join
    done.wait()                           # untimed wait


def spawn(fn):
    t = threading.Thread(target=fn)       # daemon flag unstated
    t.start()
    return t
