"""RIP009 good fixture: same shapes as the bad twin, but one global
acquisition order (never nested the other way) and every non-__init__
write to the guarded attribute holds the lock."""
import threading

_b_lock = threading.Lock()


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def add(self):
        with self._lock:
            self.count = self.count + 1
        _grab_b()  # outside the critical section: no ordering edge

    def reset(self):
        with self._lock:
            self.count = 0


_store = Store()


def _grab_b():
    with _b_lock:
        pass


def flush():
    _store.add()
