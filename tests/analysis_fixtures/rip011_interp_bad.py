"""RIP011 bad fixture: host sync pulls hidden one and two calls deep
below a jit body — invisible to RIP001's body scan, reachable through
the project call graph."""
import jax
import jax.numpy as jnp
import numpy as np


def _deep(x):
    return np.asarray(x).sum()


def _peak_value(x):
    return x.max().item() + _deep(x)


@jax.jit
def search(x):
    return jnp.float32(_peak_value(x))
