"""GOOD fixture for RIP002: every dtype named at the call site."""
import jax.numpy as jnp
import numpy as np


def prefix(data, pad):
    cs = np.cumsum(data, dtype=np.float64)
    buf = np.zeros(pad, np.float32)
    idx = jnp.arange(16, dtype=jnp.int32)
    w = jnp.asarray([1.0, 2.0], dtype=jnp.float32)
    arr = np.asarray(data, dtype=np.float32)  # named array: fine
    return cs, buf, idx, w, arr
