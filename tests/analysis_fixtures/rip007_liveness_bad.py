"""BAD fixture for RIP007: a raw multihost collective outside the
allowed wrappers, plus an alias import that would evade the call
check."""
from jax.experimental import multihost_utils
from jax.experimental import multihost_utils as mhu


def gather(x):
    return multihost_utils.process_allgather(x)   # raw collective


def ok(x):
    # The allowed wrapper (tests allowlist this function name).
    return multihost_utils.process_allgather(x)
