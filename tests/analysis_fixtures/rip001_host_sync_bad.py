"""BAD fixture for RIP001: host syncs inside a jit body and a hot
queueing path. Never imported — parsed by the analyzer only."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("n",))
def traced(x, n):
    y = x.sum().item()            # sync inside a jit body
    z = np.asarray(x)             # numpy pull inside a jit body
    return float(x[0]) + y + z[0]  # float() on a traced value


def _queue_stages(plan, parts):
    out = []
    for p in parts:
        p.block_until_ready()     # sync on the enqueue path
        out.append(np.asarray(p))  # device->host pull on the enqueue path
    return out
