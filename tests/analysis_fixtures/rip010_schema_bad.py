"""RIP010 bad fixture: one module holding a writer half and a reader
half that have drifted — the reader consumes a key the writer renamed
away and filters on a kind nothing emits, and the ledger-style row
literally names a decomposition key it later merges over itself."""


def _append_line(path, obj):
    del path, obj


def write_chunk(path, cid):
    rec = {"kind": "chunk", "chunk_id": cid, "peak_off": 0}
    _append_line(path, rec)


def write_row(path, decomposition):
    row = {"kind": "ledger", "chunk_s": 0.0}
    row.update(decomposition or {})
    _append_line(path, row)


def read_chunks(records):
    out = []
    for rec in records:
        if rec.get("kind") == "chunkz":
            out.append(rec["peaks_offset"])
    return out
