"""
Parity tests of the native C++ host runtime against the numpy oracles
(riptide_tpu/ops/reference.py) and the python plan builder. Skipped
entirely when the toolchain is unavailable.
"""
import numpy as np
import pytest

from riptide_tpu import native
from riptide_tpu.ops import reference as ref
from riptide_tpu.ops.plan import FFAPlan, num_levels

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)

rng = np.random.default_rng(42)


def test_ffa_tables_match_python_plan(monkeypatch):
    # FFAPlan takes the native fast path when available, so the pure
    # python builder must be forced explicitly or this test would
    # compare the C++ tables against themselves.
    ms = (2, 3, 5, 8, 13, 100, 257)
    with monkeypatch.context() as mp:
        mp.setattr(native, "available", lambda: False)
        plans = [FFAPlan(m) for m in ms]
    for m, plan in zip(ms, plans):
        h, t, shift = native.ffa_tables(m, plan.levels)
        np.testing.assert_array_equal(h, plan.h)
        np.testing.assert_array_equal(t, plan.t)
        np.testing.assert_array_equal(shift, plan.shift)


def test_ffa_tables_extra_levels_identity():
    m = 6
    L = num_levels(m) + 2
    h, t, shift = native.ffa_tables(m, L)
    R = m + 1
    for l in range(num_levels(m), L):
        np.testing.assert_array_equal(h[l][:m], np.arange(m))
        assert (t[l] == m).all() and (shift[l] == 0).all()
        assert h[l][m] == m


def test_ffa_transform_matches_oracle():
    for m, p in ((2, 8), (7, 16), (16, 33), (100, 50)):
        x = rng.standard_normal((m, p)).astype(np.float32)
        got = native.ffa_transform(x)
        want = ref.ffa_transform(x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_running_median_matches_oracle():
    x = rng.standard_normal(1000).astype(np.float32)
    for w in (3, 11, 101):
        got = native.running_median(x, w)
        want = ref.running_median(x, w)
        np.testing.assert_array_equal(got, want)


def test_running_median_with_duplicates():
    x = rng.integers(0, 4, size=500).astype(np.float32)
    got = native.running_median(x, 21)
    want = ref.running_median(x, 21)
    np.testing.assert_array_equal(got, want)


def test_downsample_matches_oracle():
    x = rng.standard_normal(10_000).astype(np.float32)
    for f in (2.0, 3.7, 13.2):
        got = native.downsample(x, f)
        want = ref.downsample(x, f)
        assert got.size == want.size
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_rollback_matches_np_roll():
    x = rng.standard_normal(97).astype(np.float32)
    for shift in (0, 1, 13, 96, 97, 150, -5):
        np.testing.assert_array_equal(
            native.rollback(x, shift), np.roll(x, -shift)
        )


def test_fused_rollback_add_matches_composition():
    x = rng.standard_normal(97).astype(np.float32)
    y = rng.standard_normal(97).astype(np.float32)
    for shift in (0, 1, 13, 96, 97, 150, -5):
        np.testing.assert_array_equal(
            native.fused_rollback_add(x, y, shift), x + np.roll(y, -shift)
        )
    with pytest.raises(ValueError):
        native.fused_rollback_add(x, y[:-1], 1)


def test_circular_prefix_sum_matches_oracle():
    x = rng.standard_normal(257).astype(np.float32)
    got = native.circular_prefix_sum(x, 400)
    want = ref.circular_prefix_sum(x, 400)
    np.testing.assert_allclose(got.astype(np.float32), want, rtol=1e-5, atol=1e-4)


def test_boxcar_snr_matches_oracle():
    x = rng.standard_normal((20, 64)).astype(np.float32)
    widths = np.array([1, 2, 3, 5, 9])
    got = native.boxcar_snr(x, widths, stdnoise=2.0)
    want = ref.boxcar_snr_2d(x, widths, stdnoise=2.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_decode8():
    raw = np.array([0, 1, 127, 128, 255], np.uint8).tobytes()
    np.testing.assert_array_equal(
        native.decode8(raw, signed=False), [0.0, 1.0, 127.0, 128.0, 255.0]
    )
    np.testing.assert_array_equal(
        native.decode8(raw, signed=True), [0.0, 1.0, 127.0, -128.0, -1.0]
    )


def test_read_f32(tmp_path):
    x = rng.standard_normal(100).astype(np.float32)
    path = tmp_path / "x.dat"
    x.tofile(path)
    np.testing.assert_array_equal(native.read_f32(path, 0, 100), x)
    np.testing.assert_array_equal(native.read_f32(path, 40, 10), x[10:20])


def test_benchmark_ffa_runs():
    sec = native.benchmark_ffa(64, 64, loops=2)
    assert 0 < sec < 10


def test_downsample_stages_matches_numpy():
    """Threaded all-stages batch downsample == the numpy reference path,
    bit-exactly, in both float32 and float16 wire dtypes."""
    from riptide_tpu.search.engine import (
        _ds_pack, _prefix_anchored, _stage_downsample,
    )
    from riptide_tpu.search.plan import periodogram_plan

    plan = periodogram_plan(1 << 16, 1e-3, (1, 2, 3), 64e-3, 2.0, 64, 71)
    batch = rng.standard_normal((3, 1 << 16)).astype(np.float32)
    d64, c32, anchors = _prefix_anchored(batch)
    want = np.stack([_stage_downsample(st, d64, c32, anchors)
                     for st in plan.stages])

    imin, imax, wmin, wmax, wint = _ds_pack(plan)
    got32 = native.downsample_stages(batch, imin, imax, wmin, wmax, wint,
                                     dtype=np.float32)
    np.testing.assert_array_equal(got32, want)
    got16 = native.downsample_stages(batch, imin, imax, wmin, wmax, wint,
                                     dtype=np.float16)
    np.testing.assert_array_equal(got16, want.astype(np.float16))


def test_downsample_stages_matches_numpy_ragged_n():
    """N % 4 != 0 exercises prefix_scan4's serial tail and the
    vector-to-tail carry handoff; native and numpy must still agree
    byte-for-byte."""
    from riptide_tpu.search.engine import (
        _ds_pack, _prefix_anchored, _stage_downsample,
    )
    from riptide_tpu.search.plan import periodogram_plan

    n = (1 << 16) + 3
    plan = periodogram_plan(n, 1e-3, (1, 2, 3), 64e-3, 2.0, 64, 71)
    batch = rng.standard_normal((2, n)).astype(np.float32)
    d64, c32, anchors = _prefix_anchored(batch)
    want = np.stack([_stage_downsample(st, d64, c32, anchors)
                     for st in plan.stages])
    imin, imax, wmin, wmax, wint = _ds_pack(plan)
    got = native.downsample_stages(batch, imin, imax, wmin, wmax, wint,
                                   dtype=np.float32)
    np.testing.assert_array_equal(got, want)


def test_downsample_stages_f16_conversion_edges():
    """The float16 wire conversion must be IEEE round-to-nearest-even for
    every regime numpy handles: normals, subnormals, overflow->inf, and
    exact ties. Exercised through a crafted 'downsample' whose plan is
    the identity (factor-1 stage), so values pass through untouched."""
    vals = np.array(
        [0.0, -0.0, 1.0, -1.0, 65504.0, 65520.0, 70000.0, -70000.0,
         6.1e-5, 5.96e-8, 2.98e-8, 2.0e-8, 1.0e-8, -6.1e-5,
         0.333251953125, 0.33325, 1e-3, 123.4567, -0.1],
        np.float32,
    )[None, :]
    n = vals.shape[1]
    imin = np.arange(n, dtype=np.int32)[None, :]
    imax = imin.copy()
    wmin = np.ones((1, n), np.float32)
    wmax = np.zeros((1, n), np.float32)
    wint = np.zeros((1, n), np.float32)
    got = native.downsample_stages(vals, imin, imax, wmin, wmax, wint,
                                   dtype=np.float16)[0, 0]
    np.testing.assert_array_equal(got, vals[0].astype(np.float16))
    # randomized sweep incl. tiny magnitudes (subnormal f16 range)
    r = rng.standard_normal(4096).astype(np.float32) * np.logspace(
        -8, 4, 4096, dtype=np.float32)
    r = r[None, :]
    m = np.arange(4096, dtype=np.int32)[None, :]
    got = native.downsample_stages(
        r, m, m.copy(), np.ones_like(r), np.zeros_like(r),
        np.zeros_like(r), dtype=np.float16)[0, 0]
    np.testing.assert_array_equal(got, r[0].astype(np.float16))
