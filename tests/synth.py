"""
Synthetic data-file writers for tests: produce PRESTO .inf/.dat pairs and
SIGPROC dedispersed time series with known content, so the readers and
the end-to-end apps can be exercised without real telescope data.
(Same role as riptide/tests/presto_generation.py and the checked-in
fixtures in riptide/tests/data/.)
"""
import os
import struct

import numpy as np

INF_COMMON = """\
 Data file name without suffix          =  {basename}
 Telescope used                         =  Parkes
 Instrument used                        =  Multibeam
 Object being observed                  =  Pulsar
 J2000 Right Ascension (hh:mm:ss.ssss)  =  00:00:01.0000
 J2000 Declination     (dd:mm:ss.ssss)  =  -00:00:01.0000
 Data observed by                       =  Test Suite
 Epoch of observation (MJD)             =  59000.000000
 Barycentered?           (1=yes, 0=no)  =  1
 Number of bins in the time series      =  {nsamp}
 Width of each time series bin (sec)    =  {tsamp:.12e}
 Any breaks in the data? (1=yes, 0=no)  =  {breaks}
{onoff}"""

INF_RADIO = """\
 Type of observation (EM band)          =  Radio
 Beam diameter (arcsec)                 =  981
 Dispersion measure (cm-3 pc)           =  {dm:.12f}
 Central freq of low channel (Mhz)      =  1182.1953125
 Total bandwidth (Mhz)                  =  400
 Number of channels                     =  1024
 Channel bandwidth (Mhz)                =  0.390625
 Data analyzed by                       =  Test Suite
 Any additional notes:
    Synthetic data written by the riptide_tpu test suite.
"""

# X-ray/Gamma .inf files replace the radio block with a photon-energy
# block (riptide/reading/presto.py:112-116 parsing; fixture shape per
# riptide/tests/data/README.md).
INF_XRAY = """\
 Type of observation (EM band)          =  {em_band}
 Field-of-view diameter (arcsec)        =  981
 Central energy (kev)                   =  1.0
 Energy bandpass (kev)                  =  0.87
 Data analyzed by                       =  Test Suite
 Any additional notes:
    Synthetic data written by the riptide_tpu test suite.
"""


def _pad_inf(text):
    """Align the '=' of each header line to column 40 as PRESTO does."""
    out = []
    for line in text.splitlines():
        if "=" in line:
            # rpartition: keys like "Barycentered? (1=yes, 0=no)" contain '='
            key, _, val = line.rpartition("=")
            out.append(key.ljust(40)[:40] + "=" + val)
        else:
            out.append(line)
    return "\n".join(out) + "\n"


def write_presto(outdir, basename, data, tsamp, dm=0.0, onoff_pairs=(),
                 em_band="Radio"):
    """Write a float32 array as a PRESTO .inf/.dat pair; returns the .inf
    path. ``onoff_pairs`` adds 'Any breaks ... = 1' plus one 'On/Off bin
    pair' line per pair; ``em_band`` of 'X-ray'/'Gamma' writes the
    photon-energy header block instead of the radio one."""
    data = np.asarray(data, dtype=np.float32)
    onoff = "".join(
        f" On/Off bin pair #{i + 1:2d}                     "
        f"=  {a}, {b}\n"
        for i, (a, b) in enumerate(onoff_pairs)
    )
    common = INF_COMMON.format(
        basename=basename, nsamp=data.size, tsamp=tsamp,
        breaks=1 if onoff_pairs else 0, onoff=onoff,
    )
    if em_band == "Radio":
        tail = INF_RADIO.format(dm=dm)
    else:
        tail = INF_XRAY.format(em_band=em_band)
    inf_path = os.path.join(outdir, f"{basename}.inf")
    with open(inf_path, "w") as fobj:
        fobj.write(_pad_inf(common + tail))
    data.tofile(os.path.join(outdir, f"{basename}.dat"))
    return inf_path


def generate_data_presto(outdir, basename, tobs=128.0, tsamp=256e-6, period=1.0,
                         dm=0.0, amplitude=20.0, ducy=0.05):
    """
    Seeded fake-pulsar PRESTO files (np.random.seed(0)), matching the
    deterministic generation of riptide/tests/presto_generation.py so the
    S/N oracle values carry over. Returns the .inf path.
    """
    from riptide_tpu import TimeSeries

    np.random.seed(0)
    ts = TimeSeries.generate(tobs, tsamp, period, amplitude=amplitude, ducy=ducy, stdnoise=1.0)
    return write_presto(outdir, basename, ts.data, tsamp, dm=dm)


def _sigproc_str(s):
    b = s.encode()
    return struct.pack("i", len(b)) + b


def write_sigproc(path, data, tsamp, nbits=32, signed=None, refdm=0.0,
                  src_raj=1.0, src_dej=-1.0, source_name="Pulsar", tstart=59000.0):
    """
    Write a single-channel SIGPROC dedispersed time series. nbits 32
    writes float32; nbits 8 writes int8/uint8 depending on ``signed``
    (pass signed=None to omit the 'signed' header key entirely, which
    readers must reject for 8-bit data).
    """
    data = np.asarray(data)
    hdr = _sigproc_str("HEADER_START")
    hdr += _sigproc_str("source_name") + _sigproc_str(source_name)
    hdr += _sigproc_str("src_raj") + struct.pack("d", src_raj)
    hdr += _sigproc_str("src_dej") + struct.pack("d", src_dej)
    hdr += _sigproc_str("tstart") + struct.pack("d", tstart)
    hdr += _sigproc_str("tsamp") + struct.pack("d", tsamp)
    hdr += _sigproc_str("nbits") + struct.pack("i", nbits)
    hdr += _sigproc_str("nchans") + struct.pack("i", 1)
    hdr += _sigproc_str("nifs") + struct.pack("i", 1)
    hdr += _sigproc_str("refdm") + struct.pack("d", refdm)
    if signed is not None:
        hdr += _sigproc_str("signed") + struct.pack("B", int(signed))
    hdr += _sigproc_str("HEADER_END")
    if nbits == 32:
        payload = data.astype(np.float32).tobytes()
    elif signed:
        payload = data.astype(np.int8).tobytes()
    else:
        payload = data.astype(np.uint8).tobytes()
    with open(path, "wb") as fobj:
        fobj.write(hdr + payload)
    return path
