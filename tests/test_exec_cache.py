"""
Cross-process executable cache (riptide_tpu/utils/exec_cache.py).

The real payoff needs the TPU backend (where JAX's persistent
compilation cache is unavailable); these tests exercise the wrapper's
correctness-critical plumbing on CPU: passthrough off-TPU, key
construction (numpy scalars keyed by VALUE, arrays by shape/dtype,
``cache_token`` objects by token), and the AOT load-or-compile path
with the backend check monkeypatched.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from riptide_tpu.utils import exec_cache
from riptide_tpu.utils.exec_cache import cached_jit


def test_passthrough_off_tpu():
    @cached_jit(static_argnames=("k",))
    def f(x, k):
        return x * k

    out = f(jnp.arange(4.0), k=3)
    np.testing.assert_allclose(np.asarray(out), [0, 3, 6, 9])


def test_key_distinguishes_numpy_scalar_values():
    @cached_jit(static_argnames=("off",))
    def f(x, off):
        return x + off

    # np.int64 statics must key by VALUE (an AOT executable bakes the
    # static in); arrays key by shape/dtype only.
    k1 = f._key([jnp.zeros(4), np.int64(0)])
    k2 = f._key([jnp.zeros(4), np.int64(4096)])
    assert k1 != k2
    k3 = f._key([jnp.ones(4), np.int64(0)])
    assert k1 == k3  # same shapes/dtypes, same statics

    class Tok:
        cache_token = ("plan", 1)

    class Tok2:
        cache_token = ("plan", 2)

    assert f._key([Tok()]) == f._key([Tok()])
    assert f._key([Tok()]) != f._key([Tok2()])


def test_key_distinguishes_shardings():
    """A dm-sharded and an unsharded array of identical shape must not
    share an AOT executable (the compiled program bakes in the input
    sharding)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    @cached_jit
    def f(x):
        return x + 1

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device (virtual CPU) backend")
    mesh = Mesh(np.array(devs), ("dm",))
    plain = jnp.zeros((len(devs), 4))
    sharded = jax.device_put(
        np.zeros((len(devs), 4), np.float32),
        NamedSharding(mesh, PartitionSpec("dm", None)),
    )
    assert f._key([plain]) != f._key([sharded])
    assert f._key([sharded]) == f._key([sharded])


def test_aot_path_on_forced_backend(monkeypatch, tmp_path):
    """With the backend check forced on, the wrapper AOT-compiles,
    memoizes per signature, and still returns correct results for both
    signatures (statics baked per executable)."""
    monkeypatch.setattr(exec_cache, "_on_tpu", lambda: True)
    monkeypatch.setattr(exec_cache, "_DIR", str(tmp_path))

    calls = []

    @cached_jit(static_argnames=("off",))
    def f(x, off):
        calls.append(off)
        return x + off

    x = jnp.arange(4.0)
    np.testing.assert_allclose(np.asarray(f(x, 1)), [1, 2, 3, 4])
    np.testing.assert_allclose(np.asarray(f(x, 10)), [10, 11, 12, 13])
    # repeat: memoized executables, no retrace
    n = len(calls)
    np.testing.assert_allclose(np.asarray(f(x, 1)), [1, 2, 3, 4])
    assert len(calls) == n


def test_off_switch(monkeypatch):
    monkeypatch.setattr(exec_cache, "_on_tpu", lambda: True)
    monkeypatch.setenv("RIPTIDE_EXEC_CACHE", "off")

    @cached_jit
    def f(x):
        return x * 2

    np.testing.assert_allclose(np.asarray(f(jnp.arange(3.0))), [0, 2, 4])
    assert not f._mem  # bypassed entirely


# ---------------------------------------------------------------------------
# cache_root hardening: a pre-existing .riptide_cache is only trusted
# when it is ours and not writable (or replaceable) by other users —
# entries are pickles executed at load time.
# ---------------------------------------------------------------------------

def _make_checkout(tmp_path):
    repo = tmp_path / "checkout"
    repo.mkdir(mode=0o755)
    return repo


def test_cache_root_env_override_wins(tmp_path, monkeypatch):
    monkeypatch.setenv("RIPTIDE_CACHE_ROOT", str(tmp_path / "explicit"))
    assert exec_cache.cache_root() == str(tmp_path / "explicit")


def test_cache_root_accepts_owned_0700_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("RIPTIDE_CACHE_ROOT", raising=False)
    repo = _make_checkout(tmp_path)
    cand = repo / ".riptide_cache"
    cand.mkdir(mode=0o700)
    assert exec_cache.cache_root(str(repo)) == str(cand)


def test_cache_root_rejects_group_other_writable_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("RIPTIDE_CACHE_ROOT", raising=False)
    repo = _make_checkout(tmp_path)
    cand = repo / ".riptide_cache"
    cand.mkdir(mode=0o777)  # spoofed: anyone can plant pickles
    import os as _os

    _os.chmod(cand, 0o777)  # bypass umask
    root = exec_cache.cache_root(str(repo))
    assert root != str(cand)
    assert f"riptide_tpu_cache_{_os.getuid()}" in root


def test_cache_root_rejects_symlinked_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("RIPTIDE_CACHE_ROOT", raising=False)
    repo = _make_checkout(tmp_path)
    elsewhere = tmp_path / "elsewhere"
    elsewhere.mkdir(mode=0o700)
    (repo / ".riptide_cache").symlink_to(elsewhere)
    root = exec_cache.cache_root(str(repo))
    assert root != str(repo / ".riptide_cache")


def test_cache_root_rejects_world_writable_parent(tmp_path, monkeypatch):
    monkeypatch.delenv("RIPTIDE_CACHE_ROOT", raising=False)
    import os as _os

    repo = _make_checkout(tmp_path)
    cand = repo / ".riptide_cache"
    cand.mkdir(mode=0o700)
    _os.chmod(repo, 0o777)  # any user may swap the cache dir wholesale
    try:
        root = exec_cache.cache_root(str(repo))
        assert root != str(cand)
    finally:
        _os.chmod(repo, 0o755)


def test_cache_root_fresh_checkout_uses_repo_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("RIPTIDE_CACHE_ROOT", raising=False)
    repo = _make_checkout(tmp_path)
    assert exec_cache.cache_root(str(repo)) == str(repo / ".riptide_cache")


def test_dir_trusted_accepts_sticky_world_writable_parent(tmp_path):
    """/tmp-style parents (1777) are fine: the sticky bit stops other
    users replacing our entry even though the parent is world-writable."""
    import os as _os

    parent = tmp_path / "tmplike"
    parent.mkdir()
    _os.chmod(parent, 0o1777)
    d = parent / "cache"
    d.mkdir(mode=0o700)
    assert exec_cache._dir_trusted(str(d))
    _os.chmod(parent, 0o777)  # same but sticky cleared: replaceable
    assert not exec_cache._dir_trusted(str(d))


def test_user_tmp_cache_avoids_squatted_dir(tmp_path, monkeypatch):
    """A squatted/over-permissioned per-uid tempdir must NOT be used for
    pickle caching; a fresh private directory is created instead."""
    import os as _os

    monkeypatch.setattr(exec_cache.tempfile, "gettempdir",
                        lambda: str(tmp_path))
    squat = tmp_path / f"riptide_tpu_cache_{_os.getuid()}"
    squat.mkdir()
    _os.chmod(squat, 0o777)
    path = exec_cache._user_tmp_cache()
    assert path != str(squat)
    assert exec_cache._dir_trusted(path) or _os.path.isdir(path)


# ---------------------------------------------------------------------------
# Size-capped LRU eviction.
# ---------------------------------------------------------------------------

def _put_entry(d, name, nbytes, last_used=None):
    import os as _os
    import time as _time

    path = d / name
    path.write_bytes(b"x" * nbytes)
    if last_used is not None:
        _os.utime(path, (last_used, last_used))
    else:
        last_used = _time.time()
    return path


def test_lru_eviction_drops_oldest_past_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("RIPTIDE_EXEC_CACHE_MAX_BYTES", "250")
    d = tmp_path / "exec"
    d.mkdir()
    _put_entry(d, "old.pkl", 100, last_used=1000.0)
    _put_entry(d, "mid.pkl", 100, last_used=2000.0)
    new = _put_entry(d, "new.pkl", 100)
    exec_cache._lru_note(str(new), inserted=True)
    # 300 bytes > 250 cap: the LRU entry goes, the newer two stay.
    assert not (d / "old.pkl").exists()
    assert (d / "mid.pkl").exists() and (d / "new.pkl").exists()


def test_lru_touch_on_load_protects_warm_entries(tmp_path, monkeypatch):
    monkeypatch.setenv("RIPTIDE_EXEC_CACHE_MAX_BYTES", "250")
    d = tmp_path / "exec"
    d.mkdir()
    warm = _put_entry(d, "warm.pkl", 100, last_used=1000.0)
    _put_entry(d, "cold.pkl", 100, last_used=2000.0)
    # A load refreshes warm.pkl's last_used past cold.pkl's...
    exec_cache._lru_note(str(warm), inserted=False)
    new = _put_entry(d, "new.pkl", 100)
    exec_cache._lru_note(str(new), inserted=True)
    # ...so the eviction takes cold.pkl even though warm.pkl is older
    # on disk.
    assert (d / "warm.pkl").exists()
    assert not (d / "cold.pkl").exists()


def test_lru_never_evicts_just_inserted_entry(tmp_path, monkeypatch):
    monkeypatch.setenv("RIPTIDE_EXEC_CACHE_MAX_BYTES", "50")
    d = tmp_path / "exec"
    d.mkdir()
    new = _put_entry(d, "big.pkl", 100)  # alone over the cap
    exec_cache._lru_note(str(new), inserted=True)
    assert (d / "big.pkl").exists()


def test_lru_survives_corrupt_manifest(tmp_path, monkeypatch):
    monkeypatch.setenv("RIPTIDE_EXEC_CACHE_MAX_BYTES", "150")
    d = tmp_path / "exec"
    d.mkdir()
    (d / exec_cache._MANIFEST).write_text("{not json")
    _put_entry(d, "old.pkl", 100, last_used=1000.0)
    new = _put_entry(d, "new.pkl", 100)
    exec_cache._lru_note(str(new), inserted=True)  # rebuilds from scandir
    assert not (d / "old.pkl").exists()
    assert (d / "new.pkl").exists()


def test_aot_store_and_warm_load_with_lru(monkeypatch, tmp_path):
    """End to end through load_or_compile_exec: the store registers the
    entry in the manifest; a second call loads (not recompiles) and
    refreshes last_used — warm-load behaviour intact under the cap."""
    import json
    import os as _os

    import jax

    monkeypatch.setenv("RIPTIDE_EXEC_CACHE_MAX_BYTES", str(1 << 30))
    jitted = jax.jit(lambda x: x + 1)
    path = str(tmp_path / "entry.pkl")
    args = (jnp.zeros(4),)

    info = {}
    exec_cache.load_or_compile_exec(path, jitted, args, info=info)
    assert info["action"] == "compiled"
    manifest = json.loads((tmp_path / exec_cache._MANIFEST).read_text())
    assert "entry.pkl" in manifest
    t0 = manifest["entry.pkl"]["last_used"]

    info = {}
    fn = exec_cache.load_or_compile_exec(path, jitted, args, info=info)
    assert info["action"] == "loaded"
    np.testing.assert_allclose(np.asarray(fn(jnp.zeros(4))), [1, 1, 1, 1])
    manifest = json.loads((tmp_path / exec_cache._MANIFEST).read_text())
    assert manifest["entry.pkl"]["last_used"] >= t0
    assert _os.path.exists(path)
