"""
Cross-process executable cache (riptide_tpu/utils/exec_cache.py).

The real payoff needs the TPU backend (where JAX's persistent
compilation cache is unavailable); these tests exercise the wrapper's
correctness-critical plumbing on CPU: passthrough off-TPU, key
construction (numpy scalars keyed by VALUE, arrays by shape/dtype,
``cache_token`` objects by token), and the AOT load-or-compile path
with the backend check monkeypatched.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from riptide_tpu.utils import exec_cache
from riptide_tpu.utils.exec_cache import cached_jit


def test_passthrough_off_tpu():
    @cached_jit(static_argnames=("k",))
    def f(x, k):
        return x * k

    out = f(jnp.arange(4.0), k=3)
    np.testing.assert_allclose(np.asarray(out), [0, 3, 6, 9])


def test_key_distinguishes_numpy_scalar_values():
    @cached_jit(static_argnames=("off",))
    def f(x, off):
        return x + off

    # np.int64 statics must key by VALUE (an AOT executable bakes the
    # static in); arrays key by shape/dtype only.
    k1 = f._key([jnp.zeros(4), np.int64(0)])
    k2 = f._key([jnp.zeros(4), np.int64(4096)])
    assert k1 != k2
    k3 = f._key([jnp.ones(4), np.int64(0)])
    assert k1 == k3  # same shapes/dtypes, same statics

    class Tok:
        cache_token = ("plan", 1)

    class Tok2:
        cache_token = ("plan", 2)

    assert f._key([Tok()]) == f._key([Tok()])
    assert f._key([Tok()]) != f._key([Tok2()])


def test_key_distinguishes_shardings():
    """A dm-sharded and an unsharded array of identical shape must not
    share an AOT executable (the compiled program bakes in the input
    sharding)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    @cached_jit
    def f(x):
        return x + 1

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device (virtual CPU) backend")
    mesh = Mesh(np.array(devs), ("dm",))
    plain = jnp.zeros((len(devs), 4))
    sharded = jax.device_put(
        np.zeros((len(devs), 4), np.float32),
        NamedSharding(mesh, PartitionSpec("dm", None)),
    )
    assert f._key([plain]) != f._key([sharded])
    assert f._key([sharded]) == f._key([sharded])


def test_aot_path_on_forced_backend(monkeypatch, tmp_path):
    """With the backend check forced on, the wrapper AOT-compiles,
    memoizes per signature, and still returns correct results for both
    signatures (statics baked per executable)."""
    monkeypatch.setattr(exec_cache, "_on_tpu", lambda: True)
    monkeypatch.setattr(exec_cache, "_DIR", str(tmp_path))

    calls = []

    @cached_jit(static_argnames=("off",))
    def f(x, off):
        calls.append(off)
        return x + off

    x = jnp.arange(4.0)
    np.testing.assert_allclose(np.asarray(f(x, 1)), [1, 2, 3, 4])
    np.testing.assert_allclose(np.asarray(f(x, 10)), [10, 11, 12, 13])
    # repeat: memoized executables, no retrace
    n = len(calls)
    np.testing.assert_allclose(np.asarray(f(x, 1)), [1, 2, 3, 4])
    assert len(calls) == n


def test_off_switch(monkeypatch):
    monkeypatch.setattr(exec_cache, "_on_tpu", lambda: True)
    monkeypatch.setenv("RIPTIDE_EXEC_CACHE", "off")

    @cached_jit
    def f(x):
        return x * 2

    np.testing.assert_allclose(np.asarray(f(jnp.arange(3.0))), [0, 2, 4])
    assert not f._mem  # bypassed entirely
