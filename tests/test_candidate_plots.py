"""
Candidate construction + diagnostic-plot and Periodogram-plot smoke tests
(reference: riptide/candidate.py, riptide/periodogram.py plot/display and
the serialization round trip of riptide/tests/test_ffa_search_pgram.py).
"""
import matplotlib

matplotlib.use("Agg")

import numpy as np
import pandas
import pytest

from riptide_tpu import Periodogram, TimeSeries, ffa_search, load_json, save_json
from riptide_tpu.candidate import Candidate
from riptide_tpu.peak_detection import Peak
from riptide_tpu.pipeline.peak_cluster import PeakCluster


def _make_peak(snr, dm=0.0, period=1.0, width=3):
    return Peak(
        period=period, freq=1.0 / period, width=width, ducy=width / 256.0,
        iw=0, ip=0, snr=snr, dm=dm,
    )


@pytest.fixture(scope="module")
def candidate():
    np.random.seed(0)
    ts = TimeSeries.generate(length=30.0, tsamp=1e-3, period=1.0, amplitude=25.0)
    cluster = PeakCluster(
        [_make_peak(20.0, dm=0.0), _make_peak(18.0, dm=5.0), _make_peak(12.0, dm=10.0)]
    )
    return Candidate.from_pipeline_output(ts, cluster, bins=128, subints=8)


def test_candidate_attributes(candidate):
    assert candidate.params["snr"] == 20.0
    assert candidate.params["dm"] == 0.0
    assert candidate.subints.shape == (8, 128)
    assert candidate.profile.shape == (128,)
    np.testing.assert_allclose(candidate.profile, candidate.subints.sum(axis=0), rtol=1e-6)
    dms, snrs = candidate.dm_curve
    assert list(dms) == [0.0, 5.0, 10.0]
    assert list(snrs) == [20.0, 18.0, 12.0]
    assert isinstance(candidate.peaks, pandas.DataFrame)
    assert "Candidate(P0=" in str(candidate)


def test_candidate_subints_fallback_when_too_many():
    """Requested subints that don't fit fall back to one row per period
    (reference: riptide/candidate.py:89-96)."""
    np.random.seed(1)
    ts = TimeSeries.generate(length=10.0, tsamp=1e-3, period=1.0, amplitude=10.0)
    cluster = PeakCluster([_make_peak(15.0)])
    cand = Candidate.from_pipeline_output(ts, cluster, bins=64, subints=1000)
    assert cand.subints.ndim == 2
    assert cand.subints.shape[0] <= 10  # at most the full periods that fit


def test_candidate_plot_smoke(candidate, tmp_path):
    fig = candidate.plot()
    assert len(fig.axes) == 4
    import matplotlib.pyplot as plt

    plt.close(fig)
    out = tmp_path / "cand.png"
    candidate.savefig(out)
    assert out.exists() and out.stat().st_size > 0


def test_candidate_json_roundtrip(candidate, tmp_path):
    fname = tmp_path / "cand.json"
    save_json(fname, candidate)
    out = load_json(fname)
    assert isinstance(out, Candidate)
    assert out.params == candidate.params
    assert np.allclose(out.subints, candidate.subints)
    assert list(out.peaks.columns) == list(candidate.peaks.columns)
    assert out.tsmeta["source_name"] == candidate.tsmeta["source_name"]


def test_render_spawned_parallel_plots(candidate, tmp_path):
    """Candidate PNGs render concurrently in spawned CPU-only workers
    (parallel-plotting parity with the reference's process pool,
    riptide/pipeline/pipeline.py:370-379)."""
    from riptide_tpu.pipeline.pipeline import CandidateWriter, render_spawned

    writer = CandidateWriter(str(tmp_path), plot=True)
    arglist = list(enumerate([candidate] * 3))
    render_spawned(writer, arglist, processes=2)
    for rank in range(3):
        png = tmp_path / f"candidate_{rank:04d}.png"
        jsn = tmp_path / f"candidate_{rank:04d}.json"
        assert png.exists() and png.stat().st_size > 0
        assert jsn.exists() and jsn.stat().st_size > 0


@pytest.fixture(scope="module")
def pgram():
    np.random.seed(2)
    ts = TimeSeries.generate(length=20.0, tsamp=1e-3, period=1.0, amplitude=15.0)
    _, pg = ffa_search(ts, period_min=0.5, period_max=2.0, bins_min=32, bins_max=36)
    return pg


def test_periodogram_plot_smoke(pgram):
    import matplotlib.pyplot as plt

    fig = plt.figure()
    pgram.plot()  # max over widths
    plt.close(fig)
    fig = plt.figure()
    pgram.plot(iwidth=0)  # single width trial
    plt.close(fig)


def test_periodogram_json_roundtrip(pgram, tmp_path):
    fname = tmp_path / "pgram.json"
    save_json(fname, pgram)
    out = load_json(fname)
    assert isinstance(out, Periodogram)
    assert np.allclose(out.snrs, pgram.snrs)
    assert np.allclose(out.periods, pgram.periods)
    assert np.array_equal(out.foldbins, pgram.foldbins)
    assert np.array_equal(out.widths, pgram.widths)
