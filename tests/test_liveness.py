"""
Liveness-layer tests: deadline/watchdog hang detection, retry deadline
budgets, circuit breaking with parked chunks, bounded collective waits,
heartbeat-based peer-loss detection and the degraded local-only mode of
the multi-host exchange.

Everything runs on the CPU backend; hangs and peer losses are injected
(:mod:`riptide_tpu.survey.faults`) so the machinery is exercised
end-to-end without real hardware faults. The acceptance paths: an
injected ``hang`` is cancelled by the watchdog within its deadline,
retried, and the survey completes with identical data products; a
persistent failure opens the breaker and parks chunks without aborting
the survey; an injected ``peer_loss`` degrades to local-only mode
instead of deadlocking.
"""
import pytest

from riptide_tpu.survey.faults import FaultPlan, InjectedFault, InjectedPeerLoss
from riptide_tpu.survey.journal import SurveyJournal
from riptide_tpu.survey.liveness import (
    ChunkTimeout, ChunkWatchdog, Deadline, DurationEWMA,
    PeerLivenessMonitor, PeerTimeout, bounded_wait, is_timeout_error,
)
from riptide_tpu.survey.metrics import MetricsRegistry, get_metrics
from riptide_tpu.survey.scheduler import (
    CircuitBreaker, RetryPolicy, SurveyScheduler, run_with_retry,
)
from riptide_tpu.peak_detection import Peak

from synth import generate_data_presto

TOBS = 16.0
TSAMP = 1e-3
PERIOD = 0.5


def _peak(period=0.5, snr=10.0, dm=0.0):
    return Peak(period=period, freq=1.0 / period, width=3, ducy=0.05,
                iw=1, ip=7, snr=snr, dm=dm)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------- deadline

def test_deadline_expiry_and_check():
    clk = FakeClock()
    d = Deadline(2.0, chunk_id=3, clock=clk)
    assert not d.expired
    assert d.remaining == 2.0
    clk.advance(1.5)
    d.check()  # still within budget
    clk.advance(1.0)
    assert d.expired
    with pytest.raises(ChunkTimeout):
        d.check()


def test_deadline_explicit_expire():
    d = Deadline(1e9, chunk_id=0, clock=FakeClock())
    d.expire()
    assert d.expired
    with pytest.raises(ChunkTimeout):
        d.check()


def test_is_timeout_error_classification():
    assert is_timeout_error(ChunkTimeout(0, 1.0))
    assert is_timeout_error(RuntimeError("DEADLINE_EXCEEDED: queue wedged"))
    assert not is_timeout_error(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    # The engine re-exports the helper next to is_oom_error.
    from riptide_tpu.search.engine import is_timeout_error as from_engine

    assert from_engine is is_timeout_error


# ------------------------------------------------------- EWMA + watchdog

def test_duration_ewma():
    e = DurationEWMA(alpha=0.5)
    assert e.value is None and e.count == 0
    e.observe(1.0)
    assert e.value == 1.0
    e.observe(3.0)
    assert e.value == 2.0  # 0.5*3 + 0.5*1
    assert e.count == 2


def test_watchdog_budget_clamps():
    w = ChunkWatchdog(k=2.0, floor_s=1.0, cap_s=30.0, initial_s=7.0)
    assert w.budget() == 7.0          # un-primed -> initial
    w.ewma.observe(1.0)
    assert w.budget() == 2.0          # k * EWMA
    # cap_s bounds the un-primed budget too.
    assert ChunkWatchdog(k=2.0, floor_s=1.0, cap_s=3.0,
                         initial_s=7.0).budget() == 3.0
    w2 = ChunkWatchdog(k=2.0, floor_s=1.0, cap_s=3.0)
    assert w2.budget() is None        # un-primed, no initial -> unbounded
    w2.ewma.observe(0.01)
    assert w2.budget() == 1.0         # floor
    w3 = ChunkWatchdog(k=2.0, floor_s=1.0, cap_s=3.0)
    w3.ewma.observe(100.0)
    assert w3.budget() == 3.0         # cap


def test_watchdog_rejects_bad_params():
    with pytest.raises(ValueError):
        ChunkWatchdog(k=0.0)
    with pytest.raises(ValueError):
        ChunkWatchdog(floor_s=10.0, cap_s=1.0)


def test_watchdog_runs_and_observes():
    w = ChunkWatchdog(k=4.0, floor_s=5.0, cap_s=30.0)
    assert w.run(lambda dl: 42, chunk_id=0) == 42   # unbounded first run
    assert w.ewma.count == 1
    assert w.run(lambda dl: dl.budget_s, chunk_id=1) > 0  # now bounded
    assert w.ewma.count == 2


def test_watchdog_abandons_hung_dispatch():
    import time

    w = ChunkWatchdog(k=2.0, floor_s=0.05, cap_s=0.1, initial_s=0.1)
    seen = {}

    def hung(deadline):
        seen["deadline"] = deadline
        time.sleep(2.0)
        deadline.check()  # the abandoned thread must stop here
        seen["dispatched"] = True  # pragma: no cover - must not happen

    t0 = time.monotonic()
    with pytest.raises(ChunkTimeout):
        w.run(hung, chunk_id=9)
    assert time.monotonic() - t0 < 1.0  # cancelled well before the sleep ends
    assert seen["deadline"].expired
    assert w.ewma.count == 0  # a timed-out attempt must not skew the EWMA


def test_watchdog_budget_escalates_after_timeouts():
    """Timeouts never feed the EWMA, so the budget must escalate per
    consecutive timeout — a workload that genuinely slowed down
    converges instead of timing out every chunk forever."""
    import time

    w = ChunkWatchdog(k=2.0, floor_s=0.05, cap_s=10.0, initial_s=0.05)
    assert w.budget() == 0.05
    with pytest.raises(ChunkTimeout):
        w.run(lambda dl: time.sleep(1.0), chunk_id=0)
    assert w.budget() == 0.1   # 2x after one timeout
    with pytest.raises(ChunkTimeout):
        w.run(lambda dl: time.sleep(1.0), chunk_id=0)
    assert w.budget() == 0.2   # 4x after two
    w.run(lambda dl: None, chunk_id=0)  # success resets the escalation
    assert w.ewma.count == 1
    assert w.budget() == 0.05  # floor'd k*EWMA, no escalation factor


def test_watchdog_propagates_dispatch_errors():
    w = ChunkWatchdog(initial_s=5.0)

    def boom(deadline):
        raise ValueError("no")

    with pytest.raises(ValueError):
        w.run(boom, chunk_id=0)


# ---------------------------------------------------------- bounded_wait

def test_bounded_wait_passthrough_and_timeout():
    import time

    assert bounded_wait(lambda: 5, None) == 5
    assert bounded_wait(lambda: 5, 1.0) == 5
    with pytest.raises(ValueError):
        bounded_wait(lambda: (_ for _ in ()).throw(ValueError("x")), 1.0)
    t0 = time.monotonic()
    with pytest.raises(PeerTimeout):
        bounded_wait(lambda: time.sleep(3.0), 0.05, what="test collective")
    assert time.monotonic() - t0 < 1.0


# ------------------------------------------------------- retry deadline

def test_retry_deadline_budget_stops_retrying():
    clk = FakeClock()
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        clk.advance(s)

    retry = RetryPolicy(max_retries=10, base_s=1.0, cap_s=8.0, jitter=0.0,
                        deadline_s=2.5, sleep=sleep, clock=clk)
    m = MetricsRegistry()

    def work():
        raise InjectedFault("persistent")

    with pytest.raises(InjectedFault):
        run_with_retry(work, 0, retry, FaultPlan(), m)
    # delay 1.0 fits the 2.5s budget, the next (2.0) would overrun it.
    assert sleeps == [1.0]
    assert m.counter("chunks_retried") == 1


def test_retry_reraises_operator_interrupts_immediately():
    sleeps = []
    retry = RetryPolicy(max_retries=5, sleep=sleeps.append)
    m = MetricsRegistry()

    def interrupted():
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        run_with_retry(interrupted, 0, retry, FaultPlan(), m)

    def exiting():
        raise SystemExit(1)

    with pytest.raises(SystemExit):
        run_with_retry(exiting, 0, retry, FaultPlan(), m)
    assert sleeps == []  # never slept through an interrupt
    assert m.counter("chunks_retried") == 0


def test_retry_counts_timeouts():
    retry = RetryPolicy(max_retries=5, sleep=lambda s: None)
    m = MetricsRegistry()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise ChunkTimeout(0, 0.5)
        return "ok"

    result, attempts = run_with_retry(flaky, 0, retry, FaultPlan(), m)
    assert result == "ok" and attempts == 3
    assert m.counter("chunks_timed_out") == 2
    assert m.counter("chunks_retried") == 2


# ------------------------------------------------------ circuit breaker

def test_breaker_state_machine():
    get_metrics().reset()
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=2, cooldown_s=10.0, clock=clk)
    assert b.state == b.CLOSED and b.allow()
    b.record_failure()
    assert b.state == b.CLOSED and b.allow()  # below threshold
    b.record_failure()
    assert b.state == b.OPEN and not b.allow()
    assert get_metrics().counter("breaker_opens") == 1
    clk.advance(11.0)
    assert b.state == b.HALF_OPEN
    assert b.allow()                          # the probe chunk
    b.record_success()
    assert b.state == b.CLOSED and b.allow()


def test_breaker_probe_failure_reopens():
    get_metrics().reset()
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=clk)
    b.record_failure()
    assert not b.allow()
    clk.advance(6.0)
    assert b.allow()          # half-open probe
    b.record_failure()        # probe fails
    assert b.state == b.OPEN and not b.allow()
    assert get_metrics().counter("breaker_opens") == 2
    # Success is also reachable from closed after intervening failures.
    b.record_success()
    assert b.state == b.CLOSED


def test_breaker_rejects_bad_threshold():
    with pytest.raises(ValueError):
        CircuitBreaker(failure_threshold=0)


# ------------------------------------------------------ new fault kinds

def test_fault_plan_hang_straggle_peer_loss():
    sleeps = []
    plan = FaultPlan.parse("hang:2:5,straggle:1:0.5,peer_loss:3",
                           sleep=sleeps.append)
    plan.in_flight(0)                 # no directive
    assert sleeps == []
    plan.in_flight(2)                 # hang
    assert sleeps == [5.0]
    plan.in_flight(1)                 # straggle
    assert sleeps == [5.0, 0.5]
    plan.in_flight(2)                 # consumed
    assert sleeps == [5.0, 0.5]
    with pytest.raises(InjectedPeerLoss):
        plan.before_gather(3)
    plan.before_gather(3)             # consumed
    # InjectedPeerLoss routes through the PeerTimeout handling.
    assert issubclass(InjectedPeerLoss, PeerTimeout)


# -------------------------------------------- journal: parked + beats

def test_journal_parked_records(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("abc", 3)
    j.record_parked(1, "circuit open", files=["/x/b.inf"])
    parked = j.parked_chunks()
    assert sorted(parked) == [1]
    assert parked[1]["reason"] == "circuit open"
    assert parked[1]["files"] == ["b.inf"]
    # A later completed record supersedes the parked state.
    j.record_chunk(1, ["b.inf"], [5.0], [_peak()])
    assert j.parked_chunks() == {}
    assert sorted(j.completed_chunks()) == [1]


def test_journal_heartbeat_sidecars(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.heartbeat(0, ts=1.5)
    j.heartbeat(0, ts=2.5)
    j.heartbeat(1, ts=2.0)
    assert j.read_heartbeats() == {0: 2.5, 1: 2.0}
    # Sidecars are per-process files: no shared-file write contention.
    names = sorted(p.name for p in (tmp_path / "j").glob("heartbeat_*"))
    assert names == ["heartbeat_0000.jsonl", "heartbeat_0001.jsonl"]


# ------------------------------------------------- peer liveness monitor

def test_monitor_alive_lost_and_writer_failover(tmp_path):
    m = MetricsRegistry()
    j = SurveyJournal(tmp_path / "j")
    j.heartbeat(0, ts=2.0)   # age 8 at now=10 -> lost
    j.heartbeat(2, ts=7.0)   # age 3 -> alive
    mon = PeerLivenessMonitor(j, process_index=1, process_count=3,
                              max_age_s=5.0, clock=lambda: 10.0, metrics=m)
    assert mon.alive() == [1, 2]
    assert mon.lost() == [0]
    assert mon.journal_writer() == 1  # failover: lowest ALIVE process
    assert m.snapshot()["gauges"]["heartbeat_age_s"] == 8.0


def test_monitor_unknown_peers_count_alive(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    mon = PeerLivenessMonitor(j, process_index=1, process_count=3,
                              max_age_s=5.0, clock=lambda: 10.0,
                              metrics=MetricsRegistry())
    # No heartbeats at all: peers may still be initialising.
    assert mon.alive() == [0, 1, 2]
    assert mon.journal_writer() == 0


def test_monitor_never_beat_peer_lost_after_grace(tmp_path):
    """A peer that NEVER heartbeats counts alive only within the
    max_age_s grace window from monitor construction: a process that
    crashed during startup must not hold the writer role forever."""
    clk = FakeClock(10.0)
    j = SurveyJournal(tmp_path / "j")
    mon = PeerLivenessMonitor(j, process_index=1, process_count=2,
                              max_age_s=5.0, clock=clk,
                              metrics=MetricsRegistry())
    assert mon.alive() == [0, 1]      # within the grace window
    clk.advance(6.0)
    assert mon.alive() == [1]         # grace expired, still no beat
    assert mon.lost() == [0]
    assert mon.journal_writer() == 1  # failover despite zero beats


def test_monitor_beat_and_unfinished_chunks(tmp_path):
    clk = FakeClock(100.0)
    j = SurveyJournal(tmp_path / "j")
    mon = PeerLivenessMonitor(j, process_index=0, process_count=2,
                              max_age_s=5.0, clock=clk,
                              metrics=MetricsRegistry())
    mon.beat()
    assert j.read_heartbeats() == {0: 100.0}
    j.record_chunk(1, ["b.inf"], [5.0], [])
    assert mon.unfinished_chunks(3) == [0, 2]


def test_monitor_background_beater(tmp_path):
    """The background heartbeat thread keeps a slow-but-alive process
    fresh independent of chunk progress (no per-chunk beat needed), so
    it can never spuriously lose the journal-writer role."""
    import time

    j = SurveyJournal(tmp_path / "j")
    mon = PeerLivenessMonitor(j, process_index=0, process_count=1,
                              max_age_s=10.0, metrics=MetricsRegistry())
    mon.start_beating(interval_s=0.05)
    mon.start_beating(interval_s=0.05)  # idempotent

    def beats():
        with open(j.directory + "/heartbeat_0000.jsonl") as f:
            return len(f.readlines())

    try:
        # Poll rather than a fixed sleep: a loaded CI box can starve
        # the beater thread well past 3 x interval_s; the property
        # under test is that beats keep FLOWING without any explicit
        # beat() call, not their exact rate.
        deadline = time.monotonic() + 10.0
        while beats() < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        mon.stop_beating()
    first = j.read_heartbeats()[0]
    assert first > 0
    assert beats() >= 3


def test_monitor_partial_chunks(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.record_chunk(0, ["a.inf"], [0.0], [_peak()])
    j.record_chunk(1, ["b.inf"], [5.0], [_peak()],
                   extra={"scope": "local", "process": 1})
    mon = PeerLivenessMonitor(j, process_index=1, process_count=2,
                              max_age_s=5.0, metrics=MetricsRegistry())
    assert mon.partial_chunks() == [1]
    assert mon.unfinished_chunks(2) == []  # local records still complete


# --------------------------------------- multihost degraded local mode

@pytest.fixture
def undegraded():
    import riptide_tpu.parallel.multihost as mh

    mh.reset_degraded()
    yield mh
    mh.reset_degraded()


def test_gather_injected_peer_loss_degrades(monkeypatch, undegraded):
    mh = undegraded
    get_metrics().reset()
    monkeypatch.setattr(mh.jax, "process_count", lambda: 2)
    local = [_peak()]
    out = mh.gather_peaks(local, faults=FaultPlan.parse("peer_loss:5"),
                          chunk_id=5, timeout_s=1.0)
    assert out == local
    assert mh.is_degraded()
    assert get_metrics().counter("peer_losses") == 1
    # Sticky: later gathers skip the collectives entirely (no fault
    # needed, no deadlock risk).
    assert mh.gather_peaks(local, chunk_id=6, timeout_s=1.0) == local
    assert get_metrics().counter("peer_losses") == 1


def test_gather_collective_timeout_degrades(monkeypatch, undegraded):
    mh = undegraded
    get_metrics().reset()
    monkeypatch.setattr(mh.jax, "process_count", lambda: 2)

    def timed_out(arr, timeout_s, what):
        raise PeerTimeout(f"{what} did not complete")

    monkeypatch.setattr(mh, "_allgather", timed_out)
    local = [_peak(), _peak(snr=8.0)]
    assert mh.gather_peaks(local, timeout_s=0.1) == local
    assert mh.is_degraded()
    assert get_metrics().counter("peer_losses") == 1


def test_init_distributed_noop_returns_zero():
    from riptide_tpu.parallel.distributed import init_distributed

    assert init_distributed() == 0  # truthiness-compatible no-op


# ------------------------------------------------- config + CLI surface

def test_rseek_parser_has_deadline_flag():
    from riptide_tpu.apps.rseek import get_parser

    args = get_parser().parse_args(
        ["-f", "presto", "--deadline-s", "5", "x.inf"]
    )
    assert args.deadline_s == 5.0


def test_liveness_config_validation():
    import copy

    from riptide_tpu.pipeline.config_validation import (
        InvalidPipelineConfig, validate_pipeline_config,
    )

    base = _survey_config()
    conf = copy.deepcopy(base)
    conf["liveness"] = {"enabled": True, "watchdog_k": 3.0,
                        "watchdog_floor_s": 0.5, "retry_deadline_s": None,
                        "breaker_threshold": 2}
    out = validate_pipeline_config(conf)
    assert out["liveness"]["watchdog_k"] == 3.0
    assert out["liveness"]["retry_deadline_s"] is None

    bad = copy.deepcopy(base)
    bad["liveness"] = {"watchdog_k": 0.5}  # must be > 1
    with pytest.raises(InvalidPipelineConfig):
        validate_pipeline_config(bad)
    bad = copy.deepcopy(base)
    bad["liveness"] = {"watchdgo_k": 3.0}  # typo'd key
    with pytest.raises(InvalidPipelineConfig):
        validate_pipeline_config(bad)


def test_metrics_summary_exposes_liveness_counters():
    s = MetricsRegistry().summary()
    for name in ("chunks_timed_out", "breaker_opens", "chunks_parked",
                 "peer_losses"):
        assert s[name] == 0


# ----------------------------------------------- scheduler end to end

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def _searcher(**kwargs):
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=1, **kwargs)


def _three_trials(tmp_path):
    return [
        generate_data_presto(str(tmp_path), f"t_DM{dm:.2f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm,
                             amplitude=amp, ducy=0.02)
        for dm, amp in ((0.0, 15.0), (10.0, 40.0), (20.0, 15.0))
    ]


def _fast_retry(**kwargs):
    return RetryPolicy(max_retries=3, base_s=0.01, cap_s=0.02,
                       sleep=lambda s: None, **kwargs)


def test_scheduler_watchdog_cancels_hang_and_retries(tmp_path):
    """Acceptance: an injected hang on chunk 2 is abandoned by the
    watchdog within its EWMA-derived deadline, the chunk is retried,
    and the survey completes with the identical peak list."""
    files = _three_trials(tmp_path)
    chunks = [[f] for f in files]

    get_metrics().reset()
    expected = SurveyScheduler(_searcher(), chunks).run()  # warm + oracle

    get_metrics().reset()
    journal = SurveyJournal(tmp_path / "j")
    watchdog = ChunkWatchdog(k=4.0, floor_s=0.5, cap_s=30.0)
    sched = SurveyScheduler(
        _searcher(), chunks, journal=journal, retry=_fast_retry(),
        faults=FaultPlan.parse("hang:2:15"), watchdog=watchdog,
    )
    peaks = sched.run()
    assert peaks == expected  # exact float equality: same peaks
    assert get_metrics().counter("chunks_timed_out") >= 1
    assert get_metrics().counter("chunks_retried") >= 1
    done = journal.completed_chunks()
    assert sorted(done) == [0, 1, 2]
    assert done[2][0]["attempts"] >= 2
    # The hang was cancelled at the deadline, not ridden out: the
    # budget for chunk 2 was far below the 15s injected hang.
    assert watchdog.budget() < 15.0


def test_scheduler_straggler_survives_within_deadline(tmp_path):
    """A straggling (slow but alive) chunk must NOT be killed while it
    stays inside the watchdog budget, and its duration feeds the EWMA."""
    files = _three_trials(tmp_path)
    chunks = [[f] for f in files]

    get_metrics().reset()
    expected = SurveyScheduler(_searcher(), chunks).run()

    get_metrics().reset()
    watchdog = ChunkWatchdog(k=4.0, floor_s=10.0, cap_s=60.0)
    sched = SurveyScheduler(
        _searcher(), chunks, retry=_fast_retry(),
        faults=FaultPlan.parse("straggle:1:0.3"), watchdog=watchdog,
    )
    peaks = sched.run()
    assert peaks == expected
    assert get_metrics().counter("chunks_timed_out") == 0
    assert watchdog.ewma.count == 3


def test_scheduler_breaker_parks_persistent_failure(tmp_path):
    """Acceptance: the breaker opens after N consecutive failures and
    parks chunks (journaled, survey completes) instead of aborting; a
    later resume re-dispatches the parked chunks and converges on the
    uninterrupted result."""
    files = _three_trials(tmp_path)
    chunks = [[f] for f in files]

    get_metrics().reset()
    expected = SurveyScheduler(_searcher(), chunks).run()

    get_metrics().reset()
    jdir = tmp_path / "j"
    sched = SurveyScheduler(
        _searcher(), chunks, journal=SurveyJournal(jdir),
        retry=RetryPolicy(max_retries=1, sleep=lambda s: None),
        faults=FaultPlan.parse("raise:1x50"),
        breaker=CircuitBreaker(failure_threshold=1, cooldown_s=1e9),
    )
    peaks = sched.run()  # completes despite the persistent failure
    # Chunk 1 failed and opened the circuit; chunk 2 parked undispatched.
    journal = SurveyJournal(jdir)
    assert sorted(journal.completed_chunks()) == [0]
    assert sorted(journal.parked_chunks()) == [1, 2]
    assert get_metrics().counter("chunks_parked") == 2
    assert get_metrics().counter("breaker_opens") == 1
    assert peaks == [p for p in expected if p.dm == 0.0]

    # The fault has "cleared": resume finishes the parked chunks and
    # the combined result matches the uninterrupted run exactly.
    get_metrics().reset()
    resumed = SurveyScheduler(
        _searcher(), chunks, journal=SurveyJournal(jdir), resume=True,
    ).run()
    assert resumed == expected
    assert SurveyJournal(jdir).parked_chunks() == {}


def test_scheduler_half_open_probe_recovers(tmp_path):
    """After the cooldown the breaker admits a probe chunk; its success
    closes the circuit and the rest of the survey dispatches normally."""
    files = _three_trials(tmp_path)
    chunks = [[f] for f in files]

    get_metrics().reset()
    expected = SurveyScheduler(_searcher(), chunks).run()

    get_metrics().reset()
    # cooldown 0: the breaker is half-open by the very next chunk.
    sched = SurveyScheduler(
        _searcher(), chunks, journal=SurveyJournal(tmp_path / "j"),
        retry=RetryPolicy(max_retries=0, sleep=lambda s: None),
        faults=FaultPlan.parse("raise:0"),
        breaker=CircuitBreaker(failure_threshold=1, cooldown_s=0.0),
    )
    peaks = sched.run()
    # Chunk 0 failed (parked, circuit opened); chunk 1 was the probe,
    # succeeded, closed the circuit; chunk 2 ran normally.
    assert get_metrics().counter("chunks_parked") == 1
    assert get_metrics().counter("breaker_opens") == 1
    assert peaks == [p for p in expected if p.dm != 0.0]


# ----------------------------------------------- pipeline end to end

def _survey_config():
    return {
        "processes": 1,
        "data": {"format": "presto", "fmin": None, "fmax": None,
                 "nchans": None},
        "dmselect": {"min": 0.0, "max": 30.0, "dmsinb_max": None},
        "dereddening": {"rmed_width": 4.0, "rmed_minpts": 101},
        "ranges": [{
            "name": "test",
            "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                           "bins_min": 64, "bins_max": 71,
                           "fpmin": 8, "wtsp": 1.5, "ducy_max": 0.30},
            "find_peaks": {"smin": 6.0},
            "candidates": {"bins": 64, "subints": 8},
        }],
        "clustering": {"radius": 0.2},
        "harmonic_flagging": {"denom_max": 100, "phase_distance_max": 1.0,
                              "dm_distance_max": 3.0,
                              "snr_distance_max": 3.0},
        "candidate_filters": {"dm_min": None, "snr_min": 7.0,
                              "remove_harmonics": True, "max_number": None},
        "plot_candidates": False,
    }


def test_pipeline_hang_byte_identical_products(tmp_path):
    """Acceptance: a pipeline survey with an injected hang completes
    (watchdog cancel + retry, from the YAML-style liveness config) and
    its peaks.csv is byte-identical to an unfaulted run's."""
    from riptide_tpu.pipeline import Pipeline

    indir = tmp_path / "data"
    indir.mkdir()
    files = [str(f) for f in _three_trials(indir)]

    out_a = tmp_path / "out_a"
    out_a.mkdir()
    get_metrics().reset()
    Pipeline(_survey_config()).process(files, str(out_a))  # warm + oracle

    conf = _survey_config()
    conf["liveness"] = {"enabled": True, "watchdog_k": 4.0,
                        "watchdog_floor_s": 0.5, "watchdog_cap_s": 30.0,
                        "breaker_threshold": 3,
                        "breaker_cooldown_s": 60.0}
    out_b = tmp_path / "out_b"
    out_b.mkdir()
    get_metrics().reset()
    Pipeline(conf, journal=str(tmp_path / "journal"),
             fault_spec="hang:2:15").process(files, str(out_b))
    assert get_metrics().counter("chunks_timed_out") >= 1

    for product in ("peaks.csv", "candidates.csv"):
        a = (out_a / product).read_bytes()
        b = (out_b / product).read_bytes()
        assert a == b, f"{product} differs between unfaulted and hung run"
    # The journal's metrics snapshot records the hang for posterity.
    snap = SurveyJournal(str(tmp_path / "journal")).last_metrics()
    assert snap["chunks_timed_out"] >= 1
    assert snap["chunks_parked"] == 0
