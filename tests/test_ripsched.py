"""ripsched: the schedule-exploration model checker.

What is verified here:

* the pinned model registry: 4 models, their invariant ids all mapped
  to RIPS SARIF rules, the spec document round-trips through the pin
  file, and drift is refused with the re-pin instruction;
* non-vacuity: every seeded mutation is DETECTED (a violation with
  the right invariant and a replayable schedule ID) — an invariant
  that no mutation can trip proves nothing;
* soundness on the real protocols: every model explores clean at the
  default preemption bound;
* determinism: replaying a violation's schedule ID reproduces it with
  a byte-identical trace, run to run;
* the CLI contract: exit codes 0 (clean), 1 (violation / replay
  reproduces), 2 (usage, spec drift, replay divergence).
"""
import importlib.util
import io
import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RIPSCHED = os.path.join(REPO, "tools", "ripsched.py")
SCHED = os.path.join(REPO, "riptide_tpu", "analysis", "sched.py")


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


sched = _load("sched_under_test", SCHED)
ripsched = _load("ripsched_under_test", RIPSCHED)


# -- registry + spec pin ----------------------------------------------------

def test_model_registry_shape():
    """The advertised checking surface: 4 models, 18 invariants, 8
    seeded mutations, every invariant mapped to a RIPS SARIF rule.
    Growing the registry is welcome — update this pin AND re-pin
    tools/ripsched_invariants.json in the same change."""
    assert sorted(sched.MODELS) == ["fairshare", "quarantine",
                                    "runctx", "staging"]
    n_inv = sum(len(m.invariants) for m in sched.MODELS.values())
    n_mut = sum(len(m.mutations) for m in sched.MODELS.values())
    assert n_inv == 18 and n_mut == 8
    for spec_ in sched.MODELS.values():
        assert spec_.targets, "every model names its target modules"
        for inv, desc in spec_.invariants:
            assert inv in sched._INV and desc
            assert sched.sarif_rule_of(inv).startswith("RIPS")
    assert len(sched.SARIF_RULES) == 6


def test_spec_doc_matches_pinned_file():
    with open(os.path.join(REPO, "tools",
                           "ripsched_invariants.json")) as fobj:
        assert json.load(fobj) == sched.spec_doc()


def test_spec_drift_refused_with_repin_instruction(tmp_path):
    doc = sched.spec_doc()
    doc["models"]["fairshare"]["invariants"].pop()
    drifted = tmp_path / "specs.json"
    drifted.write_text(json.dumps(doc))
    err = io.StringIO()
    code = ripsched.run(models=["staging"], specs_path=str(drifted),
                        out=io.StringIO(), err=err)
    assert code == 2
    assert "--write-specs" in err.getvalue()

    # A missing pin is the same refusal...
    err2 = io.StringIO()
    code2 = ripsched.run(models=["staging"],
                         specs_path=str(tmp_path / "absent.json"),
                         out=io.StringIO(), err=err2)
    assert code2 == 2 and "--write-specs" in err2.getvalue()

    # ... and --write-specs is the remedy.
    err3 = io.StringIO()
    assert ripsched.run(do_write_specs=True,
                        specs_path=str(drifted), err=err3) == 0
    assert "pinned 4 model(s) / 18 invariant(s)" in err3.getvalue()
    assert json.loads(drifted.read_text()) == sched.spec_doc()


# -- non-vacuity: every mutation is detected --------------------------------

MUTATIONS = [(name, mut)
             for name, spec_ in sorted(sched.MODELS.items())
             for mut in sorted(spec_.mutations)]


@pytest.mark.parametrize("model,mut", MUTATIONS,
                         ids=[f"{m}+{u}" for m, u in MUTATIONS])
def test_every_mutation_is_detected(model, mut):
    """Each seeded bug must produce a violation of an invariant the
    model declares, with a schedule ID that parses back to the run."""
    res = sched.explore_model(model, mutation=mut)
    vio = res.violation
    assert vio is not None, \
        f"mutation {model}+{mut} explored {res.schedules} schedule(s) " \
        "without tripping any invariant — the checker is vacuous for it"
    declared = [i for i, _ in sched.MODELS[model].invariants]
    assert vio.invariant in declared
    assert vio.message and vio.trace_lines
    got = sched.parse_schedule_id(vio.schedule_id)
    assert got[0] == model and got[1] == mut


def test_minimality_first_violation_is_preemption_minimal():
    """Iterative bounding contract: the reported violation carries the
    preemption count of the bound level it was found at, and replaying
    it reproduces the same invariant."""
    res = sched.explore_model("fairshare", mutation="drop_notify")
    vio = res.violation
    assert vio.preemptions <= res.bound
    rep = sched.replay(vio.schedule_id)
    assert rep.diverged is None
    assert rep.violation is not None
    assert rep.violation.invariant == vio.invariant


# -- soundness: the real protocols explore clean ----------------------------

@pytest.mark.parametrize("model", sorted(sched.MODELS))
def test_unmutated_model_explores_clean(model):
    res = sched.explore_model(model, max_schedules=150)
    assert res.violation is None, res.violation.render()
    assert res.schedules >= 1 and res.decisions >= 1


# -- determinism ------------------------------------------------------------

def test_replay_is_byte_identical_across_runs():
    res = sched.explore_model("fairshare", mutation="drop_notify")
    sid = res.violation.schedule_id
    first = sched.replay(sid).render()
    second = sched.replay(sid).render()
    assert first == second
    assert sid in first


def test_malformed_schedule_id_rejected():
    with pytest.raises(ValueError, match="malformed schedule id"):
        sched.parse_schedule_id("bogus")
    with pytest.raises(ValueError, match="unknown model"):
        sched.parse_schedule_id("nosuchmodel:000")
    with pytest.raises(ValueError, match="unknown mutation"):
        sched.parse_schedule_id("fairshare+nosuch:000")
    with pytest.raises(ValueError, match="malformed schedule digits"):
        sched.parse_schedule_id("fairshare:12a")
    with pytest.raises(ValueError):
        sched.replay("nosuchmodel:000")


def test_unknown_model_and_mutation_rejected():
    with pytest.raises(ValueError, match="unknown model"):
        sched.explore_model("nosuchmodel")
    with pytest.raises(ValueError, match="unknown mutation"):
        sched.explore_model("fairshare", mutation="nosuchmutation")


# -- CLI contract -----------------------------------------------------------

def test_cli_clean_explore_exit_zero():
    out, err = io.StringIO(), io.StringIO()
    code = ripsched.run(models=["staging", "quarantine"],
                        out=out, err=err)
    assert code == 0, out.getvalue() + err.getvalue()
    assert "ripsched OK" in err.getvalue()
    assert "zero violations" in err.getvalue()


def test_cli_mutation_exit_one_with_minimal_schedule():
    out, err = io.StringIO(), io.StringIO()
    code = ripsched.run(models=["staging"], mutation="double_release",
                        out=out, err=err)
    assert code == 1
    assert "invariant violation" in err.getvalue()
    assert "--replay" in out.getvalue()
    assert "staging+double_release:" in out.getvalue()


def test_cli_replay_reproduces_and_exits_one():
    res = sched.explore_model("staging", mutation="early_release")
    sid = res.violation.schedule_id
    out, err = io.StringIO(), io.StringIO()
    code = ripsched.run(replay_id=sid, out=out, err=err)
    assert code == 1
    assert sid in out.getvalue()


def test_cli_usage_errors_exit_two():
    # Unknown model.
    assert ripsched.run(models=["nosuchmodel"], out=io.StringIO(),
                        err=io.StringIO()) == 2
    # --mutate with more than one model.
    assert ripsched.run(models=["staging", "fairshare"],
                        mutation="double_release", out=io.StringIO(),
                        err=io.StringIO()) == 2
    # Malformed replay ID.
    assert ripsched.run(replay_id="bogus", out=io.StringIO(),
                        err=io.StringIO()) == 2


def test_cli_list_enumerates_registry():
    out = io.StringIO()
    assert ripsched.run(list_only=True, out=out) == 0
    text = out.getvalue()
    for name in sched.MODELS:
        assert f"{name}:" in text
    for inv in sched._INV:
        assert f"invariant {inv} " in text


def test_cli_sarif_shape():
    out, err = io.StringIO(), io.StringIO()
    code = ripsched.run(models=["runctx"], fmt="sarif",
                        out=out, err=err)
    assert code == 0
    doc = json.loads(out.getvalue())
    assert doc["version"] == "2.1.0"
    drv = doc["runs"][0]["tool"]["driver"]
    assert drv["name"] == "ripsched"
    assert [r["id"] for r in drv["rules"]] == \
        sorted(r[0] for r in sched.SARIF_RULES)
    assert doc["runs"][0]["results"] == []


def test_cli_sarif_violation_result_names_replay():
    out, err = io.StringIO(), io.StringIO()
    code = ripsched.run(models=["runctx"], mutation="unwrapped_worker",
                        fmt="sarif", out=out, err=err)
    assert code == 1
    results = json.loads(out.getvalue())["runs"][0]["results"]
    assert len(results) == 1
    assert results[0]["ruleId"].startswith("RIPS")
    assert "--replay" in results[0]["message"]["text"]


def test_env_defaults_come_from_the_registry():
    assert int(sched.env_default("RIPTIDE_SCHED_BOUND")) == 2
    assert int(sched.env_default("RIPTIDE_SCHED_SEED")) == 0
    assert sched.env_default("RIPTIDE_SCHED_REPLAY") == ""


def test_cli_subprocess_smoke():
    proc = subprocess.run(
        [sys.executable, RIPSCHED, "--model", "quarantine"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ripsched OK" in proc.stderr
