"""
Mesh-sharded search paths on the virtual 8-device CPU mesh
(tests/conftest.py forces JAX_PLATFORMS=cpu with
--xla_force_host_platform_device_count=8).

Covers the round-2 gaps: pytest coverage of run_periodogram_sharded
(1-D and 2-D meshes, D not divisible by the dm axis), the tiny-gather
survey path run_search_sharded, and a Pipeline(mesh=...) end-to-end run
(posture: riptide/tests/test_pipeline.py:14-31).
"""
import numpy as np
import pytest

import jax

from riptide_tpu.parallel import run_periodogram_sharded
from riptide_tpu.parallel.mesh import default_mesh, mesh_2d
from riptide_tpu.parallel.sharded import run_search_sharded
from riptide_tpu.search.engine import (
    run_periodogram, run_periodogram_batch, run_search_batch,
)
from riptide_tpu.search.plan import periodogram_plan
from riptide_tpu.libffa import generate_signal

TSAMP = 1e-3
N = 32768
PKW = dict(smin=6.0, segwidth=5.0, nstd=6.0, minseg=10, polydeg=2, clrad=0.1)


@pytest.fixture(scope="module")
def setup():
    plan = periodogram_plan(N, TSAMP, (1, 2, 3, 4), 64 * TSAMP, 0.3, 64, 71)
    rng = np.random.RandomState(7)
    batch = rng.normal(size=(5, N)).astype(np.float32)  # 5 % 8 != 0, 5 % 4 != 0
    np.random.seed(5)
    batch[2] = generate_signal(N, 0.1 / TSAMP, amplitude=16.0, ducy=0.05)
    batch -= batch.mean(axis=1, keepdims=True)
    batch /= batch.std(axis=1, keepdims=True)
    _, _, ref = run_periodogram_batch(plan, batch)
    return plan, batch, ref


def test_sharded_1d_mesh_parity(setup):
    plan, batch, ref = setup
    mesh = default_mesh()  # 8 devices on the 'dm' axis; D=5 gets padded
    assert mesh.shape["dm"] == len(jax.devices())
    periods, foldbins, snrs = run_periodogram_sharded(plan, batch, mesh=mesh)
    assert snrs.shape == ref.shape
    np.testing.assert_allclose(snrs, ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(periods, plan.all_periods)


def test_sharded_2d_mesh_parity(setup):
    plan, batch, ref = setup
    # B = 71 - 64 + 1 = 8 bins-trials, divisible by bins_shards=2
    mesh = mesh_2d(jax.devices(), bins_shards=2)
    _, _, snrs = run_periodogram_sharded(plan, batch, mesh=mesh)
    np.testing.assert_allclose(snrs, ref, rtol=1e-5, atol=1e-5)


def test_sharded_2d_mesh_bad_bins_axis(setup):
    plan, batch, _ = setup
    mesh = mesh_2d(jax.devices()[:6], bins_shards=3)  # 3 does not divide 8
    with pytest.raises(ValueError, match="does not divide"):
        run_periodogram_sharded(plan, batch, mesh=mesh)


def test_sharded_small_dm_axis(setup):
    """dm axis smaller than the device count, D divisible."""
    plan, batch, ref = setup
    mesh = default_mesh(jax.devices()[:5])
    _, _, snrs = run_periodogram_sharded(plan, batch, mesh=mesh)
    np.testing.assert_allclose(snrs, ref, rtol=1e-5, atol=1e-5)


def test_search_sharded_tiny_gather(setup):
    """The survey path: dm-sharded on-device peaks == unsharded peaks,
    and only peak buffers (not the S/N cube) reach the host."""
    plan, batch, _ = setup
    tobs = N * TSAMP
    dms = [0.0, 5.0, 10.0, 15.0, 20.0]
    want, _ = run_search_batch(plan, batch, tobs=tobs, dms=dms, **PKW)
    got, _ = run_search_sharded(
        plan, batch, tobs=tobs, dms=dms, mesh=default_mesh(), **PKW
    )
    assert len(got) == len(batch)
    for d in range(len(batch)):
        wset = [(p.ip, p.iw, round(p.snr, 4), p.dm) for p in want[d]]
        gset = [(p.ip, p.iw, round(p.snr, 4), p.dm) for p in got[d]]
        assert gset == wset, f"trial {d}"
    # the injected pulsar must be recovered through the sharded path
    assert got[2] and abs(got[2][0].period - 0.1) < 1e-3


def test_search_sharded_u6_wire_parity(setup):
    """The quantised (uint6 block-scaled) wire through the sharded path:
    the dm-sharded prepared bytes are identical to the unsharded wire
    row-for-row, and the sharded on-device peaks equal the unsharded
    peaks through the SAME transport (VERDICT r4 item 3)."""
    from riptide_tpu.parallel import prepare_stage_data_sharded
    from riptide_tpu.search.engine import prepare_stage_data

    plan, batch, _ = setup
    tobs = N * TSAMP
    dms = [0.0, 5.0, 10.0, 15.0, 20.0]
    mesh = default_mesh()

    flat, meta = prepare_stage_data(plan, batch, mode="uint6")
    (flat_sh, meta_sh), D = prepare_stage_data_sharded(
        plan, batch, mesh, mode="uint6"
    )
    # Byte-layout parity: the sharded wire is the unsharded wire with
    # zero-padded extra DM rows.
    assert D == len(batch)
    assert flat_sh.shape[0] % mesh.shape["dm"] == 0
    np.testing.assert_array_equal(flat_sh[:D], flat)
    np.testing.assert_array_equal(meta_sh["scales"][:D], meta["scales"])

    want, _ = run_search_batch(plan, None, tobs=tobs, dms=dms,
                               prepared=(flat, meta), **PKW)
    got, _ = run_search_sharded(plan, batch, tobs=tobs, dms=dms, mesh=mesh,
                                mode="uint6", **PKW)
    assert len(got) == len(batch)
    for d in range(len(batch)):
        wset = [(p.ip, p.iw, round(p.snr, 4), p.dm) for p in want[d]]
        gset = [(p.ip, p.iw, round(p.snr, 4), p.dm) for p in got[d]]
        assert gset == wset, f"trial {d}"
    assert got[2] and abs(got[2][0].period - 0.1) < 1e-3


def test_search_sharded_f16_wire_parity(setup):
    """The float16 wire through the sharded path: same transport on
    both sides must produce identical peaks (covers the float branch of
    the in-shard_map decode, which u6/u8/u12 tests do not touch)."""
    plan, batch, _ = setup
    tobs = N * TSAMP
    dms = [0.0, 5.0, 10.0, 15.0, 20.0]
    from riptide_tpu.search.engine import prepare_stage_data

    prepared = prepare_stage_data(plan, batch, mode="float16")
    want, _ = run_search_batch(plan, None, tobs=tobs, dms=dms,
                               prepared=prepared, **PKW)
    got, _ = run_search_sharded(plan, batch, tobs=tobs, dms=dms,
                                mesh=default_mesh(), mode="float16", **PKW)
    for d in range(len(batch)):
        wset = [(p.ip, p.iw, round(p.snr, 4), p.dm) for p in want[d]]
        gset = [(p.ip, p.iw, round(p.snr, 4), p.dm) for p in got[d]]
        assert gset == wset, f"trial {d}"
    assert got[2] and abs(got[2][0].period - 0.1) < 1e-3


@pytest.mark.slow
def test_pipeline_with_mesh(tmp_path):
    """Pipeline(mesh=...) end-to-end on synthetic PRESTO data: the
    DM-10 fake pulsar must come out as the top candidate through the
    mesh-sharded search (posture of the reference's real-multiprocess
    pipeline test, riptide/tests/test_pipeline.py:39-74).

    slow-marked: ~150 s on the virtual CPU mesh — run via `make tests`
    (tier-1 runs -m 'not slow'; this path was unrunnable there before
    the jax-0.4.x shard_map shim anyway)."""
    import os
    import sys
    import yaml

    sys.path.insert(0, os.path.join(os.path.dirname(__file__)))
    from synth import generate_data_presto

    from riptide_tpu.pipeline.pipeline import Pipeline

    indir = tmp_path / "data"
    outdir = tmp_path / "out"
    indir.mkdir()
    outdir.mkdir()
    fnames = []
    for dm, amp in ((0.0, 10.0), (10.0, 20.0), (20.0, 10.0)):
        fnames.append(generate_data_presto(
            str(indir), f"fake_DM{dm:.2f}", tobs=128.0, tsamp=256e-6,
            period=1.0, dm=dm, amplitude=amp, ducy=0.02,
        ))
    conf_path = os.path.join(os.path.dirname(__file__), "pipeline_config_A.yml")
    with open(conf_path) as f:
        conf = yaml.safe_load(f)

    pipe = Pipeline(conf, mesh=default_mesh())
    pipe.process(fnames, str(outdir))
    assert pipe.candidates, "no candidates from the mesh-sharded pipeline"
    best = pipe.candidates[0]
    assert abs(best.params["period"] - 1.0) < 1e-3
    assert best.params["dm"] == 10.0
    assert 17.0 < best.params["snr"] < 20.0


def test_sharded_2d_mesh_kernel_downgrade_warns(setup, caplog, monkeypatch):
    """A bins-sharded 2-D mesh cannot split the fused kernel's grid:
    forcing the kernel path must fall back to the gather formulation
    with a LOUD warning (a real throughput downgrade, not a silent
    routing choice) while staying numerically exact."""
    import logging

    plan, batch, ref = setup
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "kernel")
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float32")
    for st in plan.stages:
        st._sharded_calls = {}  # rebuild so the warning fires this run
    mesh = mesh_2d(jax.devices(), bins_shards=2)
    with caplog.at_level(logging.WARNING,
                         logger="riptide_tpu.parallel.sharded"):
        _, _, snrs = run_periodogram_sharded(plan, batch, mesh=mesh)
    assert any("falls back" in r.getMessage()
               and "bins-sharded" in r.getMessage()
               for r in caplog.records)
    np.testing.assert_allclose(snrs, ref, rtol=1e-5, atol=1e-5)


def test_sharded_1d_mesh_kernel_path_parity(monkeypatch):
    """The kernel path INSIDE shard_map (interpret mode on the virtual
    mesh) with the quantised wire: dm-sharded results must equal the
    unsharded fused kernel path bitwise — the per-trial wire bytes and
    the per-trial kernel programs are identical, sharding only routes
    them (and the in-shard_map decode is the same _udecode_view the
    fused prologue mirrors)."""
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "kernel")
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint6")
    # Same tiny two-stage plan as tests/test_fused_kernel.py, so one
    # pytest process shares the plan and its interpret-mode traces.
    plan = periodogram_plan(2500, TSAMP, (1, 2, 3), 64 * TSAMP, 0.072,
                            64, 67)
    rng = np.random.RandomState(9)
    batch = rng.normal(size=(2, 2500)).astype(np.float32)
    _, _, got = run_periodogram_sharded(plan, batch, mesh=default_mesh())
    for d in range(2):
        _, _, want = run_periodogram(plan, batch[d])
        np.testing.assert_array_equal(got[d], want)
