"""riptide_tpu test suite.

Lives at the repository root as ``tests/`` and installs as
``riptide_tpu.tests`` (see pyproject's package-dir mapping) so
``riptide_tpu.test()`` also works from an installed tree, mirroring the
reference's in-package test layout (riptide/tests/__init__.py:5-10).
"""
