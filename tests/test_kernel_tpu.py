"""
Compiled-kernel parity at PRODUCTION shapes.

The reference's suite tests its real compiled engine at production bins
(riptide/tests/test_ffa_search_pgram.py:11-47, tests/test_rseek.py:31-54
at bins 240-520); the CPU suite here can only run the Pallas kernel in
interpret mode, so a Mosaic lowering/layout regression would otherwise
pass `make tests` and die on hardware. The ``tpu``-marked sweep below
(run via `make tests-tpu` on the real chip) closes that gap: compiled
kernel vs the numpy oracle at the bins-240-260 cascade bucket plus the
480/500/520 and 960/1040 buckets. One interpret-mode case at production
bins runs in the default CPU suite as well.
"""
import numpy as np
import pytest

from riptide_tpu.ops.ffa_kernel import CycleKernel
from riptide_tpu.ops.reference import boxcar_snr_2d, ffa_transform
from riptide_tpu.ops.snr import boxcar_coeffs

WIDTHS = (1, 2, 3, 4, 6, 9, 13, 19, 28, 42)


def _kernel(ms, ps, interpret=False):
    widths = tuple(w for w in WIDTHS if w < min(ps))
    B, nw = len(ms), len(widths)
    h = np.zeros((B, nw), np.float32)
    b = np.zeros((B, nw), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.linspace(1.0, 2.0, B).astype(np.float32)
    return CycleKernel(ms, ps, widths, h, b, std, interpret=interpret), widths, std


def _check(ms, ps, interpret=False, seed=0, rel_tol=1e-4):
    k, widths, std = _kernel(ms, ps, interpret=interpret)
    rng = np.random.default_rng(seed)
    x = np.zeros((len(ms), k.rows, k.P), np.float32)
    datas = []
    for i, (m, p) in enumerate(zip(ms, ps)):
        d = rng.standard_normal((m, p)).astype(np.float32)
        datas.append(d)
        x[i, :m, :p] = d
    out = np.asarray(k(x))
    for i, (m, p, d) in enumerate(zip(ms, ps, datas)):
        want = boxcar_snr_2d(
            ffa_transform(d), np.asarray(widths), stdnoise=float(std[i])
        )
        got = out[i, :m, : len(widths)]
        rel = np.abs(got - want) / np.maximum(np.abs(want), 1.0)
        assert float(rel.max()) < rel_tol, (m, p, float(rel.max()))


def test_interpret_parity_production_bins():
    """One production-bins case through the interpret-mode kernel in the
    default CPU suite (bins 257, L = 10)."""
    _check([521], [257], interpret=True)


@pytest.mark.tpu
def test_compiled_parity_bins_240_260_bucket():
    """The headline benchmark's deepest cascade bucket: 21 problems,
    rows 2048, P 384, compiled on the real chip."""
    ms = [1046 - 4 * i for i in range(21)]
    ps = list(range(240, 261))
    _check(ms, ps)


@pytest.mark.tpu
def test_compiled_parity_bins_480_520():
    """The rseek/oracle test configuration's bins range."""
    _check([500, 481, 460], [480, 500, 520])


@pytest.mark.tpu
def test_compiled_parity_bins_960_1040():
    """Deep-bins bucket near the packed-word field limit region."""
    _check([250, 230], [960, 1040])


@pytest.mark.tpu
def test_tpu_end_to_end_search():
    """Small end-to-end ffa_search on the TPU engine path (compiled
    kernel + on-device peaks): the seeded pulsar must be recovered."""
    from riptide_tpu import TimeSeries, ffa_search
    from riptide_tpu.peak_detection import find_peaks

    np.random.seed(0)
    ts = TimeSeries.generate(
        length=16.384, tsamp=1e-3, period=0.128, amplitude=15.0, ducy=0.05
    )
    _, pgram = ffa_search(
        ts, period_min=0.1, period_max=0.5, bins_min=96, bins_max=104
    )
    peaks, _ = find_peaks(pgram)
    assert peaks and abs(peaks[0].period - 0.128) < 1e-3
