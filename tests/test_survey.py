"""
Survey subsystem tests: journal durability and reconciliation, metrics
registry, fault-injection plans, retry/backoff, scheduler
kill-and-resume (byte-identical data products), and the CLI surfaces.

Everything runs on the CPU backend against tiny synthetic surveys
(16 s @ 1 ms, 64-71 phase bins): the machinery under test is the
checkpoint/retry plumbing, not the search itself.
"""
import json
import os

import numpy as np
import pytest

from riptide_tpu.survey.faults import (FaultAbort, FaultPlan,
                                       InjectedDeviceError, InjectedFault)
from riptide_tpu.survey.journal import JournalMismatch, SurveyJournal
from riptide_tpu.survey.metrics import MetricsRegistry, get_metrics
from riptide_tpu.survey.scheduler import (
    RetryPolicy, SurveyScheduler, survey_identity,
)
from riptide_tpu.peak_detection import Peak

from synth import generate_data_presto

TOBS = 16.0
TSAMP = 1e-3
PERIOD = 0.5
# At 16 s the S/N of an amplitude-A pulse is ~A/3 here: DM 10 clears
# the snr_min=7 candidate filter comfortably, the others do not.
AMPLITUDES = {0.0: 15.0, 10.0: 40.0, 20.0: 15.0}


def _peak(period=0.5, snr=10.0, dm=0.0):
    return Peak(period=period, freq=1.0 / period, width=3, ducy=0.05,
                iw=1, ip=7, snr=snr, dm=dm)


# ---------------------------------------------------------------- metrics

def test_metrics_counters_timers_gauges():
    m = MetricsRegistry()
    m.add("chunks_done")
    m.add("chunks_done", 2)
    m.observe("device_s", 1.5)
    with m.timer("prep_s"):
        pass
    m.set_gauge("queue_depth", 4)
    snap = m.snapshot()
    assert snap["counters"]["chunks_done"] == 3
    assert snap["timers"]["device_s"] == {"total_s": 1.5, "count": 1}
    assert snap["timers"]["prep_s"]["count"] == 1
    assert snap["gauges"]["queue_depth"] == 4
    m.reset()
    assert m.snapshot() == {"counters": {}, "timers": {}, "gauges": {},
                            "hists": {}}


def test_metrics_summary_derives_wire_rate():
    m = MetricsRegistry()
    m.add("wire_bytes", 50_000_000)
    m.observe("wire_s", 2.0)
    s = m.summary()
    assert s["wire_MBps"] == 25.0
    assert s["wire_bytes"] == 50_000_000
    assert s["wire_s"] == 2.0


def test_metrics_summary_json_serializable():
    m = MetricsRegistry()
    m.add("wire_bytes", 10)
    m.observe("chunk_s", 0.25)
    m.set_gauge("queue_depth", 0)
    json.dumps(m.summary())


# ---------------------------------------------------------------- journal

def test_journal_roundtrip(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("abc", 3)
    peaks = [_peak(snr=9.0), _peak(period=1.0, snr=8.0, dm=10.0)]
    j.record_chunk(0, ["/x/a.inf"], [0.0], peaks,
                   wire_digest="d0", timings={"chunk_s": 0.5}, attempts=2)
    j.record_chunk(2, ["/x/c.inf"], [20.0], [], wire_digest="d2")
    j.record_metrics({"chunks_done": 2})

    j2 = SurveyJournal(tmp_path / "j")
    assert j2.survey_id() == "abc"
    done = j2.completed_chunks()
    assert sorted(done) == [0, 2]
    rec, got = done[0]
    assert rec["files"] == ["a.inf"]
    assert rec["attempts"] == 2
    assert got == peaks  # exact float round-trip through JSON
    assert done[2][1] == []
    assert j2.last_metrics() == {"chunks_done": 2}


def test_journal_header_mismatch_refuses_resume(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("abc", 3)
    j.write_header("abc", 3)  # idempotent
    with pytest.raises(JournalMismatch):
        SurveyJournal(tmp_path / "j").write_header("OTHER", 3)


def test_journal_tolerates_torn_tail(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("abc", 2)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak()])
    # Simulate a kill mid-append: a torn, newline-less record fragment.
    with open(j.journal_path, "ab") as f:
        f.write(b'{"kind": "chunk", "chunk_id": 1, "pea')
    done = SurveyJournal(tmp_path / "j").completed_chunks()
    assert sorted(done) == [0]


def test_journal_reconciles_missing_peak_rows(tmp_path):
    """A chunk record whose peak rows never hit the store (kill between
    the two appends) must be re-dispatched, not trusted."""
    j = SurveyJournal(tmp_path / "j")
    j.write_header("abc", 2)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak(), _peak(snr=8.0)])
    # Truncate the peak store to one row: chunk 0's claim of rows [0, 2)
    # no longer reconciles.
    with open(j.peaks_path) as f:
        first = f.readline()
    with open(j.peaks_path, "w") as f:
        f.write(first)
    done = SurveyJournal(tmp_path / "j").completed_chunks()
    assert done == {}


def test_journal_retried_chunk_last_record_wins(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("abc", 1)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak(snr=7.0)])
    j.record_chunk(0, ["a.inf"], [0.0], [_peak(snr=9.0)])
    done = SurveyJournal(tmp_path / "j").completed_chunks()
    assert done[0][1][0].snr == 9.0


def test_survey_identity_sensitivity():
    a = survey_identity(["x/a.inf", "x/b.inf"], {"k": 1})
    assert a == survey_identity(["y/a.inf", "y/b.inf"], {"k": 1})  # basenames
    assert a != survey_identity(["x/b.inf", "x/a.inf"], {"k": 1})  # order
    assert a != survey_identity(["x/a.inf", "x/b.inf"], {"k": 2})  # config


# ------------------------------------------------------------ fault plans

def test_fault_plan_parse_and_consume():
    sleeps = []
    plan = FaultPlan.parse("raise:2x2,stall:1:0.25,corrupt:0",
                           sleep=sleeps.append)
    plan.before_dispatch(0)          # no directive for chunk 0 dispatch
    plan.before_dispatch(1)          # stalls
    assert sleeps == [0.25]
    plan.before_dispatch(1)          # consumed: no further stall
    assert sleeps == [0.25]
    with pytest.raises(InjectedFault):
        plan.before_dispatch(2)
    with pytest.raises(InjectedFault):
        plan.before_dispatch(2)      # x2: raises twice
    plan.before_dispatch(2)          # then clean


def test_fault_plan_abort():
    plan = FaultPlan.parse("abort:3")
    with pytest.raises(FaultAbort):
        plan.before_dispatch(3)


def test_fault_plan_rejects_bad_spec():
    with pytest.raises(ValueError):
        FaultPlan.parse("explode:1")
    with pytest.raises(ValueError):
        FaultPlan.parse("raise")


def test_fault_plan_corrupts_prepared_wire():
    flat = np.zeros((1, 16), np.float32)
    items = [(None, None, None, None, (flat, {"scales": None}))]
    plan = FaultPlan.parse("corrupt:0")
    assert plan.corrupt_wire(0, items)
    assert flat.view("uint8").reshape(-1)[0] == 0xFF
    assert not plan.corrupt_wire(0, items)  # consumed


# ------------------------------------------------------------ retry policy

def test_retry_policy_backoff_shape():
    rp = RetryPolicy(max_retries=5, base_s=0.1, cap_s=0.4, jitter=0.0)
    assert [rp.delay(k) for k in range(4)] == [0.1, 0.2, 0.4, 0.4]
    jittered = RetryPolicy(base_s=1.0, cap_s=8.0, jitter=0.5)
    for k in range(3):
        d = jittered.delay(k)
        base = min(8.0, 1.0 * 2 ** k)
        assert 0.5 * base <= d <= 1.5 * base


def test_retry_policy_sleeps():
    slept = []
    rp = RetryPolicy(base_s=0.5, jitter=0.0, sleep=slept.append)
    rp.backoff(0)
    rp.backoff(1)
    assert slept == [0.5, 1.0]


# ------------------------------------------------------- scheduler (unit)

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def _searcher(io_threads=1):
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=io_threads)


def _two_trials(tmp_path):
    f1 = generate_data_presto(str(tmp_path), "a_DM0.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=0.0)
    f2 = generate_data_presto(str(tmp_path), "b_DM5.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=5.0)
    return f1, f2


def _fast_retry():
    return RetryPolicy(max_retries=3, base_s=0.01, cap_s=0.02,
                       sleep=lambda s: None)


def test_scheduler_transient_fault_retries(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=journal,
        retry=_fast_retry(), faults=FaultPlan.parse("raise:1"),
    )
    peaks = sched.run()
    assert peaks
    assert get_metrics().counter("chunks_retried") >= 1
    done = journal.completed_chunks()
    assert sorted(done) == [0, 1]
    assert done[1][0]["attempts"] == 2
    # The metrics snapshot lands in the journal with the retry recorded.
    assert journal.last_metrics()["chunks_retried"] >= 1


def test_scheduler_corrupted_wire_repreps_and_retries(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=journal,
        retry=_fast_retry(), faults=FaultPlan.parse("corrupt:0"),
    )
    peaks = sched.run()
    best = max(peaks, key=lambda p: p.snr)
    assert abs(best.period - PERIOD) < 1e-3
    done = journal.completed_chunks()
    assert done[0][0]["attempts"] == 2  # digest mismatch forced a re-prep
    assert done[0][0]["wire_digest"]
    assert get_metrics().counter("chunks_retried") >= 1


def test_scheduler_exhausted_retries_raise(tmp_path):
    get_metrics().reset()
    f1, _ = _two_trials(tmp_path)
    sched = SurveyScheduler(
        _searcher(), [[f1]],
        retry=RetryPolicy(max_retries=1, sleep=lambda s: None),
        faults=FaultPlan.parse("raise:0x5"),
    )
    with pytest.raises(InjectedFault):
        sched.run()


def test_scheduler_device_error_retries_and_recovers(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=journal,
        retry=_fast_retry(), faults=FaultPlan.parse("device_error:0"),
    )
    peaks = sched.run()
    # One transient XLA runtime failure: classified (not a generic
    # retry), resident executables evicted, re-fire completes.
    assert peaks
    assert get_metrics().counter("device_errors") >= 1
    assert sorted(journal.completed_chunks()) == [0, 1]


def test_scheduler_persistent_device_error_raises_with_incident(tmp_path):
    get_metrics().reset()
    f1, _ = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = SurveyScheduler(
        _searcher(), [[f1]], journal=journal,
        retry=RetryPolicy(max_retries=1, sleep=lambda s: None),
        faults=FaultPlan.parse("device_error:0x5"),
    )
    with pytest.raises(InjectedDeviceError):
        sched.run()
    # Retry exhaustion attributes the failure as a device_error
    # incident in the run's own journal (its RunContext sink).
    assert any(rec["incident"] == "device_error"
               for rec in journal.incidents())


def test_scheduler_resume_skips_and_matches(tmp_path):
    """Kill (abort fault) mid-queue, resume, and get the identical peak
    list an uninterrupted scheduler produces — with the completed chunk
    replayed, not re-searched."""
    f1, f2 = _two_trials(tmp_path)

    get_metrics().reset()
    uninterrupted = SurveyScheduler(_searcher(), [[f1], [f2]]).run()

    jdir = tmp_path / "j"
    with pytest.raises(FaultAbort):
        SurveyScheduler(
            _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
            faults=FaultPlan.parse("abort:1"),
        ).run()
    assert sorted(SurveyJournal(jdir).completed_chunks()) == [0]

    get_metrics().reset()
    resumed = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir), resume=True,
    ).run()
    assert get_metrics().counter("chunks_skipped") == 1
    assert resumed == uninterrupted


# ------------------------------------------------- pipeline (end to end)

def _survey_config(processes=1):
    return {
        "processes": processes,
        "data": {"format": "presto", "fmin": None, "fmax": None,
                 "nchans": None},
        "dmselect": {"min": 0.0, "max": 30.0, "dmsinb_max": None},
        "dereddening": {"rmed_width": 4.0, "rmed_minpts": 101},
        "ranges": [{
            "name": "test",
            "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                           "bins_min": 64, "bins_max": 71,
                           "fpmin": 8, "wtsp": 1.5, "ducy_max": 0.30},
            "find_peaks": {"smin": 6.0},
            "candidates": {"bins": 64, "subints": 8},
        }],
        "clustering": {"radius": 0.2},
        "harmonic_flagging": {"denom_max": 100, "phase_distance_max": 1.0,
                              "dm_distance_max": 3.0,
                              "snr_distance_max": 3.0},
        "candidate_filters": {"dm_min": None, "snr_min": 7.0,
                              "remove_harmonics": True, "max_number": None},
        "plot_candidates": False,
    }


def _make_survey(outdir):
    files = []
    for dm, amp in AMPLITUDES.items():
        files.append(generate_data_presto(
            str(outdir), f"fake_DM{dm:.2f}", tobs=TOBS, tsamp=TSAMP,
            period=PERIOD, dm=dm, amplitude=amp, ducy=0.02,
        ))
    return files


def _run_pipeline(files, outdir, **kwargs):
    from riptide_tpu.pipeline import Pipeline

    pipeline = Pipeline(_survey_config(), **kwargs)
    pipeline.process([str(f) for f in files], str(outdir))
    return pipeline


def test_pipeline_kill_and_resume_byte_identical(tmp_path):
    """The acceptance path: a survey killed mid-queue (injected abort on
    the last of three single-file chunks) resumes from the journal,
    skips the completed chunks, and produces byte-identical peaks.csv
    and candidates.csv to an uninterrupted run."""
    indir = tmp_path / "data"
    indir.mkdir()
    files = _make_survey(indir)

    out_a = tmp_path / "out_a"
    out_a.mkdir()
    get_metrics().reset()
    _run_pipeline(files, out_a)  # uninterrupted, no journal

    out_b = tmp_path / "out_b"
    out_b.mkdir()
    jdir = str(tmp_path / "journal")
    get_metrics().reset()
    with pytest.raises(FaultAbort):
        _run_pipeline(files, out_b, journal=jdir, fault_spec="abort:2")
    # The kill left chunks 0 and 1 journaled, chunk 2 pending, and no
    # data products written.
    assert sorted(SurveyJournal(jdir).completed_chunks()) == [0, 1]
    assert not (out_b / "peaks.csv").exists()

    get_metrics().reset()
    _run_pipeline(files, out_b, journal=jdir, resume=True, fault_spec="")
    assert get_metrics().counter("chunks_skipped") == 2
    assert get_metrics().counter("chunks_done") == 1

    for product in ("peaks.csv", "candidates.csv"):
        a = (out_a / product).read_bytes()
        b = (out_b / product).read_bytes()
        assert a == b, f"{product} differs between uninterrupted and resumed"


def test_pipeline_fault_injection_retry_completes(tmp_path):
    """Acceptance: an injected transient device error on chunk 1 is
    retried with backoff; the survey completes and the journal's metrics
    snapshot records chunks_retried >= 1."""
    indir = tmp_path / "data"
    indir.mkdir()
    files = _make_survey(indir)
    outdir = tmp_path / "out"
    outdir.mkdir()
    jdir = str(tmp_path / "journal")

    get_metrics().reset()
    _run_pipeline(files, outdir, journal=jdir, fault_spec="raise:1")
    assert (outdir / "peaks.csv").exists()
    snap = SurveyJournal(jdir).last_metrics()
    assert snap["chunks_retried"] >= 1
    assert snap["chunks_done"] == 3


def test_pipeline_resume_requires_journal():
    from riptide_tpu.pipeline import Pipeline

    with pytest.raises(ValueError):
        Pipeline(_survey_config(), resume=True)


def test_rffa_parser_has_survey_flags():
    from riptide_tpu.pipeline import get_parser

    args = get_parser().parse_args(
        ["-c", "conf.yaml", "--journal", "jdir", "--resume",
         "--fault-inject", "raise:2", "x.inf"]
    )
    assert args.journal == "jdir"
    assert args.resume is True
    assert args.fault_inject == "raise:2"


# ------------------------------------------------------------ rseek CLI

def _rseek_args(fname, extra=()):
    from riptide_tpu.apps.rseek import get_parser

    return get_parser().parse_args(
        ["-f", "presto", "--Pmin", "0.4", "--Pmax", "1.2",
         "--bmin", "64", "--bmax", "71", *extra, str(fname)]
    )


def test_rseek_journal_and_resume(tmp_path, monkeypatch):
    from riptide_tpu.apps import rseek

    inf = generate_data_presto(str(tmp_path), "fake_DM0.00", tobs=TOBS,
                               tsamp=TSAMP, period=PERIOD, dm=0.0,
                               amplitude=20.0, ducy=0.02)
    jdir = str(tmp_path / "journal")
    df1 = rseek.run_program(_rseek_args(inf, ["--journal", jdir]))
    assert df1 is not None
    assert sorted(SurveyJournal(jdir).completed_chunks()) == [0]

    # Resume must replay from the journal without searching.
    def _no_search(*a, **kw):
        raise AssertionError("resume must not re-search")

    monkeypatch.setattr(rseek, "_search_peaks", _no_search)
    df2 = rseek.run_program(_rseek_args(inf, ["--journal", jdir,
                                              "--resume"]))
    assert df2 is not None
    assert df1.equals(df2)


def test_rseek_resume_requires_journal(tmp_path):
    from riptide_tpu.apps import rseek

    inf = generate_data_presto(str(tmp_path), "fake_DM0.00", tobs=TOBS,
                               tsamp=TSAMP, period=PERIOD, dm=0.0,
                               amplitude=20.0, ducy=0.02)
    with pytest.raises(ValueError):
        rseek.run_program(_rseek_args(inf, ["--resume"]))


def test_rseek_fault_injection_retries(tmp_path):
    from riptide_tpu.apps import rseek

    inf = generate_data_presto(str(tmp_path), "fake_DM0.00", tobs=TOBS,
                               tsamp=TSAMP, period=PERIOD, dm=0.0,
                               amplitude=20.0, ducy=0.02)
    get_metrics().reset()
    df = rseek.run_program(_rseek_args(inf, ["--fault-inject", "raise:0"]))
    assert df is not None
    assert get_metrics().counter("chunks_retried") >= 1


# ------------------------------------------------------------- multihost

def test_multihost_journals_on_process_zero(tmp_path):
    """Single-process run: process_index() == 0, so the search result
    and a metrics snapshot land in the journal."""
    from riptide_tpu.libffa import generate_signal
    from riptide_tpu.parallel import run_search_multihost
    from riptide_tpu.search import periodogram_plan

    N, tsamp = 4096, 1e-3
    plan = periodogram_plan(N, tsamp, (1, 2, 3), 64e-3, 0.15, 64, 71)
    np.random.seed(0)
    batch = np.stack([
        generate_signal(N, 64.0, amplitude=15.0, ducy=0.05),
        np.random.standard_normal(N).astype(np.float32),
    ])
    batch -= batch.mean(axis=1, keepdims=True)
    batch /= batch.std(axis=1, keepdims=True)

    get_metrics().reset()
    journal = SurveyJournal(tmp_path / "j")
    journal.write_header("mh", 1)
    peaks, _ = run_search_multihost(plan, batch, tobs=N * tsamp,
                                    dms_local=[2.0, 3.0], journal=journal)
    assert peaks
    done = journal.completed_chunks()
    assert 0 in done
    assert done[0][1] == peaks
    assert journal.last_metrics() is not None


# -------------------------------------------------- engine metrics hooks

def test_engine_records_prep_wire_device_metrics(tmp_path):
    """One batched search through the engine must populate the survey
    metrics the bench emits (prep_s, wire_s/wire_bytes, device_s)."""
    from riptide_tpu.libffa import generate_signal
    from riptide_tpu.search import periodogram_plan
    from riptide_tpu.search.engine import run_search_batch

    N, tsamp = 4096, 1e-3
    plan = periodogram_plan(N, tsamp, (1, 2, 3), 64e-3, 0.15, 64, 71)
    np.random.seed(0)
    batch = generate_signal(N, 64.0, amplitude=15.0, ducy=0.05)[None]
    batch = (batch - batch.mean()) / batch.std()

    get_metrics().reset()
    run_search_batch(plan, batch, tobs=N * tsamp)
    s = get_metrics().summary()
    assert s["wire_bytes"] > 0
    assert "prep_s" in s and "wire_s" in s and "device_s" in s
