"""
Fleet observability plane tests (PR 14): the alert-rule engine (every
mode, hysteresis/flap behaviour, spec parsing), the watch-snapshot
derivation, fleet snapshot write/read/merge (including the
never-fatal-under-ENOSPC invariant), journal ``alert`` records and
follower interop, Prometheus fleet federation + the alert gauge, the
``maybe_serve`` per-process port offset, the rwatch CLI exit codes,
one small in-scheduler e2e, and backward compat (pre-PR-14 journals —
no fleet sidecars, no alert records — render/resume unchanged).

The heavier acceptance path (two real processes federating one run
directory, rwatch following live, the ENOSPC control-vs-fault
byte-identity) lives in tools/watch_demo.py (`make watch-demo`).
"""
import json
import os
import sys

import pytest

from riptide_tpu.obs import alerts, fleet, prom
from riptide_tpu.obs import report as rep
from riptide_tpu.survey import incidents
from riptide_tpu.survey.faults import FaultPlan
from riptide_tpu.survey.journal import SurveyJournal, _append_line
from riptide_tpu.survey.metrics import MetricsRegistry, get_metrics
from riptide_tpu.utils import fsio

from synth import generate_data_presto

TOOLS = os.path.normpath(
    os.path.join(os.path.dirname(__file__), "..", "tools"))


def _tool(name):
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    return __import__(name)


@pytest.fixture(autouse=True)
def _clean_process_state():
    """Alert engine, fleet source, incident sink/last and status
    provider are process-wide; clear them on both sides of every test
    (earlier suite files run real schedulers, which deliberately leave
    their hooks registered)."""
    def _clear():
        alerts.install_engine(None)
        prom.set_fleet_source(None)
        prom.set_status_provider(None)
        incidents.set_sink(None)
        incidents.clear_last()
        fsio.set_storage_faults(None)

    _clear()
    yield
    _clear()


# ------------------------------------------------------------ alert rules

def test_threshold_rule_fires_and_resolves():
    eng = alerts.AlertEngine([alerts.AlertRule(
        "r", "x", 5.0, op=">=")])
    assert eng.evaluate({"now": 1.0, "x": 1.0}) == []
    ev = eng.evaluate({"now": 2.0, "x": 7.0})
    assert [(e["event"], e["rule"]) for e in ev] == [("fired", "r")]
    assert ev[0]["kind"] == "alert" and ev[0]["value"] == 7.0
    assert eng.active() == {"r": True} and eng.unresolved() == ["r"]
    # Still breaching: no duplicate fire.
    assert eng.evaluate({"now": 3.0, "x": 9.0}) == []
    ev = eng.evaluate({"now": 4.0, "x": 1.0})
    assert [(e["event"],) for e in ev] == [("resolved",)]
    assert eng.unresolved() == []
    assert [e["event"] for e in eng.events()] == ["fired", "resolved"]


def test_consecutive_count_suppresses_flap():
    """for_count=2: a value flapping across the limit every evaluation
    never fires; two consecutive breaches do. clear_count=2 demands
    two clean evaluations before resolving."""
    eng = alerts.AlertEngine([alerts.AlertRule(
        "r", "x", 5.0, for_count=2, clear_count=2)])
    for i, x in enumerate([9, 1, 9, 1, 9, 1] * 2):
        assert eng.evaluate({"now": float(i), "x": x}) == [], \
            f"flapping input fired at step {i}"
    ev = eng.evaluate({"now": 20.0, "x": 9})
    assert ev == []  # first consecutive breach
    ev = eng.evaluate({"now": 21.0, "x": 9})
    assert [e["event"] for e in ev] == ["fired"]
    assert eng.evaluate({"now": 22.0, "x": 1}) == []  # first clean
    ev = eng.evaluate({"now": 23.0, "x": 1})
    assert [e["event"] for e in ev] == ["resolved"]


def test_absence_rule_missing_and_stale():
    missing = alerts.AlertRule("m", "age", 10.0, op=">", mode="absence",
                               missing_fires=True)
    tolerant = alerts.AlertRule("t", "age", 10.0, op=">",
                                mode="absence")
    eng = alerts.AlertEngine([missing, tolerant])
    ev = eng.evaluate({"now": 1.0})  # no signal at all
    assert [(e["rule"], e["event"]) for e in ev] == [("m", "fired")]
    ev = eng.evaluate({"now": 2.0, "age": 3.0})  # fresh again
    assert [(e["rule"], e["event"]) for e in ev] == [("m", "resolved")]
    ev = eng.evaluate({"now": 3.0, "age": 99.0})  # stale
    assert sorted((e["rule"], e["event"]) for e in ev) == \
        [("m", "fired"), ("t", "fired")]


def test_rate_rule_growth_then_quiet_window():
    eng = alerts.AlertEngine([alerts.AlertRule(
        "r", "errors", 1, op=">=", mode="rate", window_s=10.0)])
    assert eng.evaluate({"now": 0.0, "errors": 0}) == []
    assert eng.evaluate({"now": 1.0, "errors": 0}) == []
    ev = eng.evaluate({"now": 2.0, "errors": 1})  # grew within window
    assert [e["event"] for e in ev] == ["fired"]
    assert ev[0]["value"] == 1.0  # the growth, not the level
    # Same level, but the growth sample is still inside the window.
    assert eng.evaluate({"now": 5.0, "errors": 1}) == []
    # Window slides past the growth: resolves at the old LEVEL.
    ev = eng.evaluate({"now": 13.0, "errors": 1})
    assert [e["event"] for e in ev] == ["resolved"]


def test_transform_rule_hbm_drift_two_sided():
    [rule] = [r for r in alerts.default_rules() if r.name == "hbm_drift"]
    eng = alerts.AlertEngine([rule])
    assert eng.evaluate({"now": 1.0, "hbm_ratio_median": 1.2}) == []
    ev = eng.evaluate({"now": 2.0, "hbm_ratio_median": 0.3})  # |0.3-1|>.5
    assert [e["event"] for e in ev] == ["fired"]
    ev = eng.evaluate({"now": 3.0, "hbm_ratio_median": 1.1})
    assert [e["event"] for e in ev] == ["resolved"]
    ev = eng.evaluate({"now": 4.0, "hbm_ratio_median": 1.8})
    assert [e["event"] for e in ev] == ["fired"]


def test_rules_from_spec():
    assert [r.name for r in alerts.rules_from_spec(None)] == \
        [r.name for r in alerts.default_rules()]
    assert [r.name for r in alerts.rules_from_spec("default")] == \
        [r.name for r in alerts.default_rules()]
    rules = alerts.rules_from_spec("straggler_ratio:2.5:3,parked_chunks")
    assert [r.name for r in rules] == ["straggler_ratio",
                                      "parked_chunks"]
    assert rules[0].limit == 2.5 and rules[0].for_count == 3
    assert rules[1].limit == 1.0  # builtin default kept
    # `default` plus a retune: full catalog, overridden entry.
    rules = alerts.rules_from_spec("default,heartbeat_stale:30")
    assert len(rules) == len(alerts.default_rules())
    [hb] = [r for r in rules if r.name == "heartbeat_stale"]
    assert hb.limit == 30.0 and hb.mode == "absence"
    with pytest.raises(ValueError, match="unknown alert rule"):
        alerts.rules_from_spec("no_such_rule:1")
    with pytest.raises(ValueError, match="expected"):
        alerts.rules_from_spec("parked_chunks:1:2:3")


def test_on_event_hook_failure_is_swallowed():
    def boom(event):
        raise RuntimeError("sink down")

    eng = alerts.AlertEngine(
        [alerts.AlertRule("r", "x", 1.0)], on_event=boom)
    ev = eng.evaluate({"now": 1.0, "x": 5.0})
    assert [e["event"] for e in ev] == ["fired"]  # not raised
    assert eng.active() == {"r": True}


# --------------------------------------------------------- watch_snapshot

def _chunk_rec(cid, chunk_s, bound="device", ratio=None):
    rec = {"kind": "chunk", "chunk_id": cid,
           "timings": {"chunk_s": chunk_s, "bound": bound}}
    if ratio is not None:
        rec["hbm"] = {"ratio": ratio}
    return rec


def test_watch_snapshot_signals():
    state = {
        "header": {"survey_id": "s", "chunks_total": 6},
        "chunks": {i: _chunk_rec(i, 1.0 if i != 1 else 8.0,
                                 bound="tunnel" if i >= 4 else "device",
                                 ratio=1.1)
                   for i in range(5)},
        "parked": {5: {"kind": "parked"}},
        "incidents": [{"incident": "obs_write_failed"},
                      {"incident": "breaker_open"},
                      {"incident": "obs_write_failed"}],
    }
    snap = rep.watch_snapshot(state, heartbeats={0: 90.0, 1: 100.0},
                              now=103.0)
    assert snap["chunks_done"] == 5 and snap["chunks_parked"] == 1
    assert snap["complete"] is True  # 5 done + 1 parked == 6 total
    assert snap["consecutive_tunnel"] == 1  # chunk 4 only (3 is device)
    assert snap["straggler_ratio"] == 8.0  # 8.0 over median 1.0
    assert snap["heartbeat_age_s"] == 3.0  # freshest beat (p1)
    assert snap["obs_write_failures"] == 2
    assert snap["hbm_ratio_median"] == 1.1

    # Windowing: the chunk-1 straggler ages out of a 3-chunk window
    # (chunks 2-4 are all healthy), so the signal can RESOLVE.
    snap = rep.watch_snapshot(state, window=3, now=103.0)
    assert snap["straggler_ratio"] == 1.0
    assert snap["consecutive_tunnel"] == 1

    # Empty directory state: nothing measurable, nothing crashes.
    snap = rep.watch_snapshot({"chunks": {}}, now=1.0)
    assert snap["complete"] is False
    assert snap["straggler_ratio"] is None
    assert snap["heartbeat_age_s"] is None


# ------------------------------------------------------------------ fleet

def test_fleet_snapshot_roundtrip_merge_and_skew(tmp_path):
    reg = MetricsRegistry()
    reg.add("obs_write_errors", 2)
    timings = [{"chunk_s": 1.0, "wire_s": 0.2, "queue_s": 0.1,
                "collect_s": 0.5, "host_s": 0.2, "bound": "device"},
               {"chunk_s": 1.2, "wire_s": 0.9, "queue_s": 0.1,
                "collect_s": 0.1, "host_s": 0.1, "bound": "tunnel"}]
    s0 = fleet.snapshot(0, status={"survey_id": "s", "running": True,
                                   "chunks_done": 2,
                                   "rate_chunks_per_s": 1.0},
                        metrics=reg, timings=timings, ts=1000.0)
    s1 = fleet.snapshot(1, status={"survey_id": "s", "running": True,
                                   "chunks_done": 1, "chunks_parked": 1,
                                   "rate_chunks_per_s": 0.2},
                        ts=1000.0)
    assert fleet.write_snapshot(str(tmp_path), s0)
    assert fleet.write_snapshot(str(tmp_path), s1)
    assert sorted(os.listdir(tmp_path)) == ["fleet_0000.json",
                                            "fleet_0001.json"]

    snapshots = rep.read_fleet(str(tmp_path))
    assert sorted(snapshots) == [0, 1]
    merged = rep.merge_fleet(snapshots, now=1001.0)
    assert merged["nprocesses"] == 2
    assert merged["chunks_done"] == 3 and merged["chunks_parked"] == 1
    assert merged["bound_counts"] == {"device": 1, "tunnel": 1}
    assert merged["skew"]["rate_max"] == 1.0
    assert merged["stragglers"] == ["1"]  # 0.2 < 0.5 x median(0.6)
    assert merged["stale"] == []
    p0 = merged["processes"]["0"]
    assert p0["obs_write_errors"] == 2
    assert p0["phases"]["wire_s"] == pytest.approx(1.1)
    assert p0["snapshot_age_s"] == pytest.approx(1.0)

    # The human rows render with the skew highlighting.
    lines = rep.render_fleet_text(merged)
    joined = "\n".join(lines)
    assert "STRAGGLER" in joined and "p1:" in joined

    # Staleness marking, and the re-write discipline (sidecars are
    # whole-file replaces: the newest snapshot wins outright). p0's
    # rewrite heals ITS staleness (and running=false exempts it
    # regardless — a finished process's aging snapshot is not a
    # stall); p1 never rewrote, so it stays stale.
    merged = rep.merge_fleet(snapshots, now=1500.0, stale_s=120.0)
    assert merged["stale"] == ["0", "1"]
    s0b = fleet.snapshot(0, status={"running": False, "chunks_done": 4},
                         ts=2000.0)
    fleet.write_snapshot(str(tmp_path), s0b)
    snapshots = rep.read_fleet(str(tmp_path))
    assert snapshots[0]["chunks_done"] == 4
    assert rep.merge_fleet(snapshots, now=2001.0)["stale"] == ["1"]


def test_fleet_write_never_fatal_under_enospc(tmp_path):
    plan = FaultPlan.parse("enospc:fleet_snapshot")
    prev = fsio.set_storage_faults(plan.storage_op)
    seen = []
    incidents.set_sink(seen.append)
    before = get_metrics().counter("obs_write_errors")
    try:
        out = fleet.write_snapshot(
            str(tmp_path), fleet.snapshot(0, status={"running": True}))
    finally:
        fsio.set_storage_faults(prev)
    assert out is None  # degraded, not raised
    assert get_metrics().counter("obs_write_errors") == before + 1
    assert [r["incident"] for r in seen] == ["obs_write_failed"]
    assert seen[0]["detail"]["op"] == "fleet_snapshot"
    assert not os.listdir(tmp_path)
    # The hook cleared: the next write lands.
    assert fleet.write_snapshot(
        str(tmp_path), fleet.snapshot(0, status={"running": True}))


def test_fleet_disabled_by_flag(monkeypatch):
    monkeypatch.setenv("RIPTIDE_FLEET", "0")
    assert not fleet.enabled()
    monkeypatch.delenv("RIPTIDE_FLEET")
    assert fleet.enabled()


# ----------------------------------------------- journal alert records

def test_record_alert_roundtrip_and_reader_interop(tmp_path):
    j = SurveyJournal(str(tmp_path / "j"))
    j.write_header("s", 1)
    eng = alerts.AlertEngine(
        [alerts.AlertRule("parked_chunks", "chunks_parked", 1)],
        on_event=j.record_alert)
    eng.evaluate({"now": 1.0, "chunks_parked": 2})
    eng.evaluate({"now": 2.0, "chunks_parked": 0})

    state = rep.read_journal(str(tmp_path / "j"))
    assert [(a["event"], a["rule"]) for a in state["alerts"]] == \
        [("fired", "parked_chunks"), ("resolved", "parked_chunks")]
    assert state["alerts"][0]["limit"] == 1.0
    # Alert lines are invisible to every kind-filtering reader.
    assert SurveyJournal(str(tmp_path / "j")).completed_chunks() == {}
    assert SurveyJournal(str(tmp_path / "j")).incidents() == []
    report = rep.build_report(str(tmp_path / "j"))
    assert len(report["alerts"]) == 2
    txt = rep.render_text(report)
    assert "alert timeline (2)" in txt and "parked_chunks" in txt


# --------------------------------------------------- prom federation

def test_prom_render_fleet_series_and_alert_gauge():
    eng = alerts.AlertEngine([alerts.AlertRule("r1", "x", 1.0),
                              alerts.AlertRule("r2", "y", 1.0)])
    eng.evaluate({"now": 1.0, "x": 5.0, "y": 0.0})
    alerts.install_engine(eng)
    snapshots = {
        0: fleet.snapshot(0, status={"running": True, "chunks_done": 3,
                                     "rate_chunks_per_s": 0.5},
                          metrics=MetricsRegistry(), ts=1.0),
        1: fleet.snapshot(1, status={"running": False, "chunks_done": 1},
                          ts=1.0),
    }
    page = prom.render(MetricsRegistry(), fleet=snapshots)
    values = rep.parse_prom_text(page)
    assert values["riptide_fleet_chunks_done"]['process="0"'] == 3
    assert values["riptide_fleet_chunks_done"]['process="1"'] == 1
    assert values["riptide_fleet_running"]['process="0"'] == 1
    assert values["riptide_fleet_running"]['process="1"'] == 0
    assert values["riptide_fleet_chunk_rate"]['process="0"'] == 0.5
    assert values["riptide_fleet_obs_write_errors_total"][
        'process="0"'] == 0
    assert values["riptide_alert_active"]['rule="r1"'] == 1
    assert values["riptide_alert_active"]['rule="r2"'] == 0
    # HELP/TYPE hygiene for the federated series.
    assert "# TYPE riptide_fleet_chunks_done gauge" in page
    assert "# TYPE riptide_alert_active gauge" in page

    # Without an engine or fleet data the page carries neither family.
    alerts.install_engine(None)
    page = prom.render(MetricsRegistry())
    assert "alert_active" not in page and "riptide_fleet" not in page

    # An installed fleet SOURCE federates without the explicit arg
    # (how the scheduler wires /metrics for the run's duration).
    prom.set_fleet_source(lambda: snapshots)
    page = prom.render(MetricsRegistry())
    assert 'riptide_fleet_chunks_done{process="1"} 1' in page


def test_maybe_serve_offsets_port_by_process_index(monkeypatch):
    captured = []

    class FakeServer:
        port = 0

        def set_registry(self, registry):
            pass

    monkeypatch.setattr(prom, "serve",
                        lambda port, registry=None:
                        captured.append(port) or FakeServer())
    monkeypatch.setattr(prom, "_server", None)
    monkeypatch.setenv("RIPTIDE_PROM_PORT", "9400")
    assert prom.maybe_serve(process_index=3) is not None
    assert captured == [9403]

    # Flag-gated: offsetting off binds the literal port everywhere.
    monkeypatch.setattr(prom, "_server", None)
    monkeypatch.setenv("RIPTIDE_PROM_PORT_OFFSET", "0")
    prom.maybe_serve(process_index=3)
    assert captured == [9403, 9400]

    # Process 0 (and jax-less processes: _detect_process_index -> 0)
    # binds the base port with the offset on.
    monkeypatch.setattr(prom, "_server", None)
    monkeypatch.delenv("RIPTIDE_PROM_PORT_OFFSET")
    prom.maybe_serve()
    assert captured[-1] == 9400


# ------------------------------------------------------------- rwatch CLI

def test_rwatch_once_exit_codes(tmp_path):
    rwatch = _tool("rwatch")

    # Missing directory: usage error.
    assert rwatch.main([str(tmp_path / "nope"), "--once"]) == 2
    # Bad rule spec: usage error.
    os.makedirs(tmp_path / "empty")
    assert rwatch.main([str(tmp_path / "empty"), "--once",
                        "--rules", "bogus:1"]) == 2

    # Healthy complete journal: exit 0, no events.
    j = SurveyJournal(str(tmp_path / "ok"))
    j.write_header("s", 2)
    for cid in range(2):
        j.record_chunk(cid, [f"{cid}.inf"], [float(cid)], [],
                       timings={"chunk_s": 1.0, "wire_s": 0.2,
                                "queue_s": 0.1, "collect_s": 0.5,
                                "host_s": 0.2, "bound": "device"})
    out = str(tmp_path / "ok.json")
    assert rwatch.main([str(tmp_path / "ok"), "--once", "--quiet",
                        "--json", out]) == 0
    with open(out) as fobj:
        result = json.load(fobj)
    assert result["complete"] and not result["events"]

    # A parked chunk with the parked_chunks rule: unresolved, exit 1.
    j = SurveyJournal(str(tmp_path / "parked"))
    j.write_header("p", 2)
    j.record_parked(1, "breaker open")
    out = str(tmp_path / "parked.json")
    assert rwatch.main([str(tmp_path / "parked"), "--once", "--quiet",
                        "--rules", "parked_chunks", "--json", out]) == 1
    with open(out) as fobj:
        result = json.load(fobj)
    assert result["unresolved"] == ["parked_chunks"]
    assert [e["event"] for e in result["events"]] == ["fired"]


def test_rwatch_follow_until_complete(tmp_path):
    """The follow loop over a journal that completes between polls:
    a straggler fires mid-run and resolves when the window slides past
    it, and rwatch exits 0 at completion."""
    rwatch = _tool("rwatch")
    rep_mod = _tool("rreport").load_report_module()
    al = rwatch.load_alerts_module()

    j = SurveyJournal(str(tmp_path / "j"))
    j.write_header("s", 14)

    def add_chunk(cid, chunk_s):
        j.record_chunk(cid, [f"{cid}.inf"], [float(cid)], [],
                       timings={"chunk_s": chunk_s, "wire_s": 0.0,
                                "queue_s": 0.0, "collect_s": 0.0,
                                "host_s": chunk_s, "bound": "device"})

    # Scripted producer: two healthy chunks, a straggler, then enough
    # healthy chunks that the 8-chunk window slides past it.
    script = iter([(2, 1.0), (3, 30.0)] + [(cid, 1.0)
                                           for cid in range(4, 14)])
    add_chunk(0, 1.0)
    add_chunk(1, 1.0)

    def sleep(_):
        try:
            cid, wall = next(script)
        except StopIteration:
            raise AssertionError("rwatch kept polling after completion")
        add_chunk(cid, wall)

    code, result = rwatch.watch(
        rep_mod, al, str(tmp_path / "j"),
        rules=al.rules_from_spec("straggler_ratio:8.0"),
        interval=0.0, sleep=sleep)
    assert code == 0
    assert [(e["event"], e["rule"]) for e in result["events"]] == \
        [("fired", "straggler_ratio"), ("resolved", "straggler_ratio")]
    assert result["complete"] and not result["unresolved"]

    # --timeout on a run that never completes: exit 3.
    j2 = SurveyJournal(str(tmp_path / "stuck"))
    j2.write_header("s2", 5)
    clock = iter([0.0, 0.0, 5.0, 10.0, 20.0, 30.0, 40.0])
    code, result = rwatch.watch(
        rep_mod, al, str(tmp_path / "stuck"),
        rules=al.rules_from_spec("parked_chunks"),
        interval=0.0, timeout=15.0, sleep=lambda _: None,
        clock=lambda: next(clock))
    assert code == 3 and result["timed_out"]


# ------------------------------------------------------- scheduler e2e

TOBS, TSAMP, PERIOD = 16.0, 1e-3, 0.5

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def _searcher():
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=1)


def test_scheduler_alerts_and_fleet_e2e(tmp_path, monkeypatch):
    """A journaled survey with the engine on and an injected straggle:
    the alert journals + mirrors as incidents + flips the gauge, the
    fleet sidecar publishes per-chunk and finishes at running=false,
    and /status carries both the alert map and the merged fleet
    block."""
    from riptide_tpu.survey.scheduler import SurveyScheduler

    monkeypatch.setenv("RIPTIDE_ALERTS", "1")
    monkeypatch.setenv("RIPTIDE_ALERT_RULES", "straggler_ratio:3.0")
    # 5 chunks with a 5 s straggle on chunk 1: by the last evaluations
    # the windowed median is a healthy tiny chunk, so the ratio
    # breaches 3.0 decisively even when chunk 0 paid a cold compile.
    files = [
        generate_data_presto(str(tmp_path), f"w_DM{dm:.2f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=float(dm))
        for dm in (0.0, 5.0, 10.0, 15.0, 20.0)
    ]
    jdir = str(tmp_path / "j")
    get_metrics().reset()
    sched = SurveyScheduler(
        _searcher(), [[f] for f in files], journal=SurveyJournal(jdir),
        faults=FaultPlan.parse("straggle:1:5.0"))
    peaks = sched.run()
    assert peaks

    state = rep.read_journal(jdir)
    events = [(a["event"], a["rule"]) for a in state["alerts"]]
    assert ("fired", "straggler_ratio") in events, events
    inc = [i["incident"] for i in state["incidents"]]
    assert "alert_fired" in inc
    # The alert_fired incident carries the rule in its detail block.
    [fired] = [i for i in state["incidents"]
               if i["incident"] == "alert_fired"]
    assert fired["detail"]["rule"] == "straggler_ratio"

    # Fleet sidecar: per-chunk publication, final state at rest.
    snapshots = rep.read_fleet(jdir)
    assert sorted(snapshots) == [0]
    assert snapshots[0]["chunks_done"] == 5
    assert snapshots[0]["running"] is False
    assert snapshots[0]["survey_id"] == sched.survey_id
    assert snapshots[0]["bound_counts"]  # per-chunk bound labels

    # /status: alert map + merged fleet block; the installed engine
    # backs the prom gauge.
    st = sched.status()
    assert st["alerts"]["straggler_ratio"] is True  # 5 chunks: the
    # 8-chunk window never slides past the straggler, so it stays
    # firing (resolution is the demo's/unit tests' territory)
    assert st["fleet"]["nprocesses"] == 1
    assert alerts.get_engine() is sched.alerts
    page = prom.render(sched.metrics)
    assert 'riptide_alert_active{rule="straggler_ratio"} 1' in page
    assert 'riptide_fleet_chunks_done{process="0"} 5' in page

    # rtop renders the fleet summary + per-process rows.
    rtop = _tool("rtop")
    rep_mod = _tool("rreport").load_report_module()
    frame = rtop.render_frame(rep_mod, jdir, show_fleet=True)
    assert "fleet (1 process(es))" in frame and "p0:" in frame
    assert "FIRING: straggler_ratio" in frame


def test_bad_alert_spec_fails_without_leaking_hooks(tmp_path,
                                                    monkeypatch):
    """A typo'd RIPTIDE_ALERT_RULES fails the run at start — BEFORE
    the incident sink and storage-fault hook are installed, so the
    failed run leaks neither into whatever runs next in the
    process."""
    from riptide_tpu.survey.scheduler import SurveyScheduler

    monkeypatch.setenv("RIPTIDE_ALERTS", "1")
    monkeypatch.setenv("RIPTIDE_ALERT_RULES", "tunnle_bound:3")

    def sentinel_sink(rec):
        pass

    def sentinel_hook(op, site, path=None):
        return None

    incidents.set_sink(sentinel_sink)
    fsio.set_storage_faults(sentinel_hook)
    sched = SurveyScheduler(object(), [["a.inf"]],
                            journal=SurveyJournal(str(tmp_path / "j")))
    with pytest.raises(ValueError, match="RIPTIDE_ALERT_RULES"):
        sched.run()
    assert incidents.set_sink(None) is sentinel_sink
    assert fsio.set_storage_faults(None) is sentinel_hook


def test_alerts_off_by_default_and_fleet_flag(tmp_path, monkeypatch):
    """Without RIPTIDE_ALERTS the scheduler builds no engine and
    journals no alert records; with RIPTIDE_FLEET=0 no sidecar is
    written (the pre-PR-14 on-disk layout, byte for byte)."""
    from riptide_tpu.survey.scheduler import SurveyScheduler

    monkeypatch.delenv("RIPTIDE_ALERTS", raising=False)
    monkeypatch.setenv("RIPTIDE_FLEET", "0")
    f1 = generate_data_presto(str(tmp_path), "q_DM0.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=0.0)
    jdir = str(tmp_path / "j")
    get_metrics().reset()
    sched = SurveyScheduler(_searcher(), [[f1]],
                            journal=SurveyJournal(jdir))
    sched.run()
    assert sched.alerts is None
    state = rep.read_journal(jdir)
    assert state["alerts"] == []
    assert rep.read_fleet(jdir) == {}
    assert not [p for p in os.listdir(jdir) if p.startswith("fleet_")]
    st = sched.status()
    assert "alerts" not in st and "fleet" not in st


# ------------------------------------------------ pre-PR-14 compat

def _write_pre_pr14_journal(tmp_path):
    """A journal exactly as PR <= 13 wrote it: chunk records with
    timings but no alert records and no fleet sidecars."""
    j = SurveyJournal(str(tmp_path / "old"))
    _append_line(j.journal_path, {
        "kind": "header", "version": 1, "survey_id": "oldsurvey",
        "chunks_total": 2,
    })
    for cid in range(2):
        _append_line(j.journal_path, {
            "kind": "chunk", "chunk_id": cid, "files": [f"{cid}.inf"],
            "dms": [float(cid)], "wire_digest": None,
            "peaks_offset": 0, "peaks_count": 0, "attempts": 1,
            "timings": {"chunk_s": 1.0, "wire_s": 0.2, "queue_s": 0.1,
                        "collect_s": 0.5, "host_s": 0.2,
                        "bound": "device"},
        })
    return str(tmp_path / "old")


def test_pre_pr14_journal_renders_unchanged(tmp_path):
    jdir = _write_pre_pr14_journal(tmp_path)

    # Resume loader unaffected.
    assert sorted(SurveyJournal(jdir).completed_chunks()) == [0, 1]

    # Report: no fleet section, empty alert timeline, and the human
    # rendering carries neither block.
    report = rep.build_report(jdir)
    assert "fleet" not in report and report["alerts"] == []
    txt = rep.render_text(report)
    assert "fleet" not in txt and "alert" not in txt

    # rtop: frame identical in shape to pre-PR-14 (no fleet/alert
    # lines, with or without --fleet).
    rtop = _tool("rtop")
    rep_mod = _tool("rreport").load_report_module()
    for show_fleet in (False, True):
        frame = rtop.render_frame(rep_mod, jdir, show_fleet=show_fleet)
        assert "fleet" not in frame and "alert" not in frame
        assert "chunks 2/2" in frame

    # rwatch: follows it cleanly, exits 0.
    rwatch = _tool("rwatch")
    assert rwatch.main([jdir, "--once", "--quiet"]) == 0
