"""
Fused single-dispatch wire->kernel path (search/engine.py:_run_stage_fused
+ ops/ffa_kernel.py:_fused_kernel): each kernel-eligible cascade stage
runs wire decode + dequant + (m, p) pack + FFA + boxcar S/N as ONE
Pallas program per lane bucket, fed straight from the shipped byte-plane
wire view.

Correctness chain covered here (all interpret mode, CPU):

* fused program == two-dispatch XLA-pack + kernel path, BITWISE, for
  every quantised wire mode (uint6/uint8/uint12) including odd-length
  stage tails — the in-kernel decode/pack mirrors engine._udecode_view
  operation for operation — and within the transport's S/N budget of
  the float32-wire kernel path (the numpy-oracle anchor: the float32
  kernel path is oracle-tested in test_ffa_kernel.py);
* dispatch-count regression: one fused device program per eligible
  stage lane bucket, ZERO separate pack programs (the former per-stage
  XLA pack dispatch and its (D, B, rows, P) container HBM round-trip);
* lane-split occupancy buckets (p <= 128-tile boundary) produce
  bit-identical results to the unsplit container;
* on-device peaks through the fused path == host find_peaks on the
  pulled S/N cube, byte-identical down to the peaks.csv serialisation.

Configs are deliberately tiny (two cascade stages, 4 bins-trials):
interpret-mode Pallas emulates every DMA and roll, so each search costs
seconds — the shapes still cover multi-stage wiring, shipped-part
offsets, odd tails and both container families.
"""
import numpy as np
import pytest

import riptide_tpu.search.engine as eng
from riptide_tpu.search.plan import periodogram_plan
from riptide_tpu.survey.metrics import MetricsRegistry, set_metrics

# Two-stage cascade, 4 bins-trials, odd stage lengths (2500/2353):
# full coverage of the fused machinery at interpret-mode cost.
SIZE, TSAMP, WIDTHS = 2500, 1e-3, (1, 2, 3)
PMIN, PMAX, BMIN, BMAX = 64e-3, 0.072, 64, 67
# segwidth sized for the short series: >= 3 threshold control points
# (the Vandermonde normal matrix must stay invertible at tobs = 2.5 s).
PKW = dict(smin=6.0, segwidth=0.5, nstd=6.0, minseg=10, polydeg=2, clrad=0.1)


@pytest.fixture(scope="module")
def plan():
    return periodogram_plan(SIZE, TSAMP, WIDTHS, PMIN, PMAX, BMIN, BMAX)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    return rng.standard_normal(SIZE).astype(np.float32)


@pytest.fixture(scope="module")
def snr_f32(plan, data):
    """Exact-wire kernel-path reference (oracle-anchored via
    test_ffa_kernel.py), shared by every mode's budget check."""
    import os

    old = {k: os.environ.get(k) for k in
           ("RIPTIDE_FFA_PATH", "RIPTIDE_WIRE_DTYPE")}
    os.environ["RIPTIDE_FFA_PATH"] = "kernel"
    os.environ["RIPTIDE_WIRE_DTYPE"] = "float32"
    try:
        return eng.run_periodogram(plan, data)[2]
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


@pytest.fixture()
def kernel_path(monkeypatch):
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "kernel")
    return monkeypatch


def test_plan_has_fused_stages_and_odd_tails(plan):
    assert len(plan.stages) >= 2
    assert all(eng._fused_eligible(st, plan, "uint6") for st in plan.stages)
    PW = eng._view_width(plan)
    assert any(st.n % PW for st in plan.stages), "want odd-length tails"


@pytest.mark.parametrize("mode,tol", [("uint6", 0.3), ("uint8", 0.1),
                                      ("uint12", 0.01)])
def test_fused_bitwise_equals_two_dispatch(plan, data, snr_f32, kernel_path,
                                           mode, tol):
    """The fused program's decode+pack mirrors the XLA pack path op for
    op, so the S/N cube must match BITWISE — any drift means the two
    decoders diverged. The same cube must sit within the transport's
    S/N error budget of the exact float32 wire."""
    kernel_path.setenv("RIPTIDE_WIRE_DTYPE", mode)
    _, _, s_fused = eng.run_periodogram(plan, data)
    kernel_path.setattr(eng, "_fused_eligible", lambda *a: False)
    _, _, s_two = eng.run_periodogram(plan, data)
    np.testing.assert_array_equal(s_fused, s_two)
    assert np.max(np.abs(s_fused - snr_f32)) < tol


def test_fused_dispatch_counts(plan, data, kernel_path):
    """THE single-dispatch regression test: per eligible stage exactly
    one fused device program per lane bucket, and NO separate pack
    program. The pack entry points are also tripwired so a silent
    routing regression cannot pass."""
    kernel_path.setenv("RIPTIDE_WIRE_DTYPE", "uint6")

    def _no_pack(*a, **k):
        raise AssertionError("separate pack program dispatched on the "
                             "fused path")

    kernel_path.setattr(eng, "_pack_static_view", _no_pack)
    kernel_path.setattr(eng, "_pack_static", _no_pack)
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        eng.run_periodogram(plan, data)
    finally:
        set_metrics(prev)
    s = reg.summary()
    want_fused = sum(len(st.lane_buckets) for st in plan.stages
                     if eng._fused_eligible(st, plan, "uint6"))
    assert want_fused == len(plan.stages)  # all stages eligible here
    assert s.get("dispatch_fused") == want_fused
    assert s.get("dispatch_pack", 0) == 0
    assert s.get("dispatch_kernel", 0) == 0
    assert s.get("dispatch_gather", 0) == 0


def test_fused_dm_batch_and_peaks_byte_identical(plan, kernel_path):
    """(D, N) batches through the fused path with ON-DEVICE peak
    detection: each trial's S/N equals its own single-trial search
    bitwise (the wire quantises per trial), device peaks == host
    find_peaks on the pulled cube, and their CSV serialisations are
    byte-identical (the bench parity gate's invariant, pinned on CPU)."""
    import io

    import pandas

    from riptide_tpu.libffa import generate_signal
    from riptide_tpu.metadata import Metadata
    from riptide_tpu.peak_detection import find_peaks
    from riptide_tpu.periodogram import Periodogram
    from riptide_tpu.search.engine import (
        collect_search_batch, queue_search_batch, search_snr_dev,
    )

    kernel_path.setenv("RIPTIDE_WIRE_DTYPE", "uint6")
    rng = np.random.default_rng(14)
    batch = rng.standard_normal((2, SIZE)).astype(np.float32)
    np.random.seed(7)
    batch[0] = generate_signal(SIZE, 0.068 / TSAMP, amplitude=16.0,
                               ducy=0.05)
    batch -= batch.mean(axis=1, keepdims=True)
    batch /= batch.std(axis=1, keepdims=True)
    tobs = SIZE * TSAMP

    handle = queue_search_batch(plan, batch, tobs=tobs, **PKW)
    snr = np.asarray(search_snr_dev(handle))
    _, _, s1 = eng.run_periodogram(plan, batch[0])
    np.testing.assert_array_equal(snr[0], s1)

    md = Metadata({"dm": 0.0, "tobs": tobs})
    pgram = Periodogram(plan.widths, plan.all_periods, plan.all_foldbins,
                        snr[0], md)
    host_peaks, _ = find_peaks(pgram, **PKW)
    dev_peaks_all, _ = collect_search_batch(handle, np.zeros(2))
    dev_peaks = dev_peaks_all[0]
    assert dev_peaks, "expected the injected pulsar to be detected"
    assert [tuple(p) for p in dev_peaks] == [tuple(p) for p in host_peaks]

    def csv_bytes(peaks):
        buf = io.StringIO()
        pandas.DataFrame(peaks).to_csv(buf, index=False)
        return buf.getvalue().encode()

    assert csv_bytes(dev_peaks) == csv_bytes(host_peaks)


def test_lane_split_bitwise_parity(kernel_path):
    """A bins range crossing the 128-lane tile boundary splits into two
    occupancy buckets; the split run must equal the unsplit container
    BITWISE (pure re-bucketing, no numeric change)."""
    lplan = periodogram_plan(4096, 1e-3, (1, 2), 0.126, 0.13, 126, 130)
    assert len(lplan.stages) == 1  # one stage keeps interpret cost low
    st0 = lplan.stages[0]
    tiles = sorted({-(-p // 128) for p in st0.ps_padded})
    assert tiles == [1, 2]
    assert len(st0.lane_buckets) == 2
    kernel_path.setenv("RIPTIDE_WIRE_DTYPE", "uint6")
    rng = np.random.default_rng(13)
    d = rng.standard_normal(4096).astype(np.float32)
    _, _, s_split = eng.run_periodogram(lplan, d)

    kernel_path.setenv("RIPTIDE_KERNEL_LANE_SPLIT", "0")
    assert len(st0.lane_buckets) == 1
    _, _, s_one = eng.run_periodogram(lplan, d)
    np.testing.assert_array_equal(s_split, s_one)


def test_gather_path_decodes_view_wire(plan, data, monkeypatch):
    """The gather path (CPU default) must decode the SAME byte-plane
    wire: quantised gather search within budget of its float32 gather
    result."""
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "gather")
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float32")
    _, _, s32 = eng.run_periodogram(plan, data)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint6")
    _, _, s6 = eng.run_periodogram(plan, data)
    assert np.max(np.abs(s6 - s32)) < 0.3
