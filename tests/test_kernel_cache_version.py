"""
KERNEL_CACHE_VERSION guard (riptide_tpu/ops/ffa_kernel.py).

The Pallas cycle-kernel executable cache is keyed by an explicit
version constant, not file contents, so warmed entries survive source
edits — which makes a semantic edit WITHOUT a version bump silently
serve stale executables that compute wrong numbers. This test pins the
bytecode digest of everything the version constant vouches for (the
kernel body, its packing helpers, and slottables' table builders) per
Python version: change any of their bodies and it fails until either
KERNEL_CACHE_VERSION is bumped and tools/update_kernel_digest.py
re-pins, or the edit is reverted. Docstring/comment edits and local
renames do not change the digest (matching the "no bump needed"
contract in the constant's comment).
"""
import json
import os
import sys

import pytest

from riptide_tpu.ops.ffa_kernel import (
    KERNEL_CACHE_VERSION, kernel_code_digest,
)

DIGEST_FILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "riptide_tpu", "ops", "kernel_digest.json",
)


def _pinned():
    with open(DIGEST_FILE) as f:
        data = json.load(f)
    py = f"{sys.version_info[0]}.{sys.version_info[1]}"
    return py, data["digests"].get(py)


def test_kernel_digest_pinned_for_this_python():
    py, entry = _pinned()
    if entry is None:
        pytest.skip(
            f"no pinned kernel digest for python {py}; run "
            "tools/update_kernel_digest.py to add one"
        )
    assert entry["kernel_cache_version"] == KERNEL_CACHE_VERSION, (
        "kernel_digest.json pins KERNEL_CACHE_VERSION="
        f"{entry['kernel_cache_version']} but the code has "
        f"{KERNEL_CACHE_VERSION}; run tools/update_kernel_digest.py"
    )
    assert entry["digest"] == kernel_code_digest(), (
        "the kernel/table-builder code bodies changed but "
        f"KERNEL_CACHE_VERSION is still {KERNEL_CACHE_VERSION}. A stale "
        "cached kernel executable with a mismatched table layout computes "
        "WRONG NUMBERS, not a crash: bump KERNEL_CACHE_VERSION in "
        "riptide_tpu/ops/ffa_kernel.py and re-pin with "
        "tools/update_kernel_digest.py (or revert the edit if it was "
        "not meant to be semantic)"
    )


def test_kernel_digest_stable_within_process():
    assert kernel_code_digest() == kernel_code_digest()
