"""
Correctness chain of the fused Pallas FFA/S-N kernel stack:

    oracle (ops.reference.ffa_transform, parity-tested against the
    reference recursion riptide/cpp/transforms.hpp:30-50)
      == slot_transform_np      (slot-layout index algebra)
      == simulate_dense         (the kernel's exact dense-op sequence)
      == CycleKernel(interpret) (Pallas kernel, interpret mode)

plus engine-level parity of the kernel path against the gather path.
Compiled-vs-oracle verification at production shapes runs on the real
chip via tools/kverify.py (the suite forces the CPU backend).
"""
import numpy as np
import pytest

from riptide_tpu.ops.ffa_kernel import CycleKernel, NWPAD
from riptide_tpu.ops.reference import boxcar_snr_2d, ffa_transform
from riptide_tpu.ops.slotffa import slot_transform_np
from riptide_tpu.ops.slottables import build_tables, simulate_dense
from riptide_tpu.ops.snr import boxcar_coeffs

# Non-power-of-2 m, m below/above slot thresholds, p > 128, p not a
# multiple of anything convenient.
SHAPES = [(2, 8), (5, 7), (8, 16), (12, 17), (16, 16), (37, 33),
          (100, 130), (121, 240), (250, 251)]


@pytest.mark.parametrize("m,p", SHAPES)
def test_slot_transform_matches_oracle(m, p):
    rng = np.random.default_rng(m * 1000 + p)
    data = rng.standard_normal((m, p)).astype(np.float32)
    np.testing.assert_array_equal(slot_transform_np(data), ffa_transform(data))


@pytest.mark.parametrize("m,p", SHAPES)
def test_simulate_dense_matches_oracle(m, p):
    rng = np.random.default_rng(m * 1000 + p)
    data = rng.standard_normal((m, p)).astype(np.float32)
    np.testing.assert_array_equal(simulate_dense(data), ffa_transform(data))


@pytest.mark.parametrize("m,p", [(13, 16), (100, 130)])
def test_simulate_dense_padded_bucket(m, p):
    """Deeper bucket (L > ceil(log2 m)) and lane padding P > p."""
    rng = np.random.default_rng(0)
    data = rng.standard_normal((m, p)).astype(np.float32)
    L = int(np.ceil(np.log2(m))) + 1
    P = ((p + 127) // 128) * 128
    np.testing.assert_array_equal(simulate_dense(data, L=L, P=P),
                                  ffa_transform(data))


def _kernel_case(ms, ps, widths, seed=0):
    widths = tuple(w for w in widths if w < min(ps))
    B, nw = len(ms), len(widths)
    h = np.zeros((B, nw), np.float32)
    b = np.zeros((B, nw), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.linspace(1.0, 2.0, B).astype(np.float32)
    k = CycleKernel(ms, ps, widths, h, b, std, interpret=True)
    rng = np.random.default_rng(seed)
    x = np.zeros((B, k.rows, k.P), np.float32)
    datas = []
    for i, (m, p) in enumerate(zip(ms, ps)):
        d = rng.standard_normal((m, p)).astype(np.float32)
        datas.append(d)
        x[i, :m, :p] = d
    return k, x, datas, widths, std


def _check_kernel(k, out, ms, ps, datas, widths, std):
    nw = len(widths)
    for i, (m, p, d) in enumerate(zip(ms, ps, datas)):
        if m == 1:
            continue  # padding problem, never read back
        want = boxcar_snr_2d(ffa_transform(d), np.asarray(widths),
                             stdnoise=float(std[i]))
        got = np.asarray(out)[i, :m, :nw]
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("ms,ps", [
    ([16], [16]),                      # power-of-2 minimum
    ([100], [130]),                    # p > 128 (two lane tiles)
    ([37, 29, 1], [33, 40, 33]),       # mixed bucket incl. m=1 padding
    ([250, 240, 230], [240, 250, 260]),  # production-style bins trial batch
    ([40, 38], [500, 520]),            # reference range-2 bins (p > 511)
    ([17], [1040]),                    # reference range-3 bins
])
def test_cycle_kernel_interpret_matches_oracle(ms, ps):
    widths = (1, 2, 3, 4, 6, 9, 13)
    k, x, datas, widths, std = _kernel_case(ms, ps, widths)
    out = k(x)
    _check_kernel(k, out, ms, ps, datas, widths, std)


@pytest.mark.parametrize("m", [17, 24, 33, 48, 90, 96, 180, 192])
def test_simulate_dense_base3_container(m):
    """Base-3 (1.5 * 2**k) containers must stay oracle-exact."""
    from riptide_tpu.ops.plan import num_levels
    from riptide_tpu.ops.slottables import container_rows

    L = num_levels(m)
    R = container_rows(m, L)
    assert R == 3 << (L - 2), (m, L, R)  # all cases chosen base-3
    rng = np.random.default_rng(m)
    data = rng.standard_normal((m, 19)).astype(np.float32)
    np.testing.assert_array_equal(simulate_dense(data, R=R),
                                  ffa_transform(data))


@pytest.mark.parametrize("ms,ps", [
    ([17, 20, 24], [10, 12, 9]),       # base-3 L=5 bucket (rows 24)
    ([90, 96, 1], [33, 40, 33]),       # base-3 L=7 bucket (rows 96)
])
def test_cycle_kernel_interpret_base3(ms, ps):
    """Interpret-mode kernel on base-3 buckets; rows must be 3 * 2**k
    and results oracle-exact."""
    widths = (1, 2, 3, 4)
    k, x, datas, widths, std = _kernel_case(ms, ps, widths)
    assert k.rows == 3 << (k.L - 2), (k.rows, k.L)
    out = k(x)
    _check_kernel(k, out, ms, ps, datas, widths, std)


def test_cycle_kernel_base3_disable(monkeypatch):
    """RIPTIDE_KERNEL_BASE3=0 forces the power-of-two container."""
    monkeypatch.setenv("RIPTIDE_KERNEL_BASE3", "0")
    k, x, datas, widths, std = _kernel_case([17, 20, 24], [10, 12, 9],
                                            (1, 2, 3))
    assert k.rows == 32
    out = k(x)
    _check_kernel(k, out, [17, 20, 24], [10, 12, 9], datas, widths, std)


def test_cycle_kernel_streaming_tables(monkeypatch):
    """The per-level table-streaming fallback (used when the resident
    all-levels scratch would blow the VMEM budget) stays oracle-exact.
    Forced via monkeypatch — it only triggers naturally at shapes too
    large for interpret mode."""
    from riptide_tpu.ops import ffa_kernel

    monkeypatch.setattr(ffa_kernel, "tables_resident",
                        lambda *a: False)
    ffa_kernel._build_call.cache_clear()
    try:
        ms, ps = [37, 29, 1], [33, 40, 33]
        k, x, datas, widths, std = _kernel_case(ms, ps, (1, 2, 3, 4, 6))
        out = k(x)
        _check_kernel(k, out, ms, ps, datas, widths, std)
    finally:
        ffa_kernel._build_call.cache_clear()


def test_cycle_kernel_dm_batch_axis():
    """(D, B, rows, P) input: every DM trial matches its own oracle."""
    ms, ps = [37, 29], [33, 40]
    widths = (1, 2, 3, 5)
    k, x0, _, widths, std = _kernel_case(ms, ps, widths)
    rng = np.random.default_rng(7)
    D = 3
    x = np.zeros((D,) + x0.shape, np.float32)
    datas = [[rng.standard_normal((m, p)).astype(np.float32)
              for m, p in zip(ms, ps)] for _ in range(D)]
    for d in range(D):
        for i, (m, p) in enumerate(zip(ms, ps)):
            x[d, i, :m, :p] = datas[d][i]
    out = np.asarray(k(x))
    assert out.shape[:2] == (D, len(ms))
    for d in range(D):
        _check_kernel(k, out[d], ms, ps, datas[d], widths, std)


def test_cycle_kernel_validation():
    h = np.ones((1, 2), np.float32)
    b = np.ones((1, 2), np.float32)
    std = np.ones(1, np.float32)
    with pytest.raises(ValueError, match="p <= 2047"):
        CycleKernel([100], [3000], (1, 2), h, b, std)
    with pytest.raises(ValueError, match="p <= 2047"):
        build_tables(100, 3000)
    with pytest.raises(ValueError, match="widths"):
        CycleKernel([100], [64], (1, 64), h, b, std)  # w >= min(p)
    many = tuple(range(1, NWPAD + 2))
    hh = np.ones((1, len(many)), np.float32)
    with pytest.raises(ValueError, match="widths"):
        CycleKernel([100], [64], many, hh, hh, std)


def test_engine_kernel_path_parity(monkeypatch):
    """Full periodogram: kernel path == gather path on a multi-stage plan
    (and therefore == the numpy oracle, which the gather path is tested
    against in test_search.py)."""
    from riptide_tpu.search.engine import run_periodogram, run_periodogram_batch
    from riptide_tpu.search.plan import periodogram_plan

    plan = periodogram_plan(4096, 1e-3, (1, 2, 3), 64e-3, 0.15, 64, 71)
    assert any(st.kernel_depth >= 3 for st in plan.stages)
    rng = np.random.default_rng(3)
    data = rng.standard_normal(4096).astype(np.float32)

    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float32")
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "gather")
    pg, fg, sg = run_periodogram(plan, data)
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "kernel")
    pk, fk, sk = run_periodogram(plan, data)

    np.testing.assert_array_equal(pg, pk)
    np.testing.assert_array_equal(fg, fk)
    np.testing.assert_allclose(sk, sg, rtol=2e-4, atol=2e-4)

    batch = rng.standard_normal((2, 4096)).astype(np.float32)
    _, _, sbk = run_periodogram_batch(plan, batch)
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "gather")
    _, _, sbg = run_periodogram_batch(plan, batch)
    np.testing.assert_allclose(sbk, sbg, rtol=2e-4, atol=2e-4)

    # The float16 wire format (the kernel path's default) trades ~1e-3
    # absolute S/N error for half the host->device traffic — well inside
    # the reference parity bar of +/-0.15.
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "kernel")
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float16")
    _, _, s16 = run_periodogram(plan, data)
    np.testing.assert_allclose(s16, sg, atol=2e-2)


def test_cycle_kernel_traceable_under_outer_trace():
    """Inside an outer trace (the sharded path calls the kernel from a
    shard_map body) the kernel must inline its plain jitted pallas call
    — an AOT-compiled _CachedCall executable cannot take tracers. Built
    NON-interpret so build() returns the _CachedCall wrapper, then
    traced (not compiled: Mosaic cannot lower on CPU, but tracing stops
    before lowering)."""
    import jax

    from riptide_tpu.ops.snr import boxcar_coeffs as _bc

    ms, ps, widths = [12, 13], [16, 17], (1, 2, 3)
    B = len(ms)
    h = np.zeros((B, 3), np.float32)
    b = np.zeros((B, 3), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = _bc(p, widths)
    k = CycleKernel(ms, ps, widths, h, b, np.ones(B, np.float32),
                    interpret=False)
    call = k.build(2)
    assert hasattr(call, "jitted"), "expected the _CachedCall wrapper"
    x = np.zeros((2, B, k.rows, k.P), np.float32)
    jaxpr = jax.make_jaxpr(lambda xx: k(xx))(x)
    assert "pallas_call" in str(jaxpr), "kernel did not inline into the trace"
