"""
Running median tests: naive-oracle parity with edge padding, fast
(scrunched) path consistency. Mirrors riptide/tests/test_running_median.py.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from riptide_tpu.ops import reference as ref
from riptide_tpu.ops import running_median_jax, scrunch_jax, fast_running_median_jax


def naive_running_median(data, w):
    h = w // 2
    padded = np.pad(data, (h, h), mode="edge")
    return np.asarray([np.median(padded[i : i + w]) for i in range(data.size)])


@pytest.mark.parametrize("w", [1, 3, 5, 7, 11, 25, 37])
def test_oracle_vs_naive(w):
    x = np.random.RandomState(0).normal(size=100).astype(np.float32)
    assert np.array_equal(ref.running_median(x, w), naive_running_median(x, w).astype("f"))


@pytest.mark.parametrize("w", [1, 3, 5, 7, 11, 25, 37])
def test_jax_vs_oracle(w):
    x = np.random.RandomState(1).normal(size=100).astype(np.float32)
    got = np.asarray(running_median_jax(jnp.asarray(x), w))
    assert np.allclose(got, ref.running_median(x, w))


def test_oracle_errors():
    data = np.arange(10, dtype=np.float32)
    with pytest.raises(ValueError):
        ref.running_median(data, 2)
    with pytest.raises(ValueError):
        ref.running_median(data, 11)
    with pytest.raises(ValueError):
        ref.running_median(np.zeros((4, 8)), 3)


def test_scrunch():
    x = np.arange(10, dtype=np.float32)
    got = np.asarray(scrunch_jax(jnp.asarray(x), 3))
    assert np.allclose(got, [1.0, 4.0, 7.0])


def test_fast_path_no_scrunch_equals_exact():
    """When width <= min_points the fast path must be the exact median."""
    x = np.random.RandomState(2).normal(size=500).astype(np.float32)
    got = np.asarray(fast_running_median_jax(jnp.asarray(x), 51, 101))
    assert np.allclose(got, ref.running_median(x, 51))


def test_fast_path_scrunched_tracks_trend():
    """Scrunched approximate path must track a slow baseline closely."""
    n = 20000
    t = np.arange(n, dtype=np.float32)
    baseline = np.sin(2 * np.pi * t / n).astype(np.float32) * 10
    x = baseline + np.random.RandomState(3).normal(size=n).astype(np.float32)
    got = np.asarray(fast_running_median_jax(jnp.asarray(x), 2001, 101))
    # middle section (away from edges) must track the baseline
    mid = slice(2000, n - 2000)
    assert np.abs(got[mid] - baseline[mid]).max() < 0.5
