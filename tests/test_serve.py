"""
Survey-as-a-service tests: the rserve daemon (riptide_tpu/serve) over
REAL loopback HTTP — lifecycle, fair-share interleaving of concurrent
jobs, quota enforcement, chunk-boundary cancellation, warm-executable
reuse across jobs, and registry-replay restart recovery. The daemon
runs in-process (the subprocess kill/restart variant lives in the
chaos campaign's ``serve-kill-mid-job`` schedule); compiled
executables are process-wide, so the first searched job pays the CPU
compile once and every later test in this module runs warm.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from synth import generate_data_presto
from riptide_tpu.serve import ServeDaemon, FairShareQueue, TenantTable
from riptide_tpu.serve.daemon import (
    fold_job_events, geometry_key, job_record,
)
from riptide_tpu.serve.queue import (JobCancelled, JobDeadlineExceeded,
                                     JobDrained, QuotaExceeded)
from riptide_tpu.survey import incidents
from riptide_tpu.survey.journal import SurveyJournal
from riptide_tpu.survey.metrics import get_metrics

# The chaos campaign's tiny deterministic survey (CPU-fast; one
# compile for the whole module).
TOBS, TSAMP, PERIOD = 12.0, 1e-3, 0.5
DMS = (0.0, 5.0, 10.0)

SEARCH = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


@pytest.fixture(scope="module")
def data_files(tmp_path_factory):
    outdir = tmp_path_factory.mktemp("serve_data")
    return [
        generate_data_presto(str(outdir), f"s_DM{dm:.2f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm,
                             amplitude=30.0)
        for dm in DMS
    ]


def _spec(files, tenant="default", priority=0):
    return {"files": list(files), "fmt": "presto", "tenant": tenant,
            "priority": priority,
            "deredden": {"rmed_width": 4.0, "rmed_minpts": 101},
            "search": SEARCH}


def _req_full(base, path, method="GET", body=None, timeout=10.0,
              headers=None):
    """(status, body_bytes, response_headers) — the header-asserting
    variant (Retry-After back-pressure, Idempotency-Key replays)."""
    data = json.dumps(body).encode() if body is not None else None
    hdrs = {"Content-Type": "application/json"} if data else {}
    hdrs.update(headers or {})
    req = urllib.request.Request(base + path, data=data, method=method,
                                 headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as err:
        return err.code, err.read(), dict(err.headers)


def _req(base, path, method="GET", body=None, timeout=10.0, headers=None):
    code, raw, _ = _req_full(base, path, method=method, body=body,
                             timeout=timeout, headers=headers)
    return code, raw


def _req_json(base, path, method="GET", body=None, headers=None):
    code, raw = _req(base, path, method=method, body=body, headers=headers)
    return code, json.loads(raw)


def _wait_terminal(base, jid, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        code, doc = _req_json(base, f"/jobs/{jid}")
        assert code == 200, doc
        if doc.get("status") in ("done", "failed", "cancelled"):
            return doc
        time.sleep(0.1)
    raise AssertionError(f"{jid} did not finish within {timeout_s}s")


@pytest.fixture
def daemon(tmp_path):
    started = []

    def _start(**kw):
        kw.setdefault("port", 0)
        d = ServeDaemon(str(tmp_path / "serve"), **kw).start()
        started.append(d)
        return d, f"http://127.0.0.1:{d.port}"

    yield _start
    for d in started:
        d.stop()


# ------------------------------------------------------------- unit layer

def test_fold_job_events_lifecycle():
    recs = [
        job_record("j0001", "submitted", tenant="a", priority=2,
                   spec={"search": SEARCH}),
        job_record("j0001", "started"),
        job_record("j0001", "done", npeaks=4, device_s=1.5,
                   queue_wait_s=0.1, chunks_total=3),
        job_record("j0002", "submitted", tenant="b"),
    ]
    jobs = fold_job_events(recs)
    assert jobs["j0001"]["status"] == "done"
    assert jobs["j0001"]["tenant"] == "a"
    assert jobs["j0001"]["priority"] == 2
    assert jobs["j0001"]["npeaks"] == 4
    assert jobs["j0001"]["chunks_total"] == 3
    assert jobs["j0002"]["status"] == "pending"
    # Garbage and foreign kinds fold to nothing.
    assert fold_job_events([{"kind": "chunk"}, "junk", None]) == {}


def test_geometry_key_canonical():
    a = _spec(["x.inf"])
    b = _spec(["y.inf"], tenant="other")  # data/tenant don't change it
    assert geometry_key(a) == geometry_key(b)
    c = dict(a, search=[{"ffa_search": {"period_min": 0.4}}])
    assert geometry_key(a) != geometry_key(c)


def test_fair_share_queue_pick_order():
    q = FairShareQueue()
    q.register("a1", tenant="a")
    q.register("a2", tenant="a")
    q.register("b1", tenant="b")
    # Simulate accumulated device time: tenant a has consumed more, so
    # b's waiting job must win the next turn; priority trumps both.
    q._tenant_device_s["a"] = 5.0
    q._tenant_device_s["b"] = 1.0
    for jid in ("a1", "a2", "b1"):
        q._entries[jid].waiting = True
    assert q._pick().job_id == "b1"
    q.register("a0", tenant="a", priority=-1)
    q._entries["a0"].waiting = True
    assert q._pick().job_id == "a0"


def test_queue_cancel_raises_at_begin():
    q = FairShareQueue()
    gate = q.register("j1")
    q.cancel("j1")
    with pytest.raises(JobCancelled):
        gate.begin(0)


def test_queue_drain_raises_at_begin():
    q = FairShareQueue()
    gate = q.register("j1")
    q.drain()
    assert q.draining
    with pytest.raises(JobDrained):
        gate.begin(0)


def test_queue_deadline_raises_at_begin():
    q = FairShareQueue()
    gate = q.register("j1", deadline_s=0.01)
    time.sleep(0.03)
    with pytest.raises(JobDeadlineExceeded):
        gate.begin(0)
    # An unexpired deadline admits normally.
    gate2 = FairShareQueue().register("j2", deadline_s=60.0)
    gate2.begin(0)
    gate2.end(0)


def test_tenant_quota_admission_and_budget():
    t = TenantTable(budget_device_s=2.0, max_active=1)
    ok, _ = t.admit("a")
    assert ok
    t.job_started("a")
    ok, reason = t.admit("a")
    assert not ok and "max active" in reason
    t.job_finished("a")
    t.charge("a", 2.5)
    assert t.exhausted("a")
    ok, reason = t.admit("a")
    assert not ok and "budget exhausted" in reason
    assert t.remaining("a") == 0.0
    # Unlimited tenant budget (0) never exhausts.
    t2 = TenantTable(budget_device_s=0.0)
    t2.charge("a", 1e9)
    assert not t2.exhausted("a")
    q = FairShareQueue(tenants=t)
    gate = q.register("j1", tenant="a")
    with pytest.raises(QuotaExceeded):
        gate.begin(0)


# ----------------------------------------------------------- service layer

def test_job_lifecycle_over_http(daemon, data_files):
    d, base = daemon(workers=1)
    code, doc = _req_json(base, "/jobs", "POST", _spec(data_files[:1]))
    assert code == 202, doc
    jid = doc["job_id"]
    assert doc["status"] == "pending"
    doc = _wait_terminal(base, jid)
    assert doc["status"] == "done", doc.get("error")
    assert doc["npeaks"] > 0
    assert doc["chunks_total"] == 1
    assert doc["device_s"] > 0
    assert doc["queue_wait_s"] >= 0
    # The served CSV is byte-identical to the job directory's product.
    code, payload = _req(base, f"/jobs/{jid}/peaks")
    assert code == 200
    with open(os.path.join(doc["directory"], "peaks.csv"), "rb") as fobj:
        assert payload == fobj.read()
    assert payload.startswith(b"period,")
    # Listing carries the job plus the quota/queue/pin surfaces.
    code, listing = _req_json(base, "/jobs")
    assert code == 200
    assert [j["job_id"] for j in listing["jobs"]] == [jid]
    assert "default" in listing["tenants"]
    assert listing["geometry_pins"]
    # Unknown job and not-done peaks answer with proper codes.
    assert _req_json(base, "/jobs/j9999")[0] == 404
    code, _ = _req_json(base, "/jobs", "POST", {"search": SEARCH})
    assert code == 400  # no input files
    # The job's artifacts are ordinary survey artifacts: its own
    # journal replays like any batch run's.
    j = SurveyJournal(os.path.join(d.root, "jobs", jid))
    assert sorted(j.completed_chunks()) == [0]


def test_concurrent_jobs_fair_share_interleaving(daemon, data_files):
    d, base = daemon(workers=2)
    specs = [_spec(data_files, tenant="alice"),
             _spec(data_files, tenant="bob")]
    jids = []
    for spec in specs:
        code, doc = _req_json(base, "/jobs", "POST", spec)
        assert code == 202, doc
        jids.append(doc["job_id"])
    docs = [_wait_terminal(base, jid) for jid in jids]
    assert all(doc["status"] == "done" for doc in docs)
    # Journal-timestamp interleaving: merge both jobs' chunk records by
    # their journaled utc stamps — the fair-share gate must alternate
    # device turns between the tenants rather than running one job to
    # completion first.
    stamped = []
    for jid in jids:
        j = SurveyJournal(os.path.join(d.root, "jobs", jid))
        for cid, (rec, _peaks) in j.completed_chunks().items():
            stamped.append((rec["utc"], jid, cid))
        assert sorted(cid for cid, _ in j.completed_chunks().items()) \
            == [0, 1, 2]
    stamped.sort()
    order = [jid for _, jid, _ in stamped]
    switches = sum(1 for a, b in zip(order, order[1:]) if a != b)
    assert switches >= 2, f"no fair-share interleaving: {order}"
    # Both tenants show up in the device-time accounting.
    code, listing = _req_json(base, "/jobs")
    assert set(listing["tenants"]) >= {"alice", "bob"}
    assert all(v["device_s_spent"] > 0
               for k, v in listing["tenants"].items()
               if k in ("alice", "bob"))


def test_admission_rejection_and_incident(daemon, data_files):
    captured = []
    prev = incidents.set_sink(captured.append)
    try:
        # workers=0: jobs stay pending, so the resident-cap and
        # per-tenant admission checks are deterministic.
        d, base = daemon(workers=0, max_jobs=2,
                         tenants=TenantTable(max_active=1))
        code, doc = _req_json(base, "/jobs", "POST",
                              _spec(data_files[:1], tenant="alice"))
        assert code == 202
        # Same tenant again: per-tenant max_active=1 refuses.
        code, doc = _req_json(base, "/jobs", "POST",
                              _spec(data_files[:1], tenant="alice"))
        assert code == 429
        assert "max active" in doc["error"]
        # Another tenant still fits (resident 2/2)...
        code, doc = _req_json(base, "/jobs", "POST",
                              _spec(data_files[:1], tenant="bob"))
        assert code == 202
        # ...and the NEXT submit trips the daemon-wide resident cap.
        code, doc = _req_json(base, "/jobs", "POST",
                              _spec(data_files[:1], tenant="carol"))
        assert code == 429
        assert "max resident" in doc["error"]
    finally:
        incidents.set_sink(prev)
    kinds = [rec["incident"] for rec in captured]
    assert kinds.count("job_rejected") == 2


def test_runtime_quota_stops_at_chunk_boundary(daemon, data_files):
    tenants = TenantTable(budget_device_s=1e-6)
    d, base = daemon(workers=1, tenants=tenants)
    code, doc = _req_json(base, "/jobs", "POST",
                          _spec(data_files, tenant="meter"))
    assert code == 202
    doc = _wait_terminal(base, doc["job_id"])
    # The first chunk's turn exhausts the micro-budget; the stop lands
    # at the NEXT chunk boundary, so the journal keeps the completed
    # chunk and stays resumable.
    assert doc["status"] == "failed"
    assert "budget exhausted" in doc["error"]
    j = SurveyJournal(doc["directory"])
    done = j.completed_chunks()
    assert 0 < len(done) < len(DMS)
    # Job-scoped attribution: the incident lands in the job's OWN
    # journal (its RunContext sink), not the process-global fallback.
    assert any(rec["incident"] == "quota_exceeded"
               for rec in j.incidents())


def _spin(predicate, timeout_s=120.0, tick=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(tick)
    return False


def test_cancellation_leaves_resumable_journal(daemon, data_files):
    d, base = daemon(workers=1)
    # Deterministic mid-job cancellation: a higher-priority queue entry
    # (the "blocker") holds the device turn around the job's first
    # chunk, so the job is provably frozen at a chunk boundary when the
    # DELETE lands — no racing the (fast, warm) chunk wall-clock.
    blocker = d.queue.register("blocker", priority=-1)
    blocker.begin(0)  # hold the device before the job can start
    code, doc = _req_json(base, "/jobs", "POST", _spec(data_files))
    assert code == 202
    jid = doc["job_id"]
    jdir = os.path.join(d.root, "jobs", jid)
    # The job parks waiting for its first turn...
    assert _spin(lambda: d.queue.snapshot()["jobs"]
                 .get(jid, {}).get("waiting"))
    blocker.end(0)  # ...takes the device for chunk 0...
    assert _spin(lambda: d.queue.snapshot()["active"] == jid)
    # ...and the re-queued blocker wins the NEXT turn by priority, so
    # the job freezes right after journaling chunk 0.
    t = threading.Thread(target=lambda: blocker.begin(1), daemon=True)
    t.start()
    assert _spin(lambda: d.queue.snapshot()["active"] == "blocker")
    code, doc = _req_json(base, f"/jobs/{jid}", "DELETE")
    assert code in (200, 202), doc
    doc = _wait_terminal(base, jid)
    blocker.end(1)
    d.queue.unregister("blocker")
    assert doc["status"] == "cancelled"
    # Chunk-boundary cancellation: the first chunk's journal record
    # survives, the rest are still owed, nothing torn — resumable.
    done = SurveyJournal(jdir).completed_chunks()
    assert sorted(done) == [0]
    assert _req_json(base, f"/jobs/{jid}/peaks")[0] == 409
    # Cancelling a finished job is a 409 no-op.
    assert _req_json(base, f"/jobs/{jid}", "DELETE")[0] == 409


def test_second_job_runs_warm(daemon, data_files):
    d, base = daemon(workers=1)
    code, doc = _req_json(base, "/jobs", "POST", _spec(data_files[:1]))
    assert code == 202
    first = _wait_terminal(base, doc["job_id"])
    assert first["status"] == "done"
    cold_before = get_metrics().counter("exec_cold_builds")
    code, doc = _req_json(base, "/jobs", "POST", _spec(data_files[:1]))
    assert code == 202
    second = _wait_terminal(base, doc["job_id"])
    assert second["status"] == "done"
    # Warm service contract: a repeat geometry compiles NOTHING — the
    # cold-build counter stays flat while warm hits accrue, and the
    # job document says so.
    assert get_metrics().counter("exec_cold_builds") == cold_before
    assert second["warm_start"] is True
    code, listing = _req_json(base, "/jobs")
    pin = listing["geometry_pins"][geometry_key(_spec(data_files[:1]))]
    assert pin["jobs"] >= 2
    # Same inputs, same geometry, same survey: identical products.
    for name in ("peaks.csv",):
        with open(os.path.join(first["directory"], name), "rb") as f1, \
                open(os.path.join(second["directory"], name), "rb") as f2:
            assert f1.read() == f2.read()


def test_restart_requeues_unfinished_jobs(daemon, data_files):
    # Daemon 1 accepts but never runs (workers=0), then stops: the
    # submitted job survives only as jobs.jsonl events.
    d1, base1 = daemon(workers=0)
    code, doc = _req_json(base1, "/jobs", "POST", _spec(data_files[:1]))
    assert code == 202
    jid = doc["job_id"]
    d1.stop()
    # Daemon 2 on the same root replays the registry, re-queues the
    # pending job and completes it — ids continue, not restart.
    d2, base2 = daemon(workers=1)
    assert d2.root == d1.root
    doc = _wait_terminal(base2, jid)
    assert doc["status"] == "done"
    code, doc2 = _req_json(base2, "/jobs", "POST", _spec(data_files[:1]))
    assert doc2["job_id"] != jid
    code, payload = _req(base2, f"/jobs/{jid}/peaks")
    assert code == 200 and payload.startswith(b"period,")


def test_idempotent_submission_dedupes_across_restart(daemon, data_files):
    d1, base1 = daemon(workers=0)
    hdr = {"Idempotency-Key": "key-abc"}
    code, doc = _req_json(base1, "/jobs", "POST", _spec(data_files[:1]),
                          headers=hdr)
    assert code == 202
    jid = doc["job_id"]
    # A retried submit with the same key answers with the EXISTING
    # job's document — no second enqueue.
    code, doc2 = _req_json(base1, "/jobs", "POST", _spec(data_files[:1]),
                           headers=hdr)
    assert code == 202 and doc2["job_id"] == jid
    # A different key is a genuinely new job.
    code, doc3 = _req_json(base1, "/jobs", "POST", _spec(data_files[:1]),
                           headers={"Idempotency-Key": "key-def"})
    assert code == 202 and doc3["job_id"] != jid
    code, listing = _req_json(base1, "/jobs")
    assert len(listing["jobs"]) == 2
    d1.stop()
    # The dedupe map is rebuilt from the replayed registry, so a client
    # retrying ACROSS a daemon restart still dedupes.
    d2, base2 = daemon(workers=0)
    code, doc4 = _req_json(base2, "/jobs", "POST", _spec(data_files[:1]),
                           headers=hdr)
    assert code == 202 and doc4["job_id"] == jid


def test_backpressure_carries_retry_after(daemon, data_files):
    d, base = daemon(workers=0, max_jobs=1)
    code, _, _ = _req_full(base, "/jobs", "POST", _spec(data_files[:1]))
    assert code == 202
    # The resident-cap 429 advises when to retry — header and body
    # agree (the header is what generic HTTP clients honour).
    code, raw, hdrs = _req_full(base, "/jobs", "POST",
                                _spec(data_files[:1]))
    doc = json.loads(raw)
    assert code == 429
    assert doc["retry_after_s"] > 0
    assert hdrs.get("Retry-After") == str(doc["retry_after_s"])


def test_deadline_fails_job_with_timeout_incident(daemon, data_files):
    d, base = daemon(workers=1)
    # A non-positive deadline is a spec error, not an enqueue.
    code, doc = _req_json(base, "/jobs", "POST",
                          dict(_spec(data_files[:1]), deadline_s=-1))
    assert code == 400 and "deadline_s" in doc["error"]
    # The blocker holds the device turn past the micro-deadline, so
    # the job expires deterministically at its FIRST begin() — the
    # gate checks the deadline while parked, no chunk ever runs.
    blocker = d.queue.register("blocker", priority=-1)
    blocker.begin(0)
    try:
        code, doc = _req_json(base, "/jobs", "POST",
                              dict(_spec(data_files[:1]), deadline_s=0.2))
        assert code == 202
        doc = _wait_terminal(base, doc["job_id"], timeout_s=30.0)
    finally:
        blocker.end(0)
        d.queue.unregister("blocker")
    assert doc["status"] == "failed"
    assert "deadline" in doc["error"]
    # The job_timeout incident is journaled in the job's own directory.
    j = SurveyJournal(doc["directory"])
    assert any(rec["incident"] == "job_timeout" for rec in j.incidents())
    assert j.completed_chunks() == {}


def test_drain_parks_job_and_restart_resumes(daemon, data_files):
    d, base = daemon(workers=1)
    # Blocker-stepped as in the cancellation test: the job completes
    # exactly chunk 0, then freezes at the gate — the drain provably
    # lands mid-job.
    blocker = d.queue.register("blocker", priority=-1)
    blocker.begin(0)
    code, doc = _req_json(base, "/jobs", "POST", _spec(data_files))
    assert code == 202
    jid = doc["job_id"]
    assert _spin(lambda: d.queue.snapshot()["jobs"]
                 .get(jid, {}).get("waiting"))
    blocker.end(0)
    assert _spin(lambda: d.queue.snapshot()["active"] == jid)
    t = threading.Thread(target=lambda: blocker.begin(1), daemon=True)
    t.start()
    assert _spin(lambda: d.queue.snapshot()["active"] == "blocker")
    # POST /drain: admission stops with a Retry-After'd 503...
    code, doc = _req_json(base, "/drain", "POST", {})
    assert code == 202 and doc["draining"] is True
    code, raw, hdrs = _req_full(base, "/jobs", "POST",
                                _spec(data_files[:1]))
    body = json.loads(raw)
    assert code == 503 and body["draining"] is True
    assert hdrs.get("Retry-After") == str(body["retry_after_s"])
    # ...and /status says so.
    code, status = _req_json(base, "/status")
    assert code == 200 and status.get("draining") is True
    blocker.end(1)
    d.queue.unregister("blocker")
    assert d.wait_drained(timeout=60)
    # The parked job got NO terminal record: still pending/running,
    # its journal holding exactly the completed chunk.
    code, doc = _req_json(base, f"/jobs/{jid}")
    assert doc["status"] in ("pending", "running")
    jdir = os.path.join(d.root, "jobs", jid)
    assert sorted(SurveyJournal(jdir).completed_chunks()) == [0]
    d.stop()
    # The restart replays the registry, re-queues the parked job
    # (resumed-flagged) and its journal finishes the remaining chunks.
    d2, base2 = daemon(workers=1)
    doc = _wait_terminal(base2, jid)
    assert doc["status"] == "done", doc.get("error")
    assert doc.get("resumed") is True
    assert sorted(SurveyJournal(jdir).completed_chunks()) \
        == list(range(len(DMS)))


def test_drain_journals_park_record_into_each_jobs_journal(daemon,
                                                           data_files):
    # Two tenants' jobs are mid-job when the drain lands (one parked
    # between chunks, one parked before its first turn): each job's
    # OWN journal must receive its job_drained park record — the
    # worker raises JobDrained under the job's RunContext, so the
    # incident routes to that journal and never the sibling's (the
    # attribution contract RIP012 and ripsched's runctx model guard).
    d, base = daemon(workers=2)
    blocker = d.queue.register("blocker", priority=-1)
    blocker.begin(0)
    jids = []
    for tenant in ("alice", "bob"):
        code, doc = _req_json(base, "/jobs", "POST",
                              _spec(data_files, tenant=tenant))
        assert code == 202
        jids.append(doc["job_id"])
    assert _spin(lambda: all(
        d.queue.snapshot()["jobs"].get(j, {}).get("waiting")
        for j in jids))
    # Step: let exactly one job take a chunk turn, then the
    # priority-(-1) blocker reclaims the device and both jobs are
    # waiting at a gate again.
    blocker.end(0)
    assert _spin(lambda: d.queue.snapshot()["active"] in jids)
    t = threading.Thread(target=lambda: blocker.begin(1), daemon=True)
    t.start()
    assert _spin(lambda: d.queue.snapshot()["active"] == "blocker")
    code, doc = _req_json(base, "/drain", "POST", {})
    assert code == 202 and doc["draining"] is True
    blocker.end(1)
    d.queue.unregister("blocker")
    assert d.wait_drained(timeout=60)
    for jid, sibling in ((jids[0], jids[1]), (jids[1], jids[0])):
        # No terminal record: both jobs parked resumable.
        code, doc = _req_json(base, f"/jobs/{jid}")
        assert doc["status"] in ("pending", "running")
        jdir = os.path.join(d.root, "jobs", jid)
        parks = [rec for rec in SurveyJournal(jdir).incidents()
                 if rec["incident"] == "job_drained"]
        assert len(parks) == 1, f"{jid}: {parks}"
        assert parks[0]["detail"]["job_id"] == jid
        assert not any(rec["detail"].get("job_id") == sibling
                       for rec in SurveyJournal(jdir).incidents())


def test_concurrent_fault_attribution_is_job_scoped(daemon, data_files):
    # Two concurrent jobs, EACH with its own injected heartbeat-fsync
    # fault: every obs_write_failed incident must land in the journal
    # of the job whose heartbeat it was — never the sibling's. This is
    # the RunContext attribution contract under real thread
    # interleaving (two workers, fair-share alternation).
    d, base = daemon(workers=2)
    jids = []
    for tenant in ("alice", "bob"):
        spec = _spec(data_files, tenant=tenant)
        spec["fault_inject"] = "fsync_fail:heartbeat_append"
        code, doc = _req_json(base, "/jobs", "POST", spec)
        assert code == 202
        jids.append(doc["job_id"])
    docs = [_wait_terminal(base, jid) for jid in jids]
    # Heartbeats are observability: the faults degrade, never kill.
    assert all(doc["status"] == "done" for doc in docs)
    for doc, jid, sibling in ((docs[0], jids[0], jids[1]),
                              (docs[1], jids[1], jids[0])):
        errs = [rec["detail"].get("error", "")
                for rec in SurveyJournal(doc["directory"]).incidents()
                if rec["incident"] == "obs_write_failed"]
        assert errs, f"{jid}: no obs_write_failed journaled"
        # The injected error names the faulted path — which lives in
        # the job's own directory, so attribution is checkable.
        assert all(jid in err for err in errs)
        assert not any(sibling in err for err in errs)


def test_device_error_single_fault_retries_to_done(daemon, data_files):
    d, base = daemon(workers=1)
    before = get_metrics().counter("device_errors")
    spec = _spec(data_files[:1])
    spec["fault_inject"] = "device_error:0"
    code, doc = _req_json(base, "/jobs", "POST", spec)
    assert code == 202
    doc = _wait_terminal(base, doc["job_id"])
    # One transient XLA runtime failure: the retry path evicts the
    # resident executables and the re-dispatch completes the job.
    assert doc["status"] == "done", doc.get("error")
    assert get_metrics().counter("device_errors") > before


def test_persistent_device_error_fails_only_that_job(daemon, data_files):
    d, base = daemon(workers=2)
    code, clean = _req_json(base, "/jobs", "POST", _spec(data_files[:1]))
    assert code == 202
    spec = _spec(data_files[:1], tenant="victim")
    spec["fault_inject"] = "device_error:0x9"  # outlasts every retry
    code, faulted = _req_json(base, "/jobs", "POST", spec)
    assert code == 202
    fdoc = _wait_terminal(base, faulted["job_id"])
    cdoc = _wait_terminal(base, clean["job_id"])
    # Containment: only the implicated job fails, with the incident in
    # ITS journal; the sibling and the daemon are untouched.
    assert cdoc["status"] == "done", cdoc.get("error")
    assert fdoc["status"] == "failed"
    fj = SurveyJournal(fdoc["directory"])
    assert any(rec["incident"] == "device_error" for rec in fj.incidents())
    cj = SurveyJournal(cdoc["directory"])
    assert not any(rec["incident"] == "device_error"
                   for rec in cj.incidents())
    # The daemon keeps serving after the device error.
    code, doc = _req_json(base, "/jobs", "POST", _spec(data_files[:1]))
    assert code == 202
    assert _wait_terminal(base, doc["job_id"])["status"] == "done"


def test_jobs_endpoint_without_daemon():
    from riptide_tpu.obs import prom

    server = prom.serve(0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        code, doc = _req_json(base, "/jobs")
        assert code == 503
        assert "no survey service" in doc["error"]
        code, doc = _req_json(base, "/jobs", "POST", {})
        assert code == 503
    finally:
        server.close()
