"""
End-to-end pipeline tests with deterministic synthetic data, mirroring
the reference's strategy (riptide/tests/test_pipeline.py): three PRESTO
DM trials (0/10/20) with the DM-10 one brightest, run through the real
argparse entry point, asserting the top candidate's parameters; a
pure-noise run produces no candidates; config validation failures raise
typed errors.
"""
import glob
import json
import os
import types

import numpy as np
import pytest
import yaml

from riptide_tpu import load_json
from riptide_tpu.pipeline import (
    InvalidPipelineConfig,
    InvalidSearchRange,
    Pipeline,
    get_parser,
    hdiag,
    htest,
    run_program,
    select_dms,
    validate_pipeline_config,
    validate_ranges,
)
from riptide_tpu.pipeline.peak_cluster import PeakCluster, clusters_to_dataframe

from synth import generate_data_presto, write_presto

HERE = os.path.dirname(__file__)
CONFIG_A = os.path.join(HERE, "pipeline_config_A.yml")
CONFIG_B = os.path.join(HERE, "pipeline_config_B.yml")

TOBS = 128.0
TSAMP = 256e-6
PERIOD = 1.0
# Amplitude per DM trial: DM 10 is the true dispersion measure
AMPLITUDES = {0.0: 10.0, 10.0: 20.0, 20.0: 10.0}


def make_fake_survey(outdir, amplitudes=AMPLITUDES):
    """Write one PRESTO .inf/.dat pair per DM trial; identical seeded
    noise, pulsar amplitude peaking at DM 10."""
    paths = []
    for dm, amp in amplitudes.items():
        paths.append(
            generate_data_presto(
                str(outdir), f"fake_DM{dm:.2f}", tobs=TOBS, tsamp=TSAMP,
                period=PERIOD, dm=dm, amplitude=amp, ducy=0.02,
            )
        )
    return paths


def run_pipeline(config, files, outdir):
    args = get_parser().parse_args(
        ["--config", config, "--outdir", str(outdir), "--log-level", "WARNING"]
        + [str(f) for f in files]
    )
    run_program(args)


def test_pipeline_finds_fake_pulsar(tmp_path):
    indir = tmp_path / "data"
    outdir = tmp_path / "out"
    indir.mkdir()
    outdir.mkdir()
    files = make_fake_survey(indir)

    run_pipeline(CONFIG_A, files, outdir)

    for product in ("peaks.csv", "clusters.csv", "candidates.csv"):
        assert (outdir / product).exists()

    cand_files = sorted(glob.glob(str(outdir / "candidate_*.json")))
    assert cand_files, "no candidate files written"
    cand = load_json(cand_files[0])

    # The reference's deterministic oracle (riptide/tests/test_pipeline.py:64-74)
    assert abs(cand.params["period"] - PERIOD) < 0.1 / TOBS * PERIOD**2
    assert cand.params["dm"] == 10.0
    assert cand.params["width"] == 13
    assert abs(cand.params["snr"] - 18.5) < 0.15


def test_pipeline_config_B(tmp_path):
    """Config B: DM cap + dm_min filter + max_number 1 + PNG plots."""
    indir = tmp_path / "data"
    outdir = tmp_path / "out"
    indir.mkdir()
    outdir.mkdir()
    files = make_fake_survey(indir)

    run_pipeline(CONFIG_B, files, outdir)

    cand_files = sorted(glob.glob(str(outdir / "candidate_*.json")))
    assert len(cand_files) == 1  # max_number: 1
    assert (outdir / "candidate_0000.png").exists()  # plot_candidates: True
    cand = load_json(cand_files[0])
    assert cand.params["dm"] == 10.0  # dm_min: 1.0 keeps only the real DM


def test_pipeline_pure_noise(tmp_path):
    """A pure-noise survey must produce no candidate files
    (riptide/tests/test_pipeline.py:77-97)."""
    indir = tmp_path / "data"
    outdir = tmp_path / "out"
    indir.mkdir()
    outdir.mkdir()
    files = make_fake_survey(indir, amplitudes={0.0: 0.0, 10.0: 0.0, 20.0: 0.0})

    run_pipeline(CONFIG_A, files, outdir)
    assert not glob.glob(str(outdir / "candidate_*.json"))
    assert not glob.glob(str(outdir / "candidate_*.png"))


# ----------------------------------------------------------------------------
# Config validation
# ----------------------------------------------------------------------------

def load_config(path):
    with open(path) as fobj:
        return yaml.safe_load(fobj)


def test_example_config_validates():
    example = os.path.join(
        os.path.dirname(HERE), "riptide_tpu", "pipeline", "config", "example.yaml"
    )
    conf = validate_pipeline_config(load_config(example))
    assert conf["processes"] == 4
    assert len(conf["ranges"]) == 3
    validate_ranges(conf["ranges"], 64e-6)


def test_config_validation_errors():
    conf = load_config(CONFIG_A)

    bad = json.loads(json.dumps(conf))
    bad["processes"] = -1
    with pytest.raises(InvalidPipelineConfig):
        validate_pipeline_config(bad)

    bad = json.loads(json.dumps(conf))
    bad["data"]["format"] = "hdf5"
    with pytest.raises(InvalidPipelineConfig):
        validate_pipeline_config(bad)

    bad = json.loads(json.dumps(conf))
    del bad["clustering"]
    with pytest.raises(InvalidPipelineConfig):
        validate_pipeline_config(bad)

    bad = json.loads(json.dumps(conf))
    bad["ranges"][0]["ffa_search"]["wtsp"] = 0.5
    with pytest.raises(InvalidPipelineConfig):
        validate_pipeline_config(bad)

    bad = json.loads(json.dumps(conf))
    bad["unknown_section"] = {}
    with pytest.raises(InvalidPipelineConfig):
        validate_pipeline_config(bad)


def test_range_validation_against_data():
    conf = validate_pipeline_config(load_config(CONFIG_A))
    # bins_min * tsamp_max > period_min -> invalid
    with pytest.raises(InvalidSearchRange):
        validate_ranges(conf["ranges"], tsamp_max=0.5 / 480 * 1.01)
    # candidate bins unfoldable at this resolution
    conf["ranges"][0]["candidates"]["bins"] = 4096
    with pytest.raises(InvalidSearchRange):
        validate_ranges(conf["ranges"], tsamp_max=256e-6 * 8)


def test_ranges_contiguity():
    conf = validate_pipeline_config(load_config(CONFIG_A))
    rg2 = json.loads(json.dumps(conf["ranges"][0]))
    rg2["ffa_search"]["period_min"] = 3.0  # gap: 2.0 != 3.0
    rg2["ffa_search"]["period_max"] = 4.0
    with pytest.raises(InvalidSearchRange):
        validate_ranges(conf["ranges"] + [rg2], tsamp_max=256e-6)


# ----------------------------------------------------------------------------
# DM selection
# ----------------------------------------------------------------------------

BAND = dict(fmin=1182.0, fmax=1582.0, nchans=1024)


def test_select_dms_covers_range():
    trials = np.arange(0.0, 100.5, 0.05)
    sel = select_dms(trials, 0.0, 100.0, wmin=1.0e-3, **BAND)
    assert sel[0] == 0.0
    assert sel[-1] >= 99.0
    assert np.all(np.diff(sel) > 0)
    # far fewer trials than available, but never a coverage gap:
    # consecutive selected trials' radii must touch
    kdisp = (1.0 / 2.41e-4) * (BAND["fmin"] ** -2 - BAND["fmax"] ** -2)
    cw = (BAND["fmax"] - BAND["fmin"]) / BAND["nchans"]
    fmid = (BAND["fmax"] + BAND["fmin"]) / 2
    ksmear = (1.0 / 2.41e-4) * ((fmid - cw / 2) ** -2 - (fmid + cw / 2) ** -2)
    radii = np.maximum(1.0e-3, ksmear * sel) / kdisp
    gaps = (sel[1:] - radii[1:]) - (sel[:-1] + radii[:-1])
    assert np.all(gaps <= 1e-9)
    assert len(sel) < len(trials) / 2


def test_select_dms_empty_range():
    with pytest.raises(ValueError):
        select_dms([1.0, 2.0], 5.0, 10.0, wmin=1e-3, **BAND)


# ----------------------------------------------------------------------------
# Harmonic testing
# ----------------------------------------------------------------------------

def _cand(freq, snr, ducy=0.05, dm=10.0):
    return types.SimpleNamespace(freq=freq, snr=snr, ducy=ducy, dm=dm)


def test_htest_flags_true_harmonic():
    F = _cand(1.0, 20.0)
    H = _cand(2.0, 20.0 / np.sqrt(2.0))
    related, fraction = htest(F, H, tobs=128.0, fmin=1182.0, fmax=1582.0)
    assert related
    assert (fraction.numerator, fraction.denominator) == (2, 1)


def test_htest_rejects_unrelated():
    F = _cand(1.0, 20.0)
    # A bright signal at an irrational-ish frequency ratio: the closest
    # p/q has a large p*q, so the expected harmonic S/N is tiny and the
    # S/N distance test fails (and the phase drift is over one width).
    H = _cand(1.3719, 15.0)
    related, _ = htest(F, H, tobs=128.0, fmin=1182.0, fmax=1582.0)
    assert not related


def test_htest_rejects_wrong_dm():
    F = _cand(1.0, 20.0, dm=10.0)
    H = _cand(2.0, 20.0 / np.sqrt(2.0), dm=300.0)
    related, _ = htest(F, H, tobs=128.0, fmin=1182.0, fmax=1582.0)
    assert not related


def test_hdiag_values():
    F = _cand(1.0, 20.0)
    H = _cand(2.0, 20.0 / np.sqrt(2.0))
    d = hdiag(F, H, tobs=128.0, fmin=1182.0, fmax=1582.0)
    assert d["fraction"] == 2
    assert d["phase_absdiff_turns"] == pytest.approx(0.0, abs=1e-9)
    assert d["dm_absdiff"] == 0.0
    assert d["snr_distance"] == pytest.approx(0.0, abs=1e-9)


# ----------------------------------------------------------------------------
# PeakCluster
# ----------------------------------------------------------------------------

def _peak(freq, snr, dm=0.0):
    from riptide_tpu.peak_detection import Peak

    return Peak(
        period=1.0 / freq, freq=freq, width=13, ducy=0.025,
        iw=0, ip=0, snr=snr, dm=dm,
    )


def test_peak_cluster_and_dataframe():
    a = PeakCluster([_peak(1.0, 10.0), _peak(1.0001, 15.0)])
    b = PeakCluster([_peak(2.0, 8.0)])
    a.rank, b.rank = 0, 1
    assert a.centre.snr == 15.0
    assert not a.is_harmonic

    from fractions import Fraction

    b.parent_fundamental = a
    b.hfrac = Fraction(2, 1)
    assert b.is_harmonic

    df = clusters_to_dataframe([a, b])
    assert list(df.columns) == [
        "rank", "period", "dm", "snr", "ducy", "freq", "npeaks",
        "hfrac_num", "hfrac_denom", "fundamental_rank",
    ]
    # sorted by decreasing S/N: cluster a first
    assert df.iloc[0]["snr"] == 15.0
    assert df.iloc[1]["hfrac_num"] == 2
    assert df.iloc[1]["fundamental_rank"] == 0
    assert df.iloc[0]["fundamental_rank"] == 0  # fundamental points at itself


def test_batch_searcher_single_io_thread(tmp_path):
    """Regression: process_stream must not deadlock at io_threads=1
    (the per-chunk staging task once shared the pool with the file
    loads it waits on)."""
    from riptide_tpu.pipeline.batcher import BatchSearcher

    f1 = generate_data_presto(str(tmp_path), "a_DM0.00", tobs=16.0,
                              tsamp=1e-3, period=0.5, dm=0.0)
    f2 = generate_data_presto(str(tmp_path), "b_DM5.00", tobs=16.0,
                              tsamp=1e-3, period=0.5, dm=5.0)
    conf = [{
        "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                       "bins_min": 64, "bins_max": 71},
        "find_peaks": {"smin": 6.0},
    }]
    bs = BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101}, conf,
                       fmt="presto", io_threads=1)
    # Bounded wait: the failure mode guarded against is an infinite
    # block, which must fail the test rather than wedge the run.
    from concurrent.futures import ThreadPoolExecutor as _TPE

    with _TPE(max_workers=1) as runner:
        fut = runner.submit(bs.process_stream, [[f1], [f2]])
        peaks = fut.result(timeout=300)
    assert peaks, "no peaks from the single-io-thread stream"
    best = max(peaks, key=lambda p: p.snr)
    assert abs(best.period - 0.5) < 1e-3
