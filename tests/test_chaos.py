"""
Storage crash-safety + chaos campaign tests (PR 11).

Covers the fsio layer (checksummed line appends, torn-tail healing,
atomic write-rename, storage fault hooks), the journal's crash
recovery (torn/corrupt tail truncation, orphaned-peak reconciliation,
checksum-less legacy journals), the observability-writes-are-never-
fatal invariant (heartbeat/ledger/prom/trace degradations complete the
survey with incidents), the heartbeat beater's bounded retry, exec-
cache corruption recovery (detect -> incident -> evict -> rebuild),
the report readers' lenient-line tolerance, and — end to end — one
subprocess chaos schedule from :mod:`riptide_tpu.survey.chaos`
(kill mid-journal-append, resume, byte-identical peaks.csv). The full
builtin campaign plus a seeded sweep runs under ``-m slow`` (and as
``make chaos``).
"""
import errno
import json
import os
import sys

import numpy as np
import pytest

from riptide_tpu.obs import ledger, prom
from riptide_tpu.obs import report as rep
from riptide_tpu.survey import chaos, incidents
from riptide_tpu.survey.faults import FaultPlan
from riptide_tpu.survey.journal import SurveyJournal
from riptide_tpu.survey.metrics import get_metrics
from riptide_tpu.survey.scheduler import RetryPolicy, SurveyScheduler
from riptide_tpu.peak_detection import Peak
from riptide_tpu.utils import fsio

from synth import generate_data_presto

TOBS = 12.0
TSAMP = 1e-3
PERIOD = 0.5

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


@pytest.fixture(autouse=True)
def _clean_process_globals():
    """The incident sink, status provider, retained incident and fsio
    fault hook are process-wide; clear them on BOTH sides of every test
    (earlier suite files run real schedulers which leave providers
    registered by design)."""
    def _clear():
        incidents.set_sink(None)
        prom.set_status_provider(None)
        incidents.clear_last()
        fsio.set_storage_faults(None)

    _clear()
    yield
    _clear()


def _peak(period=0.5, snr=10.0, dm=0.0):
    return Peak(period=period, freq=1.0 / period, width=3, ducy=0.05,
                iw=1, ip=7, snr=snr, dm=dm)


def _capture_incidents():
    caught = []
    incidents.set_sink(caught.append)
    return caught


def _searcher():
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=1)


def _two_trials(tmp_path):
    return [
        generate_data_presto(str(tmp_path), f"c_DM{dm:.2f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm,
                             amplitude=30.0)
        for dm in (0.0, 5.0)
    ]


# ------------------------------------------------------------------- fsio

def test_checksum_roundtrip_and_statuses():
    payload = b'{"kind":"chunk","chunk_id":3}'
    line = fsio.encode_record_line(payload)
    assert line.endswith(b"\n") and b" #" in line
    got, status = fsio.split_checksum(line.rstrip(b"\n"))
    assert status == "ok" and got == payload
    # Legacy line: no suffix.
    got, status = fsio.split_checksum(payload)
    assert status == "legacy" and got == payload
    # Corrupt: payload changed after the suffix was computed.
    bad = bytearray(line.rstrip(b"\n"))
    bad[5] ^= 0x01
    _, status = fsio.split_checksum(bytes(bad))
    assert status == "corrupt"


def test_scan_jsonl_classifies_lines(tmp_path):
    path = str(tmp_path / "f.jsonl")
    fsio.append_jsonl(path, [{"a": 1}], checksum=True)
    fsio.append_jsonl(path, [{"b": 2}], checksum=False)  # legacy
    with open(path, "ab") as f:
        f.write(b"not json at all\n")
        f.write(b'{"kind":"chunk","torn')  # no newline
    entries, size = fsio.scan_jsonl(path)
    assert [s for _, s, _ in entries] == ["ok", "legacy", "garbage",
                                         "torn"]
    assert entries[0][0] == {"a": 1} and entries[1][0] == {"b": 2}
    assert entries[-1][2] == size


def test_append_heals_torn_tail_with_incident(tmp_path):
    path = str(tmp_path / "led.jsonl")
    with open(path, "wb") as f:
        f.write(b'{"kind":"survey","v":1}\n{"kind":"su')
    caught = _capture_incidents()
    fsio.append_jsonl(path, [{"kind": "survey", "n": 2}],
                      site="ledger_append", checksum=False)
    rows = rep.read_ledger(path)
    assert [r.get("n") for r in rows] == [None, 2]
    assert [c["incident"] for c in caught] == ["storage_recovered"]
    assert caught[0]["detail"]["action"] == "healed_torn_tail"


def test_atomic_write_places_whole_file(tmp_path):
    path = str(tmp_path / "page.prom")
    fsio.atomic_write_text(path, "riptide_x_total 1\n",
                           site="prom_textfile")
    assert open(path).read() == "riptide_x_total 1\n"
    # No stray tmp files after a clean write.
    assert os.listdir(tmp_path) == ["page.prom"]


# ------------------------------------------------------- storage faults

def test_fault_plan_parses_storage_kinds():
    plan = FaultPlan.parse(
        "kill_at:journal_append:3,enospc:trace_export,"
        "fsync_fail:heartbeat_appendx2,torn_write:ledger_append,"
        "cache_corrupt:exec_cache_store:1,raise:2x2")
    sites = [d.get("site") for d in plan._directives]
    assert sites[:5] == ["journal_append", "trace_export",
                         "heartbeat_append", "ledger_append",
                         "exec_cache_store"]
    assert plan._directives[0]["nth"] == 3
    # xN on a site whose NAME contains an 'x' must not parse as repeat.
    assert plan._directives[1]["remaining"] == 1
    assert plan._directives[2]["remaining"] == 2
    assert plan._directives[5] == {"kind": "raise", "chunk": 2,
                                   "arg": None, "remaining": 2}
    with pytest.raises(ValueError):
        FaultPlan.parse("enospc:not_a_site")
    with pytest.raises(ValueError):
        FaultPlan.parse("kill_at:journal_append:0")


def test_enospc_fires_on_nth_write(tmp_path):
    plan = FaultPlan.parse("enospc:journal_append:2")
    fsio.set_storage_faults(plan.storage_op)
    path = str(tmp_path / "j.jsonl")
    fsio.append_jsonl(path, [{"n": 1}], site="journal_append")
    with pytest.raises(OSError) as err:
        fsio.append_jsonl(path, [{"n": 2}], site="journal_append")
    assert err.value.errno == errno.ENOSPC
    # Consumed: the third append goes through.
    fsio.append_jsonl(path, [{"n": 3}], site="journal_append")
    assert [r["n"] for r in rep._read_jsonl(path)] == [1, 3]


def test_fsync_fail_lands_bytes_but_raises(tmp_path):
    plan = FaultPlan.parse("fsync_fail:heartbeat_append")
    fsio.set_storage_faults(plan.storage_op)
    path = str(tmp_path / "hb.jsonl")
    with pytest.raises(OSError):
        fsio.append_jsonl(path, [{"ts": 1.0}], site="heartbeat_append")


def test_kill_at_tears_the_record(tmp_path):
    class Died(Exception):
        pass

    def fake_exit(code):
        raise Died(code)

    plan = FaultPlan.parse("kill_at:journal_append:2", exit=fake_exit)
    fsio.set_storage_faults(plan.storage_op)
    path = str(tmp_path / "j.jsonl")
    fsio.append_jsonl(path, [{"kind": "header"}], site="journal_append",
                      checksum=True)
    with pytest.raises(Died) as err:
        fsio.append_jsonl(path, [{"kind": "chunk", "chunk_id": 0}],
                          site="journal_append", checksum=True)
    assert err.value.args == (fsio.KILL_EXIT,)
    entries, _ = fsio.scan_jsonl(path)
    assert [s for _, s, _ in entries] == ["ok", "torn"]


def test_torn_write_raises_eio_without_killing(tmp_path):
    plan = FaultPlan.parse("torn_write:ledger_append")
    fsio.set_storage_faults(plan.storage_op)
    path = str(tmp_path / "led.jsonl")
    with pytest.raises(OSError) as err:
        fsio.append_jsonl(path, [{"kind": "survey", "v": 1}],
                          site="ledger_append", checksum=False)
    assert err.value.errno == errno.EIO
    entries, _ = fsio.scan_jsonl(path)
    assert [s for _, s, _ in entries] == ["torn"]  # the partial prefix


# --------------------------------------------------- journal recovery

def test_journal_lines_are_checksummed_heartbeats_plain(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("abc", 1)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak()])
    j.heartbeat(0, ts=5.0)
    for line in open(j.journal_path, "rb").read().splitlines():
        assert fsio.split_checksum(line)[1] == "ok"
    for line in open(j.peaks_path, "rb").read().splitlines():
        assert fsio.split_checksum(line)[1] == "ok"
    # Heartbeat sidecars stay raw-parseable plain JSON.
    hb = open(os.path.join(j.directory, "heartbeat_0000.jsonl"),
              "rb").read().splitlines()
    assert json.loads(hb[0])["ts"] == 5.0


def test_recover_truncates_torn_tail_and_orphans(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("t", 2)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak()])
    # Chunk 1 died between its peak append and its chunk record: one
    # orphaned peak row, plus a torn chunk-record fragment.
    fsio.append_jsonl(j.peaks_path, [[1.0, 1.0, 3, 0.05, 1, 7, 8.0, 5.0]],
                      checksum=True)
    with open(j.journal_path, "ab") as f:
        f.write(b'{"kind":"chunk","chunk_id":1,"pe')
    caught = _capture_incidents()
    j2 = SurveyJournal(tmp_path / "j")
    j2.write_header("t", 2)
    kinds = [c["incident"] for c in caught]
    assert kinds == ["storage_recovered", "storage_recovered"]
    actions = {c["detail"]["action"] for c in caught}
    assert actions == {"truncated_torn_tail", "truncated_orphan_peaks"}
    # Chunk 0 intact; chunk 1 re-dispatched; the peak store holds
    # exactly the claimed rows again.
    assert sorted(j2.completed_chunks()) == [0]
    entries, _ = fsio.scan_jsonl(j2.peaks_path)
    assert len(entries) == 1 and entries[0][1] == "ok"
    # Recovery appended nothing and is idempotent: a third open is a
    # byte-for-byte no-op.
    b0 = open(j2.journal_path, "rb").read()
    j3 = SurveyJournal(tmp_path / "j")
    j3.recover()
    assert open(j3.journal_path, "rb").read() == b0


def test_recover_drops_corrupt_midfile_record_without_truncating(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("c", 2)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak()])
    j.record_metrics({"chunks_done": 1})
    lines = open(j.journal_path, "rb").read().splitlines(keepends=True)
    chunk_line = bytearray(lines[1])
    chunk_line[12] ^= 0x01  # flip a payload byte; suffix now mismatches
    with open(j.journal_path, "wb") as f:
        f.write(lines[0] + bytes(chunk_line) + lines[2])
    caught = _capture_incidents()
    j2 = SurveyJournal(tmp_path / "j")
    j2.write_header("c", 2)
    assert any(c["incident"] == "record_corrupt" for c in caught)
    # The corrupt chunk record is dropped (re-dispatch), its orphaned
    # peak rows truncated, and the VALID metrics record after it kept.
    assert j2.completed_chunks() == {}
    assert j2.last_metrics() == {"chunks_done": 1}


def test_legacy_checksumless_journal_resumes_unchanged(tmp_path):
    jdir = tmp_path / "old"
    os.makedirs(jdir)
    peaks = [_peak(), _peak(period=1.0, snr=8.0, dm=10.0)]
    rows = [[float(getattr(p, f)) if f not in ("width", "iw", "ip")
             else int(getattr(p, f))
             for f in ("period", "freq", "width", "ducy", "iw", "ip",
                       "snr", "dm")] for p in peaks]
    with open(jdir / "journal.jsonl", "w") as f:
        f.write(json.dumps({"kind": "header", "version": 1,
                            "survey_id": "old", "chunks_total": 1}) + "\n")
        f.write(json.dumps({"kind": "chunk", "chunk_id": 0,
                            "files": ["a.inf"], "dms": [0.0],
                            "wire_digest": None, "peaks_offset": 0,
                            "peaks_count": 2}) + "\n")
    with open(jdir / "peaks.jsonl", "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    before = open(jdir / "journal.jsonl", "rb").read()
    j = SurveyJournal(jdir)
    j.write_header("old", 1)  # recovery + idempotent header
    done = j.completed_chunks()
    assert done[0][1] == peaks
    # A healthy legacy journal is not rewritten or upgraded in place.
    assert open(jdir / "journal.jsonl", "rb").read() == before
    # And the report/rtop surface renders it like any other journal.
    doc = rep.read_journal(str(jdir))
    assert doc["header"]["survey_id"] == "old"
    assert sorted(doc["chunks"]) == [0]
    # New writers may append to it; mixed files parse fine both ways.
    j.record_metrics({"chunks_done": 1})
    assert j.last_metrics() == {"chunks_done": 1}
    assert rep.read_journal(str(jdir))["metrics"] == {"chunks_done": 1}


# ------------------------------------- obs writes are never fatal (e2e)

def test_survey_completes_through_obs_write_faults(tmp_path, monkeypatch):
    """ENOSPC/EIO on heartbeat, prom-textfile AND ledger writes: the
    survey completes, each degradation is incident-recorded, and the
    peak results equal a clean run's."""
    files = _two_trials(tmp_path)
    get_metrics().reset()
    clean = SurveyScheduler(_searcher(), [[f] for f in files]).run()

    promfile = str(tmp_path / "metrics.prom")
    ledgerfile = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("RIPTIDE_PROM_TEXTFILE", promfile)
    monkeypatch.setenv("RIPTIDE_LEDGER", ledgerfile)
    get_metrics().reset()
    journal = SurveyJournal(tmp_path / "j")
    faults = FaultPlan.parse("fsync_fail:heartbeat_append,"
                             "enospc:prom_textfile,"
                             "torn_write:ledger_append")
    sched = SurveyScheduler(_searcher(), [[f] for f in files],
                            journal=journal, faults=faults,
                            retry=RetryPolicy(max_retries=1,
                                              sleep=lambda s: None))
    peaks = sched.run()
    assert peaks == clean
    assert sorted(journal.completed_chunks()) == [0, 1]
    ops = sorted(inc["detail"]["op"] for inc in journal.incidents()
                 if inc["incident"] == "obs_write_failed")
    assert ops == ["heartbeat", "ledger", "prom_textfile"]
    assert get_metrics().counter("obs_write_errors") == 3
    assert not os.path.exists(promfile)
    # The torn ledger write left only a dropped partial line.
    assert rep.read_ledger(ledgerfile) == []
    # The run's fault hook was uninstalled on exit.
    assert fsio.set_storage_faults(None) is None


def test_full_replay_resume_appends_missing_ledger_row(tmp_path,
                                                       monkeypatch):
    """A run killed between its final journal write and its ledger
    append still owes the row: the full-replay resume derives it from
    the journaled timings — but only when no valid row exists yet."""
    files = _two_trials(tmp_path)
    ledgerfile = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("RIPTIDE_LEDGER", ledgerfile)
    jdir = tmp_path / "j"
    get_metrics().reset()
    SurveyScheduler(_searcher(), [[f] for f in files],
                    journal=SurveyJournal(jdir)).run()
    rows = rep.read_ledger(ledgerfile)
    assert len(rows) == 1
    # Simulate the kill landing mid-ledger-append: a torn row.
    with open(ledgerfile, "wb") as f:
        f.write(b'{"kind":"survey","surv')
    get_metrics().reset()
    SurveyScheduler(_searcher(), [[f] for f in files],
                    journal=SurveyJournal(jdir), resume=True).run()
    rows = rep.read_ledger(ledgerfile)
    assert len(rows) == 1 and rows[0]["kind"] == "survey"
    assert rows[0]["nchunks"] == 2 and rows[0]["chunks_replayed"] == 2
    # A second full-replay resume sees the valid row and appends none.
    get_metrics().reset()
    SurveyScheduler(_searcher(), [[f] for f in files],
                    journal=SurveyJournal(jdir), resume=True).run()
    assert len(rep.read_ledger(ledgerfile)) == 1


# ------------------------------------------------------- beater retry

class _FlakyJournal:
    """Stub journal whose heartbeat fails ``fail`` times then lands."""

    def __init__(self, fail):
        self.fail = fail
        self.beats = 0

    def heartbeat(self, process_index, ts=None):
        if self.fail > 0:
            self.fail -= 1
            raise OSError(errno.EIO, "wedged sidecar")
        self.beats += 1


def test_beater_retries_transient_oserror_then_lands():
    from riptide_tpu.survey.liveness import PeerLivenessMonitor

    j = _FlakyJournal(fail=2)
    mon = PeerLivenessMonitor(j, 0, 1, metrics=get_metrics())
    caught = _capture_incidents()
    assert mon.beat_retrying(attempts=3, base_backoff_s=0.001) is True
    assert j.beats == 1
    assert caught == []  # recovered: no incident


def test_beater_gives_up_with_incident_and_stays_alive():
    """The wedged-peer contract: a sidecar that keeps failing makes the
    peer LOOK stale (incident + counter), it does not kill the beater."""
    from riptide_tpu.survey.liveness import PeerLivenessMonitor

    j = _FlakyJournal(fail=99)
    get_metrics().reset()
    mon = PeerLivenessMonitor(j, 3, 4, metrics=get_metrics())
    caught = _capture_incidents()
    assert mon.beat_retrying(attempts=3, base_backoff_s=0.001) is False
    assert [c["incident"] for c in caught] == ["obs_write_failed"]
    assert caught[0]["detail"]["op"] == "heartbeat"
    assert caught[0]["detail"]["process"] == 3
    assert get_metrics().counter("obs_write_errors") == 1
    # The sidecar recovers -> the next interval's beat lands again.
    j.fail = 0
    assert mon.beat_retrying(attempts=3, base_backoff_s=0.001) is True


# ------------------------------------------------- exec cache recovery

def test_exec_cache_corruption_detect_evict_rebuild(tmp_path):
    import jax
    import jax.numpy as jnp

    from riptide_tpu.utils import exec_cache

    path = str(tmp_path / "entry.pkl")
    jitted = jax.jit(lambda x: x * 3.0)
    args = (jnp.arange(4.0),)
    want = np.arange(4.0) * 3.0

    info = {}
    exec_cache.load_or_compile_exec(path, jitted, args, name="prog",
                                    info=info)
    assert info["action"] == "compiled"
    assert open(path, "rb").read().startswith(b"RTEXEC1\n")
    info = {}
    fn = exec_cache.load_or_compile_exec(path, jitted, args, name="prog",
                                         info=info)
    assert info["action"] == "loaded"
    np.testing.assert_allclose(np.asarray(fn(*args)), want)

    # Flip a byte in the stored body: detect, incident (naming the
    # evicted path), evict, rebuild — identical results throughout.
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        byte = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([byte[0] ^ 0xFF]))
    caught = _capture_incidents()
    get_metrics().reset()
    info = {}
    fn = exec_cache.load_or_compile_exec(path, jitted, args, name="prog",
                                         info=info)
    assert info["action"] == "compiled"
    np.testing.assert_allclose(np.asarray(fn(*args)), want)
    bad = [c for c in caught if c["incident"] == "cache_corrupt"]
    assert len(bad) == 1
    assert bad[0]["detail"]["path"] == path
    assert "CRC mismatch" in bad[0]["detail"]["reason"]
    assert get_metrics().counter("cache_evictions") == 1
    # The rebuilt entry loads cleanly.
    info = {}
    exec_cache.load_or_compile_exec(path, jitted, args, name="prog",
                                    info=info)
    assert info["action"] == "loaded"


def test_exec_cache_legacy_unframed_entry_still_loads(tmp_path):
    import pickle

    import jax
    import jax.numpy as jnp
    from jax.experimental import serialize_executable as se

    from riptide_tpu.utils import exec_cache

    path = str(tmp_path / "entry.pkl")
    jitted = jax.jit(lambda x: x - 1.0)
    args = (jnp.arange(4.0),)
    compiled = jitted.lower(*args).compile()
    with open(path, "wb") as f:
        pickle.dump(se.serialize(compiled), f)
    info = {}
    fn = exec_cache.load_or_compile_exec(path, jitted, args, info=info)
    assert info["action"] == "loaded"
    np.testing.assert_allclose(np.asarray(fn(*args)),
                               np.arange(4.0) - 1.0)


# -------------------------------------------- report reader tolerance

def test_read_ledger_tolerates_suffixed_and_garbage_lines(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    fsio.append_jsonl(path, [{"kind": "survey", "n": 1}], checksum=True)
    fsio.append_jsonl(path, [{"kind": "survey", "n": 2}], checksum=False)
    corrupt = bytearray(fsio.encode_record_line(
        json.dumps({"kind": "survey", "n": 3}).encode()))
    corrupt[3] ^= 0x01
    with open(path, "ab") as f:
        f.write(bytes(corrupt))
        f.write(b"<<<garbage>>>\n")
        f.write(b'{"kind":"survey","torn')
    rows = rep.read_ledger(path)
    assert [r["n"] for r in rows] == [1, 2]


def test_journal_follower_reads_checksummed_records(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("f", 2)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak()],
                   timings={"chunk_s": 1.0})
    follower = rep.JournalFollower(str(tmp_path / "j"))
    doc = follower.poll()
    assert doc["header"]["survey_id"] == "f"
    assert sorted(doc["chunks"]) == [0]
    # A torn tail does not advance the offset; the completed record
    # appended after it (healed onto its own line) is picked up.
    with open(j.journal_path, "ab") as f:
        f.write(b'{"kind":"chunk","chunk_id":1,"to')
    assert sorted(follower.poll()["chunks"]) == [0]
    j.record_metrics({"chunks_done": 1})
    assert follower.poll()["metrics"] == {"chunks_done": 1}


def test_parse_prom_text_tolerates_suffix_and_garbage():
    page = "# HELP riptide_x_total x\n" \
           "riptide_x_total 3\n" \
           "riptide_y_total 4 #%08x\n" \
           "<<torn garbage line with no value\n" % (
               __import__("zlib").crc32(b"riptide_y_total 4") & 0xFFFFFFFF)
    values = rep.parse_prom_text(page)
    assert values["riptide_x_total"][""] == 3.0
    assert values["riptide_y_total"][""] == 4.0


def test_build_report_survives_torn_trace_json(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("t", 1)
    j.record_chunk(0, ["a.inf"], [0.0], [_peak()],
                   timings={"chunk_s": 1.0})
    with open(os.path.join(j.directory, "trace.json"), "w") as f:
        f.write('{"traceEvents": [{"ph": "X", "na')  # torn mid-write
    report = rep.build_report(j.directory)
    assert "trace" not in report
    assert "trace.json" in report["trace_error"]
    assert report["chunks_done"] == 1


# ------------------------------------------------ chaos campaign (e2e)

def _campaign_files(tmp_path):
    datadir = tmp_path / "data"
    datadir.mkdir()
    return [
        generate_data_presto(str(datadir), f"chaos_DM{dm:.2f}",
                             tobs=chaos.TOBS, tsamp=chaos.TSAMP,
                             period=chaos.PERIOD, dm=dm,
                             amplitude=chaos.AMPLITUDE)
        for dm in chaos.DMS
    ]


def test_chaos_schedule_kill_journal_append_resumes_byte_identical(
        tmp_path):
    """The acceptance path in miniature: control run, then a schedule
    whose first leg is KILLED mid-journal-append (subprocess, exit
    fsio.KILL_EXIT) and whose resume leg must end byte-identical with
    the torn tail truncated, incidents recorded, a ledger row present
    and no duplicate chunk records."""
    files = _campaign_files(tmp_path)
    schedules = [s for s in chaos.builtin_schedules()
                 if s["name"] in ("control", "kill-journal-append")]
    summary = chaos.run_campaign(files, str(tmp_path / "w"),
                                 schedules=schedules)
    assert summary["schedules"] == 2 and summary["legs"] == 3
    # The faulted schedule's journal holds the recovery incident.
    recs = [r for r in rep.read_journal(
        str(tmp_path / "w" / "kill-journal-append" / "j"))["incidents"]
        if r["incident"] == "storage_recovered"]
    assert recs


def test_seeded_schedules_are_deterministic():
    a = chaos.seeded_schedules(7, 5)
    b = chaos.seeded_schedules(7, 5)
    assert a == b
    c = chaos.seeded_schedules(8, 5)
    assert a != c
    for s in a:
        assert s["legs"][0]["expect"] == "kill"
        assert s["legs"][1].get("resume") is True


@pytest.mark.slow
def test_chaos_full_campaign_with_sweep(tmp_path):
    """`make chaos` plus a seeded sweep: every builtin schedule and
    three generated ones end byte-identical to the control run."""
    files = _campaign_files(tmp_path)
    schedules = chaos.builtin_schedules() + chaos.seeded_schedules(99, 3)
    summary = chaos.run_campaign(files, str(tmp_path / "w"),
                                 schedules=schedules)
    assert summary["schedules"] == len(schedules)


# --------------------------------------------------- rreport/rtop compat

def test_rreport_and_rtop_render_checksummed_journal(tmp_path):
    """The standalone tools parse a PR-11 (checksummed) journal the
    same way they parse a legacy one."""
    tools = os.path.normpath(os.path.join(os.path.dirname(__file__),
                                          "..", "tools"))
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import rreport
    import rtop

    j = SurveyJournal(tmp_path / "j")
    j.write_header("t", 1)
    j.record_chunk(
        0, ["a.inf"], [0.0], [_peak()],
        timings={"chunk_s": 1.0, "wire_s": 0.2, "queue_s": 0.1,
                 "collect_s": 0.6, "host_s": 0.1, "device_s": 0.5,
                 "prep_s": 0.3, "wire_MBps": 50.0, "bound": "device"})
    assert rreport.main([str(tmp_path / "j"), "--quiet"]) == 0
    frame = rtop.render_frame(rreport.load_report_module(),
                              str(tmp_path / "j"))
    assert "chunks 1/1" in frame
