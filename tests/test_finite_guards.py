"""
Tier-1 enforcement of the finite-guard discipline: every public entry
point in ops/snr.py and time_series.py must route through the
data-quality layer (tools/check_finite_guards.py), so a future kernel
or reader cannot silently drop the NaN defence.
"""
import importlib.util
import os

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
TOOL = os.path.join(REPO, "tools", "check_finite_guards.py")


def _load_tool():
    spec = importlib.util.spec_from_file_location("check_finite_guards", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_all_entry_points_guarded():
    tool = _load_tool()
    violations = tool.check()
    assert violations == [], "\n".join(violations)


def test_lint_catches_unguarded_entry_point(tmp_path):
    """The checker must actually flag a module whose entry point skips
    the quality layer (guard against a vacuous lint)."""
    tool = _load_tool()
    bad = tmp_path / "bad_snr.py"
    bad.write_text(
        "from .. import quality\n"
        "def helper(x):\n"
        "    return quality.check_finite_array(x)\n"
        "def guarded(x):\n"
        "    return helper(x)\n"
        "def unguarded(x):\n"
        "    return x.sum()\n"
    )
    violations = tool.check_module(str(bad), ["guarded", "unguarded"])
    assert len(violations) == 1
    assert "unguarded" in violations[0]
    assert tool.check_module(str(bad), ["guarded"]) == []


def test_lint_flags_missing_entry_point(tmp_path):
    tool = _load_tool()
    mod = tmp_path / "empty.py"
    mod.write_text("x = 1\n")
    violations = tool.check_module(str(mod), ["boxcar_snr"])
    assert violations and "not found" in violations[0]
