"""
Row-packed kernel containers (RIPTIDE_KERNEL_ROW_PACK): the odd-slot
container forms 5/7 * 2^(L-3) and the embedding of a SECOND same-p
bins-trial in a container's dead rows via per-row table indirection
(slottables.build_tables(base=...) / combine_tables, the paired
CycleKernel, engine._row_pack_map and the guest de-interleave in
_assemble_device).

Correctness chain:

* table level — simulate_dense on odd-slot containers and
  simulate_dense_pair on embedded pairs equal the reference oracle
  EXACTLY, per trial, across edge geometries (m near rows, non-minimal
  guest bases, m = 1 guests, every container form);
* kernel level — the paired interpret-mode CycleKernel matches the
  oracle for both trials AND its host rows are BITWISE identical to
  the unpaired kernel's (the guest rides only in dead rows);
* engine level — a DM-batched CPU survey e2e produces byte-identical
  peaks.csv with the flag on vs off, row-packed stages queue ONE fused
  program per non-absorbed lane bucket and ZERO pack programs, the
  fused and two-dispatch forms stay bitwise interchangeable, and the
  flag-off escape hatch restores the legacy container family exactly.

The e2e plans force RIPTIDE_KERNEL_BASE3=0 (pure 2^L buckets): a
MINIMAL container's largest trial fills every slot, so cross-stage
pairing engages where the family is coarse — which the pure-2^L family
is at these tiny depths (see docs/perf_notes.md round 7).
"""
import io

import numpy as np
import pytest

import riptide_tpu.search.engine as eng
from riptide_tpu.ops.ffa_kernel import CycleKernel, bucket_rows
from riptide_tpu.ops.plan import num_levels, pair_bucket_bases
from riptide_tpu.ops.reference import boxcar_snr_2d, ffa_transform
from riptide_tpu.ops.slottables import (container_forms, container_rows,
                                        guest_base, simulate_dense,
                                        simulate_dense_pair)
from riptide_tpu.ops.snr import boxcar_coeffs
from riptide_tpu.search.plan import periodogram_plan, plan_occupancy
from riptide_tpu.survey.metrics import MetricsRegistry, set_metrics

# E2E config: tiny series, 5 cascade stages, one cross-stage pair
# (stage 0 hosts stage 2) under pure-2^L buckets — probed so the
# interpret-mode cost stays tens of seconds.
SIZE, TSAMP, WIDTHS = 1200, 1e-3, (1, 2, 3)
PMIN, PMAX, BMIN, BMAX = 32e-3, 0.11, 32, 40
PKW = dict(smin=6.0, segwidth=0.2, nstd=6.0, minseg=10, polydeg=2,
           clrad=0.1)


# --------------------------------------------------------- table level

def test_container_forms_extended():
    assert container_forms(10) == [768, 1024]
    assert container_forms(10, extended=True) == [640, 768, 896, 1024]
    # odd-slot forms need L >= 6 for the 8-row sublane tile
    assert container_forms(5, extended=True) == [24, 32]
    assert container_rows(600, 10, extended=True) == 640
    assert container_rows(641, 10, extended=True) == 768
    assert container_rows(600, 10) == 768


@pytest.mark.parametrize("m,p", [(100, 130), (300, 37), (71, 64),
                                 (623, 17), (160, 9)])
def test_simulate_dense_odd_slot_containers(m, p):
    """5/7 * 2^(L-3) containers stay oracle-exact: the spread halves
    group sizes only ABOVE the final slot, so an odd slot is legal."""
    rng = np.random.default_rng(m)
    data = rng.standard_normal((m, p)).astype(np.float32)
    L = num_levels(m)
    for R in container_forms(L, extended=True):
        if R >= m:
            np.testing.assert_array_equal(simulate_dense(data, L=L, R=R),
                                          ffa_transform(data))


PAIR_GEOMS = [
    # (m_host, m_guest, p): m near rows, tiny guests, lone-row guests
    (700, 200, 130), (1006, 237, 241), (555, 100, 37), (120, 30, 17),
    (96, 30, 16), (250, 60, 251), (1000, 9, 33), (60, 3, 7),
    (33, 1, 5), (700, 1, 130),
]


@pytest.mark.parametrize("mh,mg,p", PAIR_GEOMS)
def test_simulate_dense_pair_matches_oracle(mh, mg, p):
    """Both trials of an embedded pair equal their own reference
    transforms EXACTLY, on every feasible container form, at the
    minimal guest base and at a feasible non-minimal one."""
    from riptide_tpu.ops.slotffa import node_sizes

    rng = np.random.default_rng(mh * 7 + mg)
    checked = 0
    # L and L+1: a bucket's depth comes from its LARGEST trial, so a
    # host often sits one level deeper than its own minimum.
    for L in (num_levels(mh), num_levels(mh) + 1):
        NL = min(L, 3)
        for R in container_forms(L, extended=True):
            if R < mh:
                continue
            gb = guest_base(mh, mg, L, R)
            if gb is None:
                continue
            bases = [gb]
            for extra in (1, 5):  # a non-minimal (odd-offset) base
                b2 = gb + extra
                if b2 + mg <= R and all(
                        (b2 >> d) + int(node_sizes(mg, d).max())
                        <= (R >> d)
                        for d in range(L - NL + 1)):
                    bases.append(b2)
                    break
            for base in bases:
                dh = rng.standard_normal((mh, p)).astype(np.float32)
                dg = rng.standard_normal((mg, p)).astype(np.float32)
                oh, og = simulate_dense_pair(dh, dg, L, R, base=base)
                np.testing.assert_array_equal(oh, ffa_transform(dh))
                np.testing.assert_array_equal(og, ffa_transform(dg))
                checked += 1
    assert checked, f"no feasible embedding for ({mh}, {mg})"


def test_guest_base_feasibility():
    # a full container has no dead rows to lend
    assert guest_base(1024, 10, 10, 1024) is None
    # guest bigger than the slack
    assert guest_base(800, 400, 10, 1024) is None
    # the known-good case: base at the host's slot ceiling
    assert guest_base(800, 100, 10, 1024) == 896
    # pair_bucket_bases: skip positions need no feasibility
    assert pair_bucket_bases([1024, 800], [5, 100], 10, 1024,
                             skip=(0,)) == (None, 896)
    assert pair_bucket_bases([1024, 800], [5, 100], 10, 1024) is None


# -------------------------------------------------------- kernel level

def _paired_case(ms, ps, gms, bases, widths=(1, 2, 3), seed=3):
    B = len(ms)
    h = np.zeros((B, len(widths)), np.float32)
    b = np.zeros_like(h)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.linspace(1.0, 2.0, B).astype(np.float32)
    gstd = np.linspace(1.5, 2.5, B).astype(np.float32)
    k = CycleKernel(ms, ps, widths, h, b, std, interpret=True,
                    guests=dict(ms=gms, bases=bases, hcoef=h, bcoef=b,
                                stdnoise=gstd))
    k0 = CycleKernel(ms, ps, widths, h, b, std, interpret=True)
    rng = np.random.default_rng(seed)
    x = np.zeros((B, k.rows, k.P), np.float32)
    x0 = np.zeros((B, k0.rows, k0.P), np.float32)
    dh, dg = [], []
    for i, (m, p, gm, bb) in enumerate(zip(ms, ps, gms, bases)):
        d1 = rng.standard_normal((m, p)).astype(np.float32)
        d2 = rng.standard_normal((gm, p)).astype(np.float32)
        dh.append(d1)
        dg.append(d2)
        x[i, :m, :p] = d1
        x0[i, :m, :p] = d1
        if bb is not None:
            x[i, bb : bb + gm, :p] = d2
    return k, k0, x, x0, dh, dg, std, gstd, widths


def test_paired_cycle_kernel_oracle_and_host_bitwise(monkeypatch):
    """Interpret-mode paired kernel: both trials match the reference
    S/N, and the host trial's rows are BITWISE what the unpaired
    kernel computes (the guest rides only in dead rows). Includes a
    lone unpaired trial (base None) and an m=1 padding host."""
    monkeypatch.setenv("RIPTIDE_KERNEL_BASE3", "0")
    ms, ps, gms = [200, 190, 1], [33, 40, 33], [24, 30, 1]
    L = max(num_levels(m) for m in ms)
    rows = 1 << L
    bases = [guest_base(m, gm, L, rows) for m, gm in zip(ms, gms)]
    bases[1] = None  # lone trial in a paired bucket
    k, k0, x, x0, dh, dg, std, gstd, widths = _paired_case(
        ms, ps, gms, bases)
    assert k.rows == rows
    out = np.asarray(k(x))
    out0 = np.asarray(k0(x0))
    nw = len(widths)
    for i, (m, p, gm, bb) in enumerate(zip(ms, ps, gms, bases)):
        if m > 1:
            want = boxcar_snr_2d(ffa_transform(dh[i]), np.asarray(widths),
                                 stdnoise=float(std[i]))
            np.testing.assert_allclose(out[i, :m, :nw], want,
                                       rtol=2e-4, atol=2e-4)
        if bb is not None and gm > 1:
            wantg = boxcar_snr_2d(ffa_transform(dg[i]),
                                  np.asarray(widths),
                                  stdnoise=float(gstd[i]))
            np.testing.assert_allclose(out[i, bb : bb + gm, :nw], wantg,
                                       rtol=2e-4, atol=2e-4)
        np.testing.assert_array_equal(out[i, :m, :nw], out0[i, :m, :nw])


def test_cycle_kernel_odd_slot_container():
    """Interpret-mode kernel on a 5-row-slot (5 * 2^(L-3)) bucket."""
    ms, ps = [75, 78], [33, 40]
    widths = (1, 2, 3)
    B = len(ms)
    h = np.zeros((B, len(widths)), np.float32)
    b = np.zeros_like(h)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.linspace(1.0, 2.0, B).astype(np.float32)
    k = CycleKernel(ms, ps, widths, h, b, std, interpret=True)
    assert k.rows == 5 << (k.L - 3), (k.rows, k.L)
    rng = np.random.default_rng(5)
    x = np.zeros((B, k.rows, k.P), np.float32)
    datas = []
    for i, (m, p) in enumerate(zip(ms, ps)):
        d = rng.standard_normal((m, p)).astype(np.float32)
        datas.append(d)
        x[i, :m, :p] = d
    out = np.asarray(k(x))
    for i, (m, p) in enumerate(zip(ms, ps)):
        want = boxcar_snr_2d(ffa_transform(datas[i]), np.asarray(widths),
                             stdnoise=float(std[i]))
        np.testing.assert_allclose(out[i, :m, :len(widths)], want,
                                   rtol=2e-4, atol=2e-4)


# -------------------------------------------------------- engine level

@pytest.fixture()
def kernel_env(monkeypatch):
    monkeypatch.setenv("RIPTIDE_FFA_PATH", "kernel")
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint6")
    monkeypatch.setenv("RIPTIDE_KERNEL_BASE3", "0")
    return monkeypatch


@pytest.fixture(scope="module")
def plan():
    return periodogram_plan(SIZE, TSAMP, WIDTHS, PMIN, PMAX, BMIN, BMAX)


@pytest.fixture(scope="module")
def batch():
    from riptide_tpu.libffa import generate_signal

    rng = np.random.default_rng(21)
    b = rng.standard_normal((2, SIZE)).astype(np.float32)
    np.random.seed(9)
    b[0] = generate_signal(SIZE, 0.05 / TSAMP, amplitude=14.0, ducy=0.08)
    b -= b.mean(axis=1, keepdims=True)
    b /= b.std(axis=1, keepdims=True)
    return b


def test_row_pack_map_pairs(plan, kernel_env):
    rpm = eng._row_pack_map(plan, "uint6")
    hosts = {k: v for k, v in rpm.items() if v[0] == "host"}
    guests = {k: v for k, v in rpm.items() if v[0] == "guest"}
    assert hosts and len(hosts) == len(guests)
    for (s, k), (_, s2, bases) in hosts.items():
        assert rpm[(s2, k)] == ("guest", s)
        st, st2 = plan.stages[s], plan.stages[s2]
        idx = st.lane_buckets[k]
        L, NL, rows, P = eng._bucket_shape(st, idx)
        for j, g in enumerate(idx):
            if bases[j] is None:
                continue
            assert bases[j] + st2.ms_padded[g] <= rows
            assert bases[j] >= st.ms_padded[g]
    # the map is a device-layout property: flag off empties it
    kernel_env.setenv("RIPTIDE_KERNEL_ROW_PACK", "0")
    assert eng._row_pack_map(plan, "uint6") == {}


def test_dm_batched_peaks_byte_identical_flag_on_off(plan, batch,
                                                     kernel_env):
    """THE acceptance e2e: a DM-batched CPU survey through the fused
    path with on-device peaks — S/N cube and peaks.csv bytes identical
    with RIPTIDE_KERNEL_ROW_PACK=1 vs 0, while the flag-on run queues
    FEWER fused programs (the absorbed bucket) and zero pack
    programs."""
    import pandas

    from riptide_tpu.search.engine import (
        collect_search_batch, queue_search_batch, search_snr_dev,
    )

    tobs = SIZE * TSAMP

    def run():
        reg = MetricsRegistry()
        prev = set_metrics(reg)
        try:
            handle = queue_search_batch(plan, batch, tobs=tobs, **PKW)
            snr = np.asarray(search_snr_dev(handle))
            peaks, _ = collect_search_batch(handle, np.zeros(2))
        finally:
            set_metrics(prev)
        return snr, peaks, reg.summary()

    def csv_bytes(peaks):
        buf = io.StringIO()
        pandas.DataFrame(peaks).to_csv(buf, index=False)
        return buf.getvalue().encode()

    snr_on, peaks_on, m_on = run()
    kernel_env.setenv("RIPTIDE_KERNEL_ROW_PACK", "0")
    snr_off, peaks_off, m_off = run()

    np.testing.assert_array_equal(snr_on, snr_off)
    assert any(peaks_on[0]), "expected the injected pulsar detected"
    for d in range(2):
        assert csv_bytes(peaks_on[d]) == csv_bytes(peaks_off[d])

    n_absorbed = sum(1 for v in eng._row_pack_map(plan, "uint6").values()
                     if v[0] == "guest")
    kernel_env.setenv("RIPTIDE_KERNEL_ROW_PACK", "1")
    rpm = eng._row_pack_map(plan, "uint6")
    n_absorbed = sum(1 for v in rpm.values() if v[0] == "guest")
    assert n_absorbed >= 1
    assert m_on.get("dispatch_fused") == \
        m_off.get("dispatch_fused") - n_absorbed
    assert m_on.get("dispatch_pack", 0) == 0
    assert m_off.get("dispatch_pack", 0) == 0


def test_row_packed_stage_queues_one_fused_no_pack(plan, batch,
                                                   kernel_env):
    """Dispatch-count regression with tripwired pack entry points: a
    row-packed run still queues exactly one fused program per
    NON-absorbed stage lane bucket and never a separate pack
    program."""

    def _no_pack(*a, **k):
        raise AssertionError("separate pack program dispatched on the "
                             "row-packed fused path")

    kernel_env.setattr(eng, "_pack_static_view", _no_pack)
    kernel_env.setattr(eng, "_pack_static", _no_pack)
    rpm = eng._row_pack_map(plan, "uint6")
    want = sum(
        1
        for i, st in enumerate(plan.stages)
        for k in range(len(st.lane_buckets))
        if rpm.get((i, k), ("",))[0] != "guest"
    )
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        eng.run_periodogram(plan, batch[0])
    finally:
        set_metrics(prev)
    s = reg.summary()
    assert s.get("dispatch_fused") == want
    assert s.get("dispatch_pack", 0) == 0
    assert s.get("dispatch_kernel", 0) == 0


def test_fused_equals_two_dispatch_with_flag_on(plan, batch, kernel_env):
    """With the flag ON, forcing the two-dispatch form (which never
    row-packs — pairing is a fused-path layout) must still give the
    BITWISE same S/N: per-trial results are layout-independent."""
    _, _, s_fused = eng.run_periodogram(plan, batch[1])
    kernel_env.setattr(eng, "_fused_eligible", lambda *a: False)
    _, _, s_two = eng.run_periodogram(plan, batch[1])
    np.testing.assert_array_equal(s_fused, s_two)


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["uint8", "uint12"])
def test_row_pack_parity_other_wire_modes(plan, batch, kernel_env, mode):
    """Flag on/off bitwise parity holds for every quantised wire mode
    (odd stage tails included — SIZE is not a multiple of PW)."""
    kernel_env.setenv("RIPTIDE_WIRE_DTYPE", mode)
    _, _, s_on = eng.run_periodogram(plan, batch[0])
    kernel_env.setenv("RIPTIDE_KERNEL_ROW_PACK", "0")
    _, _, s_off = eng.run_periodogram(plan, batch[0])
    np.testing.assert_array_equal(s_on, s_off)


def test_flag_off_reverts_containers(plan, kernel_env):
    """The escape hatch: RIPTIDE_KERNEL_ROW_PACK=0 restores the legacy
    container family exactly (and the single-trial plans keep working:
    a one-stage plan has no pairing candidates at all)."""
    kernel_env.setenv("RIPTIDE_KERNEL_BASE3", "1")
    assert bucket_rows([600], 10) == 640
    kernel_env.setenv("RIPTIDE_KERNEL_ROW_PACK", "0")
    assert bucket_rows([600], 10) == 768
    kernel_env.setenv("RIPTIDE_KERNEL_BASE3", "0")
    assert bucket_rows([600], 10) == 1024
    single = periodogram_plan(1200, 1e-3, (1, 2), 34e-3, 0.036, 32, 40)
    assert len(single.stages) == 1
    assert eng._row_pack_map(single, "uint6") == {}


def test_plan_occupancy_accounting(plan, kernel_env):
    occ = plan_occupancy(plan)
    t = occ["totals"]
    assert t["computed_rowlane"] - t["live_rowlane"] == \
        t["padded_rowlane"] >= 0
    assert t["legacy_padded_rowlane"] >= t["padded_rowlane"]
    assert occ["pairs"] >= 1
    assert t["padded_reduction_vs_legacy"] > 0
    assert len(occ["buckets"]) == sum(len(st.lane_buckets)
                                      for st in plan.stages)
    roles = {b["role"] for b in occ["buckets"]}
    assert "host" in roles and "guest" in roles
    # per-bucket identities
    for b in occ["buckets"]:
        if b["role"] == "guest":
            assert b["computed_rowlane"] == 0
        else:
            assert b["computed_rowlane"] == b["B"] * b["rows"] * b["P"]
    # flag off: no pairs, zero reduction vs itself
    kernel_env.setenv("RIPTIDE_KERNEL_ROW_PACK", "0")
    occ0 = plan_occupancy(plan)
    assert occ0["pairs"] == 0
    assert occ0["totals"]["padded_reduction_vs_legacy"] == 0.0
    assert occ0["totals"]["computed_rowlane"] == \
        occ0["totals"]["legacy_computed_rowlane"]
