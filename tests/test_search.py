"""
Search-level tests: periodogram engine vs the slow numpy oracle, and the
end-to-end S/N parity bar on a seeded synthetic pulsar (S/N 18.5 +/- 0.15
— the same deterministic oracle as riptide/tests/test_rseek.py:50-54).
"""
import numpy as np
import pytest

from riptide_tpu import TimeSeries, ffa_search, generate_width_trials
from riptide_tpu.ops.reference import periodogram_ref
from riptide_tpu.search import periodogram_plan, run_periodogram, run_periodogram_batch


@pytest.fixture(scope="module")
def small_cfg():
    rng = np.random.RandomState(0)
    data = rng.normal(size=8192).astype(np.float32)
    data = ((data - data.mean()) / data.std()).astype(np.float32)
    return data, dict(tsamp=0.001, period_min=0.025, period_max=0.1, bins_min=24, bins_max=26)


def test_engine_matches_oracle(small_cfg):
    data, cfg = small_cfg
    widths = generate_width_trials(cfg["bins_min"])
    P1, F1, S1 = periodogram_ref(
        data, cfg["tsamp"], widths, cfg["period_min"], cfg["period_max"],
        cfg["bins_min"], cfg["bins_max"],
    )
    plan = periodogram_plan(
        data.size, cfg["tsamp"], tuple(int(w) for w in widths),
        cfg["period_min"], cfg["period_max"], cfg["bins_min"], cfg["bins_max"],
    )
    P2, F2, S2 = run_periodogram(plan, data)
    assert len(P1) == len(P2) == plan.length
    assert np.array_equal(F1, F2)
    assert np.allclose(P1, P2, rtol=1e-12)
    assert np.allclose(S1, S2, atol=2e-3)


def test_engine_batch_matches_single(small_cfg):
    data, cfg = small_cfg
    widths = generate_width_trials(cfg["bins_min"])
    plan = periodogram_plan(
        data.size, cfg["tsamp"], tuple(int(w) for w in widths),
        cfg["period_min"], cfg["period_max"], cfg["bins_min"], cfg["bins_max"],
    )
    rng = np.random.RandomState(1)
    batch = rng.normal(size=(3, data.size)).astype(np.float32)
    batch[0] = data
    P, F, S = run_periodogram_batch(plan, batch)
    assert S.shape[0] == 3
    P0, F0, S0 = run_periodogram(plan, data)
    assert np.allclose(S[0], S0, atol=1e-4)
    for d in (1, 2):
        _, _, Sd = run_periodogram(plan, batch[d])
        assert np.allclose(S[d], Sd, atol=1e-4)


def test_periods_monotonic_and_shapes():
    """Contract checks mirrored from riptide/tests/test_ffa_search_pgram.py:
    monotone increasing trial periods, matching array lengths, decreasing
    freqs."""
    np.random.seed(42)
    ts = TimeSeries.generate(length=20.0, tsamp=0.001, period=1.0, amplitude=15.0)
    tsn, pgram = ffa_search(ts, period_min=0.5, period_max=2.0, bins_min=32, bins_max=36)
    assert np.all(np.diff(pgram.periods) > 0)
    assert pgram.snrs.shape == (pgram.periods.size, pgram.widths.size)
    assert pgram.foldbins.size == pgram.periods.size
    assert np.all(np.diff(pgram.freqs) < 0)
    assert pgram.metadata is tsn.metadata
    # trial periods span the requested range (up to bins/(bins+1) granularity)
    assert pgram.periods[0] <= 0.5 * (1 + 1.0 / 32)
    assert pgram.periods[-1] >= 2.0 * (1 - 1.0 / 32)


def test_identity_contract():
    """deredden=False + already_normalised=True must return the input
    TimeSeries object itself (riptide/tests/test_ffa_search_pgram.py:41-47)."""
    np.random.seed(0)
    ts = TimeSeries.generate(length=20.0, tsamp=0.001, period=1.0, amplitude=0.0)
    out, _ = ffa_search(
        ts, period_min=0.5, period_max=1.0, bins_min=32, bins_max=36,
        deredden=False, already_normalised=True,
    )
    assert out is ts


def test_no_downsampling_edge_case():
    """period_min == bins_min * tsamp => initial factor is exactly 1
    (regression: riptide/tests/test_ffa_search_pgram.py:77-96)."""
    np.random.seed(3)
    ts = TimeSeries.generate(length=10.0, tsamp=0.001, period=0.1, amplitude=10.0)
    _, pgram = ffa_search(ts, period_min=0.032, period_max=0.1, bins_min=32, bins_max=36)
    assert pgram.periods.size > 0
    assert np.all(np.diff(pgram.periods) > 0)


def test_snr_parity_oracle():
    """THE parity bar: seeded fake pulsar, P = 1 s, amplitude 20,
    ducy 0.02, 128 s at 256 us sampling, searched with the rseek test's
    options (P 0.5-2.0 s, bins 480-520, ducy_max 0.3): the best trial must
    come out at S/N 18.5 +/- 0.15 with width 13 bins and frequency within
    0.1/T of 1 Hz — the reference's deterministic end-to-end expectation
    (riptide/tests/test_rseek.py:17,31-54, tests/presto_generation.py:46)."""
    np.random.seed(0)
    ts = TimeSeries.generate(length=128.0, tsamp=256e-6, period=1.0, amplitude=20.0, ducy=0.02)
    _, pgram = ffa_search(
        ts, period_min=0.5, period_max=2.0, bins_min=480, bins_max=520, ducy_max=0.3
    )
    ip, iw = np.unravel_index(np.argmax(pgram.snrs), pgram.snrs.shape)
    best_snr = pgram.snrs[ip, iw]
    assert abs(1.0 / pgram.periods[ip] - 1.0) < 0.1 / 128.0
    assert int(pgram.widths[iw]) == 13
    assert abs(best_snr - 18.5) < 0.15


@pytest.mark.parametrize("wire", ["float16", "uint12", "uint8", "uint6"])
def test_snr_parity_oracle_lossy_wire(monkeypatch, wire):
    """The lossy host->device wire transports (float16, and the 12-bit
    12-bit packed option, and the 8-bit block-scaled default of the TPU
    kernel path — search/engine.py:_wire_mode)
    must hold the same 18.5 +/- 0.15 oracle bar: float16's ~5e-4
    relative rounding and uint12's max/4094 quantisation step are both
    S/N errors of order 0.01. Exercised through the CPU gather path,
    which applies the identical cast/decode."""
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", wire)
    np.random.seed(0)
    ts = TimeSeries.generate(length=128.0, tsamp=256e-6, period=1.0, amplitude=20.0, ducy=0.02)
    _, pgram = ffa_search(
        ts, period_min=0.5, period_max=2.0, bins_min=480, bins_max=520, ducy_max=0.3
    )
    ip, iw = np.unravel_index(np.argmax(pgram.snrs), pgram.snrs.shape)
    assert abs(1.0 / pgram.periods[ip] - 1.0) < 0.1 / 128.0
    assert int(pgram.widths[iw]) == 13
    assert abs(pgram.snrs[ip, iw] - 18.5) < 0.15
