"""
Unit tests of peak detection and 1-D clustering, mirroring the
reference's semantics (riptide/peak_detection.py, riptide/clustering.py).
"""
import numpy as np
import pytest

from riptide_tpu.clustering import cluster1d
from riptide_tpu.peak_detection import (
    Peak,
    find_peaks,
    find_peaks_single,
    fit_threshold,
    segment_stats,
)


# ---------------------------------------------------------------- cluster1d

def test_cluster1d_empty():
    assert cluster1d(np.array([]), 1.0) == []


def test_cluster1d_single_cluster():
    x = np.array([0.0, 0.1, 0.2, 0.3])
    out = cluster1d(x, 0.15)
    assert len(out) == 1
    assert sorted(out[0]) == [0, 1, 2, 3]


def test_cluster1d_chained_friends_of_friends():
    # Chained membership: consecutive gaps all <= r so one cluster even
    # though the extremes are far apart.
    x = np.array([0.0, 0.9, 1.8, 2.7])
    out = cluster1d(x, 1.0)
    assert len(out) == 1


def test_cluster1d_splits_on_gap():
    x = np.array([0.0, 0.1, 5.0, 5.1, 10.0])
    out = cluster1d(x, 0.5)
    groups = [sorted(g.tolist()) for g in out]
    assert groups == [[0, 1], [2, 3], [4]]


def test_cluster1d_unsorted_input_indices_into_original():
    x = np.array([5.1, 0.0, 5.0, 0.1])
    out = cluster1d(x, 0.5)
    groups = sorted(sorted(g.tolist()) for g in out)
    assert groups == [[0, 2], [1, 3]]


def test_cluster1d_assume_sorted_flag():
    x = np.array([0.0, 0.1, 2.0])
    out = cluster1d(x, 0.5, assume_sorted=True)
    groups = [sorted(g.tolist()) for g in out]
    assert groups == [[0, 1], [2]]


# ------------------------------------------------------------ segment stats

def test_segment_stats_shapes_and_values():
    # 100 segments of 10 points each over f in [1, 2], T such that
    # segwidth/T = 0.01.
    f = np.linspace(2.0, 1.0, 1000)
    s = np.full(1000, 3.0)
    fc, smed, sstd = segment_stats(f, s, T=500.0, segwidth=5.0)
    assert len(fc) == len(smed) == len(sstd) == 100
    assert np.allclose(smed, 3.0)
    assert np.allclose(sstd, 0.0)
    # Segment centres are ordered like f (decreasing here)
    assert np.all(np.diff(fc) < 0)


def test_segment_stats_robust_std():
    # Gaussian S/N values: IQR/1.349 estimates sigma.
    rng = np.random.RandomState(0)
    f = np.linspace(1.0, 2.0, 100_000)
    s = rng.normal(5.0, 2.0, size=f.size)
    fc, smed, sstd = segment_stats(f, s, T=10.0, segwidth=5.0)
    assert np.allclose(smed, 5.0, atol=0.2)
    assert np.allclose(sstd, 2.0, atol=0.3)


def test_fit_threshold_recovers_polynomial():
    fc = np.exp(np.linspace(0.0, 2.0, 50))
    tc = 1.5 * np.log(fc) ** 2 - 0.5 * np.log(fc) + 3.0
    poly = fit_threshold(fc, tc, polydeg=2)
    assert np.allclose(poly.coefficients, [1.5, -0.5, 3.0], atol=1e-8)


# -------------------------------------------------------- find_peaks_single

def test_find_peaks_single_static_fallback():
    # Too few segments for a dynamic fit: polyco falls back to [smin].
    f = np.linspace(2.0, 1.0, 100)
    s = np.zeros(100)
    s[40] = 50.0
    idx, polyco = find_peaks_single(f, s, T=10.0, smin=6.0, minseg=10)
    assert list(polyco) == [6.0]
    assert idx == [40]


def test_find_peaks_single_clusters_adjacent_points():
    f = np.linspace(2.0, 1.0, 1000)
    s = np.zeros(1000)
    s[500:505] = [20.0, 30.0, 40.0, 30.0, 20.0]  # one broad peak
    s[800] = 25.0  # a second, separate peak
    idx, _ = find_peaks_single(f, s, T=1000.0, smin=6.0, clrad=5.0)
    assert sorted(idx) == [502, 800]


def test_find_peaks_single_respects_smin():
    f = np.linspace(2.0, 1.0, 100)
    s = np.full(100, 1.0)
    s[10] = 5.9  # below smin
    idx, _ = find_peaks_single(f, s, T=10.0, smin=6.0)
    assert idx == []


# --------------------------------------------------------------- find_peaks

class _FakePgram:
    """Minimal Periodogram stand-in for find_peaks unit tests."""

    def __init__(self, freqs, widths, snrs, foldbins, tobs, dm=7.5):
        self.freqs = freqs
        self.widths = widths
        self.snrs = snrs
        self.foldbins = foldbins
        self.tobs = tobs
        self.metadata = {"dm": dm}


def test_find_peaks_typed_output():
    n = 2000
    freqs = np.linspace(2.0, 1.0, n)
    widths = np.array([1, 2])
    snrs = np.zeros((n, 2))
    snrs[700, 0] = 30.0
    snrs[700, 1] = 45.0
    foldbins = np.full(n, 256, dtype=np.uint32)
    pgram = _FakePgram(freqs, widths, snrs, foldbins, tobs=200.0)

    peaks, polycos = find_peaks(pgram, smin=6.0)
    assert len(peaks) == 2
    # Sorted by decreasing S/N; the width-2 trial wins.
    best = peaks[0]
    assert isinstance(best, Peak)
    assert best.snr == 45.0
    assert best.width == 2
    assert best.iw == 1
    assert best.ip == 700
    assert best.freq == pytest.approx(freqs[700])
    assert best.period == pytest.approx(1.0 / freqs[700])
    assert best.ducy == pytest.approx(2.0 / 256.0)
    assert best.dm == 7.5
    # Plain python types only (reference: peak_detection.py:210-212)
    assert type(best.freq) is float
    assert type(best.width) is int
    assert type(best.snr) is float
    assert set(polycos.keys()) == {0, 1}
    assert best.summary_dict() == {
        "period": best.period,
        "freq": best.freq,
        "dm": 7.5,
        "width": 2,
        "ducy": best.ducy,
        "snr": 45.0,
    }


def test_find_peaks_pure_noise_none_significant():
    rng = np.random.RandomState(1)
    n = 5000
    freqs = np.linspace(2.0, 1.0, n)
    widths = np.array([1])
    snrs = rng.normal(0.0, 1.0, size=(n, 1))
    foldbins = np.full(n, 256, dtype=np.uint32)
    pgram = _FakePgram(freqs, widths, snrs, foldbins, tobs=200.0)
    peaks, _ = find_peaks(pgram, smin=7.0)
    assert peaks == []
