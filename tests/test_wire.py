"""
Wire-transport tests: the 12-bit packed host->device format
(search/engine.py:_prepare_u12 / _u12_decode, native
rn_prepare_wire_u12) and its layout bookkeeping.
"""
import numpy as np
import pytest

from riptide_tpu import native
from riptide_tpu.search import periodogram_plan
from riptide_tpu.search.engine import (
    _prepare_u12,
    _prepare_u8,
    _scale_layout,
    _u12_decode,
    _u8_decode,
    _wire_layout,
    prepare_stage_data,
    run_periodogram,
)


def _plan():
    return periodogram_plan(4096, 1e-3, (1, 2, 3), 64e-3, 0.15, 64, 71)


def test_u12_roundtrip_error_bound():
    """decode(encode(x)) must be within half a quantisation step of x
    for every sample of every stage."""
    plan = _plan()
    rng = np.random.default_rng(0)
    batch = rng.standard_normal((3, plan.size)).astype(np.float32)
    flat, scales = _prepare_u12(plan, batch)
    offs, lens, tot = _wire_layout(plan, "uint12")
    assert flat.shape == (3, tot)
    from riptide_tpu.search.engine import _host_downsample_all

    xds = _host_downsample_all(plan, batch, np.float32)
    for i, st in enumerate(plan.stages):
        seg = flat[:, offs[i] : offs[i] + lens[i]]
        dec = np.asarray(_u12_decode(seg, scales[i]))[:, : st.n]
        want = xds[i][..., : st.n]
        step = scales[i][:, None]
        assert np.all(np.abs(dec - want) <= 0.5 * step + 1e-6), i


def test_u12_native_matches_numpy_fallback(monkeypatch):
    """The native single-pass wire preparation must produce the exact
    bytes and scales of the numpy fallback (same float64 accumulation,
    same round-half-even quantisation)."""
    if not native.available():
        pytest.skip("native library unavailable")
    plan = _plan()
    rng = np.random.default_rng(1)
    batch = rng.standard_normal((2, plan.size)).astype(np.float32)
    got_flat, got_scales = _prepare_u12(plan, batch)

    monkeypatch.setattr(native, "available", lambda: False)
    want_flat, want_scales = _prepare_u12(plan, batch)
    np.testing.assert_array_equal(got_scales, want_scales)
    np.testing.assert_array_equal(got_flat, want_flat)


def test_u12_search_close_to_exact(monkeypatch):
    """A full periodogram through the uint12 wire stays within S/N 0.05
    of the float32-wire result at every trial (pure noise input — the
    tightest relative regime)."""
    plan = _plan()
    rng = np.random.default_rng(2)
    data = rng.standard_normal(plan.size).astype(np.float32)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float32")
    _, _, snr32 = run_periodogram(plan, data)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint12")
    _, _, snr12 = run_periodogram(plan, data)
    assert np.max(np.abs(snr32 - snr12)) < 0.05


def test_prepare_stage_data_meta(monkeypatch):
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint12")
    plan = _plan()
    batch = np.zeros((2, plan.size), np.float32)
    flat, meta = prepare_stage_data(plan, batch)
    assert meta["mode"] == "uint12"
    assert flat.dtype == np.uint8
    assert meta["scales"].shape == (len(plan.stages), 2)
    # all-zero input: scale falls back to 1.0, bytes encode q = 2048
    assert np.all(meta["scales"] == 1.0)

    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "bogus")
    with pytest.raises(ValueError):
        prepare_stage_data(plan, batch)


def test_u8_roundtrip_error_bound():
    """decode(encode(x)) within half a block-quantisation step."""
    plan = _plan()
    rng = np.random.default_rng(3)
    batch = rng.standard_normal((3, plan.size)).astype(np.float32)
    flat, scales = _prepare_u8(plan, batch)
    offs, lens, tot = _wire_layout(plan, "uint8")
    soffs, nblks, stot = _scale_layout(plan)
    assert flat.shape == (3, tot) and scales.shape == (3, stot)
    from riptide_tpu.search.engine import _host_downsample_all

    xds = _host_downsample_all(plan, batch, np.float32)
    for i, st in enumerate(plan.stages):
        seg = flat[:, offs[i] : offs[i] + lens[i]]
        sc = scales[:, soffs[i] : soffs[i] + nblks[i]]
        dec = np.asarray(_u8_decode(seg, sc))[:, : st.n]
        want = xds[i][..., : st.n]
        step = np.repeat(sc, 256, axis=1)[:, : st.n]
        assert np.all(np.abs(dec - want) <= 0.5 * step + 1e-6), i


def test_u8_native_matches_numpy_fallback(monkeypatch):
    if not native.available():
        pytest.skip("native library unavailable")
    plan = _plan()
    rng = np.random.default_rng(4)
    batch = rng.standard_normal((2, plan.size)).astype(np.float32)
    got_flat, got_scales = _prepare_u8(plan, batch)
    monkeypatch.setattr(native, "available", lambda: False)
    want_flat, want_scales = _prepare_u8(plan, batch)
    np.testing.assert_array_equal(got_scales, want_scales)
    np.testing.assert_array_equal(got_flat, want_flat)


def test_u8_search_close_to_exact(monkeypatch):
    """Full periodogram through the uint8 block-adaptive wire stays
    within S/N 0.1 of the float32-wire result at every trial."""
    plan = _plan()
    rng = np.random.default_rng(5)
    data = rng.standard_normal(plan.size).astype(np.float32)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float32")
    _, _, snr32 = run_periodogram(plan, data)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint8")
    _, _, snr8 = run_periodogram(plan, data)
    assert np.max(np.abs(snr32 - snr8)) < 0.1


def test_u6_roundtrip_error_bound():
    """decode(encode(x)) within half a 6-bit block-quantisation step."""
    from riptide_tpu.search.engine import _prepare_u6, _u6_decode

    plan = _plan()
    rng = np.random.default_rng(6)
    batch = rng.standard_normal((3, plan.size)).astype(np.float32)
    flat, scales = _prepare_u6(plan, batch)
    offs, lens, tot = _wire_layout(plan, "uint6")
    soffs, nblks, stot = _scale_layout(plan)
    assert flat.shape == (3, tot) and scales.shape == (3, stot)
    from riptide_tpu.search.engine import _host_downsample_all

    xds = _host_downsample_all(plan, batch, np.float32)
    for i, st in enumerate(plan.stages):
        seg = flat[:, offs[i] : offs[i] + lens[i]]
        sc = scales[:, soffs[i] : soffs[i] + nblks[i]]
        dec = np.asarray(_u6_decode(seg, sc))[:, : st.n]
        want = xds[i][..., : st.n]
        step = np.repeat(sc, 256, axis=1)[:, : st.n]
        assert np.all(np.abs(dec - want) <= 0.5 * step + 1e-6), i


def test_u6_native_matches_numpy_fallback(monkeypatch):
    from riptide_tpu.search.engine import _prepare_u6

    if not native.available():
        pytest.skip("native library unavailable")
    plan = _plan()
    rng = np.random.default_rng(7)
    batch = rng.standard_normal((2, plan.size)).astype(np.float32)
    got_flat, got_scales = _prepare_u6(plan, batch)
    monkeypatch.setattr(native, "available", lambda: False)
    want_flat, want_scales = _prepare_u6(plan, batch)
    np.testing.assert_array_equal(got_scales, want_scales)
    np.testing.assert_array_equal(got_flat, want_flat)


def test_u6_search_close_to_exact(monkeypatch):
    """Full periodogram through the uint6 wire stays within S/N 0.25 of
    the float32-wire result at every trial (4x uint8's step)."""
    plan = _plan()
    rng = np.random.default_rng(8)
    data = rng.standard_normal(plan.size).astype(np.float32)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float32")
    _, _, snr32 = run_periodogram(plan, data)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint6")
    _, _, snr6 = run_periodogram(plan, data)
    assert np.max(np.abs(snr32 - snr6)) < 0.25
