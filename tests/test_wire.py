"""
Wire-transport tests: the quantised byte-plane VIEW formats
(search/engine.py:_prepare_uint / _udecode_view, native
rn_prepare_wire_view) and their layout bookkeeping.

Each stage ships as a (R0, PW) sample view with one float32 scale per
view row and `group` consecutive rows packed across byte planes — the
layout the fused Pallas kernel decodes with dense elementwise ops (no
byte-strided lane relayout).
"""
import numpy as np
import pytest

from riptide_tpu import native
from riptide_tpu.ops.ffa_kernel import WIRE_MODES
from riptide_tpu.search import periodogram_plan
from riptide_tpu.search.engine import (
    _decode_stage_rows,
    _prepare_uint,
    _view_layout,
    _view_width,
    _wire_layout,
    prepare_stage_data,
    run_periodogram,
)

QMAX = {"uint6": 31.0, "uint8": 127.0, "uint12": 2047.0}


def _plan():
    return periodogram_plan(4096, 1e-3, (1, 2, 3), 64e-3, 0.15, 64, 71)


def _decode_all(plan, mode, flat, scales):
    """Decode every stage of a prepared wire back to (D, n) samples."""
    import jax.numpy as jnp

    vl = _view_layout(plan, mode)
    outs = []
    for i, st in enumerate(plan.stages):
        dec = _decode_stage_rows(
            mode, jnp.asarray(flat), jnp.asarray(scales)[..., None],
            int(vl["roffs"][i]), int(vl["wrows"][i]),
            int(vl["soffs"][i]), int(vl["r0s"][i]), st.n,
        )
        outs.append(np.asarray(dec))
    return outs


@pytest.mark.parametrize("mode", ["uint6", "uint8", "uint12"])
def test_view_roundtrip_error_bound(mode):
    """decode(encode(x)) within half a quantisation step of x for every
    sample of every stage, with the step set by that sample's per-row
    scale."""
    plan = _plan()
    rng = np.random.default_rng(0)
    batch = rng.standard_normal((3, plan.size)).astype(np.float32)
    flat, scales = _prepare_uint(plan, batch, mode)
    vl = _view_layout(plan, mode)
    assert flat.shape == (3, vl["tot_rows"], vl["PW"])
    assert scales.shape == (3, vl["stot"])
    from riptide_tpu.search.engine import _host_downsample_all

    xds = _host_downsample_all(plan, batch, np.float32)
    decs = _decode_all(plan, mode, flat, scales)
    PW = vl["PW"]
    for i, st in enumerate(plan.stages):
        want = xds[i][..., : st.n]
        # per-sample step: the scale of the sample's view row
        rows = np.arange(st.n) // PW
        step = scales[:, vl["soffs"][i] + rows]
        assert np.all(np.abs(decs[i] - want) <= 0.5 * step + 1e-6), (mode, i)


@pytest.mark.parametrize("mode", ["uint6", "uint8", "uint12"])
def test_native_matches_numpy_fallback(mode, monkeypatch):
    """The native single-pass wire preparation must produce the exact
    bytes and scales of the numpy fallback (same float64 accumulation,
    same float32 reciprocal, same round-half-even)."""
    if not native.available():
        pytest.skip("native library unavailable")
    plan = _plan()
    rng = np.random.default_rng(1)
    batch = rng.standard_normal((2, plan.size)).astype(np.float32)
    got_flat, got_scales = _prepare_uint(plan, batch, mode)

    monkeypatch.setattr(native, "available", lambda: False)
    want_flat, want_scales = _prepare_uint(plan, batch, mode)
    np.testing.assert_array_equal(got_scales, want_scales)
    np.testing.assert_array_equal(got_flat, want_flat)


def test_u12_search_close_to_exact(monkeypatch):
    """A full periodogram through the uint12 wire stays within S/N 0.05
    of the float32-wire result at every trial (pure noise input — the
    tightest relative regime)."""
    plan = _plan()
    rng = np.random.default_rng(2)
    data = rng.standard_normal(plan.size).astype(np.float32)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "float32")
    _, _, snr32 = run_periodogram(plan, data)
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint12")
    _, _, snr12 = run_periodogram(plan, data)
    assert np.max(np.abs(snr32 - snr12)) < 0.05


def test_prepare_stage_data_meta(monkeypatch):
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "uint12")
    plan = _plan()
    batch = np.zeros((2, plan.size), np.float32)
    flat, meta = prepare_stage_data(plan, batch)
    assert meta["mode"] == "uint12"
    assert flat.dtype == np.uint8 and flat.ndim == 3
    vl = meta["view"]
    assert flat.shape == (2, vl["tot_rows"], vl["PW"])
    # all-zero input: scale falls back to 1.0, samples encode q = bias
    assert np.all(meta["scales"] == 1.0)

    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", "bogus")
    with pytest.raises(ValueError):
        prepare_stage_data(plan, batch)


@pytest.mark.parametrize("mode", ["uint6", "uint8", "uint12"])
def test_view_layout_bookkeeping(mode):
    """Stage extents tile the wire without overlap, scales cover every
    view row, and the tail slack is present for the fused kernel's
    chunked DMA over-reads."""
    from riptide_tpu.ops.ffa_kernel import DMA_CHUNK

    plan = _plan()
    vl = _view_layout(plan, mode)
    group, planes = WIRE_MODES[mode]
    PW = _view_width(plan)
    assert vl["PW"] == PW and PW % 128 == 0
    pos = 0
    for i, st in enumerate(plan.stages):
        r0 = -(-st.n // PW)
        assert vl["r0s"][i] == r0
        assert vl["prs"][i] == -(-r0 // group)
        assert vl["wrows"][i] == planes * vl["prs"][i]
        assert vl["roffs"][i] == pos
        pos += vl["wrows"][i]
    assert vl["tot_rows"] >= pos + DMA_CHUNK
    assert vl["stot"] >= sum(vl["r0s"])
    offs, lens, tot = _wire_layout(plan, mode)
    assert list(offs) == list(vl["roffs"]) and tot == vl["tot_rows"]


def test_float_modes_keep_flat_layout():
    plan = _plan()
    offs, lens, tot = _wire_layout(plan, "float32")
    assert tot == sum(st.n for st in plan.stages)
    batch = np.zeros((1, plan.size), np.float32)
    flat, meta = prepare_stage_data(plan, batch, mode="float32")
    assert flat.shape == (1, tot) and flat.dtype == np.float32
    assert meta["scales"] is None
