"""
Real-factor downsampling tests: oracle semantics (fractional boundary
weights), variance formula, and the device gather path incl. hi/lo
prefix-sum precision on long series.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from riptide_tpu.ops import reference as ref
from riptide_tpu.ops import (
    split_prefix_sums,
    downsample_gather,
    downsample_plan_padded,
    downsampled_size,
    downsampled_variance,
)


def test_oracle_basic():
    # Factor 2 on integers: plain pairwise sums
    x = np.arange(8, dtype=np.float32)
    assert np.allclose(ref.downsample(x, 2.0), [1, 5, 9, 13])
    # Fractional factor 1.5 on ones: every output sums to 1.5
    x = np.ones(9, dtype=np.float32)
    assert np.allclose(ref.downsample(x, 1.5), np.full(6, 1.5))


def test_oracle_errors():
    x = np.ones(16, dtype=np.float32)
    with pytest.raises(ValueError):
        ref.downsample(x, 1.0)
    with pytest.raises(ValueError):
        ref.downsample(x, 17.0)


def test_downsampled_size():
    assert downsampled_size(100, 4.0) == 25
    assert downsampled_size(100, 3.7) == 27


def test_downsampled_variance():
    # Fractional factor, long series: x = n*r > 1 -> variance = f - 1/3
    assert np.isclose(downsampled_variance(10000, 4.5), 4.5 - 1.0 / 3.0)
    # Integer factor: r = 0 so x = 0 -> (k-1)^2 + 1
    assert np.isclose(downsampled_variance(10000, 4.0), 9.0 + 1.0)
    assert np.isclose(downsampled_variance(16, 2.0), 1.0 + 1.0)


@pytest.mark.parametrize("f", [1.5, 2.0, 3.7, 16.3])
def test_device_matches_oracle(f):
    rng = np.random.RandomState(int(f * 10))
    x = rng.normal(size=10000).astype(np.float32)
    n = downsampled_size(x.size, f)
    hi, lo = split_prefix_sums(x)
    imin, imax, wmin, wmax, wint = downsample_plan_padded(x.size, f, n + 5)
    out = np.asarray(
        downsample_gather(
            jnp.asarray(x), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(imin), jnp.asarray(imax),
            jnp.asarray(wmin), jnp.asarray(wmax), jnp.asarray(wint),
        )
    )
    expected = ref.downsample(x, f)
    assert np.allclose(out[:n], expected, atol=1e-4)
    assert np.all(out[n:] == 0.0)


def test_device_identity_factor():
    """f == 1 must reproduce the input exactly through the same path
    (the reference aliases the buffer, riptide/cpp/periodogram.hpp:162-165)."""
    x = np.random.RandomState(0).normal(size=1000).astype(np.float32)
    hi, lo = split_prefix_sums(x)
    imin, imax, wmin, wmax, wint = downsample_plan_padded(x.size, 1.0, x.size)
    out = np.asarray(
        downsample_gather(
            jnp.asarray(x), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(imin), jnp.asarray(imax),
            jnp.asarray(wmin), jnp.asarray(wmax), jnp.asarray(wint),
        )
    )
    assert np.allclose(out, x, atol=1e-5)


def test_long_series_precision():
    """hi/lo split must keep float64-level accuracy on multi-million-sample
    series where a plain float32 prefix sum would lose catastrophically."""
    rng = np.random.RandomState(42)
    x = (rng.normal(size=2**21) + 100.0).astype(np.float32)  # large offset
    f = 16.3
    n = downsampled_size(x.size, f)
    hi, lo = split_prefix_sums(x)
    imin, imax, wmin, wmax, wint = downsample_plan_padded(x.size, f, n)
    out = np.asarray(
        downsample_gather(
            jnp.asarray(x), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(imin), jnp.asarray(imax),
            jnp.asarray(wmin), jnp.asarray(wmax), jnp.asarray(wint),
        )
    )
    expected = ref.downsample(x, f)
    assert np.allclose(out, expected, rtol=1e-5, atol=2e-3)
