"""
Signal-consumption layer tests (PR 9): the perf ledger (row schema,
append discipline, envflag fingerprint), the jax-free report module
(phase attribution, stragglers, tunnel stats, the noise-aware ledger
comparison), structured incident records (sink install, span-id
correlation, journal interop), the live /status + /healthz + 404 HTTP
surface, trace-file rotation on resume, and forward/backward journal
compatibility (pre-PR-9 journals report/resume/rtop cleanly).

The heavier end-to-end path (live scraping DURING a run, the compare
exit codes against a synthetic baseline) lives in tools/report_demo.py
(`make report-demo`); these tests keep tier-1 coverage of every piece
on tiny inputs.
"""
import json
import os
import sys
import urllib.error
import urllib.request

import pytest

from riptide_tpu.obs import ledger, prom
from riptide_tpu.obs import report as rep
from riptide_tpu.obs.chrome import export_run_trace, rotate_trace_file
from riptide_tpu.obs.schema import chunk_timing
from riptide_tpu.obs.trace import Tracer, set_tracer, span
from riptide_tpu.survey import incidents
from riptide_tpu.survey.journal import SurveyJournal, _append_line
from riptide_tpu.survey.metrics import get_metrics

from synth import generate_data_presto

TOOLS = os.path.normpath(
    os.path.join(os.path.dirname(__file__), "..", "tools"))


def _tool(name):
    """Import a tools/ CLI module (rreport / rtop) the way operators
    run them: standalone, jax-free."""
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    return __import__(name)


@pytest.fixture
def tracer():
    tr = Tracer(capacity=4096)
    prev = set_tracer(tr)
    yield tr
    set_tracer(prev)


@pytest.fixture(autouse=True)
def _no_leaked_sinks_or_providers():
    """Incident sink, status provider, last-incident, fleet source and
    alert engine are process-wide; clear them on BOTH sides of every
    test here (earlier suite files run real schedulers, which by
    design leave their status provider / fleet source / engine
    registered)."""
    from riptide_tpu.obs import alerts

    def _clear():
        incidents.set_sink(None)
        prom.set_status_provider(None)
        prom.set_fleet_source(None)
        alerts.install_engine(None)
        incidents.clear_last()

    _clear()
    yield
    _clear()


def _timing(chunk_s=2.0, wire_s=0.5, queue_s=0.1, collect_s=1.3,
            prep_s=0.4, device_s=1.2, wire_bytes=50_000_000):
    return chunk_timing(chunk_s, prep_s=prep_s, wire_s=wire_s,
                        queue_s=queue_s, device_s=device_s,
                        collect_s=collect_s, wire_bytes=wire_bytes)


# ------------------------------------------------------------------ ledger

def test_ledger_row_schema_and_roundtrip(tmp_path, monkeypatch):
    monkeypatch.delenv("RIPTIDE_LEDGER", raising=False)
    # Off by default: no path configured, no write, no error.
    assert ledger.maybe_append("bench", {"device_s": 1.0}) is None

    path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("RIPTIDE_LEDGER", path)
    dec = {"prep_s": 0.4, "wire_s": 0.5, "device_s": 1.2, "chunk_s": 2.0,
           "wire_MBps": 100.0}
    assert ledger.maybe_append(
        "survey", dec, nchunks=4, bound_counts={"device": 3, "tunnel": 1},
        extra={"survey_id": "abc"},
    ) == path
    rows = ledger.read_rows(path)
    assert len(rows) == 1
    row = rows[0]
    # Decomposition keys verbatim + provenance block.
    assert {k: row[k] for k in dec} == dec
    assert row["kind"] == "survey" and row["v"] == ledger.LEDGER_VERSION
    assert row["nchunks"] == 4
    assert row["bound_counts"] == {"device": 3, "tunnel": 1}
    assert row["survey_id"] == "abc"
    assert row["utc"].endswith("Z") and "T" in row["utc"]
    assert row["git_sha"]  # we run from a checkout
    assert isinstance(row["envflags_fingerprint"], str)
    assert "backend" in row["platform"]
    assert isinstance(row["kernel_cache_version"], int)

    # Appends accumulate; a torn tail line is dropped, not fatal.
    ledger.maybe_append("bench", dec, nchunks=1,
                        bound_counts={"device": 1})
    with open(path, "a") as fobj:
        fobj.write('{"kind": "torn')
    rows = ledger.read_rows(path)
    assert [r["kind"] for r in rows] == ["survey", "bench"]
    # The standalone reader applies the same tolerance.
    assert [r["kind"] for r in rep.read_ledger(path)] == ["survey", "bench"]


def test_envflag_fingerprint_tracks_non_defaults(monkeypatch):
    monkeypatch.delenv("RIPTIDE_TRACE_RING", raising=False)
    fp0, flags0 = ledger.envflag_fingerprint()
    assert "RIPTIDE_TRACE_RING" not in flags0
    monkeypatch.setenv("RIPTIDE_TRACE_RING", "123")
    fp1, flags1 = ledger.envflag_fingerprint()
    assert flags1["RIPTIDE_TRACE_RING"] == 123
    assert fp1 != fp0
    # An unparsable value is recorded, never raised.
    monkeypatch.setenv("RIPTIDE_TRACE_RING", "not-an-int")
    _, flags2 = ledger.envflag_fingerprint()
    assert "unparsable" in str(flags2["RIPTIDE_TRACE_RING"])


def test_envflag_fingerprint_ignores_recording_flags(monkeypatch):
    """RIPTIDE_LEDGER is non-default in EVERY row (rows only exist
    while it is set): recording-only flags must not make two
    perf-identical runs fingerprint as different regimes."""
    monkeypatch.delenv("RIPTIDE_TRACE_RING", raising=False)
    for name in ledger.FINGERPRINT_EXCLUDE:
        monkeypatch.delenv(name, raising=False)
    fp0, _ = ledger.envflag_fingerprint()
    monkeypatch.setenv("RIPTIDE_LEDGER", "/somewhere/else.jsonl")
    monkeypatch.setenv("RIPTIDE_PROM_PORT", "9109")
    monkeypatch.setenv("RIPTIDE_STATUS_STALE_S", "5")
    fp1, flags = ledger.envflag_fingerprint()
    assert fp1 == fp0
    assert not set(flags) & ledger.FINGERPRINT_EXCLUDE


# ------------------------------------------------------------------ report

def test_read_journal_families_and_last_record_wins(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("sid", 3)
    j.record_chunk(0, ["a.inf"], [0.0], [], timings=_timing(),
                   attempts=1)
    # Chunk 1 parked first, then completed on a later attempt: the
    # completion must erase the park for every reader.
    j.record_parked(1, "circuit open", files=["b.inf"])
    j.record_chunk(1, ["b.inf"], [5.0], [], timings=_timing(3.0),
                   attempts=2)
    j.record_parked(2, "dispatch failed", files=["c.inf"])
    j.record_incident({"incident": "breaker_open", "detail": {"x": 1}})
    j.record_metrics({"chunks_done": 2})
    # A retried chunk's final journaling wins.
    j.record_chunk(0, ["a.inf"], [0.0], [], timings=_timing(9.0),
                   attempts=3)

    doc = rep.read_journal(str(tmp_path / "j"))
    assert doc["header"]["survey_id"] == "sid"
    assert sorted(doc["chunks"]) == [0, 1]
    assert doc["chunks"][0]["attempts"] == 3
    assert list(doc["parked"]) == [2]
    assert doc["parked"][2]["reason"] == "dispatch failed"
    assert [i["incident"] for i in doc["incidents"]] == ["breaker_open"]
    assert doc["metrics"] == {"chunks_done": 2}
    # The journal's own reader agrees.
    assert [i["incident"] for i in j.incidents()] == ["breaker_open"]


def test_phase_attribution_sums_and_flags_violations():
    good = {cid: {"timings": _timing(2.0)} for cid in range(3)}
    rows, violations = rep.phase_attribution(good)
    assert not violations
    # chunk_timing constructs host_s as the serial remainder, so the
    # serial rows reconstruct total wall-clock exactly.
    serial_total = sum(t for p, t, _ in rows if p in rep.SERIAL_PHASES)
    assert serial_total == pytest.approx(6.0, rel=1e-6)
    assert rows[-1][0] == "prep (overlapped)" and rows[-1][2] is None

    bad = dict(good)
    broken = dict(_timing(2.0), collect_s=0.0)  # no longer sums
    bad[9] = {"timings": broken}
    _, violations = rep.phase_attribution(bad)
    assert [v["chunk_id"] for v in violations] == [9]


def test_stragglers_and_tunnel_stats():
    chunks = {cid: {"timings": _timing(1.0, collect_s=0.3)}
              for cid in range(5)}
    chunks[7] = {"timings": _timing(10.0, collect_s=9.3)}
    out = rep.stragglers(chunks)
    assert [cid for cid, _, _ in out] == [7]
    assert out[0][2] > 5

    tun = rep.tunnel_stats(chunks)
    assert tun["n_rates"] == 6
    assert tun["bound_counts"]["device"] == 6
    assert tun["wire_MBps_min"] <= tun["wire_MBps_median"] \
        <= tun["wire_MBps_max"]
    assert tun["chunks_below_knee"] == 0


def test_compare_to_ledger_verdicts():
    def row(dev_per_chunk, bound="device", n=4):
        return {"device_s": dev_per_chunk * n, "nchunks": n,
                "bound_counts": {bound: n}}

    base = [row(1.0), row(1.1), row(0.9), row(50.0, bound="tunnel")]

    v, rc = rep.compare_to_ledger(row(1.0), base)
    assert rc == 0 and v["verdict"] == "ok"
    # Tunnel-weather history is excluded from the baseline.
    assert v["baseline_n"] == 3 and v["excluded_tunnel_rows"] == 1
    assert v["baseline_median"] == pytest.approx(1.0)
    assert v["threshold"] == pytest.approx(
        1.0 * 1.15 + 3.0 * 0.1)  # median*(1+tol) + k*MAD

    v, rc = rep.compare_to_ledger(row(4.0), base)
    assert rc == 1 and v["verdict"] == "regression"
    assert v["ratio"] == pytest.approx(4.0)

    # A tunnel-bound current run is never judged on device time.
    v, rc = rep.compare_to_ledger(row(4.0, bound="tunnel"), base)
    assert rc == 0 and v["verdict"] == "skipped-tunnel"
    # No usable history -> no verdict, exit 0.
    v, rc = rep.compare_to_ledger(row(1.0), [row(1.0, bound="tunnel")])
    assert rc == 0 and v["verdict"] == "no-baseline"
    v, rc = rep.compare_to_ledger({"nchunks": 4}, base)
    assert rc == 0 and v["verdict"] == "no-data"


def test_compare_scopes_baseline_by_kind_and_platform():
    """A shared ledger mixes kinds and platforms; rows of the wrong
    kind or platform must never enter the baseline (a cpu smoke row
    cannot baseline a TPU regression check)."""
    def row(dev, kind="survey", backend="tpu", device_kind="TPU v4"):
        return {"kind": kind, "device_s": dev * 4, "nchunks": 4,
                "bound_counts": {"device": 4},
                "platform": {"backend": backend,
                             "device_kind": device_kind}}

    tpu = {"backend": "tpu", "device_kind": "TPU v4"}
    # History: comparable TPU survey rows at ~1 s/chunk, plus a bench
    # row and 100x-slower cpu rows that would wreck the band.
    rows = [row(1.0), row(1.1), row(0.9),
            row(5.0, kind="bench"),
            row(100.0, backend="cpu", device_kind="cpu"),
            row(110.0, backend="cpu", device_kind="cpu")]

    # Unscoped, the cpu rows inflate the median and a 4x regression
    # sails through — the failure mode the scoping exists to prevent.
    v, rc = rep.compare_to_ledger(row(4.0), rows)
    assert rc == 0 and v["verdict"] == "ok"
    v, rc = rep.compare_to_ledger(row(4.0), rows, kind="survey",
                                  platform=tpu)
    assert rc == 1 and v["verdict"] == "regression"
    assert v["baseline_n"] == 3 and v["excluded_scope_rows"] == 3

    # latest_platform: newest row carrying a platform, per kind.
    assert rep.latest_platform(rows) == {"backend": "cpu",
                                         "device_kind": "cpu"}
    assert rep.latest_platform(rows, kind="bench") == tpu
    assert rep.latest_platform([{"kind": "survey"}]) is None


def test_drop_own_row_drops_only_newest_match():
    """The run's just-appended row leaves the baseline, but a nightly
    re-run of the SAME survey (same survey_id) keeps all its history."""
    rows = [{"survey_id": "s", "device_s": 1.0},
            {"survey_id": "other", "device_s": 2.0},
            {"survey_id": "s", "device_s": 3.0}]
    kept, dropped = rep.drop_own_row(rows, "s")
    assert dropped
    assert [r["device_s"] for r in kept] == [1.0, 2.0]
    kept, dropped = rep.drop_own_row(rows, "absent")
    assert not dropped and len(kept) == 3
    kept, dropped = rep.drop_own_row(rows, None)
    assert not dropped and len(kept) == 3


def test_run_decomposition_matches_scheduler_derivation():
    timings = [_timing(2.0), _timing(4.0)]
    run, n, bounds = rep.run_decomposition_from_chunks(timings)
    assert n == 2 and bounds == {"device": 2}
    assert run["chunk_s"] == pytest.approx(3.0)
    assert run["wire_s"] == pytest.approx(1.0)
    # Empty and None-holed inputs stay well-defined.
    run0, n0, b0 = rep.run_decomposition_from_chunks([None, {}])
    assert n0 == 0 and b0 == {} and run0["wire_MBps"] is None


def test_journal_follower_incremental_and_torn_tail(tmp_path):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("sid", 3)
    j.record_chunk(0, ["a.inf"], [0.0], [], timings=_timing())

    follower = rep.JournalFollower(str(tmp_path / "j"))
    doc = follower.poll()
    assert sorted(doc["chunks"]) == [0]

    # Appends between polls are folded incrementally; a torn tail line
    # (a writer killed mid-append) is invisible until completed.
    j.record_incident({"incident": "breaker_open"})
    with open(j.journal_path, "a") as fobj:
        fobj.write('{"kind": "chunk", "chunk_id": 1')
    doc = follower.poll()
    assert sorted(doc["chunks"]) == [0]
    assert [i["incident"] for i in doc["incidents"]] == ["breaker_open"]
    with open(j.journal_path, "a") as fobj:
        fobj.write(', "attempts": 1}\n')
    doc = follower.poll()
    assert sorted(doc["chunks"]) == [0, 1]
    # Idempotent when nothing new arrived (no re-reading, no dupes).
    doc = follower.poll()
    assert len(doc["incidents"]) == 1

    # The one-shot reader agrees with the followed state.
    assert rep.read_journal(str(tmp_path / "j"))["chunks"].keys() \
        == doc["chunks"].keys()

    # A replaced (shrunken) journal resets the follower.
    with open(j.journal_path, "w") as fobj:
        fobj.write('{"kind": "header", "survey_id": "new"}\n')
    doc = follower.poll()
    assert doc["header"]["survey_id"] == "new" and not doc["chunks"]


# --------------------------------------------------------------- incidents

def test_incident_emit_without_sink_counts_and_retains():
    get_metrics().reset()
    rec = incidents.emit("quarantine", chunk_id=3, fname="x.inf",
                        masked_frac=0.5, reasons=("nan", "clip"))
    assert rec["kind"] == "incident"
    assert rec["incident"] == "quarantine"
    assert rec["chunk_id"] == 3
    assert rec["utc"].endswith("Z")
    assert "span_id" not in rec  # tracing disabled suite-wide
    # Detail values are JSON-safe (the tuple became a list).
    assert rec["detail"]["reasons"] == ["nan", "clip"]
    assert json.dumps(rec)
    assert incidents.last_incident() is rec
    assert get_metrics().snapshot()["counters"]["incidents"] == 1
    # A fresh run clears the retained incident (the scheduler calls
    # this at run start, so /status never shows a previous run's).
    incidents.clear_last()
    assert incidents.last_incident() is None


def test_incident_sink_journal_and_span_id(tmp_path, tracer):
    j = SurveyJournal(tmp_path / "j")
    j.write_header("sid", 1)
    prev = incidents.set_sink(j.record_incident)
    try:
        with span("dispatch", chunk=0):
            rec = incidents.emit("watchdog_timeout", chunk_id=0,
                                 budget_s=1.5)
    finally:
        incidents.set_sink(prev)
    # The incident carries the id of the span open when it fired, and
    # the exported trace labels that span with the same id.
    assert isinstance(rec["span_id"], int)
    (_, _, _, _, _, sid), = tracer.events()
    assert rec["span_id"] == sid
    stored, = j.incidents()
    assert stored["incident"] == "watchdog_timeout"
    assert stored["span_id"] == sid
    # Incident lines are invisible to the resume reader.
    assert SurveyJournal(tmp_path / "j").completed_chunks() == {}

    # A failing sink is logged, never raised.
    incidents.set_sink(lambda rec: (_ for _ in ()).throw(OSError("disk")))
    try:
        incidents.emit("breaker_open")
    finally:
        incidents.set_sink(None)


# ------------------------------------------------- /status + /healthz + 404

def test_status_snapshot_and_health_check(monkeypatch):
    prom.set_status_provider(None)
    assert prom.status_snapshot() == {"active": False}
    ok, problems = prom.health_check()
    assert ok and not problems  # no survey running != unhealthy

    prom.set_status_provider(lambda: {"breaker": "open",
                                      "chunks_done": 1})
    snap = prom.status_snapshot()
    assert snap["active"] is True and snap["chunks_done"] == 1
    ok, problems = prom.health_check()
    assert not ok and problems == ["circuit breaker open"]

    monkeypatch.setenv("RIPTIDE_STATUS_STALE_S", "10")
    prom.set_status_provider(
        lambda: {"heartbeat_age_s": {"0": 999.0, "1": 3.0}})
    # The FRESHEST beat decides: one live process keeps the run alive.
    ok, _ = prom.health_check()
    assert ok
    prom.set_status_provider(lambda: {"heartbeat_age_s": {"0": 999.0}})
    ok, problems = prom.health_check()
    assert not ok and "stale heartbeat" in problems[0]

    # A FINISHED run (running=false) is healthy whatever its final
    # breaker state or heartbeat ages: the probe answers "is the run
    # wedged", and a supervisor must never kill an idle process over a
    # completed run's aging beats.
    prom.set_status_provider(
        lambda: {"running": False, "breaker": "open",
                 "heartbeat_age_s": {"0": 999.0}})
    ok, problems = prom.health_check()
    assert ok and not problems


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def test_http_status_healthz_and_404(monkeypatch):
    get_metrics().reset()
    server = prom.serve(0)
    base = f"http://127.0.0.1:{server.port}"
    try:
        code, body = _get(f"{base}/status")
        assert code == 200 and json.loads(body) == {"active": False}
        code, body = _get(f"{base}/healthz")
        assert code == 200 and json.loads(body)["ok"] is True

        prom.set_status_provider(lambda: {
            "survey_id": "sid", "chunks_done": 1, "breaker": "open"})
        code, body = _get(f"{base}/status")
        doc = json.loads(body)
        assert code == 200 and doc["active"] and doc["chunks_done"] == 1
        code, body = _get(f"{base}/healthz")
        doc = json.loads(body)
        assert code == 503
        assert doc["ok"] is False
        assert "circuit breaker open" in doc["problems"]

        # Unknown paths: 404 whose body names every valid endpoint.
        code, body = _get(f"{base}/metricz")
        assert code == 404
        for endpoint in prom.ENDPOINTS:
            assert endpoint in body
    finally:
        server.close()


# -------------------------------------------------------- trace rotation

def test_rotate_trace_file_bounded_depth(tmp_path):
    path = str(tmp_path / "trace.json")
    for gen in range(5):
        with open(path, "w") as fobj:
            fobj.write(f"gen{gen}")
        rotate_trace_file(path)
        assert not os.path.exists(path)
    # Newest prior at .1, bounded at depth 3: gen0/gen1 fell off.
    kept = {i: open(f"{path}.{i}").read() for i in (1, 2, 3)}
    assert kept == {1: "gen4", 2: "gen3", 3: "gen2"}
    assert not os.path.exists(f"{path}.4")
    rotate_trace_file(str(tmp_path / "absent.json"))  # no-op


def test_export_rotates_for_fresh_tracer_only(tmp_path, tracer):
    with span("first"):
        pass
    path = os.path.join(str(tmp_path), "trace.json")
    export_run_trace(str(tmp_path))
    # Same-run re-export (scheduler end-of-search, then rffa post-stage)
    # overwrites in place: no rotation.
    export_run_trace(str(tmp_path))
    assert os.path.exists(path) and not os.path.exists(path + ".1")

    # A fresh tracer (a resumed run in a new process) rotates first.
    fresh = Tracer(capacity=64)
    prev = set_tracer(fresh)
    try:
        with span("second"):
            pass
        export_run_trace(str(tmp_path))
    finally:
        set_tracer(prev)
    names = lambda p: {e["name"] for e in json.load(open(p))["traceEvents"]
                       if e["ph"] == "X"}
    assert names(path) == {"second"}
    assert names(path + ".1") == {"first"}


# -------------------------------------- survey e2e: resume, status, ledger

TOBS, TSAMP, PERIOD = 16.0, 1e-3, 0.5

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def _searcher():
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=1)


def test_survey_resume_preserves_prior_trace_and_ledgers(tmp_path,
                                                         monkeypatch):
    """The satellite fix end-to-end: attempt 1 of a journaled survey
    exports trace.json; a resumed attempt (fresh process = fresh
    tracer) must rotate it to trace.json.1 — BOTH files survive — and
    the completed run appends a ledger row + a live status document."""
    from riptide_tpu.survey.scheduler import SurveyScheduler

    f1 = generate_data_presto(str(tmp_path), "a_DM0.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=0.0)
    f2 = generate_data_presto(str(tmp_path), "b_DM5.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=5.0)
    jdir = str(tmp_path / "j")
    ledger_path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("RIPTIDE_LEDGER", ledger_path)
    trace_path = os.path.join(jdir, "trace.json")

    # Attempt 1 (its own tracer, standing in for its own process).
    tr1 = Tracer(capacity=4096)
    prev = set_tracer(tr1)
    try:
        get_metrics().reset()
        SurveyScheduler(_searcher(), [[f1], [f2]],
                        journal=SurveyJournal(jdir)).run()
    finally:
        set_tracer(prev)
    assert os.path.exists(trace_path)
    assert not os.path.exists(trace_path + ".1")

    # Resume in a "fresh process": prior trace must survive rotation.
    tr2 = Tracer(capacity=4096)
    prev = set_tracer(tr2)
    try:
        get_metrics().reset()
        sched = SurveyScheduler(_searcher(), [[f1], [f2]],
                                journal=SurveyJournal(jdir), resume=True)
        peaks = sched.run()
    finally:
        set_tracer(prev)
    assert peaks
    assert os.path.exists(trace_path)
    assert os.path.exists(trace_path + ".1")
    with open(trace_path + ".1") as fobj:
        prior = json.load(fobj)
    # The rotated file is attempt 1's full trace (real dispatch spans).
    assert any(e.get("name") == "dispatch"
               for e in prior["traceEvents"])

    # Status document of the finished run.
    st = sched.status()
    assert st["chunks_total"] == 2
    assert st["chunks_done"] == 2 and st["chunks_parked"] == 0
    assert st["chunk_in_flight"] is None
    assert st["breaker"] is None and st["last_incident"] is None
    assert st["heartbeat_age_s"]  # single-process journaled runs beat
    assert os.path.exists(os.path.join(jdir, "heartbeat_0000.jsonl"))
    # The finished run stays healthy however stale its (legitimately
    # stopped) heartbeats get.
    assert st["running"] is False
    ok, problems = prom.health_check(st, stale_s=1e-9)
    assert ok and not problems

    # Ledger: attempt 1 recorded both chunks; the resume run replayed
    # them (no fresh timings), so exactly one survey row exists — and
    # rreport --compare against it exits 0 (a run equals its own row).
    rows = ledger.read_rows(ledger_path)
    assert len(rows) == 1
    assert rows[0]["kind"] == "survey" and rows[0]["nchunks"] == 2
    assert sum(rows[0]["bound_counts"].values()) == 2
    rreport = _tool("rreport")
    assert rreport.main([jdir, "--quiet"]) == 0
    assert rreport.main([jdir, "--compare", ledger_path, "--quiet"]) == 0


# ---------------------------------------------- pre-PR-9 journal compat

def _write_pre_pr9_journal(tmp_path):
    """A journal as PR <= 7 code wrote it: chunk records without utc,
    timings, dq or incident lines (and no heartbeat sidecars)."""
    j = SurveyJournal(tmp_path / "old")
    _append_line(j.journal_path, {
        "kind": "header", "version": 1, "survey_id": "oldsurvey",
        "chunks_total": 2,
    })
    for cid in range(2):
        _append_line(j.journal_path, {
            "kind": "chunk", "chunk_id": cid, "files": [f"{cid}.inf"],
            "dms": [float(cid)], "wire_digest": None,
            "peaks_offset": 0, "peaks_count": 0, "attempts": 1,
        })
    return str(tmp_path / "old")


def test_pre_pr9_journal_resumes_reports_and_rtops(tmp_path, capsys):
    jdir = _write_pre_pr9_journal(tmp_path)

    # Resume reader: both chunks count as completed, nothing raises.
    done = SurveyJournal(jdir).completed_chunks()
    assert sorted(done) == [0, 1]
    assert SurveyJournal(jdir).incidents() == []

    # Report: empty timings/incidents degrade to zero rows, not errors.
    report = rep.build_report(jdir)
    assert report["chunks_done"] == 2 and report["incidents"] == []
    assert not report["phase_sum_violations"]
    assert report["run"]["nchunks"] == 0  # no timing blocks to reduce
    text = rep.render_text(report)
    assert "oldsurvey" in text

    # The CLIs over the same directory: rreport exits 0, rtop renders.
    rreport, rtop = _tool("rreport"), _tool("rtop")
    assert rreport.main([jdir, "--quiet"]) == 0
    frame = rtop.render_frame(rreport.load_report_module(), jdir)
    assert "chunks 2/2" in frame and "incidents" not in frame
    capsys.readouterr()


def test_rreport_cli_errors_and_json(tmp_path):
    rreport = _tool("rreport")
    # No journal: usage error, exit 2.
    assert rreport.main([str(tmp_path / "nope"), "--quiet"]) == 2
    assert rreport.main([_write_pre_pr9_journal(tmp_path), "--quiet",
                         "--compare", str(tmp_path / "missing.jsonl")]) == 2

    # A journal whose phases cannot reconstruct chunk_s exits 1.
    j = SurveyJournal(tmp_path / "broken")
    j.write_header("sid", 1)
    bad = dict(_timing(4.0), collect_s=0.0)
    j.record_chunk(0, ["a.inf"], [0.0], [], timings=bad)
    out_json = str(tmp_path / "report.json")
    assert rreport.main([str(tmp_path / "broken"), "--quiet",
                         "--json", out_json]) == 1
    with open(out_json) as fobj:
        doc = json.load(fobj)
    assert doc["phase_sum_violations"]
