"""
Sequence-parallel FFA tests on the virtual 8-device CPU mesh: the
row-sharded transform must be bit-compatible with the single-device
ffa2 (itself validated against the golden 8x8 oracle of
riptide/tests/test_ffa_base_functions.py).
"""
import numpy as np
import pytest

import jax

from riptide_tpu.ops.ffa import ffa2
from riptide_tpu.parallel.seqffa import ffa2_seq, seq_mesh


def _mesh(n):
    return seq_mesh(jax.devices()[:n])


@pytest.mark.parametrize("S", [2, 4, 8])
@pytest.mark.parametrize("m_local", [1, 3, 4, 6])
def test_seq_matches_single_device(S, m_local):
    m = S * m_local
    p = 40
    rng = np.random.RandomState(m)
    data = rng.normal(size=(m, p)).astype(np.float32)
    ref = ffa2(data)
    out = ffa2_seq(data, mesh=_mesh(S))
    assert out.shape == (m, p)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-5)


def test_seq_single_shard_falls_back():
    rng = np.random.RandomState(0)
    data = rng.normal(size=(8, 16)).astype(np.float32)
    out = ffa2_seq(data, mesh=_mesh(1))
    np.testing.assert_allclose(out, ffa2(data), rtol=1e-6)


def test_seq_pulse_recovery():
    """A dispersed pulse train folded across 8 shards still peaks at the
    right phase drift."""
    m, p = 64, 128
    data = np.zeros((m, p), np.float32)
    for i in range(m):
        data[i, (3 + i) % p] = 1.0  # drift of exactly 1 bin per period
    out = ffa2_seq(data, mesh=_mesh(8))
    # The shift-(m-1) trial row realigns all pulses into one phase bin.
    assert out[m - 1].max() == pytest.approx(m)


@pytest.mark.parametrize("m_local", [8, 12, 13, 128])
def test_seq_windowed_ppermute_matches(m_local):
    """The S >= 8 production path (windowed ppermute exchange instead of
    per-level all_gather) is bit-compatible with ffa2; covers
    power-of-2 and non-power-of-2 local row counts."""
    from riptide_tpu.parallel.seqffa import _window_plan

    S = 8
    m = S * m_local
    assert _window_plan(m, S) is not None, "expected the windowed path"
    rng = np.random.RandomState(m)
    data = rng.normal(size=(m, 33)).astype(np.float32)
    out = ffa2_seq(data, mesh=_mesh(S))
    np.testing.assert_allclose(out, ffa2(data), rtol=1e-6, atol=1e-5)


def test_seq_window_plan_bounds():
    """Every window the plan emits spans at most two source shards, and
    the receive-buffer-local ids stay inside the 4*m_local+1 buffer."""
    from riptide_tpu.parallel.seqffa import _window_plan

    for m, S in ((64, 8), (96, 8), (1024, 8), (104, 8)):
        m_local = m // S
        levels = _window_plan(m, S)
        assert levels is not None
        for perms, hloc, tloc, _ in levels:
            assert perms.min() >= 0 and perms.max() < S
            for loc in (hloc, tloc):
                assert loc.min() >= 0
                assert loc.max() <= 4 * m_local


def test_seq_errors():
    data = np.zeros((10, 8), np.float32)
    with pytest.raises(ValueError, match="divisible"):
        ffa2_seq(data, mesh=_mesh(4))
    with pytest.raises(ValueError, match="two-dimensional"):
        ffa2_seq(np.zeros(8, np.float32), mesh=_mesh(2))
