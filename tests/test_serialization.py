"""
JSON serialization round-trip tests (reference contract:
riptide/serialization.py — ndarray as base64, DataFrame as
values+columns, SkyCoord as degrees, to_dict()-able objects tagged with
__type__/__version__).
"""
import json

import numpy as np
import pandas
import pytest

import riptide_tpu
from riptide_tpu import Metadata, TimeSeries, load_json, save_json
from riptide_tpu.serialization import from_json, to_json
from riptide_tpu.utils.coords import SkyCoord


@pytest.mark.parametrize(
    "arr",
    [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.arange(5, dtype=np.int64),
        np.array([], dtype=np.float64),
        np.random.RandomState(0).normal(size=(2, 3, 4)),
    ],
)
def test_ndarray_roundtrip(arr):
    out = from_json(to_json(arr))
    assert isinstance(out, np.ndarray)
    assert out.dtype == arr.dtype
    assert out.shape == arr.shape
    assert np.array_equal(out, arr)


def test_ndarray_decoded_copy_is_writable():
    out = from_json(to_json(np.arange(4)))
    out[0] = 99  # frombuffer alone would be read-only


def test_numpy_scalars_to_plain_python():
    s = to_json({"a": np.int32(7), "b": np.float64(2.5), "c": np.float32(1.5)})
    out = json.loads(s)
    assert out == {"a": 7, "b": 2.5, "c": 1.5}


def test_dataframe_roundtrip():
    df = pandas.DataFrame(
        {"period": [1.0, 2.0], "snr": [10.0, 20.0], "width": [3.0, 4.0]}
    )
    out = from_json(to_json(df))
    assert isinstance(out, pandas.DataFrame)
    assert list(out.columns) == ["period", "snr", "width"]
    assert np.allclose(out.values, df.values)


def test_skycoord_roundtrip():
    c = SkyCoord(123.456, -54.321)
    out = from_json(to_json(c))
    assert isinstance(out, SkyCoord)
    assert out.ra_deg == pytest.approx(123.456)
    assert out.dec_deg == pytest.approx(-54.321)


def test_reference_astropy_skycoord_tag_accepted():
    # Files written by the reference tag SkyCoord as 'astropy.SkyCoord';
    # they must load here.
    s = json.dumps({"__type__": "astropy.SkyCoord", "rajd": 10.0, "decjd": -5.0})
    out = from_json(s)
    assert isinstance(out, SkyCoord)
    assert out.ra_deg == 10.0


def test_tagged_object_roundtrip_with_version(tmp_path):
    meta = Metadata({"source_name": "J0000+0000", "dm": 12.5})
    ts = TimeSeries(np.arange(16, dtype=np.float32), 6.4e-5, metadata=meta)
    fname = tmp_path / "ts.json"
    save_json(fname, ts)
    out = load_json(fname)
    assert isinstance(out, TimeSeries)
    assert np.array_equal(out.data, ts.data)
    assert out.tsamp == ts.tsamp
    assert out.metadata["dm"] == 12.5
    # __version__ is embedded and restored
    raw = json.loads(fname.read_text())
    assert raw["__type__"] == "TimeSeries"
    assert raw["__version__"] == riptide_tpu.__version__
    assert out.version == riptide_tpu.__version__


def test_nested_containers():
    obj = {"xs": [np.arange(3), {"y": np.float32(2.0)}], "n": 5}
    out = from_json(to_json(obj))
    assert np.array_equal(out["xs"][0], np.arange(3))
    assert out["xs"][1]["y"] == 2.0
    assert out["n"] == 5


def test_unencodable_type_raises():
    with pytest.raises(TypeError):
        to_json({"f": lambda: None})
