"""
End-to-end tests of the rseek CLI on deterministic synthetic data
(reference: riptide/tests/test_rseek.py — the top candidate of the seeded
fake pulsar must come out at S/N 18.5 +/- 0.15, width 13, dm 0, freq
within 0.1/Tobs of 1 Hz; a pure-noise input must return None).
"""
import numpy as np
import pytest

from riptide_tpu.apps.rseek import get_parser, run_program

from synth import generate_data_presto, write_sigproc

TOBS = 128.0
TSAMP = 256e-6
PERIOD = 1.0


def _run(fname, fmt, extra=()):
    args = get_parser().parse_args(
        ["-f", fmt, "--Pmin", "0.5", "--Pmax", "2.0",
         "--bmin", "480", "--bmax", "520", *extra, str(fname)]
    )
    return run_program(args)


def test_rseek_finds_fake_pulsar(tmp_path, capsys):
    inf = generate_data_presto(
        tmp_path, "fake_pulsar", tobs=TOBS, tsamp=TSAMP, period=PERIOD,
        dm=0.0, amplitude=20.0, ducy=0.02,
    )
    df = _run(inf, "presto")
    assert df is not None
    top = df.iloc[0]
    assert abs(top["freq"] - 1.0 / PERIOD) < 0.1 / TOBS
    assert int(top["width"]) == 13
    assert top["dm"] == 0.0
    assert abs(top["snr"] - 18.5) < 0.15
    # The peak table is printed for the user
    out = capsys.readouterr().out
    assert "period" in out and "snr" in out


def test_rseek_sigproc_input(tmp_path):
    np.random.seed(0)
    from riptide_tpu import TimeSeries

    ts = TimeSeries.generate(TOBS, TSAMP, PERIOD, amplitude=20.0, ducy=0.02, stdnoise=1.0)
    fname = tmp_path / "fake_pulsar.tim"
    write_sigproc(fname, ts.data, TSAMP, nbits=32, refdm=0.0)
    df = _run(fname, "sigproc")
    assert df is not None
    top = df.iloc[0]
    assert abs(top["freq"] - 1.0 / PERIOD) < 0.1 / TOBS
    assert abs(top["snr"] - 18.5) < 0.15


@pytest.mark.parametrize("signed", [False, True])
def test_rseek_sigproc_8bit_input(tmp_path, signed):
    """End-to-end search of 8-bit SIGPROC data (both signednesses): the
    digitised fake pulsar must still come out on top at the oracle S/N
    (8-bit digitisation at 1/16 sigma steps costs ~0.01 in S/N).
    Mirrors the reference's 8-bit fixture coverage
    (riptide/tests/test_time_series.py + data/README.md) at search
    depth."""
    np.random.seed(0)
    from riptide_tpu import TimeSeries

    ts = TimeSeries.generate(TOBS, TSAMP, PERIOD, amplitude=20.0,
                             ducy=0.02, stdnoise=1.0)
    q = np.rint(ts.data * 16.0)
    if signed:
        q = np.clip(q, -128, 127).astype(np.int8)
    else:
        q = np.clip(q + 128.0, 0, 255).astype(np.uint8)
    fname = tmp_path / ("i8.tim" if signed else "u8.tim")
    write_sigproc(fname, q, TSAMP, nbits=8, signed=signed, refdm=0.0)
    df = _run(fname, "sigproc")
    assert df is not None
    top = df.iloc[0]
    assert abs(top["freq"] - 1.0 / PERIOD) < 0.1 / TOBS
    assert int(top["width"]) == 13
    assert abs(top["snr"] - 18.5) < 0.15


def test_rseek_pure_noise_returns_none(tmp_path, capsys):
    np.random.seed(42)
    noise = np.random.normal(size=int(32.0 / 1e-3)).astype(np.float32)
    from synth import write_presto

    inf = write_presto(tmp_path, "noise", noise, 1e-3)
    args = get_parser().parse_args(
        ["-f", "presto", "--Pmin", "1.0", "--Pmax", "2.0",
         "--bmin", "240", "--bmax", "260", str(inf)]
    )
    assert run_program(args) is None
    assert "No peaks found" in capsys.readouterr().out


def test_rseek_plan_stats(tmp_path, capsys):
    """--plan-stats prints the container-occupancy accounting as JSON
    and exits without searching."""
    import json

    inf = generate_data_presto(
        tmp_path, "plan_stats", tobs=TOBS, tsamp=TSAMP, period=PERIOD,
        dm=0.0, amplitude=20.0, ducy=0.02,
    )
    assert _run(inf, "presto", extra=("--plan-stats",)) is None
    out = capsys.readouterr().out
    occ = json.loads(out[out.index("{"):])
    t = occ["totals"]
    assert t["computed_rowlane"] - t["live_rowlane"] == \
        t["padded_rowlane"] >= 0
    assert occ["buckets"] and "padded_reduction_vs_legacy" in t


def test_rseek_parser_defaults():
    args = get_parser().parse_args(["-f", "presto", "x.inf"])
    assert args.Pmin == 1.0 and args.Pmax == 10.0
    assert args.bmin == 240 and args.bmax == 260
    assert args.smin == 7.0 and args.wtsp == 1.5
    assert args.rmed_width == 4.0 and args.clrad == 0.2
