"""
Observability subsystem tests (riptide_tpu/obs/): span tracer
thread-safety and ring bounds, the disabled-mode zero-allocation fast
path, Chrome trace-event export validity and multi-process merge,
Prometheus text-format exposition (and its histogram/counter
consistency), the shared timing-key schema, and the journal `timing`
block through a real kill-and-resume survey.
"""
import gc
import json
import os
import sys
import threading
import urllib.request

import pytest

from riptide_tpu.obs import prom
from riptide_tpu.obs.chrome import (
    export_run_trace, merge_chrome_traces, write_chrome_trace,
)
from riptide_tpu.obs.schema import (
    CHUNK_TIMING_KEYS, DECOMPOSITION_KEYS, chunk_timing, classify_bound,
    decomposition,
)
from riptide_tpu.obs.trace import NULL_SPAN, Tracer, set_tracer, span
from riptide_tpu.survey.metrics import MetricsRegistry, get_metrics

from synth import generate_data_presto


@pytest.fixture
def tracer():
    """Install a fresh tracer for the test; restore the previous (in
    the default suite: no) tracer afterwards, so the disabled fast path
    stays the suite-wide norm."""
    tr = Tracer(capacity=4096)
    prev = set_tracer(tr)
    yield tr
    set_tracer(prev)


@pytest.fixture(autouse=True)
def _no_leaked_fleet_or_engine():
    """Fleet source and alert engine are process-wide (earlier suite
    files run real schedulers, which by design leave theirs
    registered); clear both sides so the exposition tests here see
    only what they install."""
    from riptide_tpu.obs import alerts

    def _clear():
        prom.set_fleet_source(None)
        alerts.install_engine(None)

    _clear()
    yield
    _clear()


# ------------------------------------------------------------- tracer

def test_span_records_nests_and_inherits_chunk(tracer):
    with span("stage", chunk=7):
        with span("prep", mode="float32"):
            pass
    events = tracer.events()
    assert [e[0] for e in events] == ["prep", "stage"]  # completion order
    prep, stage = events
    assert prep[4]["chunk"] == 7          # inherited from parent span
    assert prep[4]["mode"] == "float32"
    assert stage[4] == {"chunk": 7}
    assert all(e[1] >= 0.0 and e[2] >= 0.0 for e in events)


def test_span_set_and_error_attrs(tracer):
    with pytest.raises(ValueError):
        with span("work", chunk=1) as s:
            s.set(files=3)
            raise ValueError("boom")
    (name, _, _, _, attrs, sid), = tracer.events()
    assert isinstance(sid, int) and sid >= 1
    assert name == "work"
    assert attrs["files"] == 3
    assert attrs["error"] == "ValueError"


def test_tracer_thread_safety():
    tr = Tracer(capacity=10_000)
    prev = set_tracer(tr)
    try:
        def worker(k):
            for i in range(200):
                with span("phase", worker=k):
                    pass

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        set_tracer(prev)
    assert tr.recorded == 8 * 200
    assert tr.dropped_events == 0
    events = tr.events()
    assert len(events) == 8 * 200
    # No cross-thread interleaving corrupted the record: every worker's
    # 200 spans all arrived, each on a single thread lane. (Thread ids
    # may be REUSED across joined threads, so lanes can coincide; what
    # must hold is one lane per worker and a complete count.)
    by_worker = {}
    for _, _, _, tid, attrs, _ in events:
        by_worker.setdefault(attrs["worker"], []).append(tid)
    assert set(by_worker) == set(range(8))
    for tids in by_worker.values():
        assert len(tids) == 200
        assert len(set(tids)) == 1


def test_ring_buffer_bounded():
    tr = Tracer(capacity=16)
    prev = set_tracer(tr)
    try:
        for i in range(100):
            with span("s", i=i):
                pass
    finally:
        set_tracer(prev)
    events = tr.events()
    assert len(events) == 16
    assert tr.recorded == 100
    assert tr.dropped_events == 84
    # The ring keeps the NEWEST spans.
    assert [e[4]["i"] for e in events] == list(range(84, 100))


def test_disabled_span_fast_path():
    """With no tracer installed, span() must return the shared no-op
    singleton and retain NOTHING: zero net allocations across 200k
    disabled spans (the 'no measurable overhead without --trace'
    acceptance assertion)."""
    from riptide_tpu.obs import trace as trace_mod

    assert trace_mod.get_tracer() is None, \
        "suite must run with tracing disabled by default"
    assert span("x") is NULL_SPAN
    assert span("x", chunk=1) is NULL_SPAN
    assert NULL_SPAN.set(a=1) is NULL_SPAN
    with span("warmup", chunk=0):
        pass
    gc.collect()
    before = sys.getallocatedblocks()
    for i in range(200_000):
        with span("phase", chunk=1, kind="fused"):
            pass
    gc.collect()
    after = sys.getallocatedblocks()
    # Interpreter noise allowance only — any per-span retention would
    # show up as >= 200k blocks.
    assert after - before < 1000, f"retained {after - before} blocks"


# ------------------------------------------------------- chrome export

def test_chrome_trace_valid_and_monotone_per_lane(tmp_path, tracer):
    def burst(tag):
        for i in range(5):
            with span("chunkwork", chunk=i, tag=tag):
                with span("inner"):
                    pass

    t = threading.Thread(target=burst, args=("bg",), name="bg-thread")
    t.start()
    t.join()
    burst("main")

    path = str(tmp_path / "trace.json")
    assert write_chrome_trace(path, tracer) == path
    with open(path) as fobj:
        doc = json.load(fobj)
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    ms = [e for e in events if e["ph"] == "M"]
    assert len(xs) == 20
    assert any(m["name"] == "process_name" for m in ms)
    assert any(m["name"] == "thread_name"
               and m["args"]["name"] == "bg-thread" for m in ms)
    for e in xs:
        assert set(e) >= {"name", "cat", "ph", "pid", "tid", "ts", "dur",
                          "args"}
        assert e["ts"] >= 0.0 and e["dur"] >= 0.0
    # Events are recorded at span COMPLETION on a monotonic clock, so
    # within each lane (tid) the end timestamps never go backwards.
    by_tid = {}
    for e in xs:
        by_tid.setdefault(e["tid"], []).append(e["ts"] + e["dur"])
    assert len(by_tid) == 2
    for ends in by_tid.values():
        assert ends == sorted(ends)
    assert doc["otherData"]["recorded"] == 20
    assert doc["otherData"]["dropped_events"] == 0
    assert doc["otherData"]["wall_t0_unix_s"] == tracer.wall_t0


def test_chrome_merge_keeps_process_lanes(tmp_path):
    paths = []
    for pid in (0, 1):
        tr = Tracer(capacity=64)
        prev = set_tracer(tr)
        try:
            with span("work", p=pid):
                pass
        finally:
            set_tracer(prev)
        # Pretend process 1 started 2 s later in absolute time.
        tr.wall_t0 = 1000.0 + 2.0 * pid
        path = str(tmp_path / f"trace_{pid:04d}.json")
        write_chrome_trace(path, tr, pid=pid)
        paths.append(path)

    out = str(tmp_path / "trace.json")
    merge_chrome_traces(paths, out)
    with open(out) as fobj:
        doc = json.load(fobj)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    # Lane 1 is re-anchored +2 s relative to the earliest process.
    ts = {e["pid"]: e["ts"] for e in xs}
    assert ts[1] - ts[0] >= 2e6 - 1e3  # microseconds
    assert doc["otherData"]["wall_t0_unix_s"] == 1000.0


def test_export_run_trace(tmp_path, tracer):
    with span("w"):
        pass
    # Multihost: each process writes its own lane; process 0 merges.
    assert export_run_trace(str(tmp_path), 1, 2).endswith(
        "trace_0001.json")
    assert not os.path.exists(tmp_path / "trace.json")
    export_run_trace(str(tmp_path), 0, 2)
    assert (tmp_path / "trace_0000.json").exists()
    with open(tmp_path / "trace.json") as fobj:
        merged = json.load(fobj)
    assert {e["pid"] for e in merged["traceEvents"]
            if e["ph"] == "X"} == {0, 1}
    # Disabled tracing: export is a no-op.
    prev = set_tracer(None)
    try:
        assert export_run_trace(str(tmp_path)) is None
    finally:
        set_tracer(prev)


# ------------------------------------------------------------ prometheus

def _parse_prom(text):
    """{name: {labels-or-'': value}} + per-name TYPE, permissively
    parsing the text format."""
    values, types = {}, {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            types[name] = kind
        elif line and not line.startswith("#"):
            lhs, val = line.rsplit(None, 1)
            name, _, labels = lhs.partition("{")
            values.setdefault(name, {})[labels.rstrip("}")] = float(val)
    return values, types


def test_prom_render_counters_gauges_histograms():
    m = MetricsRegistry()
    m.add("chunks_done", 3)
    m.set_gauge("queue_depth", 2)
    m.observe("chunk_s", 0.5)
    m.observe("chunk_s", 3.0)
    m.observe_hist("wire_MBps", 42.0)
    text = prom.render(m)
    values, types = _parse_prom(text)

    assert values["riptide_chunks_done_total"][""] == 3
    assert types["riptide_chunks_done_total"] == "counter"
    assert values["riptide_queue_depth"][""] == 2
    assert types["riptide_queue_depth"] == "gauge"

    assert types["riptide_chunk_seconds"] == "histogram"
    buckets = values["riptide_chunk_seconds_bucket"]
    assert values["riptide_chunk_seconds_count"][""] == 2
    assert values["riptide_chunk_seconds_sum"][""] == pytest.approx(3.5)
    assert buckets['le="+Inf"'] == 2
    # Cumulative bucket counts are monotone non-decreasing.
    ordered = [buckets[k] for k in buckets if k != 'le="+Inf"']
    assert ordered == sorted(ordered)
    # 0.5 s lands at le=1.0; 3.0 s at le=4.0.
    assert buckets['le="1"'] == 1
    assert buckets['le="4"'] == 2

    # Rate histogram uses the MB/s ladder, not the seconds ladder.
    assert buckets != values["riptide_wire_MBps_bucket"]
    assert values["riptide_wire_MBps_bucket"]['le="64"'] == 1
    # Every line of the page parses, and HELP precedes each family.
    assert text.count("# HELP") == text.count("# TYPE")


def test_prom_histogram_sum_equals_timer_total():
    """A histogram's _sum is the same accumulator the summary exposes —
    the 'histograms sum to the run's counter totals' acceptance
    property."""
    m = MetricsRegistry()
    for sec in (0.1, 0.2, 1.7):
        m.observe("device_s", sec)
    snap = m.snapshot()
    assert snap["hists"]["device_s"]["sum"] == pytest.approx(
        snap["timers"]["device_s"]["total_s"])
    assert snap["hists"]["device_s"]["count"] == \
        snap["timers"]["device_s"]["count"]
    values, _ = _parse_prom(prom.render(m))
    assert values["riptide_device_seconds_sum"][""] == pytest.approx(2.0)
    assert values["riptide_device_seconds_count"][""] == 3


def test_write_prom_textfile(tmp_path, monkeypatch):
    m = MetricsRegistry()
    m.add("chunks_done")
    path = str(tmp_path / "riptide.prom")
    assert prom.write_prom(path, m) == path
    with open(path) as fobj:
        assert fobj.read() == prom.render(m)
    # maybe_write_textfile honours the env flag (parsed at call time).
    monkeypatch.delenv("RIPTIDE_PROM_TEXTFILE", raising=False)
    assert prom.maybe_write_textfile(m) is None
    path2 = str(tmp_path / "auto.prom")
    monkeypatch.setenv("RIPTIDE_PROM_TEXTFILE", path2)
    assert prom.maybe_write_textfile(m) == path2
    assert os.path.exists(path2)


def test_prom_http_endpoint():
    m = MetricsRegistry()
    m.add("chunks_done", 5)
    server = prom.serve(0, registry=m)
    try:
        url = f"http://127.0.0.1:{server.port}/metrics"
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert "riptide_chunks_done_total 5" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=5.0)
    finally:
        server.close()


# ---------------------------------------------------------------- schema

def test_chunk_timing_sums_to_wall_clock():
    t = chunk_timing(2.0, prep_s=0.4, wire_s=0.5, queue_s=0.1,
                     device_s=1.0, collect_s=1.1, wire_bytes=50_000_000)
    assert set(t) == set(CHUNK_TIMING_KEYS)
    # The serial phases (prep overlaps and is excluded) reconstruct the
    # measured wall-clock exactly — the journal's 5% acceptance bound
    # holds by construction.
    assert t["wire_s"] + t["queue_s"] + t["collect_s"] + t["host_s"] == \
        pytest.approx(t["chunk_s"], rel=1e-6)
    assert t["bound"] == "device"
    assert t["wire_MBps"] == pytest.approx(100.0)
    # Timer skew cannot push host_s negative.
    t2 = chunk_timing(1.0, wire_s=0.7, queue_s=0.2, collect_s=0.3)
    assert t2["host_s"] == 0.0


def test_classify_bound():
    assert classify_bound(8.0, 1.0) == "tunnel"
    assert classify_bound(0.9, 1.0) == "tunnel"  # >= 0.8 ratio
    assert classify_bound(0.1, 1.0) == "device"
    # No device measurement: a ratio against zero must not scream
    # "tunnel".
    assert classify_bound(0.0, 0.0) == "unknown"
    assert classify_bound(0.5, 0.0) == "unknown"


def test_decomposition_keys_shared_with_bench_and_stime():
    s = {"prep_s": 1.0, "wire_s": 2.0, "device_s": 3.0, "wire_MBps": 25.0}
    d = decomposition(s, nchunks=4, elapsed=10.0)
    assert set(d) == set(DECOMPOSITION_KEYS)
    assert d["chunk_s"] == 2.5
    assert d["wire_MBps"] == 25.0


# ------------------------------------- journal timing + kill-and-resume

TOBS, TSAMP, PERIOD = 16.0, 1e-3, 0.5

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def _searcher():
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=1)


def _two_trials(tmp_path):
    f1 = generate_data_presto(str(tmp_path), "a_DM0.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=0.0)
    f2 = generate_data_presto(str(tmp_path), "b_DM5.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=5.0)
    return f1, f2


def test_survey_timing_block_spans_and_resume(tmp_path, tracer,
                                              monkeypatch):
    """The acceptance path on the tiny CPU config: a traced survey run
    journals a per-chunk `timing` decomposition that sums to the
    chunk's wall-clock, exports a Perfetto-loadable trace with
    prep/wire/dispatch/collect spans per chunk next to the journal,
    writes a Prometheus textfile whose histogram counts match the run's
    counters — and the timing/UTC fields survive kill-and-resume."""
    from riptide_tpu.survey.faults import FaultAbort, FaultPlan
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.scheduler import SurveyScheduler

    f1, f2 = _two_trials(tmp_path)
    jdir = str(tmp_path / "j")
    promfile = str(tmp_path / "riptide.prom")
    monkeypatch.setenv("RIPTIDE_PROM_TEXTFILE", promfile)
    get_metrics().reset()

    with pytest.raises(FaultAbort):
        SurveyScheduler(
            _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
            faults=FaultPlan.parse("abort:1"),
        ).run()

    j = SurveyJournal(jdir)
    done = j.completed_chunks()
    assert sorted(done) == [0]
    rec = done[0][0]
    # UTC wall-clock stamp (ISO-8601, Z suffix) on the chunk record.
    assert rec["utc"].endswith("Z") and "T" in rec["utc"]
    t = rec["timings"]
    assert set(CHUNK_TIMING_KEYS) - {"wire_MBps"} <= set(t)
    assert t["wire_s"] + t["queue_s"] + t["collect_s"] + t["host_s"] == \
        pytest.approx(t["chunk_s"], rel=1e-6, abs=2e-6)
    assert t["bound"] in ("tunnel", "device")

    # The aborted run exported nothing (the kill pre-empted the
    # end-of-run hooks) — the resume run must complete the survey and
    # leave the trace + textfile behind.
    assert not os.path.exists(os.path.join(jdir, "trace.json"))

    get_metrics().reset()
    peaks = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
        resume=True,
    ).run()
    assert peaks
    done = SurveyJournal(jdir).completed_chunks()
    assert sorted(done) == [0, 1]
    # The replayed chunk keeps its original timing block verbatim.
    assert done[0][0]["timings"] == t
    assert "utc" in done[1][0]

    # Chrome trace next to the journal: survey phases as spans, chunk
    # attribution on the engine-level spans (inherited from the
    # scheduler's chunk-tagged spans). The shared tracer ring still
    # holds the killed run's chunk-0 spans alongside the resume run's
    # chunk-1 spans.
    with open(os.path.join(jdir, "trace.json")) as fobj:
        doc = json.load(fobj)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    assert {"stage", "ship", "queue", "collect", "journal",
            "prep", "wire", "device", "dispatch"} <= names
    for nm in ("prep", "wire", "dispatch", "collect", "device"):
        chunks = {e["args"].get("chunk") for e in xs if e["name"] == nm}
        assert chunks and chunks <= {0, 1}, (nm, chunks)
    assert 1 in {e["args"].get("chunk") for e in xs
                 if e["name"] == "dispatch"}

    # Prometheus textfile (end-of-run hook): histogram counts equal the
    # resume run's counter totals.
    with open(promfile) as fobj:
        values, _ = _parse_prom(fobj.read())
    assert values["riptide_chunk_seconds_count"][""] == \
        values["riptide_chunks_done_total"][""] == 1
    assert values["riptide_chunks_skipped_total"][""] == 1


def test_resume_tolerates_records_without_new_fields(tmp_path):
    """A journal written before the timing/utc fields existed (or a
    heartbeat sidecar without them) must still resume / tail-read."""
    from riptide_tpu.survey.journal import (
        SurveyJournal, _append_line,
    )

    j = SurveyJournal(tmp_path / "j")
    j.write_header("old", 1)
    # Old-format chunk record: no utc, no timings.
    _append_line(j.journal_path, {
        "kind": "chunk", "chunk_id": 0, "files": ["a.inf"], "dms": [0.0],
        "wire_digest": None, "peaks_offset": 0, "peaks_count": 0,
        "attempts": 1,
    })
    done = SurveyJournal(tmp_path / "j").completed_chunks()
    assert sorted(done) == [0]
    assert done[0][0].get("utc") is None

    # Old-format heartbeat line: ts only.
    _append_line(os.path.join(j.directory, "heartbeat_0003.jsonl"),
                 {"process": 3, "ts": 123.0})
    assert j.read_heartbeats() == {3: 123.0}
    # New-format beats carry a UTC stamp alongside the monotonic ts.
    j.heartbeat(4, ts=5.0)
    assert j.read_heartbeats()[4] == 5.0
    import json as _json

    with open(os.path.join(j.directory, "heartbeat_0004.jsonl")) as fobj:
        rec = _json.loads(fobj.readline())
    assert rec["utc"].endswith("Z")
