"""
Job-scoped run contexts (riptide_tpu/utils/runctx.py): thread-local
resolution of the incident sink and storage-fault plan, inheritance
into worker threads via ``runctx.wrap``, and the process-global
fallback layer that keeps batch CLI behaviour unchanged.
"""
import threading

import pytest

from riptide_tpu.survey import incidents
from riptide_tpu.utils import fsio, runctx


@pytest.fixture(autouse=True)
def _clean_context():
    """Every test starts and ends with no context installed."""
    prev = runctx.install(None)
    yield
    runctx.install(prev)


def test_install_and_current_roundtrip():
    assert runctx.current() is None
    ctx = runctx.RunContext(label="t1")
    prev = runctx.install(ctx)
    assert prev is None
    assert runctx.current() is ctx
    assert runctx.install(prev) is ctx
    assert runctx.current() is None


def test_activate_restores_previous_context():
    outer = runctx.RunContext(label="outer")
    runctx.install(outer)
    inner = runctx.RunContext(label="inner")
    with runctx.activate(inner):
        assert runctx.current() is inner
    assert runctx.current() is outer
    # ...even when the body raises.
    with pytest.raises(RuntimeError):
        with runctx.activate(inner):
            raise RuntimeError("boom")
    assert runctx.current() is outer


def test_wrap_inherits_context_into_thread():
    ctx = runctx.RunContext(label="parent")
    runctx.install(ctx)
    seen = {}

    def probe():
        seen["ctx"] = runctx.current()

    t = threading.Thread(target=runctx.wrap(probe))
    t.start()
    t.join()
    assert seen["ctx"] is ctx
    # A bare (unwrapped) thread inherits NOTHING — thread-local means
    # thread-local.
    t = threading.Thread(target=probe)
    t.start()
    t.join()
    assert seen["ctx"] is None


def test_wrap_restores_the_executing_threads_context():
    mine = runctx.RunContext(label="mine")
    theirs = runctx.RunContext(label="theirs")
    runctx.install(mine)
    fn = runctx.wrap(lambda: None, ctx=theirs)
    fn()
    assert runctx.current() is mine


def test_incident_emit_prefers_context_sink():
    ctx_records, global_records = [], []
    prev = incidents.set_sink(global_records.append)
    try:
        ctx = runctx.RunContext(incident_sink=ctx_records.append,
                                label="job-a")
        with runctx.activate(ctx):
            incidents.emit("watchdog_timeout", chunk_id=1, budget_s=2.0)
        incidents.emit("watchdog_timeout", chunk_id=2, budget_s=3.0)
    finally:
        incidents.set_sink(prev)
    # In-context emission went to the context's sink ONLY; outside the
    # context the process-global fallback received it — the batch path.
    assert [r["chunk_id"] for r in ctx_records] == [1]
    assert [r["chunk_id"] for r in global_records] == [2]
    # The context retains its own last incident for status surfaces.
    assert ctx.last_incident()["chunk_id"] == 1


def test_incident_emit_context_without_sink_falls_back():
    global_records = []
    prev = incidents.set_sink(global_records.append)
    try:
        with runctx.activate(runctx.RunContext(label="sinkless")):
            incidents.emit("breaker_open", cooldown_s=1.0)
    finally:
        incidents.set_sink(prev)
    assert len(global_records) == 1


def test_fsio_fire_prefers_context_fault_plan(tmp_path):
    from riptide_tpu.survey.faults import FaultPlan

    target = str(tmp_path / "hb.jsonl")
    plan = FaultPlan.parse("enospc:heartbeat_append")
    ctx = runctx.RunContext(storage_faults=plan.storage_op)
    with runctx.activate(ctx):
        with pytest.raises(OSError, match="ENOSPC"):
            fsio.append_bytes(target, b"beat\n", site="heartbeat_append")
    # The plan was scoped to the context: the same write outside it
    # (no global hook installed) is clean.
    fsio.append_bytes(target, b"beat\n", site="heartbeat_append")
    with open(target, "rb") as fobj:
        assert fobj.read() == b"beat\n"


def test_fsio_fire_global_fallback_without_context(tmp_path):
    from riptide_tpu.survey.faults import FaultPlan

    target = str(tmp_path / "hb.jsonl")
    plan = FaultPlan.parse("enospc:heartbeat_append")
    prev = fsio.set_storage_faults(plan.storage_op)
    try:
        with pytest.raises(OSError, match="ENOSPC"):
            fsio.append_bytes(target, b"beat\n", site="heartbeat_append")
    finally:
        fsio.set_storage_faults(prev)


def test_note_incident_copies_and_is_thread_safe():
    ctx = runctx.RunContext()
    rec = {"incident": "quarantine", "detail": {"fname": "x"}}
    ctx.note_incident(rec)
    rec["incident"] = "mutated-after-noting"
    assert ctx.last_incident()["incident"] == "quarantine"
    assert ctx.last_incident() is not ctx.last_incident()  # copies out
