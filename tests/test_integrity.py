"""
Result-integrity layer tests (PR 18): the fold-digest machinery, config
parsing, the dispatch-count contract per mode (off = zero overhead on
the device path), shadow-probe detection and out-voting of a transient
in-flight bitflip, quarantine + park + clean resume to identical peaks
under persistent corruption, resume-time digest re-verification,
pre-PR-18 journal compatibility, and the golden canary's verdicts.

Everything runs on the CPU backend against tiny synthetic surveys —
the machinery under test is the integrity plumbing, not the search.
"""
import json

import numpy as np
import pytest

from riptide_tpu.survey import integrity
from riptide_tpu.survey.faults import FaultAbort, FaultPlan
from riptide_tpu.survey.integrity import (
    IntegrityConfig, IntegrityManager, IntegrityQuarantineError,
    fold_result, peaks_digest,
)
from riptide_tpu.survey.journal import SurveyJournal
from riptide_tpu.survey.metrics import get_metrics
from riptide_tpu.survey.scheduler import RetryPolicy, SurveyScheduler
from riptide_tpu.peak_detection import Peak

from synth import generate_data_presto

TOBS = 16.0
TSAMP = 1e-3
PERIOD = 0.5

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def _peak(period=0.5, snr=10.0, dm=0.0):
    return Peak(period=period, freq=1.0 / period, width=3, ducy=0.05,
                iw=1, ip=7, snr=snr, dm=dm)


def _searcher():
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=1)


def _two_trials(tmp_path):
    f1 = generate_data_presto(str(tmp_path), "a_DM0.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=0.0)
    f2 = generate_data_presto(str(tmp_path), "b_DM5.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=5.0)
    return f1, f2


def _fast_retry():
    return RetryPolicy(max_retries=3, base_s=0.01, cap_s=0.02,
                       sleep=lambda s: None)


class _CountingScheduler(SurveyScheduler):
    """Spy on the device-dispatch path: every shadow probe and every
    retry lands here, so the count IS the number of device round
    trips."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.dispatches = 0

    def _dispatch_once(self, *args, **kwargs):
        self.dispatches += 1
        return super()._dispatch_once(*args, **kwargs)


# ------------------------------------------------------------- fold digest

def test_fold_accumulator_deterministic_and_sensitive():
    a = np.arange(24, dtype=np.float32).reshape(2, 12)
    b = np.arange(7, dtype=np.int32)
    acc1 = integrity._FoldAccumulator()
    acc1.fold(a)
    acc1.fold(b)
    acc2 = integrity._FoldAccumulator()
    acc2.fold(a.copy())
    acc2.fold(b.copy())
    assert acc1.hexdigest() == acc2.hexdigest()
    assert acc1.nbuf == 2

    flipped = a.copy()
    flipped.view(np.uint8).reshape(-1)[5] ^= 0xFF
    acc3 = integrity._FoldAccumulator()
    acc3.fold(flipped)
    acc3.fold(b)
    assert acc3.hexdigest() != acc1.hexdigest()
    # Same bytes, different shape: still distinct (shape is folded).
    acc4 = integrity._FoldAccumulator()
    acc4.fold(a.reshape(4, 6))
    acc4.fold(b)
    assert acc4.hexdigest() != acc1.hexdigest()
    assert integrity._FoldAccumulator().hexdigest() is None


def test_fold_result_is_noop_without_accumulator():
    buf = np.arange(10.0)
    assert fold_result(buf) is buf  # no copy, no digest, no state


def test_fold_accumulator_corrupt_hit_flips_one_byte_once():
    a = np.zeros(8, dtype=np.float32)
    acc = integrity._FoldAccumulator(corrupt_hit=3)
    out = acc.fold(a)
    assert (a == 0).all()  # the caller's buffer is never mutated
    assert np.asarray(out).view(np.uint8)[3] == 0xFF
    # One-shot: the second fold of the same attempt is untouched.
    out2 = acc.fold(np.zeros(8, dtype=np.float32))
    assert (np.asarray(out2) == 0).all()


def test_peaks_digest_canonical():
    peaks = [_peak(snr=9.0), _peak(period=1.0, snr=8.0, dm=10.0)]
    assert peaks_digest(peaks) == peaks_digest(list(peaks))
    assert peaks_digest(peaks) != peaks_digest(peaks[:1])
    bumped = [_peak(snr=9.5), peaks[1]]
    assert peaks_digest(bumped) != peaks_digest(peaks)
    assert peaks_digest([]) == peaks_digest([])


# ------------------------------------------------------------------ config

def test_config_modes_and_validation():
    assert not IntegrityConfig().enabled
    assert IntegrityConfig(mode="digest").enabled
    assert not IntegrityConfig(mode="digest").probing
    assert IntegrityConfig(mode="probe", probe_every=2).probing
    assert not IntegrityConfig(mode="probe", probe_every=0).probing
    # strict always probes: probe_every is forced to at least 1.
    assert IntegrityConfig(mode="strict").probe_every == 1
    with pytest.raises(ValueError):
        IntegrityConfig(mode="sideways")
    with pytest.raises(ValueError):
        IntegrityConfig(mode="probe", policy="shrug")


def test_config_from_spec():
    cfg = IntegrityConfig.from_spec("probe", policy="fail")
    assert (cfg.mode, cfg.probe_every, cfg.policy) == ("probe", 1, "fail")
    assert IntegrityConfig.from_spec("digest").probe_every == 0
    cfg = IntegrityConfig.from_spec({"mode": "probe", "probe_every": 3})
    assert (cfg.mode, cfg.probe_every) == ("probe", 3)
    with pytest.raises(ValueError):
        IntegrityConfig.from_spec("sideways")
    with pytest.raises(ValueError):
        IntegrityConfig.from_spec(42)


def test_config_from_env(monkeypatch):
    monkeypatch.delenv("RIPTIDE_INTEGRITY", raising=False)
    assert not IntegrityConfig.from_env().enabled
    monkeypatch.setenv("RIPTIDE_INTEGRITY", "probe")
    monkeypatch.setenv("RIPTIDE_INTEGRITY_PROBE_EVERY", "4")
    cfg = IntegrityConfig.from_env()
    assert (cfg.mode, cfg.probe_every) == ("probe", 4)
    # None spec falls through to the environment.
    assert IntegrityConfig.from_spec(None).probe_every == 4


def test_probe_due_cadence():
    mgr = IntegrityManager(IntegrityConfig(mode="probe", probe_every=2))
    assert [mgr.probe_due(i) for i in range(4)] == [True, False, True,
                                                   False]
    mgr.quarantined = True
    assert not mgr.probe_due(0)
    strict = IntegrityManager(IntegrityConfig(mode="strict"))
    assert all(strict.probe_due(i) for i in range(3))
    assert not IntegrityManager(
        IntegrityConfig(mode="digest")).probe_due(0)


# --------------------------------------------- scheduler: modes end to end

def test_off_mode_no_extra_dispatches_no_new_record_fields(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = _CountingScheduler(_searcher(), [[f1], [f2]],
                               journal=journal)
    assert sched.integrity is None  # off: zero integrity state
    peaks = sched.run()
    assert peaks
    assert sched.dispatches == 2  # one device round trip per chunk
    # Off-mode chunk records are byte-compatible with pre-PR-18 ones:
    # neither the integrity block nor the retry attribution appears.
    done = journal.completed_chunks()
    for cid in (0, 1):
        assert "integrity" not in done[cid][0]
        assert "device_error_retries" not in done[cid][0]
    assert get_metrics().counter("shadow_probes") == 0


def test_digest_mode_records_blocks_without_probing(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = _CountingScheduler(
        _searcher(), [[f1], [f2]], journal=journal,
        integrity=IntegrityConfig(mode="digest"))
    sched.run()
    assert sched.dispatches == 2  # Ring 1 never adds a dispatch
    done = journal.completed_chunks()
    for cid in (0, 1):
        blk = done[cid][0]["integrity"]
        assert blk["algo"] == "sha256" and blk["mode"] == "digest"
        assert len(blk["result"]) == 64
        assert blk["path"] == "batch"
        assert not blk.get("probe")
        # The peaks digest is recomputable from the replayed rows.
        assert blk["peaks"] == peaks_digest(done[cid][1])
    assert get_metrics().counter("integrity_checks") >= 2
    assert get_metrics().counter("shadow_probes") == 0


def test_probe_mode_clean_run_double_dispatches(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = _CountingScheduler(
        _searcher(), [[f1], [f2]], journal=journal,
        integrity=IntegrityConfig(mode="probe", probe_every=1))
    peaks = sched.run()
    assert peaks
    assert sched.dispatches == 4  # primary + shadow per chunk, no vote
    done = journal.completed_chunks()
    for cid in (0, 1):
        blk = done[cid][0]["integrity"]
        assert blk["probe"] is True
        assert "votes" not in blk  # agreement needs no arbitration
    assert get_metrics().counter("shadow_probes") == 2
    assert get_metrics().counter("integrity_mismatches") == 0
    assert not journal.incidents()


def test_transient_bitflip_detected_and_outvoted(tmp_path):
    f1, f2 = _two_trials(tmp_path)
    get_metrics().reset()
    control = SurveyScheduler(_searcher(), [[f1], [f2]]).run()

    get_metrics().reset()
    journal = SurveyJournal(tmp_path / "j")
    sched = _CountingScheduler(
        _searcher(), [[f1], [f2]], journal=journal,
        integrity=IntegrityConfig(mode="probe", probe_every=1),
        faults=FaultPlan.parse("bitflip:1"), retry=_fast_retry())
    peaks = sched.run()
    # Corrupted primary, clean shadow, clean tie-break: 2:1 against the
    # flip, the run completes, and the data product is unharmed.
    assert peaks == control
    assert sched.dispatches == 5  # 2 + (1 primary + 2 shadows)
    assert get_metrics().counter("integrity_mismatches") == 1
    kinds = [rec["incident"] for rec in journal.incidents()]
    assert kinds.count("result_mismatch") == 1
    assert "integrity_quarantine" not in kinds
    blk = journal.completed_chunks()[1][0]["integrity"]
    assert blk["probe"] is True and len(blk["votes"]) == 3


def test_persistent_bitflip_quarantines_parks_then_resumes(tmp_path):
    f1, f2 = _two_trials(tmp_path)
    get_metrics().reset()
    control = SurveyScheduler(_searcher(), [[f1], [f2]]).run()

    # Every one of chunk 0's three dispatches flips a DIFFERENT byte:
    # three distinct digests, no majority, device marked suspect.
    get_metrics().reset()
    jdir = tmp_path / "j"
    sched = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
        integrity=IntegrityConfig(mode="probe", probe_every=1),
        faults=FaultPlan.parse("bitflip:0x3"), retry=_fast_retry())
    degraded = sched.run()
    assert degraded == []  # chunk 0 quarantined, chunk 1 latched parked
    assert sched.integrity.quarantined is True
    journal = SurveyJournal(jdir)
    assert sorted(journal.completed_chunks()) == []
    kinds = [rec["incident"] for rec in journal.incidents()]
    assert "result_mismatch" in kinds
    assert "integrity_quarantine" in kinds
    assert kinds.count("chunk_parked") == 2
    quar = next(rec for rec in journal.incidents()
                if rec["incident"] == "integrity_quarantine")
    assert len(quar["detail"]["digests"]) == 3
    assert quar["detail"]["policy"] == "park"

    # A clean scheduler (fresh latch — "the replaced device") resumes
    # the parked chunks to the identical data product.
    get_metrics().reset()
    resumed = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
        resume=True,
        integrity=IntegrityConfig(mode="probe", probe_every=1)).run()
    assert resumed == control
    assert sorted(SurveyJournal(jdir).completed_chunks()) == [0, 1]


def test_quarantine_policy_fail_raises(tmp_path):
    get_metrics().reset()
    f1, _ = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = SurveyScheduler(
        _searcher(), [[f1]], journal=journal,
        integrity=IntegrityConfig(mode="probe", probe_every=1,
                                  policy="fail"),
        faults=FaultPlan.parse("bitflip:0x3"), retry=_fast_retry())
    with pytest.raises(IntegrityQuarantineError) as exc:
        sched.run()
    assert exc.value.chunk_id == 0
    assert len(exc.value.digests) == 3
    kinds = [rec["incident"] for rec in journal.incidents()]
    assert "integrity_quarantine" in kinds


def test_replay_digest_mismatch_emits_incident(tmp_path):
    """A journaled peaks digest that no longer matches the replayed
    rows is a detected (non-fatal) event on resume."""
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    jdir = tmp_path / "j"
    with pytest.raises(FaultAbort):
        SurveyScheduler(
            _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
            integrity=IntegrityConfig(mode="digest"),
            faults=FaultPlan.parse("abort:1")).run()
    journal = SurveyJournal(jdir)
    rec, peaks0 = journal.completed_chunks()[0]
    # Re-record chunk 0 (last record wins on replay) with a forged
    # digest — the tamper-evidence scenario Ring 1 exists for.
    forged = dict(rec["integrity"], peaks="0" * 64)
    journal.record_chunk(0, rec["files"], rec["dms"], peaks0,
                         wire_digest=rec["wire_digest"],
                         extra={"integrity": forged})

    get_metrics().reset()
    resumed = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
        resume=True, integrity=IntegrityConfig(mode="digest")).run()
    assert resumed  # the replay proceeds: forensic record, not a crash
    inc = [r for r in SurveyJournal(jdir).incidents()
           if r["incident"] == "result_mismatch"]
    assert len(inc) == 1 and inc[0]["detail"]["replayed"] is True
    assert get_metrics().counter("integrity_mismatches") == 1


def test_pre_pr18_journal_resumes_with_integrity_on(tmp_path):
    """Journals written before the integrity layer (no ``integrity``
    blocks) resume cleanly under an integrity-enabled scheduler: the
    replay verification skips silently, no incidents appear."""
    f1, f2 = _two_trials(tmp_path)
    get_metrics().reset()
    control = SurveyScheduler(_searcher(), [[f1], [f2]]).run()

    jdir = tmp_path / "j"
    with pytest.raises(FaultAbort):
        SurveyScheduler(  # integrity off: pre-PR-18 record shape
            _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
            faults=FaultPlan.parse("abort:1")).run()
    assert "integrity" not in SurveyJournal(jdir).completed_chunks()[0][0]

    get_metrics().reset()
    resumed = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
        resume=True,
        integrity=IntegrityConfig(mode="digest")).run()
    assert resumed == control
    assert not [r for r in SurveyJournal(jdir).incidents()
                if r["incident"] == "result_mismatch"]
    # And the reporting side shrugs at the mixed journal too.
    from riptide_tpu.obs import report

    rep = report.build_report(str(jdir))
    assert rep["integrity"]["chunks_digested"] >= 1
    report.render_text(rep)


def test_device_error_retry_attribution_in_chunk_record(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    journal = SurveyJournal(tmp_path / "j")
    sched = SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=journal,
        integrity=IntegrityConfig(mode="digest"),
        faults=FaultPlan.parse("device_error:1"), retry=_fast_retry())
    sched.run()
    done = journal.completed_chunks()
    # The run-wide counter is monotone; the per-chunk extra pins the
    # retry to the chunk that actually suffered it.
    assert "device_error_retries" not in done[0][0]
    assert done[1][0]["device_error_retries"] == 1


# ------------------------------------------------------------------ canary

def test_canary_verdicts(tmp_path):
    get_metrics().reset()
    digest = integrity.compute_canary_digest()
    assert digest and len(digest) == 64
    platform = integrity._canary_platform()

    good = tmp_path / "pin_good.json"
    good.write_text(json.dumps(
        {"v": 1, "algo": "sha256", "platform_digests": {platform: digest}}))
    mgr = IntegrityManager(IntegrityConfig(
        mode="probe", probe_every=1, canary_pin=str(good)))
    assert mgr.canary_verdict() == "ok"

    bad = tmp_path / "pin_bad.json"
    bad.write_text(json.dumps(
        {"v": 1, "algo": "sha256",
         "platform_digests": {platform: "0" * 64}}))
    mgr = IntegrityManager(IntegrityConfig(
        mode="strict", canary_pin=str(bad)))
    assert mgr.canary_verdict() == "failed"
    with pytest.raises(RuntimeError):
        mgr.startup_canary()

    # No pin for this platform: pass-with-note, never fatal.
    empty = tmp_path / "pin_none.json"
    empty.write_text(json.dumps(
        {"v": 1, "algo": "sha256", "platform_digests": {}}))
    mgr = IntegrityManager(IntegrityConfig(
        mode="strict", canary_pin=str(empty)))
    assert mgr.canary_verdict() == "unpinned"
    assert mgr.startup_canary() == "unpinned"


def test_checked_in_cpu_canary_pin_is_current():
    """The pin shipped in tools/integrity_canary.json must match what
    this tree actually computes (the `make repin` contract)."""
    pins = integrity._read_canary_pin(integrity.canary_pin_path())
    platform = integrity._canary_platform()
    if platform not in pins:
        pytest.skip(f"no canary pin for platform {platform!r}")
    assert integrity.compute_canary_digest() == pins[platform]


# ------------------------------------------------------------- watch/report

def test_watch_snapshot_surfaces_integrity_counters(tmp_path):
    get_metrics().reset()
    f1, f2 = _two_trials(tmp_path)
    jdir = tmp_path / "j"
    SurveyScheduler(
        _searcher(), [[f1], [f2]], journal=SurveyJournal(jdir),
        integrity=IntegrityConfig(mode="probe", probe_every=1),
        faults=FaultPlan.parse("bitflip:1"), retry=_fast_retry()).run()
    from riptide_tpu.obs import report

    state = report.read_journal(str(jdir))
    snap = report.watch_snapshot(state)
    assert snap["integrity_mismatches"] == 1
    assert snap["integrity_probed"] == 2
    stats = report.integrity_stats(state["chunks"], state["incidents"])
    assert stats["chunks_digested"] == 2
    assert stats["chunks_probed"] == 2
    assert stats["chunks_voted"] == 1
    assert stats["mismatch_incidents"] == 1
    assert stats["device_verdict"] == "ok"  # detected, out-voted, no latch
