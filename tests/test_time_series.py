"""
TimeSeries / Metadata / readers / serialization tests using synthetic
fixture files (mirrors riptide/tests/test_time_series.py with 16-sample
fixtures of integers 0..15 at 64 us sampling).
"""
import numpy as np
import pytest

from riptide_tpu import TimeSeries, Metadata, save_json, load_json
from riptide_tpu.utils.coords import SkyCoord

from synth import write_presto, write_sigproc

TSAMP = 64e-6
DATA16 = np.arange(16, dtype=np.float32)


def test_from_presto(tmp_path):
    inf = write_presto(str(tmp_path), "fix16", DATA16, TSAMP, dm=12.5)
    ts = TimeSeries.from_presto_inf(inf)
    assert ts.data.dtype == np.float32
    assert np.array_equal(ts.data, DATA16)
    assert ts.tsamp == TSAMP
    assert ts.nsamp == 16
    assert ts.metadata["dm"] == 12.5
    assert ts.metadata["source_name"] == "Pulsar"
    assert isinstance(ts.metadata["skycoord"], SkyCoord)
    assert abs(ts.metadata["mjd"] - 59000.0) < 1e-9


def test_from_presto_with_breaks(tmp_path):
    """A .inf declaring breaks carries On/Off bin pairs between the
    common block and the EM-band block; the parser must collect them and
    still read the radio block that follows
    (riptide/reading/presto.py:90-110, fixture per
    riptide/tests/data/README.md and test_time_series.py:15-61)."""
    from riptide_tpu.reading import PrestoInf

    pairs = [(0, 7), (12, 15)]
    inf = write_presto(str(tmp_path), "fix16_breaks", DATA16, TSAMP,
                       dm=3.5, onoff_pairs=pairs)
    hdr = PrestoInf(inf)
    assert hdr["breaks"] is True
    assert hdr["onoff_pairs"] == pairs
    assert hdr["em_band"] == "Radio"
    assert hdr["dm"] == 3.5
    ts = TimeSeries.from_presto_inf(inf)
    assert np.array_equal(ts.data, DATA16)
    assert ts.metadata["dm"] == 3.5


@pytest.mark.parametrize("em_band", ["X-ray", "Gamma"])
def test_from_presto_xray_warns(tmp_path, em_band):
    """X-ray/Gamma .inf files parse their photon-energy block and loading
    them warns that the white-noise S/N assumption does not hold
    (riptide/reading/presto.py:112-116, riptide/time_series.py:306-315)."""
    from riptide_tpu.reading import PrestoInf

    inf = write_presto(str(tmp_path), f"fix16_{em_band}", DATA16, TSAMP,
                       em_band=em_band)
    hdr = PrestoInf(inf)
    assert hdr["em_band"] == em_band
    assert hdr["central_energy_kev"] == 1.0
    assert hdr["energy_bandpass_kev"] == 0.87
    assert "dm" not in hdr
    with pytest.warns(UserWarning, match="white noise"):
        ts = TimeSeries.from_presto_inf(inf)
    assert np.array_equal(ts.data, DATA16)


def test_from_presto_unknown_band_rejected(tmp_path):
    inf = write_presto(str(tmp_path), "fix16_bad", DATA16, TSAMP,
                       em_band="Neutrino")
    with pytest.raises(ValueError, match="EM Band"):
        TimeSeries.from_presto_inf(inf)


def test_from_sigproc_float32(tmp_path):
    path = write_sigproc(str(tmp_path / "f32.tim"), DATA16, TSAMP, nbits=32, refdm=7.0)
    ts = TimeSeries.from_sigproc(path)
    assert ts.data.dtype == np.float32
    assert np.array_equal(ts.data, DATA16)
    assert ts.metadata["dm"] == 7.0
    assert abs(ts.metadata["mjd"] - 59000.0) < 1e-9


def test_from_sigproc_uint8(tmp_path):
    path = write_sigproc(str(tmp_path / "u8.tim"), DATA16, TSAMP, nbits=8, signed=False)
    ts = TimeSeries.from_sigproc(path)
    assert ts.data.dtype == np.float32
    assert np.array_equal(ts.data, DATA16)


def test_from_sigproc_int8(tmp_path):
    data = DATA16 - 8
    path = write_sigproc(str(tmp_path / "i8.tim"), data, TSAMP, nbits=8, signed=True)
    ts = TimeSeries.from_sigproc(path)
    assert np.array_equal(ts.data, data)


def test_from_sigproc_8bit_without_signed_key_rejected(tmp_path):
    path = write_sigproc(str(tmp_path / "bad.tim"), DATA16, TSAMP, nbits=8, signed=None)
    with pytest.raises(ValueError):
        TimeSeries.from_sigproc(path)


def test_generate_properties():
    np.random.seed(0)
    ts = TimeSeries.generate(length=1.0, tsamp=0.001, period=0.1, amplitude=10.0)
    assert ts.nsamp == 1000
    assert ts.data.dtype == np.float32
    assert abs(ts.length - 1.0) < 1e-9
    assert ts.metadata["source_name"] == "fake"
    # noiseless generation
    ts0 = TimeSeries.generate(length=1.0, tsamp=0.001, period=0.1, amplitude=10.0, stdnoise=0.0)
    # L2 norm of noiseless signal == amplitude
    assert np.isclose(np.sqrt((ts0.data.astype(np.float64) ** 2).sum()), 10.0, rtol=1e-5)


def test_normalise():
    np.random.seed(1)
    ts = TimeSeries.from_numpy_array(
        np.random.normal(loc=50.0, scale=4.0, size=10000).astype(np.float32), 0.001
    )
    out = ts.normalise()
    assert abs(out.data.mean()) < 1e-4
    assert abs(out.data.std() - 1.0) < 1e-4
    ts.normalise(inplace=True)
    assert np.allclose(ts.data, out.data)


def test_deredden_removes_baseline():
    n = 20000
    t = np.arange(n)
    baseline = (10.0 * np.sin(2 * np.pi * t / n)).astype(np.float32)
    np.random.seed(2)
    noise = np.random.normal(size=n).astype(np.float32)
    ts = TimeSeries.from_numpy_array(baseline + noise, 0.001)
    out = ts.deredden(2.0)  # 2000-sample window
    mid = slice(2000, n - 2000)
    # baseline mostly gone in the interior
    assert np.abs(out.data[mid].mean()) < 0.1
    assert out.data[mid].std() < 1.5


def test_downsample():
    ts = TimeSeries.from_numpy_array(np.arange(8, dtype=np.float32), 1.0)
    out = ts.downsample(2.0)
    assert np.allclose(out.data, [1, 5, 9, 13])
    assert out.tsamp == 2.0


def test_fold_consistency():
    """Folding semantics across subints variants
    (riptide/tests/test_time_series.py:159-201)."""
    np.random.seed(3)
    ts = TimeSeries.generate(length=10.0, tsamp=0.001, period=1.0, amplitude=50.0, stdnoise=0.0)
    full = ts.fold(1.0, 100, subints=None)
    assert full.ndim == 2 and full.shape[1] == 100
    one = ts.fold(1.0, 100, subints=1)
    assert one.ndim == 1
    assert np.allclose(one, full.sum(axis=0), atol=1e-4)
    two = ts.fold(1.0, 100, subints=2)
    assert two.shape == (2, 100)
    # peak phase consistent across all variants
    assert abs(int(full.sum(0).argmax()) - int(one.argmax())) <= 1
    with pytest.raises(ValueError):
        ts.fold(20.0, 100)  # period exceeds data length
    with pytest.raises(ValueError):
        ts.fold(0.05, 100)  # bin width below tsamp


def test_json_roundtrip(tmp_path):
    np.random.seed(4)
    ts = TimeSeries.generate(length=0.5, tsamp=0.001, period=0.1, amplitude=5.0)
    ts.metadata["skycoord"] = SkyCoord(12.3, -45.6)
    path = str(tmp_path / "ts.json")
    save_json(path, ts)
    loaded = load_json(path)
    assert isinstance(loaded, TimeSeries)
    assert np.array_equal(loaded.data, ts.data)
    assert loaded.tsamp == ts.tsamp
    assert loaded.metadata["skycoord"] == ts.metadata["skycoord"]
    assert loaded.metadata["signal_period"] == 0.1


def test_metadata_validation():
    with pytest.raises(ValueError):
        Metadata({"dm": -1.0})
    with pytest.raises(ValueError):
        Metadata({"tobs": 0.0})
    with pytest.raises(ValueError):
        Metadata({"source_name": 42})
    md = Metadata({"dm": 5.0, "custom": [1, 2, 3]})
    assert md["dm"] == 5.0
    assert md["skycoord"] is None  # missing reserved keys default to None
    assert md["custom"] == [1, 2, 3]


def test_galactic_coordinates():
    # Galactic centre: (l, b) ~ (0, 0) at ra=266.405, dec=-28.936
    gc = SkyCoord(266.40499, -28.93617)
    l, b = gc.galactic
    assert abs(b) < 0.01
    assert l < 0.01 or l > 359.99
    # North galactic pole
    ngp = SkyCoord(192.85948, 27.12825)
    _, b = ngp.galactic
    assert abs(b - 90.0) < 0.01
