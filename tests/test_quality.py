"""
Degraded-input robustness tests: the data-quality scan/repair/quarantine
layer (riptide_tpu.quality), strict|salvage|skip ingest policies on
truncated/malformed files, NaN masking end-to-end through ffa_search and
the batch searcher, and OOM-aware adaptive bisection of DM batches
(fault-injected and monkeypatched).
"""
import os
import struct

import numpy as np
import pytest

from riptide_tpu import TimeSeries, ffa_search
from riptide_tpu.quality import (
    DegradedInputWarning,
    DQConfig,
    MalformedFile,
    QuarantinedSeries,
    fill_masked,
    scan_samples,
)
from riptide_tpu.survey.faults import FaultPlan, InjectedOOM
from riptide_tpu.survey.metrics import MetricsRegistry, set_metrics

from synth import generate_data_presto, write_presto, write_sigproc

TOBS = 16.0
TSAMP = 1e-3
PERIOD = 0.5

DEREDDEN = {"rmed_width": 4.0, "rmed_minpts": 101}
RANGES = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


@pytest.fixture
def fresh_metrics():
    m = MetricsRegistry()
    prev = set_metrics(m)
    yield m
    set_metrics(prev)


def make_searcher(**kwargs):
    from riptide_tpu.pipeline.batcher import BatchSearcher

    return BatchSearcher(dict(DEREDDEN), RANGES, fmt="presto",
                         io_threads=2, **kwargs)


def make_survey(outdir, amplitudes):
    return [
        generate_data_presto(str(outdir), f"fake_DM{dm:.2f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm, amplitude=amp)
        for dm, amp in amplitudes.items()
    ]


# ---------------------------------------------------------------- scanning

def test_scan_detects_nonfinite_clipping_dead(fresh_metrics):
    rng = np.random.RandomState(0)
    data = rng.normal(size=20000).astype(np.float32)
    data[1000:1100] = data.max()    # 100-sample saturation run
    data[5000:7000] = 0.125         # 2000-sample dead span
    data[100:150] = np.nan          # non-finite block
    data[200] = np.inf
    cfg = DQConfig(clip_run_min=64, dead_run_min=1024)
    mask, rep = scan_samples(data, cfg)
    assert rep.n_nonfinite == 51
    assert rep.n_clipped >= 100
    assert rep.n_dead >= 2000
    assert rep.n_masked == int(mask.sum())
    assert mask[100] and mask[1050] and mask[6000]
    assert not rep.quarantined
    assert fresh_metrics.counter("dq_scanned_samples") == 20000
    assert fresh_metrics.counter("dq_masked_samples") == rep.n_masked
    d = rep.to_dict()
    assert d["masked_frac"] == pytest.approx(rep.masked_frac, abs=1e-6)


def test_scan_clean_noise_masks_nothing():
    rng = np.random.RandomState(1)
    data = rng.normal(size=50000).astype(np.float32)
    mask, rep = scan_samples(data)
    assert rep.n_masked == 0
    assert not mask.any()
    assert rep.reasons == []


def test_scan_dc_dominated_block():
    rng = np.random.RandomState(2)
    data = rng.normal(size=40000).astype(np.float32)
    data[8192:16384] += 100.0  # a grossly DC-offset block
    cfg = DQConfig(dc_block=8192, dc_nstd=6.0)
    mask, rep = scan_samples(data, cfg)
    assert rep.n_dc >= 8192
    assert mask[12000]
    assert not mask[0]


def test_fill_masked_uses_local_level():
    rng = np.random.RandomState(3)
    data = (rng.normal(size=8192) + np.linspace(0.0, 50.0, 8192)) \
        .astype(np.float32)
    mask = np.zeros(data.size, bool)
    mask[4000:4100] = True
    out = fill_masked(data, mask, width_samples=1001)
    # good samples untouched, masked samples near the local trend (~25)
    assert np.array_equal(out[~mask], data[~mask])
    assert np.all(np.abs(out[mask] - data[3900:4000].mean()) < 5.0)


def test_masked_normalise_effective_nsamp_correction():
    rng = np.random.RandomState(4)
    data = rng.normal(size=20000).astype(np.float32)
    mask = np.zeros(data.size, bool)
    mask[:2000] = True  # 10% masked
    ts = TimeSeries(data, TSAMP)
    out = ts.normalise(mask=mask)
    assert np.all(out.data[mask] == 0.0)
    # good samples: unit variance scaled by nsamp / n_good = 1 / 0.9
    assert out.data[~mask].std() == pytest.approx(1.0 / 0.9, rel=1e-3)
    assert abs(out.data[~mask].mean()) < 1e-3 / 0.9
    # mask=None path is bit-identical to the historical normalise
    clean = ts.normalise()
    m = data.mean(dtype=np.float64)
    v = data.var(dtype=np.float64)
    assert np.array_equal(clean.data,
                          ((data - m) / v**0.5).astype(np.float32))


# ------------------------------------------------------- ingest policies

def test_from_binary_rejects_empty_and_indivisible(tmp_path):
    empty = tmp_path / "empty.bin"
    empty.write_bytes(b"")
    with pytest.raises(ValueError, match="empty"):
        TimeSeries.from_binary(str(empty), TSAMP)

    odd = tmp_path / "odd.bin"
    odd.write_bytes(np.arange(8, dtype=np.float32).tobytes() + b"\x01\x02")
    with pytest.raises(ValueError, match="not a multiple"):
        TimeSeries.from_binary(str(odd), TSAMP)

    with pytest.warns(DegradedInputWarning, match="salvaged"):
        ts = TimeSeries.from_binary(str(odd), TSAMP, policy="salvage")
    assert np.array_equal(ts.data, np.arange(8, dtype=np.float32))

    with pytest.warns(DegradedInputWarning, match="skipped"):
        assert TimeSeries.from_binary(str(odd), TSAMP, policy="skip") is None


def test_from_npy_malformed(tmp_path):
    bad = tmp_path / "bad.npy"
    bad.write_bytes(b"\x93NUMPY garbage")
    with pytest.raises(ValueError):
        TimeSeries.from_npy_file(str(bad), TSAMP)
    with pytest.warns(DegradedInputWarning):
        assert TimeSeries.from_npy_file(str(bad), TSAMP,
                                        policy="skip") is None


def test_presto_truncated_dat_policies(tmp_path, fresh_metrics):
    data = np.arange(64, dtype=np.float32)
    inf = write_presto(str(tmp_path), "trunc", data, TSAMP, dm=1.0)
    dat = os.path.join(str(tmp_path), "trunc.dat")
    with open(dat, "r+b") as f:
        f.truncate(16 * 4 + 2)  # 16 whole samples + 2 stray bytes

    with pytest.raises(MalformedFile):
        TimeSeries.from_presto_inf(inf)
    with pytest.warns(DegradedInputWarning):
        ts = TimeSeries.from_presto_inf(inf, policy="salvage")
    assert np.array_equal(ts.data, data[:16])
    with pytest.warns(DegradedInputWarning):
        assert TimeSeries.from_presto_inf(inf, policy="skip") is None
    assert fresh_metrics.counter("files_salvaged") == 1
    assert fresh_metrics.counter("files_skipped") == 1


def test_presto_truncated_inf_header(tmp_path):
    inf = write_presto(str(tmp_path), "hdr",
                       np.arange(16, dtype=np.float32), TSAMP)
    with open(inf) as f:
        head = f.read().splitlines()[:6]
    with open(inf, "w") as f:
        f.write("\n".join(head))
    with pytest.raises(ValueError, match="truncated"):
        from riptide_tpu.reading import PrestoInf

        PrestoInf(inf)
    with pytest.warns(DegradedInputWarning):
        assert TimeSeries.from_presto_inf(inf, policy="skip") is None


def test_sigproc_truncated_payload_policies(tmp_path):
    data = np.arange(32, dtype=np.float32)
    path = write_sigproc(str(tmp_path / "t.tim"), data, TSAMP, nbits=32)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 4 * 16 - 2)  # mid-sample cut

    with pytest.raises(ValueError, match="not a multiple"):
        TimeSeries.from_sigproc(path)
    with pytest.warns(DegradedInputWarning):
        ts = TimeSeries.from_sigproc(path, policy="salvage")
    assert np.array_equal(ts.data, data[:15])
    with pytest.warns(DegradedInputWarning):
        assert TimeSeries.from_sigproc(path, policy="skip") is None


def test_sigproc_corrupt_header_fails_fast(tmp_path):
    # A giant length prefix must raise instead of attempting a huge read
    path = str(tmp_path / "corrupt.tim")
    with open(path, "wb") as f:
        f.write(struct.pack("i", 0x7F000000) + b"HEAD")
    from riptide_tpu.reading import SigprocHeader

    with pytest.raises(ValueError, match="corrupt header"):
        SigprocHeader(path)
    # skip policy turns the same corruption into a structured skip
    with pytest.warns(DegradedInputWarning):
        assert TimeSeries.from_sigproc(path, policy="skip") is None


@pytest.mark.parametrize("key,value", [("nbits", 0), ("tsamp", -1.0),
                                       ("nchans", 0)])
def test_sigproc_insane_header_values(tmp_path, key, value):
    path = str(tmp_path / f"bad_{key}.tim")
    kwargs = {"nbits": 32}
    write_sigproc(path, np.arange(16, dtype=np.float32), TSAMP, **kwargs)
    # Rewrite the header with the insane value via the raw format
    raw = open(path, "rb").read()
    fmt = {"nbits": "i", "tsamp": "d", "nchans": "i"}[key]
    packed = struct.pack(fmt, {"nbits": 32, "tsamp": TSAMP, "nchans": 1}[key])
    bad = struct.pack(fmt, value)
    token = struct.pack("i", len(key)) + key.encode()
    idx = raw.index(token) + len(token)
    assert raw[idx : idx + len(packed)] == packed
    with open(path, "wb") as f:
        f.write(raw[:idx] + bad + raw[idx + len(packed):])
    from riptide_tpu.reading import SigprocHeader

    with pytest.raises(ValueError, match=key):
        SigprocHeader(path)


# ---------------------------------------------------- end-to-end masking

def test_ffa_search_nan_block_snr_parity():
    """THE degraded-input parity bar: a 5% contiguous NaN block must
    still produce a finite periodogram whose top-peak S/N is within 3%
    of the clean run (the effective-nsamp correction restores the clean
    S/N scale)."""
    np.random.seed(0)
    ts = TimeSeries.generate(length=128.0, tsamp=256e-6, period=1.0,
                             amplitude=20.0, ducy=0.02)
    _, pg_clean = ffa_search(ts, period_min=0.5, period_max=2.0,
                             bins_min=480, bins_max=520, ducy_max=0.3)
    clean = float(pg_clean.snrs.max())

    data = ts.data.copy()
    n = data.size
    blk = int(round(0.05 * n))
    data[n // 3 : n // 3 + blk] = np.nan
    with pytest.warns(DegradedInputWarning):
        degraded = TimeSeries.from_numpy_array(data, 256e-6)
    _, pg = ffa_search(degraded, period_min=0.5, period_max=2.0,
                       bins_min=480, bins_max=520, ducy_max=0.3)
    assert np.isfinite(pg.snrs).all()
    masked = float(pg.snrs.max())
    assert abs(masked - clean) / clean < 0.03
    # the peak stays at the right period
    ip, _ = np.unravel_index(np.argmax(pg.snrs), pg.snrs.shape)
    assert abs(1.0 / pg.periods[ip] - 1.0) < 0.1 / 128.0


def test_ffa_search_fully_nan_quarantined(fresh_metrics):
    ts = TimeSeries(np.full(16000, np.nan, dtype=np.float32), TSAMP)
    with pytest.warns(DegradedInputWarning):
        with pytest.raises(QuarantinedSeries) as exc:
            ffa_search(ts, period_min=0.3, period_max=1.2,
                       bins_min=64, bins_max=71)
    report = exc.value.report
    assert report.quarantined
    assert report.masked_frac == 1.0
    assert report.n_nonfinite == 16000
    assert "non-finite" in " ".join(report.reasons)
    assert fresh_metrics.counter("series_quarantined") == 1


def test_batcher_quarantines_bad_trial(tmp_path, fresh_metrics):
    """A fully-NaN DM trial is dropped from the batch with a structured
    report; the remaining trials still search normally."""
    files = make_survey(tmp_path, {0.0: 15.0, 10.0: 40.0})
    bad = write_presto(str(tmp_path), "fake_DM20.00",
                       np.full(int(TOBS / TSAMP), np.nan, np.float32),
                       TSAMP, dm=20.0)
    bs = make_searcher()
    with pytest.warns(DegradedInputWarning):
        peaks = bs.process_fname_list(files + [bad])
    assert peaks
    best = max(peaks, key=lambda p: p.snr)
    assert best.dm == 10.0
    assert abs(best.period - PERIOD) < 1e-3
    assert not any(p.dm == 20.0 for p in peaks)
    assert fresh_metrics.counter("series_quarantined") == 1
    rep = bs.dq_reports["fake_DM20.00.inf"]
    assert rep.quarantined and rep.dm == 20.0


def test_nan_inject_fault_masks_and_searches(tmp_path, fresh_metrics):
    """The nan_inject fault kind corrupts loaded samples upstream of the
    DQ scan; masking repairs them and the pulsar is still found."""
    files = make_survey(tmp_path, {0.0: 15.0, 10.0: 40.0})
    faults = FaultPlan.parse("nan_inject:0:0.05x2")
    bs = make_searcher(faults=faults)
    peaks = bs.process_fname_list(files)
    assert peaks
    best = max(peaks, key=lambda p: p.snr)
    assert best.dm == 10.0
    assert fresh_metrics.counter("dq_masked_samples") >= \
        2 * int(0.05 * TOBS / TSAMP)
    summary = fresh_metrics.summary()
    assert summary["dq_masked_frac"] > 0.0


# ------------------------------------------------- OOM-aware bisection

def test_fault_plan_oom_and_nan_parse():
    plan = FaultPlan.parse("oom:2x2,nan_inject:1:0.1")
    with pytest.raises(InjectedOOM, match="RESOURCE_EXHAUSTED"):
        plan.maybe_oom(4)
    with pytest.raises(InjectedOOM):
        plan.maybe_oom(3)
    plan.maybe_oom(4)  # budget exhausted: no raise
    plan.maybe_oom(2)  # at/below the floor: never raises
    data = np.zeros(1000, np.float32)
    assert plan.nan_inject(1, data)
    assert np.isnan(data).sum() == 100
    assert not plan.nan_inject(1, data)  # consumed


def test_is_oom_error_matches_xla_and_injected():
    from riptide_tpu.search.engine import is_oom_error

    assert is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate ..."))
    assert is_oom_error(InjectedOOM(8, 0))
    assert not is_oom_error(RuntimeError("INVALID_ARGUMENT: bad shape"))


def test_oom_bisection_fault_identical_peaks(tmp_path, fresh_metrics):
    """An injected RESOURCE_EXHAUSTED on the full DM batch converges via
    bisection to exactly the peaks of an unthrottled run, and records
    the downshift in the metrics registry."""
    amps = {0.0: 15.0, 5.0: 25.0, 10.0: 40.0, 15.0: 15.0}
    files = make_survey(tmp_path, amps)

    clean = make_searcher().process_fname_list(files)
    baseline_bisections = fresh_metrics.counter("oom_bisections")
    assert baseline_bisections == 0

    throttled = make_searcher(faults=FaultPlan.parse("oom:2"))
    peaks = throttled.process_fname_list(files)
    assert fresh_metrics.counter("oom_bisections") >= 1
    assert sorted(peaks) == sorted(clean)


def test_oom_bisection_monkeypatched_collect(tmp_path, fresh_metrics,
                                             monkeypatch):
    """A RESOURCE_EXHAUSTED surfacing at collect time (the realistic
    spot: queued device work fails when executed) also bisects to the
    same peaks."""
    import riptide_tpu.pipeline.batcher as batcher_mod

    files = make_survey(tmp_path, {0.0: 15.0, 5.0: 25.0, 10.0: 40.0})
    clean = make_searcher().process_fname_list(files)

    real = batcher_mod.collect_search_batch
    state = {"failed": False}

    def failing_collect(handle, dms):
        if not state["failed"]:
            state["failed"] = True
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                "allocate 1234567890 bytes"
            )
        return real(handle, dms)

    monkeypatch.setattr(batcher_mod, "collect_search_batch", failing_collect)
    peaks = make_searcher().process_fname_list(files)
    assert state["failed"]
    assert fresh_metrics.counter("oom_bisections") >= 1
    assert sorted(peaks) == sorted(clean)


def test_oom_at_floor_propagates(tmp_path, fresh_metrics):
    """OOM persisting at the bisection floor must propagate, not loop."""
    files = make_survey(tmp_path, {0.0: 15.0, 10.0: 40.0})
    bs = make_searcher(faults=FaultPlan.parse("oom:0x99"))
    with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
        bs.process_fname_list(files)


def test_scheduler_journal_records_dq_and_oom(tmp_path, fresh_metrics):
    """Journaled survey with an injected full-batch OOM: chunk records
    carry the DQ summary, the final metrics snapshot shows
    oom_bisections, and the peaks match an unthrottled run."""
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.scheduler import SurveyScheduler

    amps = {0.0: 15.0, 5.0: 25.0, 10.0: 40.0, 15.0: 15.0}
    files = make_survey(tmp_path, amps)
    chunks = [files[:2], files[2:]]

    clean = make_searcher().process_stream([list(c) for c in chunks])

    faults = FaultPlan.parse("oom:1")
    searcher = make_searcher(faults=faults)
    scheduler = SurveyScheduler(
        searcher, chunks, journal=SurveyJournal(tmp_path / "journal"),
        faults=faults,
    )
    peaks = scheduler.run()
    assert sorted(peaks) == sorted(clean)
    assert fresh_metrics.counter("oom_bisections") >= 1

    journal = SurveyJournal(tmp_path / "journal")
    done = journal.completed_chunks()
    assert sorted(done) == [0, 1]
    for cid, (rec, _) in done.items():
        assert "dq" in rec
        assert rec["dq"].get("masked_samples", 0) == 0
    metrics = journal.last_metrics()
    assert metrics["oom_bisections"] >= 1
    assert metrics["dq_scanned_samples"] > 0


def test_boxcar_snr_eff_frac_correction():
    """The host-level effective-nsamp correction on ops.snr.boxcar_snr:
    S/N scales by 1/eff_frac; out-of-range values are rejected."""
    from riptide_tpu.ops.snr import boxcar_snr

    rng = np.random.RandomState(11)
    profile = rng.normal(size=(3, 64)).astype(np.float32)
    base = boxcar_snr(profile, [1, 2, 4])
    corrected = boxcar_snr(profile, [1, 2, 4], eff_frac=0.95)
    assert np.allclose(corrected, base / np.float32(0.95), rtol=1e-6)
    with pytest.raises(ValueError, match="eff_frac"):
        boxcar_snr(profile, [1, 2], eff_frac=0.0)


def test_prepare_identity_path_leaves_metadata_untouched():
    """Nothing to do (clean series, no detrend, no normalise) must hand
    back the caller's object without growing provenance keys on it."""
    from riptide_tpu.quality import prepare_time_series

    rng = np.random.RandomState(12)
    ts = TimeSeries(rng.normal(size=4096).astype(np.float32), TSAMP)
    prep, report = prepare_time_series(ts, normalise=False)
    assert prep is ts
    assert report.n_masked == 0
    assert "dq_masked_frac" not in ts.metadata
    assert "dq_nsamp_eff" not in ts.metadata


def test_candidate_reload_does_not_refire_faults(tmp_path, fresh_metrics):
    """A candidate-rebuild reload (search=False) must neither consume
    leftover nan_inject directives nor re-count DQ metrics: the folded
    data must match what was searched."""
    [f] = make_survey(tmp_path, {0.0: 40.0})
    faults = FaultPlan.parse("nan_inject:0x5")
    bs = make_searcher(faults=faults)
    assert bs.load_prepared(f) is not None       # fires one injection
    searched_report = bs.dq_reports["fake_DM0.00.inf"]
    assert searched_report.n_masked > 0
    scanned = fresh_metrics.counter("dq_scanned_samples")

    ts2 = bs.load_prepared(f, search=False)      # rebuild reload
    assert np.isfinite(ts2.data).all()
    assert fresh_metrics.counter("dq_scanned_samples") == scanned
    # the search-time report (with the injected mask) is retained
    assert bs.dq_reports["fake_DM0.00.inf"] is searched_report
    # directives were NOT consumed by the reload: 4 firings remain
    assert sum(d["remaining"] for d in faults._directives) == 4


def test_dq_by_dm_handles_missing_dm():
    """A series without a DM files its provenance under 0.0 (the Peak
    rows' fallback), and collisions keep the worst masked fraction."""
    from riptide_tpu.quality import QualityReport

    bs = make_searcher()
    a = QualityReport(1000, fname="a.tim", dm=None)
    a.n_masked = 100
    b = QualityReport(1000, fname="b.tim", dm=0.0)
    b.n_masked = 0
    bs.dq_reports = {"a.tim": a, "b.tim": b}
    assert bs.dq_by_dm() == {0.0: 0.1}


def test_empty_file_salvage_degrades_to_skip(tmp_path, fresh_metrics):
    """An empty file has no readable prefix: 'salvage' must skip it
    (structured warning), not crash the run; only 'strict' raises."""
    empty = tmp_path / "empty.bin"
    empty.write_bytes(b"")
    with pytest.warns(DegradedInputWarning):
        assert TimeSeries.from_binary(str(empty), TSAMP,
                                      policy="salvage") is None
    assert fresh_metrics.counter("files_skipped") == 1
    with pytest.raises(ValueError):
        TimeSeries.from_binary(str(empty), TSAMP, policy="strict")


def test_fully_masked_quarantined_even_at_max_frac_one(fresh_metrics):
    """max_masked_frac=1.0 ('never quarantine by fraction') still cannot
    make a fully-masked series searchable: it must quarantine with a
    structured report, not crash in the repair."""
    ts = TimeSeries(np.full(16000, np.nan, dtype=np.float32), TSAMP)
    with pytest.warns(DegradedInputWarning):
        with pytest.raises(QuarantinedSeries) as exc:
            ffa_search(ts, period_min=0.3, period_max=1.2,
                       bins_min=64, bins_max=71, max_masked_frac=1.0)
    assert "no unmasked samples" in " ".join(exc.value.report.reasons)


def test_prepare_already_normalised_still_corrects():
    """normalise=False (externally-normalised input) must still zero
    masked samples and apply the effective-nsamp correction."""
    from riptide_tpu.quality import prepare_time_series

    rng = np.random.RandomState(7)
    data = rng.normal(size=20000).astype(np.float32)
    data = ((data - data.mean()) / data.std()).astype(np.float32)
    data[5000:6000] = np.inf  # 5% masked
    ts = TimeSeries(data, TSAMP)
    prepared, report = prepare_time_series(ts, normalise=False)
    assert report.masked_frac == pytest.approx(0.05)
    assert np.isfinite(prepared.data).all()
    assert np.all(prepared.data[5000:6000] == 0.0)
    good = np.ones(data.size, bool)
    good[5000:6000] = False
    # unit-variance input scaled by nsamp / n_good
    assert prepared.data[good].std() == pytest.approx(1.0 / 0.95, rel=2e-3)
    assert prepared.metadata["dq_nsamp_eff"] == 19000


def test_resume_preserves_masked_frac_provenance(tmp_path, fresh_metrics):
    """Kill-and-resume with a degraded (NaN-block) trial: the resumed
    run restores per-file DQ reports from the journal, so peaks.csv
    (including the masked_frac column) is byte-identical to an
    uninterrupted run."""
    from riptide_tpu.pipeline import Pipeline
    from riptide_tpu.survey.faults import FaultAbort

    indir = tmp_path / "data"
    indir.mkdir()
    files = make_survey(indir, {0.0: 40.0, 10.0: 40.0})
    # Degrade the FIRST chunk's trial with a 5% NaN block: that chunk
    # is journaled before the injected abort, so the resumed run must
    # reproduce its masked_frac from the journal, not from a re-load.
    dat = indir / "fake_DM0.00.dat"
    arr = np.fromfile(dat, dtype=np.float32)
    arr[len(arr) // 3 : len(arr) // 3 + len(arr) // 20] = np.nan
    arr.tofile(dat)

    conf = {
        "processes": 1,  # one file per chunk -> 2 chunks
        "data": {"format": "presto", "fmin": None, "fmax": None,
                 "nchans": None},
        "dmselect": {"min": 0.0, "max": 100.0, "dmsinb_max": None},
        "dereddening": dict(DEREDDEN),
        "ranges": [{"name": "r", "ffa_search": RANGES[0]["ffa_search"],
                    "find_peaks": RANGES[0]["find_peaks"],
                    "candidates": {"bins": 64, "subints": 8}}],
        "clustering": {"radius": 0.2},
        "harmonic_flagging": {"denom_max": 10, "phase_distance_max": 1.0,
                              "dm_distance_max": 3.0,
                              "snr_distance_max": 3.0},
        "candidate_filters": {"dm_min": None, "snr_min": 7.0,
                              "remove_harmonics": True, "max_number": None},
        "plot_candidates": False,
    }
    out_a = tmp_path / "out_a"
    out_a.mkdir()
    with pytest.warns(DegradedInputWarning):
        Pipeline(dict(conf)).process([str(f) for f in files], str(out_a))
    peaks_a = (out_a / "peaks.csv").read_bytes()
    assert b"masked_frac" in peaks_a

    out_b = tmp_path / "out_b"
    out_b.mkdir()
    jdir = str(tmp_path / "journal")
    with pytest.warns(DegradedInputWarning):
        with pytest.raises(FaultAbort):
            # Chunk 0 (the degraded trial) completes and journals;
            # the abort kills the run on chunk 1's dispatch.
            Pipeline(dict(conf), journal=jdir, fault_spec="abort:1") \
                .process([str(f) for f in files], str(out_b))
    Pipeline(dict(conf), journal=jdir, resume=True, fault_spec="") \
        .process([str(f) for f in files], str(out_b))
    assert (out_b / "peaks.csv").read_bytes() == peaks_a


def test_rseek_nan_inject_survives(tmp_path, capsys):
    """rseek with an injected NaN block masks, searches and still prints
    the pulsar line."""
    from riptide_tpu.apps.rseek import get_parser, run_program

    inf = generate_data_presto(str(tmp_path), "fake_DM0.00", tobs=TOBS,
                               tsamp=TSAMP, period=PERIOD, amplitude=40.0)
    args = get_parser().parse_args([
        "-f", "presto", "--Pmin", "0.3", "--Pmax", "1.2",
        "--bmin", "64", "--bmax", "71", "--smin", "7.0",
        "--fault-inject", "nan_inject:0:0.05", inf,
    ])
    df = run_program(args)
    assert df is not None
    assert abs(df.iloc[0]["period"] - PERIOD) < 1e-3
