"""
Test configuration: force the CPU backend with 8 virtual devices so
sharding/multi-chip code paths are exercised without TPU hardware, and
keep everything deterministic.

NOTE on the axon environment: the image's sitecustomize imports jax at
interpreter startup (to register the TPU tunnel), so environment
variables set here are too late to influence jax's import-time config
reads. ``jax.config.update`` works post-import as long as no backend has
been initialised yet, which is the case at conftest import time.
"""
import os
import sys

import pytest

# Test modules import shared helpers as plain modules (`from synth
# import ...`); keep that working both from a checkout (tests/) and from
# the installed riptide_tpu.tests package.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_TPU_MODE = os.environ.get("RIPTIDE_TESTS_TPU") == "1"

if not _TPU_MODE:
    # Effective when jax was NOT pre-imported by sitecustomize (e.g.
    # running with PALLAS_AXON_POOL_IPS unset); harmless otherwise.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # Read at CPU backend initialisation, which has not happened yet.
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
# Persistent compilation cache: kernel shapes repeat across test runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax  # noqa: E402

if not _TPU_MODE:
    # Effective even when sitecustomize already imported jax with
    # JAX_PLATFORMS=axon: config updates apply until first backend use.
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: needs the real TPU backend (run via `make tests-tpu`; "
        "skipped in the default CPU suite)",
    )
    config.addinivalue_line(
        "markers",
        "slow: minutes-scale on the CPU backend (tier-1 deselects via "
        "-m 'not slow'; still run by `make tests`)",
    )


def pytest_collection_modifyitems(config, items):
    if _TPU_MODE:
        return
    skip = pytest.mark.skip(reason="TPU-only; run `make tests-tpu`")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)
