"""
Test configuration: force the CPU backend with 8 virtual devices so
sharding/multi-chip code paths are exercised without TPU hardware, and
keep everything deterministic.
"""
import os

# Force, don't setdefault: the environment ships with JAX_PLATFORMS=axon
# (the TPU tunnel) and the single TPU chip must not be contended by tests.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Persistent compilation cache: kernel shapes repeat across test runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
