"""
Tier-1 enforcement of the semantic static pass (rprove):

* the checked-in plan contracts (tools/plan_contracts.json) match what
  the tree's staged programs trace to — zero drift on the clean tree;
* the queued-stage lowering hook AOT-lowers backend-free on CPU (no
  device execution) and the buffer-liveness peak-HBM model is sane;
* each seeded regression — an introduced extra dispatch, an f64
  promotion, a dropped donation, an unplanned host transfer — makes
  rprove exit 1 with a message naming the plan + stage (the paired
  "good twin" is the clean-tree test above);
* the HBM model SEEDS the DM-batch pick end-to-end on CPU: with an
  injected OOM threshold the model respects, `oom_bisections` is 0,
  `oom_predicted` counts the proactive split, peaks are byte-identical
  to an unthrottled run, and the journal/rreport carry the
  predicted-vs-actual `hbm` calibration block;
* the rprove CLI contracts: --update pins, drift exits 1, missing file
  exits 2, --format sarif reuses riplint's writer (driver "rprove",
  the RPV rule set);
* the riplint result cache invalidates on a plan_contracts.json edit
  (the semantic pass's pinned artifact is a tracked input of `make
  check`).

The full (slow-tier) plan sweep runs behind ``-m slow``.
"""
import importlib.util
import io
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from riptide_tpu.analysis import jaxpr_contract as jc
from riptide_tpu.ops.plan import CONTRACT_PLANS, contract_plan_params
from riptide_tpu.search import engine

# Shared survey helpers + the fresh_metrics fixture (pytest registers
# an imported fixture for this module too).
from test_quality import (  # noqa: F401
    RANGES, TSAMP, fresh_metrics, make_searcher, make_survey,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RPROVE = os.path.join(REPO, "tools", "rprove.py")
CONTRACTS = os.path.join(REPO, "tools", "plan_contracts.json")

ALL_NAMES = [s["name"] for s in CONTRACT_PLANS]


def _load_tool(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


rprove = _load_tool(RPROVE, "rprove_under_test")


def _tiny_plan(name="tiny-gather"):
    return jc.build_contract_plan(contract_plan_params([name])[0])


# ----------------------------------------------------- plan enumeration

def test_contract_plan_params_resolution():
    fast = contract_plan_params(tiers=("fast",))
    assert [s["name"] for s in fast] == ["tiny-gather", "tiny-fused"]
    assert [s["name"] for s in contract_plan_params(["tiny-fused"])] \
        == ["tiny-fused"]
    both = contract_plan_params(tiers=("fast", "slow"))
    assert len(both) == len(CONTRACT_PLANS)
    with pytest.raises(KeyError, match="unknown contract plan"):
        contract_plan_params(["renamed-away"])


# --------------------------------------------- jaxpr walks (unit level)

def test_peak_live_bytes_liveness():
    """x dies after its last use, so the peak is two 256-float buffers,
    not three."""
    def f(x):
        y = x * 2.0
        return y + 1.0

    closed = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((256,), jnp.float32))
    assert jc.peak_live_bytes(closed) == 2 * 256 * 4


def test_f64_and_dtype_collection():
    def ok(x):
        return x + 1.0

    def bad(x):
        return x.astype(jnp.float64) + 1.0

    sds = jax.ShapeDtypeStruct((8,), jnp.float32)
    with jax.experimental.enable_x64():
        assert jc.count_f64_eqns(jax.make_jaxpr(ok)(sds)) == 0
        closed = jax.make_jaxpr(bad)(sds)
        assert jc.count_f64_eqns(closed) >= 1
        assert "float64" in jc.collect_dtypes(closed)


def test_donation_report_honored_and_dropped():
    sds = jax.ShapeDtypeStruct((64,), jnp.float32)
    honored = jc.donation_report(lambda x, y: x + y, (sds, sds),
                                 donate_argnums=(0,))
    assert honored == {"donated": 1, "dropped": 0}
    dropped = jc.donation_report(lambda x, y: (x + y)[:1], (sds, sds),
                                 donate_argnums=(0,))
    assert dropped == {"donated": 1, "dropped": 1}
    assert jc.donation_report(lambda x: x, (sds,)) \
        == {"donated": 0, "dropped": 0}


# ------------------------------------------- the tiny CPU AOT-trace test

def test_staged_chunk_program_aot_lowers_backend_free():
    """The lowering hook's whole-chunk program AOT-lowers on the CPU
    backend from abstract operands alone — no data, no device
    execution — and the liveness walk over it yields a positive,
    monotone HBM model."""
    plan = _tiny_plan("tiny-gather")
    fn, args = engine.staged_chunk_program(plan, 2, path="gather",
                                           mode="float32")
    assert all(isinstance(a, jax.ShapeDtypeStruct) for a in args)
    lowered = jax.jit(fn).lower(*args)
    assert lowered.as_text()  # stablehlo module produced, nothing ran

    model = jc.hbm_model(plan, path="gather", mode="float32")
    assert model.per_dm_bytes > 0
    assert model.predict(8) > model.predict(1)
    # Exactly-at-budget probes invert to the probed batch size.
    assert model.max_batch(model.predict(3)) == 3
    assert model.max_batch(0) == 1  # never below one trial
    # A D-independent footprint must report "unbounded", not force
    # maximal splitting (review regression).
    flat = jc.HBMModel(1024, 0)
    assert flat.max_batch(10 * 1024) > 1 << 40


def test_extracted_contract_shape_fused_zero_pack(fresh_metrics):
    """The fused path's contract: one fused program per eligible stage
    lane bucket, ZERO pack programs, float32 assembled output."""
    spec = contract_plan_params(["tiny-fused"])[0]
    plan = jc.build_contract_plan(spec)
    c = jc.extract_contract("tiny-fused", plan, path="kernel",
                            mode="uint6")
    assert c["n_stages"] == len(plan.stages) == len(c["stages"])
    for st in c["stages"]:
        assert st["kind"] == "fused"
        assert st["dispatch"].get("fused", 0) >= 1
        assert "pack" not in st["dispatch"]
        assert st["f64_eqns"] == 0
    assert c["dispatch_total"].get("pack", 0) == 0
    assert c["out_dtype"] == "float32"
    assert "float64" not in c["dtypes"]
    assert c["transfers"]["h2d_bytes_per_dm"] > 0
    assert c["hbm"]["per_dm_bytes"] > 0


# ------------------------------------------------ clean-tree verification

def test_contracts_zero_drift_on_clean_tree(fresh_metrics):
    """The fast tier of `make prove`, in-process: the pinned contracts
    match the tree (the paired 'good twin' of every seeded-regression
    test below)."""
    current = rprove.build_current(tiers=("fast",))
    pinned = jc.load_contracts(CONTRACTS)
    assert pinned is not None, "tools/plan_contracts.json missing"
    findings = jc.check_contracts(pinned, current, ALL_NAMES)
    assert findings == [], "\n".join(f["message"] for f in findings)


@pytest.mark.slow
def test_contracts_zero_drift_full_sweep(fresh_metrics):
    """The full plan sweep (slow tier included): `rprove --all`."""
    current = rprove.build_current(tiers=("fast", "slow"))
    pinned = jc.load_contracts(CONTRACTS)
    findings = jc.check_contracts(pinned, current, ALL_NAMES)
    assert findings == [], "\n".join(f["message"] for f in findings)
    assert set(pinned["plans"]) == set(ALL_NAMES)


# ------------------------------------------------- seeded regressions
#
# Each seed doctors the live engine (monkeypatch, undone per test) and
# asserts rprove exits 1 with a finding naming the plan + stage; the
# clean-tree test above is the shared good twin.

def _run_rprove(names):
    out, err = io.StringIO(), io.StringIO()
    code = rprove.run(names=names, out=out, err=err)
    return code, out.getvalue(), err.getvalue()


def test_seeded_extra_dispatch_exits_1(fresh_metrics, monkeypatch):
    """Demote the fused stages to the two-dispatch pack+kernel form:
    the pack programs the fused path eliminated reappear and the
    dispatch contract drifts."""
    monkeypatch.setattr(engine, "_fused_eligible",
                        lambda st, plan, mode: False)
    code, out, _ = _run_rprove(["tiny-fused"])
    assert code == 1
    assert "RPV001" in out and "tiny-fused" in out
    assert "stage 0" in out and "dispatch drift" in out
    assert "pack" in out


def test_seeded_f64_promotion_exits_1(fresh_metrics, monkeypatch):
    """Promote a gather stage's output to float64: the dtype-flow
    audit catches it (absolute — --update could not bless it)."""
    orig = engine._run_stage_unpack_gather

    def promoted(st, part, off, plan, meta, i):
        with jax.experimental.enable_x64():
            return orig(st, part, off, plan, meta, i).astype(jnp.float64)

    monkeypatch.setattr(engine, "_run_stage_unpack_gather", promoted)
    code, out, _ = _run_rprove(["tiny-gather"])
    assert code == 1
    assert "RPV002" in out and "tiny-gather" in out
    assert "stage 0" in out and "float64" in out


def test_seeded_dropped_donation_exits_1(fresh_metrics, monkeypatch):
    """Declare stage 0's wire part donated: its output has a different
    shape, so XLA drops the donation — rprove reports the silent
    double-count."""
    orig = engine.staged_stage_programs

    def with_donation(plan, D, path=None, mode=None):
        recs = orig(plan, D, path=path, mode=mode)
        recs[0] = dict(recs[0], donate=(0,))
        return recs

    monkeypatch.setattr(engine, "staged_stage_programs", with_donation)
    code, out, _ = _run_rprove(["tiny-gather"])
    assert code == 1
    assert "RPV003" in out and "tiny-gather" in out
    assert "stage 0" in out and "dropped" in out


def test_seeded_unplanned_transfer_exits_1(fresh_metrics, monkeypatch):
    """Close an extra host array over a stage's program: it becomes a
    per-dispatch constant transfer and the operand-bytes contract
    drifts."""
    orig = engine._run_stage_unpack_gather
    stowaway = np.ones((7,), np.float32)

    def smuggling(st, part, off, plan, meta, i):
        return orig(st, part, off, plan, meta, i) \
            + jnp.asarray(stowaway[:1])

    monkeypatch.setattr(engine, "_run_stage_unpack_gather", smuggling)
    code, out, _ = _run_rprove(["tiny-gather"])
    assert code == 1
    assert "RPV004" in out and "tiny-gather" in out
    assert "stage 0" in out and "operand bytes drift" in out


# ------------------------------------------------------- checker units

def test_check_contracts_set_rules():
    pinned = {"plans": {"gone-plan": {"stages": []}}}
    findings = jc.check_contracts(pinned, {}, ALL_NAMES)
    assert len(findings) == 1 and findings[0]["rule"] == "RPV006"
    assert "gone-plan" in findings[0]["message"]

    current = rprove.build_current(["tiny-gather"])
    findings = jc.check_contracts({"plans": {}}, current, ALL_NAMES)
    assert any(f["rule"] == "RPV006" and "tiny-gather" in f["message"]
               and "--update" in f["message"] for f in findings)


# ---------------------------------------------------------- CLI surface

def test_cli_update_then_clean_then_missing(tmp_path, fresh_metrics):
    custom = tmp_path / "contracts.json"
    # Missing contract file: exit 2 with guidance.
    assert rprove.run(contracts_path=str(custom), names=["tiny-gather"],
                      out=io.StringIO(), err=io.StringIO()) == 2
    # --update pins; a fresh check against the pin is clean.
    assert rprove.run(contracts_path=str(custom), names=["tiny-gather"],
                      update=True, out=io.StringIO(),
                      err=io.StringIO()) == 0
    doc = json.loads(custom.read_text())
    assert set(doc["plans"]) == {"tiny-gather"}
    err = io.StringIO()
    assert rprove.run(contracts_path=str(custom), names=["tiny-gather"],
                      out=io.StringIO(), err=err) == 0
    assert "rprove OK" in err.getvalue()


def test_cli_sarif_reuses_riplint_writer(fresh_metrics):
    out = io.StringIO()
    code = rprove.run(names=["tiny-gather"], fmt="sarif", out=out,
                      err=io.StringIO())
    assert code == 0
    doc = json.loads(out.getvalue())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "rprove"
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == \
        [f"RPV{n:03d}" for n in range(1, 7)]
    assert run["results"] == []


def test_make_targets_wire_prove_into_check_full():
    with open(os.path.join(REPO, "Makefile")) as fobj:
        mk = fobj.read()
    assert "\nprove:" in mk
    check_full = mk.split("check-full:")[1].split("\n\n")[0]
    assert "tools/rprove.py" in check_full
    assert "lint: check-full sanitize" in mk


def test_riplint_cache_invalidates_on_contract_edit():
    """tools/plan_contracts.json is a tracked input of the riplint
    result cache: touching it must force a fresh run."""
    riplint = _load_tool(os.path.join(REPO, "tools", "riplint.py"),
                         "riplint_for_rprove_tests")
    assert riplint.run(out=io.StringIO(), err=io.StringIO()) == 0
    err = io.StringIO()
    assert riplint.run(out=io.StringIO(), err=err) == 0
    assert "[cached]" in err.getvalue()
    os.utime(CONTRACTS)
    err2 = io.StringIO()
    assert riplint.run(out=io.StringIO(), err=err2) == 0
    assert "[cached]" not in err2.getvalue()


# ------------------------------------- model-seeded batching, end to end

def test_hbm_model_seeds_batch_journal_and_report(tmp_path,
                                                  fresh_metrics,
                                                  monkeypatch):
    """CPU e2e of the model-seeded DM-batch pick: with an injected OOM
    threshold at 2 trials and a budget the model maps to a 2-trial cap,
    the 4-trial chunk splits PROACTIVELY — zero oom_bisections, the
    split counted as oom_predicted, peaks byte-identical to an
    unthrottled run — and the journal + rreport carry the
    predicted-vs-actual hbm calibration block."""
    from riptide_tpu.analysis.jaxpr_contract import hbm_model
    from riptide_tpu.obs.report import build_report, render_text
    from riptide_tpu.survey.faults import FaultPlan
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.scheduler import SurveyScheduler

    amps = {0.0: 15.0, 5.0: 25.0, 10.0: 40.0, 15.0: 15.0}
    files = make_survey(tmp_path, amps)

    clean = make_searcher().process_fname_list(files)
    assert fresh_metrics.counter("oom_bisections") == 0

    searcher = make_searcher(faults=FaultPlan.parse("oom:2"))
    nsamp = 16000  # TOBS / TSAMP of the synthetic survey files
    plan = searcher._plan_for(RANGES[0], nsamp, TSAMP)
    budget = hbm_model(plan).predict(2)
    assert hbm_model(plan).max_batch(budget) == 2
    monkeypatch.setenv("RIPTIDE_HBM_BUDGET", str(budget))

    scheduler = SurveyScheduler(
        searcher, [files], journal=SurveyJournal(tmp_path / "journal"),
        faults=searcher.faults,
    )
    peaks = scheduler.run()
    assert sorted(peaks) == sorted(clean)
    # The model seeded the split; the OOM fault (armed above 2 trials)
    # never fired and bisection never ran.
    assert fresh_metrics.counter("oom_bisections") == 0
    assert fresh_metrics.counter("oom_predicted") >= 1

    journal = SurveyJournal(tmp_path / "journal")
    (rec, _), = journal.completed_chunks().values()
    assert rec["hbm"]["predicted_bytes"] > 0
    assert rec["hbm"]["budget_bytes"] == budget
    # CPU backend exposes no memory stats: actual stays absent here
    # and is filled on real hardware.
    report = build_report(str(tmp_path / "journal"))
    assert report["hbm"]["n_modelled"] == 1
    assert report["hbm"]["predicted_bytes_max"] > 0
    assert report["hbm"]["budget_bytes"] == budget
    assert "hbm model:" in render_text(report)


def test_hbm_block_disabled_without_budget(fresh_metrics, monkeypatch):
    """Seeding off (no budget): no hbm block, no proactive split, the
    journal record carries an empty dict (pre-0.12 reader shape)."""
    monkeypatch.delenv("RIPTIDE_HBM_BUDGET", raising=False)
    bs = make_searcher()
    assert bs.chunk_hbm_block([]) is None
    assert bs._seed_batch_limit(_tiny_plan(), 1024) is None
