"""
Tier-1 enforcement of the riplint static-analysis framework
(tools/riplint.py + riptide_tpu/analysis/):

* the repo itself is clean against the checked-in baseline (this is
  the tier-1 wiring of every analyzer, including the whole-program
  RIP009/RIP010/RIP011 rules — each also wired individually below);
* each of the 14 analyzers fails on its bad fixture and passes on its
  good fixture (tests/analysis_fixtures/ — guard against vacuous
  lints);
* the runner's exit codes, baseline absorption, stale-entry detection
  (including the nearby-lines reflow fuzz), inline-pragma suppression,
  result cache and SARIF output behave as documented;
* the ProjectContext call graph resolves the bindings the
  interprocedural rules depend on (thread targets, self-methods,
  self-attribute and return types);
* a deliberately introduced lock-order inversion, journal-key rename
  and one-call-deep `.item()` below a jit body are each caught by
  their rule — on real package modules, not just fixtures;
* the analyzer set and rule ids are stable (a rename or renumber is an
  API break for baselines and pragmas — this must be a deliberate,
  test-acknowledged change), and `--list-rules` enumerates them;
* docs/env_flags.md matches the envflags registry and every RIPTIDE_*
  token in package sources is a registered flag.
"""
import io
import importlib.util
import json
import os
import re
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "analysis_fixtures")
RIPLINT = os.path.join(REPO, "tools", "riplint.py")


def _load_riplint():
    spec = importlib.util.spec_from_file_location("riplint_under_test",
                                                  RIPLINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


riplint = _load_riplint()
analysis = riplint.load_analysis(REPO)


def _mini_repo(tmp_path, mapping):
    """Build a throwaway repo: copy fixtures to their package-relative
    destinations, plus the real envflags.py (the RIP003 registry)."""
    for dest_rel, fixture in mapping.items():
        dest = tmp_path / dest_rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(FIXTURES, fixture), dest)
    reg = tmp_path / "riptide_tpu" / "utils" / "envflags.py"
    reg.parent.mkdir(parents=True, exist_ok=True)
    shutil.copy(os.path.join(REPO, "riptide_tpu", "utils", "envflags.py"),
                reg)
    return str(tmp_path)


def _run_one(repo, analyzer, dest_rel):
    ctx = analysis.ModuleContext(repo, dest_rel)
    return analyzer.run(ctx)


# -- per-analyzer fixture pairs ---------------------------------------------

# (analyzer factory, destination relpath, bad fixture, good fixture,
#  minimum bad findings)
CASES = [
    (analysis.HostSyncAnalyzer, "riptide_tpu/search/engine.py",
     "rip001_host_sync_bad.py", "rip001_host_sync_good.py", 5),
    (analysis.DtypeDisciplineAnalyzer, "riptide_tpu/ops/fixture.py",
     "rip002_dtype_bad.py", "rip002_dtype_good.py", 4),
    (analysis.EnvFlagAnalyzer, "riptide_tpu/pipeline/fixture.py",
     "rip003_envflags_bad.py", "rip003_envflags_good.py", 4),
    (analysis.LockDisciplineAnalyzer, "riptide_tpu/survey/liveness.py",
     "rip004_locks_bad.py", "rip004_locks_good.py", 5),
    (analysis.PallasLayoutAnalyzer, "riptide_tpu/ops/kern.py",
     "rip005_pallas_bad.py", "rip005_pallas_good.py", 4),
    (lambda: analysis.FiniteGuardAnalyzer(
        entry_points={"riptide_tpu/ops/snr.py": ["boxcar_snr",
                                                 "snr_batched"]}),
     "riptide_tpu/ops/snr.py",
     "rip006_finite_bad.py", "rip006_finite_good.py", 1),
    (lambda: analysis.LivenessGuardAnalyzer(
        allowed={"riptide_tpu/parallel/mh.py": {"ok"}}),
     "riptide_tpu/parallel/mh.py",
     "rip007_liveness_bad.py", "rip007_liveness_good.py", 2),
    (analysis.ObsDisciplineAnalyzer, "riptide_tpu/obs/fixture.py",
     "rip008_obs_bad.py", "rip008_obs_good.py", 4),
    (analysis.FsioDisciplineAnalyzer, "riptide_tpu/obs/writer.py",
     "rip013_fsio_bad.py", "rip013_fsio_good.py", 4),
    (analysis.GatePairingAnalyzer, "riptide_tpu/survey/gatemod.py",
     "rip014_gate_bad.py", "rip014_gate_good.py", 3),
]


@pytest.mark.parametrize(
    "factory,dest,bad,good,min_bad", CASES,
    ids=[c[2].rsplit("_", 1)[0] for c in CASES],
)
def test_analyzer_fails_bad_and_passes_good(tmp_path, factory, dest, bad,
                                            good, min_bad):
    repo_bad = _mini_repo(tmp_path / "bad", {dest: bad})
    inst = factory()
    findings = _run_one(repo_bad, inst, dest)
    assert len(findings) >= min_bad, \
        f"expected >= {min_bad} findings on {bad}, got " \
        f"{[f.gh() for f in findings]}"
    assert all(f.rule == inst.rule for f in findings)
    assert all(f.path == dest and f.line >= 1 for f in findings)

    repo_good = _mini_repo(tmp_path / "good", {dest: good})
    inst2 = factory()
    findings = _run_one(repo_good, inst2, dest)
    assert findings == [], "\n".join(f.gh() for f in findings)


# -- whole-program analyzer fixture pairs (run through run_analyzers so
# the ProjectContext is built) ----------------------------------------------

RECMOD = "riptide_tpu/survey/recmod.py"

PROJECT_CASES = [
    (analysis.LockOrderAnalyzer, "riptide_tpu/survey/pairmod.py",
     "rip009_lockorder_bad.py", "rip009_lockorder_good.py", 3),
    (lambda: analysis.RecordSchemaAnalyzer(
        writers=[(RECMOD, "write_chunk", None),
                 (RECMOD, "write_row", "ledger")],
        readers=[(RECMOD, "read_chunks")]),
     RECMOD, "rip010_schema_bad.py", "rip010_schema_good.py", 3),
    (analysis.InterpHostSyncAnalyzer, "riptide_tpu/ops/helpers.py",
     "rip011_interp_bad.py", "rip011_interp_good.py", 2),
    (analysis.RunctxDisciplineAnalyzer, "riptide_tpu/serve/spawnmod.py",
     "rip012_runctx_bad.py", "rip012_runctx_good.py", 3),
]


def _project_mini_repo(tmp_path, mapping):
    """A _mini_repo that also carries the real obs/schema.py (the
    RIP010 DECOMPOSITION_KEYS source) plus utils/runctx.py and
    survey/incidents.py (the RIP012 establish/emit anchors)."""
    repo = _mini_repo(tmp_path, mapping)
    for rel in (("obs", "schema.py"), ("utils", "runctx.py"),
                ("survey", "incidents.py")):
        dest = tmp_path / "riptide_tpu" / rel[0] / rel[1]
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, "riptide_tpu", *rel), dest)
    return repo


@pytest.mark.parametrize(
    "factory,dest,bad,good,min_bad", PROJECT_CASES,
    ids=[c[2].rsplit("_", 1)[0] for c in PROJECT_CASES],
)
def test_project_analyzer_fails_bad_and_passes_good(tmp_path, factory,
                                                    dest, bad, good,
                                                    min_bad):
    repo_bad = _project_mini_repo(tmp_path / "bad", {dest: bad})
    inst = factory()
    findings, _, _ = analysis.run_analyzers(repo_bad, [inst],
                                            baseline=analysis.Baseline())
    assert len(findings) >= min_bad, \
        f"expected >= {min_bad} findings on {bad}, got " \
        f"{[f.gh() for f in findings]}"
    assert all(f.rule == inst.rule for f in findings)
    assert all(f.path == dest and f.line >= 1 for f in findings)

    repo_good = _project_mini_repo(tmp_path / "good", {dest: good})
    inst2 = factory()
    findings, _, _ = analysis.run_analyzers(repo_good, [inst2],
                                            baseline=analysis.Baseline())
    assert findings == [], "\n".join(f.gh() for f in findings)


@pytest.mark.parametrize("cls", ["LockOrderAnalyzer",
                                 "RecordSchemaAnalyzer",
                                 "InterpHostSyncAnalyzer",
                                 "RunctxDisciplineAnalyzer",
                                 "FsioDisciplineAnalyzer",
                                 "GatePairingAnalyzer"])
def test_new_rule_clean_on_repo_against_baseline(cls):
    """Tier-1 wiring of each whole-program rule individually: the real
    repo is clean (any sanctioned site is a justified baseline entry,
    and stale entries of OTHER rules are expected when running one
    analyzer alone)."""
    baseline = analysis.Baseline.load(
        os.path.join(REPO, "tools", "riplint_baseline.json"))
    new, _, _ = analysis.run_analyzers(REPO, [getattr(analysis, cls)],
                                       baseline=baseline)
    assert new == [], "\n".join(f.gh() for f in new)


# -- ProjectContext call graph ----------------------------------------------

def test_call_graph_thread_target_and_self_resolution(tmp_path):
    """The bindings the interprocedural rules stand on: Thread(target=
    self._meth) edges, self.attr typing through __init__ assignment,
    and constructor/return-type resolution."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "workmod.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self.helper = Helper()\n"
        "\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop, daemon=True)"
        ".start()\n"
        "\n"
        "    def _loop(self):\n"
        "        self.helper.tick()\n"
        "\n"
        "class Helper:\n"
        "    def tick(self):\n"
        "        pass\n"
        "\n"
        "def make():\n"
        "    return Worker()\n"
        "\n"
        "def spin():\n"
        "    make().start()\n"
    )
    project = analysis.ProjectContext(
        repo, analysis.collect_contexts(repo))
    rel = "riptide_tpu/workmod.py"

    def edges(qual, kind):
        info = project.functions[f"{rel}::{qual}"]
        return {c for _, c, k in info.calls if k == kind}

    assert f"{rel}::Worker._loop" in edges("Worker.start", "thread")
    assert f"{rel}::Helper.tick" in edges("Worker._loop", "call")
    # Return-type inference: make() -> Worker, so make().start()
    # resolves.
    assert f"{rel}::Worker.start" in edges("spin", "call")
    # Reachability crosses thread edges only when asked to.
    roots = [f"{rel}::spin"]
    assert f"{rel}::Worker._loop" not in project.reachable(roots)
    assert f"{rel}::Worker._loop" in project.reachable(
        roots, kinds=("call", "thread"))


def test_explicit_acquire_inversion_is_caught(tmp_path):
    """A manual `A.acquire() ... try/finally: A.release()` region
    holds A for the statements between, so an inversion written in
    that style must produce the same RIP009 cycle as the `with` form
    (review regression)."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "manlock.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def one():\n"
        "    _a.acquire()\n"
        "    try:\n"
        "        with _b:\n"
        "            pass\n"
        "    finally:\n"
        "        _a.release()\n"
        "def two():\n"
        "    with _b:\n"
        "        with _a:\n"
        "            pass\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockOrderAnalyzer], baseline=analysis.Baseline())
    msgs = [f.gh() for f in new]
    assert any("lock-order inversion" in m for m in msgs), msgs


def test_balanced_try_finally_acquire_does_not_phantom_hold(tmp_path):
    """A self-contained `try: A.acquire() ... finally: A.release()`
    nets to nothing: the statements AFTER it run lock-free, so a
    later `with _b:` must not create an A->B edge (effects are applied
    in source order, not AST-walk order — review regression)."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "balanced.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def one():\n"
        "    try:\n"
        "        _a.acquire()\n"
        "    finally:\n"
        "        _a.release()\n"
        "    with _b:\n"
        "        pass\n"
        "def two():\n"
        "    with _b:\n"
        "        with _a:\n"
        "            pass\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockOrderAnalyzer], baseline=analysis.Baseline())
    assert new == [], "\n".join(f.gh() for f in new)


def test_rlock_reentrant_acquisition_not_flagged(tmp_path):
    """Re-acquiring a module-level RLock beneath itself is the whole
    point of RLock and must not be reported as a self-deadlock; the
    same shape with a plain Lock must be (review regression)."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "remod.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "_r = threading.RLock()\n"
        "def outer():\n"
        "    with _r:\n"
        "        inner()\n"
        "def inner():\n"
        "    with _r:\n"
        "        pass\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockOrderAnalyzer], baseline=analysis.Baseline())
    assert new == [], "\n".join(f.gh() for f in new)

    mod.write_text(mod.read_text().replace("RLock", "Lock"))
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockOrderAnalyzer], baseline=analysis.Baseline())
    assert len(new) == 1 and "self-deadlock" in new[0].message, \
        [f.gh() for f in new]


def test_call_graph_relative_imports_in_package_init(tmp_path):
    """`from .impl import helper` inside an __init__.py resolves
    against the package ITSELF (its dotted name already names the
    package — one fewer component to strip; review regression)."""
    repo = str(tmp_path)
    pkg = tmp_path / "riptide_tpu" / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text(
        "from .impl import helper\n"
        "def run():\n"
        "    return helper()\n"
    )
    (pkg / "impl.py").write_text("def helper():\n    return 1\n")
    project = analysis.ProjectContext(
        repo, analysis.collect_contexts(repo))
    info = project.functions["riptide_tpu/pkg/__init__.py::run"]
    assert [(c, k) for _, c, k in info.calls] == \
        [("riptide_tpu/pkg/impl.py::helper", "call")]


def test_nested_def_under_lock_is_not_attributed_to_outer(tmp_path):
    """Defining (without calling) a function under a held lock defers
    its body: no ordering edge may flow from the definition site, so
    the legitimate B->A order elsewhere is not a cycle (review
    regression). Same boundary keeps an uncalled host callback defined
    inside a jit body out of RIP011."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "defermod.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "import jax\n"
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def takes_b():\n"
        "    with _b:\n"
        "        pass\n"
        "def outer():\n"
        "    with _a:\n"
        "        def deferred():\n"
        "            takes_b()\n"
        "        return deferred\n"
        "def other():\n"
        "    with _b:\n"
        "        with _a:\n"
        "            pass\n"
        "def defines_acquirer():\n"
        "    def helper():\n"
        "        _a.acquire()\n"
        "    with _a:\n"
        "        return helper\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    def callback(v):\n"
        "        return v.item()\n"
        "    return x\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo,
        [analysis.LockOrderAnalyzer, analysis.InterpHostSyncAnalyzer],
        baseline=analysis.Baseline())
    assert new == [], "\n".join(f.gh() for f in new)


# -- the three acceptance demonstrations on real package modules ------------

def _copy_real(tmp_path, rels):
    for rel in rels:
        dest = tmp_path / rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), dest)
    return str(tmp_path)


def _patched(path, old, new):
    src = path.read_text()
    assert old in src, f"patch anchor missing from {path}: {old!r}"
    path.write_text(src.replace(old, new))


def test_introduced_lock_order_inversion_is_caught(tmp_path):
    """Deliberately invert the incidents-lock / metrics-lock order on
    the REAL modules: emit() bumps the metrics counter while holding
    incidents._lock, and MetricsRegistry.add() reads last_incident()
    under its own lock. RIP009 must report the cycle."""
    rels = ["riptide_tpu/survey/incidents.py",
            "riptide_tpu/survey/metrics.py"]
    repo = _copy_real(tmp_path, rels)
    # Clean copies first: no findings.
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockOrderAnalyzer],
        baseline=analysis.Baseline())
    assert new == [], "\n".join(f.gh() for f in new)

    _patched(
        tmp_path / "riptide_tpu" / "survey" / "incidents.py",
        "    get_metrics().add(\"incidents\")\n"
        "    with _lock:\n",
        "    with _lock:\n"
        "        get_metrics().add(\"incidents\")\n",
    )
    _patched(
        tmp_path / "riptide_tpu" / "survey" / "metrics.py",
        "    def add(self, name, value=1):\n"
        "        \"\"\"Increment counter ``name`` by ``value``.\"\"\"\n"
        "        with self._lock:\n",
        "    def add(self, name, value=1):\n"
        "        \"\"\"Increment counter ``name`` by ``value``.\"\"\"\n"
        "        from .incidents import last_incident\n"
        "        with self._lock:\n"
        "            last_incident()\n",
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockOrderAnalyzer],
        baseline=analysis.Baseline())
    msgs = [f.gh() for f in new]
    assert any("lock-order inversion" in m and "RIP009" in m
               for m in msgs), msgs


def test_renamed_journal_key_is_caught(tmp_path):
    """Rename a chunk-record writer key on the REAL journal module: the
    resume loader still reads the old name, and RIP010 must flag the
    read as consuming a key no writer emits."""
    rels = ["riptide_tpu/survey/journal.py"]
    repo = _copy_real(tmp_path, rels)
    writers = [("riptide_tpu/survey/journal.py", q, f) for q, f in [
        ("SurveyJournal.write_header", None),
        ("SurveyJournal.record_chunk", None),
        ("SurveyJournal.record_parked", None),
        ("SurveyJournal.record_metrics", None),
        ("SurveyJournal.record_incident", "incident"),
        ("SurveyJournal.heartbeat", "heartbeat"),
    ]]
    readers = [("riptide_tpu/survey/journal.py", None)]

    def run_schema():
        inst = analysis.RecordSchemaAnalyzer(writers=writers,
                                             readers=readers)
        new, _, _ = analysis.run_analyzers(repo, [inst],
                                           baseline=analysis.Baseline())
        return new

    assert run_schema() == [], \
        "\n".join(f.gh() for f in run_schema())
    _patched(tmp_path / "riptide_tpu" / "survey" / "journal.py",
             '"peaks_offset": offset,', '"peak_off": offset,')
    new = run_schema()
    assert any("'peaks_offset'" in f.message and f.rule == "RIP010"
               for f in new), [f.gh() for f in new]


def test_unwrapped_stage_submit_is_caught(tmp_path):
    """RIP012 non-vacuity on the REAL scheduler: drop the runctx.wrap
    around the staging-thread target and the rule must flag the raw
    submit (its incident/journal writes would land in the pool
    worker's empty context)."""
    rels = ["riptide_tpu/survey/scheduler.py",
            "riptide_tpu/utils/runctx.py",
            "riptide_tpu/survey/incidents.py"]
    repo = _copy_real(tmp_path, rels)
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.RunctxDisciplineAnalyzer],
        baseline=analysis.Baseline())
    assert new == [], "\n".join(f.gh() for f in new)

    _patched(tmp_path / "riptide_tpu" / "survey" / "scheduler.py",
             "stage = runctx.wrap(self._stage)",
             "stage = self._stage")
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.RunctxDisciplineAnalyzer],
        baseline=analysis.Baseline())
    assert any(f.rule == "RIP012" and "_stage" in f.message
               for f in new), [f.gh() for f in new]


def test_raw_peaks_csv_write_is_caught(tmp_path):
    """RIP013 non-vacuity on the REAL daemon: reintroduce the raw
    empty-peaks open() that fsio.atomic_write_text replaced and the
    rule must flag it."""
    dest = "riptide_tpu/serve/daemon.py"
    repo = _copy_real(tmp_path, [dest])
    inst = analysis.FsioDisciplineAnalyzer()
    assert _run_one(repo, inst, dest) == []

    _patched(tmp_path / "riptide_tpu" / "serve" / "daemon.py",
             'fsio.atomic_write_text(path, "")',
             'open(path, "w").close()')
    new = _run_one(repo, analysis.FsioDisciplineAnalyzer(), dest)
    assert len(new) == 1 and new[0].rule == "RIP013", \
        [f.gh() for f in new]
    assert "open" in new[0].message


def test_dropped_chunk_gate_end_is_caught(tmp_path):
    """RIP014 non-vacuity on the REAL scheduler: drop the end() from
    the turn-accounting finally and the rule must flag the begin()
    (a parked/failed chunk would hold the device turn forever)."""
    dest = "riptide_tpu/survey/scheduler.py"
    repo = _copy_real(tmp_path, [dest])
    inst = analysis.GatePairingAnalyzer()
    assert _run_one(repo, inst, dest) == []

    _patched(tmp_path / "riptide_tpu" / "survey" / "scheduler.py",
             "self.chunk_gate.end(cid)",
             "pass")
    new = _run_one(repo, analysis.GatePairingAnalyzer(), dest)
    assert len(new) == 1 and new[0].rule == "RIP014", \
        [f.gh() for f in new]
    assert "begin" in new[0].message


def test_kernel_root_leaf_name_does_not_capture_methods(tmp_path):
    """A class method sharing a Pallas kernel root's leaf name is host
    code: it must be neither treated as a traced root (false RIP011
    findings in its callees) nor exempted from scanning (review
    regression)."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "kmod.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "from jax.experimental import pallas as pl\n"
        "def _body(ref):\n"
        "    pass\n"
        "def launch(x, shp):\n"
        "    return pl.pallas_call(_body, out_shape=shp, grid=(1,))(x)\n"
        "def _host_helper(v):\n"
        "    return v.item()\n"
        "class Stats:\n"
        "    def _body(self, v):\n"
        "        return _host_helper(v)\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.InterpHostSyncAnalyzer],
        baseline=analysis.Baseline())
    assert new == [], "\n".join(f.gh() for f in new)


def test_local_constructor_binding_is_flow_sensitive(tmp_path):
    """A rebound local must not type earlier uses: `x = maker();
    x.close(); x = Helper()` may not produce an edge to Helper.close,
    while a straight bind-then-use still resolves (review
    regression)."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "flowmod.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "class Helper:\n"
        "    def close(self):\n"
        "        pass\n"
        "def f(maker):\n"
        "    x = maker()\n"
        "    x.close()\n"
        "    x = Helper()\n"
        "    return x\n"
        "def g():\n"
        "    h = Helper()\n"
        "    h.close()\n"
    )
    project = analysis.ProjectContext(
        repo, analysis.collect_contexts(repo))
    rel = "riptide_tpu/flowmod.py"
    f_edges = {c for _, c, _ in project.functions[f"{rel}::f"].calls}
    g_edges = {c for _, c, _ in project.functions[f"{rel}::g"].calls}
    assert f"{rel}::Helper.close" not in f_edges
    assert f"{rel}::Helper.close" in g_edges


def test_one_call_deep_item_in_jit_helper_is_caught(tmp_path):
    """A `.item()` moved one helper call below a jit body passes
    RIP001's body scan and must be caught by RIP011 instead, with the
    root and call chain named in the message."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "jithelp.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "def _threshold(x):\n"
        "    return x.min().item()\n"
        "\n"
        "@jax.jit\n"
        "def scan(x):\n"
        "    return jnp.clip(x, _threshold(x), None)\n"
    )
    # RIP001 (body-only) misses it...
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.HostSyncAnalyzer(hot_functions={})],
        baseline=analysis.Baseline())
    assert new == [], "\n".join(f.gh() for f in new)
    # ... RIP011 catches it and names the chain.
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.InterpHostSyncAnalyzer],
        baseline=analysis.Baseline())
    assert len(new) == 1 and new[0].rule == "RIP011", \
        [f.gh() for f in new]
    assert "scan" in new[0].message and "_threshold" in new[0].message


def test_liveness_good_fixture_not_vacuous(tmp_path):
    """The good RIP007 fixture must keep the wrapped-call counter
    non-zero, or finalize would report the lint as vacuous."""
    dest = "riptide_tpu/parallel/mh.py"
    repo = _mini_repo(tmp_path, {dest: "rip007_liveness_good.py"})
    inst = analysis.LivenessGuardAnalyzer(allowed={dest: {"ok"}})
    assert _run_one(repo, inst, dest) == []
    assert inst.finalize(repo, []) == []


# -- whole-repo cleanliness (the tier-1 wiring) -----------------------------

def test_repo_is_clean_against_baseline():
    out, err = io.StringIO(), io.StringIO()
    code = riplint.run(out=out, err=err)
    assert code == 0, f"riplint found new issues:\n{out.getvalue()}"


def test_runner_exit_codes_subprocess():
    proc = subprocess.run([sys.executable, RIPLINT], capture_output=True,
                          text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "riplint OK" in proc.stderr


def test_runner_flags_violation_and_baseline_absorbs(tmp_path):
    dest = "riptide_tpu/survey/liveness.py"
    repo = _mini_repo(tmp_path, {dest: "rip004_locks_bad.py"})
    analyzers = [analysis.LockDisciplineAnalyzer(modules={dest})]

    new, baselined, stale = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline()
    )
    assert new and not baselined and not stale
    # GitHub-annotation format: path:line:col: RIPxxx message
    assert re.match(r"^riptide_tpu/survey/liveness\.py:\d+:\d+: RIP004 ",
                    new[0].gh())

    # A baseline entry matching each finding's (rule, path, line text)
    # absorbs them all...
    ctx = analysis.ModuleContext(repo, dest)
    entries = [analysis.Baseline.entry_for(f, ctx, why="fixture")
               for f in new]
    new2, baselined2, stale2 = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline(entries)
    )
    assert new2 == [] and len(baselined2) >= len(entries) - 1
    assert stale2 == []

    # ... and an entry matching nothing is reported stale.
    bogus = [{"rule": "RIP004", "path": dest,
              "line_text": "this_line_does_not_exist()",
              "why": "stale"}]
    _, _, stale3 = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline(entries + bogus)
    )
    assert stale3 == bogus


def test_scope_lists_fail_loudly_when_stale(tmp_path):
    """RIP001/RIP002/RIP004 scope their checks by module/function name;
    a rename must produce a stale-scope finding, not silently unscope
    the lint (review regression)."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "search" / "engine.py"
    mod.parent.mkdir(parents=True)
    # engine.py exists but the hot function was "renamed" away.
    mod.write_text("def renamed_queue_stages():\n    pass\n")

    new, _, _ = analysis.run_analyzers(
        repo,
        [analysis.HostSyncAnalyzer, analysis.LockDisciplineAnalyzer,
         analysis.DtypeDisciplineAnalyzer],
        baseline=analysis.Baseline(),
    )
    msgs = [f.gh() for f in new]
    assert any("_queue_stages" in m and "stale" in m for m in msgs), msgs
    # Every configured-but-missing module is reported by each analyzer.
    assert any("batcher.py" in m and "stale" in m for m in msgs), msgs
    assert any("liveness.py" in m and "stale" in m for m in msgs), msgs
    assert any("peaks_device.py" in m and "stale" in m for m in msgs), msgs


def test_untimed_join_under_lock_reported_once(tmp_path):
    """One defect, one finding: the under-lock and module-wide walks
    must not double-report the same untimed join (review regression)."""
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def drain(worker):\n"
        "    with _lock:\n"
        "        worker.join()\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(),
    )
    joins = [f for f in new if "join" in f.message]
    assert len(joins) == 1, [f.gh() for f in new]


def test_pathonly_baseline_entry_is_not_stale(tmp_path):
    """An empty-line_text entry is the documented way to baseline a
    finding outside the package (no ModuleContext, e.g. docs drift);
    it must absorb the finding AND count as used, or the run could
    never go green."""
    repo = str(tmp_path)
    (tmp_path / "riptide_tpu").mkdir()
    (tmp_path / "riptide_tpu" / "empty.py").write_text("x = 1\n")

    class OutsideFinding(analysis.Analyzer):
        rule = "RIP999"
        name = "outside"

        def finalize(self, repo, contexts):
            return [analysis.Finding("docs/somewhere.md", 1, 0,
                                     self.rule, "drifted")]

    entry = {"rule": "RIP999", "path": "docs/somewhere.md",
             "line_text": "", "why": "tracked elsewhere"}
    new, baselined, stale = analysis.run_analyzers(
        repo, [OutsideFinding], baseline=analysis.Baseline([entry])
    )
    assert new == [] and len(baselined) == 1 and stale == []


def test_reused_analyzer_instance_resets_state(tmp_path):
    """A reused instance must not leak run state: after a clean run
    over a tree WITH wrapped collectives, a second run over a tree
    WITHOUT them must still report the vacuous-lint failure."""
    dest = "riptide_tpu/parallel/mh.py"
    good = _mini_repo(tmp_path / "a", {dest: "rip007_liveness_good.py"})
    empty = str(tmp_path / "b")
    (tmp_path / "b" / "riptide_tpu").mkdir(parents=True)
    (tmp_path / "b" / "riptide_tpu" / "empty.py").write_text("x = 1\n")

    inst = analysis.LivenessGuardAnalyzer(allowed={dest: {"ok"}})
    new1, _, _ = analysis.run_analyzers(good, [inst],
                                        baseline=analysis.Baseline())
    assert new1 == []
    new2, _, _ = analysis.run_analyzers(empty, [inst],
                                        baseline=analysis.Baseline())
    assert len(new2) == 1 and "vacuous" in new2[0].message


def test_keyword_timeout_under_lock_not_flagged(tmp_path):
    """A wait/join with a keyword timeout under a held lock follows
    the rule and must not be flagged (review regression)."""
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def drain(evt, worker):\n"
        "    with _lock:\n"
        "        evt.wait(timeout=5.0)\n"
        "        worker.join(timeout=5.0)\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(),
    )
    assert new == [], "\n".join(f.gh() for f in new)


def test_inline_pragma_suppression(tmp_path):
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "def shutdown(done, worker):\n"
        "    done.wait()  # riplint: disable=RIP004\n"
        "    worker.join()\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(),
    )
    # Only the unsuppressed join() survives.
    assert len(new) == 1 and "join" in new[0].message


# -- stability + docs -------------------------------------------------------

def test_analyzer_set_and_rule_ids_are_stable():
    """Rule ids are an API: baselines, pragmas and CI annotations key
    on them. Renaming or renumbering must be a deliberate change that
    updates this test (and migrates the baseline)."""
    got = {(a.rule, a.name) for a in analysis.ALL_ANALYZERS}
    assert got == {
        ("RIP001", "host-sync"),
        ("RIP002", "dtype-discipline"),
        ("RIP003", "env-flags"),
        ("RIP004", "lock-discipline"),
        ("RIP005", "pallas-layout"),
        ("RIP006", "finite-guards"),
        ("RIP007", "liveness-guards"),
        ("RIP008", "obs-discipline"),
        ("RIP009", "lock-order"),
        ("RIP010", "record-schema"),
        ("RIP011", "interp-host-sync"),
        ("RIP012", "runctx-discipline"),
        ("RIP013", "fsio-discipline"),
        ("RIP014", "gate-pairing"),
    }
    rules = [a.rule for a in analysis.ALL_ANALYZERS]
    assert len(rules) == len(set(rules)) == 14


def test_list_rules_enumerates_the_set():
    proc = subprocess.run([sys.executable, RIPLINT, "--list-rules"],
                          capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 14
    ids = [l.split()[0] for l in lines]
    assert ids == [f"RIP{n:03d}" for n in range(1, 15)]
    assert any("lock-order" in l for l in lines)
    assert any("record-schema" in l for l in lines)
    assert any("interp-host-sync" in l for l in lines)
    assert any("runctx-discipline" in l for l in lines)
    assert any("gate-pairing" in l for l in lines)


def test_env_docs_in_sync_with_registry():
    registry = analysis.env_flags.load_registry(REPO)
    with open(os.path.join(REPO, "docs", "env_flags.md")) as fobj:
        assert fobj.read() == registry.render_markdown()


def test_every_package_flag_token_is_registered():
    registry = analysis.env_flags.load_registry(REPO)
    token = re.compile(r"RIPTIDE_[A-Z0-9_]+")
    unknown = set()
    for ctx in analysis.collect_contexts(REPO):
        # Tokens ending in "_" are docs-string wildcards
        # ("RIPTIDE_TRACE_*"), not flag names.
        unknown.update(t for t in token.findall(ctx.source)
                       if t not in registry.FLAGS
                       and not t.endswith("_"))
    assert unknown == set(), \
        f"undeclared RIPTIDE_* names in package sources: {sorted(unknown)}"


def test_baseline_entries_are_justified():
    with open(os.path.join(REPO, "tools", "riplint_baseline.json")) as fobj:
        entries = json.load(fobj)["entries"]
    assert entries, "baseline exists and is non-empty"
    for e in entries:
        assert e["why"] and "TODO" not in e["why"], \
            f"unjustified baseline entry: {e}"


# -- baseline nearby-lines staleness fuzz -----------------------------------

def test_baseline_entry_survives_nearby_line_reflow(tmp_path):
    """An entry whose text survives within +-3 lines of a finding
    whose own text is a fragment of it (the flagged line of a
    reworked statement moved under an unrelated edit) must still
    absorb the finding and must NOT read as stale; an entry matching
    nothing anywhere near stays stale."""
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "def drain(worker):\n"
        "    worker.join()  # riplint: disable=RIP004\n"
        "    worker.join()\n"
    )
    analyzers = [analysis.LockDisciplineAnalyzer(modules={dest})]
    nearby = [{"rule": "RIP004", "path": dest,
               "line_text": "worker.join()  # riplint: disable=RIP004",
               "why": "reflow fuzz"}]
    new, baselined, stale = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline(nearby))
    assert new == [] and len(baselined) == 1 and stale == []

    far = [{"rule": "RIP004", "path": dest,
            "line_text": "nowhere_near_anything()", "why": "stale"}]
    _, _, stale2 = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline(nearby + far))
    assert stale2 == far


def test_nearby_fuzz_requires_related_text(tmp_path):
    """An unused entry must not absorb an UNRELATED new violation that
    merely lands within +-3 lines of its text: the finding's own line
    text must be a fragment of the entry's (or vice versa), and a
    redundant entry is reported stale rather than silently consumed
    (review regression)."""
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "def drain(worker, other):\n"
        "    worker.join()  # riplint: disable=RIP004\n"
        "    other.join()\n"
    )
    entries = [{"rule": "RIP004", "path": dest,
                "line_text": "worker.join()  # riplint: disable=RIP004",
                "why": "redundant"}]
    new, baselined, stale = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(entries))
    assert len(new) == 1 and new[0].line == 3, [f.gh() for f in new]
    assert baselined == [] and stale == entries


def test_nearby_fuzz_does_not_absorb_new_neighbour_violation(tmp_path):
    """A brand-new violation a couple of lines from a baselined one
    must still surface: the entry exact-matches its own finding (and
    is thereby used), so the fuzz may not also swallow the neighbour
    (review regression)."""
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "def drain(worker):\n"
        "    worker.join()\n"
        "\n"
        "def drain_two(other):\n"
        "    other.join()\n"
    )
    entries = [{"rule": "RIP004", "path": dest,
                "line_text": "worker.join()", "why": "documented"}]
    new, baselined, stale = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(entries))
    assert len(baselined) == 1 and stale == []
    assert len(new) == 1, [f.gh() for f in new]
    assert new[0].line == 5 and new[0].rule == "RIP004"


# -- result cache + SARIF output --------------------------------------------

def test_cache_replays_unchanged_tree_and_invalidates_on_touch():
    out1, err1 = io.StringIO(), io.StringIO()
    code1 = riplint.run(out=out1, err=err1)  # populates the cache
    out2, err2 = io.StringIO(), io.StringIO()
    code2 = riplint.run(out=out2, err=err2)
    assert code1 == code2 == 0
    assert "[cached]" in err2.getvalue()
    assert out1.getvalue() == out2.getvalue()

    # --no-cache (use_cache=False) always runs fresh.
    out3, err3 = io.StringIO(), io.StringIO()
    assert riplint.run(out=out3, err=err3, use_cache=False) == 0
    assert "[cached]" not in err3.getvalue()

    # Any tracked file's mtime change invalidates the replay.
    bench = os.path.join(REPO, "bench.py")
    os.utime(bench)
    out4, err4 = io.StringIO(), io.StringIO()
    assert riplint.run(out=out4, err=err4) == 0
    assert "[cached]" not in err4.getvalue()
    # ... and the fresh run re-primes it.
    out5, err5 = io.StringIO(), io.StringIO()
    assert riplint.run(out=out5, err=err5) == 0
    assert "[cached]" in err5.getvalue()


def test_cache_invalidates_on_out_of_tree_baseline_edit(tmp_path):
    """A custom --baseline outside the tracked roots is stat'd
    explicitly: editing it must invalidate the replay (review
    regression)."""
    custom = tmp_path / "team_baseline.json"
    shutil.copy(os.path.join(REPO, "tools", "riplint_baseline.json"),
                custom)
    riplint.run(baseline_path=str(custom), out=io.StringIO(),
                err=io.StringIO())
    err2 = io.StringIO()
    riplint.run(baseline_path=str(custom), out=io.StringIO(), err=err2)
    assert "[cached]" in err2.getvalue()
    custom.write_text(custom.read_text().replace("}\n", "} \n", 1))
    err3 = io.StringIO()
    riplint.run(baseline_path=str(custom), out=io.StringIO(), err=err3)
    assert "[cached]" not in err3.getvalue()


def test_cache_not_used_for_custom_analyzer_sets():
    """A caller-injected analyzer subset must bypass the cache in both
    directions (never served, never stored)."""
    riplint.run(out=io.StringIO(), err=io.StringIO())  # prime
    out, err = io.StringIO(), io.StringIO()
    riplint.run(analyzers=[analysis.HostSyncAnalyzer],
                out=out, err=err)
    assert "[cached]" not in err.getvalue()
    assert "1 analyzers" in err.getvalue()


def test_prune_baseline_drops_unmatched_entries(tmp_path):
    """--prune-baseline lifecycle: absorb real findings into a
    baseline, inject an entry matching nothing, prune (drops ONLY the
    unmatched entry), and a plain rerun against the pruned file is
    clean."""
    dest = "riptide_tpu/obs/writer.py"
    repo = _mini_repo(tmp_path, {dest: "rip013_fsio_bad.py"})
    bl = tmp_path / "baseline.json"
    analyzers = [analysis.FsioDisciplineAnalyzer]

    code = riplint.run(repo=repo, baseline_path=str(bl),
                       analyzers=analyzers, update_baseline=True,
                       out=io.StringIO(), err=io.StringIO())
    assert code == 0
    entries = json.loads(bl.read_text())["entries"]
    n_real = len(entries)
    assert n_real >= 4

    bogus = {"rule": "RIP013", "path": dest,
             "line_text": "this_line_does_not_exist()", "why": "gone"}
    bl.write_text(json.dumps({"entries": entries + [bogus]}))
    # A plain run reports (and fails on) the stale entry...
    out1, err1 = io.StringIO(), io.StringIO()
    code1 = riplint.run(repo=repo, baseline_path=str(bl),
                        analyzers=analyzers, out=out1, err=err1)
    assert code1 == 1 and "STALE" in out1.getvalue()
    # ... prune drops it (and only it) ...
    out2, err2 = io.StringIO(), io.StringIO()
    code2 = riplint.run(repo=repo, baseline_path=str(bl),
                        analyzers=analyzers, prune_baseline=True,
                        out=out2, err=err2)
    assert code2 == 0, out2.getvalue() + err2.getvalue()
    assert "baseline pruned" in err2.getvalue()
    pruned = json.loads(bl.read_text())["entries"]
    assert len(pruned) == n_real and bogus not in pruned
    # ... and the plain rerun against the pruned file is clean.
    out3, err3 = io.StringIO(), io.StringIO()
    code3 = riplint.run(repo=repo, baseline_path=str(bl),
                        analyzers=analyzers, out=out3, err=err3)
    assert code3 == 0, out3.getvalue() + err3.getvalue()


def test_prune_baseline_still_fails_on_new_findings(tmp_path):
    """Pruning must not launder NEW findings: a prune run over a tree
    with unbaselined findings still exits 1 (it only rewrites the
    entry list, it does not absorb)."""
    dest = "riptide_tpu/obs/writer.py"
    repo = _mini_repo(tmp_path, {dest: "rip013_fsio_bad.py"})
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"entries": [
        {"rule": "RIP013", "path": dest,
         "line_text": "this_line_does_not_exist()", "why": "gone"}]}))
    out, err = io.StringIO(), io.StringIO()
    code = riplint.run(repo=repo, baseline_path=str(bl),
                       analyzers=[analysis.FsioDisciplineAnalyzer],
                       prune_baseline=True, out=out, err=err)
    assert code == 1, out.getvalue() + err.getvalue()
    assert json.loads(bl.read_text())["entries"] == []


def test_cache_tracks_ripsched_surface():
    """The ripsched analyzer source and its pinned invariant specs are
    inside the cache's tracked file set: touching either must
    invalidate a cached replay."""
    riplint.run(out=io.StringIO(), err=io.StringIO())  # prime
    err0 = io.StringIO()
    riplint.run(out=io.StringIO(), err=err0)
    assert "[cached]" in err0.getvalue()

    os.utime(os.path.join(REPO, "tools", "ripsched_invariants.json"))
    err1 = io.StringIO()
    riplint.run(out=io.StringIO(), err=err1)
    assert "[cached]" not in err1.getvalue()

    os.utime(os.path.join(REPO, "riptide_tpu", "analysis", "sched.py"))
    err2 = io.StringIO()
    riplint.run(out=io.StringIO(), err=err2)
    assert "[cached]" not in err2.getvalue()


def test_sarif_output_schema():
    out, err = io.StringIO(), io.StringIO()
    code = riplint.run(out=out, err=err, fmt="sarif", use_cache=False)
    assert code == 0
    doc = json.loads(out.getvalue())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "riplint"
    rules = run["tool"]["driver"]["rules"]
    assert [r["id"] for r in rules] == \
        [f"RIP{n:03d}" for n in range(1, 15)]
    assert all(r["shortDescription"]["text"] for r in rules)
    assert run["results"] == []  # clean repo


def test_sarif_findings_and_stale_entries_become_results():
    instances = [a() for a in analysis.ALL_ANALYZERS]
    result = {
        "new": [{"path": "riptide_tpu/x.py", "line": 12, "col": 4,
                 "rule": "RIP009", "message": "lock-order inversion"}],
        "stale": [{"rule": "RIP004", "path": "riptide_tpu/y.py",
                   "line_text": "gone()", "why": "old"}],
        "baselined": 0, "n_rules": 14, "n_modules": 1,
    }
    doc = riplint._sarif_doc(result, instances)
    results = doc["runs"][0]["results"]
    assert len(results) == 2
    assert results[0]["ruleId"] == "RIP009"
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "riptide_tpu/x.py"
    assert loc["region"] == {"startLine": 12, "startColumn": 5}
    assert "STALE" in results[1]["message"]["text"]
