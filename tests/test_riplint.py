"""
Tier-1 enforcement of the riplint static-analysis framework
(tools/riplint.py + riptide_tpu/analysis/):

* the repo itself is clean against the checked-in baseline (this is
  the tier-1 wiring of every analyzer, including the ported finite- and
  liveness-guard rules);
* each of the 8 analyzers fails on its bad fixture and passes on its
  good fixture (tests/analysis_fixtures/ — guard against vacuous
  lints);
* the runner's exit codes, baseline absorption, stale-entry detection
  and inline-pragma suppression behave as documented;
* the analyzer set and rule ids are stable (a rename or renumber is an
  API break for baselines and pragmas — this must be a deliberate,
  test-acknowledged change);
* docs/env_flags.md matches the envflags registry and every RIPTIDE_*
  token in package sources is a registered flag.
"""
import io
import importlib.util
import json
import os
import re
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "analysis_fixtures")
RIPLINT = os.path.join(REPO, "tools", "riplint.py")


def _load_riplint():
    spec = importlib.util.spec_from_file_location("riplint_under_test",
                                                  RIPLINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


riplint = _load_riplint()
analysis = riplint.load_analysis(REPO)


def _mini_repo(tmp_path, mapping):
    """Build a throwaway repo: copy fixtures to their package-relative
    destinations, plus the real envflags.py (the RIP003 registry)."""
    for dest_rel, fixture in mapping.items():
        dest = tmp_path / dest_rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(FIXTURES, fixture), dest)
    reg = tmp_path / "riptide_tpu" / "utils" / "envflags.py"
    reg.parent.mkdir(parents=True, exist_ok=True)
    shutil.copy(os.path.join(REPO, "riptide_tpu", "utils", "envflags.py"),
                reg)
    return str(tmp_path)


def _run_one(repo, analyzer, dest_rel):
    ctx = analysis.ModuleContext(repo, dest_rel)
    return analyzer.run(ctx)


# -- per-analyzer fixture pairs ---------------------------------------------

# (analyzer factory, destination relpath, bad fixture, good fixture,
#  minimum bad findings)
CASES = [
    (analysis.HostSyncAnalyzer, "riptide_tpu/search/engine.py",
     "rip001_host_sync_bad.py", "rip001_host_sync_good.py", 5),
    (analysis.DtypeDisciplineAnalyzer, "riptide_tpu/ops/fixture.py",
     "rip002_dtype_bad.py", "rip002_dtype_good.py", 4),
    (analysis.EnvFlagAnalyzer, "riptide_tpu/pipeline/fixture.py",
     "rip003_envflags_bad.py", "rip003_envflags_good.py", 4),
    (analysis.LockDisciplineAnalyzer, "riptide_tpu/survey/liveness.py",
     "rip004_locks_bad.py", "rip004_locks_good.py", 5),
    (analysis.PallasLayoutAnalyzer, "riptide_tpu/ops/kern.py",
     "rip005_pallas_bad.py", "rip005_pallas_good.py", 4),
    (lambda: analysis.FiniteGuardAnalyzer(
        entry_points={"riptide_tpu/ops/snr.py": ["boxcar_snr",
                                                 "snr_batched"]}),
     "riptide_tpu/ops/snr.py",
     "rip006_finite_bad.py", "rip006_finite_good.py", 1),
    (lambda: analysis.LivenessGuardAnalyzer(
        allowed={"riptide_tpu/parallel/mh.py": {"ok"}}),
     "riptide_tpu/parallel/mh.py",
     "rip007_liveness_bad.py", "rip007_liveness_good.py", 2),
    (analysis.ObsDisciplineAnalyzer, "riptide_tpu/obs/fixture.py",
     "rip008_obs_bad.py", "rip008_obs_good.py", 4),
]


@pytest.mark.parametrize(
    "factory,dest,bad,good,min_bad", CASES,
    ids=[c[2].rsplit("_", 1)[0] for c in CASES],
)
def test_analyzer_fails_bad_and_passes_good(tmp_path, factory, dest, bad,
                                            good, min_bad):
    repo_bad = _mini_repo(tmp_path / "bad", {dest: bad})
    inst = factory()
    findings = _run_one(repo_bad, inst, dest)
    assert len(findings) >= min_bad, \
        f"expected >= {min_bad} findings on {bad}, got " \
        f"{[f.gh() for f in findings]}"
    assert all(f.rule == inst.rule for f in findings)
    assert all(f.path == dest and f.line >= 1 for f in findings)

    repo_good = _mini_repo(tmp_path / "good", {dest: good})
    inst2 = factory()
    findings = _run_one(repo_good, inst2, dest)
    assert findings == [], "\n".join(f.gh() for f in findings)


def test_liveness_good_fixture_not_vacuous(tmp_path):
    """The good RIP007 fixture must keep the wrapped-call counter
    non-zero, or finalize would report the lint as vacuous."""
    dest = "riptide_tpu/parallel/mh.py"
    repo = _mini_repo(tmp_path, {dest: "rip007_liveness_good.py"})
    inst = analysis.LivenessGuardAnalyzer(allowed={dest: {"ok"}})
    assert _run_one(repo, inst, dest) == []
    assert inst.finalize(repo, []) == []


# -- whole-repo cleanliness (the tier-1 wiring) -----------------------------

def test_repo_is_clean_against_baseline():
    out, err = io.StringIO(), io.StringIO()
    code = riplint.run(out=out, err=err)
    assert code == 0, f"riplint found new issues:\n{out.getvalue()}"


def test_runner_exit_codes_subprocess():
    proc = subprocess.run([sys.executable, RIPLINT], capture_output=True,
                          text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "riplint OK" in proc.stderr


def test_runner_flags_violation_and_baseline_absorbs(tmp_path):
    dest = "riptide_tpu/survey/liveness.py"
    repo = _mini_repo(tmp_path, {dest: "rip004_locks_bad.py"})
    analyzers = [analysis.LockDisciplineAnalyzer(modules={dest})]

    new, baselined, stale = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline()
    )
    assert new and not baselined and not stale
    # GitHub-annotation format: path:line:col: RIPxxx message
    assert re.match(r"^riptide_tpu/survey/liveness\.py:\d+:\d+: RIP004 ",
                    new[0].gh())

    # A baseline entry matching each finding's (rule, path, line text)
    # absorbs them all...
    ctx = analysis.ModuleContext(repo, dest)
    entries = [analysis.Baseline.entry_for(f, ctx, why="fixture")
               for f in new]
    new2, baselined2, stale2 = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline(entries)
    )
    assert new2 == [] and len(baselined2) >= len(entries) - 1
    assert stale2 == []

    # ... and an entry matching nothing is reported stale.
    bogus = [{"rule": "RIP004", "path": dest,
              "line_text": "this_line_does_not_exist()",
              "why": "stale"}]
    _, _, stale3 = analysis.run_analyzers(
        repo, analyzers, baseline=analysis.Baseline(entries + bogus)
    )
    assert stale3 == bogus


def test_scope_lists_fail_loudly_when_stale(tmp_path):
    """RIP001/RIP002/RIP004 scope their checks by module/function name;
    a rename must produce a stale-scope finding, not silently unscope
    the lint (review regression)."""
    repo = str(tmp_path)
    mod = tmp_path / "riptide_tpu" / "search" / "engine.py"
    mod.parent.mkdir(parents=True)
    # engine.py exists but the hot function was "renamed" away.
    mod.write_text("def renamed_queue_stages():\n    pass\n")

    new, _, _ = analysis.run_analyzers(
        repo,
        [analysis.HostSyncAnalyzer, analysis.LockDisciplineAnalyzer,
         analysis.DtypeDisciplineAnalyzer],
        baseline=analysis.Baseline(),
    )
    msgs = [f.gh() for f in new]
    assert any("_queue_stages" in m and "stale" in m for m in msgs), msgs
    # Every configured-but-missing module is reported by each analyzer.
    assert any("batcher.py" in m and "stale" in m for m in msgs), msgs
    assert any("liveness.py" in m and "stale" in m for m in msgs), msgs
    assert any("peaks_device.py" in m and "stale" in m for m in msgs), msgs


def test_untimed_join_under_lock_reported_once(tmp_path):
    """One defect, one finding: the under-lock and module-wide walks
    must not double-report the same untimed join (review regression)."""
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def drain(worker):\n"
        "    with _lock:\n"
        "        worker.join()\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(),
    )
    joins = [f for f in new if "join" in f.message]
    assert len(joins) == 1, [f.gh() for f in new]


def test_pathonly_baseline_entry_is_not_stale(tmp_path):
    """An empty-line_text entry is the documented way to baseline a
    finding outside the package (no ModuleContext, e.g. docs drift);
    it must absorb the finding AND count as used, or the run could
    never go green."""
    repo = str(tmp_path)
    (tmp_path / "riptide_tpu").mkdir()
    (tmp_path / "riptide_tpu" / "empty.py").write_text("x = 1\n")

    class OutsideFinding(analysis.Analyzer):
        rule = "RIP999"
        name = "outside"

        def finalize(self, repo, contexts):
            return [analysis.Finding("docs/somewhere.md", 1, 0,
                                     self.rule, "drifted")]

    entry = {"rule": "RIP999", "path": "docs/somewhere.md",
             "line_text": "", "why": "tracked elsewhere"}
    new, baselined, stale = analysis.run_analyzers(
        repo, [OutsideFinding], baseline=analysis.Baseline([entry])
    )
    assert new == [] and len(baselined) == 1 and stale == []


def test_reused_analyzer_instance_resets_state(tmp_path):
    """A reused instance must not leak run state: after a clean run
    over a tree WITH wrapped collectives, a second run over a tree
    WITHOUT them must still report the vacuous-lint failure."""
    dest = "riptide_tpu/parallel/mh.py"
    good = _mini_repo(tmp_path / "a", {dest: "rip007_liveness_good.py"})
    empty = str(tmp_path / "b")
    (tmp_path / "b" / "riptide_tpu").mkdir(parents=True)
    (tmp_path / "b" / "riptide_tpu" / "empty.py").write_text("x = 1\n")

    inst = analysis.LivenessGuardAnalyzer(allowed={dest: {"ok"}})
    new1, _, _ = analysis.run_analyzers(good, [inst],
                                        baseline=analysis.Baseline())
    assert new1 == []
    new2, _, _ = analysis.run_analyzers(empty, [inst],
                                        baseline=analysis.Baseline())
    assert len(new2) == 1 and "vacuous" in new2[0].message


def test_keyword_timeout_under_lock_not_flagged(tmp_path):
    """A wait/join with a keyword timeout under a held lock follows
    the rule and must not be flagged (review regression)."""
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def drain(evt, worker):\n"
        "    with _lock:\n"
        "        evt.wait(timeout=5.0)\n"
        "        worker.join(timeout=5.0)\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(),
    )
    assert new == [], "\n".join(f.gh() for f in new)


def test_inline_pragma_suppression(tmp_path):
    dest = "riptide_tpu/survey/liveness.py"
    repo = str(tmp_path)
    mod = tmp_path / dest
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "def shutdown(done, worker):\n"
        "    done.wait()  # riplint: disable=RIP004\n"
        "    worker.join()\n"
    )
    new, _, _ = analysis.run_analyzers(
        repo, [analysis.LockDisciplineAnalyzer(modules={dest})],
        baseline=analysis.Baseline(),
    )
    # Only the unsuppressed join() survives.
    assert len(new) == 1 and "join" in new[0].message


# -- stability + docs -------------------------------------------------------

def test_analyzer_set_and_rule_ids_are_stable():
    """Rule ids are an API: baselines, pragmas and CI annotations key
    on them. Renaming or renumbering must be a deliberate change that
    updates this test (and migrates the baseline)."""
    got = {(a.rule, a.name) for a in analysis.ALL_ANALYZERS}
    assert got == {
        ("RIP001", "host-sync"),
        ("RIP002", "dtype-discipline"),
        ("RIP003", "env-flags"),
        ("RIP004", "lock-discipline"),
        ("RIP005", "pallas-layout"),
        ("RIP006", "finite-guards"),
        ("RIP007", "liveness-guards"),
        ("RIP008", "obs-discipline"),
    }
    rules = [a.rule for a in analysis.ALL_ANALYZERS]
    assert len(rules) == len(set(rules)) == 8


def test_env_docs_in_sync_with_registry():
    registry = analysis.env_flags.load_registry(REPO)
    with open(os.path.join(REPO, "docs", "env_flags.md")) as fobj:
        assert fobj.read() == registry.render_markdown()


def test_every_package_flag_token_is_registered():
    registry = analysis.env_flags.load_registry(REPO)
    token = re.compile(r"RIPTIDE_[A-Z0-9_]+")
    unknown = set()
    for ctx in analysis.collect_contexts(REPO):
        # Tokens ending in "_" are docs-string wildcards
        # ("RIPTIDE_TRACE_*"), not flag names.
        unknown.update(t for t in token.findall(ctx.source)
                       if t not in registry.FLAGS
                       and not t.endswith("_"))
    assert unknown == set(), \
        f"undeclared RIPTIDE_* names in package sources: {sorted(unknown)}"


def test_baseline_entries_are_justified():
    with open(os.path.join(REPO, "tools", "riplint_baseline.json")) as fobj:
        entries = json.load(fobj)["entries"]
    assert entries, "baseline exists and is non-empty"
    for e in entries:
        assert e["why"] and "TODO" not in e["why"], \
            f"unjustified baseline entry: {e}"
