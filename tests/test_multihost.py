"""
Real 2-process distributed runtime test: two coordinator-connected CPU
processes (4 virtual devices each) each search their own DM shard and
exchange Peak lists through run_search_multihost — the multi-host analog
of the reference's tested ``processes: 2`` parallel pipeline mode
(riptide/tests/test_pipeline.py:14-31). Exercises
parallel/distributed.py:init_distributed with process_count > 1.
"""
import os
import socket
import subprocess
import sys

import numpy as np

_WORKER = r"""
import os, sys

pid = int(sys.argv[1])
port = sys.argv[2]

import numpy as np
from riptide_tpu.parallel.distributed import init_distributed

assert init_distributed(f"localhost:{port}", num_processes=2, process_id=pid)

import jax

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

from riptide_tpu.libffa import generate_signal
from riptide_tpu.parallel import run_search_multihost
from riptide_tpu.search import periodogram_plan

N, tsamp = 4096, 1e-3
plan = periodogram_plan(N, tsamp, (1, 2, 3), 64e-3, 0.15, 64, 71)
rng = np.random.default_rng(pid)
batch = rng.standard_normal((2, N)).astype(np.float32)
if pid == 1:
    np.random.seed(0)
    batch[0] = generate_signal(N, 64.0, amplitude=15.0, ducy=0.05)
batch -= batch.mean(axis=1, keepdims=True)
batch /= batch.std(axis=1, keepdims=True)
dms = [2.0 * pid, 2.0 * pid + 1.0]

peaks, _ = run_search_multihost(plan, batch, tobs=N * tsamp, dms_local=dms)

# EVERY process must see the pulsar searched by process 1's trial 0
# (dm == 2.0) through the cross-process gather.
best = [p for p in peaks if abs(p.period - 0.064) < 1e-3 and p.dm == 2.0]
assert best, f"pid {pid}: pulsar peak not gathered; got {peaks[:5]}"
assert peaks == sorted(peaks, key=lambda p: p.snr, reverse=True)
print(f"worker {pid} OK: {len(peaks)} global peaks, "
      f"top S/N {peaks[0].snr:.1f}")
"""


def test_two_process_distributed_search(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.update(
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_COMPILATION_CACHE_DIR="/tmp/riptide_tpu_jax_cache",
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0.5",
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert f"worker {i} OK" in out
