"""
Real 2-process distributed runtime tests: two coordinator-connected CPU
processes (4 virtual devices each) each search their own DM shard and
exchange Peak lists through run_search_multihost — the multi-host analog
of the reference's tested ``processes: 2`` parallel pipeline mode
(riptide/tests/test_pipeline.py:14-31). Exercises
parallel/distributed.py:init_distributed with process_count > 1, plus
the peer-loss degradation path (one host dies; the survivor degrades to
local-only mode, takes over the journal-writer role and finishes the
lost shard's chunks instead of deadlocking). Unit tests cover the Peak
wire encoding and the all-processes-empty padding path of gather_peaks.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from riptide_tpu.peak_detection import Peak

# The marker a worker's XlaRuntimeError carries when the installed
# jaxlib build cannot run real multi-process collectives on the CPU
# backend (environment limitation, not a code defect — skip, don't
# fail).
_BACKEND_UNSUPPORTED = \
    "Multiprocess computations aren't implemented on the CPU backend"

_WORKER = r"""
import os, sys

pid = int(sys.argv[1])
port = sys.argv[2]

import numpy as np
from riptide_tpu.parallel.distributed import init_distributed

# init returns the process count (truthiness-compatible with the old
# boolean contract).
assert init_distributed(f"localhost:{port}", num_processes=2,
                        process_id=pid) == 2

import jax

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

from riptide_tpu.libffa import generate_signal
from riptide_tpu.parallel import run_search_multihost
from riptide_tpu.search import periodogram_plan

N, tsamp = 4096, 1e-3
plan = periodogram_plan(N, tsamp, (1, 2, 3), 64e-3, 0.15, 64, 71)
rng = np.random.default_rng(pid)
batch = rng.standard_normal((2, N)).astype(np.float32)
if pid == 1:
    np.random.seed(0)
    batch[0] = generate_signal(N, 64.0, amplitude=15.0, ducy=0.05)
batch -= batch.mean(axis=1, keepdims=True)
batch /= batch.std(axis=1, keepdims=True)
dms = [2.0 * pid, 2.0 * pid + 1.0]

peaks, _ = run_search_multihost(plan, batch, tobs=N * tsamp, dms_local=dms)

# EVERY process must see the pulsar searched by process 1's trial 0
# (dm == 2.0) through the cross-process gather.
best = [p for p in peaks if abs(p.period - 0.064) < 1e-3 and p.dm == 2.0]
assert best, f"pid {pid}: pulsar peak not gathered; got {peaks[:5]}"
assert peaks == sorted(peaks, key=lambda p: p.snr, reverse=True)
print(f"worker {pid} OK: {len(peaks)} global peaks, "
      f"top S/N {peaks[0].snr:.1f}")
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env():
    env = dict(os.environ)
    env.update(
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_COMPILATION_CACHE_DIR="/tmp/riptide_tpu_jax_cache",
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0.5",
    )
    return env


def _run_two_processes(tmp_path, source, extra_args=()):
    """Launch the worker script as processes 0 and 1 of a 2-process
    runtime; returns [(returncode, output), ...]."""
    script = tmp_path / "worker.py"
    script.write_text(source)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port),
             *map(str, extra_args)],
            env=_worker_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            results.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results


def test_two_process_distributed_search(tmp_path):
    results = _run_two_processes(tmp_path, _WORKER)
    for i, (rc, out) in enumerate(results):
        if rc != 0 and _BACKEND_UNSUPPORTED in out:
            # Some jaxlib builds refuse real multi-process collectives
            # on the forced-host CPU backend; nothing to test there.
            pytest.skip("multiprocess collectives unsupported on this "
                        "CPU backend build")
        assert rc == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert f"worker {i} OK" in out


# ---------------------------------------------------------------------------
# Peer loss: one host wedges (stops heartbeating and participating, so
# from the survivor's side it is indistinguishable from dead — its next
# collective would block forever); the survivor must finish ALL shards
# instead of deadlocking in the peak gather.
# ---------------------------------------------------------------------------

_PEER_LOSS_WORKER = r"""
import os, sys, time

pid = int(sys.argv[1])
port = sys.argv[2]
jdir = sys.argv[3]

import numpy as np
from riptide_tpu.parallel.distributed import init_distributed

assert init_distributed(f"localhost:{port}", num_processes=2,
                        process_id=pid) == 2

import riptide_tpu.parallel.multihost as mh
from riptide_tpu.libffa import generate_signal
from riptide_tpu.parallel import run_search_multihost
from riptide_tpu.search import periodogram_plan
from riptide_tpu.survey.faults import FaultPlan
from riptide_tpu.survey.journal import SurveyJournal
from riptide_tpu.survey.liveness import PeerLivenessMonitor
from riptide_tpu.survey.metrics import get_metrics

journal = SurveyJournal(jdir)
monitor = PeerLivenessMonitor(journal, process_index=pid, process_count=2,
                              max_age_s=0.2)
sentinel = os.path.join(jdir, "survivor_done")

if pid == 0:
    # The lost host: heartbeat once, then wedge — never search chunk 0,
    # never heartbeat again, never enter a collective. (The process
    # itself lingers so the jax coordination service, which this
    # process hosts, stays up; killing it outright makes the client
    # library abort the survivor before the liveness layer can act.)
    monitor.beat()
    for _ in range(600):
        if os.path.exists(sentinel):
            break
        time.sleep(0.1)
    print("worker 0 OK: wedged host exiting", flush=True)
    os._exit(0)

# The survivor (process 1): let the peer's heartbeat go stale, then run
# its own shard. The injected peer_loss stands in for the bounded
# collective timing out — with the peer wedged, actually entering the
# collective would hang, which is exactly what the liveness layer is
# for. The background beater keeps THIS process fresh independent of
# chunk progress.
monitor.start_beating(interval_s=0.05)
time.sleep(0.5)
journal.write_header("peerloss-survey", 2)

N, tsamp = 4096, 1e-3
plan = periodogram_plan(N, tsamp, (1, 2, 3), 64e-3, 0.15, 64, 71)

def shard(seed, with_pulsar):
    rng = np.random.default_rng(seed)
    batch = rng.standard_normal((2, N)).astype(np.float32)
    if with_pulsar:
        np.random.seed(0)
        batch[0] = generate_signal(N, 64.0, amplitude=15.0, ducy=0.05)
    batch -= batch.mean(axis=1, keepdims=True)
    batch /= batch.std(axis=1, keepdims=True)
    return batch

peaks, _ = run_search_multihost(
    plan, shard(1, True), tobs=N * tsamp, dms_local=[2.0, 3.0],
    journal=journal, chunk_id=1, faults=FaultPlan.parse("peer_loss:1"),
    monitor=monitor,
)
assert mh.is_degraded()
assert peaks, "survivor lost its own local peaks"
# Writer failover: process 0 is stale, so the lowest ALIVE process (us)
# journals.
assert monitor.lost() == [0], monitor.lost()
assert monitor.journal_writer() == 1

# Re-enqueue the lost shard's unfinished chunks from the journal and
# finish them locally: the survivor now owns the whole survey.
lost_chunks = monitor.unfinished_chunks(2)
assert lost_chunks == [0], lost_chunks
for cid in lost_chunks:
    run_search_multihost(plan, shard(0, False), tobs=N * tsamp,
                         dms_local=[0.0, 1.0], journal=journal,
                         chunk_id=cid, monitor=monitor)

done = sorted(journal.completed_chunks())
assert done == [0, 1], done
assert get_metrics().counter("peer_losses") == 1
print(f"worker 1 OK: survived peer loss, journaled chunks {done}",
      flush=True)
with open(sentinel, "w") as f:
    f.write("done")
# Skip the distributed runtime's shutdown handshake: the wedged peer
# will never participate in it.
os._exit(0)
"""


def test_two_process_peer_loss_survivor_finishes(tmp_path):
    """Acceptance: with process 0 lost (wedged, heartbeats stale), the
    survivor degrades to local-only mode, takes over the journal-writer
    role and completes BOTH shards — verified via the shared journal —
    instead of deadlocking in the gather."""
    from riptide_tpu.survey.journal import SurveyJournal

    jdir = tmp_path / "journal"
    results = _run_two_processes(tmp_path, _PEER_LOSS_WORKER,
                                 extra_args=[jdir])
    for i, (rc, out) in enumerate(results):
        assert rc == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert f"worker {i} OK" in out

    journal = SurveyJournal(jdir)
    assert sorted(journal.completed_chunks()) == [0, 1]
    beats = journal.read_heartbeats()
    assert sorted(beats) == [0, 1]  # both sidecars exist
    snap = journal.last_metrics()
    assert snap["peer_losses"] == 1


# ---------------------------------------------------------------------------
# Peak wire encoding (unit)
# ---------------------------------------------------------------------------

def _peak(period=0.5, snr=10.0, dm=0.0, iw=1, ip=7, width=3):
    return Peak(period=period, freq=1.0 / period, width=width, ducy=0.05,
                iw=iw, ip=ip, snr=snr, dm=dm)


def test_peak_encode_decode_roundtrip():
    from riptide_tpu.parallel.multihost import _decode, _encode

    peaks = [
        _peak(),
        # Large int fields must survive the float64 wire exactly
        # (float64 is integer-exact through 2**53).
        _peak(period=1.25, snr=8.5, dm=112.75, iw=11, ip=123456789,
              width=1 << 40),
    ]
    out = _decode(_encode(peaks))
    assert out == peaks
    for p in out:
        assert isinstance(p.iw, int)
        assert isinstance(p.ip, int)
        assert isinstance(p.width, int)


def test_peak_encode_empty():
    from riptide_tpu.parallel.multihost import _FIELDS, _decode, _encode

    arr = _encode([])
    assert arr.shape == (0, len(_FIELDS))
    assert _decode(arr) == []


def test_gather_peaks_all_processes_empty_padding(monkeypatch):
    """When every process has zero peaks the gather still pads to one
    row per process (allgather needs equal shapes) and must decode back
    to an empty list, not phantom zero-peaks."""
    import riptide_tpu.parallel.multihost as mh

    mh.reset_degraded()
    monkeypatch.setattr(mh.jax, "process_count", lambda: 2)
    shapes = []

    def fake_allgather(arr, timeout_s, what):
        shapes.append(arr.shape)
        return np.stack([np.zeros_like(arr), np.zeros_like(arr)])

    monkeypatch.setattr(mh, "_allgather", fake_allgather)
    assert mh.gather_peaks([]) == []
    # One count row per process, then a single padding row of fields.
    assert shapes == [(1,), (1, len(mh._FIELDS))]


def test_gather_peaks_single_process_is_copy():
    from riptide_tpu.parallel.multihost import gather_peaks

    local = [_peak(), _peak(snr=8.0)]
    out = gather_peaks(local)
    assert out == local and out is not local
