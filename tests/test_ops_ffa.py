"""
FFA transform tests: golden 8x8 values, invariances, oracle parity for
arbitrary (including non-power-of-2) shapes, and the batched padded
container path. Mirrors the oracle strategy of the reference suite
(riptide/tests/test_ffa_base_functions.py).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from riptide_tpu.ops import reference as ref
from riptide_tpu.ops import ffa2, ffa1, ffafreq, ffaprd, ffa_levels, batch_plans

# Hand-computable case: a single spike per period, drifting through all
# phase-shift trials. Invariant under phase rotation and appended zero
# columns.
FFA_IN_88 = np.zeros((8, 8), dtype=np.float32)
FFA_IN_88[:, 7] = 1.0

FFA_OUT_88 = np.array(
    [
        [0, 0, 0, 0, 0, 0, 0, 8],
        [0, 0, 0, 0, 0, 0, 4, 4],
        [0, 0, 0, 0, 0, 2, 4, 2],
        [0, 0, 0, 0, 2, 2, 2, 2],
        [0, 0, 0, 1, 2, 2, 2, 1],
        [0, 0, 1, 2, 1, 1, 2, 1],
        [0, 1, 1, 1, 2, 1, 1, 1],
        [1, 1, 1, 1, 1, 1, 1, 1],
    ],
    dtype=np.float32,
)


def test_oracle_golden_88():
    assert np.allclose(ref.ffa_transform(FFA_IN_88), FFA_OUT_88)


def test_jax_golden_88():
    assert np.allclose(ffa2(FFA_IN_88), FFA_OUT_88)


def test_rotation_invariance():
    for shift in range(8):
        X = np.roll(FFA_IN_88, shift, axis=1)
        truth = np.roll(FFA_OUT_88, shift, axis=1)
        assert np.allclose(ffa2(X), truth)
        assert np.allclose(ffa1(X.ravel(), 8), truth)


def test_zero_column_invariance():
    for extra in range(1, 8):
        X = np.hstack([FFA_IN_88, np.zeros((8, extra), dtype=np.float32)])
        truth = np.hstack([FFA_OUT_88, np.zeros((8, extra), dtype=np.float32)])
        assert np.allclose(ffa2(X), truth)


@pytest.mark.parametrize("m", [2, 3, 5, 7, 8, 12, 13, 16, 33, 100, 127, 128, 255])
@pytest.mark.parametrize("p", [4, 16, 37, 260])
def test_jax_vs_oracle(m, p):
    rng = np.random.RandomState(m * 1000 + p)
    x = rng.normal(size=(m, p)).astype(np.float32)
    expected = ref.ffa_transform(x)
    got = ffa2(x)
    assert np.allclose(got, expected, atol=1e-4), np.abs(got - expected).max()


def test_m1_identity():
    x = np.random.RandomState(0).normal(size=(1, 16)).astype(np.float32)
    assert np.array_equal(ffa2(x), x)


def test_errors():
    with pytest.raises(ValueError):
        ffa2(np.zeros(4))
    with pytest.raises(ValueError):
        ffa1(np.zeros((4, 4)), 4)
    with pytest.raises(ValueError):
        ffa1(np.zeros(10), 11)
    with pytest.raises(ValueError):
        ffa1(np.zeros(10), 4.0)


def test_batched_padded_container():
    """Several differently-shaped problems in one padded (B, R, P) kernel
    call must each match the single-problem oracle, and padding must stay
    exactly zero."""
    shapes = [(13, 20), (8, 24), (21, 17), (1, 10), (2, 24)]
    ms = [m for m, _ in shapes]
    ps = [p for _, p in shapes]
    plan = batch_plans(ms, ps, R=max(ms) + 3, P=32)
    rng = np.random.RandomState(7)
    xs = [rng.normal(size=s).astype(np.float32) for s in shapes]

    buf = np.zeros((plan.B, plan.R, plan.P), dtype=np.float32)
    for b, x in enumerate(xs):
        buf[b, : x.shape[0], : x.shape[1]] = x

    out = np.asarray(
        ffa_levels(
            jnp.asarray(buf),
            jnp.asarray(plan.h),
            jnp.asarray(plan.t),
            jnp.asarray(plan.shift),
            jnp.asarray(plan.p),
        )
    )
    for b, x in enumerate(xs):
        m, p = x.shape
        expected = ref.ffa_transform(x)
        assert np.allclose(out[b, :m, :p], expected, atol=1e-4)
        # padding stays clean
        assert np.all(out[b, m:, :] == 0)
        assert np.all(out[b, :, p:] == 0)


def test_ffafreq_matches_closed_form():
    N, p, dt = 104, 10, 0.5
    f = ffafreq(N, p, dt=dt)
    m = N // p
    assert f.size == m
    # first trial: exactly 1/(p*dt); last trial: 1/(p+1 samples)
    assert np.isclose(f[0], 1.0 / (p * dt))
    assert np.isclose(f[-1], (1.0 / p - 1.0 / p**2) / dt)
    prd = ffaprd(N, p, dt=dt)
    assert np.allclose(prd, 1.0 / f)
    # m == 1 special case
    assert np.allclose(ffafreq(10, 10, dt=2.0), [1.0 / 20.0])


def test_ffafreq_errors():
    with pytest.raises(ValueError):
        ffafreq(0, 4)
    with pytest.raises(ValueError):
        ffafreq(16, 1)
    with pytest.raises(ValueError):
        ffafreq(8, 9)
    with pytest.raises(ValueError):
        ffafreq(8, 4, dt=0.0)
