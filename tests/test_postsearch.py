"""
PR 19 acceptance: the on-device post-search tail and multi-core wire
prep.

* ``RIPTIDE_DEVICE_CLUSTER`` byte-parity — peaks.csv and candidates.csv
  are byte-identical with the flag on and off, across the quantised
  wire transports and through a DM-batched kill-and-resume survey (the
  flag changes WHERE the clustering tail runs, never what comes out).
* dispatch regression — flag off queues ZERO extra device programs;
  flag on rides the cluster sections inside the existing fused peak
  program (exactly one ``dispatch_cluster`` count per chunk, every
  other dispatch kind unchanged).
* ``RIPTIDE_PREP_THREADS`` determinism — the native wire prep produces
  byte-identical wire digests (and identical results) at any thread
  count, verified under ``RIPTIDE_INTEGRITY=digest``.
"""
import numpy as np
import pytest

from riptide_tpu.pipeline import Pipeline
from riptide_tpu.pipeline.batcher import BatchSearcher
from riptide_tpu.survey.journal import SurveyJournal
from riptide_tpu.survey.metrics import get_metrics
from riptide_tpu.survey.scheduler import SurveyScheduler
from riptide_tpu.survey.faults import FaultAbort

from synth import generate_data_presto

TOBS = 16.0
TSAMP = 1e-3
PERIOD = 0.5
AMPLITUDES = {0.0: 15.0, 10.0: 40.0, 20.0: 15.0}

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]

# Every dispatch-counter kind the engine maintains, plus PR 19's.
DISPATCH_KINDS = ("fused", "pack", "kernel", "unpack", "gather",
                  "slice", "cluster")


def _survey_config(processes=1):
    return {
        "processes": processes,
        "data": {"format": "presto", "fmin": None, "fmax": None,
                 "nchans": None},
        "dmselect": {"min": 0.0, "max": 30.0, "dmsinb_max": None},
        "dereddening": {"rmed_width": 4.0, "rmed_minpts": 101},
        "ranges": [{
            "name": "test",
            "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                           "bins_min": 64, "bins_max": 71,
                           "fpmin": 8, "wtsp": 1.5, "ducy_max": 0.30},
            "find_peaks": {"smin": 6.0},
            "candidates": {"bins": 64, "subints": 8},
        }],
        "clustering": {"radius": 0.2},
        "harmonic_flagging": {"denom_max": 100, "phase_distance_max": 1.0,
                              "dm_distance_max": 3.0,
                              "snr_distance_max": 3.0},
        "candidate_filters": {"dm_min": None, "snr_min": 7.0,
                              "remove_harmonics": True, "max_number": None},
        "plot_candidates": False,
    }


def _make_survey(outdir, dms=(0.0, 10.0, 20.0)):
    return [
        generate_data_presto(
            str(outdir), f"fake_DM{dm:.2f}", tobs=TOBS, tsamp=TSAMP,
            period=PERIOD, dm=dm, amplitude=AMPLITUDES[dm], ducy=0.02,
        )
        for dm in dms
    ]


def _run_pipeline(files, outdir, processes=1, **kwargs):
    outdir.mkdir(exist_ok=True)
    get_metrics().reset()
    Pipeline(_survey_config(processes), **kwargs).process(
        [str(f) for f in files], str(outdir))


def _products(outdir):
    return {p: (outdir / p).read_bytes()
            for p in ("peaks.csv", "candidates.csv")}


def _searcher():
    return BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                         SEARCH_CONF, fmt="presto", io_threads=1)


def _two_trials(tmp_path):
    f1 = generate_data_presto(str(tmp_path), "a_DM0.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=0.0,
                              amplitude=25.0)
    f2 = generate_data_presto(str(tmp_path), "b_DM5.00", tobs=TOBS,
                              tsamp=TSAMP, period=PERIOD, dm=5.0,
                              amplitude=25.0)
    return f1, f2


def _dispatch_counts():
    m = get_metrics()
    return {k: int(m.counter(f"dispatch_{k}")) for k in DISPATCH_KINDS}


# -------------------------------------------------- flag byte-parity

@pytest.mark.parametrize("wire", ["uint6", "uint8", "uint12"])
def test_csv_byte_parity_flag_on_off(tmp_path, monkeypatch, wire):
    """peaks.csv and candidates.csv byte-identical with on-device
    clustering on and off, over each quantised wire transport."""
    indir = tmp_path / "data"
    indir.mkdir()
    files = _make_survey(indir, dms=(0.0, 10.0))
    monkeypatch.setenv("RIPTIDE_WIRE_DTYPE", wire)

    monkeypatch.setenv("RIPTIDE_DEVICE_CLUSTER", "1")
    _run_pipeline(files, tmp_path / "on")
    on = _products(tmp_path / "on")
    assert get_metrics().counter("dispatch_cluster") == len(files)

    monkeypatch.setenv("RIPTIDE_DEVICE_CLUSTER", "0")
    _run_pipeline(files, tmp_path / "off")
    off = _products(tmp_path / "off")
    assert get_metrics().counter("dispatch_cluster") == 0

    for product in on:
        assert on[product] == off[product], (
            f"{product} differs between device and host clustering "
            f"({wire} wire)")


def test_csv_byte_parity_dm_batched_resume(tmp_path, monkeypatch):
    """A DM-batched (2 trials per chunk) survey killed after its first
    chunk and resumed with the flag ON produces byte-identical CSVs to
    an uninterrupted flag-OFF run: flag parity and replay parity in one
    pass."""
    indir = tmp_path / "data"
    indir.mkdir()
    files = _make_survey(indir)

    monkeypatch.setenv("RIPTIDE_DEVICE_CLUSTER", "0")
    _run_pipeline(files, tmp_path / "off", processes=2)

    monkeypatch.setenv("RIPTIDE_DEVICE_CLUSTER", "1")
    jdir = str(tmp_path / "journal")
    with pytest.raises(FaultAbort):
        _run_pipeline(files, tmp_path / "on", processes=2, journal=jdir,
                      fault_spec="abort:1")
    assert sorted(SurveyJournal(jdir).completed_chunks()) == [0]
    _run_pipeline(files, tmp_path / "on", processes=2, journal=jdir,
                  resume=True, fault_spec="")
    assert get_metrics().counter("chunks_skipped") == 1

    on, off = _products(tmp_path / "on"), _products(tmp_path / "off")
    for product in on:
        assert on[product] == off[product], (
            f"{product} differs between resumed flag-on and "
            "uninterrupted flag-off runs")


# ---------------------------------------------- dispatch regression

def test_device_cluster_dispatch_regression(tmp_path, monkeypatch):
    """Flag off: zero cluster dispatches and the flag adds no program
    of any other kind. Flag on: exactly one cluster program per chunk,
    fused into the peak program (every other dispatch count
    unchanged), and the peak lists bit-identical."""
    f1, f2 = _two_trials(tmp_path)

    monkeypatch.setenv("RIPTIDE_DEVICE_CLUSTER", "0")
    get_metrics().reset()
    peaks_off = SurveyScheduler(_searcher(), [[f1], [f2]]).run()
    off = _dispatch_counts()
    assert off.pop("cluster") == 0

    monkeypatch.setenv("RIPTIDE_DEVICE_CLUSTER", "1")
    get_metrics().reset()
    peaks_on = SurveyScheduler(_searcher(), [[f1], [f2]]).run()
    on = _dispatch_counts()
    assert on.pop("cluster") == 2  # exactly one per chunk

    assert on == off, "flag state changed non-cluster dispatch counts"
    assert peaks_on == peaks_off


# ------------------------------------------ prep-thread determinism

def _digest_run(files, jdir):
    get_metrics().reset()
    peaks = SurveyScheduler(
        _searcher(), [[f] for f in files],
        journal=SurveyJournal(str(jdir)),
    ).run()
    from riptide_tpu.obs.report import read_journal

    chunks = read_journal(str(jdir))["chunks"]
    digests = {cid: (rec.get("wire_digest"),
                     (rec.get("integrity") or {}).get("result"))
               for cid, rec in chunks.items()}
    return peaks, digests


def test_prep_threads_byte_identical(tmp_path, monkeypatch):
    """N=1 vs N=4 prep threads: identical per-chunk wire digests,
    identical Ring-1 result digests (RIPTIDE_INTEGRITY=digest) and
    identical peaks — the thread count is a pure throughput knob."""
    files = _two_trials(tmp_path)
    monkeypatch.setenv("RIPTIDE_INTEGRITY", "digest")

    monkeypatch.setenv("RIPTIDE_PREP_THREADS", "1")
    peaks1, dig1 = _digest_run(files, tmp_path / "j1")
    monkeypatch.setenv("RIPTIDE_PREP_THREADS", "4")
    peaks4, dig4 = _digest_run(files, tmp_path / "j4")

    assert dig1 == dig4
    assert all(w is not None and r is not None
               for w, r in dig1.values())
    assert peaks1 == peaks4
