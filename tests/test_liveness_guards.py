"""
Tier-1 enforcement of the bounded-wait discipline: every
``multihost_utils`` collective call site in ``riptide_tpu/`` must route
through the liveness layer's wrappers
(``tools/check_liveness_guards.py``), so a future call site cannot
reintroduce an unbounded cross-process wait that deadlocks on a dead
peer.
"""
import importlib.util
import os

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
TOOL = os.path.join(REPO, "tools", "check_liveness_guards.py")


def _load_tool():
    spec = importlib.util.spec_from_file_location("check_liveness_guards",
                                                  TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_all_collective_call_sites_guarded():
    tool = _load_tool()
    violations = tool.check()
    assert violations == [], "\n".join(violations)


def _fake_repo(tmp_path, source):
    pkg = tmp_path / "riptide_tpu"
    pkg.mkdir()
    (pkg / "raw.py").write_text(source)
    return str(tmp_path)


def test_lint_catches_raw_collective(tmp_path):
    """The checker must flag a raw multihost_utils call outside the
    allowed wrappers (guard against a vacuous lint)."""
    tool = _load_tool()
    repo = _fake_repo(
        tmp_path,
        "from jax.experimental import multihost_utils\n"
        "def gather(x):\n"
        "    return multihost_utils.process_allgather(x)\n"
        "def ok(x):\n"
        "    return multihost_utils.process_allgather(x)\n"
    )
    allowed = {os.path.join("riptide_tpu", "raw.py"): {"ok"}}
    violations = tool.check(repo=repo, allowed=allowed)
    assert len(violations) == 1
    assert "gather" in violations[0]


def test_lint_catches_fully_qualified_and_module_level(tmp_path):
    tool = _load_tool()
    repo = _fake_repo(
        tmp_path,
        "import jax\n"
        "jax.experimental.multihost_utils.sync_global_devices('boot')\n"
        "def ok(x):\n"
        "    import jax.experimental.multihost_utils as multihost_utils\n"
        "    return multihost_utils.process_allgather(x)\n"
    )
    allowed = {os.path.join("riptide_tpu", "raw.py"): {"ok"}}
    violations = tool.check(repo=repo, allowed=allowed)
    assert len(violations) == 1
    assert "module level" in violations[0]


def test_lint_catches_from_import_and_alias_evasion(tmp_path):
    """Binding a collective via ``from ...multihost_utils import X`` or
    the module via ``import ... as Y`` would evade the attribute-call
    check; the lint must flag the import itself."""
    tool = _load_tool()
    repo = _fake_repo(
        tmp_path,
        "from jax.experimental.multihost_utils import process_allgather\n"
        "import jax.experimental.multihost_utils as mhu\n"
        "def sneaky(x):\n"
        "    return process_allgather(x)\n"
        "def ok(x):\n"
        "    from jax.experimental import multihost_utils\n"
        "    return multihost_utils.process_allgather(x)\n"
    )
    allowed = {os.path.join("riptide_tpu", "raw.py"): {"ok"}}
    violations = tool.check(repo=repo, allowed=allowed)
    assert len(violations) == 2  # the two module-level import bindings
    assert all("import" in v for v in violations)


def test_lint_catches_module_alias_from_import(tmp_path):
    """'from jax.experimental import multihost_utils as mu' hides the
    module under an alias, so 'mu.process_allgather(...)' would pass
    the attribute check; the import binding itself must be flagged."""
    tool = _load_tool()
    repo = _fake_repo(
        tmp_path,
        "from jax.experimental import multihost_utils as mu\n"
        "def sneaky(x):\n"
        "    return mu.process_allgather(x)\n"
        "def ok(x):\n"
        "    from jax.experimental import multihost_utils\n"
        "    return multihost_utils.process_allgather(x)\n"
    )
    allowed = {os.path.join("riptide_tpu", "raw.py"): {"ok"}}
    violations = tool.check(repo=repo, allowed=allowed)
    assert len(violations) == 1
    assert "import" in violations[0] and "module level" in violations[0]


def test_lint_flags_vacuous_allowlist(tmp_path):
    """Zero wrapped call sites means the wrappers vanished: the lint
    must fail rather than silently pass forever."""
    tool = _load_tool()
    repo = _fake_repo(tmp_path, "x = 1\n")
    violations = tool.check(repo=repo)
    assert violations and "vacuous" in violations[0]
