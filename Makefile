# Convenience targets mirroring the reference's Makefile surface.

PYTHON ?= python

.PHONY: install check check-full prove repin lint native-asan sanitize \
	tests tests-cov native bench trace-demo report-demo watch-demo \
	serve-demo ripsched ripsched-demo analyze chaos clean

install:
	$(PYTHON) -m pip install -e .

# Static analysis: the riplint framework (tools/riplint.py — 14
# analyzers including the whole-program call-graph rules RIP009-011
# and the thread-discipline rules RIP012-014)
# against the checked-in baseline, using the mtime+size result cache
# (.riplint_cache.json): an unchanged tree replays in well under a
# second. Also enforced in tier-1 via tests/test_riplint.py; the old
# tools/check_*.py entry points remain as shims onto the same analyzers.
check:
	$(PYTHON) tools/riplint.py

# Semantic static pass: trace the representative search plans' staged
# programs (jax.make_jaxpr under JAX_PLATFORMS=cpu, no device
# execution) and verify the pinned program contracts in
# tools/plan_contracts.json — dispatch counts, peak-HBM model, dtype
# flow, transfer bytes, donation. Drift = exit 1; re-pin a deliberate
# change with `python tools/rprove.py --update` (the kernel_digest
# workflow).
prove:
	JAX_PLATFORMS=cpu PYTHONPATH= $(PYTHON) tools/rprove.py

# The ONE audited step for a deliberate KERNEL_CACHE_VERSION bump:
# re-pin the kernel bytecode digest (tests/test_kernel_cache_version.py)
# and the semantic program contracts (tools/plan_contracts.json) in
# order, then re-verify. rprove's ABSOLUTE rules (no f64 on device, no
# dropped donations, zero pack programs on fused stages) are enforced
# even against a freshly written pin, so `make repin` cannot launder a
# genuinely bad kernel change — it only blesses layout/shape drift.
repin:
	$(PYTHON) tools/update_kernel_digest.py
	JAX_PLATFORMS=cpu PYTHONPATH= $(PYTHON) tools/update_canary_digest.py
	JAX_PLATFORMS=cpu PYTHONPATH= $(PYTHON) tools/rprove.py --update --all
	JAX_PLATFORMS=cpu PYTHONPATH= $(PYTHON) tools/rprove.py --all

# Concurrency verification: the schedule-exploration model checker
# (tools/ripsched.py) runs the serve plane's REAL protocol code —
# FairShareQueue pick/drain, the staging pool, runctx incident
# routing, the integrity quarantine latch — under a controlled
# scheduler, exploring every interleaving to the preemption bound
# (RIPTIDE_SCHED_BOUND, default 2) and checking the 18 pinned
# invariants in tools/ripsched_invariants.json. A violation prints a
# minimal failing schedule replayable with --replay <id>.
ripsched:
	$(PYTHON) tools/ripsched.py

# ripsched acceptance: clean models explore clean, a re-armed
# known-bad mutation (a dropped notify in the drain path) is FOUND
# with a minimal replayable schedule, and the replay is
# byte-deterministic. Wired into check-full.
ripsched-demo:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) tools/ripsched_demo.py

# The whole static surface as ONE SARIF document (riptide.sarif):
# riplint + rprove + ripsched merged one run per tool — the shape
# code-scanning uploaders ingest. Exit = max of the tools' exits.
analyze:
	$(PYTHON) tools/analyze.py

# The CI form: AST analyzers uncached + the semantic pass + the
# schedule-exploration pass + its acceptance demo + the fleet/alert
# e2e acceptance (watch-demo) + the survey-service e2e acceptance
# (serve-demo).
check-full: watch-demo serve-demo ripsched-demo
	$(PYTHON) tools/riplint.py --no-cache
	JAX_PLATFORMS=cpu PYTHONPATH= $(PYTHON) tools/rprove.py
	$(PYTHON) tools/ripsched.py

# Everything static (uncached, AST + semantic) + the sanitizer-built
# native tests: the full pre-merge hygiene gate.
lint: check-full sanitize

# ASan+UBSan flavor of the native host library. The sanitizer flags are
# part of the build cache key (own .so next to the production one), and
# the sanitized library only loads with the sanitizer runtimes
# preloaded — hence the LD_PRELOAD. detect_leaks=0: CPython itself
# "leaks" by ASan's definition; the target audits the C++ wire
# producers, not the interpreter.
ASAN_PRELOAD = $(shell g++ -print-file-name=libasan.so) \
	$(shell g++ -print-file-name=libubsan.so)
SAN_ENV = RIPTIDE_NATIVE_SANITIZE=1 LD_PRELOAD="$(ASAN_PRELOAD)" \
	ASAN_OPTIONS=detect_leaks=0 UBSAN_OPTIONS=print_stacktrace=1

native-asan:
	$(SAN_ENV) $(PYTHON) -c "from riptide_tpu import native; \
	assert native.available(), 'sanitized native build failed to load'"

# Native-parity + wire byte-parity tests under the sanitized build.
# -fno-sanitize-recover=all means any ASan/UBSan report aborts the
# test process: green == zero sanitizer reports.
sanitize: native-asan
	$(SAN_ENV) PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) -m pytest \
		tests/test_native.py \
		"tests/test_wire.py::test_native_matches_numpy_fallback" -q

# Run the test suite on the CPU backend (8 virtual devices). PYTHONPATH is
# cleared so the axon TPU site customization does not claim the device for
# a CPU-only run.
tests:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q

tests-cov:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q \
		--cov=riptide_tpu --cov-report=term

# Compiled-kernel parity sweep on the REAL TPU (tpu-marked tests only).
# Run alone — one TPU client at a time; Mosaic compiles of the three
# production buckets take minutes each on a cold cache.
tests-tpu:
	RIPTIDE_TESTS_TPU=1 $(PYTHON) -m pytest tests/ -q -m tpu

# Build the native host library explicitly (it otherwise builds lazily
# on first use).
native:
	$(PYTHON) -c "from riptide_tpu import native; assert native.available()"

# Headline benchmark on the default device (ONE JSON line).
bench:
	$(PYTHON) bench.py

# Tiny CPU survey with the span tracer on: writes a Perfetto-loadable
# Chrome trace, a journal with per-chunk timing blocks, and a
# Prometheus textfile under /tmp/riptide_trace_demo (see
# docs/observability.md).
trace-demo:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) tools/trace_demo.py

# The consumption-side counterpart of trace-demo: tiny CPU survey with
# the perf ledger + live /status//healthz endpoint on, then verifies
# the rreport phase table sums within 5%, the ledger row, both
# --compare exit codes and the healthz 503 flip on stale heartbeats
# (see docs/observability.md).
report-demo:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) tools/report_demo.py

# Fleet/alert e2e acceptance (PR 14): a two-process CPU survey
# federating fleet_<p>.json sidecars into one run directory, with an
# injected straggle fault — tools/rwatch.py (another process) must see
# the straggler_ratio alert fire then resolve and exit 0, the /status
# fleet block must merge both processes, the
# riptide_alert_active{rule=...} gauge must be observed live, and an
# injected ENOSPC on every fleet write must leave the survey complete
# with byte-identical peaks (obs writes are never fatal). Wired into
# check-full.
watch-demo:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) tools/watch_demo.py

# Survey-service e2e acceptance (PR 16): two concurrent HTTP jobs
# through one in-process rserve daemon must be byte-identical to
# their batch-scheduler controls, a repeat-geometry job must run
# with the exec_cold_builds counter flat (warm executables), and a
# tools/rserve.py subprocess KILLED mid-job (exit 137) must resume
# on restart to byte-identical peaks.csv. Wired into check-full.
serve-demo:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) tools/serve_demo.py

# Storage-chaos campaign: a tiny CPU survey run as subprocess legs that
# are KILLED mid-write at journal/ledger/cache boundaries (plus
# ENOSPC/fsync/torn-write degradations on the observability paths) and
# resumed — every schedule must end with byte-identical peaks.csv, a
# consistent journal, a ledger row and an incident per injected fault.
# Runs the fixed builtin schedule set (CI-compatible time); a fuller
# seeded sweep: tools/rchaos.py --sweep N (see docs/fault_tolerance.md).
chaos:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) tools/rchaos.py

clean:
	rm -rf riptide_tpu/native/_build build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
