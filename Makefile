# Convenience targets mirroring the reference's Makefile surface.

PYTHON ?= python

.PHONY: install check tests tests-cov native bench clean

install:
	$(PYTHON) -m pip install -e .

# Static AST lints (also enforced in tier-1 via tests/): the finite-guard
# discipline on data entry points and the bounded-wait discipline on
# multi-host collectives.
check:
	$(PYTHON) tools/check_finite_guards.py
	$(PYTHON) tools/check_liveness_guards.py

# Run the test suite on the CPU backend (8 virtual devices). PYTHONPATH is
# cleared so the axon TPU site customization does not claim the device for
# a CPU-only run.
tests:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q

tests-cov:
	PYTHONPATH= JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q \
		--cov=riptide_tpu --cov-report=term

# Compiled-kernel parity sweep on the REAL TPU (tpu-marked tests only).
# Run alone — one TPU client at a time; Mosaic compiles of the three
# production buckets take minutes each on a cold cache.
tests-tpu:
	RIPTIDE_TESTS_TPU=1 $(PYTHON) -m pytest tests/ -q -m tpu

# Build the native host library explicitly (it otherwise builds lazily
# on first use).
native:
	$(PYTHON) -c "from riptide_tpu import native; assert native.available()"

# Headline benchmark on the default device (ONE JSON line).
bench:
	$(PYTHON) bench.py

clean:
	rm -rf riptide_tpu/native/_build build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
