"""
Headline benchmark: DM-trials/sec on a 2^23-sample periodogram search at
S/N parity with the reference C library (BASELINE.json metric).

Config mirrors the reference docs' canonical search (quickstart.rst /
BASELINE.json config 5): 2^23 samples @ 64 us, trial periods 0.5-3.0 s,
240-260 phase bins, boxcar width ladder from generate_width_trials(240)
=> 222,955 trial periods x 10 widths per DM trial.

Baseline: the reference C++ engine (riptide/cpp/periodogram.hpp compiled
-O3 -ffast-math -march=native, single core, its design point — OpenMP was
removed upstream as a pessimization) measured on this machine at
0.2511 s per DM trial on the identical config (see tools/ref_bench.cpp
provenance in BASELINE.md). vs_baseline = our trials/sec over the
reference's 3.98 trials/sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

import numpy as np

REF_SECONDS_PER_TRIAL = 0.2511  # reference C++, single core, same config

N = 1 << 23
TSAMP = 64e-6
PERIOD_MIN, PERIOD_MAX = 0.5, 3.0
BINS_MIN, BINS_MAX = 240, 260
D = 8  # DM trials per timed batch


def main():
    from riptide_tpu.ffautils import generate_width_trials
    from riptide_tpu.search import periodogram_plan, run_periodogram_batch

    widths = tuple(int(w) for w in generate_width_trials(BINS_MIN))
    plan = periodogram_plan(N, TSAMP, widths, PERIOD_MIN, PERIOD_MAX, BINS_MIN, BINS_MAX)

    rng = np.random.default_rng(0)
    batch = rng.standard_normal((D, N), dtype=np.float32)

    # Warm-up at the FULL batch shape: cycle programs are jit-specialised
    # on D, so warming with a smaller batch would leave compilation
    # inside the timed region.
    run_periodogram_batch(plan, batch)

    t0 = time.perf_counter()
    periods, foldbins, snrs = run_periodogram_batch(plan, batch)
    elapsed = time.perf_counter() - t0

    trials_per_sec = D / elapsed
    vs_baseline = trials_per_sec * REF_SECONDS_PER_TRIAL
    print(
        json.dumps(
            {
                "metric": "dm_trials_per_sec_2p23_samples",
                "value": round(trials_per_sec, 3),
                "unit": "DM-trials/s",
                "vs_baseline": round(vs_baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
