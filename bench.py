"""
Headline benchmark: DM-trials/sec on a 2^23-sample periodogram search at
S/N parity with the reference C library (BASELINE.json metric).

Default run = BASELINE config 5 shape on one chip: D DM trials x 2^23
samples @ 64 us, periods 0.5-3.0 s, bins 240-260, width ladder from
generate_width_trials(240) => 222,955 trial periods x 10 widths per DM
trial, searched through the fused Pallas FFA/S-N kernel with ON-DEVICE
peak detection (only KB-sized peak buffers reach the host). Trial 0
carries an injected amplitude-20 pulsar at P = 1.0 s; before timing, its
on-device peaks are asserted identical to the host find_peaks run on the
pulled S/N column (the S/N-parity gate), and the peak must sit at 1.0 s.

Baseline: the reference C++ engine (riptide/cpp/periodogram.hpp, -O3
-ffast-math -march=native, single core — its design point; OpenMP was
removed upstream as a pessimization) measured on this machine at
0.2511 s per DM trial on the identical config (tools/ref_bench.cpp,
BASELINE.md). vs_baseline = our DM-trials/sec x 0.2511.

Prints the result as a JSON line {"metric", "value", "unit",
"vs_baseline", "passes"} plus the metrics-registry sub-metrics of the
timed pass ("device_s", "prep_s", "wire_MBps", "chunk_s" — where the
time went, recorded by the engine layer itself): one line after the
FIRST timed pass (so a number is recorded even if a later pass stalls
or the harness timeout hits), and — when time allows more passes — a
best-of-N line with N capped at 3 to mirror the reference baseline's
best-of-3 posture. The LAST line is authoritative. The run budgets itself against
RIPTIDE_BENCH_BUDGET seconds of total process wall time (default 1380;
the round-4 driver run was killed at >= 1570 s with no number emitted).
Other BASELINE.json configs: --config 1..5 (see _CONFIGS).
"""
import argparse
import faulthandler
import json
import logging
import os
import sys
import time

_PROC_T0 = time.monotonic()
BUDGET = float(os.environ.get("RIPTIDE_BENCH_BUDGET", "1380"))


def _remaining():
    return BUDGET - (time.monotonic() - _PROC_T0)


if os.environ.get("RIPTIDE_BENCH_DEBUG"):
    # Periodic stack dumps to locate long compiles / stalls.
    faulthandler.dump_traceback_later(180, repeat=True, file=sys.stderr)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# Surface the engine's per-bucket warm timings (loaded vs compiled) so a
# slow cold start names its pole in the driver log.
logging.basicConfig(stream=sys.stderr)
logging.getLogger("riptide_tpu.search.engine").setLevel(logging.INFO)

import numpy as np

REF_SECONDS_PER_TRIAL = 0.2511  # reference C++, single core, same config

N = 1 << 23
TSAMP = 64e-6
PERIOD_MIN, PERIOD_MAX = 0.5, 3.0
BINS_MIN, BINS_MAX = 240, 260
D = 32      # DM trials per device batch
CHUNKS = 5  # batches in the timed pipeline (host prep overlaps device)
PKW = dict(smin=7.0, segwidth=5.0, nstd=6.0, minseg=10, polydeg=2, clrad=0.1)


def _make_batch(d, n, tsamp, pulsar_period=1.0, seed=0):
    """(d, n) normalised noise batch, trial 0 = injected pulsar."""
    from riptide_tpu.libffa import generate_signal

    rng = np.random.default_rng(seed)
    batch = rng.standard_normal((d, n), dtype=np.float32)
    np.random.seed(0)
    batch[0] = generate_signal(
        n, pulsar_period / tsamp, amplitude=20.0, ducy=0.02, stdnoise=1.0
    )
    batch -= batch.mean(axis=1, keepdims=True)
    batch /= batch.std(axis=1, keepdims=True)
    return batch


def _parity_gate(plan, batch, tobs):
    """On-device peaks for trial 0 must equal host find_peaks on the
    pulled S/N column, and recover the injected pulsar at P = 1.0 s.
    Runs at the full batch shape so it warms the same D-specialised
    programs the timed loop uses; only trial 0's S/N column is pulled
    (the full cube would be GB-scale at D=32)."""
    import numpy as _np

    from riptide_tpu.metadata import Metadata
    from riptide_tpu.peak_detection import find_peaks
    from riptide_tpu.periodogram import Periodogram
    from riptide_tpu.search.engine import (
        collect_search_batch, queue_search_batch, search_snr_dev,
    )

    # ONE search serves both sides of the gate: trial 0's S/N column is
    # pulled from the same queued batch the on-device path collects.
    handle = queue_search_batch(plan, batch, tobs=tobs, **PKW)
    snr0 = _np.asarray(search_snr_dev(handle)[0])  # one trial's cube
    md = Metadata({"dm": 0.0, "tobs": tobs})
    pgram = Periodogram(plan.widths, plan.all_periods, plan.all_foldbins,
                        snr0, md)
    host_peaks, _ = find_peaks(pgram, **PKW)
    dev_peaks_all, _ = collect_search_batch(handle, _np.zeros(len(batch)))
    dev_peaks = dev_peaks_all[0]

    hset = [(p.ip, p.iw, round(p.snr, 3)) for p in host_peaks]
    dset = [(p.ip, p.iw, round(p.snr, 3)) for p in dev_peaks]
    assert dset == hset, f"device/host peak mismatch: {dset[:5]} vs {hset[:5]}"
    top = dev_peaks[0]
    assert abs(top.period - 1.0) < 1e-4, top
    # Parity band derived AT RUN TIME from the exact (float32-wire)
    # result of the same injected trial: a D=1 search of trial 0
    # through the float32 transport gives the reference top S/N the
    # quantised wire must reproduce within its error budget (+/- 0.15
    # S/N — the bound the uint6 wire is sized for). Deriving the band
    # from the run itself keeps the gate valid when the config or the
    # quantiser changes; the self-measured 17.3 +/- 0.15 history is
    # demoted to a secondary drift check below.
    from riptide_tpu.search.engine import (
        _ffa_path, _wire_mode, prepare_stage_data, run_search_batch,
    )

    prep32 = prepare_stage_data(plan, batch[:1], mode="float32")
    ref_peaks, _ = run_search_batch(plan, None, tobs=tobs, dms=_np.zeros(1),
                                    prepared=prep32, **PKW)
    ref_snr = ref_peaks[0][0].snr
    assert abs(ref_peaks[0][0].period - 1.0) < 1e-4, ref_peaks[0][0]
    assert abs(top.snr - ref_snr) < 0.15, (top.snr, ref_snr)
    # Secondary (historical) band: the float32 reference itself has
    # measured 17.3 at this config across rounds r03-r05; a drift here
    # means the SEARCH changed, not the wire.
    assert abs(ref_snr - 17.3) < 0.3, ref_snr

    path = _ffa_path()
    print(
        f"parity gate: {len(dev_peaks)} peaks, top S/N {top.snr:.2f} "
        f"(float32 reference {ref_snr:.2f}) at P = {top.period:.6f} s "
        f"(device == host; path={path}, wire={_wire_mode(path)})",
        file=sys.stderr,
    )


def _pipeline_pass(plan, tobs, nchunks, dms, batch_for, prepper, shipper):
    """One pipelined pass over ``nchunks`` chunks — the production
    queue-ahead posture shared by the headline and the survey configs:
    the prep thread (CPU-bound native downsampling + quantisation)
    works on chunk i+2 while the ship thread (wire-bound device_put)
    moves chunk i+1 and the device computes chunk i; the main thread
    only queues dispatches and syncs results. Steady state is
    max(prep, wire, device) rather than their sum. Only chunk 0's
    prep+ship (the pipeline fill) happens before the clock starts —
    matching the reference baseline's data-in-memory timing posture;
    every other chunk's prep AND wire transfer is inside the timed
    window. ``batch_for(i)`` supplies chunk i's host batch. Returns
    elapsed seconds."""
    from riptide_tpu.search.engine import (
        collect_search_batch, prepare_stage_data, queue_search_batch,
        ship_stage_data,
    )

    from riptide_tpu.survey.metrics import get_metrics

    def prep_ship(i):
        fut = prepper.submit(prepare_stage_data, plan, batch_for(i))
        return shipper.submit(
            lambda f=fut: ship_stage_data(plan, f.result())
        )

    shipped = prep_ship(0).result()
    # Per-pass metrics window: the engine records prep_s / wire traffic
    # / device_s into the registry; reset AFTER the pipeline fill so the
    # snapshot covers exactly the timed region.
    metrics = get_metrics()
    metrics.reset()
    t0 = time.perf_counter()
    ship_futs = {1: prep_ship(1)} if nchunks > 1 else {}
    pending = None
    for i in range(nchunks):
        handle = queue_search_batch(plan, None, tobs=tobs,
                                    shipped=shipped, **PKW)  # async
        if i + 2 < nchunks:
            ship_futs[i + 2] = prep_ship(i + 2)
        if i + 1 < nchunks:
            shipped = ship_futs.pop(i + 1).result()
        if pending is not None:
            peaks, _ = collect_search_batch(pending, dms)  # syncs
            assert peaks[0] and abs(peaks[0][0].period - 1.0) < 1e-4
        pending = handle
    peaks, _ = collect_search_batch(pending, dms)
    assert peaks[0] and abs(peaks[0][0].period - 1.0) < 1e-4
    elapsed = time.perf_counter() - t0
    metrics.observe("chunk_s", elapsed / max(nchunks, 1))
    return elapsed


def _ledger_row(kind, sub, nchunks, extra):
    """Append one run row to the perf ledger (RIPTIDE_LEDGER; no-op
    when unset). bench has no per-chunk timing records, so the
    run-level tunnel/device classification stands in for the per-chunk
    bound counts (the ratio is identical on totals)."""
    from riptide_tpu.obs import ledger
    from riptide_tpu.obs.schema import classify_bound

    bound = classify_bound(sub.get("wire_s") or 0.0,
                           sub.get("device_s") or 0.0)
    ledger.maybe_append(kind, sub, nchunks=nchunks,
                        bound_counts={bound: nchunks}, extra=extra)


def _submetrics(nchunks, elapsed):
    """Machine-readable sub-metrics of the pass just timed, from the
    metrics registry the engine records into. The key set is the ONE
    timing schema (riptide_tpu.obs.schema.decomposition) shared with
    tools/stime.py's closing block and the survey journal, so every
    surface a driver log parser reads carries identical names."""
    from riptide_tpu.obs.schema import decomposition
    from riptide_tpu.survey.metrics import get_metrics

    return decomposition(get_metrics().summary(), nchunks, elapsed)


def bench_headline():
    """Pipelined survey throughput: CHUNKS batches of D trials, with the
    host half (native threaded downsampling + wire packing) of batch i+1
    overlapping device execution of batch i — the steady-state survey
    pattern of the pipeline's BatchSearcher."""
    from concurrent.futures import ThreadPoolExecutor

    from riptide_tpu.ffautils import generate_width_trials
    from riptide_tpu.search import periodogram_plan

    widths = tuple(int(w) for w in generate_width_trials(BINS_MIN))
    plan = periodogram_plan(
        N, TSAMP, widths, PERIOD_MIN, PERIOD_MAX, BINS_MIN, BINS_MAX
    )
    tobs = N * TSAMP

    # Warm every cycle-kernel bucket first: concurrent AOT compiles, or
    # ~seconds when the cross-process executable cache is hot.
    from riptide_tpu.search.engine import warm_stage_kernels

    t0 = time.perf_counter()
    nwarm = warm_stage_kernels(plan, D)
    print(
        f"kernel warm ({nwarm} builds): {time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )

    batches = [_make_batch(D, N, TSAMP, seed=k) for k in range(2)]

    t0 = time.perf_counter()
    _parity_gate(plan, batches[0], tobs)
    print(
        f"warmup + parity gate: {time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )

    dms = np.zeros(D)

    def timed_pipeline(prepper, shipper):
        return _pipeline_pass(plan, tobs, CHUNKS, dms,
                              lambda i: batches[i % 2], prepper, shipper)

    # Container-occupancy accounting of the plan's kernel layout (live
    # vs padded row*lane work, row-pack pairing, reduction vs the
    # legacy layout): the machine-readable form of the perf_notes
    # occupancy claims, carried on every emitted line and ledger row.
    from riptide_tpu.search.plan import plan_occupancy

    occ = plan_occupancy(plan)
    occupancy = dict(occ["totals"], pairs=occ["pairs"],
                     row_pack=occ["row_pack"])

    def emit(elapsed, npasses, sub):
        trials_per_sec = D * CHUNKS / elapsed
        line = {
            "metric": "dm_trials_per_sec_2p23_samples",
            "value": round(trials_per_sec, 3),
            "unit": "DM-trials/s",
            "vs_baseline": round(
                trials_per_sec * REF_SECONDS_PER_TRIAL, 2
            ),
            "passes": npasses,
            "occupancy": occupancy,
        }
        line.update(sub)
        print(json.dumps(line), flush=True)
        print(f"(best of {npasses} pipelined passes)", file=sys.stderr)

    with ThreadPoolExecutor(max_workers=1) as prepper, \
            ThreadPoolExecutor(max_workers=1) as shipper:
        # Best-of-N pipelined passes, N <= 3 to mirror the reference
        # C++ baseline's best-of-3 posture (BASELINE.md) — more passes
        # would sample the device tunnel's transfer-rate weather (4-70
        # MB/s between minutes, the binding constraint below ~25 MB/s,
        # BENCH_MATRIX) more favourably than the baseline could. The
        # FIRST pass's result is emitted immediately so the driver
        # records a number even if a later pass stalls; further passes
        # run only while the process-wall-time budget clearly covers
        # them, and improvements are re-emitted (last line wins). Each
        # line carries the ACTUAL pass count plus the metrics-registry
        # sub-metrics of its best pass.
        best = timed_pipeline(prepper, shipper)
        best_sub = _submetrics(CHUNKS, best)
        emit(best, 1, best_sub)
        npasses = 1
        while npasses < 3 and _remaining() > 1.5 * best + 60.0:
            dt = timed_pipeline(prepper, shipper)
            npasses += 1
            if dt < best:
                best = dt
                best_sub = _submetrics(CHUNKS, best)
            # Emit after EVERY pass (last line wins, so a later stalled
            # pass cannot discard an earlier best) — each line carries
            # the best pass's dtime-style decomposition (device_s /
            # prep_s / wire_MBps / chunk_s) and the true pass count, so
            # every recorded round has the full breakdown.
            emit(best, npasses, best_sub)
    # One perf-ledger row per bench run (no-op unless RIPTIDE_LEDGER is
    # set): the best pass's decomposition plus the provenance that
    # explains round-over-round deltas (git sha, flags, device, kernel
    # cache version) — the machine-readable form of BENCH_MATRIX.
    _ledger_row("bench", best_sub, CHUNKS,
                {"metric": "dm_trials_per_sec_2p23_samples",
                 "value": round(D * CHUNKS / best, 3),
                 "passes": npasses,
                 "occupancy": occupancy})


def _warm_plan(nsamp, tsamp, period_min, period_max, bins_min, bins_max,
               D=1, **wkw):
    """Concurrently AOT-compile (or cache-load) a config's cycle-kernel
    buckets before its first search, instead of paying each bucket's
    compile serially inside the search loop."""
    from riptide_tpu.ffautils import generate_width_trials
    from riptide_tpu.search import periodogram_plan
    from riptide_tpu.search.engine import warm_stage_kernels

    widths = tuple(int(w) for w in generate_width_trials(bins_min, **wkw))
    plan = periodogram_plan(nsamp, tsamp, widths, period_min, period_max,
                            bins_min, bins_max)
    t0 = time.perf_counter()
    n = warm_stage_kernels(plan, D)
    print(f"kernel warm ({n} builds): {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)


def bench_config1():
    """ffa_search on a 2^20-sample synthetic TimeSeries (single DM)."""
    from riptide_tpu.search import ffa_search
    from riptide_tpu.time_series import TimeSeries

    _warm_plan(1 << 20, 1e-3, 1.0, 30.0, 240, 260)
    np.random.seed(0)
    ts = TimeSeries.generate(
        length=(1 << 20) * 1e-3, tsamp=1e-3, period=1.0, amplitude=20.0
    )
    _, pgram = ffa_search(ts, period_min=1.0, period_max=30.0,
                          bins_min=240, bins_max=260)  # warm
    t0 = time.perf_counter()
    _, pgram = ffa_search(ts, period_min=1.0, period_max=30.0,
                          bins_min=240, bins_max=260)
    dt = time.perf_counter() - t0
    _emit("ffa_search_2p20_seconds", dt, "s")


def bench_config2(tmpdir="/tmp/riptide_bench2"):
    """rseek on one SIGPROC dedispersed series, periods 0.5-10 s.
    Runs the CLI entry in-process: kernel executables cannot persist
    across processes in this environment, so a subprocess re-run would
    time compilation, not the search."""
    from riptide_tpu.apps.rseek import get_parser, run_program

    os.makedirs(tmpdir, exist_ok=True)
    tim = os.path.join(tmpdir, "fake.tim")
    if not os.path.exists(tim):
        _write_sigproc_tim(tim)
    args = get_parser().parse_args(
        ["--format", "sigproc", "--Pmin", "0.5", "--Pmax", "10.0", tim]
    )
    # rseek prints its candidate table; route it to stderr so stdout
    # stays the module's single JSON line.
    from contextlib import redirect_stdout

    with redirect_stdout(sys.stderr):
        run_program(args)  # warm
        t0 = time.perf_counter()
        df = run_program(args)
        dt = time.perf_counter() - t0
    assert df is not None and abs(df.iloc[0]["period"] - 1.0) < 1e-3
    _emit("rseek_sigproc_seconds", dt, "s")


def _write_sigproc_tim(path, n=1 << 22, tsamp=256e-6):
    from riptide_tpu.libffa import generate_signal

    np.random.seed(0)
    data = generate_signal(n, 1.0 / tsamp, amplitude=20.0, ducy=0.02)

    def _str(k):
        return len(k).to_bytes(4, "little") + k.encode()

    hdr = b"".join([
        _str("HEADER_START"),
        _str("nchans") + (1).to_bytes(4, "little"),
        _str("nbits") + (32).to_bytes(4, "little"),
        _str("tsamp") + np.float64(tsamp).tobytes(),
        _str("tstart") + np.float64(56000.0).tobytes(),
        _str("refdm") + np.float64(0.0).tobytes(),
        _str("src_raj") + np.float64(0.0).tobytes(),
        _str("src_dej") + np.float64(0.0).tobytes(),
        _str("HEADER_END"),
    ])
    with open(path, "wb") as f:
        f.write(hdr)
        data.astype(np.float32).tofile(f)


def bench_config3():
    """Boxcar width sweep (1-64 bins) across period octaves of 2^22."""
    from riptide_tpu.ffautils import generate_width_trials
    from riptide_tpu.search import periodogram_plan
    from riptide_tpu.search.engine import run_periodogram

    widths = tuple(w for w in generate_width_trials(256, wtsp=1.5) if w < 64)
    plan = periodogram_plan(1 << 22, 256e-6, widths, 0.5, 8.0, 256, 288)
    from riptide_tpu.search.engine import warm_stage_kernels

    warm_stage_kernels(plan, 1)
    rng = np.random.default_rng(0)
    data = rng.standard_normal(1 << 22).astype(np.float32)
    run_periodogram(plan, data)  # warm
    t0 = time.perf_counter()
    run_periodogram(plan, data)
    _emit("width_sweep_2p22_seconds", time.perf_counter() - t0, "s")


def bench_config4(d=256):
    """256 DM trials, batched periodogram + on-device peaks."""
    _survey(d, 1 << 21, "rffa_256trials_2p21_trials_per_sec")


def bench_config5(d=1024):
    """Full survey: 1024 DM trials x 2^23, on-device peak detection."""
    _survey(d, N, "survey_1024trials_2p23_trials_per_sec")


def _survey(d, n, metric, chunk=32):
    """Chunked survey throughput through the shared
    :func:`_pipeline_pass` queue-ahead posture (the same as the
    headline and the pipeline's BatchSearcher)."""
    from concurrent.futures import ThreadPoolExecutor

    from riptide_tpu.ffautils import generate_width_trials
    from riptide_tpu.search import periodogram_plan
    from riptide_tpu.search.engine import run_search_batch, warm_stage_kernels

    assert d % chunk == 0, "survey configs use whole chunks"
    widths = tuple(int(w) for w in generate_width_trials(BINS_MIN))
    plan = periodogram_plan(n, TSAMP, widths, PERIOD_MIN, PERIOD_MAX,
                            BINS_MIN, BINS_MAX)
    tobs = n * TSAMP
    warm_stage_kernels(plan, chunk)
    batch = _make_batch(chunk, n, TSAMP)
    dms = np.zeros(chunk)
    run_search_batch(plan, batch, tobs=tobs, **PKW)  # warm
    with ThreadPoolExecutor(max_workers=1) as prepper, \
            ThreadPoolExecutor(max_workers=1) as shipper:
        dt = _pipeline_pass(plan, tobs, d // chunk, dms, lambda i: batch,
                            prepper, shipper)
    extra = {"total_seconds": round(dt, 2), "passes": 1}
    sub = _submetrics(d // chunk, dt)
    extra.update(sub)
    _emit(metric, d / dt, "DM-trials/s", extra=extra)
    _ledger_row("bench", sub, d // chunk,
                {"metric": metric, "value": round(d / dt, 3), "passes": 1})


def _emit(metric, value, unit, extra=None):
    out = {"metric": metric, "value": round(value, 4), "unit": unit,
           "vs_baseline": None}
    if extra:
        out.update(extra)
    print(json.dumps(out))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", type=int, default=0,
                    help="BASELINE.json config 1-5; 0 = headline (default)")
    args = ap.parse_args()
    if args.config == 0:
        bench_headline()
    else:
        [None, bench_config1, bench_config2, bench_config3,
         bench_config4, bench_config5][args.config]()


if __name__ == "__main__":
    sys.exit(main())
