"""Compiled-kernel vs oracle verification + timing (run on the real TPU)."""
import sys
import time

import numpy as np

from riptide_tpu.ops.ffa_kernel import CycleKernel
from riptide_tpu.ops.reference import boxcar_snr_2d, ffa_transform
from riptide_tpu.ops.snr import boxcar_coeffs


def setup(ms, ps, widths, interpret=False):
    widths = tuple(w for w in widths if w < min(ps))
    B = len(ms)
    nw = len(widths)
    h = np.zeros((B, nw), np.float32)
    b = np.zeros((B, nw), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.linspace(1.0, 2.0, B).astype(np.float32)
    return CycleKernel(ms, ps, widths, h, b, std, interpret=interpret), widths, std


def fill(k, ms, ps, seed=0):
    rng = np.random.default_rng(seed)
    x = np.zeros((len(ms), k.rows, k.P), np.float32)
    datas = []
    for i, (m, p) in enumerate(zip(ms, ps)):
        d = rng.standard_normal((m, p)).astype(np.float32)
        datas.append(d)
        x[i, :m, :p] = d
    return x, datas


def run(ms, ps, widths=(1, 2, 3, 4, 6, 9, 13, 19, 28, 42), interpret=False,
        seed=0, kernel=None):
    k, widths, std = (kernel if kernel else setup(ms, ps, widths, interpret))
    nw = len(widths)
    x, datas = fill(k, ms, ps, seed)
    out = np.asarray(k(x))
    worst = 0.0
    for i, (m, p, d) in enumerate(zip(ms, ps, datas)):
        tr = ffa_transform(d)
        want = boxcar_snr_2d(tr, np.asarray(widths), stdnoise=float(std[i]))
        got = out[i, :m, :nw]
        err = np.abs(got - want)
        rel = err / np.maximum(np.abs(want), 1.0)
        worst = max(worst, float(rel.max()))
        print(f"  m={m} p={p}: max abs err {err.max():.3e}  max rel err {rel.max():.3e}")
    print("WORST_REL", worst)
    return worst


def timed(ms, ps, widths=(1, 2, 3, 4, 6, 9, 13, 19, 28, 42), reps=10, seed=0):
    """Verify, then time with the slope method (one fetch per run --
    block_until_ready does not synchronize under the axon tunnel)."""
    import jax
    import jax.numpy as jnp

    bundle = setup(ms, ps, widths)
    worst = run(ms, ps, seed=seed, kernel=bundle)
    k = bundle[0]
    x, _ = fill(k, ms, ps, seed)
    xd = jax.device_put(x)
    float(np.asarray(k(xd)[0, 0, 0]))  # warm

    def go(n):
        t0 = time.perf_counter()
        vals = [k(xd)[0, 0, 0] for _ in range(n)]
        assert np.isfinite(float(np.asarray(jnp.stack(vals)).sum()))
        return time.perf_counter() - t0

    t1 = min(go(2) for _ in range(2))
    t2 = min(go(2 + reps) for _ in range(2))
    dt = (t2 - t1) / reps
    print(f"TIMED bucket B={len(ms)} rows={k.rows} P={k.P}: {dt*1e3:.2f} ms/call "
          f"(worst rel err {worst:.2e})")
    return dt


if __name__ == "__main__":
    interp = "i" in sys.argv[1:]
    if "bucket" in sys.argv[1:]:
        # one bucket: same L, many p (like a real cascade cycle)
        ms = [1046 - 4 * i for i in range(21)]
        ps = list(range(240, 261))
        if "t" in sys.argv[1:]:
            timed(ms, ps)
        else:
            run(ms, ps, interpret=interp)
        sys.exit(0)
    pairs = [(100, 17), (250, 240), (1000, 250)]
    if "prod" in sys.argv[1:]:
        pairs = [(1046, 250), (1007, 260), (967, 241), (521, 257)]
    for m, p in pairs:
        run([m], [p], interpret=interp)
