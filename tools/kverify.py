"""Compiled-kernel vs oracle verification (run on the real TPU)."""
import sys

import numpy as np

from riptide_tpu.ops.ffa_kernel import CycleKernel
from riptide_tpu.ops.reference import boxcar_snr_2d, ffa_transform
from riptide_tpu.ops.snr import boxcar_coeffs


def run(ms, ps, widths=(1, 2, 3, 4, 6, 9, 13, 19, 28, 42), interpret=False, seed=0):
    widths = tuple(w for w in widths if w < min(ps))
    B = len(ms)
    nw = len(widths)
    h = np.zeros((B, nw), np.float32)
    b = np.zeros((B, nw), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.linspace(1.0, 2.0, B).astype(np.float32)
    k = CycleKernel(ms, ps, widths, h, b, std, interpret=interpret)
    rng = np.random.default_rng(seed)
    x = np.zeros((B, k.rows, k.P), np.float32)
    datas = []
    for i, (m, p) in enumerate(zip(ms, ps)):
        d = rng.standard_normal((m, p)).astype(np.float32)
        datas.append(d)
        x[i, :m, :p] = d
    out = np.asarray(k(x))
    worst = 0.0
    for i, (m, p, d) in enumerate(zip(ms, ps, datas)):
        tr = ffa_transform(d)
        want = boxcar_snr_2d(tr, np.asarray(widths), stdnoise=float(std[i]))
        got = out[i, :m, :nw]
        err = np.abs(got - want)
        rel = err / np.maximum(np.abs(want), 1.0)
        worst = max(worst, float(rel.max()))
        print(f"  m={m} p={p}: max abs err {err.max():.3e}  max rel err {rel.max():.3e}")
    print("WORST_REL", worst)
    return worst


if __name__ == "__main__":
    interp = "i" in sys.argv[1:]
    pairs = [(100, 17), (250, 240), (1000, 250)]
    if "prod" in sys.argv[1:]:
        pairs = [(1046, 250), (1007, 260), (967, 241), (521, 257)]
    if "bucket" in sys.argv[1:]:
        # one bucket: same L, many p (like a real cascade cycle)
        ms = [1046 - 4 * i for i in range(21)]
        ps = list(range(240, 261))
        run(ms, ps, interpret=interp)
        sys.exit(0)
    for m, p in pairs:
        run([m], [p], interpret=interp)
