"""Persistent TPU lab: warm the bench programs once, then execute timing
commands from ~/.riptide_lab/cmd (one per line appended; results appended
to ~/.riptide_lab/log; the directory is 0700 since commands are exec'd). Avoids paying the ~15 min Mosaic compile per
experiment (the compile cache cannot persist Pallas executables).

Commands: prep | ship | stages | assemble | stats | select | finalize |
full | pull1 | exit
"""
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from riptide_tpu.ffautils import generate_width_trials
from riptide_tpu.search import periodogram_plan
from riptide_tpu.search.engine import (
    _assemble_device, _peak_plan, _queue_stages, prepare_stage_data,
    run_search_batch,
)

N = 1 << 23
TSAMP = 64e-6
D = int(os.environ.get("LAB_D", "32"))
PKW = dict(smin=7.0, segwidth=5.0, nstd=6.0, minseg=10, polydeg=2, clrad=0.1)

# Command/log files live in a mode-0700 directory: the command file is
# exec'd, so it must not be world-writable.
_LAB_DIR = os.path.join(os.path.expanduser("~"), ".riptide_lab")
os.makedirs(_LAB_DIR, mode=0o700, exist_ok=True)
os.chmod(_LAB_DIR, 0o700)
CMD, LOG = os.path.join(_LAB_DIR, "cmd"), os.path.join(_LAB_DIR, "log")


def log(msg):
    with open(LOG, "a") as f:
        f.write(f"{time.strftime('%H:%M:%S')} {msg}\n")


def sync(x):
    """True device sync: fetch one element."""
    return float(np.asarray(jax.numpy.ravel(x)[0]))


def main():
    widths = tuple(int(w) for w in generate_width_trials(240))
    plan = periodogram_plan(N, TSAMP, widths, 0.5, 3.0, 240, 260)
    tobs = N * TSAMP
    rng = np.random.default_rng(0)
    batch = rng.standard_normal((D, N), dtype=np.float32)
    log(f"lab starting: D={D}, warming...")
    t0 = time.perf_counter()
    run_search_batch(plan, batch, tobs=tobs, **PKW)
    log(f"warm done in {time.perf_counter()-t0:.1f}s; ready")

    state = {}
    pos = 0
    while True:
        time.sleep(2.0)
        if not os.path.exists(CMD):
            continue
        with open(CMD) as f:
            lines = f.read().splitlines()
        new = lines[pos:]
        pos = len(lines)
        for cmd in new:
            cmd = cmd.strip()
            if not cmd:
                continue
            t0 = time.perf_counter()
            try:
                if cmd == "exit":
                    log("bye")
                    return
                elif cmd == "prep":
                    state["prep"] = prepare_stage_data(plan, batch)
                elif cmd == "ship":
                    prep = state.get("prep") or prepare_stage_data(plan, batch)
                    state["prep"] = prep
                    t0 = time.perf_counter()
                    dev = jnp.asarray(prep[0])
                    sync(dev)
                elif cmd == "stages":
                    t0 = time.perf_counter()
                    outs, layout = _queue_stages(plan, batch,
                                                 state.get("prep"))
                    sync(outs[-1][0])
                    state["outs"], state["layout"] = outs, layout
                elif cmd == "assemble":
                    outs = state["outs"]
                    t0 = time.perf_counter()
                    snr = _assemble_device(plan, state["layout"], *outs)
                    sync(snr)
                    state["snr"] = snr
                elif cmd == "stats":
                    pp = _peak_plan(plan, tobs, **PKW)
                    snr = state["snr"]
                    t0 = time.perf_counter()
                    stats = np.asarray(pp._stats(snr))
                    state["stats"] = stats
                    state["pp"] = pp
                elif cmd == "select":
                    pp, snr = state["pp"], state["snr"]
                    polyco = pp._fit(state["stats"])
                    state["polyco"] = polyco
                    t0 = time.perf_counter()
                    cnt = np.asarray(pp._block_counts(
                        snr, jnp.asarray(polyco, jnp.float32)))
                    state["cnt"] = cnt
                elif cmd == "finalize":
                    from riptide_tpu.search.peaks_device import (
                        device_find_peaks,
                    )
                    pp, snr = state["pp"], state["snr"]
                    t0 = time.perf_counter()
                    device_find_peaks(pp, snr, np.zeros(D))
                elif cmd == "full":
                    t0 = time.perf_counter()
                    run_search_batch(plan, batch, tobs=tobs, **PKW)
                elif cmd == "pull1":
                    snr = state["snr"]
                    t0 = time.perf_counter()
                    np.asarray(snr[0])
                elif cmd.startswith("exec "):
                    # arbitrary experiment: exec a python file in this
                    # process's context (plan/batch/state in scope)
                    path = cmd.split(None, 1)[1]
                    src = open(path).read()
                    t0 = time.perf_counter()
                    exec(compile(src, path, "exec"), {
                        "np": np, "jnp": jnp, "jax": jax, "time": time,
                        "plan": plan, "batch": batch, "state": state,
                        "tobs": tobs, "PKW": PKW, "log": log, "sync": sync,
                        "D": D,
                    })
                else:
                    log(f"{cmd}: unknown")
                    continue
                log(f"{cmd}: {time.perf_counter()-t0:.3f}s")
            except Exception as err:
                log(f"{cmd}: ERROR {err!r}")


if __name__ == "__main__":
    main()
