#!/usr/bin/env python
"""
rprove: verify the jaxpr-level program contracts of the search plans.

The SEMANTIC counterpart of riplint: where riplint walks the AST, this
tool abstractly traces (``jax.make_jaxpr`` / AOT lowering — no device
execution, backend-free under ``JAX_PLATFORMS=cpu``) every staged
computation the engine queues for the representative plan set
(``riptide_tpu.ops.plan.CONTRACT_PLANS``) and compares the extracted
program contracts — dispatch counts by kind, the buffer-liveness
peak-HBM model, the dtype-flow audit, host<->device transfer bytes,
donation verification — against the pinned
``tools/plan_contracts.json``. See
``riptide_tpu/analysis/jaxpr_contract.py`` and
docs/static_analysis.md ("Semantic pass").

Exit status 0 on zero drift; 1 on any drift or absolute violation
(float64 in a traced program, a dropped donation, a pack program on a
fused stage); 2 when the contract file is missing. The workflow is
``kernel_digest.json``'s: after a DELIBERATE change to the traced
programs, re-pin with ``--update`` and commit the diff.

``--format sarif`` reuses riplint's SARIF 2.1.0 writer, so both
analyzers publish one result format for CI annotation uploads.
``--all`` adds the slow-tier (survey-shaped) plans; ``--plans A,B``
(or ``RIPTIDE_PROVE_PLANS``) restricts to named plans for quick local
runs. Contracts are pinned under DEFAULT env semantics: path/wire/
kernel-shape overrides (``RIPTIDE_FFA_PATH`` etc.) are dropped from
the environment before tracing.
"""
import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
# Runnable as `python tools/rprove.py` from an uninstalled checkout.
if REPO not in sys.path:
    sys.path.insert(0, REPO)
DEFAULT_CONTRACTS = os.path.join(REPO, "tools", "plan_contracts.json")
CONTRACT_REL = "tools/plan_contracts.json"

# Env overrides that change plan geometry or dispatch structure:
# contracts describe the DEFAULT semantics, so these are dropped before
# the package configures itself.
_CONTRACT_ENV = ("RIPTIDE_FFA_PATH", "RIPTIDE_WIRE_DTYPE",
                 "RIPTIDE_KERNEL_LANE_SPLIT", "RIPTIDE_KERNEL_BASE3",
                 "RIPTIDE_KERNEL_RESIDENT", "RIPTIDE_DEVICE_CLUSTER")


def _force_cpu():
    """Tracing is backend-free: pin the CPU backend (both the env form
    and — for processes whose sitecustomize already imported jax — the
    post-import config form) and neutralise contract-changing env
    overrides."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    for k in _CONTRACT_ENV:
        os.environ.pop(k, None)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialised (e.g. under pytest): fine


def load_riplint():
    """tools/riplint.py loaded by file path — rprove reuses its SARIF
    writer so both analyzers publish one result format."""
    name = "riplint_for_rprove"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(HERE, "riplint.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[name]
        raise
    return mod


class _Rule:
    """SARIF rule-metadata shim matching the Analyzer attributes
    riplint's writer reads."""

    def __init__(self, rule, name, description):
        self.rule = rule
        self.name = name
        self.description = description


def _rules():
    from riptide_tpu.analysis.jaxpr_contract import RULES

    return [_Rule(*r) for r in RULES]


def build_current(names=None, tiers=("fast",)):
    """Freshly-extracted contracts for the selected plan set."""
    _force_cpu()
    from riptide_tpu.analysis import jaxpr_contract as jc
    from riptide_tpu.ops.plan import contract_plan_params

    out = {}
    for spec in contract_plan_params(names, tiers=tiers):
        plan = jc.build_contract_plan(spec)
        out[spec["name"]] = jc.extract_contract(
            spec["name"], plan, path=spec["path"], mode=spec["wire"])
    return out


def run(contracts_path=DEFAULT_CONTRACTS, names=None, all_tiers=False,
        update=False, fmt="text", out=sys.stdout, err=sys.stderr):
    """Extract, compare (or re-pin), emit; returns the exit code."""
    tiers = ("fast", "slow") if all_tiers else ("fast",)
    current = build_current(names, tiers)
    from riptide_tpu.analysis import jaxpr_contract as jc
    from riptide_tpu.ops.plan import CONTRACT_PLANS

    all_names = [s["name"] for s in CONTRACT_PLANS]
    pinned = jc.load_contracts(contracts_path)

    if update:
        doc = pinned or {"version": 1, "plans": {}}
        doc["plans"].update(current)
        # A renamed/removed plan spec takes its pinned entry with it.
        doc["plans"] = {k: v for k, v in sorted(doc["plans"].items())
                        if k in all_names}
        with open(contracts_path, "w") as fobj:
            json.dump(doc, fobj, indent=1, sort_keys=True)
            fobj.write("\n")
        print(f"pinned {len(current)} contract(s) "
              f"({len(doc['plans'])} total) to "
              f"{os.path.relpath(contracts_path, REPO)}", file=err)
        return 0

    if pinned is None:
        print(f"rprove: no contract file at {contracts_path!r}; run "
              "`python tools/rprove.py --update --all` and commit it",
              file=err)
        return 2

    findings = jc.check_contracts(pinned, current, all_names,
                                  contract_rel=CONTRACT_REL)
    if fmt == "sarif":
        riplint = load_riplint()
        doc = riplint._sarif_doc({"new": findings, "stale": []},
                                 _rules(), tool="rprove")
        json.dump(doc, out, indent=2)
        out.write("\n")
    else:
        for f in findings:
            print(f"{f['path']}:{f['line']}:{f['col']}: {f['rule']} "
                  f"{f['message']}", file=out)
    n_stages = sum(len(c["stages"]) for c in current.values())
    if findings:
        print(f"rprove: {len(findings)} contract violation(s) over "
              f"{len(current)} plan(s) / {n_stages} staged program(s)",
              file=err)
        return 1
    print(f"rprove OK: {len(current)} plan contract(s) verified "
          f"({n_stages} staged programs traced, zero drift)", file=err)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="rprove", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--contracts", default=DEFAULT_CONTRACTS,
                    help="pinned contract file (default "
                         "tools/plan_contracts.json)")
    ap.add_argument("--update", action="store_true",
                    help="re-pin the selected plans' contracts (the "
                         "kernel_digest workflow: commit the diff)")
    ap.add_argument("--all", action="store_true", dest="all_tiers",
                    help="include the slow-tier (survey-shaped) plans")
    ap.add_argument("--plans", default=None,
                    help="comma-separated plan-name subset (default: "
                         "the RIPTIDE_PROVE_PLANS env flag, else every "
                         "selected-tier plan)")
    ap.add_argument("--format", choices=("text", "sarif"),
                    default="text", dest="fmt",
                    help="output format: GitHub-annotation text "
                         "(default) or one SARIF 2.1.0 run (riplint's "
                         "writer)")
    args = ap.parse_args(argv)

    plans = args.plans or os.environ.get("RIPTIDE_PROVE_PLANS")
    names = [p.strip() for p in plans.split(",") if p.strip()] \
        if plans else None
    return run(contracts_path=args.contracts, names=names,
               all_tiers=args.all_tiers, update=args.update,
               fmt=args.fmt)


if __name__ == "__main__":
    sys.exit(main())
