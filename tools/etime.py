"""Time the full engine (kernel path) on the real TPU.

Usage: python tools/etime.py [log2_nsamp] [D] [reps]
"""
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import numpy as np

from riptide_tpu.ffautils import generate_width_trials
from riptide_tpu.search import periodogram_plan
from riptide_tpu.search.engine import run_periodogram_batch

LOG2N = int(sys.argv[1]) if len(sys.argv) > 1 else 20
D = int(sys.argv[2]) if len(sys.argv) > 2 else 2
REPS = int(sys.argv[3]) if len(sys.argv) > 3 else 3

N = 1 << LOG2N
TSAMP = 64e-6

widths = tuple(int(w) for w in generate_width_trials(240))
t0 = time.perf_counter()
plan = periodogram_plan(N, TSAMP, widths, 0.5, 3.0, 240, 260)
print(f"plan: {len(plan.stages)} stages, {plan.length} trials, "
      f"depths {sorted(set(st.kernel_depth for st in plan.stages))} "
      f"[{time.perf_counter()-t0:.1f}s]")

rng = np.random.default_rng(0)
batch = rng.standard_normal((D, N)).astype(np.float32)

t0 = time.perf_counter()
run_periodogram_batch(plan, batch)
print(f"warmup (incl. table build + compile): {time.perf_counter()-t0:.1f}s")

best = 1e9
for _ in range(REPS):
    t0 = time.perf_counter()
    periods, foldbins, snrs = run_periodogram_batch(plan, batch)
    best = min(best, time.perf_counter() - t0)
print(f"N=2^{LOG2N} D={D}: {best:.3f} s/batch = {D/best:.3f} DM-trials/s "
      f"(vs_baseline x0.2511 = {D/best*0.2511:.2f})")
print("snr stats:", float(snrs.max()), snrs.shape)
