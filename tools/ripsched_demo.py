#!/usr/bin/env python
"""
ripsched-demo: end-to-end acceptance of the schedule-exploration
model checker (PR 20) — the checker proven NON-VACUOUS on the serve
plane's real concurrency protocols.

Three legs, all through the real ``tools/ripsched.py`` CLI:

1. **clean exploration** — every registered model (the real
   FairShareQueue drain protocol among them) explores to the default
   preemption bound with ZERO invariant violations and exit 0.
2. **re-armed bug** — the ``drop_notify`` mutation re-arms the
   lost-wakeup bug in the fairshare model's drain path (a ``notify``
   dropped under the queue lock); the explorer MUST exit 1 and print
   the minimal failing schedule with its replay ID — a checker that
   cannot re-find a seeded bug proves nothing.
3. **deterministic replay** — replaying the reported schedule ID
   reproduces the violation (exit 1) with byte-identical output
   across two runs: the repro a violation report hands to a human is
   stable.

``make ripsched-demo`` runs this; it is wired into ``make
check-full``.
"""
import os
import re
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RIPSCHED = os.path.join(HERE, "ripsched.py")


def _run(*args):
    proc = subprocess.run([sys.executable, RIPSCHED, *args],
                          capture_output=True, text=True, cwd=REPO)
    return proc.returncode, proc.stdout, proc.stderr


def main():
    # -- leg 1: the real protocols explore clean ----------------------
    code, out, err = _run()
    if code != 0:
        print(err + out)
        print("ripsched demo FAILED: clean exploration of the real "
              f"models exited {code} (expected 0)")
        return 1
    print(err.strip().splitlines()[-1])

    # -- leg 2: the re-armed lost-wakeup bug is found -----------------
    code, out, err = _run("--model", "fairshare", "--mutate",
                          "drop_notify")
    if code != 1:
        print(err + out)
        print("ripsched demo FAILED: the drop_notify mutation was NOT "
              f"detected (exit {code}, expected 1) — the no-lost-wakeup "
              "invariant is vacuous")
        return 1
    m = re.search(r"--replay '([^']+)'", out)
    if not m or "no-lost-wakeup" not in out:
        print(out)
        print("ripsched demo FAILED: violation report did not print "
              "the minimal schedule + replay ID")
        return 1
    sid = m.group(1)
    print(f"re-armed bug found: no-lost-wakeup violated, minimal "
          f"schedule {sid}")

    # -- leg 3: byte-identical deterministic replay -------------------
    runs = [_run("--replay", sid) for _ in range(2)]
    for code, out, err in runs:
        if code != 1:
            print(err + out)
            print(f"ripsched demo FAILED: replay exited {code} "
                  "(expected 1: the violation must reproduce)")
            return 1
    if runs[0][1] != runs[1][1]:
        print("ripsched demo FAILED: two replays of the same schedule "
              "ID rendered different traces")
        return 1
    print(f"replay OK: {sid} reproduces the violation, byte-identical "
          "across runs")

    print("\nripsched demo OK: clean models explore clean, the seeded "
          "bug is found with a minimal replayable schedule, and the "
          "replay is deterministic")
    return 0


if __name__ == "__main__":
    sys.exit(main())
