"""Time the compiled CycleKernel on the real TPU at production shapes."""
import sys
import time

import jax
import numpy as np

from riptide_tpu.ops.ffa_kernel import CycleKernel
from riptide_tpu.ops.snr import boxcar_coeffs


def run(ms, ps, widths=(1, 2, 3, 4, 6, 9, 13, 19, 28, 42), reps=10):
    widths = tuple(w for w in widths if w < min(ps))
    B = len(ms)
    nw = len(widths)
    h = np.zeros((B, nw), np.float32)
    b = np.zeros((B, nw), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.ones(B, np.float32)
    k = CycleKernel(ms, ps, widths, h, b, std)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((B, k.rows, k.P)).astype(np.float32)
    import jax.numpy as jnp

    xd = jax.device_put(x)
    # Warm up + true sync (block_until_ready does not sync under the
    # axon tunnel; only a real device->host fetch does).
    t0 = time.perf_counter()
    float(np.asarray(k(xd)[0, 0, 0]))
    print(f"  warmup (compile): {time.perf_counter()-t0:.1f}s", flush=True)

    def run(reps):
        t0 = time.perf_counter()
        vals = [k(xd)[0, 0, 0] for _ in range(reps)]
        s = float(np.asarray(jnp.stack(vals)).sum())  # ONE fetch
        assert np.isfinite(s)
        dt = time.perf_counter() - t0
        print(f"  run({reps}): {dt:.3f}s", flush=True)
        return dt

    r1, r2 = 2, 2 + reps
    t1 = min(run(r1) for _ in range(2))
    t2 = min(run(r2) for _ in range(2))
    dt = (t2 - t1) / (r2 - r1)
    adds = sum(m * p * np.ceil(np.log2(max(m, 2))) for m, p in zip(ms, ps))
    print(
        f"bucket B={B} rows={k.rows} P={k.P}: {dt*1e3:.2f} ms/call "
        f"({adds/1e6:.0f} M useful adds, {adds/dt/1e9:.1f} G adds/s)"
    )
    return dt


if __name__ == "__main__":
    ms = [1046 - 4 * i for i in range(21)]
    ps = list(range(240, 261))
    run(ms, ps)
