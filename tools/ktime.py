"""Time the compiled CycleKernel on the real TPU at production shapes."""
import sys
import time

import jax
import numpy as np

from riptide_tpu.ops.ffa_kernel import CycleKernel
from riptide_tpu.ops.snr import boxcar_coeffs


def run(ms, ps, widths=(1, 2, 3, 4, 6, 9, 13, 19, 28, 42), reps=10, D=1):
    widths = tuple(w for w in widths if w < min(ps))
    B = len(ms)
    nw = len(widths)
    h = np.zeros((B, nw), np.float32)
    b = np.zeros((B, nw), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    std = np.ones(B, np.float32)
    k = CycleKernel(ms, ps, widths, h, b, std)
    rng = np.random.default_rng(0)
    shape = (B, k.rows, k.P) if D == 1 else (D, B, k.rows, k.P)
    x = rng.standard_normal(shape).astype(np.float32)
    import jax.numpy as jnp

    t0 = time.perf_counter()
    xd = jax.device_put(x)
    ix = (0, 0, 0) if D == 1 else (0, 0, 0, 0)
    print(f"  device_put({x.nbytes/1e6:.0f} MB): "
          f"{time.perf_counter()-t0:.1f}s", flush=True)
    # Warm up + true sync (block_until_ready does not sync under the
    # axon tunnel; only a real device->host fetch does).
    t0 = time.perf_counter()
    float(np.asarray(k(xd)[ix]))
    print(f"  warmup (compile): {time.perf_counter()-t0:.1f}s", flush=True)

    def run(reps):
        t0 = time.perf_counter()
        vals = [k(xd)[ix] for _ in range(reps)]
        s = float(np.asarray(jnp.stack(vals)).sum())  # ONE fetch
        assert np.isfinite(s)
        dt = time.perf_counter() - t0
        print(f"  run({reps}): {dt:.3f}s", flush=True)
        return dt

    r1, r2 = 2, 2 + reps
    t1 = min(run(r1) for _ in range(2))
    t2 = min(run(r2) for _ in range(2))
    dt = (t2 - t1) / (r2 - r1)
    adds = D * sum(m * p * np.ceil(np.log2(max(m, 2))) for m, p in zip(ms, ps))
    print(
        f"bucket D={D} B={B} rows={k.rows} P={k.P}: {dt*1e3:.2f} ms/call, "
        f"{dt*1e3/(D*B):.3f} ms/program "
        f"({adds/1e6:.0f} M useful adds, {adds/dt/1e9:.1f} G adds/s)"
    )
    return dt


def main(argv):
    D = int(argv[1]) if len(argv) > 1 else 1
    reps = int(argv[2]) if len(argv) > 2 else 10
    ms = [1046 - 4 * i for i in range(21)]
    ps = list(range(240, 261))
    run(ms, ps, reps=reps, D=D)


if __name__ == "__main__":
    main(sys.argv)
