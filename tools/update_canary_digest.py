"""
Re-pin tools/integrity_canary.json for the running backend.

The result-integrity layer's Ring 3 golden canary
(riptide_tpu/survey/integrity.py) runs a tiny pinned-input search and
compares the collected-buffer digest against the per-platform pin in
this file — the "is the DEVICE wrong?" oracle consulted at strict-mode
startup and on every quarantine decision. Run this after a deliberate
kernel/layout change shifts the canary's bytes (the `make repin`
workflow, next to the kernel-digest and plan-contract pins). A
platform with no pin is reported as `unpinned` by the canary —
pass-with-note, never fatal — so pinning a new backend is additive.

Usage: JAX_PLATFORMS=cpu python tools/update_canary_digest.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PATH = os.path.join(os.path.dirname(__file__), "integrity_canary.json")


def main():
    from riptide_tpu.survey import integrity

    try:
        with open(PATH) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {"v": 1, "algo": "sha256", "platform_digests": {}}
    import jax

    platform = str(jax.default_backend())
    digest = integrity.compute_canary_digest()
    old = data["platform_digests"].get(platform)
    data["platform_digests"][platform] = digest
    with open(PATH, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    print(f"canary [{platform}]: {old} -> {digest}")


if __name__ == "__main__":
    main()
