#!/usr/bin/env python
"""
riplint: run every riptide_tpu static analyzer over the package.

The analyzers live in ``riptide_tpu/analysis/`` (loaded standalone by
file path — no jax, no package __init__, so this runs anywhere).
Output is GitHub-annotation format, one finding per line::

    riptide_tpu/search/engine.py:991:8: RIP001 `np.asarray` inside ...

Exit status 0 when the repo is clean against the checked-in baseline
(``tools/riplint_baseline.json``); 1 when there are new findings OR
stale baseline entries (an entry whose code is gone must be deleted —
a baseline only stays honest if it cannot accumulate dead weight).

Suppression, in reviewability order:

* fix the finding;
* ``# riplint: disable=RIPxxx`` on the flagged line (visible in the
  diff it suppresses);
* a baseline entry with a one-line ``why`` (for intentional,
  long-lived exceptions: documented sync points, build-serialisation
  locks). ``--update-baseline`` regenerates the file, keeping the
  justifications of surviving entries; new entries get a TODO you must
  edit before committing.

``--write-env-docs`` regenerates ``docs/env_flags.md`` from the
``utils/envflags.py`` registry (the RIP003 analyzer fails on drift).
"""
import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "tools", "riplint_baseline.json")


def load_analysis(repo=REPO):
    """The riptide_tpu.analysis package, loaded standalone so importing
    it never drags in jax (or riptide_tpu/__init__)."""
    name = "riptide_tpu_analysis_standalone"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(repo, "riptide_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[name]
        raise
    return mod


def run(repo=REPO, baseline_path=DEFAULT_BASELINE, analyzers=None,
        update_baseline=False, out=sys.stdout, err=sys.stderr):
    """Run the analyzers; returns the process exit code."""
    analysis = load_analysis(repo)
    analyzers = analyzers or analysis.ALL_ANALYZERS
    baseline = analysis.Baseline.load(baseline_path)
    contexts = analysis.collect_contexts(repo)
    new, baselined, stale = analysis.run_analyzers(
        repo, analyzers, baseline=baseline, contexts=contexts
    )

    if update_baseline:
        by_rel = {c.relpath: c for c in contexts}
        kept = [e for e in baseline.entries if e not in stale]
        seen = {(e["rule"], e["path"], e["line_text"].strip())
                for e in kept}
        added = []
        for f in new:
            ctx = by_rel.get(f.path)
            if ctx is not None:
                entry = analysis.Baseline.entry_for(f, ctx)
            else:
                # Finding outside the package (e.g. docs drift): emit
                # the path-only (empty line_text) entry form that
                # Baseline.matches_pathonly absorbs, instead of
                # silently dropping it and leaving the next plain run
                # red.
                entry = {"rule": f.rule, "path": f.path,
                         "line_text": "", "why": "TODO: justify"}
            key = (entry["rule"], entry["path"],
                   entry["line_text"].strip())
            if key in seen:
                continue
            seen.add(key)
            added.append(entry)
        analysis.Baseline(kept + added, path=baseline_path).dump()
        print(
            f"baseline updated: {len(kept)} kept, {len(added)} added "
            f"(edit their TODO justifications), {len(stale)} stale "
            "dropped", file=err,
        )
        return 0

    for f in new:
        print(f.gh(), file=out)
    for e in stale:
        print(
            f"{e['path']}:1:0: {e['rule']} STALE baseline entry "
            f"(line_text={e['line_text']!r}) — the code it justified is "
            "gone; delete the entry or run --update-baseline",
            file=out,
        )
    n_rules = len({a.rule for a in
                   (x() if isinstance(x, type) else x for x in analyzers)})
    if new or stale:
        print(
            f"riplint: {len(new)} new finding(s), {len(stale)} stale "
            f"baseline entr(y/ies) ({len(baselined)} baselined, "
            f"{n_rules} analyzers over {len(contexts)} modules)",
            file=err,
        )
        return 1
    print(
        f"riplint OK: {n_rules} analyzers over {len(contexts)} modules, "
        f"0 new findings ({len(baselined)} baselined)", file=err,
    )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="riplint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default tools/riplint_baseline"
                         ".json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to absorb current "
                         "findings (justifications of surviving entries "
                         "are kept; new entries get a TODO)")
    ap.add_argument("--write-env-docs", action="store_true",
                    help="regenerate docs/env_flags.md from the "
                         "utils/envflags.py registry and exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the analyzer set (rule id, name, "
                         "description) and exit")
    args = ap.parse_args(argv)

    analysis = load_analysis()
    if args.list_rules:
        for cls in analysis.ALL_ANALYZERS:
            print(f"{cls.rule}  {cls.name}: {cls.description}")
        return 0
    if args.write_env_docs:
        registry = analysis.env_flags.load_registry(REPO)
        path = os.path.join(REPO, "docs", "env_flags.md")
        with open(path, "w") as fobj:
            fobj.write(registry.render_markdown())
        print(f"wrote {os.path.relpath(path, REPO)}", file=sys.stderr)
        return 0
    return run(baseline_path=args.baseline,
               update_baseline=args.update_baseline)


if __name__ == "__main__":
    sys.exit(main())
