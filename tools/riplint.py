#!/usr/bin/env python
"""
riplint: run every riptide_tpu static analyzer over the package.

The analyzers live in ``riptide_tpu/analysis/`` (loaded standalone by
file path — no jax, no package __init__, so this runs anywhere).
Output is GitHub-annotation format, one finding per line::

    riptide_tpu/search/engine.py:991:8: RIP001 `np.asarray` inside ...

Exit status 0 when the repo is clean against the checked-in baseline
(``tools/riplint_baseline.json``); 1 when there are new findings OR
stale baseline entries (an entry whose code is gone must be deleted —
a baseline only stays honest if it cannot accumulate dead weight).

Suppression, in reviewability order:

* fix the finding;
* ``# riplint: disable=RIPxxx`` on the flagged line (visible in the
  diff it suppresses);
* a baseline entry with a one-line ``why`` (for intentional,
  long-lived exceptions: documented sync points, build-serialisation
  locks). ``--update-baseline`` regenerates the file, keeping the
  justifications of surviving entries; new entries get a TODO you must
  edit before committing. ``--prune-baseline`` is the inverse
  maintenance pass: it rewrites the baseline keeping ONLY entries that
  matched a finding this run, so dead justifications (code deleted
  together with its finding, entries made redundant by a refactor)
  cannot accrete — a pruned baseline followed by a plain run is clean
  by construction.

``--write-env-docs`` regenerates ``docs/env_flags.md`` from the
``utils/envflags.py`` registry (the RIP003 analyzer fails on drift).

``--format sarif`` emits one SARIF 2.1.0 run (rule metadata included)
instead of the GitHub one-liner format, for CI annotation uploads;
``--format text`` stays the default and the exit-code contract is
identical.

Runs are cached: ``.riplint_cache.json`` (repo root, gitignored)
records the (mtime, size) of every file the analyzers can observe plus
a digest of the analyzer sources themselves, and an unchanged tree
replays the recorded result without parsing anything — ``make check``
on a clean tree is sub-second. The whole-program analyzers make
per-file reuse unsound (one module's edit moves another module's call
graph), so the cache is all-or-nothing by design. ``--no-cache``
forces a full run (CI).
"""
import argparse
import hashlib
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "tools", "riplint_baseline.json")
CACHE_REL = ".riplint_cache.json"
CACHE_VERSION = 1

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def load_analysis(repo=REPO):
    """The riptide_tpu.analysis package, loaded standalone so importing
    it never drags in jax (or riptide_tpu/__init__)."""
    name = "riptide_tpu_analysis_standalone"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(repo, "riptide_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[name]
        raise
    return mod


# -- result cache ------------------------------------------------------------

def _tracked_files(repo):
    """Repo-relative paths of every file whose content can change an
    analyzer's output: the package sources, the out-of-package surfaces
    the analyzers read (tools/, tests/, bench.py, Makefile — RIP003's
    stale-flag scan and RIP010's tools-side readers), the generated
    env-flag docs (RIP003 drift) and the baseline itself. The tools/
    walk also covers ``tools/plan_contracts.json`` (the semantic
    pass's pinned contracts) and the package walk the rprove analysis
    sources (``analysis/jaxpr_contract.py``), so a contract edit or an
    extractor edit invalidates cached `make check` runs like any other
    tracked change. The same two walks cover the ripsched surface:
    ``riptide_tpu/analysis/sched.py`` (also hashed into
    _analyzer_digest) and the pinned ``tools/ripsched_invariants.json``
    invariant specs — editing a model or re-pinning the spec
    invalidates cached results."""
    out = []
    for root in ("riptide_tpu", "tools", "tests"):
        top = os.path.join(repo, root)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fname in sorted(filenames):
                # .mk/Makefile included to match env_flags's stale-
                # flag usage scan over these same directories.
                if fname.endswith((".py", ".json", ".mk")) \
                        or fname == "Makefile":
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fname), repo))
    for rel in ("bench.py", "Makefile", os.path.join("docs",
                                                     "env_flags.md")):
        if os.path.exists(os.path.join(repo, rel)):
            out.append(rel)
    return [p.replace(os.sep, "/") for p in out
            if p != CACHE_REL]


def _file_state(repo):
    state = {}
    for rel in _tracked_files(repo):
        try:
            st = os.stat(os.path.join(repo, rel))
        except OSError:
            continue
        state[rel] = [st.st_mtime_ns, st.st_size]
    return state


def _analyzer_digest(repo):
    """Digest over the analyzer sources and this runner: any edit to
    the rules invalidates every cached result."""
    h = hashlib.sha1()
    adir = os.path.join(repo, "riptide_tpu", "analysis")
    for name in sorted(os.listdir(adir)):
        if name.endswith(".py"):
            h.update(name.encode())
            with open(os.path.join(adir, name), "rb") as fobj:
                h.update(fobj.read())
    with open(os.path.abspath(__file__), "rb") as fobj:
        h.update(fobj.read())
    return h.hexdigest()


def _baseline_state(baseline_path):
    """(mtime_ns, size) of the baseline, stat'd explicitly: a custom
    --baseline may live outside the tracked roots, and its edits must
    invalidate the cache all the same."""
    try:
        st = os.stat(baseline_path)
    except OSError:
        return None
    return [st.st_mtime_ns, st.st_size]


def _cache_key(repo, baseline_path):
    """The invalidation key, computed ONCE per run and shared by the
    load comparison and the post-run save (recomputing after the run
    could pair fresh mtimes with a result derived from older
    content)."""
    return {
        "version": CACHE_VERSION,
        "analyzer_digest": _analyzer_digest(repo),
        "baseline_path": os.path.relpath(baseline_path, repo),
        "baseline_state": _baseline_state(baseline_path),
        "files": _file_state(repo),
    }


def _load_cached_result(repo, key):
    path = os.path.join(repo, CACHE_REL)
    try:
        with open(path) as fobj:
            doc = json.load(fobj)
    except (OSError, ValueError):
        return None
    if any(doc.get(k) != v for k, v in key.items()):
        return None
    return doc.get("result")


def _save_cached_result(repo, key, result):
    path = os.path.join(repo, CACHE_REL)
    doc = dict(key, result=result)
    tmp = f"{path}.tmp"
    try:
        with open(tmp, "w") as fobj:
            json.dump(doc, fobj, indent=1)
            fobj.write("\n")
        os.replace(tmp, path)
    except OSError:
        pass  # best-effort: a read-only tree just runs uncached


# -- output formats ----------------------------------------------------------

def _sarif_doc(result, analyzers, tool="riplint"):
    """One SARIF 2.1.0 run: the analyzer set as rule metadata, each new
    finding (and stale baseline entry) as a result. ``tool`` names the
    driver — tools/rprove.py (semantic pass) and tools/ripsched.py
    (schedule exploration) reuse this writer, so all three tools
    publish one result format that `make analyze` merges."""
    rules = [
        {
            "id": a.rule,
            "name": a.name,
            "shortDescription": {"text": a.description or a.name},
        }
        for a in analyzers
    ]
    results = []
    for f in result["new"]:
        results.append({
            "ruleId": f["rule"],
            "level": "error",
            "message": {"text": f["message"]},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f["path"]},
                    "region": {"startLine": max(1, f["line"]),
                               "startColumn": f["col"] + 1},
                },
            }],
        })
    for e in result["stale"]:
        results.append({
            "ruleId": e["rule"],
            "level": "error",
            "message": {"text": (
                f"STALE baseline entry (line_text={e['line_text']!r}) "
                "— the code it justified is gone; delete the entry or "
                "run --update-baseline")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": e["path"]},
                    "region": {"startLine": 1, "startColumn": 1},
                },
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            # informationUri omitted: the property requires an
            # absolute URI and this tool has no canonical public URL
            # (docs/static_analysis.md is the in-repo reference).
            "tool": {"driver": {
                "name": tool,
                "rules": rules,
            }},
            "results": results,
        }],
    }


def _emit(result, analyzers, fmt, out, err, cached=False):
    """Render one (possibly replayed) result; returns the exit code."""
    n_new, n_stale = len(result["new"]), len(result["stale"])
    if fmt == "sarif":
        json.dump(_sarif_doc(result, analyzers), out, indent=2)
        out.write("\n")
    else:
        for f in result["new"]:
            print(f"{f['path']}:{f['line']}:{f['col']}: {f['rule']} "
                  f"{f['message']}", file=out)
        for e in result["stale"]:
            print(
                f"{e['path']}:1:0: {e['rule']} STALE baseline entry "
                f"(line_text={e['line_text']!r}) — the code it justified "
                "is gone; delete the entry or run --update-baseline",
                file=out,
            )
    tag = " [cached]" if cached else ""
    if n_new or n_stale:
        print(
            f"riplint: {n_new} new finding(s), {n_stale} stale "
            f"baseline entr(y/ies) ({result['baselined']} baselined, "
            f"{result['n_rules']} analyzers over {result['n_modules']} "
            f"modules){tag}",
            file=err,
        )
        return 1
    print(
        f"riplint OK: {result['n_rules']} analyzers over "
        f"{result['n_modules']} modules, 0 new findings "
        f"({result['baselined']} baselined){tag}", file=err,
    )
    return 0


def run(repo=REPO, baseline_path=DEFAULT_BASELINE, analyzers=None,
        update_baseline=False, prune_baseline=False, out=sys.stdout,
        err=sys.stderr, fmt="text", use_cache=True):
    """Run the analyzers; returns the process exit code."""
    analysis = load_analysis(repo)
    # Only runs of the full default analyzer set are cacheable — a
    # caller-injected subset must never poison (or be served) the
    # default result. Baseline-rewriting runs need the real match
    # bookkeeping, so they are never served from (or saved to) cache.
    cacheable = (analyzers is None and not update_baseline
                 and not prune_baseline and use_cache)
    analyzers = analyzers or analysis.ALL_ANALYZERS
    instances = [a() if isinstance(a, type) else a for a in analyzers]

    cache_key = None
    if cacheable:
        cache_key = _cache_key(repo, baseline_path)
        result = _load_cached_result(repo, cache_key)
        if result is not None:
            return _emit(result, instances, fmt, out, err, cached=True)

    baseline = analysis.Baseline.load(baseline_path)
    contexts = analysis.collect_contexts(repo)
    new, baselined, stale = analysis.run_analyzers(
        repo, instances, baseline=baseline, contexts=contexts
    )

    if update_baseline:
        by_rel = {c.relpath: c for c in contexts}
        kept = [e for e in baseline.entries if e not in stale]
        seen = {(e["rule"], e["path"], e["line_text"].strip())
                for e in kept}
        added = []
        for f in new:
            ctx = by_rel.get(f.path)
            if ctx is not None:
                entry = analysis.Baseline.entry_for(f, ctx)
            else:
                # Finding outside the package (e.g. docs drift): emit
                # the path-only (empty line_text) entry form that
                # Baseline.matches_pathonly absorbs, instead of
                # silently dropping it and leaving the next plain run
                # red.
                entry = {"rule": f.rule, "path": f.path,
                         "line_text": "", "why": "TODO: justify"}
            key = (entry["rule"], entry["path"],
                   entry["line_text"].strip())
            if key in seen:
                continue
            seen.add(key)
            added.append(entry)
        analysis.Baseline(kept + added, path=baseline_path).dump()
        print(
            f"baseline updated: {len(kept)} kept, {len(added)} added "
            f"(edit their TODO justifications), {len(stale)} stale "
            "dropped", file=err,
        )
        return 0

    if prune_baseline:
        # Keep exactly the entries that absorbed a finding this run;
        # everything else is dead weight (stale entries included — a
        # prune IS the "delete the entry" remedy the stale failure
        # asks for). New findings still fail the run below.
        kept = [e for e in baseline.entries if e not in stale]
        analysis.Baseline(kept, path=baseline_path).dump()
        print(f"baseline pruned: {len(kept)} entr(y/ies) kept, "
              f"{len(stale)} unmatched dropped", file=err)
        stale = []

    result = {
        "new": [{"path": f.path, "line": f.line, "col": f.col,
                 "rule": f.rule, "message": f.message} for f in new],
        "stale": list(stale),
        "baselined": len(baselined),
        "n_rules": len({i.rule for i in instances}),
        "n_modules": len(contexts),
    }
    if cacheable:
        # --no-cache runs never write either (the documented CI
        # contract): cacheable already folds use_cache in.
        _save_cached_result(repo, cache_key, result)
    return _emit(result, instances, fmt, out, err)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="riplint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default tools/riplint_baseline"
                         ".json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to absorb current "
                         "findings (justifications of surviving entries "
                         "are kept; new entries get a TODO)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline keeping only entries "
                         "that matched a finding this run (drops dead "
                         "justifications; new findings still fail)")
    ap.add_argument("--format", choices=("text", "sarif"),
                    default="text", dest="fmt",
                    help="output format: GitHub-annotation text "
                         "(default) or one SARIF 2.1.0 run")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write .riplint_cache.json "
                         "(CI / make check-full)")
    ap.add_argument("--write-env-docs", action="store_true",
                    help="regenerate docs/env_flags.md from the "
                         "utils/envflags.py registry and exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the analyzer set (rule id, name, "
                         "description) and exit")
    args = ap.parse_args(argv)

    analysis = load_analysis()
    if args.list_rules:
        for cls in analysis.ALL_ANALYZERS:
            print(f"{cls.rule}  {cls.name}: {cls.description}")
        return 0
    if args.write_env_docs:
        registry = analysis.env_flags.load_registry(REPO)
        path = os.path.join(REPO, "docs", "env_flags.md")
        with open(path, "w") as fobj:
            fobj.write(registry.render_markdown())
        print(f"wrote {os.path.relpath(path, REPO)}", file=sys.stderr)
        return 0
    return run(baseline_path=args.baseline,
               update_baseline=args.update_baseline,
               prune_baseline=args.prune_baseline,
               fmt=args.fmt, use_cache=not args.no_cache)


if __name__ == "__main__":
    sys.exit(main())
