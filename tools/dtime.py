"""Per-bucket device timing of the headline plan's cycle kernels.

Times each distinct compiled kernel bucket at the production D (data
already resident in HBM, repeated calls, one fetch at the end), giving
the device-only decomposition of a survey chunk: sum of per-bucket
times x stages-per-bucket ~= the chunk's pure kernel time, excluding
wire/pack/assemble/peaks. Usage: python tools/dtime.py [D] [reps]
"""
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(D=32, reps=6):
    from riptide_tpu.ffautils import generate_width_trials
    from riptide_tpu.search import periodogram_plan
    from riptide_tpu.search.engine import _kernel_eligible, warm_stage_kernels

    widths = tuple(int(w) for w in generate_width_trials(240))
    plan = periodogram_plan(1 << 23, 64e-6, widths, 0.5, 3.0, 240, 260)
    t0 = time.perf_counter()
    warm_stage_kernels(plan, D)
    print(f"warm: {time.perf_counter() - t0:.1f}s", flush=True)

    rng = np.random.default_rng(0)
    seen = {}
    stages_per = {}
    for st in plan.stages:
        if not _kernel_eligible(st, plan):
            print(f"stage n={st.n}: NOT kernel-eligible", flush=True)
            continue
        kern = st.cycle_kernel()
        key = (kern.L, kern.rows, kern.P, kern.B)
        stages_per[key] = stages_per.get(key, 0) + 1
        seen.setdefault(key, kern)

    total = 0.0
    for key, kern in seen.items():
        L, rows, P, B = key
        x = jnp.asarray(rng.standard_normal(
            (D, B, rows, P)).astype(np.float32))
        # warm + sync (a real fetch; block_until_ready does not sync
        # through the tunnel)
        float(np.asarray(kern(x)[0, 0, 0, 0]))

        def run(n):
            t0 = time.perf_counter()
            outs = [kern(x)[0, 0, 0, 0] for _ in range(n)]
            float(np.asarray(jnp.stack(outs).sum()))
            return time.perf_counter() - t0

        r1, r2 = 2, 2 + reps
        dt = (min(run(r2) for _ in range(2)) - min(run(r1) for _ in range(2))) / (r2 - r1)
        total += dt * stages_per[key]
        print(f"bucket L={L} rows={rows} P={P} B={B} x{stages_per[key]} "
              f"stages: {dt * 1e3:.1f} ms/call -> "
              f"{dt * stages_per[key]:.3f} s for its stages", flush=True)
    print(f"device kernel total per {D}-trial chunk: {total:.2f} s "
          f"({D / total:.1f} trials/s kernel-only bound)", flush=True)


if __name__ == "__main__":
    D = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 6
    main(D, reps)
