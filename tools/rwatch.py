#!/usr/bin/env python
"""
rwatch: live alert watcher over a survey running in ANOTHER process.

Follows a journal directory the way ``rtop`` does — incremental
journal reads via ``report.JournalFollower``, heartbeat-sidecar tails,
fleet ``fleet_<p>.json`` snapshots — and evaluates the alert-rule
engine (``riptide_tpu/obs/alerts.py``) over the merged live state on
every poll, printing fire/resolve events as they happen. This is the
*out-of-process* half of the detect loop: the watched run needs no
flag, no endpoint and no code change (``RIPTIDE_ALERTS`` adds the
in-process engine, which additionally journals its events; rwatch
works either way, and both evaluate the SAME
``report.watch_snapshot`` signal vector, so they fire on identical
evidence).

Usage::

    python tools/rwatch.py JDIR [--interval 1.0] [--rules SPEC]
        [--timeout S] [--once] [--json PATH] [--quiet]

By default rwatch follows the run until its journal says every chunk
is done or parked, then exits — **nonzero while any alert is still
firing** — so CI (or a supervising daemon) can gate on it:

* ``0`` — run complete, no unresolved alerts;
* ``1`` — run complete (or ``--once``) with unresolved alert(s);
* ``2`` — usage error (no journal directory);
* ``3`` — ``--timeout`` expired before the run completed;
* ``130`` — interrupted (Ctrl-C / SIGINT) before a verdict: never to
  be read as clean by a supervising gate.

``--rules`` takes the same ``name[:limit[:for_count]]`` spec as
``RIPTIDE_ALERT_RULES``; ``--once`` evaluates a single snapshot
(scripts/tests); ``--json`` writes the full event log + final
snapshot + fleet view for machine consumption. Loads the jax-free
reader and engine standalone, so it runs anywhere the journal files
are visible.
"""
import argparse
import importlib.util
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from rreport import load_report_module  # noqa: E402 (path setup first)


def load_alerts_module():
    """riptide_tpu.obs.alerts, loaded standalone by file path (the
    rreport pattern) so watching a run never needs jax."""
    name = "riptide_tpu_obs_alerts_standalone"
    if name in sys.modules:
        return sys.modules[name]
    path = os.path.normpath(
        os.path.join(HERE, "..", "riptide_tpu", "obs", "alerts.py"))
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[name]
        raise
    return mod


def _fmt_event(event):
    mark = "FIRED   " if event.get("event") == "fired" else "resolved"
    line = (f"{event.get('utc', '?')}  {mark} {event.get('rule', '?')}")
    if event.get("value") is not None:
        line += f"  (value {event['value']}, limit {event.get('limit')})"
    return line


def watch(rep, al, journal_dir, rules=None, interval=1.0, timeout=None,
          once=False, out=sys.stdout, quiet=False, clock=time.time,
          sleep=time.sleep):
    """The follow loop (importable for tests): returns
    ``(exit_code, result dict)``. ``result`` holds the event log, the
    final snapshot, the unresolved set and the merged fleet view."""
    engine = al.AlertEngine(rules if rules is not None
                            else al.default_rules())
    follower = rep.JournalFollower(journal_dir)
    deadline = None if timeout is None else clock() + float(timeout)
    timed_out = False
    snap = {}
    while True:
        state = follower.poll()
        beats = rep.read_heartbeats(journal_dir)
        snap = rep.watch_snapshot(state, heartbeats=beats, now=clock())
        for event in engine.evaluate(snap):
            if not quiet:
                out.write(_fmt_event(event) + "\n")
                out.flush()
        if once or snap.get("complete"):
            break
        if deadline is not None and clock() >= deadline:
            timed_out = True
            break
        sleep(float(interval))
    unresolved = engine.unresolved()
    result = {
        "directory": os.path.abspath(journal_dir),
        "events": engine.events(),
        "unresolved": unresolved,
        "snapshot": snap,
        "complete": bool(snap.get("complete")),
        "timed_out": timed_out,
        "fleet": rep.merge_fleet(rep.read_fleet(journal_dir)),
    }
    if timed_out and not snap.get("complete"):
        return 3, result
    return (1 if unresolved else 0), result


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="rwatch",
        description="Alert watcher over a journaled survey running in "
                    "another process (tail-reads the journal "
                    "directory; exits nonzero on unresolved alerts).",
    )
    ap.add_argument("journal", help="journal directory to watch")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="poll period in seconds (default 1)")
    ap.add_argument("--rules", default=None,
                    help="rule spec `name[:limit[:for_count]],...` "
                         "(default: the full builtin catalog)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="give up (exit 3) if the run has not "
                         "completed after this many seconds")
    ap.add_argument("--once", action="store_true",
                    help="evaluate a single snapshot and exit")
    ap.add_argument("--json", default=None,
                    help="write the event log + final snapshot as "
                         "JSON to this path ('-' for stdout)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the live event lines")
    args = ap.parse_args(argv)

    rep = load_report_module()
    al = load_alerts_module()
    if not os.path.isdir(args.journal):
        print(f"rwatch: {args.journal!r} is not a directory",
              file=sys.stderr)
        return 2
    try:
        rules = al.rules_from_spec(args.rules)
    except ValueError as err:
        print(f"rwatch: {err}", file=sys.stderr)
        return 2
    try:
        code, result = watch(
            rep, al, args.journal, rules=rules, interval=args.interval,
            timeout=args.timeout, once=args.once, quiet=args.quiet)
    except KeyboardInterrupt:
        # An interrupted watch never reached its verdict; a CI/daemon
        # gate must not read the interruption as "clean" (130 = the
        # conventional SIGINT exit).
        print("rwatch: interrupted before the run completed",
              file=sys.stderr)
        return 130
    if not args.quiet:
        status = ("timed out before completion" if result["timed_out"]
                  else "run complete" if result["complete"]
                  else "single snapshot")
        tail = (f"; UNRESOLVED: {', '.join(result['unresolved'])}"
                if result["unresolved"] else "; all alerts resolved"
                if result["events"] else "; no alerts fired")
        print(f"rwatch: {status} — {len(result['events'])} event(s)"
              + tail)
    if args.json:
        payload = json.dumps(result, indent=2, default=str)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as fobj:
                fobj.write(payload + "\n")
    return code


if __name__ == "__main__":
    sys.exit(main())
