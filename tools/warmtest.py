"""Warm the headline-bench kernel buckets via the executable cache.
Usage: python tools/warmtest.py [D] [seq]"""
import sys
import time

from riptide_tpu.ffautils import generate_width_trials
from riptide_tpu.search import periodogram_plan
from riptide_tpu.search.engine import warm_stage_kernels

D = int(sys.argv[1]) if len(sys.argv) > 1 else 32
par = "seq" not in sys.argv[1:]
widths = tuple(int(w) for w in generate_width_trials(240))
plan = periodogram_plan(1 << 23, 64e-6, widths, 0.5, 3.0, 240, 260)
t0 = time.perf_counter()
n = warm_stage_kernels(plan, D, parallel=par)
print(f"warmed {n} kernel builds (parallel={par}) in "
      f"{time.perf_counter()-t0:.1f}s", flush=True)
