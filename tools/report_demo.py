#!/usr/bin/env python
"""
report-demo: the end-to-end acceptance path of the signal-CONSUMPTION
layer (extends tools/trace_demo.py, which verifies the emission side).

Runs a tiny CPU survey through the checkpointed scheduler with the
span tracer, perf ledger and live HTTP endpoint all on, then verifies:

* ``/status`` and ``/healthz`` answered live JSON DURING the run (a
  poller thread scrapes them while chunks dispatch); after the run
  ``/healthz`` stays 200 however stale the beats (running=false gates
  the probe), but flips to 503 for a wedged RUNNING survey (simulated:
  real scheduler status with running forced on, against a tightened
  ``RIPTIDE_STATUS_STALE_S``);
* unknown endpoint paths get a 404 naming the valid endpoints;
* ``rreport`` over the journal exits 0 with a phase-attribution table
  whose serial phases sum to the chunk wall-clock within 5%;
* the scheduler appended one ``survey`` row to the ledger
  (``RIPTIDE_LEDGER``), and ``rreport --compare`` exits 0 against that
  own row but NONZERO against a synthetic baseline in which history
  was 4x faster (i.e. the current run's device time is inflated 4x
  relative to baseline — the regression CI must catch);
* ``rtop --once`` renders a progress frame from the same files.

Output directory: /tmp/riptide_report_demo (or argv[1]). ``make
report-demo`` runs this; it doubles as the CI smoke test of the whole
obs consumption path.
"""
import json
import os
import shutil
import sys
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TOBS, TSAMP, PERIOD = 16.0, 1e-3, 0.5

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def _get(url, timeout=5.0):
    """(HTTP status, body text) — 4xx/5xx included, not raised."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def main(outdir="/tmp/riptide_report_demo"):
    from synth import generate_data_presto

    from riptide_tpu.obs import prom, trace
    from riptide_tpu.pipeline.batcher import BatchSearcher
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.metrics import get_metrics
    from riptide_tpu.survey.scheduler import SurveyScheduler

    shutil.rmtree(outdir, ignore_errors=True)
    os.makedirs(outdir)
    ledger_path = os.path.join(outdir, "ledger.jsonl")
    os.environ["RIPTIDE_LEDGER"] = ledger_path
    files = [
        generate_data_presto(outdir, f"demo_DM{dm:.2f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm,
                             amplitude=25.0)
        for dm in (0.0, 5.0)
    ]

    trace.enable()
    get_metrics().reset()
    jdir = os.path.join(outdir, "j")

    # Live endpoint on an ephemeral port; the scheduler registers the
    # /status provider itself (RIPTIDE_STATUS defaults on).
    server = prom.serve(0)
    base = f"http://127.0.0.1:{server.port}"
    seen = {"status": [], "healthz": []}
    stop = threading.Event()

    def poller():
        while not stop.wait(0.05):
            code, body = _get(f"{base}/status")
            if code == 200:
                doc = json.loads(body)
                if doc.get("active"):
                    seen["status"].append(doc)
            code, _ = _get(f"{base}/healthz")
            seen["healthz"].append(code)

    watcher = threading.Thread(target=poller, daemon=True)
    watcher.start()

    searcher = BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                             SEARCH_CONF, fmt="presto", io_threads=1)
    scheduler = SurveyScheduler(searcher, [[f] for f in files],
                                journal=SurveyJournal(jdir))
    peaks = scheduler.run()
    stop.set()
    watcher.join(timeout=5.0)

    # -- live surface -------------------------------------------------
    assert seen["status"], "poller never saw a live /status during the run"
    live = seen["status"][-1]
    assert live["chunks_total"] == 2, live
    assert "heartbeat_age_s" in live, live
    assert 200 in seen["healthz"], "healthz never healthy during the run"
    code, body = _get(f"{base}/status")
    final = json.loads(body)
    assert code == 200 and final["chunks_done"] == 2, (code, final)
    # A FINISHED run's aging heartbeats must not page: healthz stays
    # 200 however stale the beats, because status says running=false.
    os.environ["RIPTIDE_STATUS_STALE_S"] = "0.2"
    time.sleep(0.45)
    code, _ = _get(f"{base}/healthz")
    assert code == 200, f"healthz paged over a COMPLETED run: {code}"

    # Stale heartbeats on a RUNNING survey flip the probe to 503:
    # simulate a wedged in-flight run (real scheduler status — live
    # heartbeat ages from the journal — with running forced back on).
    sched = scheduler
    prom.set_status_provider(lambda: dict(sched.status(), running=True))
    code, body = _get(f"{base}/healthz")
    assert code == 503, f"healthz did not flip on stale heartbeats: {code}"
    assert "stale heartbeat" in body, body
    prom.set_status_provider(sched.status)
    del os.environ["RIPTIDE_STATUS_STALE_S"]

    # Unknown paths are a 404 naming the valid endpoints.
    code, body = _get(f"{base}/nope")
    assert code == 404 and "/healthz" in body and "/metrics" in body, \
        (code, body)

    # -- rreport over the journal -------------------------------------
    import rreport
    import rtop

    report_json = os.path.join(outdir, "report.json")
    rc = rreport.main([jdir, "--json", report_json])
    assert rc == 0, f"rreport exited {rc} on a clean journal"
    with open(report_json) as fobj:
        report = json.load(fobj)
    assert not report["phase_sum_violations"], report["phase_sum_violations"]
    # Re-verify the 5% phase-sum bound from the raw journal, not just
    # rreport's own bookkeeping (journal lines carry a per-record CRC32
    # suffix since PR 11; the report module's lenient parser strips and
    # verifies it).
    rep_mod = rreport.load_report_module()
    with open(os.path.join(jdir, "journal.jsonl"), "rb") as fobj:
        records = [rep_mod.parse_record_line(l)
                   for l in fobj.read().splitlines() if l.strip()]
    chunks = [r for r in records if r and r.get("kind") == "chunk"]
    assert len(chunks) == 2
    for rec in chunks:
        t = rec["timings"]
        serial = t["wire_s"] + t["queue_s"] + t["collect_s"] + t["host_s"]
        assert abs(serial - t["chunk_s"]) <= 0.05 * max(t["chunk_s"], 1e-9)

    # -- ledger + regression sentinel ---------------------------------
    with open(ledger_path) as fobj:
        rows = [json.loads(l) for l in fobj if l.strip()]
    assert len(rows) == 1 and rows[0]["kind"] == "survey", rows
    row = rows[0]
    assert row["nchunks"] == 2 and "device_s" in row \
        and "envflags_fingerprint" in row and "platform" in row, row

    # The run's own just-appended row is dropped from the baseline
    # (comparing a run against itself would always say "ok"): with no
    # other history the verdict is no-baseline, exit 0.
    rc = rreport.main([jdir, "--compare", ledger_path, "--quiet"])
    assert rc == 0, f"compare vs the run's own ledger row exited {rc}"

    # Synthetic baseline: history (a previous round's survey) 4x
    # faster, so the current run's device time is inflated 4x relative
    # to it — a regression the sentinel must flag with a nonzero exit.
    fast = dict(row, device_s=row["device_s"] / 4.0,
                survey_id="previous-round")
    fast_ledger = os.path.join(outdir, "ledger_fast_baseline.jsonl")
    with open(fast_ledger, "w") as fobj:
        fobj.write(json.dumps(fast) + "\n")
    rc = rreport.main([jdir, "--compare", fast_ledger, "--quiet"])
    assert rc == 1, f"compare vs a 4x-faster baseline exited {rc}, not 1"

    # -- rtop over the same files -------------------------------------
    rep = rreport.load_report_module()
    frame = rtop.render_frame(rep, jdir)
    assert "chunks 2/2" in frame, frame

    server.close()
    print(f"\nreport demo OK: {len(peaks)} peaks from {len(chunks)} chunks")
    print(f"  journal   ->  {jdir}")
    print(f"  ledger    ->  {ledger_path} (1 survey row)")
    print(f"  report    ->  {report_json}")
    print("  live /status + /healthz verified during the run; healthz "
          "flipped to 503 on a wedged run's stale heartbeats\n"
          "  (and stayed 200 for the completed run);")
    print("  rreport --compare: 0 vs own row (excluded from its own "
          "baseline), 1 vs 4x-faster prior round")
    sys.stdout.write("\n" + frame)


if __name__ == "__main__":
    main(*sys.argv[1:2])
