"""Probe: do base-3 (rows = 3*2^k) slot-phase patterns compile on Mosaic?

The 1536-row container's slot phase reshapes (R, P) -> (G, 2, S_c, P)
with S_c = 3*2^(l-3) — NOT a multiple of the 8-row sublane tile for the
first two slot levels (S_c = 6, 12). This script compiles and runs one
pallas kernel per slot level at R = 1536, P = 384, checking output
against the identical numpy sequence and timing REPS in-kernel passes.

Run on the real TPU: python tools/probe1536.py
"""
import functools
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from riptide_tpu.utils.compat import pallas_compiler_params

R, P = 1536, 384
L, NL = 11, 3


NITER = 32


def _one_pass(x, w, S_d):
    G = R // S_d
    S_c = S_d // 2
    v = x.reshape(G, 2, S_c, P)
    reph = jnp.repeat(v[:, 0], 2, axis=1)          # (G, S_d, P)
    w3 = w.reshape(G, S_d, P)
    da = (w3 >> 22) & 3
    head = reph
    for dv in (0, 1, 3):
        delta = dv - 2
        cand = pltpu.roll(reph, (-delta) % S_d, axis=1)
        head = jnp.where(da == dv, cand, head)
    rept = jnp.repeat(v[:, 1], 2, axis=1)
    return (head + rept).reshape(R, P)


def slot_level_kernel(x_ref, w_ref, o_ref, *, S_d):
    """One slot level's head half: interleave + delta select (the
    reshape pattern under test), plus the add. NITER in-kernel passes
    amortize the tunnel dispatch cost."""
    w = w_ref[:]

    def step(_, x):
        return _one_pass(x, w, S_d)

    o_ref[:] = jax.lax.fori_loop(0, NITER, step, x_ref[:])


def numpy_ref(x, w, S_d):
    G = R // S_d
    S_c = S_d // 2
    v = x.reshape(G, 2, S_c, P)
    reph = np.repeat(v[:, 0], 2, axis=1)
    w3 = w.reshape(G, S_d, P)
    da = (w3 >> 22) & 3
    head = reph.copy()
    for dv in (0, 1, 3):
        delta = dv - 2
        cand = np.roll(reph, -delta, axis=1)
        head = np.where(da == dv, cand, head)
    rept = np.repeat(v[:, 1], 2, axis=1)
    return (head + rept).reshape(R, P)


def main():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((R, P)).astype(np.float32)
    w = rng.integers(0, 4, (R, P), dtype=np.int32) << 22
    xd, wd = jnp.asarray(x), jnp.asarray(w)
    for l in range(NL + 1, L + 1):
        S_d = (R >> (L - l))
        kern = pl.pallas_call(
            functools.partial(slot_level_kernel, S_d=S_d),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((R, P), jnp.float32),
            compiler_params=pallas_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024),
        )
        t0 = time.perf_counter()
        try:
            got = np.asarray(jax.jit(kern)(xd, wd))
        except Exception as err:
            print(f"l={l} S_d={S_d}: COMPILE/RUN FAIL: "
                  f"{type(err).__name__}: {str(err)[:200]}", flush=True)
            continue
        tc = time.perf_counter() - t0
        want = x
        for _ in range(NITER):
            want = numpy_ref(want, w, S_d)
        ok = np.array_equal(got, want)
        # steady-state: 4 dispatches of NITER in-kernel passes each
        t0 = time.perf_counter()
        for _ in range(4):
            r = kern(xd, wd)
        _ = np.asarray(r[0, 0])
        dt = (time.perf_counter() - t0) / (4 * NITER)
        print(f"l={l} S_d={S_d:5d} S_c={S_d//2:4d}: ok={ok} "
              f"compile {tc:.1f}s, {dt*1e3:.3f} ms/pass", flush=True)


if __name__ == "__main__":
    sys.exit(main())
