#!/usr/bin/env python
"""
rtop: live terminal view of a survey running in ANOTHER process.

Tail-reads the journal directory's artifacts — ``journal.jsonl``
(chunk / parked / incident records; successive frames re-read and
parse only newly appended bytes from a remembered offset, and the
per-frame aggregation runs over the in-memory state, never back over
the file) and the ``heartbeat_*.jsonl`` sidecars — the same files the
run is already fsync-appending, so watching costs the run nothing and
needs no endpoint (use the ``/status`` HTTP surface when
``RIPTIDE_PROM_PORT`` is up; rtop is the no-network fallback).

Shows chunk progress (done / parked / total with a bar), the recent
chunk rate and ETA, the tunnel/device bound split, per-process
heartbeat ages, and the tail of the incident timeline.

Usage::

    python tools/rtop.py JDIR [--interval 2.0] [--once]

``--once`` prints a single frame and exits (scripts/tests); otherwise
the frame redraws every ``--interval`` seconds until Ctrl-C. Loads the
jax-free reader standalone, so it runs anywhere the journal files are
visible (e.g. over a shared filesystem while the survey runs on the
TPU host).

Pointed at a SERVE directory (one holding the survey service's
``jobs.jsonl`` registry — see docs/survey_service.md) the frame shows
the per-job table instead: tenant, status, chunk progress, queue wait
and device seconds per job, grouped from each job's own journal.
"""
import argparse
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from rreport import load_report_module  # noqa: E402 (path setup first)

# Recent chunks the rate estimate averages over.
RATE_WINDOW = 8
# Incident lines shown.
INCIDENT_TAIL = 5


def _bar(frac, width=32):
    full = int(round(max(0.0, min(1.0, frac)) * width))
    return "[" + "#" * full + "-" * (width - full) + "]"


def render_frame(rep, journal_dir, now=None, follower=None,
                 show_fleet=False):
    """One frame of the progress view as a string (a function of the
    on-disk journal state — the unit tests call it directly). The live
    loop passes a persistent ``JournalFollower`` so successive frames
    only parse newly appended records; without one the journal is read
    whole (the --once path). With fleet sidecars present the frame
    carries a one-line fleet summary; ``show_fleet`` (the ``--fleet``
    flag) expands it to per-process rows with straggler/stale/breaker
    highlighting. Journals without sidecars render exactly as before."""
    now = time.time() if now is None else now
    j = (follower.poll() if follower is not None
         else rep.read_journal(journal_dir))
    header = j["header"] or {}
    chunks = j["chunks"]
    total = header.get("chunks_total")
    done, parked = len(chunks), len(j["parked"])

    lines = [f"rtop — survey {header.get('survey_id', '<no header>')} "
             f"({os.path.abspath(journal_dir)})"]

    walls = [float((chunks[cid].get("timings") or {}).get("chunk_s", 0.0))
             for cid in sorted(chunks)]
    walls = [w for w in walls if w > 0][-RATE_WINDOW:]
    rate = eta = None
    if walls:
        mean = sum(walls) / len(walls)
        if mean > 0:
            rate = 1.0 / mean
            if total is not None:
                eta = max(0, total - done - parked) * mean
    progress = f"chunks {done}"
    if total is not None:
        progress += f"/{total}"
    if parked:
        progress += f" (+{parked} parked)"
    if rate is not None:
        progress += f"  {rate:.2f} chunk/s over last {len(walls)}"
    if eta is not None:
        progress += f"  ETA {eta:.0f}s"
    lines.append(progress)
    if total:
        frac = (done + parked) / total
        lines.append(f"{_bar(frac)} {100 * frac:.0f}%")

    tun = rep.tunnel_stats(chunks)
    if tun["bound_counts"]:
        split = ", ".join(f"{k}={v}" for k, v
                          in sorted(tun["bound_counts"].items()))
        line = f"bound: {split}"
        if tun.get("n_rates"):
            line += (f"  wire {tun['wire_MBps_median']} MB/s median "
                     f"({tun['wire_MBps_min']}-{tun['wire_MBps_max']})")
        lines.append(line)

    beats = rep.read_heartbeats(journal_dir)
    if beats:
        ages = ", ".join(
            f"p{p} {max(0.0, now - ts):.1f}s ago"
            for p, ts in sorted(beats.items()))
        lines.append(f"heartbeats: {ages}")

    snapshots = rep.read_fleet(journal_dir)
    if snapshots:
        fleet = rep.merge_fleet(snapshots, now=now)
        fleet_lines = rep.render_fleet_text(fleet)
        if show_fleet:
            lines.extend(fleet_lines)
        else:
            lines.append(fleet_lines[0] + "  (--fleet for per-process "
                                          "rows)")

    alerts = j.get("alerts") or []
    if alerts:
        firing = {}
        for al in alerts:
            firing[al.get("rule")] = al.get("event") == "fired"
        active = sorted(r for r, f in firing.items() if f)
        lines.append(
            f"alerts: {len(alerts)} event(s)"
            + (f", FIRING: {', '.join(active)}" if active
               else ", all resolved"))

    if j["incidents"]:
        lines.append(f"incidents ({len(j['incidents'])}):")
        for inc in j["incidents"][-INCIDENT_TAIL:]:
            where = (f" chunk {inc['chunk_id']}"
                     if "chunk_id" in inc else "")
            lines.append(f"  {inc.get('utc', '?')} "
                         f"{inc.get('incident', '?')}{where}")
    return "\n".join(lines) + "\n"


def render_serve_frame(rep, serve_dir, now=None):
    """One frame of the SERVICE view: pointing rtop at a serve
    directory (one holding a ``jobs.jsonl`` registry) shows the per-job
    table — tenant, status, chunk progress, queue wait, device seconds
    — instead of a single survey's chunk view. Point it at a
    ``jobs/<id>/`` subdirectory to watch one job's survey the ordinary
    way."""
    now = time.time() if now is None else now
    rows = rep.job_table(serve_dir)
    running = sum(1 for r in rows if r.get("status") == "running")
    pending = sum(1 for r in rows if r.get("status") == "pending")
    lines = [f"rtop — survey service ({os.path.abspath(serve_dir)})",
             f"jobs: {len(rows)} total, {running} running, "
             f"{pending} pending"]
    lines.extend(rep.render_jobs_text(rows))
    return "\n".join(lines) + "\n"


def is_serve_dir(directory):
    return os.path.exists(os.path.join(directory, "jobs.jsonl"))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="rtop",
        description="Terminal progress view of a journaled survey "
                    "running in another process (tail-reads the "
                    "journal directory).",
    )
    ap.add_argument("journal", help="journal directory to watch")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    ap.add_argument("--fleet", action="store_true",
                    help="expand the fleet summary into per-process "
                         "rows (skew/staleness highlighting)")
    args = ap.parse_args(argv)

    rep = load_report_module()
    if not os.path.isdir(args.journal):
        print(f"rtop: {args.journal!r} is not a directory",
              file=sys.stderr)
        return 2
    serve_mode = is_serve_dir(args.journal)
    if args.once:
        sys.stdout.write(render_serve_frame(rep, args.journal)
                         if serve_mode
                         else render_frame(rep, args.journal,
                                           show_fleet=args.fleet))
        return 0
    follower = None if serve_mode else rep.JournalFollower(args.journal)
    try:
        while True:
            frame = (render_serve_frame(rep, args.journal) if serve_mode
                     else render_frame(rep, args.journal,
                                       follower=follower,
                                       show_fleet=args.fleet))
            # Clear + home, then the frame: a flicker-free-enough
            # redraw without a curses dependency.
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
