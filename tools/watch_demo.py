#!/usr/bin/env python
"""
watch-demo: end-to-end acceptance of the fleet observability plane
(PR 14) — the detect half of the loop, proven live on the CPU backend.

Four legs:

1. **control** — a tiny survey (subprocess) with fleet sidecars on;
   its ``peaks.csv`` bytes are the reference.
2. **fleet-ENOSPC** — the same survey with ``enospc:fleet_snapshot``
   injected on EVERY sidecar write: the survey must complete, peaks
   must be byte-identical to control, and the journal must carry the
   ``obs_write_failed`` degradation — fleet writes are proven
   never-fatal.
3. **two-process fleet run** — process 1 (subprocess) surveys its own
   shard, journaling into its own directory but federating its
   ``fleet_0001.json`` into the shared run directory; process 0 (in
   this process) surveys the main shard there with the alert engine on
   and an injected **straggle** fault. Meanwhile:

   * ``tools/rwatch.py`` follows the run from ANOTHER process and must
     see the ``straggler_ratio`` alert fire, then resolve, and exit 0;
   * a poller thread scrapes the live endpoint: the ``/status``
     ``fleet`` block must merge both processes and
     ``riptide_alert_active{rule="straggler_ratio"}`` must be observed
     at 1 DURING the run and 0 after it;
   * the journal must hold the ``alert`` records (fired + resolved)
     and the ``alert_fired``/``alert_resolved`` incidents;
   * ``rtop --fleet`` renders the per-process rows.

4. **rwatch exit codes** — ``--once`` over the healthy finished run
   exits 0; over a synthetic journal with a parked chunk (the
   ``parked_chunks`` rule) exits 1; over a missing directory exits 2.

Output directory: /tmp/riptide_watch_demo (or argv[1]). ``make
watch-demo`` runs this; it is wired into ``make check-full``.
"""
import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Compiled search programs repeat identically across the demo's legs;
# the jax persistent cache keeps every leg after the first (and the
# in-process run) to ~import cost.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.normpath(os.path.join(HERE, ".."))
sys.path.insert(0, os.path.join(ROOT, "tests"))
sys.path.insert(0, ROOT)
sys.path.insert(0, HERE)

TOBS, TSAMP, PERIOD = 12.0, 1e-3, 0.5

# Deliberately heavier than the chaos/report demos (wider bins range):
# the straggler rule compares chunk wall-clocks, so the healthy chunks
# must be substantial enough that scheduler jitter cannot fake an 8x
# outlier.
SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.2, "period_max": 2.0,
                   "bins_min": 64, "bins_max": 128},
    "find_peaks": {"smin": 6.0},
}]

# The straggler rule's demo tuning: chunk 1 is wedged STRAGGLE_S
# inside the dispatch (well beyond LIMIT x the healthy-chunk median),
# and the survey runs enough chunks that the 8-chunk watch window
# slides past BOTH the straggler and chunk 0's compile warmup before
# the end — so the alert provably fires AND resolves.
N_CHUNKS_P0 = 12
N_CHUNKS_P1 = 3
STRAGGLE_CHUNK, STRAGGLE_S = 1, 8.0
RULES = "straggler_ratio:8.0"


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()
    except OSError:
        return None, ""


def _child_env(ledger=None):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    for name in ("RIPTIDE_FAULT_INJECT", "RIPTIDE_PROM_PORT",
                 "RIPTIDE_ALERTS", "RIPTIDE_ALERT_RULES"):
        env.pop(name, None)
    env["JAX_PLATFORMS"] = "cpu"
    if ledger:
        env["RIPTIDE_LEDGER"] = ledger
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   "/tmp/riptide_tpu_jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    return env


def _run_child(cfg, cfg_path, timeout_s=300.0, wait=True):
    with open(cfg_path, "w") as fobj:
        json.dump(cfg, fobj, indent=1)
    cmd = [sys.executable, os.path.join(HERE, "watch_demo.py"),
           "--child", cfg_path]
    if not wait:
        return subprocess.Popen(cmd, env=_child_env(), cwd=ROOT,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
    proc = subprocess.run(cmd, env=_child_env(), cwd=ROOT,
                          capture_output=True, text=True,
                          timeout=timeout_s)
    assert proc.returncode == 0, \
        f"child leg failed ({proc.returncode}):\n" \
        + "\n".join(proc.stderr.splitlines()[-20:])
    return proc


def _child_main(cfg_path):
    """One subprocess survey leg (control / ENOSPC / fleet process 1):
    run the configured shard through the checkpointed scheduler with
    fleet writes federating into ``fleet_dir``."""
    with open(cfg_path) as fobj:
        cfg = json.load(fobj)
    from riptide_tpu.pipeline.batcher import BatchSearcher
    from riptide_tpu.survey.faults import FaultPlan
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.scheduler import SurveyScheduler

    searcher = BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                             SEARCH_CONF, fmt="presto", io_threads=1)
    scheduler = SurveyScheduler(
        searcher, [[f] for f in cfg["files"]],
        journal=SurveyJournal(cfg["journal"]),
        faults=FaultPlan.parse(cfg.get("faults") or ""),
        process_index=int(cfg.get("process_index", 0)),
        fleet_dir=cfg.get("fleet_dir"),
    )
    peaks = scheduler.run()
    if cfg.get("peaks_csv"):
        import pandas

        pandas.DataFrame.from_dict(
            [p.summary_dict() for p in peaks]
        ).to_csv(cfg["peaks_csv"], sep=",", index=False,
                 float_format="%.9f")
    return 0


def main(outdir="/tmp/riptide_watch_demo"):
    from synth import generate_data_presto

    import rreport
    import rtop
    import rwatch
    from riptide_tpu.obs import prom
    from riptide_tpu.obs import report as rep
    from riptide_tpu.pipeline.batcher import BatchSearcher
    from riptide_tpu.survey.faults import FaultPlan
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.metrics import get_metrics
    from riptide_tpu.survey.scheduler import SurveyScheduler

    shutil.rmtree(outdir, ignore_errors=True)
    os.makedirs(outdir)
    files_p0 = [
        generate_data_presto(outdir, f"p0_DM{dm:.1f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=float(dm),
                             amplitude=30.0)
        for dm in range(N_CHUNKS_P0)
    ]
    files_p1 = [
        generate_data_presto(outdir, f"p1_DM{dm:.1f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=float(dm),
                             amplitude=30.0)
        for dm in (20.0, 25.0, 30.0)
    ]
    assert len(files_p1) == N_CHUNKS_P1

    # -- leg 1+2: fleet writes are never fatal under ENOSPC -----------
    control_csv = os.path.join(outdir, "control.csv")
    _run_child({"files": files_p1,
                "journal": os.path.join(outdir, "j_control"),
                "peaks_csv": control_csv},
               os.path.join(outdir, "leg_control.json"))
    enospc_csv = os.path.join(outdir, "enospc.csv")
    _run_child({"files": files_p1,
                "journal": os.path.join(outdir, "j_enospc"),
                "peaks_csv": enospc_csv,
                "faults": "enospc:fleet_snapshot:1x99"},
               os.path.join(outdir, "leg_enospc.json"))
    with open(control_csv, "rb") as fobj:
        control_bytes = fobj.read()
    with open(enospc_csv, "rb") as fobj:
        assert fobj.read() == control_bytes, \
            "ENOSPC on fleet writes changed the data products"
    state = rep.read_journal(os.path.join(outdir, "j_enospc"))
    degr = [i for i in state["incidents"]
            if i.get("incident") == "obs_write_failed"
            and (i.get("detail") or {}).get("op") == "fleet_snapshot"]
    assert degr, "no obs_write_failed incident for the fleet ENOSPC"
    assert len(state["chunks"]) == N_CHUNKS_P1, \
        "ENOSPC leg did not complete its survey"
    print(f"fleet-ENOSPC leg OK: survey completed, peaks byte-identical "
          f"({len(control_bytes)} bytes), {len(degr)} degradation "
          "incident(s)")

    # -- leg 3: the two-process fleet run -----------------------------
    jdir = os.path.join(outdir, "j")
    jdir_p1 = os.path.join(outdir, "j_p1")
    os.makedirs(jdir, exist_ok=True)

    server = prom.serve(0)
    base = f"http://127.0.0.1:{server.port}"
    seen = {"gauge_high": False, "gauge_low": False, "fleet_procs": set()}
    stop = threading.Event()

    def poller():
        while not stop.wait(0.1):
            code, body = _get(f"{base}/metrics")
            if code == 200:
                for line in body.splitlines():
                    if line.startswith('riptide_alert_active{'
                                       'rule="straggler_ratio"}'):
                        val = line.rsplit(None, 1)[-1]
                        seen["gauge_high" if val == "1"
                             else "gauge_low"] = True
            code, body = _get(f"{base}/status")
            if code == 200:
                doc = json.loads(body)
                for p in (doc.get("fleet") or {}).get("processes", {}):
                    seen["fleet_procs"].add(p)

    watcher = threading.Thread(target=poller, daemon=True)
    watcher.start()

    # Process 1: own shard, own journal, federating into jdir.
    p1 = _run_child({"files": files_p1, "journal": jdir_p1,
                     "fleet_dir": jdir, "process_index": 1},
                    os.path.join(outdir, "leg_p1.json"), wait=False)

    # rwatch follows the shared run directory from its own process.
    rwatch_json = os.path.join(outdir, "rwatch.json")
    rw = subprocess.Popen(
        [sys.executable, os.path.join(HERE, "rwatch.py"), jdir,
         "--interval", "0.2", "--timeout", "240", "--rules", RULES,
         "--json", rwatch_json],
        env=_child_env(), cwd=ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)

    # Process 0: the main shard, in this process, alert engine on.
    os.environ["RIPTIDE_ALERTS"] = "1"
    os.environ["RIPTIDE_ALERT_RULES"] = RULES
    try:
        get_metrics().reset()
        searcher = BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                                 SEARCH_CONF, fmt="presto", io_threads=1)
        scheduler = SurveyScheduler(
            searcher, [[f] for f in files_p0],
            journal=SurveyJournal(jdir), process_index=0,
            faults=FaultPlan.parse(
                f"straggle:{STRAGGLE_CHUNK}:{STRAGGLE_S}"),
        )
        peaks = scheduler.run()
    finally:
        del os.environ["RIPTIDE_ALERTS"]
        del os.environ["RIPTIDE_ALERT_RULES"]

    p1_out, p1_err = p1.communicate(timeout=300)
    assert p1.returncode == 0, \
        f"process-1 leg failed ({p1.returncode}):\n" \
        + "\n".join(p1_err.splitlines()[-20:])
    rw_out, rw_err = rw.communicate(timeout=300)
    stop.set()
    watcher.join(timeout=5.0)

    # rwatch saw the fire AND the resolve, and exited clean.
    assert rw.returncode == 0, \
        f"rwatch exited {rw.returncode}:\n{rw_out}\n{rw_err}"
    with open(rwatch_json) as fobj:
        watched = json.load(fobj)
    w_events = [(e["event"], e["rule"]) for e in watched["events"]]
    assert ("fired", "straggler_ratio") in w_events, w_events
    assert ("resolved", "straggler_ratio") in w_events, w_events
    assert not watched["unresolved"], watched["unresolved"]
    assert watched["complete"], watched
    # rwatch exits the moment p0's journal completes; p1's sidecar is
    # normally federated by then (it runs a much shorter shard), but
    # the STRICT both-processes assertion lives below on the final
    # /status + rreport views, after p1 has provably exited.
    assert "0" in watched["fleet"]["processes"], watched["fleet"]

    # The journal carries the alert records + mirrored incidents.
    state = rep.read_journal(jdir)
    j_events = [(a.get("event"), a.get("rule")) for a in state["alerts"]]
    assert ("fired", "straggler_ratio") in j_events, j_events
    assert ("resolved", "straggler_ratio") in j_events, j_events
    inc = [i["incident"] for i in state["incidents"]]
    assert "alert_fired" in inc and "alert_resolved" in inc, inc

    # Live surfaces: the gauge was observed at 1 during the run and is
    # 0 now; the /status fleet block merged both processes.
    code, body = _get(f"{base}/metrics")
    assert code == 200 and \
        'riptide_alert_active{rule="straggler_ratio"} 0' in body, \
        [l for l in body.splitlines() if "alert_active" in l]
    assert seen["gauge_high"], \
        "poller never saw riptide_alert_active=1 during the run"
    code, body = _get(f"{base}/status")
    final = json.loads(body)
    assert code == 200 and \
        set(final["fleet"]["processes"]) == {"0", "1"}, final.get("fleet")
    assert "0" in seen["fleet_procs"], \
        "poller never saw the /status fleet block"
    assert final["fleet"]["chunks_done"] == N_CHUNKS_P0 + N_CHUNKS_P1, \
        final["fleet"]

    # The /metrics page federates both processes' fleet series.
    code, body = _get(f"{base}/metrics")
    assert 'riptide_fleet_chunks_done{process="0"}' in body
    assert 'riptide_fleet_chunks_done{process="1"}' in body

    # rtop --fleet renders the per-process rows.
    rep_mod = rreport.load_report_module()
    frame = rtop.render_frame(rep_mod, jdir, show_fleet=True)
    assert "p0:" in frame and "p1:" in frame, frame
    assert "alerts:" in frame, frame

    # rreport's fleet section over the same files.
    rc = rreport.main([jdir, "--quiet", "--json",
                       os.path.join(outdir, "report.json")])
    assert rc == 0, f"rreport exited {rc}"
    with open(os.path.join(outdir, "report.json")) as fobj:
        report = json.load(fobj)
    assert report["fleet"]["nprocesses"] == 2, report["fleet"]
    assert len(report["alerts"]) >= 2, report["alerts"]

    # -- leg 4: rwatch exit codes -------------------------------------
    rc = rwatch.main([jdir, "--once", "--rules", RULES, "--quiet"])
    assert rc == 0, f"rwatch --once on a healthy run exited {rc}"
    parked_dir = os.path.join(outdir, "j_parked")
    j = SurveyJournal(parked_dir)
    j.write_header("demo-parked", 2)
    j.record_parked(1, "demo: breaker open")
    rc = rwatch.main([parked_dir, "--once", "--rules", "parked_chunks",
                      "--quiet"])
    assert rc == 1, f"rwatch --once with a parked chunk exited {rc}"
    rc = rwatch.main([os.path.join(outdir, "nope"), "--once"])
    assert rc == 2, f"rwatch on a missing directory exited {rc}"

    server.close()
    print(f"\nwatch demo OK: {len(peaks)} peaks from "
          f"{N_CHUNKS_P0}+{N_CHUNKS_P1} chunks across 2 processes")
    print(f"  run dir    ->  {jdir}")
    print(f"  rwatch     ->  {rwatch_json} "
          f"({len(watched['events'])} events, exit 0)")
    print("  straggler_ratio fired AND resolved: journal alert records, "
          "alert_fired/alert_resolved incidents,")
    print("  riptide_alert_active gauge observed 1 live then 0; "
          "/status fleet block merged p0+p1;")
    print("  fleet ENOSPC leg completed byte-identical to control; "
          "rwatch exit codes 0/1/2 verified\n")
    sys.stdout.write(frame)
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        sys.exit(_child_main(sys.argv[2]))
    sys.exit(main(*sys.argv[1:2]))
