#!/usr/bin/env python
"""
Static lint: every ``multihost_utils`` collective call site in
``riptide_tpu/`` goes through the liveness layer's bounded-wait
wrappers.

A raw ``multihost_utils.process_allgather`` (or any other collective)
blocks forever when a peer is dead — exactly the failure mode the
liveness layer exists to bound — so the discipline is structural: the
ONLY functions allowed to invoke an attribute of ``multihost_utils``
are the wrappers in ``riptide_tpu/survey/liveness.py``
(``bounded_allgather``, ``barrier_with_timeout``), which run the
collective under :func:`bounded_wait`. Everything else must call those
wrappers. The check is AST-based and runs in tier-1 via
``tests/test_liveness_guards.py`` and the Makefile ``check`` target, so
a future call site cannot silently reintroduce an unbounded wait.

The lint also fails when it finds NO ``multihost_utils`` call at all
inside the allowed wrappers — that would mean the wrappers were
refactored away and the lint had gone vacuous.

Exit status 0 when clean; 1 with one violation per line otherwise.
"""
import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "riptide_tpu")

# file (repo-relative) -> function names allowed to call multihost_utils
ALLOWED = {
    os.path.join("riptide_tpu", "survey", "liveness.py"):
        {"bounded_allgather", "barrier_with_timeout"},
}


def _is_multihost_attr(node):
    """True for an attribute access rooted at a name (or attribute)
    called ``multihost_utils`` — covers ``multihost_utils.x`` and
    ``jax.experimental.multihost_utils.x``."""
    if not isinstance(node, ast.Attribute):
        return False
    v = node.value
    if isinstance(v, ast.Name):
        return v.id == "multihost_utils"
    if isinstance(v, ast.Attribute):
        return v.attr == "multihost_utils"
    return False


def _call_sites(tree):
    """Sites that can reach a collective, as ``(lineno, enclosing
    function name or None, kind)``:

    * ``call`` — a ``multihost_utils.<collective>(...)`` call;
    * ``import`` — a binding that would let later calls evade the
      attribute check: ``from ...multihost_utils import X`` (a
      collective under a bare name), ``from jax.experimental import
      multihost_utils as Y`` or ``import ...multihost_utils as Y``
      (the module under an alias). These are violations at the import
      itself, wherever the call happens.

    ``from jax.experimental import multihost_utils`` (the module under
    its own name) is fine — its call sites match the attribute check.
    """
    sites = []

    def visit(node, fn):
        name = fn
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            name = node.name
        if isinstance(node, ast.Call) and _is_multihost_attr(node.func):
            sites.append((node.lineno, name, "call"))
        elif isinstance(node, ast.ImportFrom):
            if node.module \
                    and node.module.split(".")[-1] == "multihost_utils":
                sites.append((node.lineno, name, "import"))
            else:
                for a in node.names:
                    if a.name == "multihost_utils" and a.asname not in (
                            None, "multihost_utils"):
                        sites.append((node.lineno, name, "import"))
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] == "multihost_utils" \
                        and a.asname is not None:
                    sites.append((node.lineno, name, "import"))
        for child in ast.iter_child_nodes(node):
            visit(child, name)

    visit(tree, None)
    return sites


def check_file(path, rel, allowed):
    """Violation strings for one module (empty list = clean); second
    return value counts call sites inside allowed wrappers."""
    with open(path) as fobj:
        tree = ast.parse(fobj.read(), filename=path)
    violations, wrapped = [], 0
    for lineno, fn, kind in _call_sites(tree):
        if fn is not None and fn in allowed.get(rel, ()):
            if kind == "call":
                wrapped += 1
            continue
        what = ("raw multihost_utils collective" if kind == "call"
                else "multihost_utils import that evades the call check")
        violations.append(
            f"{rel}:{lineno}: {what} "
            f"{'in ' + fn + '()' if fn else 'at module level'} — route it "
            "through riptide_tpu.survey.liveness (bounded_allgather / "
            "barrier_with_timeout) so a dead peer cannot deadlock the run"
        )
    return violations, wrapped


def check(repo=REPO, allowed=None):
    """All violations across ``riptide_tpu/``; vacuous-lint guard
    included (see module docstring)."""
    allowed = ALLOWED if allowed is None else allowed
    package = os.path.join(repo, "riptide_tpu")
    violations, wrapped_total = [], 0
    for dirpath, dirnames, filenames in os.walk(package):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo)
            v, wrapped = check_file(path, rel, allowed)
            violations.extend(v)
            wrapped_total += wrapped
    if wrapped_total == 0:
        violations.append(
            "no multihost_utils call found inside the allowed liveness "
            "wrappers — the lint has gone vacuous (were "
            "bounded_allgather/barrier_with_timeout refactored away? "
            "update tools/check_liveness_guards.py)"
        )
    return violations


def main():
    violations = check()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} liveness-guard violation(s)",
              file=sys.stderr)
        return 1
    print("liveness guards OK: every multihost_utils collective routes "
          "through the bounded-wait wrappers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
