#!/usr/bin/env python
"""
Back-compat shim: the bounded-collective lint now lives in the riplint
framework (``riptide_tpu/analysis/liveness_guards.py``, rule RIP007,
run by ``tools/riplint.py`` / ``make check``). This entry point keeps
the historical CLI and the ``check()`` / ``check_file()`` API working
for existing invocations and tests.

Exit status 0 when clean; 1 with one violation per line otherwise.
"""
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _analysis():
    spec = importlib.util.spec_from_file_location(
        "riplint_shim", os.path.join(REPO, "tools", "riplint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load_analysis(REPO)


_lg = _analysis().liveness_guards

ALLOWED = _lg.ALLOWED
check_file = _lg.check_file


def check(repo=REPO, allowed=None):
    """All violations across ``riptide_tpu/``; vacuous-lint guard
    included (see riptide_tpu/analysis/liveness_guards.py)."""
    return _lg.check(repo, allowed=allowed)


def main():
    violations = check()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} liveness-guard violation(s)",
              file=sys.stderr)
        return 1
    print("liveness guards OK: every multihost_utils collective routes "
          "through the bounded-wait wrappers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
