#!/usr/bin/env python
"""
analyze: the whole static surface in ONE SARIF document.

Runs the three verification passes —

* **riplint** (``tools/riplint.py --format sarif``): the 14 AST/
  call-graph analyzers against the checked-in baseline;
* **rprove** (``tools/rprove.py --format sarif``): the semantic pass
  over the pinned staged-program contracts (traced on the CPU
  backend, no device execution);
* **ripsched** (``tools/ripsched.py --format sarif``): the
  schedule-exploration model checker over the serve-plane
  concurrency protocols —

and merges their SARIF 2.1.0 runs into one multi-run document (one
``runs[]`` entry per tool, rule metadata preserved), the shape SARIF
uploaders and code-scanning UIs ingest directly.

Usage::

    python tools/analyze.py [OUT.sarif]     # default: riptide.sarif

Exit code: the MAXIMUM of the three tools' exit codes (0 all clean;
1 any findings/violations; 2 any usage/pin-drift error), so CI can
gate on this one entry point. ``make analyze`` runs this.
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DEFAULT_OUT = os.path.join(REPO, "riptide.sarif")

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

# (tool name, argv tail, extra env). rprove traces jaxprs: it needs
# the CPU backend and a clean PYTHONPATH exactly like `make prove`.
TOOLS = (
    ("riplint", ["riplint.py", "--format", "sarif"], {}),
    ("rprove", ["rprove.py", "--format", "sarif"],
     {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}),
    ("ripsched", ["ripsched.py", "--format", "sarif"], {}),
)


def main(out_path=DEFAULT_OUT):
    merged = {"version": "2.1.0", "$schema": SARIF_SCHEMA, "runs": []}
    worst = 0
    for name, tail, extra in TOOLS:
        env = dict(os.environ, **extra)
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, tail[0]), *tail[1:]],
            capture_output=True, text=True, cwd=REPO, env=env)
        worst = max(worst, proc.returncode)
        sys.stderr.write(proc.stderr)
        try:
            doc = json.loads(proc.stdout)
        except ValueError:
            # A tool that died before emitting SARIF (pin drift, usage
            # error) has no run to merge; its stderr + exit code carry
            # the diagnosis.
            print(f"analyze: {name} exited {proc.returncode} without "
                  "SARIF output", file=sys.stderr)
            continue
        runs = doc.get("runs", [])
        merged["runs"].extend(runs)
        n_results = sum(len(r.get("results", [])) for r in runs)
        n_rules = sum(len(r["tool"]["driver"].get("rules", []))
                      for r in runs)
        print(f"analyze: {name}: {n_rules} rule(s), {n_results} "
              f"result(s), exit {proc.returncode}", file=sys.stderr)

    with open(out_path, "w") as fobj:
        json.dump(merged, fobj, indent=2)
        fobj.write("\n")
    total = sum(len(r.get("results", [])) for r in merged["runs"])
    print(f"analyze: {len(merged['runs'])} run(s) merged into "
          f"{os.path.relpath(out_path, REPO)} ({total} total "
          f"result(s)); exit {worst}", file=sys.stderr)
    return worst


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:2]))
