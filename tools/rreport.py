#!/usr/bin/env python
"""
rreport: post-run report + CI regression sentinel over a survey journal.

Merges a journal directory's artifacts — per-chunk ``timing`` blocks,
structured ``incident`` records, dq blocks, an optional ``trace.json``
and an optional Prometheus textfile — into one report:

* phase-attribution table (the serial phases must sum to each chunk's
  journaled wall-clock within 5%; a violation means a broken writer
  and exits nonzero),
* straggler chunks (> 2x the median wall-clock),
* the tunnel-rate distribution against the device tunnel's observed
  4-70 MB/s swing, with the per-chunk tunnel/device bound split,
* the incident timeline (with chunk + span ids),
* with ``--compare LEDGER``: a noise-aware regression verdict of this
  run's device seconds per chunk against the perf-ledger history
  (tunnel-bound rows excluded on both sides; band = baseline median
  * (1 + rel-tol) + mad-k * MAD). Exit 1 on regression — point CI at
  it.

Usage::

    python tools/rreport.py JDIR [--trace PATH] [--prom PATH]
        [--json PATH] [--compare LEDGER] [--rel-tol 0.15] [--mad-k 3.0]
        [--quiet]

Exit codes: 0 clean / comparison ok / nothing to compare against;
1 regression or phase-sum violation; 2 usage or unreadable input.

Loads ``riptide_tpu/obs/report.py`` standalone by file path (the
riplint pattern), so running it needs no jax — it works on a login
node holding only the journal files.
"""
import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def load_report_module():
    """riptide_tpu.obs.report, loaded standalone so importing it never
    drags in jax (or riptide_tpu/__init__)."""
    name = "riptide_tpu_obs_report_standalone"
    if name in sys.modules:
        return sys.modules[name]
    path = os.path.normpath(
        os.path.join(HERE, "..", "riptide_tpu", "obs", "report.py"))
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[name]
        raise
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="rreport",
        description="Post-run report + regression sentinel over a "
                    "survey journal directory.",
    )
    ap.add_argument("journal", help="journal directory (holds "
                                    "journal.jsonl)")
    ap.add_argument("--trace", default=None,
                    help="Chrome trace file to summarise (default: "
                         "trace.json next to the journal, when present)")
    ap.add_argument("--prom", default=None,
                    help="Prometheus textfile to fold into the JSON "
                         "report")
    ap.add_argument("--json", default=None,
                    help="write the full report (+ verdict) as JSON to "
                         "this path ('-' for stdout)")
    ap.add_argument("--compare", default=None, metavar="LEDGER",
                    help="perf-ledger JSONL to compare this run's "
                         "device time per chunk against (exit 1 on "
                         "regression)")
    ap.add_argument("--kind", default="survey",
                    help="ledger row kind the baseline is drawn from "
                         "(default 'survey'; 'any' disables the "
                         "filter — bench and survey rows are not "
                         "comparable perf points)")
    ap.add_argument("--platform", default="auto",
                    help="restrict the baseline to rows of one device "
                         "platform: 'auto' (default) scopes to the "
                         "newest matching row's platform — normally "
                         "this run's own append, so cpu smoke rows "
                         "never baseline a TPU check; 'any' disables; "
                         "or 'backend[:device_kind]' literally")
    ap.add_argument("--rel-tol", type=float, default=0.15,
                    help="relative regression tolerance over the "
                         "baseline median (default 0.15)")
    ap.add_argument("--mad-k", type=float, default=3.0,
                    help="how many baseline median-absolute-deviations "
                         "widen the band (default 3.0)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human report (exit code + JSON "
                         "only)")
    args = ap.parse_args(argv)

    rep = load_report_module()
    if not os.path.exists(os.path.join(args.journal, "journal.jsonl")):
        if os.path.exists(os.path.join(args.journal, "jobs.jsonl")):
            # A survey-service directory: group its artifacts per job
            # (each job's own journal stays rreport-able at
            # jobs/<id>/).
            print("\n".join(rep.render_jobs_text(
                rep.job_table(args.journal))))
            return 0
        print(f"rreport: no journal.jsonl under {args.journal!r}",
              file=sys.stderr)
        return 2

    report = rep.build_report(args.journal, trace_path=args.trace,
                              prom_path=args.prom)
    rc = 0
    if report["phase_sum_violations"]:
        # The writer guarantees the sum by construction; a violation is
        # a broken producer, which CI must surface.
        rc = 1

    verdict = None
    if args.compare:
        if not os.path.exists(args.compare):
            print(f"rreport: ledger {args.compare!r} not found",
                  file=sys.stderr)
            return 2
        rows = rep.read_ledger(args.compare)
        kind = None if args.kind == "any" else args.kind
        # Platform scope resolves BEFORE the own-row drop: the run's
        # own just-appended row is the best available record of the
        # platform this run actually executed on.
        if args.platform == "auto":
            platform = rep.latest_platform(rows, kind=kind)
        elif args.platform == "any":
            platform = None
        else:
            backend, _, device_kind = args.platform.partition(":")
            platform = {"backend": backend,
                        "device_kind": device_kind or None}
        rows, own_dropped = rep.drop_own_row(rows,
                                             report.get("survey_id"))
        verdict, cmp_rc = rep.compare_to_ledger(
            report["run"], rows, rel_tol=args.rel_tol, mad_k=args.mad_k,
            kind=kind, platform=platform)
        verdict["own_row_excluded"] = own_dropped
        report["compare"] = verdict
        rc = max(rc, cmp_rc)

    if not args.quiet:
        sys.stdout.write(rep.render_text(report))
        if verdict is not None:
            v = verdict["verdict"]
            line = f"compare vs {args.compare}: {v}"
            if verdict.get("current") is not None:
                line += (f" (device {verdict['current']}s/chunk"
                         + (f" vs baseline median "
                            f"{verdict['baseline_median']}s, "
                            f"threshold {verdict['threshold']}s"
                            if "baseline_median" in verdict else "")
                         + ")")
            print(line)

    if args.json:
        payload = json.dumps(report, indent=2, default=str)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as fobj:
                fobj.write(payload + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
