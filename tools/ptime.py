"""Microbenchmark Pallas primitive passes on the real chip.

Times N repetitions of one primitive pattern over a (2048, 384) f32 VMEM
buffer inside a single-program pallas kernel, to locate the slow ops in
the fused FFA kernel (which is built from exactly these patterns).
"""
import functools
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from riptide_tpu.utils.compat import pallas_compiler_params

ROWS, P = 2048, 384
REPS = 32


def kern_roll(x_ref, o_ref):
    x = x_ref[:]
    acc = x
    for i in range(REPS):
        acc = acc + pltpu.roll(x, (i * 7 + 1) % P, axis=1)
    o_ref[:] = acc


def kern_roll_rows(x_ref, o_ref):
    x = x_ref[:]
    acc = x
    for i in range(REPS):
        acc = acc + pltpu.roll(x, (i * 5 + 1) % ROWS, axis=0)
    o_ref[:] = acc


def kern_select(x_ref, o_ref):
    x = x_ref[:]
    cols = jax.lax.broadcasted_iota(jnp.int32, (ROWS, P), 1)
    acc = x
    for i in range(REPS):
        acc = jnp.where(cols < (i * 11) % P, acc + 1.0, acc * 0.5)
    o_ref[:] = acc


def kern_barrel(x_ref, o_ref):
    x = x_ref[:]
    sig = jax.lax.broadcasted_iota(jnp.int32, (ROWS, P), 0)
    acc = x
    for k in range(min(REPS, 9)):
        rolled = pltpu.roll(acc, 1 << k, axis=1)
        acc = jnp.where(((sig >> k) & 1) != 0, rolled, acc)
    o_ref[:] = acc


def kern_interleave(x_ref, o_ref):
    x = x_ref[:]
    G, S_d = 8, ROWS // 8
    acc = x
    for i in range(max(REPS // 8, 1)):
        v = acc.reshape(G, 2, S_d // 2, P)
        reph = jnp.repeat(v[:, 0], 2, axis=1)
        rept = jnp.repeat(v[:, 1], 2, axis=1)
        acc = (reph + rept).reshape(ROWS, P) + float(i)
    o_ref[:] = acc


def kern_dynroll(s_ref, x_ref, o_ref):
    x = x_ref[:]
    acc = x
    for i in range(REPS):
        acc = acc + pltpu.roll(x, s_ref[i % 8], axis=0)
    o_ref[:] = acc


def build(kern, with_scal=False, shape=(ROWS, P)):
    in_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)]
    if with_scal:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
    return jax.jit(pl.pallas_call(
        kern,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
        compiler_params=pallas_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
    ))


def make_tile_add(rows, cols):
    def kern(x_ref, o_ref):
        x = x_ref[:]
        acc = x
        for i in range(REPS):
            acc = acc * 1.0001 + x
        o_ref[:] = acc
    return kern


def make_tile_roll(rows, cols):
    def kern(x_ref, o_ref):
        x = x_ref[:]
        acc = x
        for i in range(REPS):
            acc = acc + pltpu.roll(x, (i * 7 + 1) % cols, axis=1)
        o_ref[:] = acc
    return kern


def _run_k(fn, args, k):
    """k sequential device calls, ONE host sync at the end."""
    t0 = time.perf_counter()
    vals = [fn(*args)[0, 0] for _ in range(k)]
    np.asarray(jnp.stack(vals))
    return time.perf_counter() - t0


def timeit(name, fn, args, passes, k1=4, k2=16):
    fn(*args).block_until_ready()
    # slope method: (k2 calls + sync) - (k1 calls + sync) removes the
    # (wildly variable) tunnel roundtrip latency from the estimate.
    t1 = min(_run_k(fn, args, k1) for _ in range(3))
    t2 = min(_run_k(fn, args, k2) for _ in range(3))
    dt = (t2 - t1) / (k2 - k1)
    print(f"{name:12s}: {dt*1e3:8.3f} ms/call  {dt/passes*1e6:8.1f} us/pass"
          f"  ({passes} passes)")
    return dt


def kern_add(x_ref, o_ref):
    x = x_ref[:]
    acc = x
    for i in range(REPS):
        acc = acc * 1.0001 + x
    o_ref[:] = acc


def kern_repeat_tpu(x_ref, o_ref):
    x = x_ref[:]
    G, S_d = 8, ROWS // 8
    acc = x
    for i in range(max(REPS // 8, 1)):
        v = acc.reshape(G, 2, S_d // 2, P)
        reph = pltpu.repeat(v[:, 0], 2, axis=1)
        rept = pltpu.repeat(v[:, 1], 2, axis=1)
        acc = (reph + rept).reshape(ROWS, P) + float(i)
    o_ref[:] = acc


def kern_repeat_flat(x_ref, o_ref):
    """Interleave via 2-D ops only: shift + parity select (no reshape)."""
    x = x_ref[:]
    rows2 = jax.lax.broadcasted_iota(jnp.int32, (ROWS, P), 0)
    acc = x
    for i in range(max(REPS // 4, 1)):
        # repeat-each-row-twice approximation pattern: out[u] = acc[u//2 + base]
        # expressed as two strided-ish selects over static rolls
        up1 = pltpu.roll(acc, 1, axis=0)
        acc = jnp.where((rows2 & 1) == 0, acc, up1) + float(i)
    o_ref[:] = acc


def kern_stride_roll(x_ref, o_ref):
    x = x_ref[:]
    acc = x
    for i in range(REPS):
        acc = acc + pltpu.roll(x, i % P, axis=1, stride=1, stride_axis=0)
    o_ref[:] = acc


def kern_matmul(a_ref, x_ref, o_ref):
    a = a_ref[:]   # (ROWS, ROWS) selection-ish matrix
    x = x_ref[:]
    acc = x
    for i in range(max(REPS // 8, 1)):
        acc = jax.lax.dot_general(
            a, acc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * 0.001 + float(i)
    o_ref[:] = acc


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((ROWS, P)).astype(np.float32))
    scal = jnp.asarray(np.arange(8, dtype=np.int32) * 37 + 5)

    null = jax.jit(lambda a: a * 1.0)
    null(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(8):
        float(np.asarray(null(x)[0, 0]))
    rt = (time.perf_counter() - t0) / 8
    print(f"{'sync RT':12s}: {rt*1e3:8.2f} ms/call  (baseline)")

    which = sys.argv[1:] or ["all"]

    def want(n):
        return "all" in which or n in which

    if want("add"):
        timeit("add", build(kern_add), (x,), REPS)
    if want("roll"):
        timeit("roll lanes", build(kern_roll), (x,), REPS)
        timeit("roll rows", build(kern_roll_rows), (x,), REPS)
    if want("select"):
        timeit("select", build(kern_select), (x,), REPS)
    if want("barrel"):
        timeit("barrel9", build(kern_barrel), (x,), 9)
    if want("inter"):
        timeit("interleave", build(kern_interleave), (x,), REPS // 8)
        timeit("repeat_tpu", build(kern_repeat_tpu), (x,), REPS // 8)
        timeit("parity_sel", build(kern_repeat_flat), (x,), REPS // 4)
    if want("dyn"):
        timeit("dynroll", build(kern_dynroll, with_scal=True), (scal, x), REPS)
    if want("stride"):
        timeit("stride_roll", build(kern_stride_roll), (x,), REPS)
    if want("tile"):
        for rows, cols in [(64, 384), (256, 384), (512, 384), (2048, 384),
                           (2048, 128), (256, 128), (8, 384), (8, 128)]:
            xt = jnp.asarray(
                rng.standard_normal((rows, cols)).astype(np.float32))
            ksz = rows * cols
            dt = timeit(f"add {rows}x{cols}",
                        build(make_tile_add(rows, cols), shape=(rows, cols)),
                        (xt,), REPS)
            print(f"    -> {ksz*REPS/dt/1e9:.1f} Gelem/s")
            dt = timeit(f"roll {rows}x{cols}",
                        build(make_tile_roll(rows, cols), shape=(rows, cols)),
                        (xt,), REPS)
            print(f"    -> {ksz*REPS/dt/1e9:.1f} Gelem/s")
    if want("mm"):
        a = jnp.asarray(rng.standard_normal((ROWS, ROWS)).astype(np.float32))
        mm = build(kern_matmul)
        n = max(REPS // 8, 1)
        dt = timeit("matmul", mm, (a, x), n)
        fl = 2.0 * ROWS * ROWS * P * n
        print(f"  -> {fl/dt/1e12:.1f} TFLOP/s f32 ({ROWS}x{ROWS}x{P})")


if __name__ == "__main__":
    main()
