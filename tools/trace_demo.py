#!/usr/bin/env python
"""
trace-demo: produce a Perfetto-loadable trace from a tiny CPU survey.

Synthesizes two small dedispersed time series, runs them through the
checkpointed survey scheduler with the span tracer enabled, and leaves
in the output directory (default /tmp/riptide_trace_demo, or argv[1]):

* ``j/trace.json``      — Chrome trace-event JSON: open in Perfetto
  (https://ui.perfetto.dev) or chrome://tracing; one flame lane per
  host thread with stage/ship/queue/collect/journal spans per chunk
  and the engine's prep/wire/dispatch/device spans nested inside;
* ``j/journal.jsonl``   — the survey journal, each chunk record
  carrying its ``timing`` phase decomposition and UTC stamp;
* ``riptide.prom``      — Prometheus text-format exposition of the
  run's metrics registry (counters, gauges, latency histograms).

The script also sanity-checks what it wrote (trace loads as JSON and
holds the expected span names; the timing block sums to the chunk
wall-clock; the histogram counts match the counters) so ``make
trace-demo`` doubles as a smoke test of the whole obs path.
"""
import json
import os
import shutil
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TOBS, TSAMP, PERIOD = 16.0, 1e-3, 0.5

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]


def main(outdir="/tmp/riptide_trace_demo"):
    from synth import generate_data_presto

    from riptide_tpu.obs import prom, trace
    from riptide_tpu.pipeline.batcher import BatchSearcher
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.metrics import get_metrics
    from riptide_tpu.survey.scheduler import SurveyScheduler

    shutil.rmtree(outdir, ignore_errors=True)
    os.makedirs(outdir)
    files = [
        generate_data_presto(outdir, f"demo_DM{dm:.2f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm,
                             amplitude=25.0)
        for dm in (0.0, 5.0)
    ]

    trace.enable()
    get_metrics().reset()
    jdir = os.path.join(outdir, "j")
    searcher = BatchSearcher({"rmed_width": 4.0, "rmed_minpts": 101},
                             SEARCH_CONF, fmt="presto", io_threads=1)
    peaks = SurveyScheduler(searcher, [[f] for f in files],
                            journal=SurveyJournal(jdir)).run()
    promfile = os.path.join(outdir, "riptide.prom")
    prom.write_prom(promfile)

    # -- verify what we just wrote ------------------------------------
    trace_path = os.path.join(jdir, "trace.json")
    with open(trace_path) as fobj:
        doc = json.load(fobj)
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    missing = {"stage", "ship", "queue", "collect", "journal",
               "prep", "wire", "dispatch", "device", "cluster"} - names
    assert not missing, f"trace is missing spans: {missing}"
    # The cluster span moved off the serial host path (PR 19): with the
    # default RIPTIDE_DEVICE_CLUSTER it lives INSIDE a collect span's
    # time range (the post-pull tail), and the dispatch counter proves
    # the fused peak program carried the cluster sections — exactly one
    # cluster dispatch per chunk, no separate host-path program.
    m = get_metrics()
    assert m.counter("dispatch_cluster") == len(files), (
        "expected one on-device cluster dispatch per chunk, got "
        f"{m.counter('dispatch_cluster')} for {len(files)} chunk(s)")
    collects = [(e["ts"], e["ts"] + e["dur"]) for e in spans
                if e["name"] == "collect"]
    for e in spans:
        if e["name"] != "cluster":
            continue
        inside = any(t0 - 1 <= e["ts"] and e["ts"] + e["dur"] <= t1 + 1
                     for t0, t1 in collects)
        assert inside, "cluster span escaped the collect phase"

    # Journal lines carry a per-record CRC32 suffix (PR 11); the report
    # module's lenient parser strips AND verifies it.
    from riptide_tpu.obs.report import parse_record_line

    with open(os.path.join(jdir, "journal.jsonl"), "rb") as fobj:
        records = [parse_record_line(l)
                   for l in fobj.read().splitlines() if l.strip()]
    chunks = [r for r in records if r and r.get("kind") == "chunk"]
    for rec in chunks:
        t = rec["timings"]
        serial = t["wire_s"] + t["queue_s"] + t["collect_s"] + t["host_s"]
        assert abs(serial - t["chunk_s"]) <= 0.05 * max(t["chunk_s"], 1e-9)
        # PR 19 sub-phases: reported, inside collect_s, never summed.
        assert 0.0 <= t["cluster_s"] <= t["postsearch_s"] + 1e-9
        assert t["postsearch_s"] <= t["collect_s"] + 1e-9

    with open(promfile) as fobj:
        page = fobj.read()
    assert "riptide_chunk_seconds_bucket" in page
    assert f"riptide_chunks_done_total {len(chunks)}" in page

    print(f"\ntrace demo OK: {len(peaks)} peaks from {len(chunks)} chunks")
    print(f"  spans      {len(spans):5d}  ->  {trace_path}")
    print(f"  journal            ->  {os.path.join(jdir, 'journal.jsonl')}")
    print(f"  prometheus         ->  {promfile}")
    print("open the trace at https://ui.perfetto.dev (or chrome://tracing)")


if __name__ == "__main__":
    main(*sys.argv[1:2])
