"""
Re-pin riptide_tpu/ops/kernel_digest.json for the running Python.

Run this AFTER bumping KERNEL_CACHE_VERSION (or when adding a new
Python version to CI). tests/test_kernel_cache_version.py fails when
the kernel/table-builder bytecode changes while the pinned version
stays the same — the reminder that stale cached kernel executables
compute wrong numbers, not crashes.

Usage: python tools/update_kernel_digest.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from riptide_tpu.ops.ffa_kernel import (  # noqa: E402
    KERNEL_CACHE_VERSION, kernel_code_digest,
)

PATH = os.path.join(os.path.dirname(__file__), "..", "riptide_tpu", "ops",
                    "kernel_digest.json")


def main():
    with open(PATH) as f:
        data = json.load(f)
    py = f"{sys.version_info[0]}.{sys.version_info[1]}"
    entry = {"kernel_cache_version": KERNEL_CACHE_VERSION,
             "digest": kernel_code_digest()}
    old = data["digests"].get(py)
    data["digests"][py] = entry
    with open(PATH, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    print(f"python {py}: {old} -> {entry}")


if __name__ == "__main__":
    main()
