#!/usr/bin/env python
"""
ripsched: schedule-exploration model checking of the concurrency
protocols (the DYNAMIC counterpart of riplint's RIP012-014 rules).

Loads ``riptide_tpu/analysis/sched.py`` standalone (no jax, no package
import) and explores the thread interleavings of four models built
from the repo's REAL protocol code — the FairShareQueue + drain, the
TenantTable charge path, the _StagingPool release discipline, the
runctx context-inheritance layer, and a mirrored integrity-quarantine
latch — under iterative preemption bounding. Every decision sequence
is a replayable schedule ID; any invariant violation prints the
minimal failing schedule and its ``--replay`` repro line.

The pinned ``tools/ripsched_invariants.json`` is the machine-readable
statement of what this gate proves (models, invariants, mutations).
The CLI refuses to run while it drifts from the registry in sched.py —
re-pin a DELIBERATE change with ``--write-specs`` and commit the diff
(the ``kernel_digest.json`` workflow); the riplint cache tracks the
file, so a drift also invalidates cached lint results.

Exit status 0 when every explored schedule of every selected model
holds all invariants; 1 on any violation (or a ``--replay`` that
reproduces one); 2 on usage errors, spec drift or a replay whose
digits no longer match the model (divergence).

``--mutate NAME`` re-arms a known-bad code shape and EXPECTS the
violation (the non-vacuity check used by `make ripsched-demo` and the
seeded-regression tests). ``--format sarif`` reuses riplint's SARIF
2.1.0 writer so riplint, rprove and ripsched publish one result
format (`make analyze` merges all three).

Flag defaults come from the typed envflags registry:
``RIPTIDE_SCHED_BOUND`` (preemption bound), ``RIPTIDE_SCHED_SEED``
(exploration-order seed) and ``RIPTIDE_SCHED_REPLAY`` (schedule ID to
replay instead of exploring).
"""
import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DEFAULT_SPECS = os.path.join(REPO, "tools", "ripsched_invariants.json")
SPECS_REL = "tools/ripsched_invariants.json"


def load_sched():
    """riptide_tpu/analysis/sched.py loaded by file path — no jax, no
    riptide_tpu/__init__ (riplint's standalone-loading idiom)."""
    name = "ripsched_sched_standalone"
    if name in sys.modules:
        return sys.modules[name]
    path = os.path.join(REPO, "riptide_tpu", "analysis", "sched.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[name]
        raise
    return mod


def load_riplint():
    """tools/riplint.py loaded by file path — ripsched reuses its
    SARIF writer so all three analyzers publish one result format."""
    name = "riplint_for_ripsched"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(HERE, "riplint.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[name]
        raise
    return mod


class _Rule:
    """SARIF rule-metadata shim matching the Analyzer attributes
    riplint's writer reads."""

    def __init__(self, rule, name, description):
        self.rule = rule
        self.name = name
        self.description = description


def _sarif_findings(sched_mod, violations):
    out = []
    for vio in violations:
        path = sched_mod.MODELS[vio.model].targets[0]
        out.append({
            "rule": sched_mod.sarif_rule_of(vio.invariant),
            "path": path,
            "line": 1,
            "col": 0,
            "message": (
                f"[{vio.invariant}] {vio.message} — replay with "
                f"`python tools/ripsched.py --replay "
                f"'{vio.schedule_id}'`"),
        })
    return out


def _emit_sarif(sched_mod, violations, out):
    riplint = load_riplint()
    doc = riplint._sarif_doc(
        {"new": _sarif_findings(sched_mod, violations), "stale": []},
        [_Rule(*r) for r in sched_mod.SARIF_RULES], tool="ripsched")
    json.dump(doc, out, indent=2)
    out.write("\n")


def check_specs(sched_mod, specs_path, err):
    """0 when the pinned invariant spec matches the registry; 2 (with
    the re-pin instruction) on drift or a missing file."""
    want = sched_mod.spec_doc()
    if not os.path.exists(specs_path):
        print(f"ripsched: no invariant spec at {specs_path!r}; run "
              "`python tools/ripsched.py --write-specs` and commit it",
              file=err)
        return 2
    try:
        with open(specs_path) as fobj:
            have = json.load(fobj)
    except (OSError, ValueError) as exc:
        print(f"ripsched: unreadable invariant spec {specs_path!r}: "
              f"{exc}", file=err)
        return 2
    if have != want:
        print(f"ripsched: {SPECS_REL} drifted from the model registry "
              "in riptide_tpu/analysis/sched.py — after a DELIBERATE "
              "model/invariant change, re-pin with `python "
              "tools/ripsched.py --write-specs` and commit the diff",
              file=err)
        return 2
    return 0


def write_specs(sched_mod, specs_path, err):
    with open(specs_path, "w") as fobj:
        json.dump(sched_mod.spec_doc(), fobj, indent=1, sort_keys=True)
        fobj.write("\n")
    doc = sched_mod.spec_doc()
    n_inv = sum(len(m["invariants"]) for m in doc["models"].values())
    print(f"pinned {len(doc['models'])} model(s) / {n_inv} "
          f"invariant(s) to {os.path.relpath(specs_path, REPO)}",
          file=err)
    return 0


def _list_models(sched_mod, out):
    for name, spec in sorted(sched_mod.MODELS.items()):
        print(f"{name}: {spec.description}", file=out)
        print(f"  targets: {', '.join(spec.targets)}", file=out)
        for inv, desc in spec.invariants:
            print(f"  invariant {inv} "
                  f"[{sched_mod.sarif_rule_of(inv)}]: {desc}", file=out)
        for mut, desc in sorted(spec.mutations.items()):
            print(f"  mutation {mut}: {desc}", file=out)
    return 0


def run(models=None, mutation=None, bound=None, seed=None,
        replay_id=None, max_schedules=None, fmt="text",
        specs_path=DEFAULT_SPECS, do_write_specs=False, list_only=False,
        out=sys.stdout, err=sys.stderr):
    """Explore (or replay / pin / list), emit, return the exit code."""
    sched_mod = load_sched()
    if do_write_specs:
        return write_specs(sched_mod, specs_path, err)
    if list_only:
        return _list_models(sched_mod, out)
    rc = check_specs(sched_mod, specs_path, err)
    if rc:
        return rc

    if replay_id is None:
        replay_id = sched_mod.env_default("RIPTIDE_SCHED_REPLAY")
    if replay_id:
        try:
            res = sched_mod.replay(replay_id)
        except ValueError as exc:
            print(f"ripsched: {exc}", file=err)
            return 2
        print(res.render(), file=out)
        if res.diverged is not None:
            return 2
        return 1 if res.violation is not None else 0

    if models is None:
        names = sorted(sched_mod.MODELS)
    else:
        names = models
        for name in names:
            if name not in sched_mod.MODELS:
                print(f"ripsched: unknown model {name!r} (known: "
                      f"{sorted(sched_mod.MODELS)})", file=err)
                return 2
    if mutation is not None and len(names) != 1:
        print("ripsched: --mutate needs exactly one --model", file=err)
        return 2

    violations = []
    total_schedules = total_decisions = 0
    capped = []
    for name in names:
        try:
            res = sched_mod.explore_model(
                name, mutation=mutation, bound=bound, seed=seed,
                max_schedules=max_schedules,
                log=lambda msg: print(msg, file=err))
        except ValueError as exc:
            print(f"ripsched: {exc}", file=err)
            return 2
        total_schedules += res.schedules
        total_decisions += res.decisions
        if res.capped:
            capped.append(name)
        if res.violation is not None:
            violations.append(res.violation)

    if fmt == "sarif":
        _emit_sarif(sched_mod, violations, out)
    else:
        for vio in violations:
            print(vio.render(), file=out)

    tag = "+".join(filter(None, [",".join(names), mutation]))
    if violations:
        print(f"ripsched: {len(violations)} invariant violation(s) in "
              f"{tag} ({total_schedules} schedule(s) explored)",
              file=err)
        return 1
    note = f" (CAPPED: {', '.join(capped)})" if capped else ""
    print(f"ripsched OK: {tag}: {total_schedules} schedule(s) / "
          f"{total_decisions} decision(s) explored to the preemption "
          f"bound, zero violations{note}", file=err)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ripsched", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--model", action="append", dest="models",
                    metavar="NAME",
                    help="model(s) to explore (repeatable; default: "
                         "all). See --list.")
    ap.add_argument("--mutate", default=None, metavar="NAME",
                    help="re-arm a named known-bad mutation in the "
                         "selected model (requires exactly one "
                         "--model); the run is EXPECTED to exit 1 "
                         "with a minimal schedule")
    ap.add_argument("--bound", type=int, default=None,
                    help="preemption bound (default: "
                         "RIPTIDE_SCHED_BOUND)")
    ap.add_argument("--seed", type=int, default=None,
                    help="exploration-order seed (default: "
                         "RIPTIDE_SCHED_SEED); replay never depends "
                         "on it")
    ap.add_argument("--replay", default=None, metavar="ID",
                    help="re-execute one recorded schedule ID "
                         "deterministically instead of exploring "
                         "(default: RIPTIDE_SCHED_REPLAY)")
    ap.add_argument("--max-schedules", type=int, default=None,
                    help="schedule cap per (model, mutation); hitting "
                         "it is reported, never silent (0 = unlimited)")
    ap.add_argument("--format", choices=("text", "sarif"),
                    default="text", dest="fmt",
                    help="output format: human text (default) or one "
                         "SARIF 2.1.0 run (riplint's writer)")
    ap.add_argument("--specs", default=DEFAULT_SPECS,
                    help="pinned invariant spec (default "
                         f"{SPECS_REL})")
    ap.add_argument("--write-specs", action="store_true",
                    help="re-pin the invariant spec from the model "
                         "registry (commit the diff)")
    ap.add_argument("--list", action="store_true", dest="list_only",
                    help="list models, invariants and mutations")
    args = ap.parse_args(argv)
    return run(models=args.models, mutation=args.mutate,
               bound=args.bound, seed=args.seed, replay_id=args.replay,
               max_schedules=args.max_schedules, fmt=args.fmt,
               specs_path=args.specs, do_write_specs=args.write_specs,
               list_only=args.list_only)


if __name__ == "__main__":
    sys.exit(main())
