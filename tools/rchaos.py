#!/usr/bin/env python
"""
rchaos: the seeded storage-chaos campaign CLI (``make chaos``).

Generates a tiny deterministic CPU survey, then runs every chaos
schedule from :mod:`riptide_tpu.survey.chaos` — subprocess legs that
are killed mid-write at journal/ledger/cache boundaries, restarted
with resume, and degraded with ENOSPC/fsync/torn-write faults on the
observability paths — asserting after each schedule:

* byte-identical ``peaks.csv`` vs the fault-free control run;
* a consistent resumed journal (one record per chunk, no torn/corrupt
  lines, phase sums within tolerance, no orphaned peak rows);
* a perf-ledger row for the completed run;
* an incident record per injected fault and zero uncaught exceptions;
* control-run byte transparency (recovery/report passes change no
  bytes; ledger rows stay plain JSON).

Usage::

    python tools/rchaos.py [--outdir DIR] [--sweep N] [--seed S]
        [--keep] [--list]

``--sweep N`` appends N seeded schedules to the fixed builtin set
(defaults: RIPTIDE_CHAOS_SWEEP / RIPTIDE_CHAOS_SEED; the slow test
tier runs a sweep too). Exit 0 on a clean campaign, 1 on any violated
invariant (the working directory is kept for post-mortem).
"""
import argparse
import os
import shutil
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))
sys.path.insert(0, os.path.join(HERE, "..", "tests"))


def main(argv=None):
    from synth import generate_data_presto

    from riptide_tpu.survey import chaos
    from riptide_tpu.utils import envflags

    parser = argparse.ArgumentParser(
        description="storage-chaos campaign over the survey scheduler")
    parser.add_argument("--outdir", default=None,
                        help="campaign working directory (default "
                             "RIPTIDE_CHAOS_DIR or a fixed tempdir)")
    parser.add_argument("--sweep", type=int, default=None,
                        help="extra seeded schedules beyond the builtin "
                             "set (default RIPTIDE_CHAOS_SWEEP)")
    parser.add_argument("--seed", type=int, default=None,
                        help="sweep seed (default RIPTIDE_CHAOS_SEED)")
    parser.add_argument("--keep", action="store_true",
                        help="keep the working directory on success too")
    parser.add_argument("--list", action="store_true",
                        help="print the schedule set and exit")
    args = parser.parse_args(argv)

    seed = args.seed if args.seed is not None \
        else envflags.get("RIPTIDE_CHAOS_SEED")
    sweep = args.sweep if args.sweep is not None \
        else envflags.get("RIPTIDE_CHAOS_SWEEP")
    schedules = chaos.builtin_schedules() + chaos.seeded_schedules(seed,
                                                                   sweep)
    if args.list:
        for s in schedules:
            faults = " | ".join(leg.get("faults") or "-"
                                for leg in s["legs"])
            print(f"{s['name']:<24} {len(s['legs'])} leg(s)  {faults}")
        return 0

    outdir = args.outdir or chaos.default_workdir()
    keep = args.keep or chaos.default_keep()
    datadir = os.path.join(outdir, "data")
    shutil.rmtree(outdir, ignore_errors=True)
    os.makedirs(datadir)
    files = [
        generate_data_presto(datadir, f"chaos_DM{dm:.2f}",
                             tobs=chaos.TOBS, tsamp=chaos.TSAMP,
                             period=chaos.PERIOD, dm=dm,
                             amplitude=chaos.AMPLITUDE)
        for dm in chaos.DMS
    ]

    t0 = time.monotonic()
    try:
        summary = chaos.run_campaign(files, outdir, schedules=schedules)
    except chaos.ChaosFailure as err:
        print(f"\nchaos campaign FAILED: {err}", file=sys.stderr)
        print(f"  artifacts kept under {outdir}", file=sys.stderr)
        return 1
    elapsed = time.monotonic() - t0
    print(f"\nchaos campaign OK: {summary['schedules']} schedule(s), "
          f"{summary['legs']} leg(s) in {elapsed:.1f}s")
    print("  every schedule ended byte-identical to the fault-free "
          "control run,\n  with a consistent resumed journal, a ledger "
          "row, and an incident per fault")
    if keep:
        print(f"  artifacts kept under {outdir}")
    else:
        shutil.rmtree(outdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
