"""Pallas op throughput with in-kernel fori_loop repetition.

One dispatch = NITER passes of the op, so tunnel latency/noise (~0.4 s
per roundtrip) is amortized away. Reports per-pass time and Gelem/s.
"""
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from riptide_tpu.utils.compat import pallas_compiler_params


def build(body_fn, shape, niter):
    def kern(x_ref, o_ref):
        def step(i, acc):
            return body_fn(i, acc, shape)
        o_ref[:] = jax.lax.fori_loop(0, niter, step, x_ref[:])

    return jax.jit(pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
        compiler_params=pallas_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
    ))


def body_add(i, acc, shape):
    return acc * 1.0001 + 0.5


def body_roll(i, acc, shape):
    return acc + pltpu.roll(acc, 1, axis=1) * 1e-6


def body_rollrow(i, acc, shape):
    return acc + pltpu.roll(acc, 1, axis=0) * 1e-6


def body_dynroll(i, acc, shape):
    return acc + pltpu.roll(acc, i % shape[1], axis=1) * 1e-6


def body_select(i, acc, shape):
    cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return jnp.where(cols < i % shape[1], acc * 1.0001, acc)


def body_barrelbit(i, acc, shape):
    # one masked-roll barrel step with a data-ish mask
    cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    rolled = pltpu.roll(acc, 4, axis=1)
    return jnp.where((cols & 3) == (i & 3), rolled, acc)


BODIES = {
    "add": body_add,
    "roll1": body_roll,
    "rollrow": body_rollrow,
    "dynroll": body_dynroll,
    "select": body_select,
    "barrelbit": body_barrelbit,
}


def measure(name, shape, niter):
    fn = build(BODIES[name], shape, niter)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 1e-3)
    # NOTE: block_until_ready does NOT synchronize under the axon tunnel;
    # only a real device->host fetch does. Fetch one element each time.
    float(np.asarray(fn(x)[0, 0]))
    t0 = time.perf_counter()
    float(np.asarray(fn(x)[0, 0]))
    dt = time.perf_counter() - t0
    per = dt / niter
    gel = shape[0] * shape[1] / per / 1e9
    print(f"{name:10s} {shape[0]:5d}x{shape[1]:<4d}: {per*1e6:9.2f} us/pass"
          f"  {gel:8.1f} Gelem/s  (call {dt*1e3:.0f} ms)")


def main():
    niter = int(os.environ.get("NITER", "20000"))
    names = sys.argv[1:] or ["add", "roll1", "dynroll", "select"]
    for name in names:
        for shape in [(2048, 384), (1024, 384), (2048, 128), (256, 384)]:
            measure(name, shape, niter)


if __name__ == "__main__":
    main()
