"""Survey phase timing: where does a pipelined chunk's wall time go?

Replicates bench.py's timed pipeline at the headline shape but records,
per iteration, the MAIN-THREAD blocking time of each phase:

  prep    host wire preparation (runs on the worker thread; reported
          as its own wall time, not main-thread time)
  ship    ship_stage_data call (device_put of the wire buffer): if the
          tunnel's transfer API blocks, this shows the full wire time
  queue   queue_search_batch (dispatch enqueue of ~45 device programs)
  collect collect_search_batch (sync: waits for the device + one pull)

Also runs two microbenches first:
  wire    raw device_put of a wire-sized buffer, 3x (today's tunnel rate)
  rtt     tiny device_put + pull roundtrip, 5x (today's tunnel latency)

The LAST stdout line is a machine-readable JSON block with the same
dtime-style decomposition bench.py emits (device_s / prep_s /
wire_MBps / chunk_s plus trials_per_sec and the dispatch_* counters),
so driver logs capture where a round's time went even when only the
tail survives.

Usage: python tools/stime.py [D] [CHUNKS]
"""
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = 1 << 23
TSAMP = 64e-6
PKW = dict(smin=7.0, segwidth=5.0, nstd=6.0, minseg=10, polydeg=2, clrad=0.1)


def main(D=32, CHUNKS=4):
    from bench import _make_batch
    from riptide_tpu.ffautils import generate_width_trials
    from riptide_tpu.search import periodogram_plan
    from riptide_tpu.search.engine import (
        collect_search_batch, prepare_stage_data, queue_search_batch,
        ship_stage_data, warm_stage_kernels, _wire_layout, _wire_mode,
        _ffa_path,
    )

    widths = tuple(int(w) for w in generate_width_trials(240))
    plan = periodogram_plan(N, TSAMP, widths, 0.5, 3.0, 240, 260)
    tobs = N * TSAMP

    mode = _wire_mode(_ffa_path())
    _, _, tot = _wire_layout(plan, mode)
    print(f"wire mode {mode}: {tot * D / 1e6:.1f} MB per {D}-trial chunk",
          flush=True)

    # --- microbench: raw tunnel rate + latency ---
    rng = np.random.default_rng(0)
    buf = rng.integers(0, 255, (D, tot), dtype=np.uint8)
    for k in range(3):
        t0 = time.perf_counter()
        dev = jnp.asarray(buf)
        t1 = time.perf_counter()
        _ = np.asarray(dev[0, :8])  # force completion
        t2 = time.perf_counter()
        print(f"  device_put {buf.nbytes/1e6:.0f} MB: call {t1-t0:.2f}s, "
              f"complete {t2-t0:.2f}s -> {buf.nbytes/1e6/(t2-t0):.1f} MB/s",
              flush=True)
    tiny = np.zeros(8, np.float32)
    for k in range(5):
        t0 = time.perf_counter()
        _ = np.asarray(jnp.asarray(tiny)[:1])
        print(f"  rtt: {time.perf_counter()-t0:.3f}s", flush=True)

    t0 = time.perf_counter()
    nw = warm_stage_kernels(plan, D)
    print(f"kernel warm ({nw}): {time.perf_counter()-t0:.1f}s", flush=True)

    batches = [_make_batch(D, N, TSAMP, seed=k) for k in range(2)]
    dms = np.zeros(D)

    # Warmup pass (compiles engine programs / loads exec cache)
    t0 = time.perf_counter()
    h = queue_search_batch(plan, batches[0], tobs=tobs, **PKW)
    collect_search_batch(h, dms)
    print(f"warmup pass: {time.perf_counter()-t0:.1f}s", flush=True)

    # Metrics window covering exactly the timed loop below, so the
    # closing JSON block decomposes the steady-state chunks only.
    from riptide_tpu.survey.metrics import get_metrics

    metrics = get_metrics()
    metrics.reset()

    with ThreadPoolExecutor(max_workers=1) as ex:
        def prep(i):
            t0 = time.perf_counter()
            r = prepare_stage_data(plan, batches[i % 2])
            return r, time.perf_counter() - t0

        fut = ex.submit(prep, 0)
        prepared, tprep = fut.result()
        t0 = time.perf_counter()
        shipped = ship_stage_data(plan, prepared)
        tship = time.perf_counter() - t0
        print(f"fill: prep {tprep:.2f}s ship {tship:.2f}s", flush=True)
        fut = ex.submit(prep, 1)

        pending = None
        tstart = time.perf_counter()
        for i in range(CHUNKS):
            it0 = time.perf_counter()
            t0 = time.perf_counter()
            handle = queue_search_batch(plan, None, tobs=tobs,
                                        shipped=shipped, **PKW)
            tqueue = time.perf_counter() - t0
            tship = tprep_i = twait = 0.0
            if i + 1 < CHUNKS:
                t0 = time.perf_counter()
                prepared, tprep_i = fut.result()
                twait = time.perf_counter() - t0
                t0 = time.perf_counter()
                shipped = ship_stage_data(plan, prepared)
                tship = time.perf_counter() - t0
                if i + 2 < CHUNKS:
                    fut = ex.submit(prep, i + 2)
            tcollect = 0.0
            if pending is not None:
                t0 = time.perf_counter()
                collect_search_batch(pending, dms)
                tcollect = time.perf_counter() - t0
            pending = handle
            print(f"iter {i}: queue {tqueue:.2f}s  prep-wait {twait:.2f}s "
                  f"(prep {tprep_i:.2f}s)  ship {tship:.2f}s  "
                  f"collect {tcollect:.2f}s  total "
                  f"{time.perf_counter()-it0:.2f}s", flush=True)
        t0 = time.perf_counter()
        collect_search_batch(pending, dms)
        print(f"final collect: {time.perf_counter()-t0:.2f}s", flush=True)
        dt = time.perf_counter() - tstart
        print(f"steady: {CHUNKS} chunks in {dt:.2f}s = "
              f"{D*CHUNKS/dt:.2f} trials/s", flush=True)
        # The decomposition keys come from the ONE timing schema shared
        # with bench.py's best line and the survey journal
        # (riptide_tpu.obs.schema), so all three surfaces stay
        # key-identical for log parsers.
        from riptide_tpu.obs.schema import decomposition

        s = metrics.summary()
        block = {
            "metric": "stime_decomposition",
            "trials_per_sec": round(D * CHUNKS / dt, 3),
        }
        sub = decomposition(s, CHUNKS, dt)
        block.update(sub)
        block.update({k: v for k, v in s.items()
                      if k.startswith("dispatch_")})
        print(json.dumps(block), flush=True)

        # One perf-ledger row per stime run (no-op unless RIPTIDE_LEDGER
        # is set) — stime has no per-chunk timing records, so the
        # run-level bound classification stands in for the counts.
        from riptide_tpu.obs import ledger
        from riptide_tpu.obs.schema import classify_bound

        bound = classify_bound(sub.get("wire_s") or 0.0,
                               sub.get("device_s") or 0.0)
        ledger.maybe_append(
            "stime", sub, nchunks=CHUNKS, bound_counts={bound: CHUNKS},
            extra={"metric": "stime_decomposition",
                   "trials_per_sec": round(D * CHUNKS / dt, 3)},
        )


if __name__ == "__main__":
    D = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    CH = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    main(D, CH)
