"""Cold-start experiments on the real TPU:

1. Can a compiled Pallas executable be serialized with
   jax.experimental.serialize_executable and reloaded (in THIS process)?
   (Cross-process reload is tested by running the script twice: pass
   `load` to skip compilation and deserialize from disk.)
2. Do two Mosaic compiles overlap when issued from two Python threads?

Usage: python tools/coldstart_exp.py [load]
"""
import os
import pickle
import sys
import time

import numpy as np

CACHE = "/tmp/riptide_exec_cache"


def small_kernel(bins):
    from riptide_tpu.ops.ffa_kernel import CycleKernel
    from riptide_tpu.ops.snr import boxcar_coeffs

    ms = [121, 118]
    ps = [bins, bins + 4]
    widths = (1, 2, 3)
    h = np.zeros((2, 3), np.float32)
    b = np.zeros((2, 3), np.float32)
    for i, p in enumerate(ps):
        h[i], b[i] = boxcar_coeffs(p, widths)
    k = CycleKernel(ms, ps, widths, h, b, np.ones(2, np.float32))
    x = np.random.default_rng(0).standard_normal(
        (2, k.rows, k.P)).astype(np.float32)
    return k, x


def main():
    import jax
    from jax.experimental import serialize_executable as se

    os.makedirs(CACHE, exist_ok=True)
    path = os.path.join(CACHE, "k64.pkl")

    k, x = small_kernel(64)
    scal, coef, wrep = k._operands()
    from riptide_tpu.ops.ffa_kernel import _build_call

    call = _build_call(k.L, k.NL, k.rows, k.P, k.RS, k.widths, k.nspread,
                       k.pbits, 1, k.B, False)
    args = (scal, coef, x[None], wrep)

    if "load" in sys.argv[1:]:
        t0 = time.perf_counter()
        with open(path, "rb") as f:
            payload, in_tree, out_tree = pickle.load(f)
        loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        print(f"deserialize: {time.perf_counter()-t0:.1f}s", flush=True)
        t0 = time.perf_counter()
        out = loaded(*args)
        v = float(np.asarray(out)[0, 0, 0, 0])
        print(f"run-from-cache: {time.perf_counter()-t0:.1f}s val={v:.3f}",
              flush=True)
        return

    t0 = time.perf_counter()
    lowered = jax.jit(call).lower(*args)
    compiled = lowered.compile()
    print(f"compile: {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    payload, in_tree, out_tree = se.serialize(compiled)
    with open(path, "wb") as f:
        pickle.dump((payload, in_tree, out_tree), f)
    print(f"serialize: {time.perf_counter()-t0:.1f}s "
          f"({os.path.getsize(path)/1e6:.1f} MB)", flush=True)
    t0 = time.perf_counter()
    out = compiled(*args)
    v = float(np.asarray(out)[0, 0, 0, 0])
    print(f"run: {time.perf_counter()-t0:.1f}s val={v:.3f}", flush=True)

    # same-process reload sanity
    with open(path, "rb") as f:
        payload, in_tree, out_tree = pickle.load(f)
    loaded = se.deserialize_and_load(payload, in_tree, out_tree)
    v2 = float(np.asarray(loaded(*args))[0, 0, 0, 0])
    assert v2 == v, (v, v2)
    print("same-process reload OK", flush=True)

    # experiment 2: threaded compile overlap (two DISTINCT kernels)
    import threading

    k2, x2 = small_kernel(96)
    k3, x3 = small_kernel(128)

    def compile_one(kk, xx):
        t0 = time.perf_counter()
        float(np.asarray(kk(xx)[0, 0, 0]))
        return time.perf_counter() - t0

    t0 = time.perf_counter()
    ts = []
    res = {}
    for name, (kk, xx) in {"A": (k2, x2), "B": (k3, x3)}.items():
        th = threading.Thread(
            target=lambda n=name, kk=kk, xx=xx: res.update({n: compile_one(kk, xx)})
        )
        th.start()
        ts.append(th)
    for th in ts:
        th.join()
    wall = time.perf_counter() - t0
    print(f"threaded 2-compile wall: {wall:.1f}s, individual: {res}",
          flush=True)


if __name__ == "__main__":
    main()
