// Standalone timing harness for the reference C++ periodogram engine.
// Includes the read-only reference headers; used only to measure the
// single-core CPU baseline that bench.py compares against.
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

#include "periodogram.hpp"

int main(int argc, char** argv) {
    size_t n = argc > 1 ? strtoul(argv[1], nullptr, 10) : (1UL << 23);
    int loops = argc > 2 ? atoi(argv[2]) : 3;
    double tsamp = 64e-6, pmin = 0.5, pmax = 3.0;
    size_t bmin = 240, bmax = 260;
    std::vector<size_t> widths = {1, 2, 3, 4, 6, 9, 13, 19, 28, 42};

    std::mt19937 rng(0);
    std::normal_distribution<float> gauss(0.0f, 1.0f);
    std::vector<float> data(n);
    for (auto& x : data) x = gauss(rng);

    size_t len = riptide::periodogram_length(n, tsamp, pmin, pmax, bmin, bmax);
    std::vector<double> periods(len);
    std::vector<uint32_t> foldbins(len);
    std::vector<float> snr(len * widths.size());

    double best = 1e30;
    for (int i = 0; i < loops; ++i) {
        auto t0 = std::chrono::steady_clock::now();
        riptide::periodogram(data.data(), n, tsamp, widths.data(), widths.size(),
                             pmin, pmax, bmin, bmax,
                             periods.data(), foldbins.data(), snr.data());
        auto t1 = std::chrono::steady_clock::now();
        double dt = std::chrono::duration<double>(t1 - t0).count();
        if (dt < best) best = dt;
        fprintf(stderr, "loop %d: %.3f s\n", i, dt);
    }
    printf("{\"n\": %zu, \"trials\": %zu, \"seconds_per_dm_trial\": %.4f}\n",
           n, len, best);
    return 0;
}
