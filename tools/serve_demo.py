#!/usr/bin/env python
"""
serve-demo: end-to-end acceptance of the survey service (PR 16) — the
warm, multi-tenant rserve daemon proven live on the CPU backend.

Four legs:

1. **batch controls** — the demo's two input sets run through the
   ordinary in-process :class:`SurveyScheduler`; their ``peaks.csv``
   bytes are the references every service job is compared against.
2. **concurrent + warm service** — one in-process
   :class:`ServeDaemon`: two jobs from two tenants submitted
   back-to-back over real loopback HTTP run CONCURRENTLY through the
   fair-share chunk gate, and each job's served CSV must be
   byte-identical to its batch control. Then a third job repeating the
   first's plan geometry must run with the ``exec_cold_builds``
   counter FLAT (zero recompiles — the warm-executable pin), report
   ``warm_start`` in its job document, and reproduce the control bytes
   a third time. The ``rtop`` serve frame and ``rreport``'s job table
   render the registry.
3. **kill/restart recovery** — a ``tools/rserve.py`` SUBPROCESS with a
   kill fault injected at a journal append boundary
   (``RIPTIDE_FAULT_INJECT=kill_at:journal_append:3``) dies with exit
   137 mid-job; a clean restart on the same root replays
   ``jobs.jsonl``, re-queues the job (``resumed`` flagged), resumes
   its survey journal and serves a ``peaks.csv`` byte-identical to the
   control — the durability contract of docs/survey_service.md.
4. **graceful drain (PR 17)** — a fresh rserve subprocess gets SIGTERM
   while a job is mid-survey (a ``stall`` spec fault holds a chunk
   open long enough to land the signal deterministically): the daemon
   must finish the in-flight chunk, park the job WITHOUT a terminal
   registry record and exit **0**; the restart re-queues it
   (``resumed``) and serves a byte-identical ``peaks.csv``.
5. **result-integrity containment (PR 18)** — one daemon, two
   concurrent tenants: a job with a persistent ``bitflip`` fault and
   ``integrity: probe`` must FAIL with ``integrity_quarantine`` (the
   serve-side quarantine policy fails only the implicated job), with
   the mismatch/quarantine incidents contained to its own journal,
   while the concurrent clean job completes byte-identical to its
   batch control; a malformed integrity spec 400s at admission.

Output directory: /tmp/riptide_serve_demo (or argv[1]). ``make
serve-demo`` runs this; it is wired into ``make check-full``.
"""
import json
import os
import shutil
import subprocess
import sys
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Every leg (and the rserve subprocess) compiles the same tiny search
# plan; the persistent cache keeps all but the first to ~import cost.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/riptide_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.normpath(os.path.join(HERE, ".."))
sys.path.insert(0, os.path.join(ROOT, "tests"))
sys.path.insert(0, ROOT)
sys.path.insert(0, HERE)

TOBS, TSAMP, PERIOD = 12.0, 1e-3, 0.5
DMS_A = (0.0, 5.0, 10.0)
DMS_B = (2.0, 7.0, 12.0)

SEARCH_CONF = [{
    "ffa_search": {"period_min": 0.3, "period_max": 1.2,
                   "bins_min": 64, "bins_max": 71},
    "find_peaks": {"smin": 6.0},
}]
DEREDDEN = {"rmed_width": 4.0, "rmed_minpts": 101}


def _req(base, path, method="GET", body=None, timeout=10.0):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        base + path, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def _req_json(base, path, method="GET", body=None):
    code, raw = _req(base, path, method=method, body=body)
    return code, json.loads(raw)


def _spec(files, tenant):
    return {"files": list(files), "fmt": "presto", "tenant": tenant,
            "deredden": dict(DEREDDEN), "search": SEARCH_CONF}


def _wait_terminal(base, jid, timeout_s=300.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        code, doc = _req_json(base, f"/jobs/{jid}")
        assert code == 200, doc
        if doc.get("status") in ("done", "failed", "cancelled"):
            return doc
        time.sleep(0.1)
    raise AssertionError(f"{jid} did not finish within {timeout_s}s")


def _batch_control(files, jdir, csv_path):
    from riptide_tpu.pipeline.batcher import BatchSearcher
    from riptide_tpu.serve.daemon import write_peaks_csv
    from riptide_tpu.survey.journal import SurveyJournal
    from riptide_tpu.survey.scheduler import SurveyScheduler

    searcher = BatchSearcher(dict(DEREDDEN), SEARCH_CONF, fmt="presto",
                             io_threads=1)
    scheduler = SurveyScheduler(searcher, [[f] for f in files],
                                journal=SurveyJournal(jdir))
    peaks = scheduler.run()
    write_peaks_csv(peaks, csv_path)
    with open(csv_path, "rb") as fobj:
        return fobj.read()


def _chunk_count(journal_path):
    from riptide_tpu.utils import fsio

    entries, _ = fsio.scan_jsonl(journal_path)
    return sum(1 for obj, _status, _off in entries
               if obj and obj.get("kind") == "chunk")


def _journal_incidents(root, jid):
    """Incident kinds journaled into ONE job's own survey journal —
    the containment check's evidence (integrity incidents must appear
    in the implicated job's journal and nowhere else)."""
    from riptide_tpu.utils import fsio

    path = os.path.join(root, "jobs", jid, "journal.jsonl")
    entries, _ = fsio.scan_jsonl(path)
    return [obj.get("incident") for obj, _status, _off in entries
            if isinstance(obj, dict) and obj.get("kind") == "incident"]


def _fold_registry(root):
    """``{job_id: state}`` folded straight from a serve root's
    ``jobs.jsonl`` (for asserting registry state with no daemon up)."""
    from riptide_tpu.serve.daemon import fold_job_events
    from riptide_tpu.utils import fsio

    entries, _ = fsio.scan_jsonl(os.path.join(root, "jobs.jsonl"))
    return fold_job_events([obj for obj, _status, _off in entries
                            if obj])


def _rserve_env(faults=None):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    for name in ("RIPTIDE_FAULT_INJECT", "RIPTIDE_PROM_PORT"):
        env.pop(name, None)
    env["JAX_PLATFORMS"] = "cpu"
    if faults:
        env["RIPTIDE_FAULT_INJECT"] = faults
    return env


def _start_rserve(root, faults=None, timeout_s=120.0):
    """``(proc, base_url)`` of a tools/rserve.py subprocess, discovered
    through the root's ``serve.port`` file (removed first so a restart
    cannot read the PREVIOUS daemon's port)."""
    port_file = os.path.join(root, "serve.port")
    if os.path.exists(port_file):
        os.remove(port_file)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(HERE, "rserve.py"),
         "--root", root, "--port", "0", "--workers", "1"],
        env=_rserve_env(faults), cwd=ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(port_file):
            port = int(open(port_file).read().strip())
            return proc, f"http://127.0.0.1:{port}"
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise AssertionError(
                f"rserve exited {proc.returncode} before binding:\n"
                + "\n".join(out.splitlines()[-20:]))
        time.sleep(0.05)
    proc.kill()
    raise AssertionError("rserve never published serve.port")


def main(outdir="/tmp/riptide_serve_demo"):
    from synth import generate_data_presto

    import rreport
    import rtop
    from riptide_tpu.serve import ServeDaemon
    from riptide_tpu.survey.metrics import get_metrics

    shutil.rmtree(outdir, ignore_errors=True)
    os.makedirs(outdir)
    files_a = [
        generate_data_presto(outdir, f"a_DM{dm:.1f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm,
                             amplitude=30.0)
        for dm in DMS_A
    ]
    files_b = [
        generate_data_presto(outdir, f"b_DM{dm:.1f}", tobs=TOBS,
                             tsamp=TSAMP, period=PERIOD, dm=dm,
                             amplitude=30.0)
        for dm in DMS_B
    ]

    # -- leg 1: batch controls ----------------------------------------
    control_a = _batch_control(files_a, os.path.join(outdir, "j_ctl_a"),
                               os.path.join(outdir, "control_a.csv"))
    control_b = _batch_control(files_b, os.path.join(outdir, "j_ctl_b"),
                               os.path.join(outdir, "control_b.csv"))
    print(f"controls OK: {len(control_a)} / {len(control_b)} bytes of "
          "batch peaks.csv")

    # -- leg 2: concurrent + warm service -----------------------------
    serve1 = os.path.join(outdir, "serve1")
    daemon = ServeDaemon(serve1, port=0, workers=2).start()
    base = f"http://127.0.0.1:{daemon.port}"
    try:
        code, doc_a = _req_json(base, "/jobs", "POST",
                                _spec(files_a, "alice"))
        assert code == 202, doc_a
        code, doc_b = _req_json(base, "/jobs", "POST",
                                _spec(files_b, "bob"))
        assert code == 202, doc_b
        jid_a, jid_b = doc_a["job_id"], doc_b["job_id"]
        done_a = _wait_terminal(base, jid_a)
        done_b = _wait_terminal(base, jid_b)
        assert done_a["status"] == "done", done_a.get("error")
        assert done_b["status"] == "done", done_b.get("error")
        assert _req(base, f"/jobs/{jid_a}/peaks")[1] == control_a, \
            "service job A diverged from its batch control"
        assert _req(base, f"/jobs/{jid_b}/peaks")[1] == control_b, \
            "service job B diverged from its batch control"

        # The warm second (here: third) job of the SAME plan geometry:
        # zero cold builds, and the job document says so.
        cold_before = get_metrics().counter("exec_cold_builds")
        code, doc_c = _req_json(base, "/jobs", "POST",
                                _spec(files_a, "alice"))
        assert code == 202, doc_c
        done_c = _wait_terminal(base, doc_c["job_id"])
        assert done_c["status"] == "done", done_c.get("error")
        cold_after = get_metrics().counter("exec_cold_builds")
        assert cold_after == cold_before, \
            f"warm repeat geometry recompiled: exec_cold_builds " \
            f"{cold_before} -> {cold_after}"
        assert done_c["warm_start"] is True, done_c
        assert _req(base, f"/jobs/{doc_c['job_id']}/peaks")[1] \
            == control_a, "warm service job diverged from control"
        code, listing = _req_json(base, "/jobs")
        pins = listing["geometry_pins"]
        assert any(p["jobs"] >= 2 for p in pins.values()), pins
        tenants = listing["tenants"]
        assert tenants["alice"]["device_s_spent"] > 0
        assert tenants["bob"]["device_s_spent"] > 0
    finally:
        daemon.stop()
    print(f"service OK: 2 concurrent jobs byte-identical to controls; "
          f"warm repeat job ran with exec_cold_builds flat "
          f"({cold_after}) and warm_start={done_c['warm_start']}")

    # The observability tools group the registry per job.
    rep_mod = rreport.load_report_module()
    frame = rtop.render_serve_frame(rep_mod, serve1)
    assert jid_a in frame and "alice" in frame, frame
    rc = rreport.main([serve1])
    assert rc == 0, f"rreport on the serve dir exited {rc}"

    # -- leg 3: kill mid-job, restart, byte-identical resume ----------
    serve2 = os.path.join(outdir, "serve2")
    proc, base = _start_rserve(serve2,
                               faults="kill_at:journal_append:3")
    code, doc = _req_json(base, "/jobs", "POST", _spec(files_a, "alice"))
    assert code == 202, doc
    jid = doc["job_id"]
    proc.wait(timeout=300)
    assert proc.returncode == 137, \
        f"kill leg exited {proc.returncode}, wanted 137 (SIGKILL path)"
    proc, base = _start_rserve(serve2)  # clean env: no fault this time
    try:
        doc = _wait_terminal(base, jid)
        assert doc["status"] == "done", doc.get("error")
        assert doc.get("resumed") is True, doc
        code, payload = _req(base, f"/jobs/{jid}/peaks")
        assert code == 200
        assert payload == control_a, \
            "restarted job's peaks.csv diverged from the batch control"
    finally:
        proc.terminate()
        proc.wait(timeout=60)
    assert proc.returncode == 0, f"rserve shutdown exited {proc.returncode}"
    print(f"recovery OK: daemon killed mid-job (exit 137), restart "
          f"resumed {jid} to byte-identical peaks.csv")

    # -- leg 4: graceful drain (SIGTERM), restart, resume -------------
    serve3 = os.path.join(outdir, "serve3")
    proc, base = _start_rserve(serve3)
    # The stall holds chunk 1's dispatch open for 2.5 s — a wide,
    # deterministic window to land SIGTERM mid-survey. On the restart
    # leg it is inert: chunk 1 is already journaled, so the directive
    # never re-fires even though the spec fault persists in the
    # registry.
    spec = _spec(files_a, "alice")
    spec["fault_inject"] = "stall:1:2.5"
    code, doc = _req_json(base, "/jobs", "POST", spec)
    assert code == 202, doc
    jid = doc["job_id"]
    jpath = os.path.join(serve3, "jobs", jid, "journal.jsonl")
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        if os.path.exists(jpath) and _chunk_count(jpath) >= 1:
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f"{jid} never journaled its first chunk")
    proc.terminate()  # SIGTERM: the graceful-drain path
    proc.wait(timeout=120)
    assert proc.returncode == 0, \
        f"drain leg exited {proc.returncode}, wanted 0 (graceful drain)"
    st = _fold_registry(serve3).get(jid, {})
    assert st.get("status") not in ("done", "failed", "cancelled"), \
        f"drained job ended terminal ({st.get('status')!r}); " \
        "drain must park it resumable"
    proc, base = _start_rserve(serve3)
    try:
        doc = _wait_terminal(base, jid)
        assert doc["status"] == "done", doc.get("error")
        assert doc.get("resumed") is True, doc
        code, payload = _req(base, f"/jobs/{jid}/peaks")
        assert code == 200
        assert payload == control_a, \
            "drained job's peaks.csv diverged from the batch control"
    finally:
        proc.terminate()
        proc.wait(timeout=60)
    assert proc.returncode == 0, f"rserve shutdown exited {proc.returncode}"
    print(f"drain OK: SIGTERM mid-job exited 0 with {jid} parked "
          "non-terminally; restart resumed it to byte-identical "
          "peaks.csv")

    # -- leg 5: result-integrity containment (PR 18) ------------------
    serve4 = os.path.join(outdir, "serve4")
    daemon = ServeDaemon(serve4, port=0, workers=2).start()
    base = f"http://127.0.0.1:{daemon.port}"
    try:
        # Job A's device cannot agree with itself: every one of chunk
        # 1's three dispatches (primary, shadow, tie-break) flips a
        # DIFFERENT result byte, so the vote cannot resolve and the
        # serve quarantine policy ("fail", never park) must end this
        # job — and only this job — as failed.
        spec_bad = _spec(files_a, "alice")
        spec_bad["fault_inject"] = "bitflip:1x3"
        spec_bad["integrity"] = {"mode": "probe", "probe_every": 1}
        code, doc_bad = _req_json(base, "/jobs", "POST", spec_bad)
        assert code == 202, doc_bad
        code, doc_ok = _req_json(base, "/jobs", "POST",
                                 _spec(files_b, "bob"))
        assert code == 202, doc_ok
        bad = _wait_terminal(base, doc_bad["job_id"])
        ok = _wait_terminal(base, doc_ok["job_id"])
        assert bad["status"] == "failed", bad
        assert "mismatch" in (bad.get("error") or ""), bad
        assert ok["status"] == "done", ok.get("error")
        assert _req(base, f"/jobs/{doc_ok['job_id']}/peaks")[1] \
            == control_b, "clean job alongside a quarantined one " \
            "diverged from its batch control"
        inc_bad = _journal_incidents(serve4, doc_bad["job_id"])
        assert "result_mismatch" in inc_bad, inc_bad
        assert "integrity_quarantine" in inc_bad, inc_bad
        inc_ok = _journal_incidents(serve4, doc_ok["job_id"])
        leaked = [k for k in inc_ok if k in (
            "result_mismatch", "integrity_quarantine", "canary_failed")]
        assert not leaked, \
            f"integrity incidents leaked into the clean job: {leaked}"
        # A typo'd integrity spec is rejected at admission, not at run.
        spec_nope = _spec(files_b, "bob")
        spec_nope["integrity"] = "sideways"
        code, err = _req_json(base, "/jobs", "POST", spec_nope)
        assert code == 400, (code, err)
    finally:
        daemon.stop()
    print("integrity OK: bitflipped job failed with integrity_quarantine "
          "contained to its own journal; concurrent clean job "
          "byte-identical to control")

    print(f"\nserve demo OK: 7 service jobs across 4 daemons")
    print(f"  serve dirs ->  {serve1}  {serve2}  {serve3}  {serve4}")
    sys.stdout.write(frame)
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:2]))
