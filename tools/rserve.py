#!/usr/bin/env python
"""
rserve: the survey-as-a-service daemon.

Starts a :class:`riptide_tpu.serve.daemon.ServeDaemon` rooted at a
serve directory and keeps it up until SIGTERM/SIGINT. Compiled
executables stay warm across jobs for the life of the process — a
second job with an already-served plan geometry starts its first
chunk with zero cold builds (the point of running a daemon at all).

Usage::

    python tools/rserve.py --root DIR [--port N] [--workers N]
        [--max-jobs N]

* ``--root`` (or ``RIPTIDE_SERVE_DIR``): the serve directory —
  ``jobs.jsonl`` registry, per-job ``jobs/<id>/`` run directories,
  ``serve.port`` discovery file.
* ``--port`` (or ``RIPTIDE_SERVE_PORT``, default 0 = ephemeral): the
  loopback HTTP port; the bound port is printed and written to
  ``<root>/serve.port`` either way.
* ``--workers``: concurrent job runners (the fair-share queue still
  grants one device turn at a time).

Submit with ``rseek --submit http://127.0.0.1:<port>`` or raw HTTP
(``POST /jobs``); see docs/survey_service.md. On restart the daemon
replays ``jobs.jsonl`` and resumes every unfinished job from its own
survey journal.

Shutdown is a graceful drain: SIGTERM/SIGINT (or ``POST /drain``)
stops admission (503), lets the chunk holding the device turn finish,
parks every other job at its chunk gate WITHOUT a terminal registry
record, and exits 0 once the workers have parked (bounded by
``RIPTIDE_SERVE_DRAIN_TIMEOUT_S``). A restarted rserve re-queues the
parked jobs (``resumed``) and they continue from their journals.
"""
import argparse
import logging
import os
import signal
import sys
import threading

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

from riptide_tpu.serve import ServeDaemon  # noqa: E402 (path setup first)
from riptide_tpu.utils import envflags  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="rserve", description="warm multi-tenant survey service")
    ap.add_argument("--root", default=None,
                    help="serve directory (default: RIPTIDE_SERVE_DIR)")
    ap.add_argument("--port", type=int, default=None,
                    help="HTTP port (default: RIPTIDE_SERVE_PORT; "
                         "0 = ephemeral)")
    ap.add_argument("--workers", type=int, default=2,
                    help="concurrent job runner threads (default 2)")
    ap.add_argument("--max-jobs", type=int, default=None,
                    help="resident pending+running job cap "
                         "(default: RIPTIDE_SERVE_MAX_JOBS)")
    args = ap.parse_args(argv)

    root = args.root or envflags.get("RIPTIDE_SERVE_DIR")
    if not root:
        ap.error("no serve directory: give --root or set "
                 "RIPTIDE_SERVE_DIR")
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    daemon = ServeDaemon(root, port=args.port, max_jobs=args.max_jobs,
                         workers=args.workers)
    daemon.start()
    print(f"rserve: listening on http://127.0.0.1:{daemon.port}/jobs "
          f"(root {daemon.root})", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.wait(timeout=0.5):
            # POST /drain initiates the same shutdown from the HTTP
            # side; fall through to the drain wait below.
            if daemon.draining:
                break
    finally:
        # Graceful drain: stop admission, let the running chunk finish,
        # park queued jobs at the chunk gate (journals resumable, no
        # terminal registry record), then tear the daemon down.
        timeout = float(envflags.get("RIPTIDE_SERVE_DRAIN_TIMEOUT_S"))
        daemon.drain(timeout=timeout)
        if not daemon.wait_drained(timeout=timeout):
            print("rserve: drain timed out; exiting with workers "
                  "still parked", flush=True)
        daemon.stop()
    print("rserve: drained, exiting", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
