#!/usr/bin/env python
"""
Static lint: every public data entry point routes through the
data-quality layer (riptide_tpu.quality).

A single NaN reaching the compute path silently poisons a whole
periodogram, so the guard discipline is structural, not optional: each
checked function must — directly, or through one local helper it
calls — invoke something from ``riptide_tpu.quality`` (a ``quality.*``
attribute call, or a name imported from the quality module). The check
is AST-based and runs in tier-1 via ``tests/test_finite_guards.py``, so
a future kernel or reader cannot silently drop the guard.

Checked entry points:

* ``riptide_tpu/ops/snr.py``: every function in ``__all__``;
* ``riptide_tpu/time_series.py``: the TimeSeries constructors and
  ``normalise``.

Exit status 0 when clean; 1 with one violation per line otherwise.
"""
import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# path (repo-relative) -> list of required-guarded function/method names
ENTRY_POINTS = {
    os.path.join("riptide_tpu", "ops", "snr.py"): [
        "boxcar_snr", "snr_batched",
    ],
    os.path.join("riptide_tpu", "time_series.py"): [
        "from_binary", "from_npy_file", "from_presto_inf", "from_sigproc",
        "from_numpy_array", "generate", "normalise",
    ],
}


def _quality_aliases(tree):
    """Names bound (anywhere in the module, including inside function
    bodies) by ``from ...quality import X [as Y]``."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[-1] == "quality":
            for a in node.names:
                aliases.add(a.asname or a.name)
    return aliases


def _called_names(fn_node):
    """Names invoked inside a function body: bare calls by name,
    attribute calls by attribute name (covers self.x / cls.x /
    quality.x)."""
    direct_quality = False
    names = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            names.add(f.id)
        elif isinstance(f, ast.Attribute):
            names.add(f.attr)
            if isinstance(f.value, ast.Name) and f.value.id == "quality":
                direct_quality = True
    return names, direct_quality


def _functions(tree):
    """{name: node} over every (async) function/method in the module.
    Later definitions win, matching runtime shadowing."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def check_module(path, required):
    """Violation strings for one module (empty list = clean)."""
    with open(path) as fobj:
        tree = ast.parse(fobj.read(), filename=path)
    aliases = _quality_aliases(tree)
    functions = _functions(tree)

    def guarded_directly(name):
        node = functions.get(name)
        if node is None:
            return False
        called, direct = _called_names(node)
        return direct or bool(called & aliases)

    violations = []
    for name in required:
        node = functions.get(name)
        if node is None:
            violations.append(f"{path}: entry point {name!r} not found "
                              "(update tools/check_finite_guards.py)")
            continue
        if guarded_directly(name):
            continue
        # One level of indirection: a local helper that is itself guarded.
        called, _ = _called_names(node)
        if any(guarded_directly(h) for h in called if h in functions):
            continue
        violations.append(
            f"{path}:{node.lineno}: {name!r} does not route through the "
            "data-quality layer (riptide_tpu.quality)"
        )
    return violations


def check(repo=REPO):
    """All violations across the configured entry points."""
    violations = []
    for rel, required in ENTRY_POINTS.items():
        violations.extend(check_module(os.path.join(repo, rel), required))
    return violations


def main():
    violations = check()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} finite-guard violation(s)", file=sys.stderr)
        return 1
    print("finite guards OK: every checked entry point routes through "
          "riptide_tpu.quality")
    return 0


if __name__ == "__main__":
    sys.exit(main())
