#!/usr/bin/env python
"""
Back-compat shim: the finite-guard lint now lives in the riplint
framework (``riptide_tpu/analysis/finite_guards.py``, rule RIP006, run
by ``tools/riplint.py`` / ``make check``). This entry point keeps the
historical CLI and the ``check()`` / ``check_module()`` API working
for existing invocations and tests.

Exit status 0 when clean; 1 with one violation per line otherwise.
"""
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _analysis():
    spec = importlib.util.spec_from_file_location(
        "riplint_shim", os.path.join(REPO, "tools", "riplint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load_analysis(REPO)


_fg = _analysis().finite_guards

ENTRY_POINTS = _fg.ENTRY_POINTS
check_module = _fg.check_module


def check(repo=REPO):
    """All violations across the configured entry points."""
    return _fg.check(repo)


def main():
    violations = check()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} finite-guard violation(s)", file=sys.stderr)
        return 1
    print("finite guards OK: every checked entry point routes through "
          "riptide_tpu.quality")
    return 0


if __name__ == "__main__":
    sys.exit(main())
