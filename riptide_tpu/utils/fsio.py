"""
Crash-safe file I/O: the ONE place survey persistence touches disk.

Every durable artifact the package writes — the survey journal and its
peak store, heartbeat sidecars, the perf ledger, Chrome trace exports,
the Prometheus textfile, executable-cache entries — funnels through the
helpers here, which hold the crash-consistency discipline in one spot:

* **checksummed line appends** (:func:`append_jsonl`): each record is a
  single ``write()`` of one ``\\n``-terminated line on an ``O_APPEND``
  fd, fsync'd, optionally suffixed with `` #xxxxxxxx`` — a CRC32 over
  the JSON payload — so a reader can tell a *torn* record (kill
  mid-append) from a *corrupted* one (bit rot, lying firmware) from a
  valid legacy record written before checksums existed. Compact JSON
  never ends in `` #<8 hex>``, so the suffix is self-describing and
  suffix-less lines parse as legacy (:func:`split_checksum`).
* **torn-tail healing**: an append to a file whose last byte is not a
  newline (a prior writer died mid-record) first writes a lone newline
  so the new record starts on its own line instead of gluing onto the
  torn fragment — without healing, one torn append would also destroy
  the NEXT record. Healing emits a ``storage_recovered`` incident.
* **atomic whole-file writes** (:func:`atomic_write_bytes`): tmp file
  in the target directory, fsync, ``os.replace``, then fsync of the
  directory itself — a reader never observes a torn page and the
  rename survives a machine crash.
* **storage fault injection**: the survey fault plan
  (:mod:`riptide_tpu.survey.faults`) installs a hook via
  :func:`set_storage_faults`; every helper announces its operation
  (``write`` / ``fsync`` / ``placed``) and *site* (which persistence
  path: :data:`SITES`) to the hook, which may raise ``OSError``
  (``enospc`` / ``fsync_fail``), request a torn partial write
  (``torn_write``), hard-exit the process mid-write (``kill_at`` — the
  chaos campaign's kill points), or corrupt the placed file
  (``cache_corrupt``). With no hook installed every announcement is a
  single ``None`` check.

This module is deliberately stdlib-only (no jax, no package imports at
module level) so every persistence layer — including the jax-free obs
exposition — can use it.
"""
import errno
import json
import logging
import os
import tempfile
import threading
import zlib

log = logging.getLogger("riptide_tpu.utils.fsio")

__all__ = [
    "SITES", "KILL_EXIT", "crc32_hex", "encode_record_line",
    "split_checksum", "scan_jsonl", "append_bytes", "append_jsonl",
    "atomic_write_bytes", "atomic_write_text", "fsync_dir",
    "set_storage_faults",
]

# Exit status of an injected mid-write kill (mirrors SIGKILL's 128+9 so
# the chaos campaign's supervisors treat it like a real kill).
KILL_EXIT = 137

# The named persistence paths storage faults can target. Fault specs
# validate against this tuple so a typo'd site fails at parse time
# instead of silently never firing.
SITES = (
    "journal_append",     # journal.jsonl records
    "peaks_append",       # peaks.jsonl peak-store rows
    "heartbeat_append",   # heartbeat_<p>.jsonl liveness sidecars
    "ledger_append",      # perf-ledger rows (RIPTIDE_LEDGER)
    "trace_export",       # Chrome trace-event JSON exports
    "prom_textfile",      # Prometheus textfile page
    "exec_cache_store",   # compiled-executable cache entries
    "fleet_snapshot",     # fleet_<p>.json per-process status sidecars
    "job_append",         # jobs.jsonl service job-registry events
)

_HEX = frozenset(b"0123456789abcdef")
# " #" + 8 lowercase hex chars appended after the JSON payload.
_SUFFIX_LEN = 10


def crc32_hex(payload):
    """8-hex-digit CRC32 of ``payload`` bytes."""
    return format(zlib.crc32(payload) & 0xFFFFFFFF, "08x")


def encode_record_line(payload, checksum=True):
    """One record line: ``payload`` (compact JSON bytes, no newline)
    plus the optional `` #crc32`` suffix and the terminating newline."""
    if checksum:
        return payload + b" #" + crc32_hex(payload).encode() + b"\n"
    return payload + b"\n"


def split_checksum(line):
    """``(payload, status)`` of one newline-stripped record line.

    ``status`` is ``"ok"`` (suffix present, CRC verified), ``"legacy"``
    (no suffix — a record written before checksums existed, or a
    format that never carries them) or ``"corrupt"`` (suffix present,
    CRC mismatch: the payload bytes changed after they were written).
    Compact JSON always ends in ``}``/``]``/a digit/a quote, never in
    `` #<8 hex>``, so suffix detection cannot misfire on legacy lines.
    """
    if len(line) > _SUFFIX_LEN and line[-_SUFFIX_LEN:-8] == b" #" \
            and all(c in _HEX for c in line[-8:]):
        payload = line[:-_SUFFIX_LEN]
        if line[-8:].decode() == crc32_hex(payload):
            return payload, "ok"
        return payload, "corrupt"
    return line, "legacy"


def scan_jsonl(path):
    """``(entries, size)`` over every line of an append-only JSONL file.

    ``entries`` is a list of ``(obj, status, end_offset)`` where
    ``status`` is ``"ok"``/``"legacy"`` (parsed, ``obj`` set),
    ``"corrupt"`` (checksum mismatch), ``"garbage"`` (unparseable) or
    ``"torn"`` (the final line, missing its newline — a kill
    mid-append); ``end_offset`` is the byte offset just past the line's
    newline (for recovery truncation). Blank lines are skipped."""
    if not os.path.exists(path):
        return [], 0
    with open(path, "rb") as fobj:
        raw = fobj.read()
    entries = []
    pos = 0
    lines = raw.split(b"\n")
    for i, line in enumerate(lines):
        if i == len(lines) - 1:
            # Past the final newline: empty when the file is cleanly
            # terminated, else an unterminated (torn) tail. A torn line
            # is never trusted even if it happens to parse — appending
            # after it would glue two records onto one line.
            if line:
                entries.append((None, "torn", pos + len(line)))
            break
        end = pos + len(line) + 1
        if line:
            payload, status = split_checksum(line)
            if status == "corrupt":
                entries.append((None, "corrupt", end))
            else:
                try:
                    entries.append((json.loads(payload), status, end))
                except ValueError:
                    entries.append((None, "garbage", end))
        pos = end
    return entries, len(raw)


# ---------------------------------------------------------------------------
# Storage fault injection.
#
# The hook is a callable ``hook(op, site, path)``; ``op`` is "write"
# (about to write), "fsync" (about to fsync the data fd) or "placed"
# (atomic write landed at its final path). It may raise OSError, may
# hard-exit the process, or may return a command dict:
# ``{"torn_frac": f, "exit": callable_or_None}`` asking the writer to
# write only the first ``f`` of the payload and then either call
# ``exit(KILL_EXIT)`` (a mid-write kill) or raise EIO (a torn write the
# caller survives). Installed process-wide by the survey layers for the
# duration of a run; ``None`` (the default) costs one attribute read.
#
# PR 17: a thread owned by a job-scoped RunContext (utils.runctx)
# resolves its ``storage_faults`` plan FIRST, so two concurrent service
# jobs each see only their own injected plan; the process-wide hook
# stays the fallback layer for batch paths.
# ---------------------------------------------------------------------------

try:  # fsio stays usable standalone; runctx is stdlib-only anyway
    from . import runctx as _runctx
except ImportError:  # pragma: no cover - standalone module use
    _runctx = None

_fault_hook = None
# Reentrancy guard: healing a torn tail emits an incident, whose sink
# appends to the journal, which may itself need healing — bounded, but
# the inner heal must not announce to the fault hook again mid-action.
_in_recovery = threading.local()


def set_storage_faults(hook):
    """Install ``hook(op, site, path)`` as the process-wide storage
    fault injector (normally a FaultPlan's ``storage_op``); returns the
    previous hook. ``None`` uninstalls."""
    global _fault_hook
    prev, _fault_hook = _fault_hook, hook
    return prev


def _fire(op, site, path):
    if site is None:
        return None
    if _runctx is not None:
        ctx = _runctx.current()
        if ctx is not None and ctx.storage_faults is not None:
            return ctx.storage_faults(op, site, path)
    hook = _fault_hook
    if hook is None:
        return None
    return hook(op, site, path)


def _emit_recovery_incident(action, path, **detail):
    """Best-effort ``storage_recovered`` incident (lazy import: fsio is
    stdlib-only at module level; emission must never fail a write)."""
    if getattr(_in_recovery, "active", False):
        return
    _in_recovery.active = True
    try:
        from ..survey.incidents import emit

        emit("storage_recovered", action=action,
             path=os.path.basename(path), **detail)
    except Exception as err:  # pragma: no cover - emission is advisory
        log.warning("storage_recovered incident failed for %s: %s",
                    path, err)
    finally:
        _in_recovery.active = False


def _write_all(fd, data):
    """Loop ``os.write`` to completion (short writes are legal on
    signals/ENOSPC boundaries; a silent short write would tear the
    record this module exists to protect)."""
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        view = view[n:]


def _torn_write(fd, data, cmd, site, path):
    """Execute an injected torn write: a prefix of ``data`` lands (and
    is fsync'd, so it survives the coming death), then the process
    either hard-exits (``kill_at``) or sees EIO (``torn_write``)."""
    frac = float(cmd.get("torn_frac", 0.5))
    prefix = data[:max(1, int(len(data) * frac))]
    _write_all(fd, prefix)
    os.fsync(fd)
    exit_fn = cmd.get("exit")
    if exit_fn is not None:
        log.warning("fault injection: killing the process mid-%s (%s, "
                    "%d/%d bytes written)", site, path, len(prefix),
                    len(data))
        exit_fn(KILL_EXIT)
    raise OSError(
        errno.EIO,
        f"injected torn write at {site}: {len(prefix)}/{len(data)} "
        f"bytes of {path!r} written",
    )


def append_bytes(path, data, site=None, heal=True):
    """Append ``data`` to ``path`` in one write on an ``O_APPEND`` fd,
    fsync'd before returning.

    With ``heal`` (the default), a file whose last byte is not a
    newline — a previous writer died mid-record — gets a lone newline
    first, so the new record starts on its own line instead of gluing
    onto the torn fragment (which readers drop as garbage); the heal is
    incident-recorded. Raises ``OSError`` on failure — the CALLER
    decides whether the path is correctness-critical (journal: raise)
    or observability (ledger/trace/prom/heartbeat: degrade to an
    incident)."""
    if not data:
        return
    cmd = _fire("write", site, path)
    # O_RDWR (not O_WRONLY): the heal check preads the current last
    # byte through the same fd; appends still go through O_APPEND.
    fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        if heal:
            size = os.fstat(fd).st_size
            if size > 0 and os.pread(fd, 1, size - 1) != b"\n":
                _write_all(fd, b"\n")
                log.warning("%s: healed a torn tail before appending "
                            "(previous writer died mid-record)", path)
                _emit_recovery_incident("healed_torn_tail", path,
                                        site=site)
        if cmd and cmd.get("torn_frac") is not None:
            _torn_write(fd, data, cmd, site, path)
        _write_all(fd, data)
        _fire("fsync", site, path)
        os.fsync(fd)
    finally:
        os.close(fd)


def append_jsonl(path, objs, site=None, checksum=False, heal=True):
    """Append JSON records as individually-parseable lines in ONE
    write/fsync cycle (a chunk's whole peak batch costs one append).
    ``checksum`` adds the per-record CRC32 suffix."""
    data = b"".join(
        encode_record_line(
            json.dumps(obj, separators=(",", ":")).encode(), checksum)
        for obj in objs
    )
    append_bytes(path, data, site=site, heal=heal)


def fsync_dir(dirpath):
    """Best-effort fsync of a directory (persists a just-renamed
    entry's existence across a machine crash; some filesystems reject
    directory fsync, which is as good as it gets there)."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fs-dependent
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path, data, site=None):
    """Crash-safe whole-file write: unique tmp file in the target
    directory, fsync, ``os.replace`` onto ``path``, fsync of the
    directory. A reader never sees a torn page; a kill mid-write leaves
    at worst a stale ``*.tmp`` next to an intact previous version."""
    cmd = _fire("write", site, path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        try:
            if cmd and cmd.get("torn_frac") is not None:
                _torn_write(fd, data, cmd, site, path)
            _write_all(fd, data)
            _fire("fsync", site, path)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    fsync_dir(d)
    _fire("placed", site, path)
    return path


def atomic_write_text(path, text, site=None):
    """:func:`atomic_write_bytes` for text content."""
    return atomic_write_bytes(path, text.encode(), site=site)
