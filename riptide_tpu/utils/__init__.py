"""Shared utilities: coordinate transforms (astropy-free SkyCoord
equivalent) and the cross-process executable cache."""
