"""
Job-scoped run contexts (PR 17).

PR 16 turned the repo into a job-accepting service, but the three
run-shaped hooks — the incident sink (``survey.incidents.set_sink``),
the live status provider (``obs.prom.set_status_provider``) and the
fsio storage-fault hook (``utils.fsio.set_storage_faults``) — stayed
process-global.  With several jobs in flight the LAST started job
owned them, so a sibling's incidents could journal into the wrong
run's directory.

A :class:`RunContext` carries those three hooks for the run that owns
the *current thread*.  ``SurveyScheduler.run()`` installs one for the
calling thread and threads it into its stager/loader pool via
:func:`wrap`; ``ServeDaemon._run_job`` installs a per-job context on
the worker thread so daemon-level incidents (cancellation, quota,
deadline) land in the job's own journal too.  Resolution everywhere is
*context first, process-global second*: the pre-PR-17 setters remain
the fallback layer, so batch CLI paths and existing fixtures behave
byte-identically with no context installed.

The module is stdlib-only on purpose — ``utils.fsio`` (itself
stdlib-only) imports it at module scope.
"""
import contextlib
import threading

__all__ = ["RunContext", "activate", "current", "install", "wrap"]

_MISSING = object()


class RunContext:
    """The hook bundle of one run: incident sink, status provider and
    storage-fault plan, plus the run's own last-incident slot so a
    concurrent sibling can never clobber this run's ``/status`` tail.

    Every field is optional — a ``None`` hook falls through to the
    process-global layer for that hook only.
    """

    __slots__ = ("incident_sink", "status_provider", "storage_faults",
                 "label", "_last_incident", "_lock")

    def __init__(self, incident_sink=None, status_provider=None,
                 storage_faults=None, label=None):
        self.incident_sink = incident_sink
        self.status_provider = status_provider
        self.storage_faults = storage_faults
        self.label = label
        self._last_incident = None
        self._lock = threading.Lock()

    def note_incident(self, rec):
        with self._lock:
            self._last_incident = dict(rec)

    def last_incident(self):
        with self._lock:
            rec = self._last_incident
        return dict(rec) if rec is not None else None

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"RunContext(label={self.label!r})"


_tls = threading.local()


def current():
    """The :class:`RunContext` owning the calling thread, or None."""
    return getattr(_tls, "ctx", None)


def install(ctx):
    """Install ``ctx`` (or None) on the calling thread; returns the
    previously installed context so callers can restore it."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


@contextlib.contextmanager
def activate(ctx):
    """``with activate(ctx): ...`` — install for the block, restore
    the previous context after (exception-safe)."""
    prev = install(ctx)
    try:
        yield ctx
    finally:
        install(prev)


def wrap(fn, ctx=_MISSING):
    """Bind a context into ``fn`` for execution on ANOTHER thread.

    Captures the *caller's* current context (or an explicit ``ctx``)
    and returns a callable that installs it around the real call —
    the inheritance shim for ``ThreadPoolExecutor.submit``/``map``
    workers, watchdog sacrificial threads and liveness beaters, whose
    thread-locals would otherwise be empty.
    """
    bound = current() if ctx is _MISSING else ctx

    def _inherit(*args, **kwargs):
        prev = install(bound)
        try:
            return fn(*args, **kwargs)
        finally:
            install(prev)

    return _inherit
