"""
Typed registry of every ``RIPTIDE_*`` environment flag.

Every environment flag the package reads is declared here once — name,
type, default, effect, and the PR that introduced it — and read through
:func:`get`, which parses and validates the raw string at call time (so
tests that monkeypatch ``os.environ`` keep working). Direct
``os.environ`` reads of ``RIPTIDE_*`` names anywhere else in the
package are rejected by the riplint env-flag analyzer (RIP003, see
``riptide_tpu/analysis/env_flags.py``), which also fails when a
registry entry goes stale (no remaining read anywhere in the repo) or
when ``docs/env_flags.md`` drifts from :func:`render_markdown`.

This module must stay importable WITHOUT jax (and without triggering
``riptide_tpu/__init__``): the lint runner loads it by file path.
"""
import os
from dataclasses import dataclass, field

__all__ = ["EnvFlag", "FLAGS", "get", "render_markdown"]

# Raw values parsed as False for "bool" flags; anything else set and
# non-empty is True. An empty string counts as unset (the default
# applies), matching the package's historical `os.environ.get(...)`
# truthiness checks.
_FALSE_WORDS = ("0", "false", "off", "no")


@dataclass(frozen=True)
class EnvFlag:
    """One registered environment flag.

    type is one of ``bool`` / ``int`` / ``float`` / ``str`` /
    ``choice`` (``choices`` + optional raw-value ``aliases`` apply to
    ``choice`` only). ``scope`` is ``package`` for flags read through
    this registry inside ``riptide_tpu/``, ``tools`` for flags read
    directly by out-of-package entry points (bench.py, tests/conftest,
    Makefile) that must stay importable before jax configuration.
    """

    name: str
    type: str
    default: object
    help: str
    since: str
    choices: tuple = ()
    aliases: dict = field(default_factory=dict)
    scope: str = "package"


_ALL = [
    EnvFlag(
        "RIPTIDE_FFA_PATH", "choice", "auto",
        "Periodogram execution path: `kernel` (fused Pallas cycle "
        "kernel), `gather` (XLA modular-gather formulation), or `auto` "
        "(kernel on TPU backends, gather elsewhere).",
        since="seed", choices=("auto", "kernel", "gather"),
    ),
    EnvFlag(
        "RIPTIDE_WIRE_DTYPE", "choice", None,
        "Host->device wire transport for downsampled stage data. "
        "Default: `uint6` on the kernel path, `float32` on the gather "
        "path.",
        since="seed",
        choices=("float32", "float16", "uint12", "uint8", "uint6"),
        aliases={"u12": "uint12", "u8": "uint8", "u6": "uint6"},
    ),
    EnvFlag(
        "RIPTIDE_KERNEL_BASE3", "bool", True,
        "Allow base-3 (1.5 * 2^k) kernel containers where the bucket "
        "fits, cutting power-of-two padding waste ~25% on affected "
        "stages; `0` forces pure 2^L containers.",
        since="PR 0 (0.3.0)",
    ),
    EnvFlag(
        "RIPTIDE_KERNEL_ROW_PACK", "bool", True,
        "Row-packed kernel containers: the odd-slot container forms "
        "(5/7 * 2^(L-3)) join the bucket family, and a second same-p "
        "bins-trial is packed into a container's dead rows via per-row "
        "table indirection where the plan's cross-stage pairing finds "
        "a fit (results stay bit-identical per trial; buckets with no "
        "reclaim or over the VMEM model fall back automatically). `0` "
        "reverts to the pre-row-pack layout.",
        since="PR 15 (0.14.0)",
    ),
    EnvFlag(
        "RIPTIDE_KERNEL_LANE_SPLIT", "bool", True,
        "Split each stage's bins trials into lane-occupancy buckets "
        "(grouped by ceil(p / 128) tiles) so most trials run in a "
        "narrower container; `0` reverts to one full-width bucket. "
        "Results are bit-identical either way.",
        since="PR 4 (0.6.0)",
    ),
    EnvFlag(
        "RIPTIDE_KERNEL_RESIDENT", "bool", True,
        "Keep each bins-trial's all-levels table set resident in a "
        "persistent VMEM scratch (one DMA per trial instead of one per "
        "level); `0` forces level-by-level streaming everywhere.",
        since="seed",
    ),
    EnvFlag(
        "RIPTIDE_KERNEL_CACHE", "str", None,
        "Directory for the cross-process compiled Pallas-kernel "
        "executable cache (default `<cache_root>/kernel`); `off` "
        "disables the cache (kernels compile per process).",
        since="PR 1 (0.4.0)",
    ),
    EnvFlag(
        "RIPTIDE_EXEC_CACHE", "str", None,
        "Directory for the cross-process cached_jit executable cache "
        "(default `<cache_root>/exec`); `off` disables it.",
        since="seed",
    ),
    EnvFlag(
        "RIPTIDE_EXEC_CACHE_MAX_BYTES", "int", 2 << 30,
        "Byte cap per on-disk executable cache directory: inserts "
        "evict least-recently-used entries above it; <= 0 disables "
        "eviction.",
        since="PR 1 (0.4.0)",
    ),
    EnvFlag(
        "RIPTIDE_CACHE_ROOT", "str", None,
        "Root directory for all on-disk executable caches (explicit "
        "operator intent, used as given). Default: a trusted "
        "`.riptide_cache/` at the checkout root, else a per-user 0700 "
        "tempdir.",
        since="PR 1 (0.4.0)",
    ),
    EnvFlag(
        "RIPTIDE_FAULT_INJECT", "str", None,
        "Fault-injection spec for the survey scheduler / batch "
        "searcher, e.g. `stall:0:0.1,raise:2x2,oom:0` — including the "
        "storage fault kinds (`kill_at`/`torn_write`/`enospc`/"
        "`fsync_fail`/`cache_corrupt` targeting a persistence site, "
        "e.g. `kill_at:journal_append:3`; see "
        "riptide_tpu/survey/faults.py for the grammar). CLI "
        "`--fault-inject` takes precedence.",
        since="PR 1 (0.4.0)",
    ),
    EnvFlag(
        "RIPTIDE_CHAOS_DIR", "str", None,
        "Working directory of the storage-chaos campaign "
        "(`make chaos` / tools/rchaos.py); default: a fixed per-system "
        "tempdir. Kept on failure for post-mortems.",
        since="PR 11 (0.11.0)",
    ),
    EnvFlag(
        "RIPTIDE_CHAOS_SEED", "int", 1234,
        "Seed of the chaos campaign's generated schedule sweep: the "
        "same seed reproduces the same kill-point/degradation "
        "combinations (tools/rchaos.py --seed overrides).",
        since="PR 11 (0.11.0)",
    ),
    EnvFlag(
        "RIPTIDE_CHAOS_SWEEP", "int", 0,
        "How many seeded schedules the chaos campaign appends to the "
        "fixed builtin set (0 = builtin only, the `make chaos` "
        "default; the slow test tier and tools/rchaos.py --sweep run "
        "more).",
        since="PR 11 (0.11.0)",
    ),
    EnvFlag(
        "RIPTIDE_CHAOS_KEEP", "bool", False,
        "Keep the chaos campaign's working directory after a PASSING "
        "run too (failures always keep it).",
        since="PR 11 (0.11.0)",
    ),
    EnvFlag(
        "RIPTIDE_NATIVE_SANITIZE", "bool", False,
        "Build the native host library with ASan+UBSan "
        "(`-fsanitize=address,undefined`, no-recover). The sanitized "
        ".so only loads when the sanitizer runtimes are preloaded — "
        "use `make native-asan` / `make sanitize`, which set "
        "LD_PRELOAD accordingly.",
        since="PR 5 (0.7.0)",
    ),
    EnvFlag(
        "RIPTIDE_TRACE", "bool", False,
        "Enable the span tracer for the whole process at import time: "
        "host-side survey phases (prep/wire/queue/device/collect, "
        "per-dispatch kinds) record into a bounded in-memory ring, "
        "exportable as a Perfetto-loadable Chrome trace "
        "(riptide_tpu.obs). Off by default; the disabled path is a "
        "single None check per span.",
        since="PR 8 (0.8.0)",
    ),
    EnvFlag(
        "RIPTIDE_TRACE_RING", "int", 65536,
        "Span-ring capacity of the tracer (completed spans retained "
        "for export). The ring is bounded: a long survey drops the "
        "oldest spans and the export records how many "
        "(`dropped_events`), so memory stays flat.",
        since="PR 8 (0.8.0)",
    ),
    EnvFlag(
        "RIPTIDE_PROM_PORT", "int", 0,
        "Serve Prometheus text-format metrics from the process-wide "
        "registry at http://127.0.0.1:<port>/metrics on a daemon "
        "thread (stdlib-only; started by survey runs via "
        "riptide_tpu.obs.prom.maybe_serve). 0 disables the endpoint.",
        since="PR 8 (0.8.0)",
    ),
    EnvFlag(
        "RIPTIDE_PROM_PORT_OFFSET", "bool", True,
        "Offset the Prometheus endpoint port by this process's "
        "distributed index (port = RIPTIDE_PROM_PORT + "
        "jax.process_index()), so multiple processes on one host get "
        "deterministic per-process endpoints instead of racing to "
        "bind the same port (the loser silently lost its endpoint). "
        "`0` binds the literal port in every process.",
        since="PR 14 (0.13.0)",
    ),
    EnvFlag(
        "RIPTIDE_PROM_TEXTFILE", "str", None,
        "Path of a Prometheus textfile (node_exporter textfile-"
        "collector format) the survey layers write the metrics "
        "registry to at the end of each run; unset disables.",
        since="PR 8 (0.8.0)",
    ),
    EnvFlag(
        "RIPTIDE_LEDGER", "str", None,
        "Path of the append-only JSONL performance ledger: every "
        "bench.py / tools/stime.py / journaled-survey run appends ONE "
        "run record (phase decomposition, git sha, envflag "
        "fingerprint, device platform, KERNEL_CACHE_VERSION, per-chunk "
        "bound counts). `tools/rreport.py --compare` reads it as the "
        "regression baseline. Unset disables.",
        since="PR 9 (0.9.0)",
    ),
    EnvFlag(
        "RIPTIDE_STATUS", "bool", True,
        "Publish the live survey status surface: journaled survey runs "
        "register a /status + /healthz source on the Prometheus "
        "endpoint (RIPTIDE_PROM_PORT). `0` leaves the endpoint "
        "metrics-only.",
        since="PR 9 (0.9.0)",
    ),
    EnvFlag(
        "RIPTIDE_STATUS_STALE_S", "float", 120.0,
        "Heartbeat age (seconds) beyond which the /healthz probe "
        "reports 503: a survey process whose freshest journal "
        "heartbeat is older than this is up but not making progress.",
        since="PR 9 (0.9.0)",
    ),
    EnvFlag(
        "RIPTIDE_FLEET", "bool", True,
        "Write the per-process fleet status sidecar (`fleet_<p>.json`, "
        "atomically rewritten next to the journal after every chunk) "
        "that /status, rreport, `rtop --fleet` and rwatch merge into "
        "the cross-process fleet view. Writes are never fatal "
        "(ENOSPC degrades to an incident). `0` disables the sidecar.",
        since="PR 14 (0.13.0)",
    ),
    EnvFlag(
        "RIPTIDE_ALERTS", "bool", False,
        "Evaluate the alert-rule engine (riptide_tpu/obs/alerts.py) "
        "over the live run after every chunk of a journaled survey: "
        "firing/resolving journals an `alert` record, emits "
        "alert_fired/alert_resolved incidents and flips the "
        "riptide_alert_active{rule=...} Prometheus gauge. Off by "
        "default (tools/rwatch.py can watch any run from outside "
        "without it).",
        since="PR 14 (0.13.0)",
    ),
    EnvFlag(
        "RIPTIDE_ALERT_RULES", "str", None,
        "Alert rule spec for the in-scheduler engine: comma-separated "
        "`name[:limit[:for_count]]` entries naming builtin rules "
        "(tunnel_bound, heartbeat_stale, parked_chunks, "
        "straggler_ratio, obs_write_errors, hbm_drift, integrity), or "
        "`default` "
        "for the full catalog with stock thresholds. Unset = the full "
        "catalog. Unknown names fail the run at start (a typo'd rule "
        "must not silently never fire).",
        since="PR 14 (0.13.0)",
    ),
    EnvFlag(
        "RIPTIDE_HBM_BUDGET", "int", 0,
        "Peak device-HBM budget (bytes) for the model-seeded DM-batch "
        "pick: when > 0, the batch searcher caps each queued DM batch "
        "at the largest size the plan's traced peak-HBM model "
        "(riptide_tpu/analysis/jaxpr_contract.py) predicts fits, so "
        "OOM bisection becomes a fallback instead of the first resort "
        "(`oom_predicted` counts proactive splits), and journaled "
        "chunks carry a predicted-vs-actual `hbm` calibration block. "
        "`0` disables seeding.",
        since="PR 12 (0.12.0)",
    ),
    EnvFlag(
        "RIPTIDE_PROVE_PLANS", "str", None,
        "Comma-separated subset of contract plan names tools/rprove.py "
        "verifies (see riptide_tpu/ops/plan.py CONTRACT_PLANS); unset "
        "verifies every fast-tier plan and `rprove --all` adds the "
        "slow tier. Read raw by tools/rprove.py before jax "
        "configuration; the --plans CLI flag takes precedence.",
        since="PR 12 (0.12.0)", scope="tools",
    ),
    EnvFlag(
        "RIPTIDE_BENCH_BUDGET", "float", 1380.0,
        "Total process wall-time budget (seconds) bench.py runs "
        "against: the first timed pass always emits a JSON line, "
        "further best-of-N passes run only while budget remains.",
        since="PR 1 (0.4.0)", scope="tools",
    ),
    EnvFlag(
        "RIPTIDE_BENCH_DEBUG", "bool", False,
        "Enable bench.py's periodic faulthandler stack dumps (locates "
        "long compiles / stalls). Read raw by bench.py: ANY non-empty "
        "value — including `0` — enables; unset/empty disables.",
        since="PR 4 (0.6.0)", scope="tools",
    ),
    EnvFlag(
        "RIPTIDE_TESTS_TPU", "bool", False,
        "Run the test suite against the real TPU backend (`make "
        "tests-tpu`): tpu-marked tests run, the CPU-backend forcing in "
        "tests/conftest.py is skipped. Read raw by tests/conftest.py: "
        "exactly `1` enables; everything else disables.",
        since="seed", scope="tools",
    ),
    EnvFlag(
        "RIPTIDE_SERVE", "bool", True,
        "Serve the /jobs API from the survey service daemon "
        "(tools/rserve.py): accept, queue and run jobs submitted over "
        "HTTP. `0` starts the daemon metrics/status-only (the /jobs "
        "surface answers 503) — a drain mode for maintenance.",
        since="PR 16 (0.15.0)",
    ),
    EnvFlag(
        "RIPTIDE_SERVE_MAX_JOBS", "int", 16,
        "Max jobs the service daemon keeps resident (pending + "
        "running) across ALL tenants; a submit over the cap is "
        "rejected with HTTP 429 and a `job_rejected` incident. "
        "Completed/failed/cancelled jobs do not count.",
        since="PR 16 (0.15.0)",
    ),
    EnvFlag(
        "RIPTIDE_SERVE_QUOTA_DEVICE_S", "float", 0.0,
        "Default per-tenant device-seconds budget for service jobs "
        "(riptide_tpu/serve/tenants.py): every fair-share device turn "
        "is charged against it, and an exhausted tenant's jobs stop at "
        "their next chunk boundary with a `quota_exceeded` incident "
        "(journals stay resumable). `0` = unlimited.",
        since="PR 16 (0.15.0)",
    ),
    EnvFlag(
        "RIPTIDE_SERVE_PORT", "int", 0,
        "Port of the survey service daemon's HTTP endpoint "
        "(tools/rserve.py; loopback only, like RIPTIDE_PROM_PORT). "
        "`0` binds an ephemeral port, published in the serve root's "
        "`serve.port` discovery file either way.",
        since="PR 16 (0.15.0)",
    ),
    EnvFlag(
        "RIPTIDE_SERVE_DIR", "str", None,
        "Default serve root for tools/rserve.py (the directory holding "
        "jobs.jsonl, per-job journal directories and the serve.port "
        "discovery file). Unset = the rserve --root argument is "
        "required.",
        since="PR 16 (0.15.0)",
    ),
    EnvFlag(
        "RIPTIDE_SERVE_DRAIN_TIMEOUT_S", "float", 60.0,
        "Graceful-drain budget of the survey service daemon: on "
        "SIGTERM/SIGINT or POST /drain, how long to wait for the "
        "running chunk to finish and queued jobs to park at the chunk "
        "gate before rserve exits anyway. Parked jobs keep no terminal "
        "registry record, so a restart re-queues them (`resumed`).",
        since="PR 17 (0.16.0)",
    ),
    EnvFlag(
        "RIPTIDE_INTEGRITY", "choice", "off",
        "Result-integrity mode of the survey scheduler "
        "(riptide_tpu/survey/integrity.py). `off` = nothing (no fold, "
        "no extra dispatches — the pre-PR-18 fast path). `digest` = "
        "Ring 1: per-chunk result digests journaled in an `integrity` "
        "block and re-verified on resume. `probe` = Ring 1 + Ring 2 "
        "shadow recompute probes per RIPTIDE_INTEGRITY_PROBE_EVERY "
        "(mismatch -> `result_mismatch` incident + third-dispatch "
        "vote; persistent mismatch -> suspect-device quarantine), "
        "plus the golden canary on every quarantine decision. "
        "`strict` = probe EVERY chunk and run the canary at scheduler "
        "warmup, aborting before tenant work if it misses its pinned "
        "digest. Serve jobs can override per job via the spec's "
        "`integrity` field.",
        since="PR 18 (0.17.0)",
        choices=("off", "digest", "probe", "strict"),
    ),
    EnvFlag(
        "RIPTIDE_DEVICE_CLUSTER", "bool", True,
        "Run 1-D peak clustering (and the advisory harmonic screen) on "
        "device inside the fused peak program: cluster representatives "
        "come home in the single result pull and the host skips the "
        "per-point float64 re-check + friends-of-friends loop for "
        "every column the exact-parity guards accept (marginal-band "
        "points, representative overflow or a float32-threshold drift "
        "beyond EPS fall back per column to the host path, which stays "
        "bit-identical). `0` reverts to the pure host tail — peaks.csv "
        "and candidates.csv are byte-identical either way.",
        since="PR 19 (0.18.0)",
    ),
    EnvFlag(
        "RIPTIDE_PREP_THREADS", "int", 0,
        "Worker threads of the native wire-prep runtime (downsample + "
        "quantise). `0` (default) uses every core (capped at 32); a "
        "positive value pins the count, e.g. `1` for single-core "
        "baselines. Pure throughput knob: the native job pool assigns "
        "disjoint output regions per (stage, trial) job, so wire bytes "
        "and digests are identical at any thread count (excluded from "
        "the ledger envflag fingerprint for the same reason).",
        since="PR 19 (0.18.0)",
    ),
    EnvFlag(
        "RIPTIDE_INTEGRITY_PROBE_EVERY", "int", 0,
        "Shadow-probe cadence of `RIPTIDE_INTEGRITY=probe`: dispatch "
        "every Nth chunk twice through the already-compiled "
        "executables and compare result digests bit-exactly before "
        "the record is written. `0` disables probing (digest-only "
        "even in probe mode); `strict` mode probes every chunk "
        "regardless.",
        since="PR 18 (0.17.0)",
    ),
    EnvFlag(
        "RIPTIDE_SCHED_BOUND", "int", 2,
        "Preemption bound of the `ripsched` schedule-exploration model "
        "checker (`make ripsched`): schedules with at most this many "
        "preemptive context switches are explored exhaustively, "
        "shallowest first, so any violation found is minimal in "
        "preemptions. Raising it widens coverage at exponential cost. "
        "Checker-only knob — never read by a survey run, and excluded "
        "from the ledger envflag fingerprint.",
        since="PR 20 (0.19.0)",
    ),
    EnvFlag(
        "RIPTIDE_SCHED_SEED", "int", 0,
        "Seed ordering the alternatives `ripsched` expands first "
        "within each preemption bound. Changes which violation (if "
        "several exist) is reported first, never whether one is found "
        "at the bound; replay IDs embed the decision digits and do "
        "not depend on it. Checker-only knob, excluded from the "
        "ledger envflag fingerprint.",
        since="PR 20 (0.19.0)",
    ),
    EnvFlag(
        "RIPTIDE_SCHED_REPLAY", "str", "",
        "When non-empty, `tools/ripsched.py` replays this recorded "
        "schedule ID (`model[+mutation]:digits`) deterministically "
        "instead of exploring — the repro workflow printed with every "
        "violation. Checker-only knob, excluded from the ledger "
        "envflag fingerprint.",
        since="PR 20 (0.19.0)",
    ),
]

FLAGS = {f.name: f for f in _ALL}


def _parse(flag, raw):
    if flag.type == "bool":
        return raw.strip().lower() not in _FALSE_WORDS
    if flag.type == "int":
        return int(raw)
    if flag.type == "float":
        return float(raw)
    if flag.type == "choice":
        val = flag.aliases.get(raw, raw)
        if flag.choices and val not in flag.choices:
            raise ValueError(
                f"unsupported {flag.name}={raw!r}: expected one of "
                f"{flag.choices}"
            )
        return val
    return raw


def get(name, env=None):
    """The parsed value of registered flag ``name``, read from the
    environment at call time (monkeypatched environments apply).
    Unset or empty -> the registered default. Raises KeyError for an
    unregistered name and ValueError for an unparsable value."""
    flag = FLAGS[name]
    raw = (os.environ if env is None else env).get(name)
    if raw is None or raw == "":
        return flag.default
    return _parse(flag, raw)


def render_markdown():
    """The full ``docs/env_flags.md`` content, generated from the
    registry so the documentation cannot drift from the code (riplint
    RIP003 fails when the checked-in file differs)."""
    lines = [
        "# Environment flags",
        "",
        "Every `RIPTIDE_*` environment variable the project reads, "
        "generated",
        "from the typed registry in `riptide_tpu/utils/envflags.py` "
        "(regenerate",
        "with `python tools/riplint.py --write-env-docs`). Package "
        "code reads",
        "flags exclusively through `envflags.get(...)`; the riplint "
        "env-flag",
        "analyzer (RIP003) rejects direct `os.environ` reads of "
        "`RIPTIDE_*`",
        "names and flags stale registry entries.",
        "",
        "Registry-routed boolean flags parse `0` / `false` / `off` / "
        "`no` as",
        "False and any other non-empty value as True; an empty string "
        "counts as",
        "unset (default applies). `scope: tools` flags are read RAW by "
        "their",
        "out-of-package entry points (bench.py, tests/conftest.py, "
        "Makefile)",
        "before jax configuration — they do NOT follow the registry "
        "parse; each",
        "entry below states its exact trigger.",
        "",
        "| Flag | Type | Default | Since | Scope |",
        "|------|------|---------|-------|-------|",
    ]
    for f in _ALL:
        typ = f.type
        if f.type == "choice":
            typ = " \\| ".join(f"`{c}`" for c in f.choices)
            if f.aliases:
                typ += " (aliases: " + ", ".join(
                    f"`{a}`" for a in f.aliases) + ")"
        default = "unset" if f.default is None else f"`{f.default}`"
        lines.append(
            f"| `{f.name}` | {typ} | {default} | {f.since} | {f.scope} |"
        )
    lines.append("")
    for f in _ALL:
        lines.append(f"## `{f.name}`")
        lines.append("")
        lines.append(f.help)
        lines.append("")
    return "\n".join(lines)
