"""
Version shims for the narrow band of jax APIs whose names moved between
the 0.4.x line and current releases. The framework targets current jax;
these shims keep the SAME call sites working on a 0.4.x runtime (the CI
image pins 0.4.37) instead of failing with AttributeError at program
build time:

* ``pallas_compiler_params`` — ``pltpu.CompilerParams`` was named
  ``TPUCompilerParams`` on 0.4.x. Construction arguments used here
  (``vmem_limit_bytes``) are identical.
* ``shard_map`` — ``jax.shard_map`` graduated from
  ``jax.experimental.shard_map.shard_map``; the replication-check
  keyword was renamed ``check_rep`` -> ``check_vma`` in the move.

Call sites pass the CURRENT names/keywords; the shim translates only
when running on the old runtime.
"""
import jax
from jax.experimental.pallas import tpu as pltpu

__all__ = ["pallas_compiler_params", "shard_map"]


if hasattr(pltpu, "CompilerParams"):
    pallas_compiler_params = pltpu.CompilerParams
else:  # jax 0.4.x
    pallas_compiler_params = pltpu.TPUCompilerParams


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)
