"""
Cross-process executable cache for jitted XLA programs.

The TPU backend in this environment does not populate JAX's persistent
compilation cache, so every fresh process re-pays the remote XLA compile
of every engine program (~15 s each through a tunneled compiler — the
dominant cost of process startup). Compiled executables DO round-trip
through ``jax.experimental.serialize_executable`` here, so this module
wraps ``jax.jit`` with a disk cache of serialized executables:

* key = package source hash + jax version + device kind + program name
  + per-argument signature (array shape/dtype; ``repr`` for statics;
  an object's ``cache_token`` attribute when present — plans define one);
* on miss: AOT ``lower(...).compile()``, serialize, store atomically;
* off-TPU (the CPU test suite) or on any failure: plain jit.

The whole-package source hash is deliberately coarse: any source edit
invalidates every cached engine program (correctness over warm starts).
These programs recompile in ~15 s each (~3 min total for a survey), so
a content-keyed miss is an acceptable cost; the Pallas cycle kernel,
whose compiles run 10-50 MINUTES, keeps its own narrower version-keyed
cache in ops/ffa_kernel so only semantic kernel changes invalidate it.
"""
import functools
import hashlib
import logging
import os
import pickle
import tempfile
import threading

import jax

log = logging.getLogger("riptide_tpu.exec_cache")

__all__ = ["cached_jit", "load_or_compile_exec", "cache_root"]


def cache_root():
    """Root directory for the on-disk executable caches.

    Precedence: ``RIPTIDE_CACHE_ROOT``; a ``.riptide_cache`` directory
    at the checkout root (the package's parent) when that location is
    writable — unlike a tempdir it is guaranteed to survive into every
    later process run from the same checkout, in particular the
    driver's end-of-round benchmark run; else a per-user tempdir
    (0700: entries are pickles, the directory must not be writable by
    other local users)."""
    env = os.environ.get("RIPTIDE_CACHE_ROOT")
    if env:
        return env
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if os.access(repo, os.W_OK):
        return os.path.join(repo, ".riptide_cache")
    return os.path.join(tempfile.gettempdir(),
                        f"riptide_tpu_cache_{os.getuid()}")


_DIR = os.environ.get(
    "RIPTIDE_EXEC_CACHE", os.path.join(cache_root(), "exec")
)

_lock = threading.Lock()
_src_hash_memo = None


def _src_hash():
    global _src_hash_memo
    if _src_hash_memo is None:
        h = hashlib.sha1()
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for root, dirs, files in os.walk(pkg):
            dirs.sort()
            for f in sorted(files):
                if f.endswith(".py"):
                    with open(os.path.join(root, f), "rb") as fh:
                        h.update(fh.read())
        h.update(jax.__version__.encode())
        _src_hash_memo = h.hexdigest()
    return _src_hash_memo


def load_or_compile_exec(path, jitted, args, kw=None, name="program",
                         info=None):
    """Deserialize a compiled executable from ``path``, or AOT-compile
    ``jitted`` at ``args``/``kw`` and store it there (atomic write,
    0700 parent dir). Returns a compiled callable taking only the ARRAY
    arguments (statics are baked in by ``lower``). When ``info`` is a
    dict, ``info['action']`` records what actually happened ('loaded'
    or 'compiled' — a corrupt entry falls through to a compile). Shared
    by the generic :func:`cached_jit` wrapper and the Pallas
    cycle-kernel cache (ops/ffa_kernel.py), which keys its entries more
    narrowly."""
    from jax.experimental import serialize_executable as se

    if info is None:
        info = {}
    if os.path.exists(path):
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            info["action"] = "loaded"
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as err:
            log.warning("exec cache load failed for %s (%s); recompiling",
                        name, err)
    info["action"] = "compiled"
    compiled = jitted.lower(*args, **(kw or {})).compile()
    try:
        d = os.path.dirname(path)
        os.makedirs(d, mode=0o700, exist_ok=True)
        payload = se.serialize(compiled)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, path)
    except Exception as err:
        log.warning("exec cache store failed for %s (%s)", name, err)
    return compiled


def _on_tpu():
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:
        return False


def _is_array(a):
    # numpy scalars (np.int64 etc.) have shape/dtype but carry VALUE
    # semantics a compiled executable bakes in — treat them as statics
    # so the cache key includes the value.
    import numpy as _np

    if isinstance(a, _np.generic):
        return False
    return hasattr(a, "shape") and hasattr(a, "dtype")


class _Cached:
    def __init__(self, jitted, name):
        self.jitted = jitted
        self.name = name
        self._mem = {}

    def _key(self, flat_args):
        parts = [self.name, _src_hash(), jax.devices()[0].platform,
                 getattr(jax.devices()[0], "device_kind", "")]
        for a in flat_args:
            tok = getattr(a, "cache_token", None)
            if tok is not None:
                parts.append(("t", tok))
            elif _is_array(a):
                # Sharding is part of the AOT executable's signature: a
                # dm-sharded and an unsharded call with identical shapes
                # must not share one compiled program.
                sh = getattr(a, "sharding", None)
                parts.append(("a", tuple(a.shape), str(a.dtype),
                              str(sh) if sh is not None else ""))
            else:
                parts.append(("s", repr(a)))
        return hashlib.sha1(repr(parts).encode()).hexdigest()

    def _load_or_compile(self, key, args, kw):
        return load_or_compile_exec(os.path.join(_DIR, key + ".pkl"),
                                    self.jitted, args, kw, name=self.name)

    def __get__(self, obj, objtype=None):
        # Descriptor protocol so the wrapper also works on methods
        # (static self carries the instance's cache_token).
        if obj is None:
            return self
        return functools.partial(self.__call__, obj)

    def __call__(self, *args, **kw):
        if not _on_tpu() or os.environ.get("RIPTIDE_EXEC_CACHE") == "off":
            return self.jitted(*args, **kw)
        flat = list(args) + [kw[k] for k in sorted(kw)]
        key = self._key(flat)
        fn = self._mem.get(key)
        if fn is None:
            with _lock:
                fn = self._mem.get(key)
                if fn is None:
                    try:
                        fn = self._load_or_compile(key, args, kw)
                    except Exception as err:
                        log.warning("exec cache disabled for %s (%s)",
                                    self.name, err)
                        fn = self.jitted
                    self._mem[key] = fn
        if fn is self.jitted:
            return fn(*args, **kw)
        # AOT executables take only the ARRAY arguments; statics were
        # baked in at lower() time.
        darr = [a for a in flat
                if _is_array(a) and getattr(a, "cache_token", None) is None]
        return fn(*darr)


def cached_jit(fun=None, *, static_argnames=()):
    """``jax.jit`` with the cross-process executable cache. Supports the
    decorator forms ``@cached_jit`` and
    ``@cached_jit(static_argnames=...)``. Static args must be
    non-arrays (or carry a stable ``cache_token``)."""
    if fun is None:
        return functools.partial(cached_jit, static_argnames=static_argnames)
    jitted = jax.jit(fun, static_argnames=static_argnames)
    wrapper = _Cached(jitted, getattr(fun, "__qualname__", repr(fun)))
    return functools.wraps(fun)(wrapper)
