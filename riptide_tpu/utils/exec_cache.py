"""
Cross-process executable cache for jitted XLA programs.

The TPU backend in this environment does not populate JAX's persistent
compilation cache, so every fresh process re-pays the remote XLA compile
of every engine program (~15 s each through a tunneled compiler — the
dominant cost of process startup). Compiled executables DO round-trip
through ``jax.experimental.serialize_executable`` here, so this module
wraps ``jax.jit`` with a disk cache of serialized executables:

* key = package source hash + jax version + device kind + program name
  + per-argument signature (array shape/dtype; ``repr`` for statics;
  an object's ``cache_token`` attribute when present — plans define one);
* on miss: AOT ``lower(...).compile()``, serialize, store atomically;
* off-TPU (the CPU test suite) or on any failure: plain jit.

The whole-package source hash is deliberately coarse: any source edit
invalidates every cached engine program (correctness over warm starts).
These programs recompile in ~15 s each (~3 min total for a survey), so
a content-keyed miss is an acceptable cost; the Pallas cycle kernel,
whose compiles run 10-50 MINUTES, keeps its own narrower version-keyed
cache in ops/ffa_kernel so only semantic kernel changes invalidate it.
"""
import functools
import hashlib
import json
import logging
import os
import pickle
import stat
import tempfile
import threading
import time
import weakref

import jax

from . import envflags, fsio

log = logging.getLogger("riptide_tpu.exec_cache")

__all__ = ["cached_jit", "load_or_compile_exec", "cache_root"]

# Integrity framing of on-disk entries: MAGIC + 8-hex CRC32 of the
# pickled body + newline + body. A flipped bit anywhere in the body
# fails the CRC at load, which is the difference between "recompile"
# and "deserialize attacker-grade garbage into the runtime". Entries
# without the magic are legacy (pre-framing) and load as before.
_ENTRY_MAGIC = b"RTEXEC1\n"


def _evict_corrupt(path, name, reason):
    """A cache entry failed its integrity/load check: incident-record
    it (naming the evicted path), remove it, and let the caller
    recompile — corruption must never crash or silently poison a run."""
    log.warning("exec cache entry for %s is corrupt (%s); evicting %s "
                "and recompiling", name, reason, path)
    try:
        os.remove(path)
    except OSError as err:
        log.warning("could not evict corrupt cache entry %s: %s",
                    path, err)
    try:
        from ..survey.incidents import emit
        from ..survey.metrics import get_metrics

        get_metrics().add("cache_evictions")
        emit("cache_corrupt", path=path, name=str(name),
             reason=str(reason))
    except Exception as err:  # pragma: no cover - advisory path
        log.warning("cache_corrupt incident emission failed: %s", err)


def _dir_trusted(path):
    """Whether a pre-existing cache directory is safe to load pickles
    from: a real directory (not a symlink), owned by us, with no
    group/other write bits, whose parent cannot be used to replace the
    directory wholesale — i.e. the parent is not world-writable, unless
    it has the sticky bit set (/tmp's 1777: others can neither delete
    nor rename our entry there)."""
    try:
        st = os.lstat(path)
        parent_st = os.lstat(os.path.dirname(path) or ".")
    except OSError:
        return False
    if not stat.S_ISDIR(st.st_mode):
        return False
    if st.st_uid != os.getuid():
        return False
    if st.st_mode & (stat.S_IWGRP | stat.S_IWOTH):
        return False
    if (parent_st.st_mode & stat.S_IWOTH
            and not parent_st.st_mode & stat.S_ISVTX):
        return False
    return True


def _user_tmp_cache():
    """Per-user 0700 tempdir fallback (entries are pickles: the
    directory must not be writable — or squattable — by other users).
    If the canonical per-uid name was squatted by someone else, caching
    there would execute their pickles; use a fresh ``mkdtemp`` instead
    (safe, at the price of a cold cache for this process tree)."""
    path = os.path.join(tempfile.gettempdir(),
                        f"riptide_tpu_cache_{os.getuid()}")
    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
    except OSError as err:
        log.warning("could not create tempdir cache %r (%s)", path, err)
    if _dir_trusted(path):
        return path
    try:
        fallback = tempfile.mkdtemp(prefix="riptide_tpu_cache_")
        log.warning(
            "tempdir cache %r failed the ownership/permission check "
            "(squatted or over-permissioned); using fresh %r instead",
            path, fallback,
        )
        return fallback
    except OSError as err:
        log.warning("could not create fallback cache dir (%s)", err)
        return path


def cache_root(checkout_dir=None):
    """Root directory for the on-disk executable caches.

    Precedence: ``RIPTIDE_CACHE_ROOT`` (explicit operator intent, used
    as given); a ``.riptide_cache`` directory at the checkout root (the
    package's parent) — unlike a tempdir it is guaranteed to survive
    into every later process run from the same checkout, in particular
    the driver's end-of-round benchmark run; else a per-user 0700
    tempdir. Cache entries are pickles executed at load time, so a
    PRE-EXISTING ``.riptide_cache`` is trusted only when it passes
    :func:`_dir_trusted` (ours, not group/other-writable, parent not
    world-writable); a spoofed or over-permissioned directory falls
    back to the tempdir instead of being loaded from."""
    env = envflags.get("RIPTIDE_CACHE_ROOT")
    if env:
        return env
    repo = checkout_dir or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    cand = os.path.join(repo, ".riptide_cache")
    if os.path.lexists(cand):
        if _dir_trusted(cand):
            return cand
        log.warning(
            "%r exists but is not a directory owned by uid %d with "
            "group/other write bits clear (or its parent is "
            "world-writable); falling back to the per-user tempdir cache",
            cand, os.getuid(),
        )
        return _user_tmp_cache()
    try:
        repo_st = os.lstat(repo)
    except OSError:
        return _user_tmp_cache()
    if os.access(repo, os.W_OK) and not (repo_st.st_mode & stat.S_IWOTH):
        return cand
    return _user_tmp_cache()


_DIR = (envflags.get("RIPTIDE_EXEC_CACHE")
        or os.path.join(cache_root(), "exec"))

_lock = threading.Lock()
_src_hash_memo = None


# ---------------------------------------------------------------------------
# Size-capped LRU eviction.
#
# Compiled-executable pickles are tens of MB each and the cache keys
# include a whole-package source hash, so a long-lived checkout
# accumulates dead generations without bound. Each cache directory
# keeps a manifest of {entry: {bytes, last_used}}; inserts evict the
# least-recently-used entries until the directory fits the byte cap,
# and loads refresh last_used so warm entries survive. The manifest is
# advisory — corruption or concurrent writers at worst evict
# suboptimally, never break correctness (a missing entry recompiles).
# ---------------------------------------------------------------------------

_MANIFEST = "manifest.json"
_lru_lock = threading.Lock()


def _cache_cap_bytes():
    """Byte cap per cache directory (default 2 GiB); <= 0 disables
    eviction."""
    return envflags.get("RIPTIDE_EXEC_CACHE_MAX_BYTES")


def _manifest_scan(d):
    """Rebuild manifest state from the directory contents (mtime as the
    initial last-used ordering)."""
    entries = {}
    try:
        names = os.listdir(d)
    except OSError:
        return entries
    for name in names:
        if not name.endswith(".pkl"):
            continue
        try:
            st = os.stat(os.path.join(d, name))
        except OSError:
            continue
        entries[name] = {"bytes": int(st.st_size),
                         "last_used": float(st.st_mtime)}
    return entries


def _manifest_load(d):
    try:
        with open(os.path.join(d, _MANIFEST)) as f:
            m = json.load(f)
        if isinstance(m, dict) and all(
            isinstance(v, dict) and "bytes" in v and "last_used" in v
            for v in m.values()
        ):
            return m
    except (OSError, ValueError):
        pass
    return _manifest_scan(d)


def _manifest_write(d, m):
    try:
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(m, f)
        os.replace(tmp, os.path.join(d, _MANIFEST))
    except OSError as err:
        log.debug("manifest write failed in %s (%s)", d, err)


def _lru_note(path, inserted):
    """Record a cache hit (``inserted=False``: refresh last_used) or a
    new entry (``inserted=True``: register it, then evict the oldest
    entries past the byte cap, never the one just inserted)."""
    d, name = os.path.split(path)
    with _lru_lock:
        m = _manifest_load(d)
        try:
            size = int(os.stat(path).st_size)
        except OSError:
            return
        m[name] = {"bytes": size, "last_used": time.time()}
        if inserted:
            cap = _cache_cap_bytes()
            if cap > 0:
                victims = sorted(
                    (k for k in m if k != name),
                    key=lambda k: m[k]["last_used"],
                )
                total = sum(v["bytes"] for v in m.values())
                for k in victims:
                    if total <= cap:
                        break
                    try:
                        os.remove(os.path.join(d, k))
                    except OSError:
                        pass
                    total -= m.pop(k)["bytes"]
                    log.info("evicted LRU executable-cache entry %s", k)
        _manifest_write(d, m)


def _src_hash():
    global _src_hash_memo
    if _src_hash_memo is None:
        h = hashlib.sha1()
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for root, dirs, files in os.walk(pkg):
            dirs.sort()
            for f in sorted(files):
                if f.endswith(".py"):
                    with open(os.path.join(root, f), "rb") as fh:
                        h.update(fh.read())
        h.update(jax.__version__.encode())
        _src_hash_memo = h.hexdigest()
    return _src_hash_memo


def load_or_compile_exec(path, jitted, args, kw=None, name="program",
                         info=None):
    """Deserialize a compiled executable from ``path``, or AOT-compile
    ``jitted`` at ``args``/``kw`` and store it there (atomic write,
    0700 parent dir). Returns a compiled callable taking only the ARRAY
    arguments (statics are baked in by ``lower``). When ``info`` is a
    dict, ``info['action']`` records what actually happened ('loaded'
    or 'compiled' — a corrupt entry falls through to a compile). Shared
    by the generic :func:`cached_jit` wrapper and the Pallas
    cycle-kernel cache (ops/ffa_kernel.py), which keys its entries more
    narrowly."""
    from jax.experimental import serialize_executable as se

    if info is None:
        info = {}
    if os.path.exists(path):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as err:
            raw = None
            log.warning("exec cache read failed for %s (%s); recompiling",
                        name, err)
        if raw is not None:
            body, why = _check_entry(raw)
            if body is None:
                # Detected corruption (CRC mismatch / torn frame):
                # incident, evict, fall through to a clean rebuild.
                _evict_corrupt(path, name, why)
            else:
                try:
                    payload, in_tree, out_tree = pickle.loads(body)
                    info["action"] = "loaded"
                    loaded = se.deserialize_and_load(payload, in_tree,
                                                     out_tree)
                    _lru_note(path, inserted=False)
                    return loaded
                except Exception as err:
                    # Undetectable-by-CRC badness (legacy entry rot, a
                    # jax version change mid-entry): same treatment —
                    # never crash, never keep the bad entry around.
                    _evict_corrupt(path, name, f"load failed: {err}")
    info["action"] = "compiled"
    compiled = jitted.lower(*args, **(kw or {})).compile()
    try:
        d = os.path.dirname(path)
        os.makedirs(d, mode=0o700, exist_ok=True)
        payload = se.serialize(compiled)
        body = pickle.dumps(payload)
        fsio.atomic_write_bytes(
            path, _ENTRY_MAGIC + fsio.crc32_hex(body).encode() + b"\n" + body,
            site="exec_cache_store",
        )
        _lru_note(path, inserted=True)
    except Exception as err:
        log.warning("exec cache store failed for %s (%s)", name, err)
    return compiled


def _check_entry(raw):
    """``(body, reason)`` integrity check of one on-disk entry: framed
    entries verify their CRC32 (mismatch -> ``(None, reason)``); legacy
    unframed entries pass through for the pickle layer to judge."""
    if not raw.startswith(_ENTRY_MAGIC):
        return raw, "legacy"
    head = raw[len(_ENTRY_MAGIC):]
    if len(head) < 9 or head[8:9] != b"\n":
        return None, "torn integrity header"
    want, body = head[:8].decode("ascii", "replace"), head[9:]
    got = fsio.crc32_hex(body)
    if got != want:
        return None, f"CRC mismatch (stored {want}, computed {got})"
    return body, "ok"


def _on_tpu():
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:
        return False


def _is_array(a):
    # numpy scalars (np.int64 etc.) have shape/dtype but carry VALUE
    # semantics a compiled executable bakes in — treat them as statics
    # so the cache key includes the value.
    import numpy as _np

    if isinstance(a, _np.generic):
        return False
    return hasattr(a, "shape") and hasattr(a, "dtype")


def _bump_warmth(fresh):
    """Count one executable use as cold (first sighting of its key on
    this wrapper) or warm. Best-effort: metrics live in the survey
    layer (imported lazily to keep utils dependency-free), and a
    failure to count must never fail the call being counted."""
    try:
        from ..survey.metrics import get_metrics
        get_metrics().add("exec_cold_builds" if fresh else "exec_warm_hits")
    except Exception:
        pass


# Every live _Cached wrapper, so a device-error recovery can drop ALL
# resident executables at once (weak: wrappers normally live as
# module-level decorated functions, but nothing must pin a dynamically
# created one).
_wrappers = weakref.WeakSet()


def evict_resident(reason=None):
    """Drop every resident (in-memory) compiled executable from every
    live ``cached_jit`` wrapper, forcing the next call of each to
    reload/recompile. The device-error recovery path (PR 17): after a
    non-OOM XLA runtime error the loaded device programs are suspect —
    the serialized on-disk entries are not (they were framed at compile
    time), so the disk layer stays and the rebuild is a deserialize,
    not a recompile. Returns the number of executables dropped; the
    warm/cold accounting (``_seen``) is untouched."""
    dropped = 0
    with _lock:
        for wrapper in list(_wrappers):
            dropped += len(wrapper._mem)
            wrapper._mem.clear()
    if dropped or reason:
        log.warning("evicted %d resident executable(s)%s", dropped,
                    f" ({reason})" if reason else "")
    return dropped


class _Cached:
    def __init__(self, jitted, name):
        self.jitted = jitted
        self.name = name
        self._mem = {}
        # Keys this wrapper has already served: the warm/cold split the
        # serve daemon's warm-start assertion reads (see __call__).
        self._seen = set()
        _wrappers.add(self)

    def _key(self, flat_args):
        parts = [self.name, _src_hash(), jax.devices()[0].platform,
                 getattr(jax.devices()[0], "device_kind", "")]
        for a in flat_args:
            tok = getattr(a, "cache_token", None)
            if tok is not None:
                parts.append(("t", tok))
            elif _is_array(a):
                # Sharding is part of the AOT executable's signature: a
                # dm-sharded and an unsharded call with identical shapes
                # must not share one compiled program.
                sh = getattr(a, "sharding", None)
                parts.append(("a", tuple(a.shape), str(a.dtype),
                              str(sh) if sh is not None else ""))
            else:
                parts.append(("s", repr(a)))
        return hashlib.sha1(repr(parts).encode()).hexdigest()

    def _load_or_compile(self, key, args, kw):
        return load_or_compile_exec(os.path.join(_DIR, key + ".pkl"),
                                    self.jitted, args, kw, name=self.name)

    def __get__(self, obj, objtype=None):
        # Descriptor protocol so the wrapper also works on methods
        # (static self carries the instance's cache_token).
        if obj is None:
            return self
        return functools.partial(self.__call__, obj)

    def __call__(self, *args, **kw):
        flat = list(args) + [kw[k] for k in sorted(kw)]
        # Warm/cold accounting on EVERY backend: the first call with a
        # given key is a cold build (jax.jit trace+compile, or an AOT
        # compile on TPU); later calls reuse the live executable. On
        # CPU — where the disk cache below is bypassed — jax.jit's
        # in-process cache provides the same reuse, so a long-lived
        # daemon's warm-start claim (`exec_cold_builds` flat across a
        # same-geometry job) is assertable in CPU CI.
        try:
            key = self._key(flat)
        except Exception:
            key = None
        if key is not None:
            with _lock:
                fresh = key not in self._seen
                if fresh:
                    self._seen.add(key)
            _bump_warmth(fresh)
        if not _on_tpu() or envflags.get("RIPTIDE_EXEC_CACHE") == "off" \
                or key is None:
            return self.jitted(*args, **kw)
        fn = self._mem.get(key)
        if fn is None:
            with _lock:
                fn = self._mem.get(key)
                if fn is None:
                    try:
                        fn = self._load_or_compile(key, args, kw)
                    except Exception as err:
                        log.warning("exec cache disabled for %s (%s)",
                                    self.name, err)
                        fn = self.jitted
                    self._mem[key] = fn
        if fn is self.jitted:
            return fn(*args, **kw)
        # AOT executables take only the ARRAY arguments; statics were
        # baked in at lower() time.
        darr = [a for a in flat
                if _is_array(a) and getattr(a, "cache_token", None) is None]
        return fn(*darr)


def cached_jit(fun=None, *, static_argnames=()):
    """``jax.jit`` with the cross-process executable cache. Supports the
    decorator forms ``@cached_jit`` and
    ``@cached_jit(static_argnames=...)``. Static args must be
    non-arrays (or carry a stable ``cache_token``)."""
    if fun is None:
        return functools.partial(cached_jit, static_argnames=static_argnames)
    jitted = jax.jit(fun, static_argnames=static_argnames)
    wrapper = _Cached(jitted, getattr(fun, "__qualname__", repr(fun)))
    return functools.wraps(fun)(wrapper)
