"""
Minimal sky-coordinate support (astropy is not a dependency).

Provides just what the framework needs from astropy's SkyCoord in the
reference (riptide/reading/*.py, riptide/pipeline/dmiter.py:120-133):
ICRS RA/Dec storage, parsing from PRESTO sexagesimal strings and SIGPROC
packed floats, galactic latitude (for the DM * |sin b| cap), equality,
and JSON round-tripping.
"""
import math

__all__ = ["SkyCoord", "parse_sexagesimal", "parse_sigproc_float_coord"]

# ICRS coordinates of the north galactic pole and the galactic longitude
# of the ascending node of the galactic plane (J2000, IAU definition).
_RA_NGP = math.radians(192.85948)
_DEC_NGP = math.radians(27.12825)
_L_NCP = math.radians(122.93192)


def parse_sexagesimal(s):
    """Parse '[+-]hh:mm:ss.sss' (or dd:mm:ss.sss) to a float in the
    leading unit (hours or degrees)."""
    s = s.strip()
    sign = -1.0 if s.startswith("-") else 1.0
    parts = s.lstrip("+-").split(":")
    val = 0.0
    for i, part in enumerate(parts):
        val += abs(float(part)) / 60.0**i
    return sign * val


def parse_sigproc_float_coord(f):
    """
    Parse SIGPROC's packed ddmmss.s float coordinate to hours (RA) or
    degrees (Dec) (riptide/reading/sigproc.py:148-156).
    """
    sign = -1.0 if f < 0 else 1.0
    x = abs(f)
    hh, x = divmod(x, 10000.0)
    mm, ss = divmod(x, 100.0)
    return sign * (hh + mm / 60.0 + ss / 3600.0)


class SkyCoord:
    """ICRS sky position in degrees, hashable and JSON round-trippable."""

    def __init__(self, ra_deg, dec_deg):
        self.ra_deg = float(ra_deg)
        self.dec_deg = float(dec_deg)

    @classmethod
    def from_radec_str(cls, raj, decj):
        """From PRESTO-style 'hh:mm:ss.ssss' RA and 'dd:mm:ss.ss' Dec."""
        return cls(parse_sexagesimal(raj) * 15.0, parse_sexagesimal(decj))

    @classmethod
    def from_sigproc(cls, src_raj, src_dej):
        """From SIGPROC packed-float src_raj (hours) / src_dej (degrees)."""
        return cls(parse_sigproc_float_coord(src_raj) * 15.0, parse_sigproc_float_coord(src_dej))

    @property
    def galactic(self):
        """(l, b) galactic coordinates in degrees."""
        ra = math.radians(self.ra_deg)
        dec = math.radians(self.dec_deg)
        sb = math.sin(dec) * math.sin(_DEC_NGP) + math.cos(dec) * math.cos(
            _DEC_NGP
        ) * math.cos(ra - _RA_NGP)
        b = math.asin(max(-1.0, min(1.0, sb)))
        y = math.cos(dec) * math.sin(ra - _RA_NGP)
        x = math.sin(dec) * math.cos(_DEC_NGP) - math.cos(dec) * math.sin(
            _DEC_NGP
        ) * math.cos(ra - _RA_NGP)
        l = (_L_NCP - math.atan2(y, x)) % (2.0 * math.pi)
        return math.degrees(l), math.degrees(b)

    def to_dict(self):
        return {"ra_deg": self.ra_deg, "dec_deg": self.dec_deg}

    @classmethod
    def from_dict(cls, items):
        return cls(items["ra_deg"], items["dec_deg"])

    def __eq__(self, other):
        return (
            isinstance(other, SkyCoord)
            and abs(self.ra_deg - other.ra_deg) < 1e-9
            and abs(self.dec_deg - other.dec_deg) < 1e-9
        )

    def __hash__(self):
        return hash((round(self.ra_deg, 9), round(self.dec_deg, 9)))

    def __repr__(self):
        return f"SkyCoord(ra={self.ra_deg:.6f} deg, dec={self.dec_deg:.6f} deg)"
