"""
JSON round-tripping of framework objects (TimeSeries, Periodogram,
Candidate, ...).

Any object with ``to_dict()``/``from_dict()`` serializes as a tagged dict
with ``__type__`` and ``__version__`` keys; numpy arrays are embedded as
base64, DataFrames as values+columns, SkyCoord as ra/dec degrees. Same
on-disk contract as the reference (riptide/serialization.py), and the
decoder additionally accepts the reference's 'astropy.SkyCoord' tag so
files written by riptide load here.
"""
import base64
import importlib
import json

import numpy as np

from .utils.coords import SkyCoord

__all__ = ["JSONEncoder", "object_hook", "to_json", "from_json", "save_json", "load_json"]


def _framework_version():
    return getattr(importlib.import_module("riptide_tpu"), "__version__")


def _get_class(clsname):
    # Serializable classes are all re-exported from the base package.
    return getattr(importlib.import_module("riptide_tpu"), clsname)


class JSONEncoder(json.JSONEncoder):
    """Encoder handling numpy, pandas, SkyCoord and to_dict()-able types."""

    def default(self, obj):
        if isinstance(obj, np.ndarray):
            b64_str = base64.b64encode(np.ascontiguousarray(obj).data).decode()
            return {
                "__type__": "numpy.ndarray",
                "data": b64_str,
                "dtype": str(obj.dtype),
                "shape": obj.shape,
            }
        if isinstance(obj, np.integer):
            return int(obj)
        if isinstance(obj, np.floating):
            return float(obj)
        # pandas is optional: only consult it if it is already loaded
        # (a DataFrame cannot exist otherwise).
        import sys

        pandas = sys.modules.get("pandas")
        if pandas is not None and isinstance(obj, pandas.DataFrame):
            return {
                "__type__": "pandas.DataFrame",
                "values": self.default(obj.values),
                "columns": list(obj.columns),
            }
        if isinstance(obj, SkyCoord):
            return {
                "__type__": "SkyCoord",
                "rajd": obj.ra_deg,
                "decjd": obj.dec_deg,
                "frame": "icrs",
            }
        # Anything exposing to_dict() is a framework serializable object
        if hasattr(obj, "to_dict"):
            items = obj.to_dict()
            items["__type__"] = type(obj).__name__
            if getattr(obj, "version", None):
                items["__version__"] = obj.version
            else:
                items["__version__"] = _framework_version()
            return items
        return super().default(obj)


def object_hook(items):
    if "__type__" not in items:
        return items
    typename = items["__type__"]
    if typename == "numpy.ndarray":
        data = base64.b64decode(items["data"].encode())
        return np.frombuffer(data, items["dtype"]).reshape(items["shape"]).copy()
    if typename == "pandas.DataFrame":
        import pandas

        # Decoding happens deepest-first: 'values' is already an ndarray.
        return pandas.DataFrame(items["values"], columns=items["columns"])
    if typename in ("SkyCoord", "astropy.SkyCoord"):
        return SkyCoord(items["rajd"], items["decjd"])
    cls = _get_class(typename)
    obj = cls.from_dict(items)
    obj.version = items.get("__version__", _framework_version())
    return obj


def to_json(obj, **kwargs):
    """Serialize an object to a JSON string."""
    kwargs.setdefault("cls", JSONEncoder)
    return json.dumps(obj, **kwargs)


def from_json(s):
    """De-serialize a JSON string produced by :func:`to_json`."""
    return json.loads(s, object_hook=object_hook)


def save_json(fname, obj, **kwargs):
    """Save an object to a JSON file."""
    with open(fname, "w") as fobj:
        fobj.write(to_json(obj, **kwargs))


def load_json(fname):
    """Load an object from a JSON file."""
    with open(fname, "r") as fobj:
        return from_json(fobj.read())
