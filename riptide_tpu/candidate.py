"""
Candidate: final data product of the pipeline — best-fit signal
parameters, folded sub-integrations, the associated periodogram peaks
and a diagnostic plot (reference contract: riptide/candidate.py).
"""
import logging

import numpy as np

log = logging.getLogger("riptide_tpu.candidate")

__all__ = ["Candidate"]


class Candidate:
    """
    Attributes
    ----------
    params : dict
        Best-fit parameters: period, freq, dm, width, ducy, snr.
    tsmeta : Metadata
        Metadata of the DM trial in which the candidate peaked.
    peaks : pandas.DataFrame
        Periodogram peaks associated with the candidate.
    subints : ndarray
        (num_subints, num_bins) folded sub-integrations (or 1D profile).
    """

    def __init__(self, params, tsmeta, peaks, subints):
        self.params = params
        self.tsmeta = tsmeta
        self.peaks = peaks
        self.subints = subints

    @property
    def profile(self):
        """Folded profile (sum of sub-integrations)."""
        if self.subints.ndim == 1:
            return self.subints
        return self.subints.sum(axis=0)

    @property
    def dm_curve(self):
        """(dm trials, best S/N per trial) from the associated peaks."""
        df = self.peaks.copy().groupby("dm").max()
        return df.index.values, df.snr.values

    @classmethod
    def from_pipeline_output(cls, ts, peak_cluster, bins, subints=1):
        """
        Fold the given TimeSeries at the cluster's centre period. If the
        requested number of sub-integrations does not fit in the data,
        fall back to one row per full period.
        """
        centre = peak_cluster.centre
        P0 = centre.period
        if subints is not None and subints * P0 >= ts.length:
            log.debug(
                f"Period ({P0:.3f}) x requested subints ({subints:d}) exceeds time "
                f"series length ({ts.length:.3f}), setting subints = full periods "
                "that fit in the data"
            )
            subints = None
        subints_array = ts.fold(centre.period, bins, subints=subints)
        return cls(
            centre.summary_dict(), ts.metadata, peak_cluster.summary_dataframe(), subints_array
        )

    def to_dict(self):
        return {
            "params": self.params,
            "tsmeta": self.tsmeta,
            "peaks": self.peaks,
            "subints": self.subints,
        }

    @classmethod
    def from_dict(cls, items):
        from .metadata import Metadata

        tsmeta = items["tsmeta"]
        if isinstance(tsmeta, dict) and not hasattr(tsmeta, "to_dict"):
            tsmeta = Metadata(tsmeta)
        return cls(items["params"], tsmeta, items["peaks"], items["subints"])

    def __str__(self):
        p = self.params
        return (
            f"Candidate(P0={p.get('period', float('nan')):.9f}, "
            f"DM={p.get('dm')}, S/N={p.get('snr', float('nan')):.1f})"
        )

    __repr__ = __str__

    def plot(self, figsize=(18, 4.5), dpi=80):
        """
        Four-panel diagnostic figure: sub-integrations image, folded
        profile, parameter table, and DM curve. Returns the figure.
        """
        import matplotlib.pyplot as plt
        from matplotlib.gridspec import GridSpec

        fig = plt.figure(figsize=figsize, dpi=dpi)
        gs = GridSpec(1, 4, figure=fig, width_ratios=[1.2, 1.5, 1.0, 1.2])

        p = self.params
        nbins = self.profile.size

        # Panel 1: sub-integrations
        ax = fig.add_subplot(gs[0])
        if self.subints.ndim == 2 and self.subints.shape[0] > 1:
            ax.imshow(self.subints, aspect="auto", origin="lower", cmap="Greys")
        else:
            ax.plot(self.profile, color="#303030")
        ax.set_xlabel("Phase bin")
        ax.set_ylabel("Sub-integration")
        ax.set_title("Sub-integrations")

        # Panel 2: folded profile (bar plot, like a pulse profile)
        ax = fig.add_subplot(gs[1])
        ax.bar(np.arange(nbins), self.profile, width=1.0, color="#305080")
        ax.set_xlim(-0.5, nbins - 0.5)
        ax.set_xlabel("Phase bin")
        ax.set_ylabel("Amplitude")
        ax.set_title(f"Profile (P0 = {p.get('period', float('nan')):.6f} s)")

        # Panel 3: parameter table
        ax = fig.add_subplot(gs[2])
        ax.axis("off")
        rows = []
        for key in ("period", "freq", "dm", "width", "ducy", "snr"):
            val = p.get(key)
            rows.append((key, f"{val:.6g}" if isinstance(val, float) else str(val)))
        meta_keys = ("source_name", "mjd", "fname")
        for key in meta_keys:
            val = self.tsmeta.get(key) if self.tsmeta is not None else None
            if val is not None:
                sval = str(val)
                rows.append((key, sval if len(sval) < 40 else "..." + sval[-37:]))
        table = ax.table(cellText=rows, loc="center", cellLoc="left")
        table.auto_set_font_size(False)
        table.set_fontsize(9)
        ax.set_title("Parameters")

        # Panel 4: DM curve
        ax = fig.add_subplot(gs[3])
        dms, snrs = self.dm_curve
        ax.plot(dms, snrs, marker="o", color="#803030")
        ax.set_xlabel(r"DM (pc cm$^{-3}$)")
        ax.set_ylabel("Best S/N")
        ax.set_title("DM curve")
        ax.grid(linestyle=":")

        fig.tight_layout()
        return fig

    def savefig(self, fname, **kwargs):
        """Render :meth:`plot` to a file and close the figure."""
        import matplotlib.pyplot as plt

        fig = self.plot(**kwargs)
        fig.savefig(fname)
        plt.close(fig)
