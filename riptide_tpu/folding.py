"""
Time series folding: produce the (sub-integrations, phase bins) array a
candidate plot is made of.

Behavioral contract follows the reference's fold()
(riptide/folding.py:19-81): downsample so one phase bin spans one
sample, cut into whole periods, scale so white noise keeps unit
variance, then optionally reduce the period count to ``subints`` rows.
The row reduction here is ONE vectorised real-factor downsample plan
applied to all phase-bin columns at once (an (nsub, m) weight-matrix
product in effect), not a per-column loop.
"""
import numpy as np

from .ops.reference import downsample_indices

__all__ = ["fold", "downsample_vertical"]


def downsample_vertical(X, factor):
    """
    Downsample the ROWS of a 2-D array by a real-valued ``factor``: every
    output row is the weighted sum of ~``factor`` input rows, fractional
    boundary rows split linearly (same per-axis semantics as the
    reference's downsample, riptide/cpp/downsample.hpp:44-82).

    All columns share one index/weight plan, applied in a handful of
    vectorised operations over the whole array.
    """
    X = np.asarray(X)
    m = X.shape[0]
    if not 1 < factor < m:
        raise ValueError(
            f"downsampling factor must be in (1, rows={m}), got {factor}"
        )
    imin, imax, wmin, wmax = downsample_indices(m, factor)
    x64 = X.astype(np.float64)
    cs = np.zeros((m + 1,) + X.shape[1:], np.float64)
    np.cumsum(x64, axis=0, out=cs[1:])
    interior = cs[imax] - cs[imin + 1]
    out = wmin[:, None] * x64[imin] + interior + wmax[:, None] * x64[imax]
    # float32 regardless of input dtype (integer inputs would otherwise
    # silently truncate the fractional boundary-row contributions).
    return np.ascontiguousarray(out, dtype=np.float32)


def _check_fold_args(ts, period, bins, subints):
    if period > ts.length:
        raise ValueError(
            f"cannot fold at period {period:.6f} s: longer than the "
            f"data ({ts.length:.6f} s)"
        )
    if period / bins <= ts.tsamp:
        raise ValueError(
            f"{bins} phase bins at period {period:.6f} s gives a bin "
            f"narrower than the sampling time {ts.tsamp:.2e} s"
        )
    if subints is None:
        return
    nper = ts.length / period
    if not 1 <= subints <= nper:
        raise ValueError(
            f"subints must be in [1, {int(nper)}] (whole periods in the "
            f"data), got {subints}"
        )


def fold(ts, period, bins, subints=None):
    """
    Fold a TimeSeries at ``period`` into ``bins`` phase bins.

    Returns a (subints, bins) array, or 1-D of length ``bins`` when
    ``subints`` is 1 (or only one period fits). ``subints=None`` keeps
    one row per whole period. Output is scaled by (m * factor)^-1/2 so
    unit-variance white noise stays unit variance after folding.
    """
    if subints is not None:
        subints = int(subints)
    _check_fold_args(ts, period, bins, subints)

    factor = period / (bins * ts.tsamp)
    down = ts.downsample(factor)
    m = down.nsamp // bins
    prof = down.data[: m * bins].reshape(m, bins) * (m * factor) ** -0.5

    if subints == 1 or m == 1:
        return prof.sum(axis=0)
    if subints is None or subints == m:
        return prof
    return downsample_vertical(prof, m / subints)
