"""
Time series folding (candidate sub-integration production).
Reference semantics: riptide/folding.py.
"""
import numpy as np

from .libffa import downsample

__all__ = ["fold", "downsample_vertical"]


def downsample_vertical(X, factor):
    """Downsample each column of a 2D array by a real factor (used to
    reduce sub-integration counts)."""
    m, _ = X.shape
    if not factor > 1:
        raise ValueError("factor must be > 1")
    if not factor < m:
        raise ValueError("factor must be strictly smaller than the number of input lines")
    out = np.asarray([downsample(col, factor) for col in np.ascontiguousarray(X.T)])
    return np.ascontiguousarray(out.T)


def fold(ts, period, bins, subints=None):
    """
    Fold a TimeSeries at the given period.

    Parameters
    ----------
    ts : TimeSeries
    period : float
        Period in seconds.
    bins : int
        Number of phase bins; bin width must exceed the sampling time.
    subints : int or None, optional
        Number of sub-integrations; None keeps one row per full period.

    Returns
    -------
    ndarray — (subints, bins) if subints > 1, else 1D with ``bins``
    elements. Scaled by (m * factor)^-1/2 so white noise keeps unit
    variance.
    """
    if period > ts.length:
        raise ValueError("Period exceeds data length")
    tbin = period / bins
    if not tbin > ts.tsamp:
        raise ValueError("Bin width is shorter than sampling time")
    if subints is not None:
        subints = int(subints)
        if not subints >= 1:
            raise ValueError("subints must be >= 1 or None")
        full_periods = ts.length / period
        if subints > full_periods:
            raise ValueError(
                f"subints ({subints}) exceeds the number of signal periods "
                f"that fit in the data ({full_periods})"
            )

    factor = tbin / ts.tsamp
    tsdown = ts.downsample(factor)
    m = tsdown.nsamp // bins
    folded = tsdown.data[: m * bins].reshape(m, bins)
    folded = folded * (m * factor) ** -0.5

    if subints == 1 or m == 1:
        return folded.sum(axis=0)
    if subints is None or subints == m:
        return folded
    return downsample_vertical(folded, m / subints)
