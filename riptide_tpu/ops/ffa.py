"""
FFA transform on TPU via XLA.

The transform executes as ``L`` vectorised levels over an (R, P) buffer
(see :mod:`riptide_tpu.ops.plan` for how the reference's recursion —
riptide/cpp/transforms.hpp:30-50 — is flattened into level tables). Each
level is a row gather, a per-row circular left-roll of the tail operand
(the ``fused_rollback_add`` of riptide/cpp/kernels.hpp:19-29, expressed
as a modular column gather so XLA fuses it with the add), and an add.

Two entry points:

* :func:`ffa2` — user-facing transform of a single (m, p) array.
* :func:`ffa_levels` — the raw level executor over a padded batch
  container, used by the periodogram engine and wrapped in scan/vmap.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .plan import ffa_plan

__all__ = ["ffa2", "ffa1", "ffa_levels", "ffa_transform_padded", "ffafreq", "ffaprd"]


def _level_step(buf, tables, p):
    """
    One FFA level over a batched container.

    buf : (B, R, P) float32
    tables : (3, B, R) int32 — stacked (h, t, shift)
    p : (B,) int32 — per-problem phase bin counts (columns >= p[b] are
        masked back to zero so padding stays clean)
    """
    B, R, P = buf.shape
    h, t, shift = tables[0], tables[1], tables[2]
    head = jnp.take_along_axis(buf, h[:, :, None], axis=1)
    tail = jnp.take_along_axis(buf, t[:, :, None], axis=1)
    cols = jnp.arange(P, dtype=jnp.int32)[None, None, :]
    pb = p[:, None, None]
    idx = (cols + shift[:, :, None]) % pb
    rolled = jnp.take_along_axis(tail, idx, axis=2)
    out = head + rolled
    return jnp.where(cols < pb, out, 0.0)


def ffa_levels(buf, h, t, shift, p):
    """
    Run all FFA levels over a padded batch container.

    buf : (B, R, P) float32 with rows >= m[b] all zero
    h, t, shift : (L, B, R) int32 level tables
    p : (B,) int32

    Returns the transformed (B, R, P) container; valid data is in
    ``out[b, :m[b], :p[b]]``.
    """
    tables = jnp.stack([h, t, shift], axis=1)  # (L, 3, B, R)

    def step(carry, tab):
        return _level_step(carry, tab, p), None

    out, _ = jax.lax.scan(step, buf, tables)
    return out


def ffa_transform_padded(data, m, p):
    """
    Traceable single-problem transform body: pad an (m, p) block into the
    (1, m + 1, p) zero-row container, run :func:`ffa_levels` with the
    cached plan tables, slice back. Shared by :func:`ffa2` and the
    sequence-parallel path (riptide_tpu.parallel.seqffa) so the buffer
    contract lives in one place.
    """
    plan = ffa_plan(m)
    if plan.levels == 0:
        return data
    buf = jnp.zeros((1, m + 1, p), jnp.float32).at[0, :m, :].set(data)
    out = ffa_levels(
        buf,
        jnp.asarray(plan.h)[:, None, :],
        jnp.asarray(plan.t)[:, None, :],
        jnp.asarray(plan.shift)[:, None, :],
        jnp.asarray([p], jnp.int32),
    )
    return out[0, :m, :]


_ffa2_padded = jax.jit(ffa_transform_padded, static_argnums=(1, 2))


def ffa2(data):
    """
    Compute the FFA transform of a 2D input of shape (m, p): m signal
    periods by p phase bins. Returns a float32 (m, p) array whose row s is
    the phase-drift-s folded profile.

    Equivalent of the reference's ``libffa.ffa2`` / ``libcpp.ffa2``
    (riptide/libffa.py:71-91), executed on the default JAX device.
    """
    data = np.asarray(data, dtype=np.float32)
    if data.ndim != 2:
        raise ValueError("input data must be two-dimensional")
    m, p = data.shape
    if m == 1:
        return data.copy()
    return np.asarray(_ffa2_padded(jnp.asarray(data), m, p))


def ffa1(data, p):
    """
    FFA transform of a 1D time series at base period ``p`` (in samples).
    The last ``N % p`` samples are ignored. Equivalent of
    riptide/libffa.py:94-126.
    """
    data = np.asarray(data)
    if data.ndim != 1:
        raise ValueError("input data must be one-dimensional")
    if not (isinstance(p, (int, np.integer)) and p > 0):
        raise ValueError("p must be an integer > 1")
    if p > data.size:
        raise ValueError("p must be smaller than the total number of samples")
    m = data.size // p
    return ffa2(data[: m * p].reshape(m, p))


def ffafreq(N, p, dt=1.0):
    """
    Trial frequencies of every folded profile in an FFA output
    (riptide/libffa.py:129-169): f(s) = (1/p - s/(m-1) * 1/p^2) / dt.
    """
    if not (isinstance(N, (int, np.integer)) and N > 0):
        raise ValueError("N must be a strictly positive integer")
    if not (isinstance(p, (int, np.integer)) and p > 1):
        raise ValueError("p must be an integer > 1")
    if not N >= p:
        raise ValueError("p must be smaller than (or equal to) N")
    if not dt > 0:
        raise ValueError("dt must be strictly positive")
    f0 = 1.0 / p
    m = N // p
    if m == 1:
        f = np.asarray([f0])
    else:
        s = np.arange(m)
        f = f0 - s / (m - 1.0) * f0**2
    return f / dt


def ffaprd(N, p, dt=1.0):
    """Trial periods of every folded profile in an FFA output: 1/ffafreq."""
    return 1.0 / ffafreq(N, p, dt=dt)
