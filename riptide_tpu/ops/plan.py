"""
Host-side planning for the TPU FFA transform.

The reference implements the FFA as a recursive divide-in-half of the row
axis (reference: riptide/cpp/transforms.hpp:30-50). On TPU we execute the
same computation *iteratively* as ``L = ceil(log2(m))`` data-parallel
levels over an (R, P) buffer: at each level, every output row is

    out[i] = buf[h[i]] + roll(buf[t[i]], -shift[i])

where (h, t, shift) are integer tables precomputed here on the host. This
turns the recursion into a static sequence of vectorised gather+roll+add
stages that XLA/Pallas can tile onto the VPU, with no data-dependent
control flow inside jit.

Scheduling: a tree node at depth d from the root performs its merge at
level ``L - d`` (levels are 1-indexed; level 1 runs first). Rows of nodes
that are already complete (single-row leaves) are carried unchanged
through intervening levels via identity entries that add a guaranteed
all-zero row: every plan assumes the working buffer has ``R >= m + 1``
rows with row ``R - 1`` ("Z") held at zero. Padding rows in [m, R-1) also
map to Z so they stay zero, which is what lets many differently-sized
problems share one compiled kernel (see FFABatchPlan).
"""
from functools import lru_cache

import numpy as np

from .reference import _merge_mapping

__all__ = ["ffa_plan", "FFAPlan", "batch_plans", "num_levels",
           "pair_bucket_bases", "CONTRACT_PLANS", "contract_plan_params"]


def pair_bucket_bases(ms_host, ms_guest, L, rows, skip=()):
    """Which trials of a guest bucket co-habit a host bucket's
    containers: per-trial guest base rows for embedding guest trial j
    (``ms_guest[j]`` rows) into host trial j's ``rows``-row container
    at depth ``L``, or None if ANY needed trial has no feasible base.

    ``skip`` marks trial positions that never need embedding (padding
    dummies / zero evaluated rows) — they get a None base, which the
    kernel turns into an empty guest row mask. Same-position pairing
    keeps p equal per container, which is what lets the paired kernel
    share every per-program scalar (wrap roll, column mask, widths)
    between the two trials.
    """
    from .slottables import guest_base

    bases = []
    for j, (mh, mg) in enumerate(zip(ms_host, ms_guest)):
        if j in skip:
            bases.append(None)
            continue
        gb = guest_base(mh, mg, L, rows)
        if gb is None:
            return None
        bases.append(gb)
    if not any(b is not None for b in bases):
        return None
    return tuple(bases)


# Representative search-plan parameter sets the semantic static pass
# pins program contracts for (riptide_tpu/analysis/jaxpr_contract.py,
# tools/rprove.py, tools/plan_contracts.json). Each spec names a
# PeriodogramPlan configuration plus the execution path/wire mode the
# contract describes. `fast`-tier plans are tiny (traced in tier-1 and
# on every `make prove`); the `slow` tier adds a survey-shaped plan
# (`rprove --all`, slow test tier). The two tiny plans share one
# geometry so the gather and fused-kernel formulations of the SAME
# search are pinned side by side.
CONTRACT_PLANS = (
    {"name": "tiny-gather", "tier": "fast", "path": "gather",
     "wire": "float32", "size": 2048, "tsamp": 0.01, "widths": (1, 2),
     "period_min": 1.0, "period_max": 2.0, "bins_min": 16,
     "bins_max": 24},
    {"name": "tiny-fused", "tier": "fast", "path": "kernel",
     "wire": "uint6", "size": 2048, "tsamp": 0.01, "widths": (1, 2),
     "period_min": 1.0, "period_max": 2.0, "bins_min": 16,
     "bins_max": 24},
    {"name": "survey-fused", "tier": "slow", "path": "kernel",
     "wire": "uint6", "size": 16000, "tsamp": 1e-3,
     "widths": (1, 2, 3), "period_min": 0.3, "period_max": 1.2,
     "bins_min": 64, "bins_max": 71},
)


def contract_plan_params(names=None, tiers=("fast",)):
    """Resolve the contract plan set: by explicit ``names`` (unknown
    names raise KeyError — a stale name list must fail loudly, the
    HOT_FUNCTIONS discipline), else by tier."""
    specs = [dict(s) for s in CONTRACT_PLANS]
    if names:
        wanted = set(names)
        unknown = wanted - {s["name"] for s in specs}
        if unknown:
            raise KeyError(
                f"unknown contract plan name(s) {sorted(unknown)}; "
                f"known: {[s['name'] for s in specs]}"
            )
        return [s for s in specs if s["name"] in wanted]
    return [s for s in specs if s["tier"] in tiers]


def num_levels(m):
    """Number of merge levels for an m-row transform: ceil(log2(m)), 0 for m=1."""
    if m <= 1:
        return 0
    return int(np.ceil(np.log2(m)))


class FFAPlan:
    """
    Level tables for one m-row FFA transform.

    Attributes
    ----------
    m : int
        Number of rows of the transform.
    levels : int
        Number of merge levels, ceil(log2(m)).
    h, t, shift : ndarray of int32, shape (levels, m + 1)
        Per-level gather tables over an (m + 1)-row buffer whose last row
        is held at zero. Row i of level l output is
        ``buf[h[l, i]] + roll(buf[t[l, i]], -shift[l, i])``.
    """

    def __init__(self, m):
        m = int(m)
        L = num_levels(m)
        R = m + 1
        Z = m
        # Fast path: the native plan builder fills the same tables in C++
        # (riptide_tpu/native/src/riptide_native.cpp, rn_ffa_tables);
        # parity is asserted in tests/test_native.py.
        from .. import native

        if native.available():
            self.m = m
            self.levels = L
            self.h, self.t, self.shift = native.ffa_tables(m, L)
            return
        # Identity-carry default: out[i] = buf[i] + buf[Z] (zero row).
        h = np.tile(np.arange(R, dtype=np.int32), (L, 1))
        t = np.full((L, R), Z, dtype=np.int32)
        shift = np.zeros((L, R), dtype=np.int32)
        # The zero row must reproduce itself at every level.
        if L:
            h[:, Z] = Z

        def fill(r0, mn, level):
            # Merge of the node occupying buffer rows [r0, r0 + mn) happens
            # at `level` (1-based); its children merge one level earlier.
            if mn == 1:
                return
            mh = mn // 2
            fill(r0, mh, level - 1)
            fill(r0 + mh, mn - mh, level - 1)
            hh, tt, ss = _merge_mapping(mn)
            l = level - 1
            h[l, r0 : r0 + mn] = r0 + hh
            t[l, r0 : r0 + mn] = r0 + mh + tt
            shift[l, r0 : r0 + mn] = ss

        fill(0, m, L)
        self.m = m
        self.levels = L
        self.h = h
        self.t = t
        self.shift = shift


@lru_cache(maxsize=512)
def ffa_plan(m):
    """Cached :class:`FFAPlan` for an m-row transform."""
    return FFAPlan(m)


class FFABatchPlan:
    """
    A batch of B differently-shaped FFA problems padded into one
    (B, R, P)-shaped container so they execute as a single compiled kernel.

    Problem b folds ``m[b]`` rows of ``p[b]`` phase bins; the container has
    ``R = max(m) + 1`` rows (last row zero) and ``P >= max(p)`` columns.
    Shallower plans are padded with identity levels at the end.

    Attributes (all numpy, ready to ship to device):
    h, t, shift : (L, B, R) int32 level tables
    m, p : (B,) int32 problem dimensions
    """

    def __init__(self, ms, ps, R=None, P=None, L=None):
        ms = [int(m) for m in ms]
        ps = [int(p) for p in ps]
        if len(ms) != len(ps):
            raise ValueError("ms and ps must have equal length")
        B = len(ms)
        Rmin = max(ms) + 1
        R = Rmin if R is None else int(R)
        if R < Rmin:
            raise ValueError("R must be >= max(m) + 1")
        P = max(ps) if P is None else int(P)
        if P < max(ps):
            raise ValueError("P must be >= max(p)")
        Lmin = max(num_levels(m) for m in ms)
        # Extra levels beyond a problem's own depth are identity carries;
        # padding L lets differently-deep batches share compiled kernels.
        L = Lmin if L is None else int(L)
        if L < Lmin:
            raise ValueError("L must be >= the deepest problem's level count")
        Z = R - 1

        h = np.tile(np.arange(R, dtype=np.int32), (L, B, 1))
        t = np.full((L, B, R), Z, dtype=np.int32)
        shift = np.zeros((L, B, R), dtype=np.int32)
        for b, m in enumerate(ms):
            plan = ffa_plan(m)
            lb = plan.levels
            if lb:
                h[:lb, b, : m + 1] = plan.h
                t[:lb, b, : m + 1] = plan.t
                shift[:lb, b, : m + 1] = plan.shift
                # plan's zero row is index m; remap to the container's Z.
                h[:lb, b, : m + 1] = np.where(
                    h[:lb, b, : m + 1] == m, Z, h[:lb, b, : m + 1]
                )
                t[:lb, b, : m + 1] = np.where(
                    t[:lb, b, : m + 1] == m, Z, t[:lb, b, : m + 1]
                )
            # Padding rows [m, R) map to the zero row so they stay zero
            # (t/shift already default to Z/0; rows finished before level
            # lb carry via the identity init).
            h[:, b, m:] = Z

        self.B = B
        self.R = R
        self.P = P
        self.L = L
        self.h = h
        self.t = t
        self.shift = shift
        self.m = np.asarray(ms, dtype=np.int32)
        self.p = np.asarray(ps, dtype=np.int32)


def batch_plans(ms, ps, R=None, P=None):
    """Build an :class:`FFABatchPlan` for problems of shapes zip(ms, ps)."""
    return FFABatchPlan(ms, ps, R=R, P=P)
