"""
Sliding-window ("running") median on TPU.

The reference computes an exact running median with a quickselect per
pushed sample (riptide/cpp/running_median.hpp) — inherently serial. The
TPU formulation materialises all windows of the (edge-padded) series as a
(n, width) strided gather and takes the median of each row with one
vectorised sort, which is the natural data-parallel shape for the VPU.
Memory is n*width floats, which is fine for the widths this is actually
used with: the de-reddening path always scrunches the series first so
that width <= ~2*min_points (riptide/running_medians.py:49-83).
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["running_median_jax", "scrunch_jax", "fast_running_median_jax"]


@partial(jax.jit, static_argnums=(1,))
def running_median_jax(x, width):
    """
    Exact running median of odd ``width`` with both ends padded by the edge
    values, matching riptide/cpp/running_median.hpp:100-132. x is 1D.
    """
    n = x.shape[0]
    half = width // 2
    idx = jnp.clip(
        jnp.arange(n, dtype=jnp.int32)[:, None]
        + jnp.arange(width, dtype=jnp.int32)[None, :]
        - half,
        0,
        n - 1,
    )
    windows = jnp.take(x, idx)
    return jnp.median(windows, axis=-1)


@partial(jax.jit, static_argnums=(1,))
def scrunch_jax(x, factor):
    """Mean-pool by an integer factor (riptide/running_medians.py:40-46)."""
    n = (x.shape[0] // factor) * factor
    return x[:n].reshape(-1, factor).mean(axis=1)


@partial(jax.jit, static_argnums=(1, 2))
def fast_running_median_jax(x, width, min_points=101):
    """
    Approximate running median over large windows: scrunch so that the
    window is ~min_points samples, take the exact running median at low
    resolution, and linearly interpolate back
    (riptide/running_medians.py:49-83). Window/centre conventions match
    the reference exactly (sample k of the scrunched series sits at
    original coordinate k*factor + (factor-1)/2).
    """
    # width/min_points are static_argnums: host arithmetic on trace-time
    # constants, not a sync on a traced value.
    factor = int(max(1, width / float(min_points)))  # riplint: disable=RIP001
    if factor == 1:
        return running_median_jax(x, width)
    lo = scrunch_jax(x, factor)
    rmed_lo = running_median_jax(lo, min_points)
    x_lo = jnp.arange(lo.shape[0], dtype=jnp.int32) * factor \
        + 0.5 * (factor - 1)
    return jnp.interp(jnp.arange(x.shape[0], dtype=jnp.float32), x_lo, rmed_lo)
