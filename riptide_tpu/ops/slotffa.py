"""
Slot-layout FFA planning: the gather-free formulation of the transform.

The reference computes the FFA as a recursive divide-in-half merge tree
(riptide/cpp/transforms.hpp:30-50). The round-1 TPU executor flattened
that recursion into per-level row/column *gathers* — which measure at
~100 ns/element on TPU (scalar lowering) and dominated the round-1
benchmark. This module reformulates every level as **dense** operations
only (static slices, power-of-two row/lane rolls, selects), which is
what the Pallas kernel in :mod:`riptide_tpu.ops.ffa_kernel` executes
from VMEM.

Layout
------
All 2**L tree nodes of one depth ``d`` are stored in equal *slots* of
``S_d = 2**(L-d)`` rows (last-level slots hold single rows), so a node's
rows live at ``[k*S_d, k*S_d + size(d, k))``. Key closed form (verified
against the recursion in tests): the node at depth ``d``, index ``k``
(bits of ``k`` = head/tail path from the root) folds

    size(d, k) = (m + bitrev_d(k)) >> d

rows, where ``bitrev_d(k)`` reverses the low ``d`` bits of ``k``. The
head child (2k) gets ``size >> 1`` rows, matching the reference's
``head = rows / 2`` split (riptide/cpp/block.hpp:30).

With slots in place, one merge level becomes, for every output row
``u = k*S_d + s`` (``S_c = S_d / 2``):

    out[u] = buf[u - dh(u)] + roll_p(buf[u + S_c - sigma(u)], -sigma(u))

where ``sigma(u) = s - t(s)`` is the tail phase shift of the reference
merge (riptide/cpp/transforms.hpp:13-27) and ``dh(u) = s - h(s)``. Both
row reads are *upward* shifts bounded by ``S_c + 1``, so they and the
phase roll all execute as log2-depth barrel shifts of power-of-two
rolls + selects — no gather anywhere. Tables built here (float32 index
rounding identical to the reference, via ``_merge_mapping``).
"""
from functools import lru_cache

import numpy as np

from .plan import num_levels
from .reference import _merge_mapping

__all__ = ["node_sizes", "leaf_rows", "SlotLevel", "SlotPlan", "slot_plan",
           "slot_transform_np"]


def _bitrev(k, d):
    """Reverse the low d bits of (array) k."""
    k = np.asarray(k)
    out = np.zeros_like(k)
    for i in range(d):
        out |= ((k >> i) & 1) << (d - 1 - i)
    return out


def node_sizes(m, d):
    """Row counts of all 2**d depth-d nodes of an m-row FFA tree, in slot
    order: size(d, k) = (m + bitrev_d(k)) >> d."""
    k = np.arange(1 << d, dtype=np.int64)
    return (m + _bitrev(k, d)) >> d


def leaf_rows(m, L):
    """Natural input-row index held by each of the 2**L leaf slots
    (-1 for empty slots): the exclusive cumsum of leaf sizes."""
    sz = node_sizes(m, L)
    r0 = np.concatenate(([0], np.cumsum(sz, dtype=np.int64)[:-1]))
    return np.where(sz > 0, r0, -1).astype(np.int64)


class SlotLevel:
    """Dense tables for one merge level of one problem.

    Level ``l`` (1-based) merges depth ``L-l+1`` children into depth
    ``d = L-l`` parents. All arrays have length ``rows = 2**L`` (the
    constant container height); entries of invalid rows are zero.

    Attributes
    ----------
    dh : (rows,) int64 -- head-read upward row drift, ``s - h(s)``.
    sigma : (rows,) int64 -- tail phase shift AND tail-read row drift
        (after the static ``S_c`` pre-shift), ``s - t(s)``.
    valid : (rows,) bool -- rows holding real output data.
    """

    def __init__(self, m, L, l):
        d = L - l
        S_d = 1 << l
        S_c = S_d >> 1
        rows = 1 << L
        sizes = node_sizes(m, d)          # (2**d,)
        csizes = node_sizes(m, d + 1)     # (2**(d+1),)

        dh = np.zeros(rows, np.int64)
        sigma = np.zeros(rows, np.int64)
        valid = np.zeros(rows, bool)
        for k in range(1 << d):
            mn = int(sizes[k])
            if mn == 0:
                continue
            base = k * S_d
            valid[base : base + mn] = True
            if mn == 1:
                # Children are (0, 1): the single row is carried from the
                # tail child at row base + S_c; head slot is all-zero.
                # out[base] = buf[base] (zeros) + buf[base + S_c - 0]:
                # dh = 0 reads the empty head slot, sigma = 0.
                continue
            mh = int(csizes[2 * k])
            assert mh == mn // 2, (m, L, l, k, mn, mh)
            h, t, sh = _merge_mapping(mn)
            s = np.arange(mn)
            dh[base : base + mn] = s - h
            sigma[base : base + mn] = sh  # == s - t
            # Row-read bounds that the barrel bit-width relies on.
            assert (s - h >= 0).all() and (s - h <= S_c + 1).all()
            assert (sh >= 0).all() and (sh <= S_c + 1).all()

        self.l = l
        self.S_c = S_c
        self.dh = dh
        self.sigma = sigma
        self.valid = valid


class SlotPlan:
    """All levels of an m-row transform in the 2**L slot container."""

    def __init__(self, m, L=None):
        m = int(m)
        Lmin = num_levels(m)
        L = Lmin if L is None else int(L)
        if L < Lmin:
            raise ValueError("L must be >= ceil(log2(m))")
        self.m = m
        self.L = L
        self.rows = 1 << L
        self.leaf = leaf_rows(m, L)
        self.levels = [SlotLevel(m, L, l) for l in range(1, L + 1)]


@lru_cache(maxsize=512)
def slot_plan(m, L=None):
    return SlotPlan(m, L)


def _roll_rows_up(buf, drift):
    """buf[u + drift[u]] per row, via explicit numpy take (oracle only)."""
    rows = buf.shape[0]
    idx = np.clip(np.arange(rows) + drift, 0, rows - 1)
    return buf[idx]


def slot_transform_np(data, L=None):
    """
    Numpy oracle of the slot-layout algorithm: must equal
    :func:`riptide_tpu.ops.reference.ffa_transform` exactly. Exists to
    pin down the index algebra the Pallas kernel implements with dense
    rolls; uses the same per-level (dh, sigma) tables.
    """
    data = np.asarray(data, dtype=np.float32)
    m, p = data.shape
    plan = slot_plan(m, L)
    rows = plan.rows

    buf = np.zeros((rows, p), np.float32)
    occ = plan.leaf >= 0
    buf[occ] = data[plan.leaf[occ]]

    cols = np.arange(p)
    for lev in plan.levels:
        # Head read: rows shifted up by dh within the same slot range
        # (reads the all-zero head slot for carry rows).
        head = _roll_rows_up(buf, -lev.dh)
        # Tail read: static down-shift by S_c, then up by sigma.
        tail = _roll_rows_up(buf, lev.S_c - lev.sigma)
        sig = np.mod(lev.sigma, p)[:, None]
        rolled = np.take_along_axis(tail, (cols[None, :] + sig) % p, axis=1)
        buf = np.where(lev.valid[:, None], head + rolled, 0.0).astype(np.float32)
    return buf[:m]
