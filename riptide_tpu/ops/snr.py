"""
Boxcar matched-filter S/N on TPU.

Implements the reference's profile S/N semantics
(riptide/cpp/snr.hpp:37-65): for each trial width w, slide a zero-mean,
unit-square-sum boxcar over the circularly-extended profile and take the
best phase. On TPU the circular prefix sum is a single ``cumsum`` (XLA's
log-depth scan, which also has *better* rounding than the reference's
sequential loop), and the per-width phase maximum is an elementwise
gather + subtract + masked max, all fused by XLA. Widths are vectorised
by unrolling over the (static, ~10-element) width ladder.

The batched entry point operates on the padded (B, R, P) FFA output
container of :mod:`riptide_tpu.ops.ffa`, with per-problem bin counts
``p[b]`` and noise normalisations, so one compiled kernel evaluates every
phase-bin trial of a periodogram downsampling cycle at once.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import quality
from .reference import _boxcar_coeffs

__all__ = ["boxcar_coeffs", "snr_batched", "boxcar_snr"]


def boxcar_coeffs(nbins, widths):
    """
    Height h and (negated) baseline b of a zero-mean unit-square-sum boxcar
    of each width on an ``nbins``-bin profile (riptide/cpp/snr.hpp:45-49):
    the filter is +h over w bins and -b elsewhere, h = sqrt((n-w)/(n*w)),
    b = w/(n-w) * h. Host-side, float64. Single source of truth shared
    with the numpy oracle.
    """
    return _boxcar_coeffs(nbins, widths)


def _snr_one_width(cs, total, p, w, P):
    """
    max over phase of the w-bin circular boxcar sum, for container cs.

    cs : (..., P) cumulative sum along phase with clean zero padding
    total : (..., 1) profile totals
    p : broadcastable int32 per-problem bin count
    """
    cols = jnp.arange(P, dtype=jnp.int32)
    idx = cols + w  # boxcar covering phases [j+1, j+w]
    wrap = idx >= p
    idx2 = jnp.clip(jnp.where(wrap, idx - p, idx), 0, P - 1)
    hi = jnp.take_along_axis(cs, jnp.broadcast_to(idx2, cs.shape[:-1] + (P,)), axis=-1)
    d = hi + jnp.where(wrap, total, 0.0) - cs
    d = jnp.where(cols < p, d, -jnp.inf)
    return jnp.max(d, axis=-1)


def snr_batched(tbuf, p, widths, hcoef, bcoef, stdnoise):
    """
    S/N of every row of a padded FFA output container, for every width.

    tbuf : (B, R, P) float32, clean-padded (columns >= p[b] and rows >= m[b]
        are zero)
    p : (B,) int32 per-problem phase bin counts
    widths : static tuple of ints (the boxcar width ladder)
    hcoef, bcoef : (B, NW) float32 per-(problem, width) boxcar coefficients
    stdnoise : (B,) float32 noise normalisation per problem

    Returns (B, R, NW) float32. Rows >= rows_eval are garbage to be
    discarded by the caller (they are still computed; pruning happens by
    slicing on the host, which is cheaper than dynamic shapes on TPU).

    Inputs are expected already DQ-clean: the finite guard below trips
    only on concrete host arrays (tracers pass through), since one
    non-finite profile value poisons every phase of its problem via the
    cumulative sum.
    """
    quality.check_finite_array(tbuf, where="ops.snr.snr_batched")
    B, R, P = tbuf.shape
    # float32 by design: this is the device S/N path, matching the
    # Pallas kernel's in-VMEM float32 prefix sum bit for bit.
    cs = jnp.cumsum(tbuf, axis=-1, dtype=jnp.float32)
    total = cs[..., -1:]
    pb = p[:, None, None]
    outs = []
    for iw, w in enumerate(widths):
        dmax = _snr_one_width(cs, total, pb, int(w), P)  # (B, R)
        h = hcoef[:, iw][:, None]
        b = bcoef[:, iw][:, None]
        outs.append(((h + b) * dmax - b * total[..., 0]) / stdnoise[:, None])
    return jnp.stack(outs, axis=-1)


@partial(jax.jit, static_argnums=(2,))
def _boxcar_snr_2d(data, coeffs, widths):
    m, p = data.shape
    cs = jnp.cumsum(data, axis=-1, dtype=jnp.float32)
    total = cs[..., -1:]
    outs = []
    for iw, w in enumerate(widths):
        # widths is a static_argnums tuple: trace-time host arithmetic.
        dmax = _snr_one_width(cs, total, p, int(w), p)  # riplint: disable=RIP001
        outs.append((coeffs[iw, 0] + coeffs[iw, 1]) * dmax - coeffs[iw, 1] * total[..., 0])
    return jnp.stack(outs, axis=-1)


def boxcar_snr(data, widths, stdnoise=1.0, eff_frac=1.0):
    """
    S/N of pulse profile(s) for a range of boxcar width trials; same
    contract as the reference's ``libffa.boxcar_snr``
    (riptide/libffa.py:194-225): input of any shape with phase as the last
    axis, output gains a trailing width-trial axis.

    ``eff_frac`` is the effective-nsamp fraction of the folded series
    (``nsamp_eff / nsamp``, i.e. ``1 - masked_frac`` from the
    data-quality scan): the S/N is scaled by ``1 / eff_frac`` so folds
    of partially-masked data stay on the clean S/N scale — the same
    correction ``TimeSeries.normalise(mask=...)`` applies upstream on
    the batched device path (do not apply both).
    """
    data = np.asarray(data, dtype=np.float32)
    quality.check_finite_array(data, where="ops.snr.boxcar_snr")
    if not 0.0 < eff_frac <= 1.0:
        raise ValueError("eff_frac must be in (0, 1]")
    # Integer widths only, like the reference's uint64 cast
    # (riptide/libffa.py:219); truncating BEFORE computing coefficients
    # keeps window and coefficients consistent.
    widths = np.asarray(widths).astype(np.int64)
    nbins = data.shape[-1]
    if not np.all((widths > 0) & (widths < nbins)):
        raise ValueError("trial widths must be all > 0 and < columns")
    if not stdnoise > 0:
        raise ValueError("stdnoise must be > 0")
    h, b = boxcar_coeffs(nbins, widths)
    coeffs = np.stack([h, b], axis=-1).astype(np.float32)
    flat = data.reshape(-1, nbins)
    snr = _boxcar_snr_2d(jnp.asarray(flat), jnp.asarray(coeffs), tuple(int(w) for w in widths))
    snr = np.asarray(snr) / np.float32(stdnoise)
    if eff_frac != 1.0:
        snr = snr / np.float32(eff_frac)
    return snr.reshape(list(data.shape[:-1]) + [widths.size])
