"""
Real-factor downsampling on TPU.

The reference downsamples by a real-valued factor f with fractional
boundary samples split by linear weights, always starting from the
*original* time series for each factor in the periodogram cascade
(riptide/cpp/downsample.hpp:44-82, riptide/cpp/periodogram.hpp:162-168).

The TPU formulation precomputes one prefix sum of the input and turns
every downsampling of the cascade into pure gathers:

    out[k] = wmin[k]*x[imin[k]] + (cs[imax[k]] - cs[imin[k]+1])
           + wmax[k]*x[imax[k]]

with the (imin, imax, wmin, wmax) plans built host-side in float64
(:func:`riptide_tpu.ops.reference.downsample_indices`). The prefix sum is
computed once per search in float64 on the host and shipped as a hi/lo
float32 pair; differences of nearby prefix values then cancel in the hi
part with error relative to the *difference* (Sterbenz-style), and the lo
part restores the float64 residual — giving ~float64 accuracy from pure
float32 TPU arithmetic. This both fixes the fp32 cancellation hazard and
makes every cascade cycle O(n) gathers instead of an O(N) re-scan.
"""
import jax.numpy as jnp
import numpy as np

from .reference import downsample_indices, downsampled_size, downsampled_variance

__all__ = [
    "split_prefix_sums",
    "downsample_gather",
    "downsample_plan_padded",
    "downsampled_size",
    "downsampled_variance",
]


def split_prefix_sums(data):
    """
    Host-side: inclusive prefix sum of ``data`` with a leading 0, computed
    in float64 and split into (hi, lo) float32 arrays with
    hi + lo ~= exact sum. Length is ``data.size + 1``.
    """
    cs = np.concatenate(([0.0], np.cumsum(np.asarray(data, dtype=np.float64),
                                          dtype=np.float64)))
    hi = cs.astype(np.float32)
    lo = (cs - hi).astype(np.float32)
    return hi, lo


def downsample_plan_padded(nsamp, f, nout):
    """
    Host-side downsampling plan by factor f, padded to ``nout`` output
    samples (padding entries produce exact zeros). Returns int32/float32
    numpy arrays (imin, imax, wmin, wmax) each of length ``nout``.

    Handles f == 1 as the identity (the reference aliases the input in
    that case, riptide/cpp/periodogram.hpp:162-165).
    """
    n = downsampled_size(nsamp, f)
    if n > nout:
        raise ValueError("nout too small for downsampling factor")
    imin, imax, wmin, wmax = downsample_indices(nsamp, f)
    pad = nout - n
    imin = np.concatenate([imin, np.zeros(pad, np.int64)]).astype(np.int32)
    imax = np.concatenate([imax, np.zeros(pad, np.int64)]).astype(np.int32)
    # wint masks the interior prefix-sum term so padding rows are exactly 0
    # (their boundary weights are already 0).
    wmin = np.concatenate([wmin, np.zeros(pad, np.float64)]).astype(np.float32)
    wmax = np.concatenate([wmax, np.zeros(pad, np.float64)]).astype(np.float32)
    wint = np.concatenate([np.ones(n, np.float64),
                           np.zeros(pad, np.float64)]).astype(np.float32)
    return imin, imax, wmin, wmax, wint


def downsample_gather(x, cs_hi, cs_lo, imin, imax, wmin, wmax, wint):
    """
    Device-side downsample-by-gather. All index/weight operands come from
    :func:`downsample_plan_padded`; ``cs_hi``/``cs_lo`` from
    :func:`split_prefix_sums` of the same ``x``.
    """
    interior = (jnp.take(cs_hi, imax) - jnp.take(cs_hi, imin + 1)) + (
        jnp.take(cs_lo, imax) - jnp.take(cs_lo, imin + 1)
    )
    out = wmin * jnp.take(x, imin) + wint * interior + wmax * jnp.take(x, imax)
    return out.astype(jnp.float32)
