"""
Fused Pallas TPU kernel: FFA transform + boxcar S/N for one cascade
cycle's bins-trial batch.

One grid program processes one (m_b, p_b) problem entirely in VMEM:
the container never round-trips to HBM between merge levels, which is
what makes this ~1000x faster than the round-1 gather formulation (HBM
scalar gathers measured at ~100 ns/element; the dense rolls/selects here
run at VMEM bandwidth). The operation sequence is the verified dense
algorithm of :mod:`riptide_tpu.ops.slottables` (`simulate_dense` ==
reference oracle, exact): natural K-way levels, 2-D spread, slot levels
with interleaved row-doubling + delta selects, lane barrel + mod-p wrap
select for every phase roll, then the reference's matched-filter S/N
(riptide/cpp/snr.hpp:37-65) computed from an in-VMEM prefix sum.

The grid is (D, B): D DM trials x B bins-trials. Tables, scalars and
coefficients are indexed by b only — one table set serves the whole DM
batch. Inputs per program (d, b):
  x     (D, B, rows, P)  f32 natural-packed rows (zero padded), HBM
  tab   (B, T, rows, 128) int32 packed level words (slottables layout),
        lane-replicated on device, HBM; T = NL + 2*(L - NL)
  scal  (B, SCAL_SLOTS) int32 SMEM: [0]=p, [1]=guest base row (rows
        when the trial has no row-packed guest), [2+2j], [3+2j] =
        spread roll amounts of step j (precomputed mod rows),
        [32+3j..34+3j] = the guest's three per-step amounts
  coef  (B, COEF_SLOTS) f32 SMEM: [w] = (h_w+b_w)/stdnoise,
        [NWPAD+w] = b_w/stdnoise, then the same two banks for a
        row-packed guest trial at [2*NWPAD+w] / [3*NWPAD+w]
Output:
  snr   (D, B, RS, 128) f32; lanes [0, NW) hold widths, rows [0, m)
        valid. (CycleKernel.__call__ also accepts/returns the 3-D
        single-trial forms without the D axis.)
"""
import functools
import hashlib
import logging
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

log = logging.getLogger("riptide_tpu.ffa_kernel")

from ..utils import envflags
from ..utils.compat import pallas_compiler_params
from .slottables import (A_SHIFT, A_BITS, B_SHIFT, B_BITS, NAT_LEVELS,
                         PH_BITS, PH_MASK, build_tables, combine_tables)

__all__ = ["ffa_snr_cycle", "NWPAD", "VMEM_LIMIT", "kernel_vmem_bytes",
           "WIRE_MODES", "pack_gather_words"]

NWPAD = 16  # coef slots reserved per coefficient bank
# SMEM bank widths (v7): scal [0]=p, [1]=guest base row (rows = no
# guest), [2+2j]/[3+2j] host spread rolls, [32+3j..34+3j] guest spread
# rolls; coef holds four NWPAD-slot banks (host (h+b)/std, host b/std,
# guest (h+b)/std, guest b/std). Row-packed pairs ride entirely in
# these per-trial slots — the kernel body is shared across paired and
# lone trials of one bucket.
SCAL_SLOTS = 64
COEF_SLOTS = 4 * NWPAD

# Quantised wire transports the FUSED kernel prologue can decode in
# VMEM: mode -> (group, planes). ``group`` consecutive view rows of the
# stage's (R0, PW) sample view share one packed byte-plane row;
# ``planes`` byte planes per stage (uint8 stores samples directly, the
# packed modes split each group's little-endian words into byte planes
# so the in-kernel decode is pure elementwise shifts — no byte-strided
# lane relayout, which Mosaic cannot express densely).
WIRE_MODES = {"uint8": (1, 1), "uint12": (2, 3), "uint6": (4, 3)}

# In-kernel gather-word layout for the fused (m, p) pack: one int32 per
# (problem, container row) holding
#   bits 0-10   r = (i * p) mod PW       (lane phase of the row's data)
#   bits 11-24  s = i - (i * p) // PW    (row drift; monotone in i,
#                                         increments 0/1 since p <= PW)
#   bit  31     valid (i < m)
# The kernel recovers container[i, j] = view_flat[i * p + j] as an
# MSB-first row barrel over the bits of s followed by a lane barrel over
# the bits of r: monotone unit-increment drifts compose exactly under
# the MSB-first schedule (s_i - s_{i - 2^k} <= 2^k <= s_i mod 2^{k+1}
# whenever bit k of s_i is set), so the whole pack is dense rolls +
# per-row selects — no gather, no HBM round-trip.
PK_R_BITS = 11
PK_S_SHIFT = PK_R_BITS
PK_S_BITS = 14

# Wire-plane DMA granularity (rows of the (D, WROWS, PW) wire view per
# chunk): plane extents are dynamic per stage while DMA shapes must be
# static, so planes stream in fixed 32-row chunks guarded by pl.when —
# the over-read past a stage's last plane is then < 32 rows, which the
# host covers with a 32-row tail slack per shipped wire part instead of
# a full bucket-sized one.
DMA_CHUNK = 32


def _prcap(rows, group):
    """Static per-plane row capacity of the fused decode scratch: covers
    the largest plane extent any stage in a ``rows`` bucket can need
    (ceil((rows + 1) / group) rows — n < (m + 1) * p <= (rows + 1) * PW
    bounds the view at rows + 1), rounded up to whole DMA chunks."""
    need = -(-(rows + 1) // group) + 1
    return -(-need // DMA_CHUNK) * DMA_CHUNK


def pack_gather_words(ms, ps, rows, PW, guests=None):
    """(B, rows) int32 pack words (see PK_* layout above) for one
    bucket's problems against a plan-wide view width ``PW``.

    ``guests``: optional per-problem list of ``(m_guest, base)`` (or
    None) — rows at or above ``base`` carry the GUEST trial's drift
    against its own view (which the paired kernel places at ``base``
    in the barrel source), so one MSB-first barrel packs both trials:
    a guest row's drift never exceeds its distance to ``base``, hence
    every barrel read of a live row stays inside its own region."""
    B = len(ms)
    out = np.zeros((B, rows), np.int32)
    i = np.arange(rows, dtype=np.int64)
    for bi, (m, p) in enumerate(zip(ms, ps)):
        m, p = int(m), int(p)
        q = (i * p) // PW
        r = (i * p) % PW
        s = i - q
        assert p <= PW and s.max() < (1 << PK_S_BITS), (p, PW, rows)
        assert r.max() < (1 << PK_R_BITS)
        w = r | (s << PK_S_SHIFT)
        w = np.where(i < m, w | (1 << 31), w)
        g = guests[bi] if guests else None
        if g is not None:
            mg, base = int(g[0]), int(g[1])
            ig = np.maximum(i - base, 0)
            qg = (ig * p) // PW
            wg = ((ig * p) % PW) | ((ig - qg) << PK_S_SHIFT)
            wg = np.where(ig < mg, wg | (1 << 31), wg)
            w = np.where(i >= base, wg, w)
        out[bi] = w.astype(np.int64).astype(np.int32)
    return out

# Scoped-VMEM budget shared by the kernel's CompilerParams and the
# engine's stage-eligibility check (search/engine.py:_kernel_eligible):
# deriving both from this one place means a change to the kernel's
# temporary count cannot silently break one of them. v5e has 128 MiB of
# VMEM per core.
VMEM_LIMIT = 100 * 1024 * 1024
# Live (rows, P) float32 temporaries of the unrolled select chains, by
# inspection of the deepest level's dataflow (head/tail chains + barrel)
# plus the A/B ping-pong scratch, with slack for Mosaic's own spills.
N_LIVE_BUFS = 10


def num_level_tables(L, NL):
    """Packed level-word tables per problem: NL natural + (L - NL)
    spread + (L - NL) slot."""
    return NL + 2 * (L - NL)


# Live (rows, PW) float32 temporaries of the fused prologue's pack
# barrels (Av/Bv plus the decoded view and select scratch).
N_LIVE_FUSED = 4


def kernel_vmem_bytes(L, NL, rows, P, resident_tables, fused_mode=None,
                      PW=None, gext=None):
    """Worst-case scoped-VMEM bytes of one kernel program.

    ``resident_tables=True`` accounts for the persistent all-levels
    table scratch used when the grid iterates DM trials innermost;
    ``False`` is the streaming fallback (one level table at a time).
    ``fused_mode`` adds the fused wire->container prologue's scratch
    (byte planes, decoded view, scales, pack-barrel temporaries) for a
    plan view width ``PW``. ``gext`` (row-packed pairs only) is the
    bucket's largest guest container extent: it sizes the guest wire
    scratch of the paired prologue plus its extra merge temporaries.
    """
    bufs = N_LIVE_BUFS * rows * P * 4
    extra_tab = 1 if fused_mode else 0
    ntab = (num_level_tables(L, NL) + extra_tab) if resident_tables else 1
    tot = bufs + ntab * rows * 128 * 4
    if fused_mode:
        group, planes = WIRE_MODES[fused_mode]
        prcap = _prcap(rows, group)
        tot += planes * prcap * PW              # byte-plane scratch (u8)
        tot += group * prcap * (PW * 4 + 4)     # decoded view + row scales
        tot += N_LIVE_FUSED * rows * PW * 4     # pack barrel temporaries
        if gext is not None:
            prg = _prcap(gext, group)
            tot += planes * prg * PW            # guest byte planes (u8)
            tot += group * prg * (PW * 4 + 4)   # guest view + scales
            tot += 3 * rows * PW * 4            # pad/roll/merge temporaries
    return tot


# Resident table scratches beyond this size reproducibly OOM-kill the
# Mosaic compiler service on the deep (L=11, rows 2048, ~20 MB) bucket;
# the largest observed-good scratch is the L=10 bucket's ~8.9 MB.
RESIDENT_TABLE_CAP = 12 * 1024 * 1024


def tables_resident(L, NL, rows, P, fused_mode=None, PW=None, gext=None):
    """Whether the per-bins-trial all-levels table scratch is used:
    it must fit the VMEM budget AND stay under the compiler-friendly
    size cap (larger scratches crash the Mosaic compiler — deeper
    buckets stream tables level-by-level as before).
    RIPTIDE_KERNEL_RESIDENT=0 forces streaming everywhere."""
    if not envflags.get("RIPTIDE_KERNEL_RESIDENT"):
        return False
    ntab = num_level_tables(L, NL) + (1 if fused_mode else 0)
    tab_bytes = ntab * rows * 128 * 4
    return (tab_bytes <= RESIDENT_TABLE_CAP
            and kernel_vmem_bytes(L, NL, rows, P, True, fused_mode, PW,
                                  gext)
            < VMEM_LIMIT)


def _roll_r(x, c, rows):
    """Read rows shifted: out[u] = x[u - c mod rows] (c static)."""
    c %= rows
    return x if c == 0 else pltpu.roll(x, c, axis=0)


def _lane_up(x, c, P):
    """out[..., j] = x[..., j + c mod P] (c static)."""
    c %= P
    return x if c == 0 else pltpu.roll(x, (P - c) % P, axis=1)


def _make_load_tab(tab_hbm, T, semt, b, d, resident):
    """Table loader shared by both kernel variants: ``resident`` DMAs
    the whole per-b level-table set into the persistent VMEM scratch
    once per b (at d == 0 — the grid is (B, D) with the DM trial
    innermost so D consecutive programs share tables); streaming DMAs
    one table per call. ``load_tab(lev, width)`` returns the table
    widened from its lane-replicated 128 lanes to ``width``."""
    if resident:
        @pl.when(d == 0)
        def _load_tables():
            cpt = pltpu.make_async_copy(tab_hbm.at[b], T, semt)
            cpt.start()
            cpt.wait()

        def load_tab(lev, width):
            tv = T[lev]
            return tv if width == 128 else pltpu.repeat(tv, width // 128,
                                                        axis=1)

    else:
        def load_tab(lev, width):
            cpt = pltpu.make_async_copy(tab_hbm.at[b, lev], T, semt)
            cpt.start()
            cpt.wait()
            # The words are lane-replicated in HBM; widen 128 -> width
            # lanes with a tiled repeat (a width-1 lane slice +
            # broadcast SIGABRTs the Mosaic compiler at rows >= 8
            # sublane tiles).
            tv = T[:]
            return tv if width == 128 else pltpu.repeat(tv, width // 128,
                                                        axis=1)

    return load_tab


def _kernel(scal, coef, x_hbm, tab_hbm, out_ref, A, Bs, T, semx, semt,
            *, L, NL, rows, P, RS, widths, nspread, pbits, resident,
            paired):
    b = pl.program_id(0)  # bins-trial index
    d = pl.program_id(1)  # DM-trial index (tables are shared across it)
    p = scal[b, 0]

    cp = pltpu.make_async_copy(x_hbm.at[d, b], A, semx)
    cp.start()
    load_tab = _make_load_tab(tab_hbm, T, semt, b, d, resident)
    cp.wait()
    _cascade_body(scal, coef, lambda lev: load_tab(lev, P), out_ref,
                  A, Bs, b, p, L=L, NL=NL, rows=rows, P=P, RS=RS,
                  widths=widths, nspread=nspread, pbits=pbits,
                  paired=paired)


def _cascade_body(scal, coef, load_tab, out_ref, A, Bs, b, p,
                  *, L, NL, rows, P, RS, widths, nspread, pbits,
                  paired=False):
    cols = jax.lax.broadcasted_iota(jnp.int32, (rows, P), 1)
    colmask = cols < p

    def tail_wrap(tail, sig, thr, nbits):
        for k in range(nbits):
            rolled = _lane_up(tail, 1 << k, P)
            tail = jnp.where(((sig >> k) & 1) != 0, rolled, tail)
        # wrap branch: value one extra (P - p) ahead on the ring
        wrapped = pltpu.roll(tail, p, axis=1)
        return jnp.where(cols < thr, tail, wrapped)

    bufs = [A, Bs]
    cur = 0

    # ---- natural levels -------------------------------------------------
    for l in range(1, NL + 1):
        src, dst = bufs[cur], bufs[1 - cur]
        w = load_tab(l - 1)
        valid = w < 0
        af = (w >> A_SHIFT) & ((1 << A_BITS) - 1)
        bf = (w >> B_SHIFT) & ((1 << B_BITS) - 1)
        lone = bf == (1 << B_BITS) - 1
        sv = src[:]
        head = sv
        # Head drift dh = s - h(s) is bounded by the tail child size:
        # h(s) = round(kh * s) >= kh * s - 1/2 gives dh <= s * mt /
        # (mn - 1) + 1/2 <= mt <= 2^(l-1) (asserted at table-build
        # time), so the select chain stops there — the former 2^l - 1
        # bound burnt ~2x the rolls+selects at the deepest natural
        # level for candidates no table entry can name.
        for c in range(1, (1 << (l - 1)) + 1):
            head = jnp.where(af == c, _roll_r(sv, c, rows), head)
        dst[:] = head
        tail = jnp.zeros((rows, P), jnp.float32)
        for bv in range(0, (1 << (l - 1)) + 2):
            tail = jnp.where(bf == bv, _roll_r(sv, 1 - bv, rows), tail)
        tail = tail_wrap(tail, w & PH_MASK, (w >> PH_BITS) & PH_MASK,
                         min(l, pbits))
        dst[:] = jnp.where(
            valid & colmask,
            dst[:] + jnp.where(lone, 0.0, tail),
            0.0,
        )
        cur = 1 - cur

    # ---- spread steps ---------------------------------------------------
    # Row-packed pairs add the guest trial's three candidates (its
    # depth-j block rides at in-slot offset base >> j): selects 3..5
    # against per-trial roll amounts in the guest half of the scalar
    # bank. Lone trials in a paired bucket simply never select them.
    for j in range(nspread):
        src, dst = bufs[cur], bufs[1 - cur]
        w = load_tab(NL + j)
        sel = (w >> 22) & (7 if paired else 3)
        sv = src[:]
        c1 = pltpu.roll(sv, scal[b, 2 + 2 * j], axis=0)
        c2 = pltpu.roll(sv, scal[b, 3 + 2 * j], axis=0)
        out = jnp.where(sel == 1, c1, sv)
        out = jnp.where(sel == 2, c2, out)
        if paired:
            for sv_code, slot in ((3, 32 + 3 * j), (4, 33 + 3 * j),
                                  (5, 34 + 3 * j)):
                cand = pltpu.roll(sv, scal[b, slot], axis=0)
                out = jnp.where(sel == sv_code, cand, out)
        dst[:] = jnp.where(w < 0, out, 0.0)
        cur = 1 - cur

    # ---- slot levels ----------------------------------------------------
    # Interleaved row-doubling + bounded delta selects. (A flat-container
    # alternative — log2(S_d) static-masked roll stages instead of the
    # jnp.repeat interleave — was measured 40% SLOWER on chip: 10.1 vs
    # 7.05 ms per 21-problem bucket; the interleave relayout is cheap.)
    for l in range(NL + 1, L + 1):
        src, dst = bufs[cur], bufs[1 - cur]
        w = load_tab(NL + nspread + (l - NL - 1))
        G = 1 << (L - l)
        S_d = rows >> (L - l)   # 2**l, or 3 * 2**(l-2) in a base-3 container
        S_c = S_d >> 1
        v = src[:].reshape(G, 2, S_c, P)
        reph = jnp.repeat(v[:, 0], 2, axis=1)          # (G, S_d, P)
        w3 = w.reshape(G, S_d, P)
        da = (w3 >> A_SHIFT) & 3
        head = reph
        for dv in (0, 1, 3):
            delta = dv - 2
            cand = pltpu.roll(reph, (-delta) % S_d, axis=1)
            head = jnp.where(da == dv, cand, head)
        dst[:] = head.reshape(rows, P)
        rept = jnp.repeat(v[:, 1], 2, axis=1)
        db = (w3 >> B_SHIFT) & 3
        tail = rept
        for dv in (0, 1, 3):
            delta = dv - 2
            cand = pltpu.roll(rept, (-delta) % S_d, axis=1)
            tail = jnp.where(db == dv, cand, tail)
        tail = tail.reshape(rows, P)
        tail = tail_wrap(tail, w & PH_MASK, (w >> PH_BITS) & PH_MASK,
                         min(l, pbits))
        dst[:] = jnp.where((w < 0) & colmask, dst[:] + tail, 0.0)
        cur = 1 - cur

    # ---- boxcar S/N -----------------------------------------------------
    # Computed over the full 2**L row container (RS == rows): Mosaic
    # SIGABRTs on any sublane slice of a VMEM scratch whose tile count
    # differs from the allocation, so partial-row evaluation is done by
    # the caller slicing the output instead. Padding rows are all-zero
    # after the transform and produce S/N 0.
    src = bufs[cur]
    xv = src[:]
    ccols = cols
    cs = xv
    for k in range(PH_BITS):
        if (1 << k) >= P:
            break
        sh = jnp.where(ccols >= (1 << k), pltpu.roll(cs, 1 << k, axis=1), 0.0)
        cs = cs + sh
    # Ring total per row as a lane reduction (xv is zero outside lanes
    # [0, p)); avoids slicing lane P-1, which Mosaic cannot re-broadcast.
    totc = jnp.sum(xv, axis=1, keepdims=True)
    total = jnp.broadcast_to(totc, (RS, P))
    lanes = jax.lax.broadcasted_iota(jnp.int32, (RS, 128), 1)
    acc = jnp.zeros((RS, 128), jnp.float32)
    neg = jnp.float32(-3.0e38)
    if paired:
        # Rows at or above the trial's guest base belong to the guest
        # trial: same p and widths, its own noise normalisation.
        riota = jax.lax.broadcasted_iota(jnp.int32, (RS, 1), 0)
        guestrow = riota >= scal[b, 1]
    for iw, wdt in enumerate(widths):
        aw = _lane_up(cs, wdt, P)
        bw = pltpu.roll(aw, p, axis=1)
        maskw = ccols < (p - wdt)
        d = jnp.where(maskw, aw, bw + total) - cs
        d = jnp.where(ccols < p, d, neg)
        dmax = jnp.max(d, axis=1, keepdims=True)
        snr_w = coef[b, iw] * dmax - coef[b, NWPAD + iw] * totc
        if paired:
            gsnr = (coef[b, 2 * NWPAD + iw] * dmax
                    - coef[b, 3 * NWPAD + iw] * totc)
            snr_w = jnp.where(guestrow, gsnr, snr_w)
        acc = acc + jnp.where(lanes == iw, jnp.broadcast_to(snr_w, (RS, 128)), 0.0)
    out_ref[0, 0] = acc


def _wire_chunk_copy(stagevec, svoff, wire_hbm, WB, semw, d, pi, c):
    """Async copy of one static DMA_CHUNK of plane ``pi`` of the stage
    slice whose [row offset, plane rows] sit at ``stagevec[0, svoff:]``
    (svoff 0 = the host stage, 4 = a row-packed guest stage)."""
    roff = stagevec[0, svoff]
    pr = stagevec[0, svoff + 1]
    return pltpu.make_async_copy(
        wire_hbm.at[d, pl.ds(roff + pi * pr + c * DMA_CHUNK, DMA_CHUNK)],
        WB.at[pi, pl.ds(c * DMA_CHUNK, DMA_CHUNK)],
        semw.at[pi, c],
    )


def _decode_planes(WB, SC, r0, *, mode, R0C, PW):
    """Byte planes -> dequantised (R0C, PW) sample view.

    Elementwise only: the host's plane layout groups `group`
    consecutive view rows per plane row, so the bit extraction never
    crosses lanes; the group interleave is a sublane stack/reshape
    (the same relayout family as the slot phase's row-doubling).
    Operation order matches engine._u*_decode_view exactly, so the
    fused container is BIT-identical to the XLA pack path's. Rows
    beyond the stage's ``r0`` view rows are DMA over-read garbage
    (possibly times a non-finite scale): zeroed BEFORE the barrels."""
    if mode == "uint8":
        xq = WB[0].astype(jnp.float32) - 128.0
    else:
        b0 = WB[0].astype(jnp.int32)
        b1 = WB[1].astype(jnp.int32)
        b2 = WB[2].astype(jnp.int32)
        if mode == "uint6":
            word = b0 | (b1 << 8) | (b2 << 16)
            qs = [((word >> (6 * j)) & 63).astype(jnp.float32) - 32.0
                  for j in range(4)]
        else:  # uint12
            qs = [(b0 | ((b1 & 15) << 8)).astype(jnp.float32) - 2048.0,
                  ((b1 >> 4) | (b2 << 4)).astype(jnp.float32) - 2048.0]
        xq = jnp.stack(qs, axis=1).reshape(R0C, PW)
    rowi = jax.lax.broadcasted_iota(jnp.int32, (R0C, PW), 0)
    x = xq * jnp.broadcast_to(SC[:], (R0C, PW))
    return jnp.where(rowi < r0, x, 0.0)


def _fused_kernel(stagevec, scal, coef, wire_hbm, scales_hbm, tab_hbm,
                  out_ref, A, Bs, T, WB, SC, semt, semw, sems,
                  *, mode, L, NL, rows, P, RS, widths, nspread, pbits,
                  sbits, resident, PW):
    """Single-dispatch cascade stage: wire decode + dequant + (m, p)
    pack + FFA + boxcar S/N in ONE Pallas program. The per-stage wire
    bytes arrive as a slice of the shipped (D, WROWS, PW) byte-plane
    view (dynamic row offsets from the SMEM stage vector, streamed in
    static DMA_CHUNK-row chunks), so the former per-stage XLA pack
    program — and its full (D, B, rows, P) f32 container round-trip
    through HBM — disappears entirely."""
    b = pl.program_id(0)  # bins-trial index
    d = pl.program_id(1)  # DM-trial index (tables are shared across it)
    p = scal[b, 0]
    pr = stagevec[0, 1]     # stage's rows per byte plane
    soff = stagevec[0, 2]   # stage's scale row offset
    r0 = stagevec[0, 3]     # stage's view rows (= ceil(n / PW))
    group, planes = WIRE_MODES[mode]
    PR = _prcap(rows, group)
    R0C = group * PR
    NCH = PR // DMA_CHUNK

    cps = pltpu.make_async_copy(
        scales_hbm.at[d, pl.ds(soff, R0C)], SC, sems
    )
    cps.start()

    # Start every needed wire chunk (plane extents are dynamic, chunk
    # shapes static), then overlap the per-b table DMA with the stream.
    for pi in range(planes):
        for c in range(NCH):
            @pl.when(c * DMA_CHUNK < pr)
            def _start(pi=pi, c=c):
                _wire_chunk_copy(stagevec, 0, wire_hbm, WB, semw, d,
                                 pi, c).start()

    load_tab = _make_load_tab(tab_hbm, T, semt, b, d, resident)

    for pi in range(planes):
        for c in range(NCH):
            @pl.when(c * DMA_CHUNK < pr)
            def _wait(pi=pi, c=c):
                # Pallas async-copy semaphore wait (DMA completion
                # inside the kernel body), not a thread wait.
                _wire_chunk_copy(  # riplint: disable=RIP004
                    stagevec, 0, wire_hbm, WB, semw, d, pi, c).wait()
    cps.wait()

    x = _decode_planes(WB, SC, r0, mode=mode, R0C=R0C, PW=PW)
    y = x[:rows]  # R0C >= rows + 1 by _prcap construction
    _pack_and_cascade(scal, coef, load_tab, out_ref, A, Bs, b, p, y,
                      L=L, NL=NL, rows=rows, P=P, RS=RS, widths=widths,
                      nspread=nspread, pbits=pbits, sbits=sbits, PW=PW,
                      paired=False)


def _pack_and_cascade(scal, coef, load_tab, out_ref, A, Bs, b, p, y,
                      *, L, NL, rows, P, RS, widths, nspread, pbits,
                      sbits, PW, paired):
    """Pack the (rows, PW) barrel source ``y`` into the (m, p)
    container — container[i, j] = y_flat[i * p + j] — and run the
    cascade. For a row-packed pair, ``y`` is the row-wise merge of the
    host view (below the trial's guest base) and the guest view
    (placed AT the base): every barrel read of a live row stays inside
    its own region (drift <= distance to the region floor whenever the
    selecting bit is set), so ONE barrel packs both trials."""
    pw = load_tab(0, PW)
    rphase = pw & ((1 << PK_R_BITS) - 1)
    sdrift = (pw >> PK_S_SHIFT) & ((1 << PK_S_BITS) - 1)
    av = y                     # will become view[q_i, (j + r_i) mod PW]
    bv = _roll_r(y, -1, rows)  # and view[q_i + 1, ...] for the wrap
    # MSB-first row barrel over the monotone drift s_i = i - q_i: exact
    # because s has unit increments (see pack_gather_words).
    for k in reversed(range(sbits)):
        take = ((sdrift >> k) & 1) != 0
        av = jnp.where(take, pltpu.roll(av, 1 << k, axis=0), av)
        bv = jnp.where(take, pltpu.roll(bv, 1 << k, axis=0), bv)
    for k in range((PW - 1).bit_length()):
        take = ((rphase >> k) & 1) != 0
        av = jnp.where(take, _lane_up(av, 1 << k, PW), av)
        bv = jnp.where(take, _lane_up(bv, 1 << k, PW), bv)
    colsw = jax.lax.broadcasted_iota(jnp.int32, (rows, PW), 1)
    xpk = jnp.where(colsw < (PW - rphase), av, bv)
    xpk = jnp.where((pw < 0) & (colsw < p), xpk, 0.0)
    if P < PW:
        # Lane-split sub-buckets run the merge tree at their own (
        # narrower) container width; the view width is plan-wide.
        xpk = xpk[:, :P]
    A[:] = xpk
    _cascade_body(scal, coef, lambda lev: load_tab(1 + lev, P), out_ref,
                  A, Bs, b, p, L=L, NL=NL, rows=rows, P=P, RS=RS,
                  widths=widths, nspread=nspread, pbits=pbits,
                  paired=paired)


def _fused_kernel_paired(stagevec, scal, coef, wire_hbm, gwire_hbm,
                         scales_hbm, tab_hbm, out_ref, A, Bs, T, WB, SC,
                         WG, SG, semt, semw, sems, semw2, sems2,
                         *, mode, L, NL, rows, P, RS, widths, nspread,
                         pbits, sbits, resident, PW, gext):
    """Row-packed variant of :func:`_fused_kernel`: ONE program serves
    the host stage's trial AND a guest stage's same-p trial riding in
    the host container's dead rows. The guest stage's wire slice (a
    second shipped part; stagevec slots 4..7) streams into its own
    scratch, decodes identically, and is row-merged into the pack
    barrel source at the trial's guest base — the barrels, merge tree
    and S/N then run ONCE over the combined per-row tables."""
    b = pl.program_id(0)
    d = pl.program_id(1)
    p = scal[b, 0]
    pr = stagevec[0, 1]
    soff = stagevec[0, 2]
    r0 = stagevec[0, 3]
    prg = stagevec[0, 5]
    gsoff = stagevec[0, 6]
    gr0 = stagevec[0, 7]
    group, planes = WIRE_MODES[mode]
    PR = _prcap(rows, group)
    R0C = group * PR
    NCH = PR // DMA_CHUNK
    PRG = _prcap(gext, group)
    R0G = group * PRG
    NCHG = PRG // DMA_CHUNK

    cps = pltpu.make_async_copy(
        scales_hbm.at[d, pl.ds(soff, R0C)], SC, sems
    )
    cps.start()
    cps2 = pltpu.make_async_copy(
        scales_hbm.at[d, pl.ds(gsoff, R0G)], SG, sems2
    )
    cps2.start()

    for pi in range(planes):
        for c in range(NCH):
            @pl.when(c * DMA_CHUNK < pr)
            def _start(pi=pi, c=c):
                _wire_chunk_copy(stagevec, 0, wire_hbm, WB, semw, d,
                                 pi, c).start()
        for c in range(NCHG):
            @pl.when(c * DMA_CHUNK < prg)
            def _gstart(pi=pi, c=c):
                _wire_chunk_copy(stagevec, 4, gwire_hbm, WG, semw2, d,
                                 pi, c).start()

    load_tab = _make_load_tab(tab_hbm, T, semt, b, d, resident)

    # Pallas async-copy semaphore waits (DMA completion inside the
    # kernel body), not thread waits — no timeout API exists.
    for pi in range(planes):
        for c in range(NCH):
            @pl.when(c * DMA_CHUNK < pr)
            def _wait(pi=pi, c=c):
                _wire_chunk_copy(  # riplint: disable=RIP004
                    stagevec, 0, wire_hbm, WB, semw, d, pi, c).wait()
        for c in range(NCHG):
            @pl.when(c * DMA_CHUNK < prg)
            def _gwait(pi=pi, c=c):
                _wire_chunk_copy(  # riplint: disable=RIP004
                    stagevec, 4, gwire_hbm, WG, semw2, d, pi, c).wait()
    cps.wait()
    cps2.wait()  # riplint: disable=RIP004

    x = _decode_planes(WB, SC, r0, mode=mode, R0C=R0C, PW=PW)
    y = x[:rows]
    xg = _decode_planes(WG, SG, gr0, mode=mode, R0C=R0G, PW=PW)
    # Place the guest view AT the trial's guest base: pad its rows to
    # the container height, roll down by the (per-trial, SMEM) base and
    # row-select. Rows below the base keep the host view; the roll's
    # wrapped rows land only there and are therefore never read.
    if R0G >= rows:
        ygf = xg[:rows]
    else:
        ygf = jnp.concatenate(
            [xg, jnp.zeros((rows - R0G, PW), jnp.float32)], axis=0)
    gb = scal[b, 1]  # guest base row; == rows for a guestless trial
    rolled = pltpu.roll(ygf, gb, axis=0)
    rowi = jax.lax.broadcasted_iota(jnp.int32, (rows, PW), 0)
    y = jnp.where(rowi >= gb, rolled, y)
    _pack_and_cascade(scal, coef, load_tab, out_ref, A, Bs, b, p, y,
                      L=L, NL=NL, rows=rows, P=P, RS=RS, widths=widths,
                      nspread=nspread, pbits=pbits, sbits=sbits, PW=PW,
                      paired=True)


def _pack_scal(tables, rows):
    """(B, SCAL_SLOTS) int32 scalar bank for one bucket's problems.
    Tables from :func:`slottables.combine_tables` (row-packed pairs)
    fill the guest half: [1] = guest base row (``rows`` marks a
    guestless trial so the kernel's guest row masks come up empty) and
    [32+3j..34+3j] = the guest's three spread-roll amounts per step."""
    B = len(tables)
    scal = np.zeros((B, SCAL_SLOTS), np.int32)
    for i, t in enumerate(tables):
        scal[i, 0] = t.p
        gbase = getattr(t, "gbase", 0)
        scal[i, 1] = gbase if gbase else rows
        for j, A in enumerate(t.spread):
            half = rows >> (j + 1)
            scal[i, 2 + 2 * j] = (half - A) % rows
            scal[i, 3 + 2 * j] = (half - A - 1) % rows
        if gbase:
            for j, (Ag, aj, an) in enumerate(t.gspread):
                half = rows >> (j + 1)
                scal[i, 32 + 3 * j] = (an - aj) % rows
                scal[i, 33 + 3 * j] = (an - aj + half - Ag) % rows
                scal[i, 34 + 3 * j] = (an - aj + half - Ag - 1) % rows
    return scal


def _pack_coef(ps, widths, hcoef, bcoef, stdnoise, ghcoef=None,
               gbcoef=None, gstdnoise=None):
    """(B, COEF_SLOTS) f32 coefficient bank: (h+b)/std then b/std in
    the first two NWPAD blocks; a row-packed bucket's guest trials fill
    the third and fourth (same layout, the guest's normalisation)."""
    B = len(ps)
    nw = len(widths)
    coef = np.zeros((B, COEF_SLOTS), np.float32)
    coef[:, :nw] = (hcoef + bcoef) / stdnoise[:, None]
    coef[:, NWPAD : NWPAD + nw] = bcoef / stdnoise[:, None]
    if gstdnoise is not None:
        coef[:, 2 * NWPAD : 2 * NWPAD + nw] = (
            (ghcoef + gbcoef) / gstdnoise[:, None])
        coef[:, 3 * NWPAD : 3 * NWPAD + nw] = gbcoef / gstdnoise[:, None]
    return coef


# ---------------------------------------------------------------------------
# Persistent executable cache.
#
# Mosaic/Pallas executables are NOT stored in JAX's persistent
# compilation cache (only plain XLA programs are), so every fresh
# process pays the full multi-minute kernel compile. The compiled
# executable, however, serializes and reloads across processes in ~0.1 s
# (jax.experimental.serialize_executable), which is what turns a cold
# ~10-minute survey warmup into seconds on a warm cache. Keyed by an
# explicit format-version constant, jax version, device kind and the
# full build key; any failure falls back to the ordinary jit path.
# ---------------------------------------------------------------------------

# Version of everything a compiled kernel executable depends on that the
# build key does not carry: this file's kernel body and slottables'
# packed-word/table layout. BUMP THIS on any semantic change to either
# (a stale executable with a mismatched table layout computes wrong
# numbers, not a crash). Comment/docstring edits need no bump — keying
# on an explicit version instead of file contents is what lets a cache
# warmed during a build round stay valid for the driver's fresh-process
# benchmark run afterwards (round 4 recorded no number because content
# keying invalidated every entry, VERDICT r4 item 1).
# v6: fused wire->kernel stages (decode + dequant + pack moved into the
# kernel prologue, pack-word table prepended at index 0), natural-level
# head-chain trim to the provable 2^(l-1) drift bound.
# v7: row-packed containers (a second same-p bins-trial embedded in the
# dead rows via per-row table indirection: guest spread selects 3..5,
# guest halves of the SMEM banks — scal widened to 64 slots, coef to
# 4 * NWPAD — paired fused/two-dispatch kernel bodies) and the odd-slot
# container forms 5/7 * 2^(L-3).
KERNEL_CACHE_VERSION = 7


def _hash_code_object(h, code):
    """Feed one code object (and its nested code objects) into a hash:
    raw bytecode plus the global/attribute names it references. Local
    variable names and docstrings are excluded — renames and comment or
    docstring edits are exactly the changes that must NOT demand a
    KERNEL_CACHE_VERSION bump."""
    import types as _types

    h.update(code.co_code)
    h.update("\0".join(code.co_names).encode())
    consts = code.co_consts
    if consts and isinstance(consts[0], str):
        consts = consts[1:]  # docstring slot
    for c in consts:
        if isinstance(c, _types.CodeType):
            _hash_code_object(h, c)
        else:
            h.update(repr(c).encode())


def kernel_code_digest():
    """Bytecode digest of everything :data:`KERNEL_CACHE_VERSION`
    vouches for: this file's kernel body and packing helpers, and
    slottables' table builders / packed-word layout. The guard test
    pins (version, digest) pairs so a semantic edit to any of these
    without a version bump fails CI — a stale cached executable with a
    mismatched table layout computes wrong numbers, not a crash. The
    digest is bytecode-based and therefore specific to the running
    Python's major.minor version."""
    from . import slottables

    h = hashlib.sha1()
    for fn in (_kernel, _fused_kernel, _fused_kernel_paired,
               _pack_and_cascade, _decode_planes, _wire_chunk_copy,
               _cascade_body, _make_load_tab,
               pack_gather_words, _pack_scal, _pack_coef,
               slottables.pack_word, slottables.build_tables,
               slottables.combine_tables, slottables.guest_base,
               slottables._merge_tables, slottables.container_rows,
               slottables.container_forms):
        h.update(fn.__name__.encode())
        _hash_code_object(h, fn.__code__)
    return h.hexdigest()


_EXEC_DIR = None


def _exec_dir():
    global _EXEC_DIR
    if _EXEC_DIR is None:
        from ..utils.exec_cache import cache_root

        _EXEC_DIR = (envflags.get("RIPTIDE_KERNEL_CACHE")
                     or os.path.join(cache_root(), "kernel"))
    return _EXEC_DIR


def _exec_cache_path(key):
    h = hashlib.sha1()
    h.update(f"kernel-format-v{KERNEL_CACHE_VERSION}".encode())
    h.update(jax.__version__.encode())
    dev = jax.devices()[0]
    h.update(f"{dev.platform}:{getattr(dev, 'device_kind', '')}".encode())
    h.update(repr(key).encode())
    return os.path.join(_exec_dir(), h.hexdigest() + ".pkl")


class _CachedCall:
    """Lazily compiled pallas call with a cross-process executable cache
    (TPU backends only; CPU/interpret use the plain jit path)."""

    def __init__(self, key, jitted, arg_shapes):
        self.key = key
        self.jitted = jitted
        self.arg_shapes = arg_shapes
        self._fn = None
        self._lock = threading.Lock()
        # Set by warm(): 'loaded' | 'compiled' | 'jit', and the seconds
        # the warm took — warm_stage_kernels logs these per bucket so a
        # slow cold start names its pole (VERDICT r4 item 1b).
        self.source = None
        self.warm_seconds = 0.0

    def _aot_args(self):
        return [jax.ShapeDtypeStruct(s, d) for s, d in self.arg_shapes]

    def warm(self):
        """Compile (or load) the executable without running it."""
        from ..utils.exec_cache import load_or_compile_exec

        with self._lock:
            if self._fn is not None:
                return
            try:
                tpu = jax.default_backend() in ("tpu", "axon")
            except RuntimeError:
                tpu = False
            if not tpu or envflags.get("RIPTIDE_KERNEL_CACHE") == "off":
                self._fn = self.jitted
                self.source = "jit"
                return
            t0 = time.perf_counter()
            info = {}
            try:
                self._fn = load_or_compile_exec(
                    _exec_cache_path(self.key), self.jitted,
                    self._aot_args(), name=f"cycle_kernel{self.key}",
                    info=info,
                )
                self.source = info.get("action", "compiled")
            except Exception as err:
                log.warning("AOT kernel compile failed (%s); "
                            "falling back to jit", err)
                self._fn = self.jitted
                self.source = "jit"
            self.warm_seconds = time.perf_counter() - t0

    def __call__(self, *args):
        if self._fn is None:
            self.warm()
        return self._fn(*args)


@functools.lru_cache(maxsize=64)
def _build_call(L, NL, rows, P, RS, widths, nspread, pbits, D, B,
                interpret, paired=False):
    resident = tables_resident(L, NL, rows, P)
    kern = functools.partial(
        _kernel, L=L, NL=NL, rows=rows, P=P, RS=RS,
        widths=widths, nspread=nspread, pbits=pbits, resident=resident,
        paired=paired,
    )
    ntab = num_level_tables(L, NL)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(B, D),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, RS, 128), lambda b, d: (d, b, 0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, P), jnp.float32),
            pltpu.VMEM((rows, P), jnp.float32),
            pltpu.VMEM((ntab, rows, 128) if resident else (rows, 128),
                       jnp.int32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    call = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((D, B, RS, 128), jnp.float32),
        # The unrolled select chains keep ~8 (rows, P) f32 temporaries
        # live; at the deepest bucket (2048, 384) that exceeds the 16M
        # default scoped-vmem limit (budget shared with the engine's
        # eligibility check via kernel_vmem_bytes).
        compiler_params=pallas_compiler_params(vmem_limit_bytes=VMEM_LIMIT),
        interpret=bool(interpret),
    )
    jitted = jax.jit(call)
    if interpret:
        return jitted
    key = (L, NL, rows, P, RS, widths, nspread, pbits, D, B, resident,
           paired)
    arg_shapes = (
        ((B, SCAL_SLOTS), jnp.int32),
        ((B, COEF_SLOTS), jnp.float32),
        ((D, B, rows, P), jnp.float32),
        ((B, ntab, rows, 128), jnp.int32),
    )
    return _CachedCall(key, jitted, arg_shapes)


@functools.lru_cache(maxsize=128)
def _build_fused_call(mode, L, NL, rows, P, RS, widths, nspread, pbits,
                      sbits, D, B, PW, wrows, srows, interpret,
                      gext=None, gwrows=None):
    """Compiled fused wire->container->FFA->S/N program (one device
    dispatch per cascade stage). Keyed like :func:`_build_call` plus the
    wire mode, plan view width and the shipped wire/scale row counts
    (the last two only retrace, never re-bucket — the kernel body and
    scratch shapes depend on (mode, rows, P, PW) alone, so stages
    sharing a shape bucket share one Mosaic build exactly as before).
    ``gext``/``gwrows`` (row-packed pairs) select the paired kernel: a
    second wire-part operand of ``gwrows`` rows and a guest decode
    scratch sized for ``gext`` container rows."""
    paired = gext is not None
    resident = tables_resident(L, NL, rows, P, fused_mode=mode, PW=PW,
                               gext=gext)
    group, planes = WIRE_MODES[mode]
    PR = _prcap(rows, group)
    if paired:
        kern = functools.partial(
            _fused_kernel_paired, mode=mode, L=L, NL=NL, rows=rows, P=P,
            RS=RS, widths=widths, nspread=nspread, pbits=pbits,
            sbits=sbits, resident=resident, PW=PW, gext=gext,
        )
    else:
        kern = functools.partial(
            _fused_kernel, mode=mode, L=L, NL=NL, rows=rows, P=P, RS=RS,
            widths=widths, nspread=nspread, pbits=pbits, sbits=sbits,
            resident=resident, PW=PW,
        )
    ntab = num_level_tables(L, NL) + 1  # + the pack-word table (index 0)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),   # stage vector (1, 8)
        pl.BlockSpec(memory_space=pltpu.SMEM),   # scal (B, SCAL_SLOTS)
        pl.BlockSpec(memory_space=pltpu.SMEM),   # coef (B, COEF_SLOTS)
        pl.BlockSpec(memory_space=pl.ANY),       # wire (D, wrows, PW)
        pl.BlockSpec(memory_space=pl.ANY),       # scales (D, srows, 1)
        pl.BlockSpec(memory_space=pl.ANY),       # tables
    ]
    scratch = [
        pltpu.VMEM((rows, P), jnp.float32),
        pltpu.VMEM((rows, P), jnp.float32),
        pltpu.VMEM((ntab, rows, 128) if resident else (rows, 128),
                   jnp.int32),
        pltpu.VMEM((planes, PR, PW), jnp.uint8),
        pltpu.VMEM((group * PR, 1), jnp.float32),
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((planes, PR // DMA_CHUNK)),
        pltpu.SemaphoreType.DMA,
    ]
    if paired:
        PRG = _prcap(gext, group)
        # guest wire part after the host's (stagevec slots 4..7)
        in_specs.insert(4, pl.BlockSpec(memory_space=pl.ANY))
        scratch[5:5] = [
            pltpu.VMEM((planes, PRG, PW), jnp.uint8),     # WG
            pltpu.VMEM((group * PRG, 1), jnp.float32),    # SG
        ]
        scratch += [
            pltpu.SemaphoreType.DMA((planes, PRG // DMA_CHUNK)),  # semw2
            pltpu.SemaphoreType.DMA,                              # sems2
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(B, D),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, RS, 128), lambda b, d: (d, b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=scratch,
    )
    call = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((D, B, RS, 128), jnp.float32),
        compiler_params=pallas_compiler_params(vmem_limit_bytes=VMEM_LIMIT),
        interpret=bool(interpret),
    )
    jitted = jax.jit(call)
    if interpret:
        return jitted
    key = ("fused", mode, L, NL, rows, P, RS, widths, nspread, pbits,
           sbits, D, B, PW, wrows, srows, resident, gext, gwrows)
    arg_shapes = [
        ((1, 8), jnp.int32),
        ((B, SCAL_SLOTS), jnp.int32),
        ((B, COEF_SLOTS), jnp.float32),
        ((D, wrows, PW), jnp.uint8),
        ((D, srows, 1), jnp.float32),
        ((B, ntab, rows, 128), jnp.int32),
    ]
    if paired:
        arg_shapes.insert(4, ((D, gwrows, PW), jnp.uint8))
    return _CachedCall(key, jitted, tuple(arg_shapes))


def bucket_rows(ms, L):
    """Container height for a bucket of problems ``ms`` at depth L
    under the live container-family flags: 2**L only when
    RIPTIDE_KERNEL_BASE3=0, the {2**L, 3 * 2**(L-2)} family otherwise,
    plus the odd-slot 5/7 * 2**(L-3) forms when the row-pack layout is
    on. THE single source of the flag->family mapping — CycleKernel and
    the engine's eligibility/occupancy models all derive from it."""
    from .slottables import container_rows

    if not envflags.get("RIPTIDE_KERNEL_BASE3"):
        return 1 << L
    return container_rows(max(ms), L,
                          extended=bool(envflags.get(
                              "RIPTIDE_KERNEL_ROW_PACK")))


class CycleKernel:
    """Host-side bundle: tables + jitted pallas call for one bucket.

    Parameters
    ----------
    ms, ps : per-problem row/bin counts (equal length B)
    widths : static boxcar ladder
    hcoef, bcoef : (B, NW) float arrays
    stdnoise : (B,) float
    L : bucket depth (>= max over ceil(log2 m))
    guests : optional row-pack spec — a second stage's same-p trials
        riding in this bucket's dead container rows: dict with ``ms``
        (per-trial guest row counts), ``bases`` (per-trial guest base
        row or None for no guest on that trial), ``hcoef``/``bcoef``/
        ``stdnoise`` (the guest trials' own normalisation). Bases must
        be feasible per :func:`slottables.guest_base`.
    """

    def __init__(self, ms, ps, widths, hcoef, bcoef, stdnoise, L=None,
                 interpret=False, guests=None):
        ms = [int(m) for m in ms]
        ps = [int(p) for p in ps]
        widths = tuple(int(w) for w in widths)
        # The packed-word layout carries sigma/thr in PH_BITS-wide fields
        # and the boxcar prefix scan covers a 2**PH_BITS-lane window, so
        # p is capped at PH_MASK (callers fall back to the XLA gather
        # path beyond it).
        if max(ps) > PH_MASK:
            raise ValueError(
                f"CycleKernel supports p <= {PH_MASK} ({PH_BITS}-bit "
                f"packed phase fields); got max p = {max(ps)}"
            )
        # One static width ladder serves the whole bucket: every width
        # must be a valid trial for the smallest problem, mirroring the
        # reference's check_trial_widths (riptide/cpp/snr.hpp:14-31).
        if not widths or min(widths) < 1 or max(widths) >= min(ps):
            raise ValueError("trial widths must satisfy 0 < w < min(p)")
        if len(widths) > NWPAD:
            raise ValueError(f"at most {NWPAD} trial widths supported")
        from .plan import num_levels

        Lmin = max(num_levels(m) for m in ms)
        self.L = L = Lmin if L is None else max(int(L), Lmin)
        self.NL = NL = min(L, NAT_LEVELS)
        rows = bucket_rows(ms, L)
        self.rows = rows
        pmax = max(ps)
        self.P = P = ((pmax + 127) // 128) * 128
        # Wrap-barrel bit count: sigma mod p < pmax, so only the bits of
        # pmax-1 ever select a roll; PH_BITS-wide loops would waste
        # passes for small-p buckets.
        self.pbits = (pmax - 1).bit_length()
        # RS == rows always: Mosaic cannot compile sublane slices of the
        # VMEM scratch at a smaller tile count (SIGABRT, `limits[i] <=
        # dim(i)`), so the kernel evaluates S/N for every container row
        # and callers slice the valid/evaluated prefix on the host side.
        self.RS = RS = rows
        self.widths = widths
        self.B = B = len(ms)
        self.nspread = L - NL
        # Guest spread-roll slots end at 32 + 3 * nspread - 1 < 64.
        assert self.nspread <= 10, (L, NL)

        self.guest_ms = None
        self.guest_bases = None
        self.gext = None
        if guests is not None:
            gms = [int(m) for m in guests["ms"]]
            bases = [None if bb is None else int(bb)
                     for bb in guests["bases"]]
            assert len(gms) == len(bases) == B
            from .slottables import guest_base as _gbase

            for m, p, gm, bb in zip(ms, ps, gms, bases):
                if bb is None:
                    continue
                lo = _gbase(m, gm, L, rows)
                assert lo is not None and bb >= lo and bb + gm <= rows, (
                    m, gm, L, rows, bb)
            self.guest_ms = gms
            self.guest_bases = bases
            exts = [rows - bb for bb in bases if bb is not None]
            # Guest wire scratch extent (static): at least one DMA
            # chunk's worth so an all-dummy-guest bucket still builds.
            self.gext = max(exts) if exts else DMA_CHUNK
        self.paired = guests is not None

        tabs = []
        for i, (m, p) in enumerate(zip(ms, ps)):
            t = build_tables(m, p, L, R=rows)
            if self.paired and self.guest_bases[i] is not None:
                tg = build_tables(self.guest_ms[i], p, L, R=rows,
                                  base=self.guest_bases[i])
                t = combine_tables(t, tg)
            tabs.append(t)
        T = NL + 2 * (L - NL)
        words = np.zeros((B, T, rows), np.int32)
        for i, t in enumerate(tabs):
            words[i, :NL] = t.nat_words
            if L > NL:
                words[i, NL : NL + self.nspread] = t.spread_words
                words[i, NL + self.nspread :] = t.slot_words
        self.words = words
        self.ms = ms
        self.ps = ps
        self.scal = _pack_scal(tabs, rows)
        if self.paired:
            self.coef = _pack_coef(
                ps, widths, np.asarray(hcoef), np.asarray(bcoef),
                np.asarray(stdnoise), np.asarray(guests["hcoef"]),
                np.asarray(guests["bcoef"]),
                np.asarray(guests["stdnoise"]))
        else:
            self.coef = _pack_coef(ps, widths, np.asarray(hcoef),
                                   np.asarray(bcoef),
                                   np.asarray(stdnoise))
        self.interpret = bool(interpret)
        self._dev = None
        self._dev_fused = {}

    def _operands(self):
        if self._dev is None:
            # Lane-replicate the packed words on DEVICE (cheap broadcast;
            # host->device ships only the compact (B, T, rows) tensor).
            w = jnp.asarray(self.words)
            wrep = jnp.broadcast_to(w[..., None], w.shape + (128,))
            self._dev = (
                jnp.asarray(self.scal),
                jnp.asarray(self.coef),
                jnp.asarray(wrep),
            )
        return self._dev

    def build(self, D=1):
        """The (possibly disk-cached) compiled call for a DM-batch of
        ``D``; see :class:`_CachedCall`."""
        return _build_call(self.L, self.NL, self.rows, self.P, self.RS,
                           self.widths, self.nspread, self.pbits,
                           D, self.B, self.interpret, self.paired)

    # -- fused single-dispatch path --------------------------------------

    def _sbits(self, PW):
        """Static bit count of the pack row drift for this bucket: the
        drift is monotone with maximum (rows-1) - ((rows-1) * p) // PW,
        largest for the bucket's smallest p."""
        i = self.rows - 1
        smax = max(i - (i * p) // PW for p in self.ps)
        return max(smax.bit_length(), 1)

    def _operands_fused(self, PW):
        """Device operands of the fused call for plan view width ``PW``:
        level words prefixed with the PW-specific pack-word table at
        index 0, lane-replicated on device like the level words."""
        dev = self._dev_fused.get(PW)
        if dev is None:
            guests = None
            if self.paired:
                guests = [None if bb is None else (gm, bb)
                          for gm, bb in zip(self.guest_ms,
                                            self.guest_bases)]
            pack = pack_gather_words(self.ms, self.ps, self.rows, PW,
                                     guests=guests)
            words = np.concatenate([pack[:, None], self.words], axis=1)
            w = jnp.asarray(words)
            wrep = jnp.broadcast_to(w[..., None], w.shape + (128,))
            dev = self._dev_fused[PW] = (
                jnp.asarray(self.scal),
                jnp.asarray(self.coef),
                jnp.asarray(wrep),
            )
        return dev

    def build_fused(self, D, mode, PW, wrows, srows, gwrows=None):
        """The compiled fused wire->FFA->S/N call (one device dispatch
        per stage) for a DM-batch of ``D`` reading a shipped
        (D, wrows, PW) wire part and (D, srows, 1) scale view; a
        row-packed bucket also reads its guest stage's (D, gwrows, PW)
        part."""
        return _build_fused_call(mode, self.L, self.NL, self.rows, self.P,
                                 self.RS, self.widths, self.nspread,
                                 self.pbits, self._sbits(PW), D, self.B,
                                 PW, wrows, srows, self.interpret,
                                 self.gext if self.paired else None,
                                 gwrows if self.paired else None)

    def run_fused(self, stagevec, wire_dev, scales_dev, mode,
                  gwire_dev=None):
        """Queue the fused single-dispatch program: ``stagevec`` is the
        (1, 8) int32 stage vector [wire row offset, plane rows, scale
        row offset, view rows, then the guest stage's four or 0s];
        returns (D, B, RS, 128) f32. A paired bucket passes the guest
        stage's shipped wire part as ``gwire_dev``."""
        PW = int(wire_dev.shape[2])
        scal, coef, wrep = self._operands_fused(PW)
        assert (gwire_dev is not None) == self.paired
        call = self.build_fused(int(wire_dev.shape[0]), mode, PW,
                                int(wire_dev.shape[1]),
                                int(scales_dev.shape[1]),
                                int(gwire_dev.shape[1])
                                if self.paired else None)
        if isinstance(wire_dev, jax.core.Tracer) and hasattr(call, "jitted"):
            call = call.jitted  # inside an outer trace (see __call__)
        if self.paired:
            return call(stagevec, scal, coef, wire_dev, gwire_dev,
                        scales_dev, wrep)
        return call(stagevec, scal, coef, wire_dev, scales_dev, wrep)

    def __call__(self, x):
        """x: (B, rows, P) or (D, B, rows, P) f32 natural-packed
        container(s). Returns (B, RS, 128) / (D, B, RS, 128) f32 S/N.
        Tables/coefficients are shared across the leading DM axis; the
        grid is (B, D) so nothing is replicated per DM trial."""
        scal, coef, wrep = self._operands()
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        call = self.build(x.shape[0])
        if isinstance(x, jax.core.Tracer) and hasattr(call, "jitted"):
            # Inside an outer trace (the sharded path calls the kernel
            # from a shard_map body): an AOT-compiled executable cannot
            # take tracers — inline the plain jitted pallas call, which
            # the outer program compiles as part of itself.
            call = call.jitted
        out = call(scal, coef, x, wrep)
        return out[0] if squeeze else out


def ffa_snr_cycle(kernel: CycleKernel, x):
    return kernel(x)
