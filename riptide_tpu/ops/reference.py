"""
Pure-numpy reference ("oracle") implementations of the core FFA numerics.

These functions define the *semantics* that the TPU kernels in
:mod:`riptide_tpu.ops` must reproduce. They intentionally mirror the
behaviour of riptide's C++ compute core (see reference files
``riptide/cpp/{kernels,transforms,downsample,snr,running_median}.hpp``),
including rounding conventions and edge handling, but are written as
vectorised numpy code rather than translated loops. They are used:

* as oracles in the test suite (every JAX/Pallas kernel is checked
  against them),
* as host-side fallbacks for small problems where device dispatch is
  not worth it.

Semantics notes
---------------
* The FFA merge row mapping uses float32 arithmetic for the ``kh * s + 0.5``
  index rounding, matching the reference exactly
  (reference: riptide/cpp/transforms.hpp:17-24).
* ``circular_prefix_sum`` uses a float64 accumulator
  (reference: riptide/cpp/kernels.hpp:73-101).
* ``running_median`` pads both array ends with the edge values
  (reference: riptide/cpp/running_median.hpp:100-132).
"""
from functools import lru_cache

import numpy as np

__all__ = [
    "ffa_transform",
    "ffa_shifts",
    "circular_prefix_sum",
    "boxcar_snr_1d",
    "boxcar_snr_2d",
    "downsampled_size",
    "downsampled_variance",
    "downsample",
    "running_median",
    "generate_width_trials",
    "periodogram_ref",
]


# ---------------------------------------------------------------------------
# FFA transform
# ---------------------------------------------------------------------------

def _merge_mapping(m):
    """
    Row mapping of one FFA merge step for an m-row node split into a head of
    ``m // 2`` rows and a tail of ``m - m // 2`` rows.

    Returns (h, t, shift) int arrays of length m such that output row ``s``
    of the merged node is ``head[h[s]] + roll(tail[t[s]], -shift[s])``.

    The index rounding is done in float32, matching the reference C++
    (riptide/cpp/transforms.hpp:17-24: ``h = kh * s + 0.5f`` with float kh).
    The total phase shift applied to the tail row works out to ``s - t[s]``.
    """
    mh = m // 2
    mt = m - mh
    s = np.arange(m, dtype=np.float32)
    kh = np.float32(mh - 1.0) / np.float32(m - 1.0)
    kt = np.float32(mt - 1.0) / np.float32(m - 1.0)
    h = (kh * s + np.float32(0.5)).astype(np.int64)
    t = (kt * s + np.float32(0.5)).astype(np.int64)
    shift = np.arange(m, dtype=np.int64) - t
    return h, t, shift


def ffa_transform(data):
    """
    FFA transform of a 2D array of shape (m, p): m pulse periods by p phase
    bins in, m phase-drift trials by p phase bins out. Row s of the output is
    the sum of the input rows with a linear phase drift of s bins applied
    across the whole array.

    Matches the recursive divide-in-half structure of the reference
    (riptide/cpp/transforms.hpp:30-50): head of ``m // 2`` rows and tail of
    the rest are transformed independently, then merged.
    """
    data = np.asarray(data, dtype=np.float32)
    if data.ndim != 2:
        raise ValueError("input data must be two-dimensional")
    m, p = data.shape
    if m == 1:
        return data.copy()
    mh = m // 2
    head = ffa_transform(data[:mh])
    tail = ffa_transform(data[mh:])
    h, t, shift = _merge_mapping(m)
    cols = (np.arange(p)[None, :] + shift[:, None]) % p
    rolled = np.take_along_axis(tail[t], cols, axis=1)
    return head[h] + rolled


@lru_cache(maxsize=None)
def ffa_shifts(m):
    """
    Total phase drift (in bins, unreduced) applied to each output row of an
    m-row FFA transform. Row s of the output has drift s: this function
    exists to assert that invariant in tests and document the row meaning.
    """
    return np.arange(m)


# ---------------------------------------------------------------------------
# Boxcar S/N
# ---------------------------------------------------------------------------

def circular_prefix_sum(x, nsum):
    """
    Prefix sum of ``x`` as if its elements repeated circularly, evaluated for
    ``nsum`` elements: out[j] = x[0] + x[1] + ... + x[j mod size] (with full
    wraps adding the array total). Uses a float64 accumulator like the
    reference (riptide/cpp/kernels.hpp:73-101).
    """
    x = np.asarray(x, dtype=np.float32)
    n = x.size
    cs = np.cumsum(x, dtype=np.float64)
    total = cs[-1]
    j = np.arange(nsum)
    out = cs[j % n] + (j // n) * total
    return out.astype(np.float32)


def _boxcar_coeffs(nbins, widths):
    """
    Height ``h`` and baseline ``b`` of a zero-mean, unit-square-sum boxcar
    filter of each trial width on an ``nbins``-bin profile
    (reference: riptide/cpp/snr.hpp:45-49).
    """
    w = np.asarray(widths, dtype=np.float64)
    h = np.sqrt((nbins - w) / (nbins * w))
    b = w / (nbins - w) * h
    return h, b


def boxcar_snr_1d(x, widths, stdnoise=1.0):
    """
    Matched-filter S/N of a single folded profile for each boxcar width
    trial; phase-rotation invariant (reference: riptide/cpp/snr.hpp:37-55).
    """
    x = np.asarray(x, dtype=np.float32)
    widths = np.asarray(widths)
    n = x.size
    if not np.all((widths > 0) & (widths < n)):
        raise ValueError("trial widths must be all > 0 and < columns")
    if not stdnoise > 0:
        raise ValueError("stdnoise must be > 0")
    wmax = int(widths.max())
    cpf = circular_prefix_sum(x, n + wmax)
    total = cpf[n - 1]
    out = np.empty(widths.size, dtype=np.float32)
    for iw, w in enumerate(widths):
        h, b = _boxcar_coeffs(n, w)
        dmax = (cpf[w : w + n] - cpf[:n]).max()
        out[iw] = ((h + b) * dmax - b * total) / stdnoise
    return out


def boxcar_snr_2d(x, widths, stdnoise=1.0):
    """Row-wise :func:`boxcar_snr_1d` over a (rows, bins) array."""
    x = np.asarray(x, dtype=np.float32)
    return np.stack([boxcar_snr_1d(row, widths, stdnoise) for row in x])


# ---------------------------------------------------------------------------
# Downsampling by a real-valued factor
# ---------------------------------------------------------------------------

def downsampled_size(nsamp, f):
    """Output length after downsampling ``nsamp`` samples by real factor f."""
    return int(np.floor(nsamp / f))


def downsampled_variance(nsamp, f):
    """
    Variance of unit-variance Gaussian noise after downsampling by a real
    factor f; piecewise formula from the reference
    (riptide/cpp/downsample.hpp:29-38).
    """
    k = np.floor(f)
    r = f - k
    x = downsampled_size(nsamp, f) * r
    if x > 1:
        return f - 1.0 / 3.0
    return (k - 1.0) ** 2 + 2.0 / 3.0 * x**2 - x + 1.0


def downsample_indices(nsamp, f):
    """
    Host-side index/weight plan for real-factor downsampling: output sample k
    sums input samples ``imin[k]..imax[k]`` where the two boundary samples
    get fractional weights ``wmin[k]`` / ``wmax[k]`` and interior samples
    weight 1 (reference: riptide/cpp/downsample.hpp:44-82). All arithmetic in
    float64, indices exact.

    Returns (imin, imax, wmin, wmax) arrays of length ``downsampled_size``.
    """
    n = downsampled_size(nsamp, f)
    k = np.arange(n, dtype=np.float64)
    start = k * f
    end = start + f
    imin = np.floor(start).astype(np.int64)
    imax = np.minimum(np.floor(end), nsamp - 1.0).astype(np.int64)
    wmin = (imin + 1.0 - start).astype(np.float64)
    wmax = (end - imax).astype(np.float64)
    return imin, imax, wmin, wmax


def downsample(data, f):
    """
    Downsample a 1D array by a real-valued factor f, 1 < f <= size.
    Fractional boundary samples are split by linear weights.
    """
    data = np.asarray(data, dtype=np.float32)
    n = data.size
    if not (f > 1.0 and f <= n):
        raise ValueError("Downsampling factor must verify: 1 < f <= size")
    imin, imax, wmin, wmax = downsample_indices(n, f)
    cs = np.concatenate(([0.0], np.cumsum(data, dtype=np.float64)))
    # sum of interior samples imin+1 .. imax-1 inclusive
    interior = cs[imax] - cs[imin + 1]
    out = wmin * data[imin] + interior + wmax * data[imax]
    return out.astype(np.float32)


# ---------------------------------------------------------------------------
# Running median
# ---------------------------------------------------------------------------

def running_median(data, width):
    """
    Exact sliding-window median of odd ``width``, with both array ends padded
    by the edge values (reference: riptide/cpp/running_median.hpp:100-132).
    """
    data = np.asarray(data)
    if data.ndim != 1:
        raise ValueError("data must be one-dimensional")
    if not width % 2:
        raise ValueError("width must be an odd number")
    if not width < data.size:
        raise ValueError("width must be < size")
    half = width // 2
    padded = np.pad(data, (half, half), mode="edge")
    windows = np.lib.stride_tricks.sliding_window_view(padded, width)
    return np.median(windows, axis=-1).astype(data.dtype)


# ---------------------------------------------------------------------------
# Full periodogram (slow oracle for the device engine)
# ---------------------------------------------------------------------------

def periodogram_ref(data, tsamp, widths, period_min, period_max, bins_min, bins_max):
    """
    Slow numpy periodogram with the exact semantics of the reference's
    search loop (riptide/cpp/periodogram.hpp:117-201): geometric
    downsampling cascade x phase-bin loop x (FFA transform + boxcar S/N),
    with ceilshift row pruning and float64 trial periods. Oracle for
    :mod:`riptide_tpu.search.engine`.

    Returns (periods float64, foldbins uint32, snrs float32 (len, NW)).
    """
    data = np.asarray(data, dtype=np.float32)
    size = data.size
    widths = np.asarray(widths)
    ds_ini = period_min / (tsamp * bins_min)
    ds_geo = (bins_max + 1.0) / bins_min
    num_ds = int(np.ceil(np.log(period_max / period_min) / np.log(ds_geo)))

    periods, foldbins, snrs = [], [], []
    for ids in range(num_ds):
        f = ds_ini * ds_geo**ids
        tau = f * tsamp
        pms = period_max / tau
        n = downsampled_size(size, f)
        x = data if f == 1 else downsample(data, f)
        x = x[:n]
        for bins in range(bins_min, min(bins_max, n, int(pms)) + 1):
            rows = n // bins
            stdnoise = np.sqrt(rows * downsampled_variance(size, f))
            period_ceil = min(pms, bins + 1.0)
            cshift = int(np.ceil(bins * (rows - 1.0) * (1.0 - bins / period_ceil)))
            rows_eval = min(rows, max(cshift, 0))
            if rows_eval <= 0:
                continue
            tf = ffa_transform(x[: rows * bins].reshape(rows, bins))
            snrs.append(boxcar_snr_2d(tf[:rows_eval], widths, stdnoise))
            s = np.arange(rows_eval, dtype=np.float64)
            periods.append(tau * bins * bins / (bins - s / (rows - 1.0)))
            foldbins.append(np.full(rows_eval, bins, np.uint32))
    nw = widths.size
    if not periods:
        return (np.empty(0, np.float64), np.empty(0, np.uint32),
                np.empty((0, nw), np.float32))
    return (
        np.concatenate(periods),
        np.concatenate(foldbins),
        np.concatenate(snrs).astype(np.float32),
    )


# ---------------------------------------------------------------------------
# Width trials
# ---------------------------------------------------------------------------

def generate_width_trials(nbins, ducy_max=0.20, wtsp=1.5):
    """
    Geometric-ish boxcar width trial ladder: w(n+1) = max(w + 1, floor(wtsp * w)),
    capped at ``ducy_max * nbins`` (reference: riptide/ffautils.py:3-10).
    With wtsp=1.5: 1, 2, 3, 4, 6, 9, 13, 19, ...
    """
    widths = []
    w = 1
    wmax = int(max(1, ducy_max * nbins))
    while w <= wmax:
        widths.append(w)
        w = int(max(w + 1, wtsp * w))
    return np.asarray(widths)
