"""
TPU-native compute kernels for the FFA search.

This package plays the role of the reference's C++ compute core
(riptide/cpp/): every hot numerical operation — the FFA fold tree, boxcar
matched-filter S/N, real-factor downsampling and running medians — is
implemented as XLA/Pallas programs planned on the host and executed on
device. :mod:`riptide_tpu.ops.reference` holds the pure-numpy oracles the
kernels are verified against.
"""
from . import reference
from .plan import ffa_plan, batch_plans, num_levels, FFAPlan, FFABatchPlan
from .ffa import ffa2, ffa1, ffafreq, ffaprd, ffa_levels
from .snr import boxcar_snr, boxcar_coeffs, snr_batched
from .downsample import (
    split_prefix_sums,
    downsample_gather,
    downsample_plan_padded,
    downsampled_size,
    downsampled_variance,
)
from .running_median import running_median_jax, scrunch_jax, fast_running_median_jax
