"""
Table construction + dense-op simulator for the Pallas FFA kernel.

The kernel (riptide_tpu/ops/ffa_kernel.py) executes the slot-layout FFA
of :mod:`riptide_tpu.ops.slotffa` using ONLY dense primitives: static
row/lane rolls, elementwise selects against precomputed per-row tables,
and one dynamic whole-array roll per problem (the mod-p wrap). This
module builds those tables on the host and provides a numpy simulator
(`simulate_dense`) that performs the *identical* sequence of dense
operations, so kernel correctness reduces to "kernel == simulator"
(cheap, via interpret mode) plus "simulator == reference oracle"
(asserted here against riptide/cpp/transforms.hpp semantics through
ops.reference.ffa_transform).

Pipeline per problem (m rows of p phase bins, bucket depth L):

1. natural phase  -- levels 1..E (E = min(L, 3)) merge in natural row
   layout. Row reads stay within +/-4 rows => K-way select over static
   row rolls, driven by two small per-row offset tables (ah, at).
2. spread phase   -- L-E halving steps move completed depth-(L-E) nodes
   into uniform power-of-two slots of 8 rows (3-D steps: 2 static rolls
   + per-group select), giving the slot container of `slotffa`.
3. slot phase     -- levels E+1..L with the interleave trick: per-slot
   row-doubling (jnp.repeat) + delta in [-2, 1] select, exact because
   the reference's float32 index rounding keeps h(s), t(s) within 2 of
   s/2 (asserted below).
4. Phase rolls    -- every level's tail roll = lane barrel over the bits
   of sigma mod p + one wrap select against `thr = p - sigma mod p`,
   using the problem's dynamic whole-array roll by (P - p).

All tables are packed per row into one int32 (see pack_level_word).
"""
import numpy as np

from .reference import _merge_mapping
from .slotffa import node_sizes
from .plan import num_levels

__all__ = [
    "KernelTables", "build_tables", "combine_tables", "simulate_dense",
    "simulate_dense_pair", "container_rows", "container_forms",
    "guest_base", "NAT_LEVELS", "SLOT_S",
]

NAT_LEVELS = 3      # levels executed in natural layout
SLOT_S = 8          # slot size after the spread (2**NAT_LEVELS)


def container_forms(L, extended=False):
    """Legal container heights at bucket depth L, ascending. The base
    family is {2**L, 3 * 2**(L-2)}; ``extended`` adds the odd-slot
    forms 5 * 2**(L-3) and 7 * 2**(L-3) (row-pack layout): the spread
    still halves group sizes down to the final slot, and the final
    slot itself is never halved, so an ODD slot size (5, 7) is legal —
    the interleaved row-doubling's floor division absorbs it. Odd-slot
    forms need L >= 6 to stay a multiple of the 8-row sublane tile."""
    forms = []
    if extended and L >= 6:
        forms.append(5 << (L - 3))
    if L >= 5:
        forms.append(3 << (L - 2))
    if extended and L >= 6:
        forms.append(7 << (L - 3))
    forms.append(1 << L)
    return forms


def container_rows(m, L, extended=False):
    """Container height for an m-row problem at bucket depth L: the
    smallest legal form of :func:`container_forms` holding m rows. The
    base-3 container cuts the ~1.44x average power-of-two padding
    waste to ~1.19x; the ``extended`` (row-pack) family's 1.25x-family
    steps cut it to ~1.10x. Slot sizes become s * 2**j for s in
    {5, 6, 7, 8}, which every phase below supports (row-doubling only
    needs EVEN slot sizes above the final slot, and the spread/natural
    phases are container-size agnostic). Non-2**L forms are gated on L
    so the container stays a multiple of the 8-row sublane tile."""
    for rows in container_forms(L, extended):
        if rows >= m:
            return rows
    return 1 << L


def guest_base(m_host, m_guest, L, rows):
    """Smallest base row at which an m_guest-row problem can co-habit
    an m_host-row problem's ``rows`` container at depth L, or None.

    The guest's depth-d tree nodes sit at offset ``base >> d`` inside
    the depth-d slots (the floor chain: the interleaved row-doubling's
    (s + delta) // 2 read absorbs odd offsets with the STANDARD delta
    tables, see build_tables). Feasibility per depth d is therefore a
    per-slot capacity check between the two fixed canonical trees:
    host's largest depth-d node must fit below the guest offset, and
    the offset plus the guest's largest node inside the slot."""
    m_host, m_guest, rows = int(m_host), int(m_guest), int(rows)
    if m_host < 1 or m_guest < 1:
        return None
    NL = min(L, NAT_LEVELS)
    D0 = L - NL
    base = m_host
    for d in range(D0 + 1):
        base = max(base, int(node_sizes(m_host, d).max()) << d)
    if base + m_guest > rows:
        return None
    for d in range(D0 + 1):
        if (base >> d) + int(node_sizes(m_guest, d).max()) > (rows >> d):
            return None
    return base

# packed word layout (int32):
#   bits 0-10  sigma mod p            (lane roll;  < p <= 2047)
#   bits 11-21 thr = p - sigma mod p  (wrap-select threshold, 1..2047)
#   bits 22-24 field A: natural phase: head row drift  s - h(s)   in [0,7]
#              slot phase:    delta_h + 2                          in [0,3]
#   bits 25-28 field B: natural phase: tail row offset  (biased)   in [0,15]
#              slot phase:    delta_t + 2                          in [0,3]
#   bit  31    valid (sign bit)
PH_BITS = 11           # sigma / thr field width; bins cap = 2**PH_BITS - 1
PH_MASK = (1 << PH_BITS) - 1
A_SHIFT, A_BITS = 2 * PH_BITS, 3
B_SHIFT, B_BITS = 2 * PH_BITS + A_BITS, 4


def pack_word(sigma_mod, thr, a, b, valid):
    w = (
        (sigma_mod & PH_MASK)
        | ((thr & PH_MASK) << PH_BITS)
        | ((a & ((1 << A_BITS) - 1)) << A_SHIFT)
        | ((b & ((1 << B_BITS) - 1)) << B_SHIFT)
    )
    # valid lives in bit 31 == the int32 sign bit, so kernels test `w < 0`.
    return np.where(valid, w | (1 << 31), w).astype(np.int64).astype(np.int32)


class KernelTables:
    """All static tables + metadata for one problem in one bucket.

    Attributes
    ----------
    m, p, L : problem shape and bucket depth.
    nat_words : (NL, m_pad) int64 -- packed words for natural levels
        (NL = min(L, NAT_LEVELS)); row dimension padded to `nat_rows`.
    spread_hi : list over steps of (groups,) int8 -- 1 where the group's
        head size is the larger candidate (mh == A+1).
    spread_sizes : list over steps of ((groups,) head-size-A, child rows)
    slot_words : (L - NL, rows) int64 -- packed words for slot levels.
    base : guest base row (0 for a container-owning host problem).
    gspread : guest problems only -- per spread step (A, alpha_j,
        alpha_{j+1}): the guest head-child size candidate floor and the
        step's in-slot offsets ``base >> j`` / ``base >> (j+1)``.
    """


def _merge_tables(mn):
    """(h, t, sigma) for an mn-row merge; mn >= 2."""
    return _merge_mapping(mn)


def build_tables(m, p, L=None, R=None, base=0):
    """Build all kernel tables for one (m, p) problem at bucket depth L
    in a container of ``R`` rows (a :func:`container_forms` member;
    default 2**L). ``base`` > 0 builds the GUEST placement of a
    row-packed pair: the problem's depth-d tree nodes sit at offset
    ``base >> d`` inside the depth-d slots (natural phase contiguous at
    ``base``), with spread selects 3..5 instead of the host's 0..2.
    The floor chain base >> d needs NO divisibility: the interleaved
    row-doubling reads ``(s + delta) // 2``, so an odd parent offset
    (base >> d = 2 * (base >> (d+1)) + 1) lands on the same child row
    with the STANDARD delta tables. Feasibility (no collision with a
    base-0 host of a given m) is :func:`guest_base`'s contract."""
    m, p = int(m), int(p)
    if not 0 < p <= PH_MASK:
        # sigma/thr live in PH_BITS-wide packed fields and the kernel's
        # boxcar prefix scan covers a 2**PH_BITS-lane window; beyond that
        # the packed words silently truncate, so refuse loudly.
        raise ValueError(
            f"packed-word layout requires 0 < p <= {PH_MASK}, got {p}"
        )
    Lmin = num_levels(m)
    L = Lmin if L is None else int(L)
    assert L >= Lmin
    NL = min(L, NAT_LEVELS)
    rows = (1 << L) if R is None else int(R)
    # Non-2**L containers require a minimum L, matching container_forms:
    # below that the container is not a multiple of the 8-row sublane
    # tile and the spread/slot group halves come out odd — tables would
    # build but the device path cannot serve them.
    legal = tuple(container_forms(L, extended=True))
    assert rows >= m and rows in legal, (m, L, rows)
    base = int(base)
    assert 0 <= base and base + m <= rows, (m, base, rows)
    t = KernelTables()
    t.m, t.p, t.L, t.NL, t.rows = m, p, L, NL, rows
    t.base = base

    # ---- natural phase -------------------------------------------------
    # Level l (1..NL) merges depth d+1 = L-l+1 children into depth d
    # nodes, all in natural packing. For output row u = R0(d,k) + s:
    #   head read  u - dh,   dh = s - h(s)          in [0, 2**l - 1]
    #   tail read  u + o,    o  = mh + t(s) - s = mh - sigma(s)
    #                                               in [-1, 2**(l-1)]
    # Field B stores o + 1 (sentinel all-ones marks a lone carried row).
    nat_words = np.zeros((NL, rows), np.int32)
    for l in range(1, NL + 1):
        d = L - l
        sizes = node_sizes(m, d)
        csizes = node_sizes(m, d + 1)
        # dtype already int64 (node_sizes); left implicit because this
        # body is covered by the KERNEL_CACHE_VERSION bytecode digest.
        r0 = base + np.concatenate(([0], np.cumsum(sizes)[:-1]))  # riplint: disable=RIP002
        sig = np.zeros(rows, np.int64)
        dh = np.zeros(rows, np.int64)
        bb = np.zeros(rows, np.int64)
        val = np.zeros(rows, bool)
        for k in range(1 << d):
            mn = int(sizes[k])
            if mn == 0:
                continue
            r0k = int(r0[k])
            val[r0k : r0k + mn] = True
            if mn == 1:
                # lone row carries itself: head read self, no tail.
                # dh = 0; mark tail invalid via sigma/thr: we encode
                # "no tail" as B = 0 with zero-read? Instead: tail read
                # offset o chosen to read row itself with sigma=0 and
                # head reads ZERO... Simpler: head = self (dh = 0),
                # tail weight zero: set B to the sentinel 2**B_BITS - 1.
                bb[r0k] = (1 << B_BITS) - 1
                continue
            mh = int(csizes[2 * k])
            h, tt, sh = _merge_tables(mn)
            s = np.arange(mn)
            dh[r0k : r0k + mn] = s - h
            o = mh + tt - s                      # tail read offset
            bb[r0k : r0k + mn] = o + 1           # in [0, 2**(l-1) + 1]
            sig[r0k : r0k + mn] = sh
            # Head drift is bounded by the tail child size: h(s) =
            # round(kh*s) >= kh*s - 1/2 gives s - h <= s*mt/(mn-1) + 1/2
            # <= mt <= 2^(l-1). The kernel's head select chain stops at
            # that bound (ffa_kernel natural levels), so it is asserted
            # here at table-build time.
            assert (s - h >= 0).all() and (s - h <= (1 << (l - 1))).all(), (m, l)
            assert (o + 1 >= 0).all() and (o + 1 < (1 << B_BITS) - 1).all(), (m, l)
        sigm = sig % p
        thr = p - sigm
        nat_words[l - 1] = pack_word(sigm, thr, dh, bb, val)
    t.nat_words = nat_words

    # ---- spread phase --------------------------------------------------
    # After the natural phase, depth D0 = L - NL nodes are complete and
    # contiguously packed. Halving steps j = 0..D0-1 split depth-j node
    # groups into their two children, padding each to the power-of-two
    # slot: state (2**j, 2**(L-j)) -> (2**(j+1), 2**(L-j-1)) rows.
    # Per step only two candidate head sizes exist: A and A+1.
    # Each step is fully 2-D: output row u (slot 2g+child of the step's
    # output layout, in-slot index i) reads input flat row
    #   g*S + (child ? mh(g) + i : i)  =  u + child*(mh(g) - half),
    # i.e. one of THREE static row offsets {0, A - half, A + 1 - half}.
    # Per-row word: bits 22-24 select the candidate (0 head, 1 tail with
    # mh = A, 2 tail with mh = A + 1); sign bit = row valid. A GUEST
    # placement (base > 0) keeps its depth-j block at in-slot offset
    # alpha_j = base >> j, so its three candidates gain the constant
    # alpha_j - alpha_{j+1} and select as 3..5 (amounts live in the
    # paired kernel's per-trial scalar bank, like the host's).
    spread = []
    gspread = []
    spread_words = np.zeros((max(L - NL, 0), rows), np.int32)
    for j in range(L - NL):
        sizes = node_sizes(m, j)
        mh = sizes >> 1                 # head child sizes
        A = int(mh.min()) if len(mh) else 0
        hi = (mh > A).astype(np.int64)
        assert int(mh.max()) <= A + 1
        spread.append(A)
        gspread.append((A, base >> j, base >> (j + 1)))
        # Group size at step j is rows >> j (a multiple of 2 while
        # j <= L - NL - 1 for every container form); plain division
        # rather than bit tricks so non-2**L rows work too.
        half = rows >> (j + 1)
        iota = np.arange(rows)
        g = iota // (rows >> j)         # parent group
        child = (iota // half) % 2
        i = iota % half
        mh_g = mh[g]
        cnt = np.where(child == 0, mh_g, sizes[g] - mh_g)
        if base:
            an = base >> (j + 1)
            assert an + int(cnt.max()) <= half, (m, j, base, rows)
            sel = np.where(child == 0, 3, 4 + hi[g])
            val = (i >= an) & (i < an + cnt)
        else:
            sel = np.where(child == 0, 0, 1 + hi[g])
            val = i < cnt
        w = sel << 22
        spread_words[j] = np.where(val, w | (1 << 31), w).astype(np.int64).astype(np.int32)
    t.spread = spread
    t.gspread = gspread
    t.spread_words = spread_words

    # ---- slot phase ----------------------------------------------------
    # Levels l = NL+1 .. L in the uniform slot container (2**L rows,
    # slot size S_d = 2**l for outputs). Tables per output row
    # u = k * S_d + s:
    #   delta_h = 2*h(s) - s  in [-2, 1]
    #   delta_t = 2*t(s) - s  in [-2, 1]
    # A guest placement shifts every node by beta_d = base >> d inside
    # its slot; the delta tables are UNCHANGED: the kernel's
    # (s + delta) // 2 interleave read absorbs an odd beta_d exactly
    # (beta_d = 2 * beta_{d+1} + eps, eps in {0, 1}, lands on
    # beta_{d+1} + h either way).
    slot_words = np.zeros((L - NL, rows), np.int32)
    for l in range(NL + 1, L + 1):
        d = L - l
        S_d = rows >> d               # 2**l, or (s/8) * 2**l (odd-slot)
        beta = base >> d
        sizes = node_sizes(m, d)
        csizes = node_sizes(m, d + 1)
        sig = np.zeros(rows, np.int64)
        da = np.zeros(rows, np.int64)
        db = np.zeros(rows, np.int64)
        val = np.zeros(rows, bool)
        for k in range(1 << d):
            mn = int(sizes[k])
            if mn == 0:
                continue
            r0 = k * S_d + beta
            assert beta + mn <= S_d, (m, l, k, base, rows)
            val[r0 : r0 + mn] = True
            if mn == 1:
                # carry: tail child holds the row (head child empty).
                # delta_t for s=0 must read tails[k, beta_{d+1}]:
                # (beta_d + 0) // 2 = beta_{d+1} with delta 0.
                da[r0] = 2      # delta_h = 0 -> reads empty head slot (zeros)
                db[r0] = 2      # delta_t = 0
                continue
            h, tt, sh = _merge_tables(mn)
            s = np.arange(mn)
            dlh = 2 * h - s
            dlt = 2 * tt - s
            assert (dlh >= -2).all() and (dlh <= 1).all(), (m, l, k)
            assert (dlt >= -2).all() and (dlt <= 1).all(), (m, l, k)
            da[r0 : r0 + mn] = dlh + 2
            db[r0 : r0 + mn] = dlt + 2
            sig[r0 : r0 + mn] = sh
        sigm = sig % p
        thr = p - sigm
        slot_words[l - NL - 1] = pack_word(sigm, thr, da, db, val)
    t.slot_words = slot_words
    return t


def combine_tables(th, tg):
    """Merge a base-0 host's tables with a guest's (built at a feasible
    :func:`guest_base`) into ONE set of per-row words for the paired
    container: each level's words select the owning trial's entry by
    the row's structural position (guest owns in-slot offsets at or
    above its ``base >> d`` chain). Dead rows' words are whichever
    side's padding entry the region select lands on — their outputs
    are invalid-masked and no live row reads them."""
    assert th.base == 0 and tg.base > 0
    assert (th.rows, th.L, th.NL, th.p) == (tg.rows, tg.L, tg.NL, tg.p)
    rows, L, NL, base = th.rows, th.L, th.NL, tg.base
    t = KernelTables()
    t.m, t.p, t.L, t.NL, t.rows = th.m, th.p, L, NL, rows
    t.base = 0
    t.gm, t.gbase = tg.m, base
    iota = np.arange(rows)
    t.nat_words = np.where(iota[None, :] >= base, tg.nat_words,
                           th.nat_words)
    spread_words = np.empty_like(th.spread_words)
    for j in range(L - NL):
        half = rows >> (j + 1)
        spread_words[j] = np.where((iota % half) >= (base >> (j + 1)),
                                   tg.spread_words[j], th.spread_words[j])
    t.spread_words = spread_words
    slot_words = np.empty_like(th.slot_words)
    for l in range(NL + 1, L + 1):
        d = L - l
        S_d = rows >> d
        slot_words[l - NL - 1] = np.where(
            (iota % S_d) >= (base >> d),
            tg.slot_words[l - NL - 1], th.slot_words[l - NL - 1])
    t.slot_words = slot_words
    t.spread = th.spread
    t.gspread = tg.gspread
    return t


# ---------------------------------------------------------------------------
# Dense-op simulator: numpy mirror of the kernel's operation sequence.
# ---------------------------------------------------------------------------

def _lane_roll(x, c):
    """Circular roll of phase lanes by +c: out[..., j] = x[..., j + c mod P]."""
    return np.roll(x, -c, axis=-1)


def _row_roll(x, c):
    """Roll rows by +c upward reads: out[u] = x[u + c mod rows]."""
    return np.roll(x, -c, axis=0)


def _tail_lane_roll(tail, words, p, P):
    """Barrel lane roll by sigma-mod-p with the two-pass mod-p wrap."""
    sigm = (words & PH_MASK).astype(np.int64)
    thr = ((words >> PH_BITS) & PH_MASK).astype(np.int64)
    acc = tail
    for k in range(PH_BITS):
        if not ((sigm >> k) & 1).any():
            continue
        rolled = _lane_roll(acc, 1 << k)
        acc = np.where((((sigm >> k) & 1) != 0)[:, None], rolled, acc)
    # Wrap branch: for j >= p - sigma the window crosses the phase ring;
    # the correct value sits one further whole-array roll of (P - p) on:
    #   wrapped[j] = acc[(j + P - p) mod P] = tail[(j + sigma + P - p) mod P]
    # which lands on tail[j + sigma - p] for the wrap region.
    wrapped = _lane_roll(acc, P - p)
    cols = np.arange(P)
    return np.where(cols[None, :] < thr[:, None], acc, wrapped)


def simulate_dense(data, L=None, P=None, R=None):
    """
    Execute the kernel's dense-op sequence in numpy. `data` is (m, p);
    returns the (m, p) FFA transform (must equal ffa_transform exactly).
    ``R`` selects the container height (see :func:`container_rows`).
    """
    data = np.asarray(data, dtype=np.float32)
    m, p = data.shape
    t = build_tables(m, p, L, R)
    buf = np.zeros((t.rows, p if P is None else int(P)), np.float32)
    buf[:m, :p] = data
    return _simulate_cascade(t, buf)[:m, :p]


def simulate_dense_pair(data_host, data_guest, L, R, base=None, P=None):
    """
    The paired (row-packed) container's dense-op sequence: host trial
    at rows [0, m_h), guest trial embedded at ``base`` (default: the
    minimal :func:`guest_base`), SAME p. Returns (host, guest) (m, p)
    transforms — each must equal its own ffa_transform exactly.
    """
    data_host = np.asarray(data_host, dtype=np.float32)
    data_guest = np.asarray(data_guest, dtype=np.float32)
    mh, p = data_host.shape
    mg, pg = data_guest.shape
    assert p == pg, "paired trials share one phase-bin count"
    rows = int(R)
    if base is None:
        base = guest_base(mh, mg, L, rows)
        assert base is not None, (mh, mg, L, rows)
    th = build_tables(mh, p, L, rows)
    tg = build_tables(mg, p, L, rows, base=base)
    t = combine_tables(th, tg)
    buf = np.zeros((rows, p if P is None else int(P)), np.float32)
    buf[:mh, :p] = data_host
    buf[base : base + mg, :p] = data_guest
    out = _simulate_cascade(t, buf)
    return out[:mh, :p], out[base : base + mg, :p]


def _simulate_cascade(t, buf):
    """Numpy mirror of the kernel's cascade over prebuilt (possibly
    :func:`combine_tables`-paired) tables; `buf` is the loaded
    (rows, P) container."""
    L, NL, rows, p = t.L, t.NL, t.rows, t.p
    P = buf.shape[1]
    cols = np.arange(P)
    colmask = (cols < p)[None, :]

    # natural phase
    for l in range(1, NL + 1):
        w = t.nat_words[l - 1]
        valid = w < 0
        a = ((w >> A_SHIFT) & ((1 << A_BITS) - 1)).astype(np.int64)
        b = ((w >> B_SHIFT) & ((1 << B_BITS) - 1)).astype(np.int64)
        lone = b == (1 << B_BITS) - 1
        # head: K-way select over row rolls up by c = a(u); the chain
        # stops at the provable drift bound 2^(l-1) (see build_tables),
        # matching the kernel's trimmed select chain.
        head = buf.copy()
        for c in range(1, (1 << (l - 1)) + 1):
            if not (a == c).any():
                continue
            head = np.where((a == c)[:, None], _row_roll(buf, -c), head)
        # tail: K-way select over row reads at offset o = b - 1
        tail = np.zeros_like(buf)
        for bv in range(0, (1 << B_BITS) - 1):
            sel = (b == bv) & valid & ~lone
            if not sel.any():
                continue
            tail = np.where(sel[:, None], _row_roll(buf, bv - 1), tail)
        tail = _tail_lane_roll(tail, w, p, P)
        out = head + np.where(lone[:, None], 0.0, tail)
        buf = np.where(valid[:, None] & colmask, out, 0.0).astype(np.float32)

    # spread phase: natural depth-(L-NL) nodes -> slot container, one
    # step = select over static whole-array row rolls (three host
    # candidates; a paired guest adds its three at sel 3..5).
    for j, A in enumerate(t.spread):
        w = t.spread_words[j]
        half = rows >> (j + 1)
        sel = (w >> 22) & 7
        valid = w < 0
        offs = [(1, A - half), (2, A + 1 - half)]
        if getattr(t, "gbase", 0):
            Ag, aj, an = t.gspread[j]
            offs += [(3, aj - an), (4, aj - an + Ag - half),
                     (5, aj - an + Ag + 1 - half)]
        out = buf
        for sv, off in offs:
            if (sel == sv).any():
                out = np.where((sel == sv)[:, None], _row_roll(buf, off), out)
        buf = np.where(valid[:, None], out, 0.0).astype(np.float32)

    # slot phase (interleaved row-doubling, mirroring the kernel)
    for l in range(NL + 1, L + 1):
        w = t.slot_words[l - NL - 1]
        da = ((w >> A_SHIFT) & ((1 << A_BITS) - 1)).astype(np.int64)
        db = ((w >> B_SHIFT) & ((1 << B_BITS) - 1)).astype(np.int64)
        d = L - l
        G = 1 << d
        S_d = rows >> d
        S_c = S_d >> 1
        v = buf.reshape(G, 2, S_c, P)
        heads, tails = v[:, 0], v[:, 1]
        reph = np.repeat(heads, 2, axis=1)        # (G, S_d, P) interleaved
        rept = np.repeat(tails, 2, axis=1)
        da3 = da.reshape(G, S_d)
        db3 = db.reshape(G, S_d)
        head = np.zeros_like(reph)
        tail = np.zeros_like(rept)
        for dv in range(4):
            delta = dv - 2
            if (da3 == dv).any():
                head = np.where((da3 == dv)[:, :, None], np.roll(reph, -delta, axis=1), head)
            if (db3 == dv).any():
                tail = np.where((db3 == dv)[:, :, None], np.roll(rept, -delta, axis=1), tail)
        head = head.reshape(rows, P)
        tail = tail.reshape(rows, P)
        tail = _tail_lane_roll(tail, w, p, P)
        out = head + tail
        buf = np.where((w < 0)[:, None] & colmask, out, 0.0).astype(np.float32)

    return buf
