"""
Table construction + dense-op simulator for the Pallas FFA kernel.

The kernel (riptide_tpu/ops/ffa_kernel.py) executes the slot-layout FFA
of :mod:`riptide_tpu.ops.slotffa` using ONLY dense primitives: static
row/lane rolls, elementwise selects against precomputed per-row tables,
and one dynamic whole-array roll per problem (the mod-p wrap). This
module builds those tables on the host and provides a numpy simulator
(`simulate_dense`) that performs the *identical* sequence of dense
operations, so kernel correctness reduces to "kernel == simulator"
(cheap, via interpret mode) plus "simulator == reference oracle"
(asserted here against riptide/cpp/transforms.hpp semantics through
ops.reference.ffa_transform).

Pipeline per problem (m rows of p phase bins, bucket depth L):

1. natural phase  -- levels 1..E (E = min(L, 3)) merge in natural row
   layout. Row reads stay within +/-4 rows => K-way select over static
   row rolls, driven by two small per-row offset tables (ah, at).
2. spread phase   -- L-E halving steps move completed depth-(L-E) nodes
   into uniform power-of-two slots of 8 rows (3-D steps: 2 static rolls
   + per-group select), giving the slot container of `slotffa`.
3. slot phase     -- levels E+1..L with the interleave trick: per-slot
   row-doubling (jnp.repeat) + delta in [-2, 1] select, exact because
   the reference's float32 index rounding keeps h(s), t(s) within 2 of
   s/2 (asserted below).
4. Phase rolls    -- every level's tail roll = lane barrel over the bits
   of sigma mod p + one wrap select against `thr = p - sigma mod p`,
   using the problem's dynamic whole-array roll by (P - p).

All tables are packed per row into one int32 (see pack_level_word).
"""
import numpy as np

from .reference import _merge_mapping
from .slotffa import node_sizes
from .plan import num_levels

__all__ = [
    "KernelTables", "build_tables", "simulate_dense", "container_rows",
    "NAT_LEVELS", "SLOT_S",
]

NAT_LEVELS = 3      # levels executed in natural layout
SLOT_S = 8          # slot size after the spread (2**NAT_LEVELS)


def container_rows(m, L):
    """Container height for an m-row problem at bucket depth L: the
    smaller of 2**L and 1.5 * 2**(L-1) = 3 * 2**(L-2) that still holds
    m rows. The base-3 container cuts the ~1.44x average power-of-two
    padding waste to ~1.19x; slot sizes become 3 * 2**j, which every
    phase below supports (row-doubling only needs EVEN slot sizes, and
    the spread/natural phases are container-size agnostic). Base-3 is
    used only for L >= 5 so the container stays a multiple of the 8-row
    sublane tile (3 * 2**(L-2) % 8 == 0 needs L >= 5)."""
    if L >= 5 and 3 << (L - 2) >= m:
        return 3 << (L - 2)
    return 1 << L

# packed word layout (int32):
#   bits 0-10  sigma mod p            (lane roll;  < p <= 2047)
#   bits 11-21 thr = p - sigma mod p  (wrap-select threshold, 1..2047)
#   bits 22-24 field A: natural phase: head row drift  s - h(s)   in [0,7]
#              slot phase:    delta_h + 2                          in [0,3]
#   bits 25-28 field B: natural phase: tail row offset  (biased)   in [0,15]
#              slot phase:    delta_t + 2                          in [0,3]
#   bit  31    valid (sign bit)
PH_BITS = 11           # sigma / thr field width; bins cap = 2**PH_BITS - 1
PH_MASK = (1 << PH_BITS) - 1
A_SHIFT, A_BITS = 2 * PH_BITS, 3
B_SHIFT, B_BITS = 2 * PH_BITS + A_BITS, 4


def pack_word(sigma_mod, thr, a, b, valid):
    w = (
        (sigma_mod & PH_MASK)
        | ((thr & PH_MASK) << PH_BITS)
        | ((a & ((1 << A_BITS) - 1)) << A_SHIFT)
        | ((b & ((1 << B_BITS) - 1)) << B_SHIFT)
    )
    # valid lives in bit 31 == the int32 sign bit, so kernels test `w < 0`.
    return np.where(valid, w | (1 << 31), w).astype(np.int64).astype(np.int32)


class KernelTables:
    """All static tables + metadata for one problem in one bucket.

    Attributes
    ----------
    m, p, L : problem shape and bucket depth.
    nat_words : (NL, m_pad) int64 -- packed words for natural levels
        (NL = min(L, NAT_LEVELS)); row dimension padded to `nat_rows`.
    spread_hi : list over steps of (groups,) int8 -- 1 where the group's
        head size is the larger candidate (mh == A+1).
    spread_sizes : list over steps of ((groups,) head-size-A, child rows)
    slot_words : (L - NL, rows) int64 -- packed words for slot levels.
    """


def _merge_tables(mn):
    """(h, t, sigma) for an mn-row merge; mn >= 2."""
    return _merge_mapping(mn)


def build_tables(m, p, L=None, R=None):
    """Build all kernel tables for one (m, p) problem at bucket depth L
    in a container of ``R`` rows (2**L, or 3 * 2**(L-2) — see
    :func:`container_rows`; default 2**L)."""
    m, p = int(m), int(p)
    if not 0 < p <= PH_MASK:
        # sigma/thr live in PH_BITS-wide packed fields and the kernel's
        # boxcar prefix scan covers a 2**PH_BITS-lane window; beyond that
        # the packed words silently truncate, so refuse loudly.
        raise ValueError(
            f"packed-word layout requires 0 < p <= {PH_MASK}, got {p}"
        )
    Lmin = num_levels(m)
    L = Lmin if L is None else int(L)
    assert L >= Lmin
    NL = min(L, NAT_LEVELS)
    rows = (1 << L) if R is None else int(R)
    # Base-3 containers require L >= 5, matching container_rows: below
    # that the container is not a multiple of the 8-row sublane tile and
    # the spread/slot group halves come out odd — tables would build but
    # the device path cannot serve them.
    legal = (1 << L,) + ((3 << (L - 2),) if L >= 5 else ())
    assert rows >= m and rows in legal, (m, L, rows)
    t = KernelTables()
    t.m, t.p, t.L, t.NL, t.rows = m, p, L, NL, rows

    # ---- natural phase -------------------------------------------------
    # Level l (1..NL) merges depth d+1 = L-l+1 children into depth d
    # nodes, all in natural packing. For output row u = R0(d,k) + s:
    #   head read  u - dh,   dh = s - h(s)          in [0, 2**l - 1]
    #   tail read  u + o,    o  = mh + t(s) - s = mh - sigma(s)
    #                                               in [-1, 2**(l-1)]
    # Field B stores o + 1 (sentinel all-ones marks a lone carried row).
    nat_words = np.zeros((NL, rows), np.int32)
    for l in range(1, NL + 1):
        d = L - l
        sizes = node_sizes(m, d)
        csizes = node_sizes(m, d + 1)
        # dtype already int64 (node_sizes); left implicit because this
        # body is covered by the KERNEL_CACHE_VERSION bytecode digest
        # and a no-op edit must not force a cache-version bump.
        r0 = np.concatenate(([0], np.cumsum(sizes)[:-1]))  # riplint: disable=RIP002
        sig = np.zeros(rows, np.int64)
        dh = np.zeros(rows, np.int64)
        bb = np.zeros(rows, np.int64)
        val = np.zeros(rows, bool)
        for k in range(1 << d):
            mn = int(sizes[k])
            if mn == 0:
                continue
            base = int(r0[k])
            val[base : base + mn] = True
            if mn == 1:
                # lone row carries itself: head read self, no tail.
                # dh = 0; mark tail invalid via sigma/thr: we encode
                # "no tail" as B = 0 with zero-read? Instead: tail read
                # offset o chosen to read row itself with sigma=0 and
                # head reads ZERO... Simpler: head = self (dh = 0),
                # tail weight zero: set B to the sentinel 2**B_BITS - 1.
                bb[base] = (1 << B_BITS) - 1
                continue
            mh = int(csizes[2 * k])
            h, tt, sh = _merge_tables(mn)
            s = np.arange(mn)
            dh[base : base + mn] = s - h
            o = mh + tt - s                      # tail read offset
            bb[base : base + mn] = o + 1         # in [0, 2**(l-1) + 1]
            sig[base : base + mn] = sh
            # Head drift is bounded by the tail child size: h(s) =
            # round(kh*s) >= kh*s - 1/2 gives s - h <= s*mt/(mn-1) + 1/2
            # <= mt <= 2^(l-1). The kernel's head select chain stops at
            # that bound (ffa_kernel natural levels), so it is asserted
            # here at table-build time.
            assert (s - h >= 0).all() and (s - h <= (1 << (l - 1))).all(), (m, l)
            assert (o + 1 >= 0).all() and (o + 1 < (1 << B_BITS) - 1).all(), (m, l)
        sigm = sig % p
        thr = p - sigm
        nat_words[l - 1] = pack_word(sigm, thr, dh, bb, val)
    t.nat_words = nat_words

    # ---- spread phase --------------------------------------------------
    # After the natural phase, depth D0 = L - NL nodes are complete and
    # contiguously packed. Halving steps j = 0..D0-1 split depth-j node
    # groups into their two children, padding each to the power-of-two
    # slot: state (2**j, 2**(L-j)) -> (2**(j+1), 2**(L-j-1)) rows.
    # Per step only two candidate head sizes exist: A and A+1.
    # Each step is fully 2-D: output row u (slot 2g+child of the step's
    # output layout, in-slot index i) reads input flat row
    #   g*S + (child ? mh(g) + i : i)  =  u + child*(mh(g) - half),
    # i.e. one of THREE static row offsets {0, A - half, A + 1 - half}.
    # Per-row word: bits 22-23 select the candidate (0 head, 1 tail with
    # mh = A, 2 tail with mh = A + 1); sign bit = row valid.
    spread = []
    spread_words = np.zeros((max(L - NL, 0), rows), np.int32)
    for j in range(L - NL):
        sizes = node_sizes(m, j)
        mh = sizes >> 1                 # head child sizes
        A = int(mh.min()) if len(mh) else 0
        hi = (mh > A).astype(np.int64)
        assert int(mh.max()) <= A + 1
        spread.append(A)
        # Group size at step j is rows >> j (a multiple of 2 while
        # j <= L - NL - 1 for both container forms); plain division
        # rather than bit tricks so base-3 rows work too.
        half = rows >> (j + 1)
        iota = np.arange(rows)
        g = iota // (rows >> j)         # parent group
        child = (iota // half) % 2
        i = iota % half
        mh_g = mh[g]
        cnt = np.where(child == 0, mh_g, sizes[g] - mh_g)
        sel = np.where(child == 0, 0, 1 + hi[g])
        w = sel << 22
        spread_words[j] = np.where(i < cnt, w | (1 << 31), w).astype(np.int64).astype(np.int32)
    t.spread = spread
    t.spread_words = spread_words

    # ---- slot phase ----------------------------------------------------
    # Levels l = NL+1 .. L in the uniform slot container (2**L rows,
    # slot size S_d = 2**l for outputs). Tables per output row
    # u = k * S_d + s:
    #   delta_h = 2*h(s) - s  in [-2, 1]
    #   delta_t = 2*t(s) - s  in [-2, 1]
    slot_words = np.zeros((L - NL, rows), np.int32)
    for l in range(NL + 1, L + 1):
        d = L - l
        S_d = rows >> d               # 2**l, or 3 * 2**(l-2) (base-3)
        sizes = node_sizes(m, d)
        csizes = node_sizes(m, d + 1)
        sig = np.zeros(rows, np.int64)
        da = np.zeros(rows, np.int64)
        db = np.zeros(rows, np.int64)
        val = np.zeros(rows, bool)
        for k in range(1 << d):
            mn = int(sizes[k])
            if mn == 0:
                continue
            base = k * S_d
            val[base : base + mn] = True
            if mn == 1:
                # carry: tail child holds the row (head child empty).
                # delta_t for s=0 must read tails[k, 0]: 2*t - s = 0.
                da[base] = 2      # delta_h = 0 -> reads empty head slot (zeros)
                db[base] = 2      # delta_t = 0
                continue
            h, tt, sh = _merge_tables(mn)
            s = np.arange(mn)
            dlh = 2 * h - s
            dlt = 2 * tt - s
            assert (dlh >= -2).all() and (dlh <= 1).all(), (m, l, k)
            assert (dlt >= -2).all() and (dlt <= 1).all(), (m, l, k)
            da[base : base + mn] = dlh + 2
            db[base : base + mn] = dlt + 2
            sig[base : base + mn] = sh
        sigm = sig % p
        thr = p - sigm
        slot_words[l - NL - 1] = pack_word(sigm, thr, da, db, val)
    t.slot_words = slot_words
    return t


# ---------------------------------------------------------------------------
# Dense-op simulator: numpy mirror of the kernel's operation sequence.
# ---------------------------------------------------------------------------

def _lane_roll(x, c):
    """Circular roll of phase lanes by +c: out[..., j] = x[..., j + c mod P]."""
    return np.roll(x, -c, axis=-1)


def _row_roll(x, c):
    """Roll rows by +c upward reads: out[u] = x[u + c mod rows]."""
    return np.roll(x, -c, axis=0)


def _tail_lane_roll(tail, words, p, P):
    """Barrel lane roll by sigma-mod-p with the two-pass mod-p wrap."""
    sigm = (words & PH_MASK).astype(np.int64)
    thr = ((words >> PH_BITS) & PH_MASK).astype(np.int64)
    acc = tail
    for k in range(PH_BITS):
        if not ((sigm >> k) & 1).any():
            continue
        rolled = _lane_roll(acc, 1 << k)
        acc = np.where((((sigm >> k) & 1) != 0)[:, None], rolled, acc)
    # Wrap branch: for j >= p - sigma the window crosses the phase ring;
    # the correct value sits one further whole-array roll of (P - p) on:
    #   wrapped[j] = acc[(j + P - p) mod P] = tail[(j + sigma + P - p) mod P]
    # which lands on tail[j + sigma - p] for the wrap region.
    wrapped = _lane_roll(acc, P - p)
    cols = np.arange(P)
    return np.where(cols[None, :] < thr[:, None], acc, wrapped)


def simulate_dense(data, L=None, P=None, R=None):
    """
    Execute the kernel's dense-op sequence in numpy. `data` is (m, p);
    returns the (m, p) FFA transform (must equal ffa_transform exactly).
    ``R`` selects the container height (see :func:`container_rows`).
    """
    data = np.asarray(data, dtype=np.float32)
    m, p = data.shape
    t = build_tables(m, p, L, R)
    L, NL, rows = t.L, t.NL, t.rows
    P = p if P is None else int(P)
    cols = np.arange(P)
    colmask = (cols < p)[None, :]

    buf = np.zeros((rows, P), np.float32)
    buf[:m, :p] = data

    # natural phase
    for l in range(1, NL + 1):
        w = t.nat_words[l - 1]
        valid = w < 0
        a = ((w >> A_SHIFT) & ((1 << A_BITS) - 1)).astype(np.int64)
        b = ((w >> B_SHIFT) & ((1 << B_BITS) - 1)).astype(np.int64)
        lone = b == (1 << B_BITS) - 1
        # head: K-way select over row rolls up by c = a(u); the chain
        # stops at the provable drift bound 2^(l-1) (see build_tables),
        # matching the kernel's trimmed select chain.
        head = buf.copy()
        for c in range(1, (1 << (l - 1)) + 1):
            if not (a == c).any():
                continue
            head = np.where((a == c)[:, None], _row_roll(buf, -c), head)
        # tail: K-way select over row reads at offset o = b - 1
        tail = np.zeros_like(buf)
        for bv in range(0, (1 << B_BITS) - 1):
            sel = (b == bv) & valid & ~lone
            if not sel.any():
                continue
            tail = np.where(sel[:, None], _row_roll(buf, bv - 1), tail)
        tail = _tail_lane_roll(tail, w, p, P)
        out = head + np.where(lone[:, None], 0.0, tail)
        buf = np.where(valid[:, None] & colmask, out, 0.0).astype(np.float32)

    # spread phase: natural depth-(L-NL) nodes -> slot-SLOT_S container,
    # one step = select over three static whole-array row rolls.
    for j, A in enumerate(t.spread):
        w = t.spread_words[j]
        half = rows >> (j + 1)
        sel = (w >> 22) & 3
        valid = w < 0
        out = buf
        for sv, off in ((1, A - half), (2, A + 1 - half)):
            if (sel == sv).any():
                out = np.where((sel == sv)[:, None], _row_roll(buf, off), out)
        buf = np.where(valid[:, None], out, 0.0).astype(np.float32)

    # slot phase (interleaved row-doubling, mirroring the kernel)
    for l in range(NL + 1, L + 1):
        w = t.slot_words[l - NL - 1]
        da = ((w >> A_SHIFT) & ((1 << A_BITS) - 1)).astype(np.int64)
        db = ((w >> B_SHIFT) & ((1 << B_BITS) - 1)).astype(np.int64)
        d = L - l
        G = 1 << d
        S_d = rows >> d
        S_c = S_d >> 1
        v = buf.reshape(G, 2, S_c, P)
        heads, tails = v[:, 0], v[:, 1]
        reph = np.repeat(heads, 2, axis=1)        # (G, S_d, P) interleaved
        rept = np.repeat(tails, 2, axis=1)
        da3 = da.reshape(G, S_d)
        db3 = db.reshape(G, S_d)
        head = np.zeros_like(reph)
        tail = np.zeros_like(rept)
        for dv in range(4):
            delta = dv - 2
            if (da3 == dv).any():
                head = np.where((da3 == dv)[:, :, None], np.roll(reph, -delta, axis=1), head)
            if (db3 == dv).any():
                tail = np.where((db3 == dv)[:, :, None], np.roll(rept, -delta, axis=1), tail)
        head = head.reshape(rows, P)
        tail = tail.reshape(rows, P)
        tail = _tail_lane_roll(tail, w, p, P)
        out = head + tail
        buf = np.where((w < 0)[:, None] & colmask, out, 0.0).astype(np.float32)

    return buf[:m, :p]
